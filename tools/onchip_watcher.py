"""Relay watcher: probe the tunneled TPU, drain a workload queue on
recovery.

The axon relay is intermittent (SURVEY §5.0/§7.14: up ~35 min one
session, down 10 h the next, and it can answer a probe then hang
mid-compile). This watcher turns chip availability into captured
numbers without a human in the loop: every --interval seconds it
launches a subprocess that jits a trivial matmul (timeout --probe-s;
np.asarray sync — block_until_ready returns at enqueue on the relay);
when the probe passes it runs the next pending workload from QUEUE,
each in its own watchdogged subprocess, and appends one JSON line per
attempt to --out (ONCHIP_r04.jsonl at the repo root by default).
A workload that times out or errors is retried on a later recovery,
up to --retries attempts; between workloads the probe re-runs so a
mid-drain relay death stops the queue instead of burning every
workload's timeout against a dead chip.

Run: nohup python tools/onchip_watcher.py &   (stdout is the ledger)
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE_SRC = ("import jax, jax.numpy as jnp, numpy as np;"
             "x = jnp.ones((256, 256), jnp.bfloat16);"
             "y = jax.jit(lambda a: a @ a)(x);"
             "np.asarray(y.astype(jnp.float32));"
             "print('PROBE_OK', flush=True)")

# (name, argv, timeout_s) — argv runs from the repo root
QUEUE = [
    ('conv_bwd_microbench',
     [sys.executable, 'tools/conv_bwd_microbench.py', '--inner', '8'], 1500),
    ('resnet50_anatomy',
     [sys.executable, 'bench.py', '--workload', 'resnet50_anatomy',
      '--backend', 'tpu'], 900),
    ('attention_microbench',
     [sys.executable, 'bench.py', '--workload', 'attention_microbench',
      '--backend', 'tpu'], 900),
    ('transformer_seq256',
     [sys.executable, 'bench.py', '--workload', 'transformer_seq256',
      '--backend', 'tpu'], 600),
    ('moe_cap1.25',
     [sys.executable, 'bench.py', '--workload', 'moe_cap1.25',
      '--backend', 'tpu'], 600),
    ('resnet50_bn_fp32',
     [sys.executable, 'bench.py', '--workload', 'resnet50',
      '--backend', 'tpu'], 600, {'PADDLE_TPU_BN_COMPUTE': 'fp32'}),
    ('resnet50_nchw_ir',
     [sys.executable, 'bench.py', '--workload', 'resnet50',
      '--backend', 'tpu'], 600, {'PADDLE_TPU_RESNET_LAYOUT': 'NCHW'}),
    ('resnet50_s2d_stem',
     [sys.executable, 'bench.py', '--workload', 'resnet50',
      '--backend', 'tpu'], 600, {'PADDLE_TPU_CONV_S2D': '1'}),
    ('transformer_naive_ce',
     [sys.executable, 'bench.py', '--workload', 'transformer',
      '--backend', 'tpu'], 600, {'PADDLE_TPU_FUSED_CE': '0'}),
    ('transformer_fused_ce',
     [sys.executable, 'bench.py', '--workload', 'transformer',
      '--backend', 'tpu'], 600),
    ('transformer_seq4096',
     [sys.executable, 'bench.py', '--workload', 'transformer_seq4096',
      '--backend', 'tpu'], 700),
    ('transformer_seq4096_pallas',
     [sys.executable, 'bench.py', '--workload', 'transformer_seq4096',
      '--backend', 'tpu'], 700, {'PADDLE_TPU_USE_PALLAS': '1'}),
    ('transformer_big',
     [sys.executable, 'bench.py', '--workload', 'transformer_big',
      '--backend', 'tpu'], 700),
    ('rnn_lstm',
     [sys.executable, 'bench.py', '--workload', 'rnn_lstm',
      '--backend', 'tpu'], 600),
]


def probe(timeout):
    try:
        r = subprocess.run([sys.executable, '-c', PROBE_SRC],
                           capture_output=True, text=True, timeout=timeout,
                           cwd=REPO)
        return 'PROBE_OK' in (r.stdout or '')
    except subprocess.TimeoutExpired:
        return False


def run_one(name, argv, timeout, extra_env=None):
    env = dict(os.environ)
    env.setdefault('JAX_COMPILATION_CACHE_DIR', '/tmp/xla_cache')
    env.update(extra_env or {})
    t0 = time.time()
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO, env=env)
        ok = r.returncode == 0
        out = r.stdout or ''
    except subprocess.TimeoutExpired as e:
        ok = False
        out = (e.stdout.decode() if isinstance(e.stdout, bytes)
               else (e.stdout or ''))
    # keep every RESULT / RESULT_JSON / json line the child printed
    results = [ln for ln in out.splitlines()
               if ln.startswith(('RESULT', '{'))]
    return {'workload': name, 'ok': ok, 'wall_s': round(time.time() - t0, 1),
            'results': results[-40:],
            'env': {k: v for k, v in (extra_env or {}).items()}}


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--interval', type=float, default=180)
    p.add_argument('--probe-s', type=float, default=75)
    p.add_argument('--retries', type=int, default=3)
    p.add_argument('--out', default=os.path.join(REPO, 'ONCHIP_r04.jsonl'))
    args = p.parse_args()
    attempts = {name: 0 for name, *_ in QUEUE}
    done = set()

    def emit(rec):
        rec['ts'] = round(time.time(), 1)
        with open(args.out, 'a') as f:
            f.write(json.dumps(rec) + '\n')
        print(json.dumps(rec), flush=True)

    def exhausted():
        return all(item[0] in done or attempts[item[0]] >= args.retries
                   for item in QUEUE)

    while not exhausted():
        if not probe(args.probe_s):
            time.sleep(args.interval)
            continue
        emit({'event': 'relay_up'})
        for item in QUEUE:
            name, argv, timeout = item[0], item[1], item[2]
            extra_env = item[3] if len(item) > 3 else None
            if name in done or attempts[name] >= args.retries:
                continue
            attempts[name] += 1
            rec = run_one(name, argv, timeout, extra_env)
            rec['attempt'] = attempts[name]
            emit(rec)
            if rec['ok']:
                done.add(name)
            elif not probe(args.probe_s):
                emit({'event': 'relay_down_mid_drain'})
                break
        # failed-but-retryable workloads go around again; the probe at
        # the top of the loop rate-limits re-drains while the relay
        # flaps, and exhausted() is the only terminal condition
        if not exhausted():
            time.sleep(args.interval)
    emit({'event': 'watcher_exit', 'done': sorted(done)})


if __name__ == '__main__':
    main()
