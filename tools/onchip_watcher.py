"""Relay watcher: probe the tunneled TPU, drain the bench queue on
recovery into the shared results store.

The axon relay is intermittent (SURVEY §5.0/§7.14: up ~35 min one
session, down 10 h the next, and it can answer a probe then hang
mid-compile). This watcher turns chip availability into captured
numbers without a human in the loop: every --interval seconds it
launches a subprocess that jits a trivial matmul (timeout --probe-s;
np.asarray sync — block_until_ready returns at enqueue on the relay);
when the probe passes it runs the next pending workload from QUEUE,
each in its own watchdogged subprocess via bench._run_workload, and
appends one record per attempt to the SHARED store (ONCHIP_r05.jsonl —
the same resumable queue file bench.py's driver run reads and writes,
provenance-tagged 'watcher'). A workload that already has an ok record
in the store is skipped, so watcher restarts and driver runs compose
instead of re-measuring. Failures retry on a later recovery, up to
--retries attempts; between workloads the probe re-runs so a mid-drain
relay death stops the queue instead of burning every workload's
timeout against a dead chip.

Run: nohup python tools/onchip_watcher.py &   (stdout is the ledger)
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402  (repo-root bench.py: _run_workload + store)

# (key, workload, extra_env, timeout_s) — VERDICT r4 next-#1 priority:
# headline pair, fused-CE A/B, s2d A/B, anatomy, MoE sweep, the fixed
# attention microbench; then the rest of the ablation table.
QUEUE = [
    ('transformer', 'transformer', None, 500),
    ('resnet50', 'resnet50', None, 500),
    ('transformer_seq512_masked', 'transformer_seq512_masked', None, 600),
    ('transformer_seq512_masked_pallas', 'transformer_seq512_masked',
     {'PADDLE_TPU_USE_PALLAS': '1'}, 600),
    ('transformer_naive_ce', 'transformer',
     {'PADDLE_TPU_FUSED_CE': '0'}, 500),
    ('resnet50_s2d_stem', 'resnet50', {'PADDLE_TPU_CONV_S2D': '1'}, 500),
    ('resnet50_bn_pallas', 'resnet50', {'PADDLE_TPU_BN_PALLAS': '1'}, 500),
    ('resnet50_anatomy', 'resnet50_anatomy', None, 900),
    ('moe_cap1.0', 'moe_cap1.0', None, 600),
    ('moe_cap1.25', 'moe_cap1.25', None, 600),
    ('moe_cap2.0', 'moe_cap2.0', None, 600),
    ('attention_microbench', 'attention_microbench', None, 900),
    # BLOCK_K sweep (VERDICT r4 next-#3: beyond the pinned 128) — the
    # Pallas legs of the microbench re-run at wider key tiles
    ('attention_microbench_bk256', 'attention_microbench',
     {'PADDLE_TPU_PALLAS_BLOCK_K': '256'}, 900),
    ('attention_microbench_bk512', 'attention_microbench',
     {'PADDLE_TPU_PALLAS_BLOCK_K': '512'}, 900),
    ('transformer_seq1024', 'transformer_seq1024', None, 600),
    ('transformer_seq1024_pallas', 'transformer_seq1024',
     {'PADDLE_TPU_USE_PALLAS': '1'}, 600),
    ('resnet50_nchw_ir', 'resnet50',
     {'PADDLE_TPU_RESNET_LAYOUT': 'NCHW'}, 500),
    ('resnet50_bn_fp32', 'resnet50',
     {'PADDLE_TPU_BN_COMPUTE': 'fp32'}, 500),
    ('transformer_seq4096', 'transformer_seq4096', None, 700),
    ('transformer_seq4096_pallas', 'transformer_seq4096',
     {'PADDLE_TPU_USE_PALLAS': '1'}, 700),
    ('transformer_seq256', 'transformer_seq256', None, 600),
    # pipelined trainer loop sync-vs-D=2/4 (host-fed; overlap fraction
    # lands in the metrics JSONL beside the throughput rows)
    ('pipeline_transformer', 'pipeline_transformer', None, 700),
    ('pipeline_resnet50', 'pipeline_resnet50', None, 700),
    # decode serving: continuous batching + paged KV cache tokens/sec
    # (PR 6), now on the shared-prefix traffic mix (95% shared system
    # prompt) with the prefix cache on and a spec-decode off/on
    # ablation (ISSUE 12) — cache-hit-rate, prefill-tokens-skipped,
    # and accepted-draft-length land in the shared metrics JSONL
    # beside inter-token percentiles
    ('decode_transformer', 'decode_transformer', None, 700),
    # fleet chaos scenario (ISSUE 10): 3-replica router under flash
    # crowd + replica kill; slo.*/router.* burn-rate/goodput metrics
    # land in the shared metrics JSONL (metrics_report.py --slo)
    ('fleet', 'fleet', None, 700),
    # self-healing autoscaling fleet (ISSUE 11): flash-crowd scale-up,
    # crash-loop quarantine, trough scale-in, hedged-request budget
    ('autoscale', 'autoscale', None, 700),
    # quantization end-to-end (ISSUE 13): int8-allreduce bytes/loss
    # ablation, equal-bytes quantized-KV capacity + parity, fleet A/B
    # on goodput/burn; quant.* gauges land in the shared metrics JSONL
    ('quant', 'quant', None, 700),
    # disaggregated prefill/decode fleet (ISSUE 14): disagg-vs-coloc
    # inter-token p99 at equal chips, TTFT budget, zero-recompile
    # across the KV handoff; handoff.* metrics land in the JSONL
    ('disagg', 'disagg', None, 700),
    # distributed linear algebra (ISSUE 15): SUMMA parity + memory
    # contract + panel autotune, blocked Cholesky/QR residuals, power
    # iteration exact-vs-quantized allreduce; linalg.* gauges land in
    # the shared metrics JSONL (does the panel winner flip on-chip?)
    ('linalg', 'linalg', None, 700),
    ('transformer_big', 'transformer_big', None, 700),
    ('rnn_lstm', 'rnn_lstm', None, 600),
    ('pallas_parity', 'pallas_parity', None, 300),
    # autotuner + AOT warm start (ISSUE 8): tuned-vs-default attention
    # at the r4 seq{1024,4096} shapes (does the winner flip on THIS
    # chip?) + cold-vs-warm startup seconds; tuning.*/aot.* gauges land
    # in the shared metrics JSONL
    ('autotune', 'autotune', None, 900),
    # static verifier overhead guard (ISSUE 9): analysis passes vs cold
    # compile on the transformer program; analysis.* gauges land in the
    # shared metrics JSONL and `ok` asserts the <1% contract on-chip
    ('verify', 'verify', None, 600),
    # cross-host fleet chaos (ISSUE 16): replica workers as REAL
    # subprocesses behind the RPC control plane — SIGKILL mid-load
    # (zero loss + typed errors + heal), SIGSTOP hung-worker heartbeat
    # death, crash-loop quarantine, subprocess-vs-in-process bit
    # identity; rpc.*/worker.* metrics land in the shared JSONL
    ('crosshost', 'crosshost', None, 900),
    # multi-tenant policies: noisy-neighbor isolation, typed quota
    # sheds, priority preemption ordering, trainer co-location yield
    # with bit-identical params; tenant.* metrics land in the JSONL
    ('multitenant', 'multitenant', None, 700),
    # training raw speed: bucketed-exact bit identity, backward/
    # allreduce overlap fraction, fp8 matmul dispatch discipline,
    # ZeRO-1 memory + bit identity, unified-MFU headline deltas
    ('trainspeed', 'trainspeed', None, 900),
]

# non-bench tools: (key, argv, timeout) — raw stdout lines stored
TOOL_QUEUE = [
    ('conv_bwd_microbench',
     [sys.executable, 'tools/conv_bwd_microbench.py', '--inner', '8'], 1500),
]


def probe(timeout):
    # one definition of "relay alive" shared with the driver bench run
    return bench._probe_quick(timeout)


def run_tool(name, argv, timeout):
    t0 = time.time()
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO)
        ok = r.returncode == 0
        out = r.stdout or ''
    except subprocess.TimeoutExpired as e:
        ok = False
        out = (e.stdout.decode() if isinstance(e.stdout, bytes)
               else (e.stdout or ''))
    lines = [ln for ln in out.splitlines()
             if ln.startswith(('RESULT', '{'))][-40:]
    return ok, lines, round(time.time() - t0, 1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--interval', type=float, default=180)
    p.add_argument('--probe-s', type=float, default=75)
    p.add_argument('--retries', type=int, default=3)
    args = p.parse_args()
    # one shared compile cache with bench.py: a workload the watcher got
    # halfway through compiling finishes instantly on the driver's run
    os.environ.setdefault('JAX_COMPILATION_CACHE_DIR',
                          '/tmp/paddle_tpu_jax_cache')
    # telemetry: every workload child (bench._run_workload subprocess)
    # enables paddle_tpu.observe and appends pid-tagged snapshots to the
    # shared metrics JSONL beside the results store
    os.environ.setdefault('PADDLE_TPU_METRICS_JSONL',
                          bench._metrics_path())
    attempts = {k: 0 for k, *_ in QUEUE + TOOL_QUEUE}
    done = set(bench.store_load())  # resumable: ok records are final

    def log(rec):
        rec['ts'] = round(time.time(), 1)
        print(json.dumps(rec), flush=True)

    def exhausted():
        return all(k in done or attempts[k] >= args.retries
                   for k, *_ in QUEUE + TOOL_QUEUE)

    while not exhausted():
        if not probe(args.probe_s):
            time.sleep(args.interval)
            continue
        log({'event': 'relay_up'})
        for key, workload, extra_env, timeout in QUEUE:
            if key in done or attempts[key] >= args.retries:
                continue
            attempts[key] += 1
            t0 = time.time()
            val, err = bench._run_workload(workload, 'tpu', False, timeout,
                                           env=extra_env)
            bench.store_put(key, workload, 'tpu', value=val,
                            ok=err is None, env=extra_env,
                            provenance='watcher', error=err)
            log({'workload': key, 'ok': err is None,
                 'wall_s': round(time.time() - t0, 1),
                 'attempt': attempts[key], 'error': err})
            if err is None:
                done.add(key)
            elif not probe(args.probe_s):
                log({'event': 'relay_down_mid_drain'})
                break
        else:
            for key, argv, timeout in TOOL_QUEUE:
                if key in done or attempts[key] >= args.retries:
                    continue
                attempts[key] += 1
                ok, lines, wall = run_tool(key, argv, timeout)
                bench.store_put(key, key, 'tpu', value=lines, ok=ok,
                                provenance='watcher',
                                error=None if ok else 'tool failed')
                log({'workload': key, 'ok': ok, 'wall_s': wall,
                     'attempt': attempts[key]})
                if ok:
                    done.add(key)
                elif not probe(args.probe_s):
                    log({'event': 'relay_down_mid_drain'})
                    break
        if not exhausted():
            time.sleep(args.interval)
    log({'event': 'watcher_exit', 'done': sorted(done)})


if __name__ == '__main__':
    main()
