"""Load generator for paddle_tpu.serving — closed- and open-loop.

Drives a `ServingEngine` (tiny built-in MLP by default, or any
`save_inference_model` directory via --model-dir) and reports
p50/p95/p99 request latency plus throughput:

    python tools/serving_bench.py --duration 2 --clients 8
    python tools/serving_bench.py --mode open --qps 500 --duration 5
    python tools/serving_bench.py --json | jq .latency_ms.p99

closed loop: `--clients` threads each keep exactly one request in
flight (latency under a fixed concurrency); open loop: one pacer
submits at `--qps` regardless of completions (latency under offered
load — overload shows up as `requests_rejected` growing, the
QueueFullError backpressure path). Request batch sizes are sampled
uniformly from [--rows-lo, --rows-hi].

--tenant-mix 'fg:3:interactive,bg:1:batch' drives the same load as a
weighted multi-tenant mix (loadgen.tenant_mix): requests flow through
a quota-equipped Router with tenant-prefixed session ids, --tenant-rps
caps each tenant's request rate (QuotaExceededError sheds count as
rejects), and the report gains per-tenant admitted/shed rows from the
tenant.* counters.

Metrics land in the standard observe pipeline: pass --metrics-jsonl
(or set PADDLE_TPU_METRICS_JSONL) and read the run afterwards with
tools/metrics_report.py. --json emits one machine-readable object on
stdout — its schema is asserted by tests/test_serving.py so this tool
cannot rot.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_tiny_model(dirname, in_dim=8, hidden=16, classes=4):
    """Save the default benchmark model: fc-relu-fc-softmax."""
    import paddle_tpu as fluid
    x = fluid.layers.data(name='x', shape=[in_dim], dtype='float32')
    h = fluid.layers.fc(input=x, size=hidden, act='relu')
    out = fluid.layers.fc(input=h, size=classes, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(dirname, ['x'], [out], exe)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    return dirname


def _closed_loop(submit, make_request, stats, deadline, clients):
    from paddle_tpu.serving.loadgen import closed_loop

    def do_request(rng):
        feed, rows, session = make_request(rng)
        submit(feed, session).result(timeout=60)
        return rows

    closed_loop(do_request, stats, deadline, clients)


def _open_loop(submit, make_request, stats, deadline, qps, seed=7):
    from paddle_tpu.serving.loadgen import open_loop

    def submit_request(rng):
        feed, rows, session = make_request(rng)
        return submit(feed, session), rows

    open_loop(submit_request, stats, deadline, qps, seed=seed)
    # engine.shutdown(drain=True) in main() is the completion barrier


def _parse_tenant_mix(spec):
    """'name:weight[:priority],...' -> [(name, weight, priority)]."""
    out = []
    for part in spec.split(','):
        bits = part.split(':')
        if len(bits) not in (2, 3) or not bits[0]:
            raise SystemExit("serving_bench: --tenant-mix wants "
                             "'name:weight[:priority],...', got %r"
                             % spec)
        try:
            weight = float(bits[1])
        except ValueError:
            raise SystemExit('serving_bench: bad tenant weight in %r'
                             % part)
        out.append((bits[0], weight,
                    bits[2] if len(bits) == 3 else 'standard'))
    return out


def main(argv=None):
    p = argparse.ArgumentParser(
        description='paddle_tpu.serving load generator')
    p.add_argument('--model-dir', default=None,
                   help='save_inference_model dir (default: build a '
                        'tiny MLP in a temp dir)')
    p.add_argument('--mode', choices=['closed', 'open'], default='closed')
    p.add_argument('--duration', type=float, default=2.0,
                   help='seconds of load after warmup')
    p.add_argument('--clients', type=int, default=4,
                   help='closed-loop concurrent clients')
    p.add_argument('--qps', type=float, default=200.0,
                   help='open-loop offered request rate')
    p.add_argument('--qps-schedule', default=None,
                   help="open-loop time-varying rate: 't:qps' "
                        "breakpoints, e.g. '0:50,2:500,4:50' (step-"
                        'hold; overrides --qps)')
    p.add_argument('--max-batch-size', type=int, default=8)
    p.add_argument('--batch-timeout-ms', type=float, default=2.0)
    p.add_argument('--max-queue-depth', type=int, default=64)
    p.add_argument('--rows-lo', type=int, default=1,
                   help='min rows per request')
    p.add_argument('--rows-hi', type=int, default=0,
                   help='max rows per request (default max-batch-size)')
    p.add_argument('--no-warmup', action='store_true',
                   help='skip AOT warmup (shows live-compile cost)')
    p.add_argument('--tenant-mix', default=None,
                   help="weighted tenant mix 'name:weight[:priority]"
                        ",...' — requests route through a quota-"
                        'equipped Router with tenant-prefixed '
                        'session ids')
    p.add_argument('--tenant-rps', type=float, default=None,
                   help='per-tenant request-rate quota (requests/s; '
                        'default unlimited)')
    p.add_argument('--tenant-sessions', type=int, default=4,
                   help='distinct session ids per tenant')
    p.add_argument('--metrics-jsonl', default=None,
                   help='observe JSONL path (or set '
                        'PADDLE_TPU_METRICS_JSONL)')
    p.add_argument('--json', action='store_true',
                   help='emit one machine-readable JSON object')
    args = p.parse_args(argv)

    from paddle_tpu import observe
    from paddle_tpu.inference import create_predictor
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.loadgen import Stats, percentiles

    model_dir = args.model_dir or build_tiny_model(
        os.path.join(tempfile.mkdtemp(prefix='serving_bench_'), 'model'))

    # counters on AFTER the model build so executor.cache_miss_total
    # counts serving compiles only — with warmup on, cache_misses ==
    # warmup signatures is the zero-live-compile invariant the report
    # (and the smoke test) asserts
    jsonl = args.metrics_jsonl or os.environ.get(
        'PADDLE_TPU_METRICS_JSONL')
    observe.enable(jsonl=jsonl)

    predictor = create_predictor(model_dir)
    specs = predictor.feed_specs()
    engine = ServingEngine(predictor,
                           max_batch_size=args.max_batch_size,
                           batch_timeout_ms=args.batch_timeout_ms,
                           max_queue_depth=args.max_queue_depth)

    rows_hi = args.rows_hi or args.max_batch_size
    feed_shapes = {n: [d for d in shape] for n, (shape, _) in
                   specs.items()}

    def build_feed(rng, rows):
        feed = {}
        for name, (shape, dtype) in specs.items():
            dims = [rows] + [int(d) for d in shape[1:]]
            if any(d < 0 for d in dims[1:]):
                raise SystemExit(
                    'serving_bench: feed %r has unbound non-batch dims '
                    '%s — this generator only drives fixed-shape '
                    'models' % (name, shape))
            feed[name] = rng.rand(*dims).astype('float32') \
                if str(dtype).startswith(('float', 'bfloat')) \
                else np.zeros(dims, dtype=str(dtype))
        return feed

    mix_specs = _parse_tenant_mix(args.tenant_mix) \
        if args.tenant_mix else None
    router = None
    if mix_specs:
        from paddle_tpu.serving import Router, TenantRegistry
        from paddle_tpu.serving.loadgen import tenant_mix
        registry = TenantRegistry()
        for name, _weight, prio in mix_specs:
            registry.add(name, priority=prio,
                         request_rate=args.tenant_rps)
        router = Router([engine], tenants=registry)
        weights = [(n, w) for n, w, _ in mix_specs]

        def make_request(rng):
            _tenant, session, rows = tenant_mix(
                rng, weights,
                sessions_per_tenant=args.tenant_sessions,
                rows=(args.rows_lo, rows_hi))
            return build_feed(rng, rows), rows, session

        submit = lambda feed, session: router.submit(  # noqa: E731
            feed, session=session)
    else:
        def make_request(rng):
            rows = int(rng.randint(args.rows_lo, rows_hi + 1))
            return build_feed(rng, rows), rows, None

        submit = lambda feed, session: engine.submit(feed)  # noqa: E731

    t_w0 = time.perf_counter()
    signatures = 0 if args.no_warmup else engine.warmup()
    warmup_s = time.perf_counter() - t_w0
    engine.start()

    qps = args.qps
    if args.qps_schedule:
        try:
            qps = [(float(t), float(q)) for t, q in
                   (part.split(':', 1)
                    for part in args.qps_schedule.split(','))]
        except ValueError:
            raise SystemExit("serving_bench: --qps-schedule wants "
                             "'t:qps,t:qps,...', got %r"
                             % args.qps_schedule)

    stats = Stats()
    t0 = time.perf_counter()
    deadline = t0 + args.duration
    if args.mode == 'closed':
        _closed_loop(submit, make_request, stats, deadline,
                     args.clients)
    else:
        _open_loop(submit, make_request, stats, deadline, qps)
    engine.shutdown(drain=True)
    if router is not None:
        router.close()
    wall = time.perf_counter() - t0

    snap = observe.snapshot()
    counters = snap['counters']
    misses = sum(v for k, v in counters.items()
                 if k.startswith('executor.cache_miss_total'))
    hits = sum(v for k, v in counters.items()
               if k.startswith('executor.cache_hit_total'))
    waste = snap['histograms'].get('serving.padding_waste', {})
    bsz = snap['histograms'].get('serving.batch_size', {})

    report = {
        'mode': args.mode,
        'duration_s': round(wall, 4),
        'clients': args.clients if args.mode == 'closed' else None,
        'offered_qps': args.qps if args.mode == 'open' else None,
        'qps_schedule': args.qps_schedule
        if args.mode == 'open' else None,
        'rejects_timeline': [round(t, 3) for t in stats.reject_times],
        'requests_ok': stats.ok,
        'requests_rejected': stats.rejected,
        'requests_errored': stats.errors,
        'rows': stats.rows,
        'throughput_rps': round(stats.ok / wall, 2) if wall else None,
        'throughput_rows_per_s': round(stats.rows / wall, 2)
        if wall else None,
        'latency_ms': percentiles(stats.latencies),
        'batch_size_mean': bsz.get('mean'),
        'padding_waste_mean': waste.get('mean'),
        'warmup': {'signatures': signatures,
                   'seconds': round(warmup_s, 4)},
        'executor': {'cache_misses': misses, 'cache_hits': hits},
        'engine': {'max_batch_size': args.max_batch_size,
                   'batch_timeout_ms': args.batch_timeout_ms,
                   'max_queue_depth': args.max_queue_depth,
                   'buckets': engine._ladder.batch_sizes},
        'feed_shapes': feed_shapes,
    }
    if mix_specs:
        sel = lambda prefix, name: sum(  # noqa: E731
            v for k, v in counters.items()
            if k.startswith(prefix) and 'tenant=%s' % name in k)
        report['tenants'] = {
            name: {'weight': weight, 'priority': prio,
                   'admitted': sel('tenant.admitted', name),
                   'shed': sel('tenant.shed', name)}
            for name, weight, prio in mix_specs}
    observe.disable()

    if args.json:
        print(json.dumps(report))
    else:
        lat = report['latency_ms']
        print('serving_bench: %s loop, %.2fs' % (args.mode, wall))
        print('  requests   ok=%d rejected=%d errored=%d (%.1f req/s, '
              '%.1f rows/s)' % (stats.ok, stats.rejected, stats.errors,
                                report['throughput_rps'] or 0.0,
                                report['throughput_rows_per_s'] or 0.0))
        if lat['p50'] is not None:
            print('  latency ms p50=%.2f p95=%.2f p99=%.2f mean=%.2f '
                  'max=%.2f' % (lat['p50'], lat['p95'], lat['p99'],
                                lat['mean'], lat['max']))
        print('  batching   mean batch=%.2f rows, mean padding waste='
              '%.1f%%' % (bsz.get('mean') or 0.0,
                          100.0 * (waste.get('mean') or 0.0)))
        print('  compiles   %d warmup signatures in %.2fs; %d total '
              'misses, %d hits' % (signatures, warmup_s, misses, hits))
        if mix_specs:
            for name, row in sorted(report['tenants'].items()):
                print('  tenant     %s (%s, w=%g): admitted=%d '
                      'shed=%d' % (name, row['priority'],
                                   row['weight'], row['admitted'],
                                   row['shed']))
    return 0


if __name__ == '__main__':
    sys.exit(main())
