"""Summarize a paddle_tpu.observe metrics JSONL.

Reads the snapshot/summary lines written by ``observe.enable(jsonl=...)``
(one JSON object per line; bench.py and tools/onchip_watcher.py children
append here, pid-tagged) and prints a human summary: p50/p95/max per
histogram, final counter/gauge values, and the MFU/goodput headline.

    python tools/metrics_report.py ONCHIP_r05_metrics.jsonl
    python tools/metrics_report.py run.jsonl --json | jq .mfu

By default the newest ``kind: "summary"`` line is reported (the
end-of-run state); ``--all-pids`` reports the newest summary per pid,
``--per-host`` per host (merged multihost JSONLs — records carry a
``host`` = jax.process_index() field), ``--snapshot`` takes the newest
line of any kind. ``--json`` emits one machine-readable object for
scripting, ``--slo`` renders the SLO panel (per-route objectives,
error-budget burn rate, goodput, and the top-5 slowest sampled trace
ids — each one a ``/tracez?trace_id=`` timeline), ``--tenants``
renders the multi-tenant isolation panel (per-tenant
admitted/shed/preempted/evicted-pages from the ``tenant.*`` counters,
plus the co-located trainer's yield ledger), and ``--prom``
converts the chosen record to Prometheus text exposition (drop it in a node_exporter textfile-collector dir and
offline runs feed the same dashboards as live ``/metrics`` scrapes) —
fast tests exercise all three paths so this tool cannot bit-rot.

See ``tools/flight_report.py`` for the crash-forensics companion (the
flight recorder's postmortem JSON).
"""

import argparse
import glob
import json
import os
import sys


def _registry_mod():
    """paddle_tpu/observe/registry.py loaded standalone (stdlib-only
    module; importing it via the package would drag in jax)."""
    import importlib.util
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'paddle_tpu', 'observe', 'registry.py')
    spec = importlib.util.spec_from_file_location(
        '_paddle_tpu_observe_registry', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_records(path):
    """Parse records, skipping torn lines (concurrent appenders).

    ``path`` may be a single JSONL file, a directory (every ``*.jsonl``
    inside is merged — the shape a cross-host run leaves behind: the
    parent's sink plus one ``<stem>-<replica>.jsonl`` per worker
    process), or a glob pattern. Merged records are ordered by ``ts``
    so counter-delta timelines stay monotonic; each record's ``host``
    field says which process emitted it."""
    if os.path.isdir(path):
        paths = sorted(glob.glob(os.path.join(path, '*.jsonl')))
    elif any(ch in path for ch in '*?['):
        paths = sorted(glob.glob(path))
    else:
        paths = [path]
    out = []
    for p in paths:
        with open(p) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    if len(paths) > 1:
        out.sort(key=lambda r: (r.get('ts') is None, r.get('ts') or 0))
    return out


def pick(records, any_kind=False):
    """Newest summary record (fallback: newest of any kind)."""
    if not any_kind:
        summaries = [r for r in records if r.get('kind') == 'summary']
        if summaries:
            return summaries[-1]
    return records[-1] if records else None


def derive(rec):
    """Flat scripting-friendly view of one record."""
    gauges = rec.get('gauges', {})
    out = {
        'ts': rec.get('ts'),
        'pid': rec.get('pid'),
        'host': rec.get('host', 0),
        'kind': rec.get('kind'),
        'counters': rec.get('counters', {}),
        'gauges': gauges,
        'histograms': rec.get('histograms', {}),
        'mfu': gauges.get('trainer.mfu'),
        'goodput': gauges.get('run.goodput'),
        'step_flops': gauges.get('executor.step_flops'),
        'steps_per_sec_ema': gauges.get('trainer.steps_per_sec_ema'),
        'host_blocked_seconds':
            gauges.get('trainer.host_blocked_seconds'),
        'device_blocked_seconds':
            gauges.get('trainer.device_blocked_seconds'),
    }
    # pipelined-loop overlap: 1 - (host-blocked + device-blocked)/wall.
    # The trainer publishes its own per-train() figure; reconstruct
    # from the blocked ledgers when only those made it into the record.
    overlap = gauges.get('trainer.pipeline_overlap_fraction')
    if overlap is None:
        hb = out['host_blocked_seconds']
        db = out['device_blocked_seconds']
        wall = gauges.get('run.wall_seconds')
        if hb is not None and db is not None and wall:
            overlap = max(0.0, 1.0 - (hb + db) / wall)
    out['overlap_fraction'] = overlap
    return out


def _fmt_val(v):
    if isinstance(v, float):
        return '%.6g' % v
    return str(v)


# ------------------------------------------------------------ SLO view
def derive_slo(rec):
    """Per-route SLO panel from one record's slo.* metrics: declared
    objective, burn rate, goodput, predicted p99, and the top-5
    slowest sampled trace ids (slo.slowest_seconds{route,trace_id}
    gauges — each names a /tracez?trace_id= timeline)."""
    parse = _registry_mod().parse_rendered
    routes = {}

    def ent(route):
        return routes.setdefault(route or '?', {
            'latency_budget_s': None, 'availability_target': None,
            'window_s': None, 'burn_rate': None, 'goodput_rps': None,
            'predicted_p99_s': None, 'requests_total': 0,
            'in_slo_total': 0, 'violations_total': 0, 'slowest': []})

    gmap = {'slo.latency_budget_seconds': 'latency_budget_s',
            'slo.availability_target': 'availability_target',
            'slo.window_seconds': 'window_s',
            'slo.burn_rate': 'burn_rate',
            'slo.goodput_rps': 'goodput_rps',
            'slo.predicted_p99_seconds': 'predicted_p99_s'}
    for rendered, v in rec.get('gauges', {}).items():
        name, labels = parse(rendered)
        if name in gmap:
            ent(labels.get('route'))[gmap[name]] = v
        elif name == 'slo.slowest_seconds':
            ent(labels.get('route'))['slowest'].append(
                {'seconds': v, 'trace_id': labels.get('trace_id')})
    cmap = {'slo.requests_total': 'requests_total',
            'slo.in_slo_total': 'in_slo_total',
            'slo.violations_total': 'violations_total'}
    for rendered, v in rec.get('counters', {}).items():
        name, labels = parse(rendered)
        if name in cmap:
            ent(labels.get('route'))[cmap[name]] = v
    for r in routes.values():
        r['slowest'].sort(key=lambda s: -(s['seconds'] or 0.0))
        del r['slowest'][5:]
    return {'ts': rec.get('ts'), 'pid': rec.get('pid'),
            'host': rec.get('host', 0), 'routes': routes}


def render_slo(rec):
    doc = derive_slo(rec)
    lines = []
    if not doc['routes']:
        return 'no slo.* metrics in this record'
    for route in sorted(doc['routes']):
        r = doc['routes'][route]
        obj = 'objective: p(lat <= %ss) >= %s over %ss window' % (
            _fmt_val(r['latency_budget_s'] or 0.0),
            _fmt_val(r['availability_target'] or 0.0),
            _fmt_val(r['window_s'] or 0.0))
        lines.append('== route %r — %s' % (route, obj))
        lines.append('   burn rate %s   goodput %s rps   '
                     'predicted p99 %s s'
                     % (_fmt_val(r['burn_rate'] or 0.0),
                        _fmt_val(r['goodput_rps'] or 0.0),
                        _fmt_val(r['predicted_p99_s'])
                        if r['predicted_p99_s'] is not None else '?'))
        lines.append('   requests %d   in-SLO %d   violations %d'
                     % (r['requests_total'], r['in_slo_total'],
                        r['violations_total']))
        if r['slowest']:
            lines.append('   slowest sampled requests:')
            for s in r['slowest']:
                lines.append('     %10.6fs  trace_id=%s  '
                             '(/tracez?trace_id=%s)'
                             % (s['seconds'], s['trace_id'],
                                s['trace_id']))
    return '\n'.join(lines)


# --------------------------------------------------------- tenant view
# render order for the isolation panel: most protected class first
_TENANT_PRIORITIES = ('interactive', 'standard', 'batch')


def derive_tenants(rec):
    """Multi-tenant isolation panel from one record's tenant.*
    metrics: per-tenant admitted/shed (with the shed-reason split:
    'requests' vs 'tokens' bucket), decode preemptions, prefix-cache
    pages evicted, and the co-located trainer's yield ledger
    (tenant.trainer_yields_total / tenant.trainer_yielded /
    trainer.yield_seconds)."""
    parse = _registry_mod().parse_rendered
    tenants = {}

    def ent(labels):
        e = tenants.setdefault(labels.get('tenant', '?'), {
            'priority': None, 'admitted': 0, 'shed': 0,
            'shed_reasons': {}, 'preempted': 0, 'evicted_pages': 0})
        if labels.get('priority'):
            e['priority'] = labels['priority']
        return e

    trainer = {}
    for rendered, v in rec.get('counters', {}).items():
        name, labels = parse(rendered)
        if name == 'tenant.admitted':
            ent(labels)['admitted'] += v
        elif name == 'tenant.shed':
            e = ent(labels)
            e['shed'] += v
            reason = labels.get('reason', '?')
            e['shed_reasons'][reason] = \
                e['shed_reasons'].get(reason, 0) + v
        elif name == 'tenant.preempted':
            ent(labels)['preempted'] += v
        elif name == 'tenant.evicted_pages':
            ent(labels)['evicted_pages'] += v
        elif name == 'tenant.trainer_yields_total':
            trainer['yields'] = trainer.get('yields', 0) + v
    for rendered, v in rec.get('gauges', {}).items():
        name, _labels = parse(rendered)
        if name == 'tenant.trainer_yielded':
            trainer['yielded'] = v
    for rendered, stats in rec.get('histograms', {}).items():
        name, _labels = parse(rendered)
        if name == 'trainer.yield_seconds':
            trainer['yield_seconds'] = {
                k: stats.get(k) for k in ('count', 'mean', 'max')}
    return {'ts': rec.get('ts'), 'pid': rec.get('pid'),
            'host': rec.get('host', 0), 'tenants': tenants,
            'trainer': trainer}


def render_tenants(rec):
    doc = derive_tenants(rec)
    if not doc['tenants'] and not doc['trainer']:
        return 'no tenant.* metrics in this record'
    lines = ['== per-tenant admission / scheduling '
             '(most protected class first)']
    lines.append('%-16s %-12s %10s %10s %10s %12s'
                 % ('Tenant', 'Priority', 'Admitted', 'Shed',
                    'Preempted', 'EvictedPgs'))

    def order(item):
        name, e = item
        prio = e['priority']
        rank = _TENANT_PRIORITIES.index(prio) \
            if prio in _TENANT_PRIORITIES else 1
        return (rank, name)

    for name, e in sorted(doc['tenants'].items(), key=order):
        lines.append('%-16s %-12s %10d %10d %10d %12d'
                     % (name, e['priority'] or '?', e['admitted'],
                        e['shed'], e['preempted'],
                        e['evicted_pages']))
        if e['shed_reasons']:
            lines.append('     shed by: %s' % '  '.join(
                '%s=%d' % (k, v) for k, v in
                sorted(e['shed_reasons'].items())))
    t = doc['trainer']
    if t:
        lines.append('== co-located trainer')
        ys = t.get('yield_seconds') or {}
        lines.append('   yields %s   currently yielded %s   '
                     'parked mean %s s max %s s'
                     % (t.get('yields', 0),
                        int(t['yielded']) if 'yielded' in t else '?',
                        _fmt_val(ys.get('mean')),
                        _fmt_val(ys.get('max'))))
    return '\n'.join(lines)


# ---------------------------------------------------------- fleet view
_FLEET_STATES = {0: 'UP', 1: 'DRAINING', 2: 'QUARANTINED', 3: 'DEAD'}


def derive_fleet(records):
    """Fleet-controller timeline from a metrics JSONL: the replica
    census over time (from the periodic snapshot records the autoscale
    bench flushes), scale-out/in/heal/quarantine counter deltas per
    snapshot, the final per-replica state machine, and the hedge
    ledger (hedge+failover dispatch rate vs the retry budget). Works
    on counters/gauges alone — no flight ring needed offline."""
    parse = _registry_mod().parse_rendered

    def census_of(rec):
        out = {}
        for rendered, v in rec.get('gauges', {}).items():
            name, labels = parse(rendered)
            if name == 'controller.replicas':
                out.setdefault(labels.get('route', '?'), {})[
                    labels.get('state', '?')] = v
        return out

    def totals_of(rec, names):
        out = dict.fromkeys(names, 0)
        for rendered, v in rec.get('counters', {}).items():
            name, _ = parse(rendered)
            if name in out:
                out[name] += v
        return out

    cnames = ('controller.scale_out_total', 'controller.scale_in_total',
              'controller.heals_total', 'controller.quarantines_total',
              'controller.deaths_total',
              'controller.spawn_failures_total')
    census_timeline, events = [], []
    prev = dict.fromkeys(cnames, 0)
    t0 = None
    for rec in records:
        census = census_of(rec)
        if not census and not any(
                parse(k)[0].startswith('controller.')
                for k in rec.get('counters', {})):
            continue
        ts = rec.get('ts')
        if t0 is None:
            t0 = ts
        t = round(ts - t0, 3) if (ts is not None and
                                  t0 is not None) else None
        if census:
            census_timeline.append({'t': t, 'census': census})
        totals = totals_of(rec, cnames)
        delta = {k.split('.')[1].replace('_total', ''):
                 totals[k] - prev[k]
                 for k in cnames if totals[k] != prev[k]}
        if delta:
            events.append(dict({'t': t}, **delta))
        prev = totals

    last = None
    for rec in records:
        if any(parse(k)[0].startswith('controller.')
               for k in list(rec.get('gauges', {}))
               + list(rec.get('counters', {}))):
            last = rec
    replicas, hedge = {}, {}
    if last is not None:
        for rendered, v in last.get('gauges', {}).items():
            name, labels = parse(rendered)
            if name == 'controller.replica_state':
                replicas[labels.get('replica', '?')] = \
                    _FLEET_STATES.get(int(v), '?')
            elif name == 'router.retry_budget_tokens':
                hedge['retry_budget_tokens'] = v
        hedges = requests = dispatches = failovers = mismatches = 0
        for rendered, v in last.get('counters', {}).items():
            name, _ = parse(rendered)
            if name == 'router.hedge_total':
                hedges += v
            elif name == 'router.requests_total':
                requests += v
            elif name == 'router.dispatch_total':
                dispatches += v
            elif name == 'router.failover_total':
                failovers += v
            elif name == 'router.hedge_mismatch_total':
                mismatches += v
        hedge.update({
            'hedges': hedges, 'requests': requests,
            'failovers': failovers, 'mismatches': mismatches,
            'hedge_fraction': round(hedges / requests, 6)
            if requests else None,
        })
        totals = totals_of(last, cnames)
    else:
        totals = dict.fromkeys(cnames, 0)
    # per-process census of a cross-host run: every replica worker
    # heartbeats worker.up / worker.ready / worker.queue_depth into its
    # own JSONL (host = replica name); the newest record per host wins
    workers = {}
    # controller-estimated per-replica clock offsets (the NTP-style
    # heartbeat exchange) — the numbers tools/fleet_trace.py wants as
    # its per-input :OFFSET_S suffixes
    clock_offsets = {}
    for rec in records:
        doc = None
        for rendered, v in rec.get('gauges', {}).items():
            name, labels = parse(rendered)
            if name.startswith('worker.'):
                if doc is None:
                    doc = {'pid': rec.get('pid')}
                doc[name.split('.', 1)[1]] = v
            elif name == 'rpc.clock_offset_seconds':
                clock_offsets[labels.get('replica', '?')] = v
        if doc is not None:
            workers[str(rec.get('host', '?'))] = doc
    depths = [w['queue_depth'] for w in workers.values()
              if isinstance(w.get('queue_depth'), (int, float))]
    return {
        'census_timeline': census_timeline,
        'scale_events': events,
        'replicas': replicas,
        'workers': workers,
        'queue_depth_skew': round(max(depths) - min(depths), 6)
        if depths else None,
        'clock_offsets': clock_offsets,
        'totals': {k.split('.', 1)[1]: v for k, v in totals.items()},
        'hedge': hedge,
        'phases': derive_phases(records),
    }


def derive_phases(records):
    """Phase-split view of a disaggregated fleet from the snapshot
    JSONL: per-phase replica census (``router.phase_replicas*``
    gauges), handoff count/latency/bytes/dedup (``handoff.*``), and
    the TTFT-vs-inter-token attribution (how much of TTFT the
    prefill+handoff hop explains vs the decode replica's inter-token
    cadence — ``handoff.ttft_attributed_seconds`` against
    ``decode.ttft_seconds`` / ``decode.inter_token_seconds``).
    Empty-dict when the JSONL has no phase/handoff metrics (a
    colocated fleet)."""
    parse = _registry_mod().parse_rendered
    last = None
    for rec in records:
        keys = list(rec.get('gauges', {})) + \
            list(rec.get('counters', {}))
        if any(parse(k)[0].startswith('handoff.')
               or parse(k)[0].startswith('router.phase_')
               for k in keys):
            last = rec
    if last is None:
        return {}
    phases = {}
    for rendered, v in last.get('gauges', {}).items():
        name, labels = parse(rendered)
        if name in ('router.phase_replicas',
                    'router.phase_replicas_ready'):
            ph = phases.setdefault(labels.get('phase', '?'), {})
            key = 'replicas_ready' if name.endswith('_ready') \
                else 'replicas'
            ph[key] = v
    handoff = {}
    for rendered, v in last.get('counters', {}).items():
        name, labels = parse(rendered)
        if name == 'router.phase_dispatch_total':
            ph = phases.setdefault(labels.get('phase', '?'), {})
            ph['dispatched'] = ph.get('dispatched', 0) + v
        elif name == 'handoff.count_total':
            handoff['count'] = handoff.get('count', 0) + v
        elif name == 'handoff.bytes_total':
            handoff['bytes'] = handoff.get('bytes', 0) + v
        elif name == 'handoff.pages_installed_total':
            handoff['pages_installed'] = \
                handoff.get('pages_installed', 0) + v
        elif name == 'handoff.pages_deduped_total':
            handoff['pages_deduped'] = \
                handoff.get('pages_deduped', 0) + v
    attribution = {}
    for rendered, stats in last.get('histograms', {}).items():
        name, labels = parse(rendered)
        if name == 'handoff.seconds':
            handoff['seconds'] = {k: stats.get(k) for k in
                                  ('count', 'mean', 'p50', 'p99')}
        elif name == 'handoff.ttft_attributed_seconds':
            attribution['prefill_plus_handoff'] = {
                k: stats.get(k) for k in ('count', 'mean', 'p99')}
        elif name == 'decode.ttft_seconds':
            key = 'ttft_cached' if labels.get('cached') == '1' \
                else 'ttft_cold'
            attribution[key] = {k: stats.get(k)
                                for k in ('count', 'mean', 'p99')}
        elif name == 'decode.inter_token_seconds':
            attribution['inter_token'] = {
                k: stats.get(k) for k in ('count', 'mean', 'p99')}
    return {'census': phases, 'handoff': handoff,
            'attribution': attribution}


def render_fleet(records):
    doc = derive_fleet(records)
    if not doc['census_timeline'] and not doc['replicas'] and \
            not doc['scale_events'] and not doc.get('phases') and \
            not doc.get('workers'):
        return 'no controller.* or phase/handoff metrics in this JSONL'
    lines = ['== fleet controller timeline']
    for ev in doc['scale_events']:
        what = ', '.join('%s +%d' % (k, v) for k, v in
                         sorted(ev.items()) if k != 't')
        lines.append('   t=%-8s %s' % (ev.get('t'), what))
    if doc['census_timeline']:
        lines.append('== replica census (state counts over time, '
                     'per route)')
        for row in doc['census_timeline']:
            cells = []
            for route in sorted(row['census']):
                c = row['census'][route]
                cells.append('%s[%s]' % (route, ' '.join(
                    '%s=%d' % (k, v) for k, v in sorted(c.items()))))
            lines.append('   t=%-8s %s' % (row['t'], '  '.join(cells)))
    if doc['replicas']:
        lines.append('== final replica states')
        for name in sorted(doc['replicas']):
            lines.append('   %-24s %s' % (name, doc['replicas'][name]))
    if doc.get('workers'):
        lines.append('== worker processes (child-emitted gauges)')
        for host in sorted(doc['workers']):
            w = doc['workers'][host]
            lines.append('   %-24s pid %-8s up %-3s ready %-3s '
                         'queue_depth %s'
                         % (host, w.get('pid', '?'),
                            int(w.get('up', 0)),
                            int(w.get('ready', 0)),
                            w.get('queue_depth', '?')))
        if doc.get('queue_depth_skew') is not None:
            lines.append('   queue depth skew (max-min): %s'
                         % doc['queue_depth_skew'])
    if doc.get('clock_offsets'):
        lines.append('== per-replica clock offsets (controller '
                     'heartbeat estimate, s)')
        for name in sorted(doc['clock_offsets']):
            lines.append('   %-24s %+.*f' % (name, 6,
                                             doc['clock_offsets'][name]))
    h = doc['hedge']
    if h:
        lines.append('== hedged requests vs retry budget')
        lines.append('   requests %s   hedges %s (%s of traffic)   '
                     'failovers %s   mismatches %s   tokens left %s'
                     % (h.get('requests'), h.get('hedges'),
                        ('%.2f%%' % (100 * h['hedge_fraction']))
                        if h.get('hedge_fraction') is not None else '?',
                        h.get('failovers'), h.get('mismatches'),
                        h.get('retry_budget_tokens')))
    ph = doc.get('phases') or {}
    if ph.get('census'):
        lines.append('== phase split (disaggregated fleet)')
        for phase in sorted(ph['census']):
            c = ph['census'][phase]
            lines.append('   %-8s replicas %s (ready %s)  '
                         'dispatched %s'
                         % (phase, c.get('replicas', '?'),
                            c.get('replicas_ready', '?'),
                            c.get('dispatched', 0)))
        h = ph.get('handoff', {})
        if h:
            sec = h.get('seconds') or {}
            lines.append('   handoffs %s   pages installed %s / '
                         'deduped %s   bytes %s   latency mean %s '
                         'p99 %s'
                         % (h.get('count', 0),
                            h.get('pages_installed', 0),
                            h.get('pages_deduped', 0),
                            h.get('bytes', 0),
                            _fmt_val(sec.get('mean')),
                            _fmt_val(sec.get('p99'))))
        att = ph.get('attribution', {})
        if att:
            lines.append('== TTFT vs inter-token attribution')
            for key in ('prefill_plus_handoff', 'ttft_cold',
                        'ttft_cached', 'inter_token'):
                if key in att:
                    s = att[key]
                    lines.append(
                        '   %-22s n=%-6s mean %s   p99 %s'
                        % (key, s.get('count'),
                           _fmt_val(s.get('mean')),
                           _fmt_val(s.get('p99'))))
    t = doc['totals']
    lines.append('== totals: %s' % '  '.join(
        '%s=%d' % (k, v) for k, v in sorted(t.items())))
    return '\n'.join(lines)


def render(rec):
    lines = []
    d = derive(rec)
    head = []
    if d['mfu'] is not None:
        head.append('MFU %.2f%%' % (100.0 * d['mfu']))
    if d['goodput'] is not None:
        head.append('goodput %.2f%%' % (100.0 * d['goodput']))
    if d['steps_per_sec_ema'] is not None:
        head.append('%.4g steps/s' % d['steps_per_sec_ema'])
    if d['overlap_fraction'] is not None:
        head.append('overlap %.2f%%' % (100.0 * d['overlap_fraction']))
    if d['step_flops'] is not None:
        head.append('%.4g FLOPs/step' % d['step_flops'])
    lines.append('== %s (host %s, pid %s, ts %s) %s' % (
        d['kind'] or 'record', d['host'], d['pid'], d['ts'],
        ('— ' + ', '.join(head)) if head else ''))
    hists = d['histograms']
    if hists:
        lines.append('%-52s %8s %12s %12s %12s'
                     % ('Histogram', 'Count', 'P50', 'P95', 'Max'))
        for name in sorted(hists):
            st = hists[name]
            lines.append('%-52s %8d %12.6g %12.6g %12.6g'
                         % (name, st.get('count', 0),
                            st.get('p50') or 0.0, st.get('p95') or 0.0,
                            st.get('max') or 0.0))
    if d['gauges']:
        lines.append('%-52s %14s' % ('Gauge', 'Value'))
        for name in sorted(d['gauges']):
            lines.append('%-52s %14s' % (name, _fmt_val(d['gauges'][name])))
    if d['counters']:
        lines.append('%-52s %14s' % ('Counter', 'Value'))
        for name in sorted(d['counters']):
            lines.append('%-52s %14s'
                         % (name, _fmt_val(d['counters'][name])))
    return '\n'.join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description='Summarize a paddle_tpu.observe metrics JSONL.')
    p.add_argument('path', help='metrics JSONL file')
    p.add_argument('--json', action='store_true',
                   help='emit one machine-readable JSON object')
    p.add_argument('--snapshot', action='store_true',
                   help='use the newest record of any kind, not just '
                        'the newest end-of-run summary')
    p.add_argument('--all-pids', action='store_true',
                   help='report the newest record per pid (multi-child '
                        'bench runs)')
    p.add_argument('--per-host', action='store_true',
                   help='report the newest record per host '
                        '(jax.process_index() — merged multihost '
                        'JSONLs)')
    p.add_argument('--prom', action='store_true',
                   help='emit the chosen record(s) as Prometheus text '
                        'exposition (textfile-collector format)')
    p.add_argument('--slo', action='store_true',
                   help='render the SLO panel: per-route objectives, '
                        'burn rate, goodput, and the top-5 slowest '
                        'sampled trace ids')
    p.add_argument('--fleet', action='store_true',
                   help='render the fleet-controller timeline: replica '
                        'census and scale/heal/quarantine events over '
                        'the JSONL\'s snapshots, final per-replica '
                        'states, and hedge rate vs retry budget')
    p.add_argument('--tenants', action='store_true',
                   help='render the multi-tenant isolation panel: '
                        'per-tenant admitted/shed/preempted/evicted '
                        'pages by priority class, and the co-located '
                        'trainer yield ledger')
    args = p.parse_args(argv)
    if args.json and args.prom:
        sys.stderr.write('metrics_report: --json and --prom are '
                         'mutually exclusive\n')
        return 2
    if (args.slo or args.fleet or args.tenants) and args.prom:
        sys.stderr.write('metrics_report: --slo/--fleet/--tenants and '
                         '--prom are mutually exclusive\n')
        return 2

    records = load_records(args.path)
    if not records:
        sys.stderr.write('metrics_report: no parseable records in %s\n'
                         % args.path)
        return 1
    if args.all_pids or args.per_host:
        group_key = (lambda r: r.get('host', 0)) if args.per_host \
            else (lambda r: r.get('pid'))
        by_key = {}
        for r in records:
            if args.snapshot or r.get('kind') == 'summary':
                by_key[group_key(r)] = r
        chosen = [by_key[k] for k in sorted(by_key, key=str)] \
            or [records[-1]]
    else:
        chosen = [pick(records, any_kind=args.snapshot)]

    try:
        if args.fleet:
            # the timeline wants EVERY record, not one chosen summary
            if args.json:
                print(json.dumps(derive_fleet(records)))
            else:
                print(render_fleet(records))
        elif args.slo:
            if args.json:
                docs = [derive_slo(r) for r in chosen]
                print(json.dumps(docs[0] if len(docs) == 1 else docs))
            else:
                print('\n\n'.join(render_slo(r) for r in chosen))
        elif args.tenants:
            if args.json:
                docs = [derive_tenants(r) for r in chosen]
                print(json.dumps(docs[0] if len(docs) == 1 else docs))
            else:
                print('\n\n'.join(render_tenants(r) for r in chosen))
        elif args.json:
            docs = [derive(r) for r in chosen]
            print(json.dumps(docs[0] if len(docs) == 1 else docs))
        elif args.prom:
            expo = _registry_mod().prometheus_exposition
            sys.stdout.write(''.join(expo(r) for r in chosen))
        else:
            print('\n\n'.join(render(r) for r in chosen))
    except BrokenPipeError:      # `... | head` is a normal way to use this
        try:
            sys.stdout.close()
        except Exception:
            pass
    return 0


if __name__ == '__main__':
    sys.exit(main())
