"""Summarize a paddle_tpu.observe metrics JSONL.

Reads the snapshot/summary lines written by ``observe.enable(jsonl=...)``
(one JSON object per line; bench.py and tools/onchip_watcher.py children
append here, pid-tagged) and prints a human summary: p50/p95/max per
histogram, final counter/gauge values, and the MFU/goodput headline.

    python tools/metrics_report.py ONCHIP_r05_metrics.jsonl
    python tools/metrics_report.py run.jsonl --json | jq .mfu

By default the newest ``kind: "summary"`` line is reported (the
end-of-run state); ``--all-pids`` reports the newest summary per pid,
``--snapshot`` takes the newest line of any kind. ``--json`` emits one
machine-readable object for scripting — a fast test exercises both
paths so this tool cannot bit-rot.
"""

import argparse
import json
import sys


def load_records(path):
    """Parse records, skipping torn lines (concurrent appenders)."""
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def pick(records, any_kind=False):
    """Newest summary record (fallback: newest of any kind)."""
    if not any_kind:
        summaries = [r for r in records if r.get('kind') == 'summary']
        if summaries:
            return summaries[-1]
    return records[-1] if records else None


def derive(rec):
    """Flat scripting-friendly view of one record."""
    gauges = rec.get('gauges', {})
    out = {
        'ts': rec.get('ts'),
        'pid': rec.get('pid'),
        'kind': rec.get('kind'),
        'counters': rec.get('counters', {}),
        'gauges': gauges,
        'histograms': rec.get('histograms', {}),
        'mfu': gauges.get('trainer.mfu'),
        'goodput': gauges.get('run.goodput'),
        'step_flops': gauges.get('executor.step_flops'),
        'steps_per_sec_ema': gauges.get('trainer.steps_per_sec_ema'),
        'host_blocked_seconds':
            gauges.get('trainer.host_blocked_seconds'),
        'device_blocked_seconds':
            gauges.get('trainer.device_blocked_seconds'),
    }
    # pipelined-loop overlap: 1 - (host-blocked + device-blocked)/wall.
    # The trainer publishes its own per-train() figure; reconstruct
    # from the blocked ledgers when only those made it into the record.
    overlap = gauges.get('trainer.pipeline_overlap_fraction')
    if overlap is None:
        hb = out['host_blocked_seconds']
        db = out['device_blocked_seconds']
        wall = gauges.get('run.wall_seconds')
        if hb is not None and db is not None and wall:
            overlap = max(0.0, 1.0 - (hb + db) / wall)
    out['overlap_fraction'] = overlap
    return out


def _fmt_val(v):
    if isinstance(v, float):
        return '%.6g' % v
    return str(v)


def render(rec):
    lines = []
    d = derive(rec)
    head = []
    if d['mfu'] is not None:
        head.append('MFU %.2f%%' % (100.0 * d['mfu']))
    if d['goodput'] is not None:
        head.append('goodput %.2f%%' % (100.0 * d['goodput']))
    if d['steps_per_sec_ema'] is not None:
        head.append('%.4g steps/s' % d['steps_per_sec_ema'])
    if d['overlap_fraction'] is not None:
        head.append('overlap %.2f%%' % (100.0 * d['overlap_fraction']))
    if d['step_flops'] is not None:
        head.append('%.4g FLOPs/step' % d['step_flops'])
    lines.append('== %s (pid %s, ts %s) %s' % (
        d['kind'] or 'record', d['pid'], d['ts'],
        ('— ' + ', '.join(head)) if head else ''))
    hists = d['histograms']
    if hists:
        lines.append('%-52s %8s %12s %12s %12s'
                     % ('Histogram', 'Count', 'P50', 'P95', 'Max'))
        for name in sorted(hists):
            st = hists[name]
            lines.append('%-52s %8d %12.6g %12.6g %12.6g'
                         % (name, st.get('count', 0),
                            st.get('p50') or 0.0, st.get('p95') or 0.0,
                            st.get('max') or 0.0))
    if d['gauges']:
        lines.append('%-52s %14s' % ('Gauge', 'Value'))
        for name in sorted(d['gauges']):
            lines.append('%-52s %14s' % (name, _fmt_val(d['gauges'][name])))
    if d['counters']:
        lines.append('%-52s %14s' % ('Counter', 'Value'))
        for name in sorted(d['counters']):
            lines.append('%-52s %14s'
                         % (name, _fmt_val(d['counters'][name])))
    return '\n'.join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description='Summarize a paddle_tpu.observe metrics JSONL.')
    p.add_argument('path', help='metrics JSONL file')
    p.add_argument('--json', action='store_true',
                   help='emit one machine-readable JSON object')
    p.add_argument('--snapshot', action='store_true',
                   help='use the newest record of any kind, not just '
                        'the newest end-of-run summary')
    p.add_argument('--all-pids', action='store_true',
                   help='report the newest record per pid (multi-child '
                        'bench runs)')
    args = p.parse_args(argv)

    records = load_records(args.path)
    if not records:
        sys.stderr.write('metrics_report: no parseable records in %s\n'
                         % args.path)
        return 1
    if args.all_pids:
        by_pid = {}
        for r in records:
            if args.snapshot or r.get('kind') == 'summary':
                by_pid[r.get('pid')] = r
        chosen = [by_pid[k] for k in sorted(by_pid, key=str)] \
            or [records[-1]]
    else:
        chosen = [pick(records, any_kind=args.snapshot)]

    try:
        if args.json:
            docs = [derive(r) for r in chosen]
            print(json.dumps(docs[0] if len(docs) == 1 else docs))
        else:
            print('\n\n'.join(render(r) for r in chosen))
    except BrokenPipeError:      # `... | head` is a normal way to use this
        try:
            sys.stdout.close()
        except Exception:
            pass
    return 0


if __name__ == '__main__':
    sys.exit(main())
