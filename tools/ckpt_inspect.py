"""Inspect a paddle_tpu checkpoint directory — stdlib only, no jax.

Prints what a checkpoint actually holds before you bet a resume on it:
the step, trainer (epoch / in-epoch step) and reader (epoch / offset /
seed / shard width) state, the WRITING topology (format version, mesh
axis sizes, host count), the per-variable logical sharding specs
recorded in the manifest, and whether the recorded sha1s still match
the installed files (the torn-checkpoint check io.verify_checkpoint
performs — recomputed here without importing paddle_tpu, so it runs on
a bastion host with nothing but python3).

    python tools/ckpt_inspect.py /ckpt/run42                # newest step dir
    python tools/ckpt_inspect.py /ckpt/run42/step_00000012  # one checkpoint
    python tools/ckpt_inspect.py DIR --json | jq .verification
    python tools/ckpt_inspect.py DIR --no-verify            # skip sha1 pass
    python tools/ckpt_inspect.py DIR --vars 50              # longer var table

Companion of ``tools/flight_report.py`` (postmortems) and
``tools/metrics_report.py`` (metrics JSONL).
"""

import argparse
import hashlib
import json
import os
import re
import sys

_STEP_RE = re.compile(r'^step_(\d{8,})$')
_PARAMS_FILE = 'params.npz'
_MANIFEST_FILE = 'manifest.json'


def _sha1_of(path):
    h = hashlib.sha1()
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(1 << 20), b''):
            h.update(chunk)
    return h.hexdigest()


def resolve_dir(dirname):
    """Accept a checkpoint dir OR a managed tree root (pick the newest
    step dir, the same newest-first scan CheckpointManager uses —
    LATEST is a convenience pointer, not the source of truth)."""
    if os.path.exists(os.path.join(dirname, 'checkpoint.json')) or \
            os.path.exists(os.path.join(dirname, _MANIFEST_FILE)):
        return dirname
    steps = []
    try:
        for n in os.listdir(dirname):
            m = _STEP_RE.match(n)
            if m and os.path.isdir(os.path.join(dirname, n)):
                steps.append((int(m.group(1)), os.path.join(dirname, n)))
    except OSError:
        pass
    if not steps:
        raise SystemExit('%s: neither a checkpoint directory nor a '
                         'managed tree with step_* dirs' % dirname)
    return max(steps)[1]


def _verify(dirname, meta):
    """'ok' | 'unverified: ...' | 'torn: ...' — mirrors
    io.verify_checkpoint without importing it."""
    if meta is None:
        return 'unverified: no checkpoint.json (pre-checkpoint legacy '\
               'layout, or the save died before the meta rename)'
    problems = []
    for key, fname in (('params_sha1', _PARAMS_FILE),
                       ('manifest_sha1', _MANIFEST_FILE)):
        want = meta.get(key)
        if want is None:
            problems.append('%s not recorded' % key)
            continue
        fpath = os.path.join(dirname, fname)
        if not os.path.exists(fpath):
            problems.append('%s is missing' % fname)
        elif _sha1_of(fpath) != want:
            problems.append('%s sha1 mismatch' % fname)
    if problems:
        return 'torn: ' + '; '.join(problems)
    return 'ok'


def inspect(dirname, verify=True):
    dirname = resolve_dir(dirname)
    meta = None
    meta_path = os.path.join(dirname, 'checkpoint.json')
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except ValueError:
            return {'kind': 'paddle_tpu_checkpoint', 'dirname': dirname,
                    'verification': 'torn: checkpoint.json does not '
                                    'parse'}
    manifest = {}
    man_path = os.path.join(dirname, _MANIFEST_FILE)
    if os.path.exists(man_path):
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except ValueError:
            manifest = {}
    doc = {
        'kind': 'paddle_tpu_checkpoint',
        'dirname': dirname,
        'step': (meta or {}).get('step'),
        'format_version': (meta or {}).get('format_version'),
        'mesh': (meta or {}).get('mesh'),
        'hosts': (meta or {}).get('hosts'),
        'trainer': (meta or {}).get('trainer'),
        'reader': (meta or {}).get('reader'),
        'verification': (_verify(dirname, meta) if verify
                         else 'skipped (--no-verify)'),
        'n_vars': len(manifest),
        'vars': {name: {'dtype': e.get('dtype'),
                        'shape': e.get('shape'),
                        'spec': e.get('spec')}
                 for name, e in sorted(manifest.items())},
    }
    doc['sharded_vars'] = sorted(
        n for n, e in manifest.items() if e.get('spec'))
    return doc


def _fmt_mesh(mesh, hosts):
    if not mesh:
        return 'not recorded (pre-elastic format: same-topology '\
               'restore only)'
    active = ['%s=%d' % (a, s) for a, s in sorted(mesh.items())
              if int(s) > 1]
    return '%s hosts=%s' % (' '.join(active) or 'unsharded', hosts or 1)


def render(doc, max_vars):
    out = []
    out.append('checkpoint  %s' % doc['dirname'])
    out.append('  step            %s' % doc.get('step'))
    out.append('  format_version  %s%s'
               % (doc.get('format_version'),
                  '' if doc.get('format_version') else
                  '  (pre-elastic)'))
    out.append('  mesh            %s'
               % _fmt_mesh(doc.get('mesh'), doc.get('hosts')))
    tr = doc.get('trainer')
    if tr:
        out.append('  trainer         epoch=%s epoch_step=%s'
                   % (tr.get('epoch'), tr.get('epoch_step')))
    rd = doc.get('reader')
    if rd:
        out.append('  reader          epoch=%s offset=%s seed=%s '
                   'shuffle_buf=%s hosts=%s'
                   % (rd.get('epoch'), rd.get('offset'), rd.get('seed'),
                      rd.get('shuffle_buf'), rd.get('hosts', 1)))
    out.append('  verification    %s' % doc.get('verification'))
    out.append('  vars            %d (%d with a sharded spec)'
               % (doc.get('n_vars', 0), len(doc.get('sharded_vars', []))))
    shown = list(doc.get('vars', {}).items())[:max_vars]
    if shown:
        w = max(len(n) for n, _ in shown)
        for name, e in shown:
            spec = e.get('spec')
            out.append('    %-*s  %-8s %-16s %s'
                       % (w, name, e.get('dtype'),
                          'x'.join(str(d) for d in (e.get('shape') or []))
                          or 'scalar',
                          json.dumps(spec) if spec else ''))
        if len(doc.get('vars', {})) > max_vars:
            out.append('    ... %d more (--vars N to widen)'
                       % (len(doc['vars']) - max_vars))
    return '\n'.join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Inspect a paddle_tpu checkpoint directory.')
    ap.add_argument('dirname', help='checkpoint dir or managed tree root')
    ap.add_argument('--json', action='store_true',
                    help='emit the full machine-readable document')
    ap.add_argument('--no-verify', action='store_true',
                    help='skip the sha1 recompute (large params.npz)')
    ap.add_argument('--vars', type=int, default=20, metavar='N',
                    help='max vars in the text table (default 20)')
    args = ap.parse_args(argv)
    doc = inspect(args.dirname, verify=not args.no_verify)
    if args.json:
        json.dump(doc, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write('\n')
    else:
        print(render(doc, args.vars))
    return 0


if __name__ == '__main__':
    sys.exit(main())
