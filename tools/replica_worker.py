#!/usr/bin/env python
"""Replica worker process — one engine, one PID, one port.

Spawned by ``serving.rpc.ProcessReplicaFactory`` (or by hand) with a
JSON config file::

    python tools/replica_worker.py --config /path/to/replica.json

The config describes the engine this process hosts::

    {"name": "r0", "kind": "serving",          # or "decode"
     "model_dir": "/tmp/model",                 # serving: saved model
     "engine": {"max_batch_size": 8, ...},      # engine kwargs
     "compute_delay_ms": 10.0,                  # serving: chaos floor
     "spec": {"vocab_size": 64, ...},           # decode: LMSpec kwargs
     "weights_npz": "/tmp/w.npz",               # decode: params
     "backend": "cpu",                          # cpu -> force_host_cpu
     "port": 0,                                 # 0 = ephemeral
     "port_file": "/tmp/r0.port",               # where to publish url
     "metrics_jsonl": "/tmp/run-r0.jsonl",      # JSONL beside parent's
     "host_label": "r0"}                        # observe record host

Boot sequence: build + warmup + start the engine, start the observe
diagnostics HTTP server (which carries /readyz, /metrics, /statusz AND
— via ``serving.rpc.serve_engine`` — the POST control plane:
submit/generate/drain/shutdown/state/kv), then atomically publish
``{"url", "port", "pid"}`` to ``port_file``. The parent treats that
file appearing as "worker is up"; /readyz flipping 200 as "worker is
serving". The main loop just heartbeats worker.* gauges into the
JSONL until a remote /rpc/shutdown (or SIGTERM) lands, then exits 0.

Env reads live inside functions only (tools/repo_lint.py enforces the
same env-scoped rule here as for serving/rpc.py); the one env WRITE —
``PADDLE_TPU_OBSERVE_HOST`` from ``host_label`` — happens before any
paddle_tpu import so every metrics record this process emits carries
the replica name as its ``host``.
"""

import argparse
import json
import os
import signal
import sys
import threading


def _publish_port_file(path, doc):
    """Atomic write (tmp + rename): the parent polling this file never
    sees a torn JSON."""
    tmp = '%s.tmp.%d' % (path, os.getpid())
    with open(tmp, 'w') as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _DelayPredictor(object):
    """Fixed per-batch compute floor (same duck-type as bench.py's
    chaos predictor) so cross-host chaos scenarios keep machine-
    independent overload arithmetic."""

    def __init__(self, inner, delay_s):
        self._inner = inner
        self._delay_s = delay_s

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def predict(self, feed):
        import time
        out = self._inner.predict(feed)
        if self._delay_s:
            time.sleep(self._delay_s)
        return out


def _build_engine(cfg):
    kind = cfg.get('kind', 'serving')
    name = cfg.get('name') or 'worker-%d' % os.getpid()
    engine_kw = dict(cfg.get('engine') or {})
    if kind == 'decode':
        import numpy as np

        from paddle_tpu.serving.decode import (DecodeEngine, LMSpec,
                                               random_weights)
        spec = LMSpec(**(cfg.get('spec') or {}))
        if cfg.get('weights_seed') is not None:
            # deterministic init: every process seeding the same way
            # holds bit-identical params (the bit-identity assertion
            # in bench crosshost rides on this)
            engine_kw.setdefault(
                'weights', random_weights(spec,
                                          seed=int(cfg['weights_seed'])))
        eng = DecodeEngine(spec, name=name, **engine_kw)
        wpath = cfg.get('weights_npz')
        if wpath:
            with np.load(wpath) as npz:
                eng.load_weights({k: npz[k] for k in npz.files})
        return eng
    if kind == 'serving':
        from paddle_tpu.inference import create_predictor
        from paddle_tpu.serving import ServingEngine
        pred = create_predictor(cfg['model_dir'])
        delay_ms = float(cfg.get('compute_delay_ms') or 0.0)
        if delay_ms:
            pred = _DelayPredictor(pred, delay_ms / 1000.0)
        return ServingEngine(pred, name=name, **engine_kw)
    raise ValueError('unknown replica kind %r' % kind)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--config', required=True,
                    help='path to the replica JSON config')
    args = ap.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    name = cfg.get('name') or 'worker-%d' % os.getpid()

    # stamp BEFORE any paddle_tpu import: every observe record this
    # process writes carries the replica name as its host field
    os.environ['PADDLE_TPU_OBSERVE_HOST'] = str(
        cfg.get('host_label') or name)

    if cfg.get('backend', 'cpu') == 'cpu':
        from paddle_tpu.core.platform_boot import force_host_cpu
        force_host_cpu()
    from paddle_tpu.core.platform_boot import arm_compile_cache
    arm_compile_cache()

    from paddle_tpu import observe
    from paddle_tpu.serving import rpc

    if cfg.get('metrics_jsonl') or cfg.get('trace_json'):
        observe.enable(jsonl=cfg.get('metrics_jsonl'),
                       trace=cfg.get('trace_json'),
                       every_secs=float(cfg.get('flush_every_s', 0.25)))
    # label this process's span track for the merged fleet Perfetto
    # view (tools/fleet_trace.py): pid -> replica name
    observe.spans().set_process_name(name)

    engine = _build_engine(cfg)
    if callable(getattr(engine, 'warmup', None)):
        engine.warmup()
    engine.start()

    stop = threading.Event()
    binding = rpc.serve_engine(engine, on_shutdown=stop.set)
    # order matters: install OUR stop handler first, THEN arm the
    # flight recorder — its SIGTERM handler dumps the postmortem and
    # chains to the previously installed handler (stop.set), so a
    # SIGTERM both leaves the dump AND exits the main loop cleanly
    terminated = threading.Event()

    def _on_sigterm(*_):
        terminated.set()
        stop.set()
    signal.signal(signal.SIGTERM, _on_sigterm)
    if cfg.get('flight_dump'):
        observe.arm_flight(path=cfg['flight_dump'])

    srv = observe.serve(port=int(cfg.get('port', 0)))
    observe.set_gauge('worker.up', 1, replica=name)
    if cfg.get('port_file'):
        _publish_port_file(cfg['port_file'],
                           {'url': srv.url, 'port': srv.port,
                            'pid': os.getpid(), 'name': name})

    # heartbeat loop: worker.* gauges land in the JSONL so the parent's
    # metrics_report --fleet renders a per-process census; on a
    # snapshot cadence the flight ring re-dumps to the controller-known
    # path, so even a SIGKILL (no handler runs) leaves the controller a
    # recent postmortem of this worker's final seconds
    import time as _time
    snap_every = float(cfg.get('postmortem_snapshot_s', 1.0))
    last_snap = _time.monotonic()
    try:
        while not stop.wait(0.25):
            observe.set_gauge('worker.ready', int(bool(engine.ready())),
                              replica=name)
            observe.set_gauge('worker.queue_depth',
                              int(engine.queue_depth()), replica=name)
            observe.maybe_flush()
            if cfg.get('flight_dump') and \
                    _time.monotonic() - last_snap >= snap_every:
                last_snap = _time.monotonic()
                observe.flight_dump('heartbeat_snapshot')
    finally:
        binding.close()
        try:
            engine.shutdown(drain=False)   # idempotent post-/rpc/shutdown
        except Exception:
            pass
        observe.set_gauge('worker.up', 0, replica=name)
        if cfg.get('flight_dump') and not terminated.is_set():
            # a SIGTERM already dumped with reason='sigterm' (via the
            # arm_flight handler) — don't overwrite that with a clean
            # worker_exit dump
            observe.flight_dump('worker_exit')
        observe.stop_serving()
        observe.disable()                  # exports trace_json if set
    return 0


if __name__ == '__main__':
    sys.exit(main())
