"""Inspect a paddle_tpu kernel-tuning table — stdlib only, no jax.

Prints what the autotuner actually decided before you bet a serving
fleet on it: every (op, shape, dtype) key per device kind, the winning
variant, the measured candidate timings (and the winner's margin over
the runner-up), whether the entry was measured in-process or recorded
for replay, and the writer's jax version. Runs on a bastion host with
nothing but python3 — the same contract as ``tools/ckpt_inspect.py``.

    python tools/tuning_inspect.py /tmp/paddle_tpu_tuning_me.json
    python tools/tuning_inspect.py TABLE --json | jq .tables
    python tools/tuning_inspect.py TABLE --op flash_attention
    python tools/tuning_inspect.py TABLE --device-kind 'TPU v5e'

Schema: paddle_tpu/tuning/table.py (format_version 1). Companion of
``tools/ckpt_inspect.py`` (checkpoints), ``tools/flight_report.py``
(postmortems) and ``tools/metrics_report.py`` (metrics JSONL).
"""

import argparse
import json
import os
import sys

FORMAT_VERSION = 1   # mirrors paddle_tpu.tuning.table.FORMAT_VERSION

# The distributed linear-algebra op family (ISSUE 15): panel/block-size
# entries recorded by tuning.decide_summa_panel / decide_linalg_block.
LINALG_OPS = ('summa_matmul', 'blocked_cholesky', 'blocked_qr')

# Matmul compute-dtype entries (ISSUE 19): fp8(e4m3)-cast vs native,
# recorded by tuning.decide_matmul_dtype. The winner decides whether
# ops.fp8_matmul dispatches at that shape (PADDLE_TPU_FP8_MATMUL
# overrides the table either way).
MATMUL_DTYPE_OPS = ('matmul_dtype',)


def _variant_label(variant):
    if not isinstance(variant, dict):
        return str(variant)
    impl = variant.get('impl', '?')
    extras = ' '.join('%s%s' % (k.replace('block_', 'b'), v)
                      for k, v in sorted(variant.items()) if k != 'impl')
    return ('%s %s' % (impl, extras)).strip()


def inspect(path):
    if not os.path.exists(path):
        raise SystemExit('%s: no such file' % path)
    try:
        with open(path) as f:
            data = json.load(f)
    except ValueError as e:
        return {'kind': 'paddle_tpu_tuning_table', 'path': path,
                'status': 'corrupted: %s' % e}
    status = 'ok'
    ver = data.get('format_version') if isinstance(data, dict) else None
    if ver != FORMAT_VERSION:
        status = ('format_version %r != %d (the loader ignores this '
                  'table and re-measures)' % (ver, FORMAT_VERSION))
    tables = data.get('tables') if isinstance(data, dict) else None
    tables = tables if isinstance(tables, dict) else {}
    doc = {
        'kind': 'paddle_tpu_tuning_table',
        'path': path,
        'status': status,
        'format_version': ver,
        'jax': (data.get('jax') if isinstance(data, dict) else None),
        'device_kinds': sorted(tables),
        'n_entries': sum(len(t) for t in tables.values()
                         if isinstance(t, dict)),
        'tables': {},
    }
    for kind, entries in sorted(tables.items()):
        if not isinstance(entries, dict):
            continue
        rows = {}
        for key, ent in sorted(entries.items()):
            timings = {k: v for k, v in (ent.get('timings') or {}).items()
                       if isinstance(v, (int, float))}
            ran = sorted(v for v in timings.values() if v >= 0)
            margin = None
            if len(ran) >= 2 and ran[0] > 0:
                margin = round(ran[1] / ran[0], 3)
            rows[key] = {
                'winner': _variant_label(ent.get('winner')),
                'winner_variant': ent.get('winner'),
                'timings_ms': {k: (round(v * 1e3, 4) if v >= 0 else
                                   'failed')
                               for k, v in sorted(timings.items())},
                'margin_over_runner_up': margin,
                'mode': ent.get('mode'),
                'ts': ent.get('ts'),
            }
        doc['tables'][kind] = rows

    # linalg family summary: the panel/block winners and their margins
    # in one table — what you check before trusting a pod-scale matmul
    # to a replayed tuning table
    doc['linalg'] = {}
    for kind, rows in doc['tables'].items():
        fam = {}
        for key, e in rows.items():
            if not key.startswith(LINALG_OPS):
                continue
            variant = e.get('winner_variant') or {}
            fam[key] = {
                'op': key.split('|', 1)[0],
                'size': variant.get('panel', variant.get('block')),
                'winner': e['winner'],
                'margin_over_runner_up': e.get('margin_over_runner_up'),
                'mode': e.get('mode'),
            }
        if fam:
            doc['linalg'][kind] = fam

    # matmul dtype summary: where the tuner measured fp8 to win — the
    # shapes at which fp8_matmul will actually dispatch off this table
    doc['matmul_dtype'] = {}
    for kind, rows in doc['tables'].items():
        fam = {}
        for key, e in rows.items():
            if not key.startswith(MATMUL_DTYPE_OPS):
                continue
            variant = e.get('winner_variant') or {}
            fam[key] = {
                'op': key.split('|', 1)[0],
                'shape': key.split('|')[1] if '|' in key else None,
                'winner': variant.get('impl', e['winner']),
                'margin_over_runner_up': e.get('margin_over_runner_up'),
                'mode': e.get('mode'),
            }
        if fam:
            doc['matmul_dtype'][kind] = fam
    return doc


def render(doc):
    out = []
    out.append('tuning table  %s' % doc['path'])
    out.append('  status          %s' % doc.get('status'))
    out.append('  format_version  %s' % doc.get('format_version'))
    out.append('  writer jax      %s' % doc.get('jax'))
    out.append('  device kinds    %s'
               % (', '.join(doc.get('device_kinds', [])) or '(none)'))
    out.append('  entries         %d' % doc.get('n_entries', 0))
    for kind, rows in sorted(doc.get('tables', {}).items()):
        out.append('  [%s]' % kind)
        for key, e in rows.items():
            margin = e.get('margin_over_runner_up')
            out.append('    %s' % key)
            out.append('      winner  %-24s %s%s'
                       % (e['winner'],
                          ('x%.2f vs runner-up' % margin) if margin
                          else '',
                          ('  (%s)' % e['mode']) if e.get('mode') else ''))
            for label, ms in e.get('timings_ms', {}).items():
                out.append('        %-28s %s'
                           % (label, ms if ms == 'failed'
                              else '%.4f ms' % ms))
    if doc.get('linalg'):
        out.append('  linalg panel/block winners')
        for kind, fam in sorted(doc['linalg'].items()):
            out.append('    [%s]' % kind)
            for key, e in sorted(fam.items()):
                margin = e.get('margin_over_runner_up')
                out.append('      %-14s size %-6s %s%s  (%s)'
                           % (e['op'], e.get('size'), key.split('|')[1]
                              if '|' in key else '',
                              (' x%.2f vs runner-up' % margin)
                              if margin else '', e.get('mode')))
    if doc.get('matmul_dtype'):
        out.append('  matmul dtype winners')
        for kind, fam in sorted(doc['matmul_dtype'].items()):
            out.append('    [%s]' % kind)
            for key, e in sorted(fam.items()):
                margin = e.get('margin_over_runner_up')
                out.append('      %-8s %-24s %s%s  (%s)'
                           % (e.get('winner'), e.get('shape') or '',
                              key.split('|')[2] if key.count('|') >= 2
                              else '',
                              (' x%.2f vs runner-up' % margin)
                              if margin else '', e.get('mode')))
    return '\n'.join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Inspect a paddle_tpu kernel-tuning table '
                    '(PADDLE_TPU_TUNING_TABLE).')
    ap.add_argument('path', help='tuning table JSON file')
    ap.add_argument('--json', action='store_true',
                    help='emit the full machine-readable document')
    ap.add_argument('--op', help='only keys of this op '
                                 '(prefix match, e.g. flash_attention)')
    ap.add_argument('--device-kind', help='only this device kind')
    ap.add_argument('--linalg', action='store_true',
                    help='only the distributed linear-algebra family '
                         '(summa_matmul / blocked_cholesky / '
                         'blocked_qr panel+block winners)')
    ap.add_argument('--matmul-dtype', action='store_true',
                    help='only the matmul compute-dtype entries '
                         '(fp8 vs native winners per shape)')
    args = ap.parse_args(argv)
    doc = inspect(args.path)
    if args.device_kind is not None:
        doc['tables'] = {k: v for k, v in doc.get('tables', {}).items()
                         if k == args.device_kind}
        doc['linalg'] = {k: v for k, v in doc.get('linalg', {}).items()
                         if k == args.device_kind}
        doc['matmul_dtype'] = {
            k: v for k, v in doc.get('matmul_dtype', {}).items()
            if k == args.device_kind}
    if args.op:
        doc['tables'] = {
            kind: {key: e for key, e in rows.items()
                   if key.startswith(args.op)}
            for kind, rows in doc.get('tables', {}).items()}
    if args.linalg:
        doc['tables'] = {
            kind: {key: e for key, e in rows.items()
                   if key.startswith(LINALG_OPS)}
            for kind, rows in doc.get('tables', {}).items()}
    if args.matmul_dtype:
        doc['tables'] = {
            kind: {key: e for key, e in rows.items()
                   if key.startswith(MATMUL_DTYPE_OPS)}
            for kind, rows in doc.get('tables', {}).items()}
    if args.json:
        json.dump(doc, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write('\n')
    else:
        print(render(doc))
    return 0


if __name__ == '__main__':
    sys.exit(main())
