"""Render a paddle_tpu flight-recorder postmortem as a timeline.

Reads the JSON written by the flight recorder on the way down
(``observe.flight_dump`` — wired into the trainer's exception path, the
bad-step guards, SIGTERM, and the fault-injection kill; arm it with
``PADDLE_TPU_FLIGHT_DUMP=/path/postmortem.json``) and prints what the
process was doing in its final seconds: the event timeline with
inter-event deltas, loss deltas between consecutive step ends, the
anomaly-detector state at death, and the final metrics headline.

    python tools/flight_report.py postmortem.json
    python tools/flight_report.py postmortem.json --events 30
    python tools/flight_report.py postmortem.json --json | jq .reason

Companion of ``tools/metrics_report.py`` (the whole-run metrics JSONL
view); the postmortem's ``metrics`` field is one snapshot of the same
registry shape, frozen at death.
"""

import argparse
import json
import sys


def load(path):
    with open(path) as f:
        doc = json.load(f)
    if doc.get('kind') != 'paddle_tpu_postmortem':
        raise ValueError('%s is not a paddle_tpu postmortem (kind=%r)'
                         % (path, doc.get('kind')))
    return doc


def _fmt_data(data):
    if not data:
        return ''
    return ' '.join('%s=%s' % (k, data[k]) for k in sorted(data))


def _event_lines(events, limit):
    """Timeline rows: relative/delta timestamps plus Δloss between
    consecutive step_end events (the dying run's trajectory)."""
    shown = events[-limit:] if limit else list(events)
    lines = []
    t_first = shown[0]['ts'] if shown else 0.0
    prev_ts = None
    prev_loss = None
    for ev in shown:
        dt = '' if prev_ts is None else '(+%.3fs)' % (ev['ts'] - prev_ts)
        prev_ts = ev['ts']
        data = dict(ev.get('data') or {})
        extra = ''
        if ev.get('kind') == 'step_end':
            loss = data.get('loss')
            if isinstance(loss, (int, float)):
                if isinstance(prev_loss, (int, float)):
                    extra = '  Δloss=%+.4g' % (loss - prev_loss)
                prev_loss = loss
        lines.append('  %+9.3fs %-10s %-18s %s%s'
                     % (ev['ts'] - t_first, dt, ev.get('kind', '?'),
                        _fmt_data(data), extra))
    return lines


def _headline_metrics(metrics):
    g = metrics.get('gauges', {})
    c = metrics.get('counters', {})
    parts = []
    for label, val, fmt in (
            ('steps', c.get('trainer.steps_total'), '%d'),
            ('goodput', g.get('run.goodput'), '%.2f'),
            ('mfu', g.get('trainer.mfu'), '%.2f'),
            ('steps/s', g.get('trainer.steps_per_sec_ema'), '%.4g'),
            ('bad_steps', c.get('fault.bad_steps_total'), '%d'),
            ('saves', c.get('fault.checkpoint_saves_total'), '%d')):
        if val is not None:
            try:
                parts.append('%s %s' % (label, fmt % val))
            except TypeError:
                parts.append('%s %s' % (label, val))
    return ', '.join(parts)


def render(doc, limit=40):
    lines = []
    lines.append('== paddle_tpu postmortem — reason: %s (pid %s, host %s)'
                 % (doc.get('reason'), doc.get('pid'), doc.get('host')))
    lines.append('   dumped at ts %s after %.3fs up; schema %s'
                 % (doc.get('ts'), doc.get('uptime_seconds') or 0.0,
                    doc.get('schema')))
    exc = doc.get('exception')
    if exc:
        lines.append('   exception: %s: %s'
                     % (exc.get('type'), exc.get('message')))
    head = _headline_metrics(doc.get('metrics') or {})
    if head:
        lines.append('   final metrics: %s' % head)
    anomalies = doc.get('anomalies') or {}
    if anomalies:
        lines.append('anomaly state at death:')
        for sig in sorted(anomalies):
            st = anomalies[sig]
            lines.append('  %-12s score %-10.4g %s (mean %.4g, n=%s)'
                         % (sig, st.get('score') or 0.0,
                            'TRIPPED' if st.get('tripped') else 'ok',
                            st.get('mean') or 0.0, st.get('count')))
    events = doc.get('events') or []
    total = doc.get('events_total', len(events))
    evicted = doc.get('evicted_events', 0)
    shown = min(limit or len(events), len(events))
    lines.append('timeline (last %d of %d events%s):'
                 % (shown, total,
                    ', %d evicted from the ring' % evicted
                    if evicted else ''))
    if events:
        lines.extend(_event_lines(events, limit))
    else:
        lines.append('  (no events recorded)')
    return '\n'.join(lines)


def summarize(doc):
    """Machine-readable --json view."""
    events = doc.get('events') or []
    anomalies = doc.get('anomalies') or {}
    exc = doc.get('exception') or {}
    return {
        'reason': doc.get('reason'),
        'pid': doc.get('pid'),
        'host': doc.get('host'),
        'ts': doc.get('ts'),
        'uptime_seconds': doc.get('uptime_seconds'),
        'exception_type': exc.get('type'),
        'exception_message': exc.get('message'),
        'events_total': doc.get('events_total', len(events)),
        'evicted_events': doc.get('evicted_events', 0),
        'last_event': events[-1] if events else None,
        'last_step': max(
            [e['data']['step'] for e in events
             if e.get('kind') == 'step_end'
             and isinstance((e.get('data') or {}).get('step'), int)]
            or [None], key=lambda v: -1 if v is None else v),
        'tripped': sorted(s for s, st in anomalies.items()
                          if st.get('tripped')),
        'metrics': doc.get('metrics') or {},
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description='Render a paddle_tpu flight-recorder postmortem '
                    'JSON as a timeline of the final events.',
        epilog='See tools/metrics_report.py for the whole-run metrics '
               'JSONL view.')
    p.add_argument('path', help='postmortem JSON '
                               '(observe.flight_dump output)')
    p.add_argument('--events', type=int, default=40, metavar='N',
                   help='show the last N events (default 40; 0 = all)')
    p.add_argument('--json', action='store_true',
                   help='emit one machine-readable JSON object')
    args = p.parse_args(argv)
    try:
        doc = load(args.path)
    except (OSError, ValueError) as e:
        sys.stderr.write('flight_report: %s\n' % e)
        return 1
    if args.json:
        print(json.dumps(summarize(doc), sort_keys=True, default=str))
    else:
        print(render(doc, limit=args.events))
    return 0


if __name__ == '__main__':
    sys.exit(main())
