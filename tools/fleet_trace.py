#!/usr/bin/env python
"""Merge per-process span exports into one fleet-wide Perfetto trace.

Every process in a cross-host serving fleet (controller, replica
workers) exports its own chrome-trace JSON — the controller via
``observe.disable()`` / ``observe.export_trace``, each worker via its
``trace_json`` config key. Those files share a wall-clock timebase
only approximately: replica clocks drift, and a handoff span that
*follows* an RPC admission span can render *before* it if the replica
clock runs early. This tool merges N trace files into ONE Perfetto
file, applying a per-input clock offset (as estimated by the
controller's NTP-style heartbeat exchange — ``rpc.clock_offset_seconds``
gauge, or ``RemoteReplica.clock_offset()``) so every track sits on the
controller's timebase, with each process on its own named (pid, tid)
track::

    python tools/fleet_trace.py \
        --input controller.trace.json \
        --input r0=r0.trace.json:0.0032 \
        --input r1=r1.trace.json:-0.0011 \
        --output fleet.trace.json

Input spec: ``[label=]path[:offset_s]``. The offset is the replica's
clock offset relative to the controller in SECONDS (positive = replica
clock ahead); every event's ``ts`` is shifted by ``-offset*1e6`` µs.
Accepted file shapes: a chrome-trace doc (``{"traceEvents": [...]}``),
an ``/tracez`` doc (``{"spans": [...]}``), or a bare event list.

Because controller-side and replica-side spans of one request share a
trace_id-derived flow id (``reqtrace`` wire propagation), the merged
file renders the full cross-process request path as one connected flow
in Perfetto / chrome://tracing.
"""

import argparse
import json
import os
import sys

__all__ = ['merge_traces', 'load_trace_events', 'parse_input_spec']


def load_trace_events(doc):
    """Extract the event list from any of the accepted trace shapes."""
    if isinstance(doc, list):
        return doc
    if isinstance(doc, dict):
        if isinstance(doc.get('traceEvents'), list):
            return doc['traceEvents']
        if isinstance(doc.get('spans'), list):
            return doc['spans']
    raise ValueError('unrecognized trace shape: expected a list, '
                     '{"traceEvents": [...]}, or {"spans": [...]}')


def parse_input_spec(spec):
    """``[label=]path[:offset_s]`` -> (label_or_None, path, offset_s).

    The offset suffix must parse as a float; a Windows-style drive
    colon would not, so ``C:\\x.json`` stays a path.
    """
    label = None
    if '=' in spec:
        label, spec = spec.split('=', 1)
        label = label or None
    offset = 0.0
    if ':' in spec:
        head, tail = spec.rsplit(':', 1)
        try:
            offset = float(tail)
        except ValueError:
            head = spec
        spec = head
    return label, spec, offset


def merge_traces(inputs):
    """Merge [(label, events, offset_s), ...] into one chrome-trace doc.

    Per input: shift every event's ``ts`` by ``-offset_s*1e6`` µs onto
    the common (controller) timebase, remap colliding pids (two workers
    on different hosts can share a pid) to unique ones, and inject an
    ``M``/process_name metadata event when the input is labeled so each
    process gets a named track in Perfetto. Events sorted by ts.
    """
    merged = []
    used_pids = {}     # (input_index, orig_pid) -> merged pid
    taken = set()
    next_pid = [1]

    def _alloc(idx, pid):
        key = (idx, pid)
        got = used_pids.get(key)
        if got is not None:
            return got
        cand = pid
        while cand in taken:
            cand = next_pid[0]
            next_pid[0] += 1
        taken.add(cand)
        used_pids[key] = cand
        return cand

    for idx, (label, events, offset_s) in enumerate(inputs):
        shift_us = float(offset_s or 0.0) * 1e6
        named_pids = set()
        for ev in events:
            if not isinstance(ev, dict):
                continue
            out = dict(ev)
            pid = _alloc(idx, out.get('pid', 0))
            out['pid'] = pid
            if 'ts' in out and out.get('ph') != 'M':
                try:
                    out['ts'] = float(out['ts']) - shift_us
                except (TypeError, ValueError):
                    pass
            if label and out.get('ph') != 'M':
                args = dict(out.get('args') or {})
                args.setdefault('replica', label)
                out['args'] = args
            if label and pid not in named_pids:
                named_pids.add(pid)
                merged.append({'name': 'process_name', 'ph': 'M',
                               'pid': pid, 'tid': out.get('tid', 0),
                               'args': {'name': label}})
            merged.append(out)
    merged.sort(key=lambda e: (e.get('ph') != 'M',
                               float(e.get('ts', 0) or 0)))
    return {'traceEvents': merged, 'displayTimeUnit': 'ms'}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--input', action='append', default=[],
                    metavar='[LABEL=]PATH[:OFFSET_S]',
                    help='trace file; optional track label and clock '
                         'offset in seconds (positive = that clock '
                         'runs ahead of the controller)')
    ap.add_argument('--output', required=True,
                    help='merged Perfetto JSON path')
    args = ap.parse_args(argv)
    if not args.input:
        ap.error('at least one --input is required')

    inputs = []
    for spec in args.input:
        label, path, offset = parse_input_spec(spec)
        with open(path) as f:
            events = load_trace_events(json.load(f))
        if label is None:
            label = os.path.splitext(os.path.basename(path))[0]
        inputs.append((label, events, offset))

    doc = merge_traces(inputs)
    with open(args.output, 'w') as f:
        json.dump(doc, f)
    print('wrote %s (%d events from %d inputs)'
          % (args.output, len(doc['traceEvents']), len(inputs)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
