"""Lint a serialized Program with the static-analysis passes.

Points at a ``save_inference_model`` directory (or its
``__model__.json`` directly), deserializes the program — no jax, no
devices — and runs every ``paddle_tpu.analysis`` pass over it, using
the feed/fetch names recorded in the model meta. Construction
provenance survives serialization, so diagnostics still name the
``file.py:line`` that appended the offending op.

Usage::

    python tools/program_lint.py /path/to/model_dir
    python tools/program_lint.py model_dir/__model__.json --json
    python tools/program_lint.py model_dir --strict     # warnings fail too

Exit codes: 0 clean (infos allowed), 1 errors found (or, with
--strict, warnings too), 2 unreadable input.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_meta(path):
    if os.path.isdir(path):
        path = os.path.join(path, '__model__.json')
    with open(path) as f:
        return path, json.load(f)


def lint(meta, passes=None):
    """(diagnostics, counts) for a loaded __model__.json meta dict."""
    from paddle_tpu import analysis
    from paddle_tpu.core.serialize import program_from_dict
    program = program_from_dict(meta['program'])
    diags = analysis.run_passes(program,
                                feed_names=meta.get('feed_names'),
                                fetch_names=meta.get('fetch_names'),
                                passes=passes)
    return diags, analysis.summarize(diags)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='static-analysis lint over a serialized Program')
    ap.add_argument('model', help='save_inference_model dir or the '
                                  '__model__.json inside it')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable report on stdout')
    ap.add_argument('--strict', action='store_true',
                    help='non-zero exit on warnings as well as errors')
    ap.add_argument('--pass', dest='passes', action='append',
                    metavar='NAME',
                    help='run only the named pass (repeatable)')
    args = ap.parse_args(argv)

    try:
        path, meta = _load_meta(args.model)
    except (OSError, ValueError) as e:
        print('program_lint: cannot read %s: %s' % (args.model, e),
              file=sys.stderr)
        return 2

    try:
        diags, counts = lint(meta, passes=args.passes)
    except ValueError as e:          # unknown --pass name
        print('program_lint: %s' % e, file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            'model': path,
            'ops': sum(len(b['ops']) for b in meta['program']['blocks']),
            'counts': counts,
            'diagnostics': [d.to_dict() for d in diags],
        }, indent=2, sort_keys=True))
    else:
        for d in diags:
            print(d.format())
        print('%s: %d error(s), %d warning(s), %d info(s)'
              % (path, counts['error'], counts['warning'],
                 counts['info']))

    failed = counts['error'] or (args.strict and counts['warning'])
    return 1 if failed else 0


if __name__ == '__main__':
    sys.exit(main())
