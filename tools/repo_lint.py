"""Repo-wide AST lint for the bug classes that have actually bitten
this codebase — run as a tier-1 test (tests/test_repo_lint.py).

Rules:

- ``import-time-env`` (paddle_tpu/ops/, paddle_tpu/tuning/, and the
  ENV_SCOPED_FILES serving/observe modules): no ``os.environ`` /
  ``os.getenv`` / ``get_flag`` / ``FLAGS`` reads at module import
  time — including class bodies, decorators, and function DEFAULT
  argument expressions (all evaluate at import). An env knob frozen
  at import cannot be flipped per call or per test; this is the exact
  class PR 8 fixed by hand in flash_attention / batch_norm
  (PADDLE_TPU_PALLAS_BLOCK_K read once, forever).
- ``bare-except`` (paddle_tpu/ everywhere): ``except:`` swallows
  KeyboardInterrupt/SystemExit — name the exception.
- ``mutable-default`` (paddle_tpu/ everywhere): list/dict/set literals
  (or list()/dict()/set() calls) as default argument values share one
  instance across every call.

Usage::

    python tools/repo_lint.py                # lint the repo, exit 1 on hits
    python tools/repo_lint.py --root DIR --json
"""

import argparse
import ast
import json
import os
import sys

# Directories (relative to --root) where import-time env/flag reads are
# banned. ops/ and tuning/ lowerings run inside jit-compiled dispatch:
# a knob read at import silently pins the process to its boot-time env.
ENV_SCOPED_DIRS = ('paddle_tpu/ops', 'paddle_tpu/tuning')
# Individual modules under the same ban: long-lived serving-path code
# whose knobs (trace sampling, admission policy) must stay flippable
# per call/per test — the exact class PR 8 fixed in ops/ by hand.
ENV_SCOPED_FILES = ('paddle_tpu/serving/router.py',
                    'paddle_tpu/serving/controller.py',
                    # KV-handoff knobs (PADDLE_TPU_HANDOFF_VERIFY /
                    # HANDOFF_WORKERS) must stay per-call reads
                    'paddle_tpu/serving/handoff.py',
                    'paddle_tpu/serving/decode/prefix_cache.py',
                    'paddle_tpu/serving/decode/spec.py',
                    'paddle_tpu/observe/slo.py',
                    'paddle_tpu/observe/reqtrace.py',
                    # quantization knobs (PADDLE_TPU_QUANT_ALLREDUCE /
                    # QUANT_BLOCK / KV_DTYPE) must stay per-call reads
                    'paddle_tpu/quant/__init__.py',
                    'paddle_tpu/quant/core.py',
                    'paddle_tpu/quant/ptq.py',
                    'paddle_tpu/parallel/collective.py',
                    # cross-host RPC knobs (timeouts, verify default)
                    # must stay per-call reads
                    'paddle_tpu/serving/rpc.py',
                    # tenant quota knobs (PADDLE_TPU_TENANT_*) must
                    # stay per-call reads
                    'paddle_tpu/serving/tenancy.py',
                    # PADDLE_TPU_SHARD_OPT_STATE (ISSUE 19) must stay
                    # a per-transpile read
                    'paddle_tpu/parallel/transpiler.py',
                    # fleet federation poll cadence
                    # (PADDLE_TPU_FLEET_POLL_S) must stay a per-cycle
                    # read so tests can speed it up live
                    'paddle_tpu/observe/fleet.py')
LINT_ROOT = 'paddle_tpu'

# files OUTSIDE the lint root that still get the full env-scoped lint —
# the replica worker entrypoint runs paddle_tpu code in a fresh process
# and must not freeze env at import either
EXTRA_ENV_SCOPED_FILES = ('tools/replica_worker.py',
                          'tools/fleet_trace.py')

_ENV_ATTRS = ('environ', 'getenv')
_ENV_NAMES = ('environ', 'getenv', 'get_flag', 'FLAGS')
_MUTABLE_CALLS = ('list', 'dict', 'set')


class Violation(object):
    __slots__ = ('path', 'line', 'code', 'message')

    def __init__(self, path, line, code, message):
        self.path = path
        self.line = line
        self.code = code
        self.message = message

    def to_dict(self):
        return {'path': self.path, 'line': self.line, 'code': self.code,
                'message': self.message}

    def format(self):
        return '%s:%d: [%s] %s' % (self.path, self.line, self.code,
                                   self.message)


def _is_env_read(node):
    """True for os.environ / os.getenv / <x>.environ / bare environ /
    getenv / get_flag / FLAGS references."""
    if isinstance(node, ast.Attribute) and node.attr in _ENV_ATTRS:
        return True
    if isinstance(node, ast.Name) and node.id in _ENV_NAMES:
        return True
    return False


def _walk_import_time(body, visit):
    """Visit every expression that evaluates at module import: module
    statements, class bodies, decorators, and function default args —
    but NOT function bodies."""
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                visit(d)
            a = node.args
            for default in list(a.defaults) + [d for d in a.kw_defaults
                                               if d is not None]:
                visit(default)
        elif isinstance(node, ast.ClassDef):
            for d in node.decorator_list:
                visit(d)
            _walk_import_time(node.body, visit)
        else:
            visit(node)


def lint_source(path, source, env_scoped=False):
    """Violations for one file's source text."""
    out = []
    try:
        tree = ast.parse(source, path)
    except SyntaxError as e:
        out.append(Violation(path, e.lineno or 0, 'syntax-error', str(e)))
        return out

    if env_scoped:
        def visit(expr):
            for sub in ast.walk(expr):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    # deferred bodies are fine; their defaults are
                    # re-visited by _walk_import_time only at top level,
                    # which is the case that matters (nested defs whose
                    # defaults read env at import are vanishingly rare)
                    continue
                if _is_env_read(sub):
                    out.append(Violation(
                        path, sub.lineno, 'import-time-env',
                        'environment/flag read at module import time — '
                        'read it inside the function (per call) instead'))
        _walk_import_time(tree.body, visit)

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(Violation(
                path, node.lineno, 'bare-except',
                "bare 'except:' catches KeyboardInterrupt/SystemExit — "
                'name the exception (Exception at the widest)'))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            for default in list(a.defaults) + [d for d in a.kw_defaults
                                               if d is not None]:
                bad = isinstance(default, (ast.List, ast.Dict, ast.Set,
                                           ast.ListComp, ast.DictComp,
                                           ast.SetComp))
                if not bad and isinstance(default, ast.Call) and \
                        isinstance(default.func, ast.Name) and \
                        default.func.id in _MUTABLE_CALLS:
                    bad = True
                if bad:
                    out.append(Violation(
                        path, default.lineno, 'mutable-default',
                        'mutable default argument in %r shares one '
                        'instance across calls — default to None'
                        % node.name))
    return out


def lint_tree(root):
    """Violations over <root>/paddle_tpu/**.py."""
    violations = []
    scoped = tuple(os.path.join(root, d.replace('/', os.sep)) + os.sep
                   for d in ENV_SCOPED_DIRS)
    scoped_files = frozenset(os.path.join(root, f.replace('/', os.sep))
                             for f in ENV_SCOPED_FILES)
    top = os.path.join(root, LINT_ROOT)
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = sorted(d for d in dirnames if d != '__pycache__')
        for fname in sorted(filenames):
            if not fname.endswith('.py'):
                continue
            path = os.path.join(dirpath, fname)
            env_scoped = path.startswith(scoped) or path in scoped_files
            try:
                with open(path, encoding='utf-8') as f:
                    source = f.read()
            except OSError as e:
                violations.append(Violation(path, 0, 'unreadable',
                                            str(e)))
                continue
            violations.extend(lint_source(
                os.path.relpath(path, root), source,
                env_scoped=env_scoped))
    for rel in EXTRA_ENV_SCOPED_FILES:
        path = os.path.join(root, rel.replace('/', os.sep))
        try:
            with open(path, encoding='utf-8') as f:
                source = f.read()
        except OSError:
            continue                 # entrypoint not present in this tree
        violations.extend(lint_source(rel, source, env_scoped=True))
    return violations


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='repo-wide AST lint (import-time env reads, bare '
                    'except, mutable defaults)')
    ap.add_argument('--root', default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), help='repo root (contains '
                                          'paddle_tpu/)')
    ap.add_argument('--json', action='store_true')
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    if args.json:
        print(json.dumps({
            'root': args.root,
            'violations': [v.to_dict() for v in violations],
            'count': len(violations),
        }, indent=2, sort_keys=True))
    else:
        for v in violations:
            print(v.format())
        print('repo_lint: %d violation(s)' % len(violations))
    return 1 if violations else 0


if __name__ == '__main__':
    sys.exit(main())
