"""Per-shape ResNet-50 conv microbench: fwd vs dgrad vs wgrad.

The anatomy (bench.py resnet50_anatomy) says WHERE the step time goes at
phase granularity (fwd vs bwd+update); this says WHICH conv directions
are slow at op granularity, so the bwd gap (VERDICT r3 #2) can be
attacked shape by shape. Times each representative ResNet-50 conv shape
(batch 64, NHWC, bf16) three ways inside one jitted fori_loop — forward
conv, input gradient, filter gradient — chaining iterations through the
data so the relay cannot memoize (SURVEY §5.1), syncing via np.asarray
(block_until_ready returns at enqueue on the relay).

Run: python tools/conv_bwd_microbench.py [--inner 8] [--batch 64]
Prints one JSON line per shape with ms and achieved TFLOP/s per leg.
"""

import argparse
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# (H, W, Cin, Cout, k, stride, count) — count = how many times the shape
# appears in ResNet-50 so the weighted total reconstructs the step.
SHAPES = [
    (224, 224, 3, 64, 7, 2, 1),      # conv1
    (56, 56, 64, 64, 1, 1, 1),       # stage2 reduce (first block)
    (56, 56, 64, 64, 3, 1, 3),       # stage2 3x3
    (56, 56, 64, 256, 1, 1, 3),      # stage2 expand
    (56, 56, 256, 64, 1, 1, 2),      # stage2 reduce (later blocks)
    (56, 56, 256, 512, 1, 2, 1),     # stage3 shortcut
    (56, 56, 256, 128, 1, 2, 1),     # stage3 reduce s2
    (28, 28, 128, 128, 3, 1, 4),     # stage3 3x3
    (28, 28, 128, 512, 1, 1, 4),     # stage3 expand
    (28, 28, 512, 128, 1, 1, 3),     # stage3 reduce
    (28, 28, 512, 1024, 1, 2, 1),    # stage4 shortcut
    (28, 28, 512, 256, 1, 2, 1),     # stage4 reduce s2
    (14, 14, 256, 256, 3, 1, 6),     # stage4 3x3
    (14, 14, 256, 1024, 1, 1, 6),    # stage4 expand
    (14, 14, 1024, 256, 1, 1, 5),    # stage4 reduce
    (14, 14, 1024, 2048, 1, 2, 1),   # stage5 shortcut
    (14, 14, 1024, 512, 1, 2, 1),    # stage5 reduce s2
    (7, 7, 512, 512, 3, 1, 3),       # stage5 3x3
    (7, 7, 512, 2048, 1, 1, 3),      # stage5 expand
    (7, 7, 2048, 512, 1, 1, 2),      # stage5 reduce
]


def conv(x, w, stride):
    pad = (w.shape[0] - 1) // 2
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))


def time_leg(fn, args, inner, chain):
    """Run `fn` inner times inside one jit, chaining via `chain` so the
    relay can't memoize; return per-iteration seconds."""
    def many(args):
        def body(_, carry):
            return chain(carry, fn(*carry))
        return jax.lax.fori_loop(0, inner, body, args)

    jmany = jax.jit(many)
    out1 = jmany(args)          # compile + warm; outputs feed timed call
    np.asarray(out1[0][..., 0])
    t0 = time.perf_counter()
    out2 = jmany(out1)
    np.asarray(out2[0][..., 0])
    return (time.perf_counter() - t0) / inner


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--inner', type=int, default=8)
    p.add_argument('--batch', type=int, default=64)
    args = p.parse_args()
    rng = np.random.RandomState(0)
    totals = {'fwd': 0.0, 'dgrad': 0.0, 'wgrad': 0.0}
    for (h, w_, cin, cout, k, s, count) in SHAPES:
        x0 = jnp.asarray(rng.randn(args.batch, h, w_, cin) * 0.1,
                         jnp.bfloat16)
        w0 = jnp.asarray(rng.randn(k, k, cin, cout) * 0.1, jnp.bfloat16)
        fwd = functools.partial(conv, stride=s)
        ho, wo = -(-h // s), -(-w_ // s)
        flops = 2.0 * args.batch * ho * wo * cout * cin * k * k

        def dgrad(x, w):
            return jax.grad(
                lambda x: fwd(x, w).astype(jnp.float32).sum())(x)

        def wgrad(x, w):
            return jax.grad(
                lambda w: fwd(x, w).astype(jnp.float32).sum())(w)

        res = {'shape': '%dx%dx%d->%d k%d s%d x%d'
                        % (h, w_, cin, cout, k, s, count)}
        legs = {
            # fwd: chain y back into x (shapes differ; fold via mean)
            'fwd': (fwd, lambda c, y: (
                c[0] + 1e-3 * jnp.mean(y).astype(c[0].dtype), c[1])),
            'dgrad': (dgrad, lambda c, dx: (c[0] + 1e-3 * dx, c[1])),
            'wgrad': (wgrad, lambda c, dw: (c[0], c[1] + 1e-3 * dw)),
        }
        for name, (fn, chain) in legs.items():
            dt = time_leg(fn, (x0, w0), args.inner, chain)
            res[name + '_ms'] = round(dt * 1e3, 3)
            res[name + '_tflops'] = round(flops / dt / 1e12, 1)
            totals[name] += dt * count
        print(json.dumps(res), flush=True)
    print(json.dumps({'weighted_totals_ms':
                      {k: round(v * 1e3, 2) for k, v in totals.items()}}),
          flush=True)


if __name__ == '__main__':
    main()
