"""Load generator for the decode engine — tokens/sec and inter-token
latency under continuous batching.

Drives a `DecodeEngine` (tiny built-in decoder-only LM by default) with
closed-loop clients streaming generations, and reports decode
throughput plus the latency numbers that matter for token streaming:

    python tools/decode_bench.py --duration 3 --clients 8
    python tools/decode_bench.py --json | jq .inter_token_ms.p99

The loop discipline comes from paddle_tpu.serving.loadgen (shared with
tools/serving_bench.py); each client iterates its GenerationStream and
records per-token gaps, so `inter_token_ms` measures what a streaming
caller actually sees — including stalls from prefill insertions and
pool-exhaustion preemptions (visible as p99 spikes; cross-check the
flight recorder / decode.preemptions_total).

Prefix caching and speculative decoding are first-class here:
`--shared-prefix 0.95 --shared-prefix-len 24` makes 95% of requests
open with one shared system prompt (the fleet-realistic mix),
`--prefix-cache` turns the radix prefix cache on (watch
`cache_hit_rate`, `prefill_tokens_skipped`, and the cached-vs-cold
`ttft_ms` split), and `--spec-k K` turns on draft-and-verify decoding
(watch `accepted_draft_length` p50/mean and tokens/sec vs the k=0
baseline), and `--kv-dtype int8` (or fp8/bf16) quantizes the KV page
arena — `kv_bytes_per_token` and `resident_seqs_peak` report the
capacity side of that trade so it is measured, not asserted.

Metrics land in the standard observe pipeline (--metrics-jsonl /
PADDLE_TPU_METRICS_JSONL -> tools/metrics_report.py). --json emits one
machine-readable object; its schema is asserted by
tests/test_decode_serving.py so this tool cannot rot.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main(argv=None):
    p = argparse.ArgumentParser(
        description='paddle_tpu.serving.decode load generator')
    p.add_argument('--duration', type=float, default=3.0,
                   help='seconds of load after warmup')
    p.add_argument('--clients', type=int, default=4,
                   help='closed-loop concurrent streaming clients')
    p.add_argument('--max-batch', type=int, default=8)
    p.add_argument('--block-size', type=int, default=16)
    p.add_argument('--num-blocks', type=int, default=256)
    p.add_argument('--pages-per-seq', type=int, default=8)
    p.add_argument('--max-queue-depth', type=int, default=64)
    p.add_argument('--prompt-lo', type=int, default=4)
    p.add_argument('--prompt-hi', type=int, default=32)
    p.add_argument('--max-new', type=int, default=32,
                   help='max generated tokens per request')
    p.add_argument('--temperature', type=float, default=0.0)
    p.add_argument('--prefix-cache', action='store_true',
                   help='enable the global radix prefix cache')
    p.add_argument('--spec-k', type=int, default=0,
                   help='speculative decoding draft length (0 = off)')
    p.add_argument('--kv-dtype', default='fp32',
                   choices=['fp32', 'bf16', 'int8', 'fp8'],
                   help='KV arena storage dtype (int8/fp8 carry '
                        'per-row fp32 scales; watch '
                        'kv_bytes_per_token and resident_seqs_peak '
                        'for the capacity win)')
    p.add_argument('--shared-prefix', type=float, default=0.0,
                   help='fraction of requests opening with one shared '
                        'system prompt (0..1)')
    p.add_argument('--shared-prefix-len', type=int, default=0,
                   help='shared system prompt length in tokens '
                        '(default: half the per-sequence capacity '
                        'headroom)')
    p.add_argument('--vocab', type=int, default=1000)
    p.add_argument('--n-layer', type=int, default=2)
    p.add_argument('--n-head', type=int, default=4)
    p.add_argument('--d-model', type=int, default=128)
    p.add_argument('--d-inner', type=int, default=256)
    p.add_argument('--no-warmup', action='store_true',
                   help='skip AOT warmup (shows live-compile cost)')
    p.add_argument('--metrics-jsonl', default=None,
                   help='observe JSONL path (or set '
                        'PADDLE_TPU_METRICS_JSONL)')
    p.add_argument('--json', action='store_true',
                   help='emit one machine-readable JSON object')
    args = p.parse_args(argv)

    from paddle_tpu import observe
    from paddle_tpu.serving.decode import DecodeEngine, LMSpec
    from paddle_tpu.serving.loadgen import Stats, closed_loop, percentiles

    jsonl = args.metrics_jsonl or os.environ.get(
        'PADDLE_TPU_METRICS_JSONL')
    observe.enable(jsonl=jsonl)

    d_head = max(8, args.d_model // args.n_head)
    spec = LMSpec(vocab_size=args.vocab, n_layer=args.n_layer,
                  n_head=args.n_head, d_key=d_head, d_value=d_head,
                  d_model=args.d_model, d_inner=args.d_inner)
    engine = DecodeEngine(spec, max_batch=args.max_batch,
                          block_size=args.block_size,
                          num_blocks=args.num_blocks,
                          pages_per_seq=args.pages_per_seq,
                          max_queue_depth=args.max_queue_depth,
                          prefix_cache=args.prefix_cache or None,
                          spec_k=args.spec_k or None,
                          kv_dtype=args.kv_dtype)
    capacity = engine.capacity
    prompt_hi = min(args.prompt_hi, max(args.prompt_lo,
                                        capacity - args.max_new))
    shared = []
    if args.shared_prefix > 0.0:
        n_shared = args.shared_prefix_len or \
            max(args.block_size, (prompt_hi - args.prompt_lo) // 2)
        n_shared = min(n_shared, max(1, prompt_hi - 1))
        shared = np.random.RandomState(1234).randint(
            0, args.vocab, n_shared).tolist()

    t_w0 = time.perf_counter()
    signatures = 0 if args.no_warmup else engine.warmup()
    warmup_s = time.perf_counter() - t_w0
    engine.start()

    stats = Stats()
    gaps = []
    gaps_mu = __import__('threading').Lock()
    token_count = [0]

    def do_request(rng):
        plen = int(rng.randint(args.prompt_lo, prompt_hi + 1))
        if shared and rng.rand() < args.shared_prefix:
            tail = max(1, plen - len(shared))
            prompt = shared + rng.randint(0, args.vocab, tail).tolist()
        else:
            prompt = rng.randint(0, args.vocab, plen).tolist()
        stream = engine.submit(prompt, max_new_tokens=args.max_new,
                               temperature=args.temperature,
                               seed=int(rng.randint(1 << 30)))
        n, t_prev, local_gaps = 0, None, []
        for _tok in stream:
            now = time.perf_counter()
            if t_prev is not None:
                local_gaps.append(now - t_prev)
            t_prev = now
            n += 1
        with gaps_mu:
            gaps.extend(local_gaps)
            token_count[0] += n
        return n

    t0 = time.perf_counter()
    closed_loop(do_request, stats, t0 + args.duration, args.clients)
    engine.shutdown(drain=True)
    wall = time.perf_counter() - t0

    snap = observe.snapshot()
    counters = snap['counters']
    hists = snap['histograms']
    misses = sum(v for k, v in counters.items()
                 if k.startswith('executor.cache_miss_total'))
    occ = hists.get('decode.batch_occupancy', {})

    hit = counters.get('decode.prefix_cache_lookups_total{outcome=hit}',
                       0)
    miss = counters.get(
        'decode.prefix_cache_lookups_total{outcome=miss}', 0)
    acc = hists.get('decode.spec_accepted_len', {})

    def _ms(h):
        return {k: (round(h[k] * 1000.0, 3)
                    if h.get(k) is not None else None)
                for k in ('p50', 'p95', 'p99', 'mean') if k in h} \
            if h else None

    report = {
        'duration_s': round(wall, 4),
        'clients': args.clients,
        'requests_ok': stats.ok,
        'requests_rejected': stats.rejected,
        'requests_errored': stats.errors,
        'tokens': token_count[0],
        'tokens_per_s': round(token_count[0] / wall, 2) if wall else None,
        'requests_per_s': round(stats.ok / wall, 2) if wall else None,
        'request_ms': percentiles(stats.latencies),
        'inter_token_ms': percentiles(gaps),
        'batch_occupancy_mean': occ.get('mean'),
        'preemptions': counters.get('decode.preemptions_total', 0),
        'pool_exhausted': counters.get('decode.pool_exhausted_total', 0),
        'kv_blocks_free_end': engine.pool.free_blocks(),
        # capacity: most sequences ever page-resident at once, and what
        # one cached token costs at this arena dtype — measure, don't
        # assert, the quantized-KV win
        'resident_seqs_peak': engine.resident_seqs_peak,
        'kv_bytes_per_token': engine.kv_bytes_per_token,
        # prefix cache: lookup hit rate, tokens whose prefill was
        # skipped (the shared spans mapped from cached pages), and
        # time-to-first-token split by hit/miss — the TTFT delta IS
        # the cache's latency win
        'cache_hit_rate': round(hit / float(hit + miss), 4)
        if (hit + miss) else None,
        'prefill_tokens_skipped':
            counters.get('decode.prefix_tokens_reused_total', 0),
        'prefix_evictions':
            counters.get('decode.prefix_evictions_total', 0),
        'ttft_ms': {
            'cached': _ms(hists.get('decode.ttft_seconds{cached=1}')),
            'cold': _ms(hists.get('decode.ttft_seconds{cached=0}')),
        },
        # speculative decoding: accepted draft tokens per verify step
        # (0 means the draft never helped; > 1 means multi-token steps)
        'accepted_draft_length': {
            'p50': acc.get('p50'), 'mean': acc.get('mean'),
            'max': acc.get('max'),
        } if acc else None,
        'spec_steps': counters.get('decode.spec_steps_total', 0),
        'warmup': {'signatures': signatures,
                   'seconds': round(warmup_s, 4)},
        'executor': {'cache_misses': misses},
        'engine': {'max_batch': args.max_batch,
                   'block_size': args.block_size,
                   'num_blocks': args.num_blocks,
                   'pages_per_seq': args.pages_per_seq,
                   'capacity_tokens': capacity,
                   'prompt_buckets': engine.prompt_buckets,
                   'prefix_cache': engine.prefix_cache_on,
                   'spec_k': engine.spec_k,
                   'kv_dtype': engine.kv_dtype},
        'workload': {'shared_prefix': args.shared_prefix,
                     'shared_prefix_len': len(shared)},
        'model': {'vocab': args.vocab, 'n_layer': args.n_layer,
                  'n_head': args.n_head, 'd_model': args.d_model},
    }
    observe.disable()

    if args.json:
        print(json.dumps(report))
    else:
        it = report['inter_token_ms']
        rq = report['request_ms']
        print('decode_bench: %d clients, %.2fs' % (args.clients, wall))
        print('  requests   ok=%d rejected=%d errored=%d (%.1f req/s)'
              % (stats.ok, stats.rejected, stats.errors,
                 report['requests_per_s'] or 0.0))
        print('  tokens     %d (%.1f tok/s), mean batch occupancy %.2f'
              % (token_count[0], report['tokens_per_s'] or 0.0,
                 occ.get('mean') or 0.0))
        if it['p50'] is not None:
            print('  inter-token ms p50=%.2f p95=%.2f p99=%.2f max=%.2f'
                  % (it['p50'], it['p95'], it['p99'], it['max']))
        if rq['p50'] is not None:
            print('  request ms  p50=%.2f p95=%.2f p99=%.2f'
                  % (rq['p50'], rq['p95'], rq['p99']))
        print('  pool       preemptions=%d exhaustion-events=%d '
              'free-at-end=%d/%d'
              % (report['preemptions'], report['pool_exhausted'],
                 engine.pool.free_blocks(), args.num_blocks))
        print('  kv         dtype=%s bytes/token=%d '
              'resident-seqs-peak=%d'
              % (engine.kv_dtype, engine.kv_bytes_per_token,
                 report['resident_seqs_peak']))
        if report['cache_hit_rate'] is not None:
            tt = report['ttft_ms']

            def fmt(h):
                return '%.2f' % h['p50'] if h and \
                    h.get('p50') is not None else '-'
            print('  prefix     hit-rate=%.2f prefill-tokens-skipped=%d '
                  'evictions=%d ttft-p50 cached=%sms cold=%sms'
                  % (report['cache_hit_rate'],
                     report['prefill_tokens_skipped'],
                     report['prefix_evictions'],
                     fmt(tt['cached']), fmt(tt['cold'])))
        if report['accepted_draft_length']:
            a = report['accepted_draft_length']
            print('  spec       k=%d accepted-draft-len p50=%s mean=%.2f'
                  % (engine.spec_k, a['p50'], a['mean'] or 0.0))
        print('  compiles   %d warmup signatures in %.2fs; %d total '
              'misses' % (signatures, warmup_s, misses))
    return 0


if __name__ == '__main__':
    sys.exit(main())
