"""Fleet serving observability (ISSUE 10): per-request distributed
tracing across threads (flow events, trace ids, histogram exemplars,
/tracez?trace_id=), the SLO layer (objectives, burn rate, goodput,
predicted p99), the multi-replica router (least-loaded + affinity
placement, failover on replica kill, SLO-aware admission), the
loadgen's time-varying QPS schedules, metrics_report --slo, and the
bench.py fleet chaos scenario's acceptance contract."""

import json
import os
import subprocess
import sys
import threading
import time

from concurrent.futures import Future

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observe
from paddle_tpu.observe import reqtrace
from paddle_tpu.observe.slo import Objective, SloTracker
from paddle_tpu.serving import (EngineClosedError,
                                NoReplicaAvailableError, QueueFullError,
                                Router, ServingEngine, SLOShedError)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _observe_clean():
    from paddle_tpu.observe import diagnostics
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.disable()
    observe.reset()
    with diagnostics._checks_lock:
        diagnostics._checks.clear()
    os.environ.pop('PADDLE_TPU_TRACE_SAMPLE', None)


def _save_mlp(dirname, in_dim=6):
    x = fluid.layers.data(name='x', shape=[in_dim], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    out = fluid.layers.fc(input=h, size=3, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(dirname, ['x'], [out], exe)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    return dirname


def _engine(model_dir, name, **kw):
    from paddle_tpu.inference import create_predictor
    pred = create_predictor(model_dir, place=fluid.CPUPlace())
    kw.setdefault('max_batch_size', 4)
    kw.setdefault('batch_timeout_ms', 1.0)
    eng = ServingEngine(pred, name=name, **kw)
    eng.warmup()
    eng.start()
    return eng


# ------------------------------------------------- cross-thread spans
def test_flow_events_link_threads():
    """spans satellite: flow_begin on the producer thread +
    flow_step/flow_end on a consumer thread emit linked s/t/f events
    with one shared id across two tids."""
    observe.enable()
    rec = observe.spans()
    handle = rec.flow_begin('handoff', attrs={'k': 'v'})
    done = threading.Event()

    def consumer():
        rec.flow_step(handle)
        rec.flow_end(handle)
        done.set()

    t = threading.Thread(target=consumer)
    t.start()
    t.join()
    assert done.is_set()
    flows = [e for e in rec.events() if e.get('cat') == 'flow']
    assert [e['ph'] for e in flows] == ['s', 't', 'f']
    assert len({e['id'] for e in flows}) == 1
    assert len({e['tid'] for e in flows}) == 2   # producer + consumer
    assert flows[-1]['bp'] == 'e'                # arrowhead binding


def test_add_span_explicit_interval_and_instant():
    observe.enable()
    rec = observe.spans()
    t0 = time.perf_counter()
    rec.add_span('stage', t0, t0 + 0.25, attrs={'trace_id': 'abc'})
    rec.add_instant('mark', attrs={'trace_id': 'abc'})
    evs = rec.events()
    span = next(e for e in evs if e['name'] == 'stage')
    assert span['ph'] == 'X'
    assert abs(span['dur'] - 250000.0) < 1000.0     # microseconds
    mark = next(e for e in evs if e['name'] == 'mark')
    assert mark['ph'] == 'i' and mark['s'] == 't'
    # the thread-local begin/end stack API is unchanged alongside
    with observe.span('nested'):
        pass
    assert any(e['name'] == 'nested' for e in rec.events())


# ------------------------------------------------------ request context
def test_sample_rate_reads_env_per_call():
    assert reqtrace.sample_rate({}) == 0.0
    assert reqtrace.sample_rate({'PADDLE_TPU_TRACE_SAMPLE': '1'}) == 1.0
    assert reqtrace.sample_rate({'PADDLE_TPU_TRACE_SAMPLE': '0.5'}) == 0.5
    assert reqtrace.sample_rate({'PADDLE_TPU_TRACE_SAMPLE': '7'}) == 1.0
    assert reqtrace.sample_rate({'PADDLE_TPU_TRACE_SAMPLE': 'zzz'}) == 0.0
    # per-call: flipping the env var flips fresh contexts, no reimport
    observe.enable()
    os.environ['PADDLE_TPU_TRACE_SAMPLE'] = '1'
    assert reqtrace.new_context('r').sampled
    os.environ['PADDLE_TPU_TRACE_SAMPLE'] = '0'
    assert not reqtrace.new_context('r').sampled


def test_context_deadline_and_unsampled_noops():
    observe.enable()
    ctx = reqtrace.new_context('r', deadline_s=30.0, sample=0.0)
    assert not ctx.sampled and ctx.trace_id is None
    assert 29.0 < ctx.remaining() <= 30.0
    assert not ctx.expired()
    assert ctx.exemplar() is None
    ctx.stage('s', 0.0, 1.0)       # all no-ops, nothing recorded
    ctx.event('e')
    ctx.flow_begin('f')
    ctx.flow_end()
    assert observe.spans().events() == []
    expired = reqtrace.new_context('r', deadline_s=-0.001, sample=0.0)
    assert expired.expired()
    # sampling requires telemetry: disabled observe never samples
    observe.disable()
    assert not reqtrace.new_context('r', sample=1.0).sampled


# ----------------------------------------- engine tracing (acceptance)
def test_request_trace_spans_three_threads_with_exemplar(tmp_path):
    """Acceptance: a sampled request exports X-phase spans from >= 3
    distinct threads linked under one trace id in the Perfetto JSON,
    flow events stitch the handoffs, and the Prometheus exposition
    carries the trace id as an exemplar on the request-latency
    histogram."""
    from paddle_tpu.observe.registry import prometheus_exposition

    observe.enable()
    os.environ['PADDLE_TPU_TRACE_SAMPLE'] = '1'
    d = _save_mlp(str(tmp_path / 'm'))
    eng = _engine(d, 'traced')
    rng = np.random.RandomState(0)
    for _ in range(4):
        eng.predict({'x': rng.rand(2, 6).astype('float32')},
                    timeout=60)
    eng.shutdown(drain=True)

    doc = observe.spans().chrome_trace()        # the Perfetto export
    by_trace = {}
    for ev in doc['traceEvents']:
        tid = (ev.get('args') or {}).get('trace_id')
        if tid and ev.get('ph') == 'X':
            by_trace.setdefault(tid, []).append(ev)
    assert by_trace, 'no sampled spans recorded'
    best = max(by_trace.values(), key=lambda evs: len({e['tid']
                                                      for e in evs}))
    names = {e['name'] for e in best}
    assert {'submit', 'queue_wait', 'batch_assemble', 'dispatch',
            'compute', 'unpad'} <= names
    assert len({e['tid'] for e in best}) >= 3   # client+batcher+dispatch
    # flow events share the trace's id and stitch >= 2 threads
    trace_id = (best[0].get('args') or {})['trace_id']
    flows = [e for e in doc['traceEvents'] if e.get('cat') == 'flow'
             and e.get('id') == int(trace_id, 16)]
    assert {'s', 'f'} <= {e['ph'] for e in flows}
    assert len({e['tid'] for e in flows}) >= 2

    expo = prometheus_exposition(observe.snapshot())
    ex_lines = [ln for ln in expo.splitlines()
                if ln.startswith('serving_request_seconds')
                and '# {trace_id="' in ln]
    assert ex_lines, 'no exemplar on the request-latency histogram'
    assert 'quantile="0.99"' in ex_lines[0]


def test_tracez_filters_by_trace_id(tmp_path):
    from paddle_tpu.observe import diagnostics

    observe.enable()
    d = _save_mlp(str(tmp_path / 'm'))
    eng = _engine(d, 'tz')
    ctx = reqtrace.new_context('tz', sample=1.0)
    eng.submit({'x': np.ones((1, 6), 'float32')}, ctx=ctx).result(60)
    eng.predict({'x': np.ones((1, 6), 'float32')})   # unsampled noise
    eng.shutdown(drain=True)

    doc = diagnostics._tracez_doc('trace_id=%s' % ctx.trace_id)
    assert doc['trace_id'] == ctx.trace_id
    assert doc['spans'], 'filter returned nothing'
    assert all((e.get('args') or {}).get('trace_id') == ctx.trace_id
               for e in doc['spans'])
    assert len(doc['threads']) >= 3
    # no filter: plain recent-spans payload
    plain = diagnostics._tracez_doc('n=5')
    assert 'dropped' in plain and len(plain['spans']) <= 5


# ---------------------------------------------------------------- SLO
def test_slo_objective_validation():
    with pytest.raises(ValueError):
        Objective('r', latency_budget_s=0.0)
    with pytest.raises(ValueError):
        Objective('r', 0.1, availability_target=1.0)
    with pytest.raises(ValueError):
        SloTracker([])
    with pytest.raises(ValueError):
        SloTracker([Objective('r', 0.1), Objective('r', 0.2)])
    t = SloTracker([Objective('r', 0.1)])
    with pytest.raises(KeyError):
        t.record('unknown', 0.05)


def test_slo_burn_rate_goodput_p99():
    """Synthetic clock: 100 requests, 5 bad (1 error + 4 over-budget)
    against a 99% availability target -> burn rate 5x; goodput counts
    only in-SLO completions; predicted p99 tracks the window; old
    events evict."""
    obj = Objective('r', latency_budget_s=0.1,
                    availability_target=0.99, window_s=10.0)
    t = SloTracker([obj], registry=None)
    now = 1000.0
    for i in range(95):
        t.record('r', 0.01, ok=True, now=now + i * 0.01)
    t.record('r', 0.05, ok=False, now=now + 1.0)          # 1 error
    for i in range(4):
        t.record('r', 0.5, ok=True, now=now + 1.1 + i * 0.01)  # late
    q = now + 2.0
    assert t.window_counts('r', now=q) == (100, 5)
    assert t.burn_rate('r', now=q) == pytest.approx(5.0)
    # goodput: 95 good over the window's observed span
    span = (now + 1.13) - now
    assert t.goodput('r', now=q) == pytest.approx(95.0 / min(10.0, q - now))
    del span
    p99 = t.predicted_p99('r', now=q + 1.0)
    assert p99 == pytest.approx(0.5)          # the late tail dominates
    # eviction: everything ages out of the 10s window
    assert t.window_counts('r', now=now + 100.0) == (0, 0)
    assert t.burn_rate('r', now=now + 100.0) == 0.0


def test_slo_p99_visible_right_after_idle_read():
    """Regression: reading an idle route (publish/statusz) primes the
    latency cache EMPTY; records landing within the 0.25s re-sort
    throttle must still produce a predicted p99 — SLO admission is
    blind exactly at flash-crowd onset otherwise."""
    t = SloTracker([Objective('r', 0.1, window_s=10.0)], registry=None)
    now = 1000.0
    assert t.predicted_p99('r', now=now) is None   # idle: cache = ()
    for i in range(20):
        t.record('r', 0.02, ok=True, now=now + 0.001 * i)
    assert t.predicted_p99('r', now=now + 0.05) == pytest.approx(0.02)


def test_slo_publishes_metrics_and_slowest():
    observe.enable()
    t = SloTracker([Objective('serve', 0.1, 0.99, window_s=60.0)])
    for i in range(10):
        t.record('serve', 0.01 * (i + 1), ok=True,
                 trace_id='t%02d' % i)
    snap = observe.snapshot()
    assert 'slo.burn_rate{route=serve}' in snap['gauges']
    assert 'slo.latency_budget_seconds{route=serve}' in snap['gauges']
    assert snap['counters']['slo.requests_total{route=serve}'] == 10
    slowest = t.slowest('serve')
    assert len(slowest) == 5
    assert slowest[0][0] == pytest.approx(0.1)   # worst first
    assert slowest[0][1] == 't09'
    assert [s for s, _ in slowest] == sorted(
        [s for s, _ in slowest], reverse=True)
    # the statusz panel renders from the same snapshot
    from paddle_tpu.observe.diagnostics import _slo_status
    panel = _slo_status(observe.snapshot())
    assert panel['serve']['latency_budget_s'] == pytest.approx(0.1)
    assert len(panel['serve']['slowest']) == 5


# ------------------------------------------------------------- loadgen
def test_qps_schedules():
    from paddle_tpu.serving.loadgen import (diurnal, flash_crowd,
                                            heavy_tailed_rows, qps_at)
    assert qps_at(50.0, 3.0) == 50.0
    bp = [(0.0, 10.0), (2.0, 100.0), (4.0, 20.0)]
    assert qps_at(bp, 0.0) == 10.0
    assert qps_at(bp, 1.99) == 10.0
    assert qps_at(bp, 2.0) == 100.0
    assert qps_at(bp, 10.0) == 20.0
    assert qps_at([(1.0, 5.0)], 0.5) == 0.0      # before first breakpoint
    d = diurnal(10.0, 50.0, period_s=10.0)
    assert qps_at(d, 0.0) == pytest.approx(10.0)
    assert qps_at(d, 5.0) == pytest.approx(50.0)
    f = flash_crowd(d, 400.0, t_start=2.0, duration_s=1.0)
    assert qps_at(f, 2.5) == 400.0
    assert qps_at(f, 3.5) == pytest.approx(qps_at(d, 3.5))
    rng = np.random.RandomState(0)
    rows = [heavy_tailed_rows(rng, 1, 8) for _ in range(500)]
    assert min(rows) >= 1 and max(rows) <= 8
    assert np.median(rows) <= 3                  # most requests small


def test_open_loop_schedule_and_stats_timestamps():
    """loadgen satellite: open_loop follows a (t, qps) schedule — the
    quiet and burst phases differ in submission rate — and the Stats
    ledger timestamps rejects/errors so shed windows are plottable."""
    from paddle_tpu.serving.loadgen import Stats, open_loop

    stats = Stats()
    times = []
    state = {'n': 0}

    def submit_request(rng):
        times.append(time.perf_counter())
        state['n'] += 1
        if state['n'] % 5 == 0:
            return None                    # every 5th: a reject
        f = Future()
        if state['n'] % 7 == 0:
            f.set_exception(RuntimeError('boom'))   # typed error
        else:
            f.set_result(None)
        return f, 1

    t0 = time.perf_counter()
    open_loop(submit_request, stats,
              deadline=t0 + 1.0, qps=[(0.0, 30.0), (0.5, 300.0)])
    lo = sum(1 for t in times if t - t0 < 0.5)
    hi = sum(1 for t in times if t - t0 >= 0.5)
    assert hi > 2 * lo, (lo, hi)          # the burst phase is denser
    assert stats.rejected >= 1 and stats.errors >= 1
    assert len(stats.reject_times) == stats.rejected
    assert len(stats.error_times) == stats.errors
    assert all(0.0 <= t <= 1.5 for t in stats.reject_times)
    win = stats.counts_between(0.0, 2.0)
    assert win['ok'] == stats.ok
    assert win['rejected'] == stats.rejected


# -------------------------------------------------------------- router
class FakeReplica(object):
    """Duck-typed replica: resolves futures synchronously."""

    def __init__(self, name, depth=0, ready=True, exc=None):
        self.name = name
        self._depth = depth
        self._ready = ready
        self.exc = exc
        self.submitted = 0

    def ready(self):
        return self._ready

    def queue_depth(self):
        return self._depth

    def submit(self, feed, ctx=None):
        self.submitted += 1
        if isinstance(self.exc, QueueFullError):
            raise self.exc
        f = Future()
        if self.exc is not None:
            f.set_exception(self.exc)
        else:
            f.set_result([self.name])
        return f


def test_router_least_loaded_and_affinity():
    observe.enable()
    a = FakeReplica('a', depth=5)
    b = FakeReplica('b', depth=0)
    c = FakeReplica('c', depth=9)
    r = Router([a, b, c], session_affinity=True)
    # least-loaded without a session: everything lands on b
    for _ in range(3):
        assert r.predict({'x': 1}) == ['b']
    assert (a.submitted, b.submitted, c.submitted) == (0, 3, 0)
    # session affinity beats depth and is sticky
    first = r.predict({'x': 1}, session='user-1')[0]
    for _ in range(3):
        assert r.predict({'x': 1}, session='user-1') == [first]
    # a dead pinned replica falls back to least-loaded, not an error
    pinned = {'a': a, 'b': b, 'c': c}[first]
    pinned._ready = False
    alive = r.predict({'x': 1}, session='user-1')[0]
    assert alive != first
    # no replica ready -> typed availability error
    for rep in (a, b, c):
        rep._ready = False
    with pytest.raises(NoReplicaAvailableError):
        r.submit({'x': 1})
    # queue-full everywhere -> the QueueFullError propagates
    for rep in (a, b, c):
        rep._ready = True
        rep.exc = QueueFullError('full')
    with pytest.raises(QueueFullError):
        r.submit({'x': 1})
    r.close()


def test_router_failover_retries_on_dead_replica():
    observe.enable()
    dead = FakeReplica('dead', depth=0,
                       exc=EngineClosedError('replica gone'))
    live = FakeReplica('live', depth=3)
    r = Router([dead, live], session_affinity=False, retries=2)
    assert r.predict({'x': 1}) == ['live']   # retried transparently
    assert dead.submitted == 1 and live.submitted == 1
    assert observe.get_counter('router.failover_total', replica='dead',
                               route='serve') == 1
    # retries exhausted -> the typed error surfaces, nothing hangs
    lone = FakeReplica('lone', exc=EngineClosedError('gone'))
    r2 = Router([lone], session_affinity=False, retries=1)
    with pytest.raises(EngineClosedError):
        r2.predict({'x': 1})
    r.close()
    r2.close()


def test_router_slo_admission_shed_and_degrade():
    observe.enable()
    tracker = SloTracker([Objective('serve', latency_budget_s=0.05,
                                    window_s=60.0)])
    rep = FakeReplica('r0')
    router = Router([rep], slo=tracker, retries=0)
    assert router.admission == 'slo'
    # healthy window: predicted p99 under budget, admitted
    for _ in range(20):
        tracker.record('serve', 0.01)
    assert router.predict({'x': 1}) == ['r0']
    # poisoned window: predicted p99 blows the budget -> shed, typed
    # as a QueueFullError subclass so reject handling applies
    for i in range(50):
        tracker.record('serve', 0.5, now=time.perf_counter() + 1.0)
    # force past the 0.25s sorted-latency cache so admission sees the
    # poisoned window immediately
    assert tracker.predicted_p99(
        'serve', now=time.perf_counter() + 10.0) == pytest.approx(0.5)
    with pytest.raises(SLOShedError):
        router.submit({'x': 1})
    with pytest.raises(QueueFullError):
        router.submit({'x': 1})
    assert observe.get_counter('router.shed_total',
                               reason='predicted_p99',
                               route='serve') >= 2
    # a long per-request deadline overrides the route budget: admitted
    assert router.predict({'x': 1}, deadline_s=30.0) == ['r0']
    # degrade mode admits past the breach and counts it
    router2 = Router([rep], slo=tracker, on_breach='degrade', retries=0)
    assert router2.predict({'x': 1}) == ['r0']
    assert observe.get_counter('router.degraded_total',
                               route='serve') == 1
    router.close()
    router2.close()


def test_router_failover_kill_replica_midload(tmp_path):
    """Failover satellite: kill one replica mid-load via
    fault.inject.kill_replica — every accepted request completes or
    fails typed (none lost or hung), the dead replica's readiness
    check flips, and traffic rebalances onto the survivors."""
    from paddle_tpu.fault import inject
    from paddle_tpu.observe.diagnostics import run_health_checks

    observe.enable()
    d = _save_mlp(str(tmp_path / 'm'))
    engines = [_engine(d, 'r%d' % i, max_queue_depth=64)
               for i in range(3)]
    tracker = SloTracker([Objective('serve', 1.0, window_s=30.0)])
    router = Router(engines, slo=tracker, retries=3)
    victim = engines[0]
    ok, checks = run_health_checks(include_readiness=True)
    assert checks['serving.r0']['ok']

    rng = np.random.RandomState(0)
    futures = []
    accepted = rejected = 0
    kill_after = 60
    for i in range(180):
        try:
            fut = router.submit(
                {'x': rng.rand(2, 6).astype('float32')}, session=i % 8)
            futures.append(fut)
            accepted += 1
        except QueueFullError:
            rejected += 1
        if i == kill_after:
            before = {n: observe.get_counter('router.dispatch_total',
                                             replica=n, route='serve')
                      for n, _ in router.replicas()}
            inject.kill_replica(victim, drain=False)
            assert victim.ready() is False
        time.sleep(0.002)
    for eng in engines[1:]:
        eng.shutdown(drain=True)

    # zero lost/hung: every accepted future resolves, errors are typed
    resolved, typed_errors = 0, 0
    for fut in futures:
        try:
            fut.result(timeout=30)
            resolved += 1
        except (QueueFullError, EngineClosedError,
                NoReplicaAvailableError):
            typed_errors += 1
    assert resolved + typed_errors == accepted
    assert resolved > 0

    # the dead replica's /readyz check flips (kill_replica keeps the
    # corpse's check registered, unlike a graceful shutdown)
    ok, checks = run_health_checks(include_readiness=True)
    assert checks['serving.r0']['ok'] is False
    # traffic rebalanced: survivors took dispatches after the kill
    after = {n: observe.get_counter('router.dispatch_total',
                                    replica=n, route='serve')
             for n, _ in router.replicas()}
    assert after['r1'] + after['r2'] > before['r1'] + before['r2']
    assert after['r0'] == before['r0']        # corpse takes nothing
    # the kill is a flight event (chaos forensics)
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'replica_kill' in kinds
    router.close()


# ------------------------------------------------- metrics_report --slo
def test_metrics_report_slo_json(tmp_path):
    """CLI satellite: --slo renders objectives/burn/goodput/slowest
    from a JSONL, stdlib-only (no jax import), --json schema stable."""
    observe.enable(jsonl=str(tmp_path / 'm.jsonl'))
    t = SloTracker([Objective('fleet', 0.2, 0.95, window_s=30.0)])
    for i in range(20):
        t.record('fleet', 0.01 * (i + 1), ok=(i % 7 != 0),
                 trace_id='%012x' % i)
    t.publish()
    observe.flush(kind='summary')

    tool = os.path.join(REPO, 'tools', 'metrics_report.py')
    r = subprocess.run(
        [sys.executable, tool, str(tmp_path / 'm.jsonl'), '--slo',
         '--json'],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    route = doc['routes']['fleet']
    assert route['latency_budget_s'] == pytest.approx(0.2)
    assert route['availability_target'] == pytest.approx(0.95)
    assert route['burn_rate'] is not None and route['burn_rate'] > 0
    assert route['goodput_rps'] is not None
    assert route['predicted_p99_s'] is not None
    assert len(route['slowest']) == 5
    lats = [s['seconds'] for s in route['slowest']]
    assert lats == sorted(lats, reverse=True)
    assert all(s['trace_id'] for s in route['slowest'])
    # human rendering mentions the objective and trace ids
    r2 = subprocess.run(
        [sys.executable, tool, str(tmp_path / 'm.jsonl'), '--slo'],
        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0, r2.stderr
    assert 'objective' in r2.stdout and 'trace_id=' in r2.stdout
    # no jax import on the --slo path
    probe = subprocess.run(
        [sys.executable, '-c',
         'import importlib.util, sys\n'
         'spec = importlib.util.spec_from_file_location("mr", %r)\n'
         'm = importlib.util.module_from_spec(spec)\n'
         'spec.loader.exec_module(m)\n'
         'assert m.main([%r, "--slo"]) == 0\n'
         'assert "jax" not in sys.modules\n'
         % (tool, str(tmp_path / 'm.jsonl'))],
        capture_output=True, text=True, timeout=60)
    assert probe.returncode == 0, probe.stderr


# ------------------------------------------------ fleet chaos scenario
def test_bench_fleet_chaos_scenario(tmp_path):
    """Acceptance: bench.py --workload fleet runs flash-crowd +
    replica-kill against a 3-replica router and the ledger proves:
    zero accepted-request losses, burn rate > 0 during the kill
    window, goodput recovery after it, and slo.* metrics in the
    metrics JSONL."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    jsonl = str(tmp_path / 'fleet.jsonl')
    observe.enable(jsonl=jsonl)
    r = bench.bench_fleet(duration=3.0, steady_qps=30.0,
                          spike_qps=700.0, spike_at=1.0, spike_s=1.0,
                          kill_at=1.2, window_s=1.0, max_queue_depth=8,
                          trace_sample=0.1)
    observe.flush(kind='summary')

    assert r['replicas'] == 3
    assert r['accepted'] > 0
    assert r['lost'] == 0, r                      # zero accepted losses
    assert r['burn_during_kill'] > 0.0            # the kill burned budget
    assert r['goodput_end_rps'] > 0.0             # and the fleet recovered
    assert r['kill']['ready_before'] is True
    assert r['kill']['ready_after'] is False
    assert r['max_trace_threads'] >= 3            # cross-thread traces
    assert r['sampled_traces'] > 0
    # the spike overloaded 2 survivors: shed/reject windows exist and
    # are timestamped (plottable), concentrated in the spike phase
    assert r['phases']['spike']['ok'] > r['phases']['steady']['ok']

    # slo.* metrics landed in the metrics JSONL
    with open(jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    summary = [x for x in recs if x.get('kind') == 'summary'][-1]
    gauges = summary['gauges']
    assert 'slo.burn_rate{route=fleet}' in gauges
    assert 'slo.goodput_rps{route=fleet}' in gauges
    assert 'slo.latency_budget_seconds{route=fleet}' in gauges
    assert any(k.startswith('router.dispatch_total')
               for k in summary['counters'])
