"""Shared helpers for the test suite."""

import numpy as np

import paddle_tpu as fluid


def run_startup_and(feed, fetch_list, place=None):
    exe = fluid.Executor(place or fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch_list)


def rand(*shape, dtype='float32', seed=None, low=None, high=None):
    rng = np.random.RandomState(seed if seed is not None else 0)
    if dtype.startswith('int'):
        return rng.randint(low or 0, high or 10, shape).astype(dtype)
    return rng.uniform(low if low is not None else -1.0,
                       high if high is not None else 1.0,
                       shape).astype(dtype)
