"""CTC / CRF / beam search vs brute-force numpy references (reference:
fluid/tests/unittests/test_warpctc_op.py, test_linear_chain_crf_op.py,
test_crf_decoding_op.py, test_beam_search_op.py)."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid
from util import run_startup_and, rand


# ---------------------------------------------------------------- references
def ctc_loss_brute(log_probs, label, blank=0):
    """Sum over all alignments (exponential — only for tiny T)."""
    T, C = log_probs.shape
    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        # collapse path
        out = []
        prev = None
        for s in path:
            if s != prev and s != blank:
                out.append(s)
            prev = s
        if out == list(label):
            lp = sum(log_probs[t, path[t]] for t in range(T))
            total = np.logaddexp(total, lp)
    return -total


def crf_nll_brute(emission, transition, label):
    """Enumerate all tag paths."""
    T, C = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]

    def score(path):
        s = start[path[0]] + emission[0, path[0]] + stop[path[-1]]
        for t in range(1, T):
            s += trans[path[t - 1], path[t]] + emission[t, path[t]]
        return s

    logz = -np.inf
    for path in itertools.product(range(C), repeat=T):
        logz = np.logaddexp(logz, score(path))
    return logz - score(label)


def viterbi_brute(emission, transition):
    T, C = emission.shape
    best, best_path = -np.inf, None
    start, stop, trans = transition[0], transition[1], transition[2:]
    for path in itertools.product(range(C), repeat=T):
        s = start[path[0]] + emission[0, path[0]] + stop[path[-1]]
        for t in range(1, T):
            s += trans[path[t - 1], path[t]] + emission[t, path[t]]
        if s > best:
            best, best_path = s, path
    return list(best_path)


# --------------------------------------------------------------------- tests
def test_warpctc_matches_bruteforce():
    T, C, L = 4, 3, 2
    rng = np.random.RandomState(0)
    logits_np = rng.randn(2, T, C).astype('float32')
    labels_np = np.array([[1, 2], [2, 1]], dtype='int64')

    logits = fluid.layers.data(name='logits', shape=[T, C], dtype='float32')
    label = fluid.layers.data(name='label', shape=[L], dtype='int64')
    loss = fluid.layers.warpctc(input=logits, label=label, blank=0)
    got = run_startup_and({'logits': logits_np, 'label': labels_np},
                          [loss])[0]
    lp = logits_np - np.log(np.exp(logits_np).sum(-1, keepdims=True))
    for b in range(2):
        expect = ctc_loss_brute(lp[b], labels_np[b])
        np.testing.assert_allclose(got[b, 0], expect, rtol=1e-4)


def test_warpctc_variable_lengths_and_grad():
    T, C, L = 6, 4, 3
    rng = np.random.RandomState(1)
    logits_np = rng.randn(2, T, C).astype('float32')
    labels_np = np.array([[1, 2, 3], [2, 1, 0]], dtype='int64')
    tl = np.array([6, 4], dtype='int64')
    ll = np.array([3, 2], dtype='int64')

    logits = fluid.layers.data(name='logits', shape=[T, C], dtype='float32')
    label = fluid.layers.data(name='label', shape=[L], dtype='int64')
    tlen = fluid.layers.data(name='tlen', shape=[], dtype='int64')
    llen = fluid.layers.data(name='llen', shape=[], dtype='int64')
    loss = fluid.layers.warpctc(input=logits, label=label, blank=0,
                                input_length=tlen, label_length=llen)
    mean = fluid.layers.mean(loss)
    got = run_startup_and({'logits': logits_np, 'label': labels_np,
                           'tlen': tl, 'llen': ll}, [loss, mean])
    lp = logits_np - np.log(np.exp(logits_np).sum(-1, keepdims=True))
    # example 1 truncated to T=4, L=2
    expect0 = ctc_loss_brute(lp[0], labels_np[0])
    expect1 = ctc_loss_brute(lp[1, :4], labels_np[1, :2])
    np.testing.assert_allclose(got[0][0, 0], expect0, rtol=1e-4)
    np.testing.assert_allclose(got[0][1, 0], expect1, rtol=1e-4)


def test_ctc_greedy_decoder():
    # probs argmax sequence: [blank a a blank b b] -> [a b]
    C = 3
    seq = np.array([0, 1, 1, 0, 2, 2])
    probs_np = np.eye(C, dtype='float32')[seq][None]  # [1, 6, 3]
    probs = fluid.layers.data(name='p', shape=[6, C], dtype='float32')
    out, out_len = fluid.layers.ctc_greedy_decoder(probs, blank=0)
    got, got_len = run_startup_and({'p': probs_np}, [out, out_len])
    assert got_len[0, 0] == 2
    np.testing.assert_array_equal(got[0, :2], [1, 2])
    assert (got[0, 2:] == -1).all()


def test_linear_chain_crf_matches_bruteforce():
    T, C = 3, 3
    rng = np.random.RandomState(2)
    em_np = rng.randn(2, T, C).astype('float32')
    trans_np = rng.randn(C + 2, C).astype('float32')
    label_np = np.array([[0, 1, 2], [2, 2, 0]], dtype='int64')

    em = fluid.layers.data(name='em', shape=[T, C], dtype='float32')
    label = fluid.layers.data(name='label', shape=[T], dtype='int64')
    nll = fluid.layers.linear_chain_crf(
        input=em, label=label,
        param_attr=fluid.ParamAttr(
            name='crf_w',
            initializer=fluid.initializer.NumpyArrayInitializer(trans_np)))
    got = run_startup_and({'em': em_np, 'label': label_np}, [nll])[0]
    for b in range(2):
        expect = crf_nll_brute(em_np[b].astype('float64'),
                               trans_np.astype('float64'), label_np[b])
        np.testing.assert_allclose(got[b, 0], expect, rtol=1e-4)


def test_crf_decoding_matches_bruteforce():
    T, C = 4, 3
    rng = np.random.RandomState(3)
    em_np = rng.randn(2, T, C).astype('float32')
    trans_np = rng.randn(C + 2, C).astype('float32')

    em = fluid.layers.data(name='em', shape=[T, C], dtype='float32')
    label = fluid.layers.data(name='label', shape=[T], dtype='int64')
    attr = fluid.ParamAttr(
        name='crf_w2',
        initializer=fluid.initializer.NumpyArrayInitializer(trans_np))
    nll = fluid.layers.linear_chain_crf(input=em, label=label,
                                        param_attr=attr)
    path = fluid.layers.crf_decoding(input=em, param_attr=attr)
    label_np = np.zeros((2, T), dtype='int64')
    got = run_startup_and({'em': em_np, 'label': label_np}, [path, nll])[0]
    for b in range(2):
        expect = viterbi_brute(em_np[b].astype('float64'),
                               trans_np.astype('float64'))
        np.testing.assert_array_equal(got[b], expect)


def test_crf_trains():
    """CRF as a loss: nll decreases when transitions+emissions learn."""
    T, C = 5, 4
    words = fluid.layers.data(name='w', shape=[T], dtype='int64')
    label = fluid.layers.data(name='y', shape=[T], dtype='int64')
    emb = fluid.layers.embedding(input=words, size=[20, 8])
    em = fluid.layers.fc(input=emb, size=C, num_flatten_dims=2)
    nll = fluid.layers.linear_chain_crf(
        input=em, label=label, param_attr=fluid.ParamAttr(name='crf_w3'))
    loss = fluid.layers.mean(nll)
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(4)
    ws = rng.randint(0, 20, (8, T)).astype('int64')
    ys = (ws % C).astype('int64')
    losses = [float(np.asarray(exe.run(feed={'w': ws, 'y': ys},
                                       fetch_list=[loss])[0]).reshape(()))
              for _ in range(15)]
    assert losses[-1] < losses[0]


def test_beam_search_step():
    B, beam, K = 1, 2, 3
    pre_ids_np = np.array([[3, 5]], dtype='int64')  # no end yet
    pre_scores_np = np.array([[-1.0, -2.0]], dtype='float32')
    ids_np = np.array([[[10, 11, 12], [20, 21, 22]]], dtype='int64')
    scores_np = np.log(np.array(
        [[[0.6, 0.3, 0.1], [0.7, 0.2, 0.1]]], dtype='float32'))

    pre_ids = fluid.layers.data(name='pi', shape=[beam], dtype='int64')
    pre_scores = fluid.layers.data(name='ps', shape=[beam],
                                   dtype='float32')
    ids = fluid.layers.data(name='ids', shape=[beam, K], dtype='int64')
    scores = fluid.layers.data(name='sc', shape=[beam, K], dtype='float32')
    sel_ids, sel_scores, parent = fluid.layers.beam_search(
        pre_ids, pre_scores, ids, scores, beam_size=beam, end_id=0)
    got_ids, got_scores, got_parent = run_startup_and(
        {'pi': pre_ids_np, 'ps': pre_scores_np, 'ids': ids_np,
         'sc': scores_np}, [sel_ids, sel_scores, parent])
    # candidates: beam0: -1+log .6/.3/.1 ; beam1: -2+log .7/.2/.1
    all_scores = np.concatenate(
        [pre_scores_np[0, 0] + scores_np[0, 0],
         pre_scores_np[0, 1] + scores_np[0, 1]])
    order = np.argsort(-all_scores)[:beam]
    np.testing.assert_allclose(got_scores[0], all_scores[order], rtol=1e-6)
    np.testing.assert_array_equal(got_parent[0], order // K)
    np.testing.assert_array_equal(
        got_ids[0], np.array([10, 11, 12, 20, 21, 22])[order])


def test_beam_search_finished_beam_frozen():
    pre_ids_np = np.array([[0, 5]], dtype='int64')  # beam 0 hit end_id=0
    pre_scores_np = np.array([[-0.5, -3.0]], dtype='float32')
    ids_np = np.array([[[10, 11], [20, 21]]], dtype='int64')
    scores_np = np.full((1, 2, 2), -0.1, dtype='float32')

    pre_ids = fluid.layers.data(name='pi', shape=[2], dtype='int64')
    pre_scores = fluid.layers.data(name='ps', shape=[2], dtype='float32')
    ids = fluid.layers.data(name='ids', shape=[2, 2], dtype='int64')
    scores = fluid.layers.data(name='sc', shape=[2, 2], dtype='float32')
    sel_ids, sel_scores, parent = fluid.layers.beam_search(
        pre_ids, pre_scores, ids, scores, beam_size=2, end_id=0)
    got_ids, got_scores, got_parent = run_startup_and(
        {'pi': pre_ids_np, 'ps': pre_scores_np, 'ids': ids_np,
         'sc': scores_np}, [sel_ids, sel_scores, parent])
    # finished beam keeps score -0.5 and emits end_id exactly once
    assert got_scores[0, 0] == pytest.approx(-0.5)
    assert got_ids[0, 0] == 0
    assert (got_ids[0] == 0).sum() == 1


def test_beam_search_decode_backtrack():
    # T=3, B=1, beam=2; parents chain: step2 beam0 <- step1 beam1 <- step0 b0
    step_ids_np = np.array(
        [[[1, 2]], [[3, 4]], [[5, 6]]], dtype='int64')  # [T,B,beam]... wait
    step_ids_np = np.transpose(step_ids_np, (0, 1, 2))
    step_parents_np = np.array(
        [[[0, 1]], [[1, 0]], [[1, 0]]], dtype='int64')
    step_ids = fluid.layers.data(name='si', shape=[1, 2], dtype='int64')
    step_ids.shape = (3, 1, 2)
    step_parents = fluid.layers.data(name='sp', shape=[1, 2], dtype='int64')
    step_parents.shape = (3, 1, 2)
    sent, _ = fluid.layers.beam_search_decode(step_ids, step_parents,
                                              end_id=0)
    got = run_startup_and({'si': step_ids_np, 'sp': step_parents_np},
                          [sent])[0]
    # final slot 0: token 5 at t2, parent=1 -> t1 token 4 (slot1),
    # its parent=0 -> t0 token 1
    np.testing.assert_array_equal(got[0, 0], [1, 4, 5])
    # final slot 1: token 6, parent 0 -> t1 token 3, parent 1 -> t0 token 2
    np.testing.assert_array_equal(got[0, 1], [2, 3, 6])
