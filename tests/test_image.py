"""Image preprocessing (reference: python/paddle/v2/image.py) + reader
wiring."""

import io

import numpy as np

from paddle_tpu import image


def _checker(h, w):
    im = np.zeros((h, w, 3), dtype='uint8')
    im[::2, ::2] = 255
    im[:, :, 1] = (np.arange(w) % 256).astype('uint8')
    return im


def test_resize_short_keeps_aspect():
    im = _checker(40, 80)
    out = image.resize_short(im, 20)
    assert out.shape[:2] == (20, 40)
    out2 = image.resize_short(_checker(80, 40), 20)
    assert out2.shape[:2] == (40, 20)


def test_crops_and_flip():
    im = _checker(30, 40)
    c = image.center_crop(im, 20)
    assert c.shape == (20, 20, 3)
    np.testing.assert_array_equal(c, im[5:25, 10:30])
    rng = np.random.RandomState(0)
    rc = image.random_crop(im, 16, rng=rng)
    assert rc.shape == (16, 16, 3)
    f = image.left_right_flip(im)
    np.testing.assert_array_equal(f, im[:, ::-1])


def test_to_chw_and_simple_transform():
    im = _checker(50, 60)
    chw = image.to_chw(im)
    assert chw.shape == (3, 50, 60)
    rng = np.random.RandomState(1)
    out = image.simple_transform(im, 32, 24, is_train=True,
                                 mean=[1.0, 2.0, 3.0], rng=rng)
    assert out.shape == (3, 24, 24)
    assert out.dtype == np.float32
    out_eval = image.simple_transform(im, 32, 24, is_train=False)
    # eval path is deterministic: center crop of resize_short
    again = image.simple_transform(im, 32, 24, is_train=False)
    np.testing.assert_array_equal(out_eval, again)


def test_load_image_bytes_roundtrip(tmp_path):
    from PIL import Image
    im = _checker(24, 24)
    buf = io.BytesIO()
    Image.fromarray(im).save(buf, format='PNG')
    decoded = image.load_image_bytes(buf.getvalue())
    np.testing.assert_array_equal(decoded, im)
    p = tmp_path / 'x.png'
    Image.fromarray(im).save(str(p))
    loaded = image.load_image(str(p))
    np.testing.assert_array_equal(loaded, im)
    gray = image.load_image(str(p), is_color=False)
    assert gray.ndim == 2


def test_batch_images_from_tar(tmp_path):
    import pickle
    import tarfile
    from PIL import Image
    tar_path = str(tmp_path / 'imgs.tar')
    img2label = {}
    with tarfile.open(tar_path, 'w') as tf:
        for i in range(5):
            buf = io.BytesIO()
            Image.fromarray(_checker(8, 8)).save(buf, format='PNG')
            data = buf.getvalue()
            info = tarfile.TarInfo('img_%d.png' % i)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            img2label['img_%d.png' % i] = i % 2
    meta = image.batch_images_from_tar(tar_path, 'train', img2label,
                                       num_per_batch=2)
    batches = open(meta).read().splitlines()
    assert len(batches) == 3  # 5 images / 2 per batch
    with open(batches[0], 'rb') as f:
        b0 = pickle.load(f)
    assert len(b0['data']) == 2 and len(b0['label']) == 2


def test_flowers_reader_uses_image_pipeline():
    from paddle_tpu.dataset import flowers
    img, label = next(flowers.train()())
    assert img.shape == (3, flowers.CROP_SIZE, flowers.CROP_SIZE)
    assert img.dtype == np.float32
    assert np.abs(img).max() <= 1.0 + 1e-6  # mean/scale applied
    assert 0 <= label < flowers.CLASS_NUM
    img_t, _ = next(flowers.test()())
    assert img_t.shape == (3, flowers.CROP_SIZE, flowers.CROP_SIZE)


def test_voc2012_reader_chw():
    from paddle_tpu.dataset import voc2012
    img, seg = next(voc2012.train()())
    assert img.shape[0] == 3 and img.shape[1:] == seg.shape
