"""Telemetry subsystem (paddle_tpu.observe): registry semantics, JSONL
round-trip, Chrome-trace span nesting, the instrumented Trainer/Executor
path (compile-cache miss-then-hit, phase timings, reader/fault counters),
the disabled-path overhead bound, and the profiler-on-observe rebuild."""

import json
import os
import re
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _observe_clean():
    """Leave the global telemetry state exactly as tests expect: gate
    off, sinks unset, registry/spans/goodput empty."""
    from paddle_tpu import observe
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.disable()
    observe.reset()


# ------------------------------------------------------------- registry
def test_counter_gauge_histogram_semantics():
    from paddle_tpu.observe.registry import Registry

    reg = Registry()
    c = reg.counter('requests_total')
    c.inc()
    c.inc(2)
    c.inc(5, shard='a')
    assert c.value() == 3
    assert c.value(shard='a') == 5
    assert reg.counter('requests_total') is c  # get-or-create

    g = reg.gauge('depth')
    g.set(4)
    g.set(7)
    g.set(1.5, ring='x')
    assert g.value() == 7
    assert g.value(ring='x') == 1.5
    assert g.value(ring='missing', default=-1) == -1

    h = reg.histogram('latency')
    for v in range(100):
        h.observe(float(v))
    st = h.stats()
    assert st['count'] == 100
    assert st['sum'] == sum(range(100))
    assert st['min'] == 0.0 and st['max'] == 99.0
    assert abs(st['p50'] - 50.0) <= 2.0
    assert abs(st['p95'] - 95.0) <= 2.0
    # labeled series are independent
    h.observe(1000.0, phase='feed')
    assert h.stats(phase='feed')['count'] == 1
    assert h.stats()['count'] == 100

    with pytest.raises(TypeError):
        reg.gauge('requests_total')   # name already a counter


def test_histogram_reservoir_bounded():
    from paddle_tpu.observe.registry import RESERVOIR_CAP, Registry

    reg = Registry()
    h = reg.histogram('h')
    n = RESERVOIR_CAP + 500
    for v in range(n):
        h.observe(float(v))
    st = h.stats()
    assert st['count'] == n          # exact stats survive the cap
    assert st['max'] == float(n - 1)
    lk = ()
    assert len(h._values[lk].samples) == RESERVOIR_CAP


def test_registry_jsonl_round_trip(tmp_path):
    from paddle_tpu.observe.registry import Registry

    reg = Registry()
    reg.counter('c').inc(3, shard='a')
    reg.gauge('g').set(1.5)
    h = reg.histogram('h')
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    path = str(tmp_path / 'm.jsonl')
    with open(path, 'a') as f:
        f.write(reg.to_json_line(ts=1.0, kind='snapshot') + '\n')
        f.write(reg.to_json_line(ts=2.0, kind='summary') + '\n')
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    rec = lines[-1]
    assert rec['kind'] == 'summary'
    assert rec['counters']['c{shard=a}'] == 3
    assert rec['gauges']['g'] == 1.5
    st = rec['histograms']['h']
    assert st['count'] == 3 and st['sum'] == 6.0
    assert st['min'] == 1.0 and st['max'] == 3.0
    # the summary table renders every metric
    table = reg.summary_table()
    assert 'c{shard=a}' in table and 'g' in table and 'h' in table


# ---------------------------------------------------------------- spans
def test_chrome_trace_valid_nested(tmp_path):
    from paddle_tpu import observe

    trace = str(tmp_path / 'trace.json')
    observe.enable(trace=trace)
    with observe.span('outer', phase='x'):
        time.sleep(0.002)
        with observe.span('inner'):
            time.sleep(0.002)
        with observe.span('inner2'):
            pass
        time.sleep(0.001)
    observe.disable()

    doc = json.load(open(trace))          # valid JSON or this raises
    evs = doc['traceEvents']
    assert len(evs) == 3
    by_name = {e['name']: e for e in evs}
    for e in evs:
        assert e['ph'] == 'X'
        assert set(('name', 'ts', 'dur', 'pid', 'tid')) <= set(e)
    outer, inner = by_name['outer'], by_name['inner']
    assert outer['tid'] == inner['tid']
    # correctly nested: inner lies inside outer on the same track
    assert inner['ts'] >= outer['ts'] - 1
    assert inner['ts'] + inner['dur'] <= outer['ts'] + outer['dur'] + 1
    assert by_name['inner2']['ts'] >= inner['ts'] + inner['dur'] - 1
    assert outer['args'] == {'phase': 'x'}


# ------------------------------------------------- instrumented trainer
def _tiny_trainer(fluid, ckpt_dir=None):
    def train_func():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))

    def opt():
        return fluid.optimizer.SGD(learning_rate=0.01)

    cfg = None
    if ckpt_dir is not None:
        cfg = fluid.CheckpointConfig(ckpt_dir, async_save=False,
                                     nan_policy=None)
    return fluid.Trainer(train_func, opt, place=fluid.CPUPlace(),
                         checkpoint_config=cfg)


def _label_keys(rendered):
    m = re.search(r'\bkey=([0-9a-f]{8})', rendered)
    return m.group(1) if m else None


def test_trainer_two_steps_miss_then_hit_and_jsonl(tmp_path):
    """The acceptance-criteria e2e: 2-step CPU train run with observe on
    emits (a) a metrics JSONL with compile-cache hit/miss counts,
    per-phase timings, and reader/fault counters, (b) a Chrome trace of
    valid nested spans; the step program compiles exactly once then
    hits."""
    import paddle_tpu as fluid
    from paddle_tpu import observe
    from paddle_tpu.fault import inject
    from paddle_tpu.reader.decorator import retry

    jsonl = str(tmp_path / 'metrics.jsonl')
    trace = str(tmp_path / 'trace.json')
    observe.enable(jsonl=jsonl, trace=trace)

    trainer = _tiny_trainer(fluid, ckpt_dir=str(tmp_path / 'ckpt'))
    rng = np.random.RandomState(0)
    batches = [{'x': rng.rand(8, 4).astype('float32'),
                'y': rng.rand(8, 1).astype('float32')} for _ in range(2)]

    def base_reader():
        for b in batches:
            yield b

    # one injected transient reader failure -> reader.retry_total fires
    reader = retry(inject.flaky(base_reader, fail_times=1, fail_after=1),
                   tries=3, backoff=0)
    events = []
    trainer.train(1, reader=reader, event_handler=events.append)
    observe.disable()

    snap = observe.snapshot()
    counters = snap['counters']

    # exactly 1 compile-cache miss then 1 hit for the step program (the
    # startup program is its own key and never re-runs)
    misses = {k: v for k, v in counters.items()
              if k.startswith('executor.cache_miss_total')}
    hits = {k: v for k, v in counters.items()
            if k.startswith('executor.cache_hit_total')}
    assert sum(hits.values()) == 1, (misses, hits)
    step_key = _label_keys(list(hits)[0])
    miss_for_step = [v for k, v in misses.items()
                     if _label_keys(k) == step_key]
    assert miss_for_step == [1], (misses, hits)
    assert len(misses) == 2        # startup + step program

    # reader/fault counters
    assert counters.get('reader.retry_total') == 1
    assert counters.get('fault.checkpoint_saves_total') == 1

    # per-phase step timings
    hists = snap['histograms']
    for phase in ('feed', 'compute', 'fetch'):
        name = 'trainer.phase_seconds{phase=%s}' % phase
        assert hists[name]['count'] == 2, (name, hists.keys())
    assert hists['trainer.step_seconds']['count'] == 2
    assert hists['fault.checkpoint_save_seconds{mode=sync}']['count'] == 1
    # compile wall per key: one first-dispatch record per cache miss
    fd = [v for k, v in hists.items()
          if k.startswith('executor.first_dispatch_seconds')]
    assert len(fd) == 2 and all(st['count'] == 1 for st in fd)

    # the JSONL on disk round-trips with the same content
    recs = [json.loads(l) for l in open(jsonl)]
    assert recs, 'no metrics JSONL lines written'
    final = recs[-1]
    assert final['kind'] == 'summary'
    assert any(k.startswith('executor.cache_hit_total')
               for k in final['counters'])
    assert any(k.startswith('trainer.phase_seconds')
               for k in final['histograms'])
    assert final['counters'].get('reader.retry_total') == 1
    assert 'run.goodput' in final['gauges']

    # EndStepEvent carries wall_time + telemetry
    ends = [e for e in events
            if isinstance(e, fluid.trainer.EndStepEvent)]
    assert len(ends) == 2
    for e in ends:
        assert e.wall_time > 0
        assert 'steps_per_sec_ema' in e.telemetry
    assert ends[-1].telemetry['goodput'] is not None

    # Chrome trace: valid JSON, nested spans (executor.trace inside the
    # first trainer.step)
    doc = json.load(open(trace))
    evs = doc['traceEvents']
    steps = [e for e in evs if e['name'] == 'trainer.step']
    traces = [e for e in evs if e['name'] == 'executor.trace']
    assert len(steps) == 2 and traces
    first = min(steps, key=lambda e: e['ts'])
    tr = traces[-1]   # the step program's trace (startup ran un-spanned)
    assert first['ts'] - 1 <= tr['ts']
    assert tr['ts'] + tr['dur'] <= first['ts'] + first['dur'] + 1


def test_guard_counters():
    import paddle_tpu as fluid  # noqa: F401  (platform boot)
    from paddle_tpu import observe
    from paddle_tpu.fault.guards import BadStepError, BadStepGuard

    observe.enable()
    g = BadStepGuard('raise')
    assert g.handle(np.float32(1.0), 1) == 'ok'
    with pytest.raises(BadStepError):
        g.handle(np.float32(np.nan), 2)
    assert observe.get_counter('fault.bad_steps_total') == 1
    assert observe.get_counter('fault.guard_triggers_total',
                               policy='raise', action='raise') == 1


# ------------------------------------------------------------- overhead
def test_disabled_path_overhead():
    from paddle_tpu import observe

    observe.disable()
    assert not observe.enabled()
    n = 100000
    # warm up
    for _ in range(1000):
        observe.inc('x')
    t0 = time.perf_counter()
    for _ in range(n):
        observe.inc('executor.cache_hit_total')
        observe.record('trainer.step_seconds', 1.0)
        observe.set_gauge('g', 1)
    dt = (time.perf_counter() - t0) / (3 * n)
    # one global read + return per call; generous bound for slow CI
    assert dt < 2e-6, 'disabled observe call costs %.3gs' % dt
    # and nothing was recorded
    assert observe.snapshot()['counters'] == {}


# ------------------------------------------------------------- profiler
def test_profiler_record_event_gated_and_registry_backed(tmp_path):
    from paddle_tpu import observe, profiler

    profiler.reset_profiler()
    with profiler.record_event('idle'):
        pass
    # not started: nothing recorded anywhere (the old bug appended to a
    # module list unconditionally)
    assert observe.registry().metrics('profiler.') == []

    profiler.start_profiler('All')
    with profiler.record_event('work'):
        time.sleep(0.001)
    with profiler.record_event('work'):
        pass
    path = str(tmp_path / 'profile.txt')
    profiler.stop_profiler(profile_path=path)
    text = open(path).read()
    assert 'work' in text
    row = [l for l in text.splitlines() if l.startswith('work')][0]
    assert re.search(r'\s2\s', row), row   # 2 calls aggregated
    # one substrate: the event is an observe histogram
    h = observe.registry().histogram('profiler.work')
    assert h.count() == 2

    # reset_profiler clears the observe registry too
    observe.registry().counter('other').inc()
    profiler.reset_profiler()
    assert observe.snapshot()['counters'] == {}
    assert observe.registry().metrics('profiler.') == []


def test_profiler_summarize_format_preserved():
    from paddle_tpu import profiler

    profiler.reset_profiler()
    profiler.start_profiler()
    with profiler.record_event('a'):
        time.sleep(0.002)
    with profiler.record_event('b'):
        pass
    s = profiler.summarize()
    profiler._active = False
    lines = s.splitlines()
    assert lines[0].split() == ['Event', 'Total(s)', 'Calls', 'Avg(s)']
    # sorted by total descending: the slept event first
    assert lines[1].startswith('a')


# -------------------------------------------------------- report CLI
def test_metrics_report_cli(tmp_path):
    """tools/metrics_report.py on a real JSONL: human table + --json."""
    import subprocess

    from paddle_tpu import observe

    jsonl = str(tmp_path / 'm.jsonl')
    observe.enable(jsonl=jsonl)
    observe.inc('executor.cache_miss_total', kind='single', key='deadbeef')
    for v in (0.01, 0.02, 0.03):
        observe.record('trainer.step_seconds', v)
    observe.set_gauge('run.goodput', 0.75)
    observe.set_gauge('trainer.mfu', 0.42)
    observe.flush()
    observe._SINK['path'] = None
    observe.disable()

    tool = os.path.join(REPO, 'tools', 'metrics_report.py')
    r = subprocess.run([sys.executable, tool, jsonl],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert 'trainer.step_seconds' in r.stdout
    assert 'P95' in r.stdout
    assert 'MFU 42.00%' in r.stdout and 'goodput 75.00%' in r.stdout

    r = subprocess.run([sys.executable, tool, jsonl, '--json'],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc['mfu'] == 0.42 and doc['goodput'] == 0.75
    st = doc['histograms']['trainer.step_seconds']
    assert st['count'] == 3 and st['max'] == 0.03

    # empty/garbage file: clean failure, not a traceback
    bad = str(tmp_path / 'empty.jsonl')
    open(bad, 'w').close()
    r = subprocess.run([sys.executable, tool, bad],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1


# ------------------------------------------------------------- mfu
def test_mfu_and_goodput_accounting(monkeypatch):
    from paddle_tpu import observe
    from paddle_tpu.observe.mfu import GoodputTracker, device_peak_flops

    monkeypatch.setenv('PADDLE_TPU_PEAK_TFLOPS', '100')
    assert device_peak_flops() == 100e12

    gp = GoodputTracker()
    gp.begin()
    gp.step(0.5, steps=5)
    gp.overhead('compile', 0.1)
    reg = observe.registry()
    gp.publish(reg)
    snap = reg.snapshot()
    assert snap['gauges']['run.productive_steps'] == 5
    assert snap['gauges']['run.overhead_seconds{kind=compile}'] == \
        pytest.approx(0.1)
    assert 0.0 < snap['gauges']['run.goodput'] <= 1.0


def test_cost_analysis_flops_forms():
    from paddle_tpu.observe.mfu import cost_analysis_flops

    assert cost_analysis_flops({'flops': 12.0}) == 12.0
    assert cost_analysis_flops([{'flops': 7.0}]) == 7.0
    assert cost_analysis_flops({}) is None
    assert cost_analysis_flops('garbage') is None
