"""Live diagnostics surface (paddle_tpu.observe): the /metrics
Prometheus exposition (round-trip parsed mid-train), /varz /statusz
/tracez payloads, /healthz-/readyz health-check plumbing (including the
anomaly-driven degradation and ServingEngine.ready), the flight
recorder ring + postmortem dump + tools/flight_report.py, the
spans_dropped_total satellite, metrics_report --prom/--per-host, and
the disabled-path overhead contract for the new call sites."""

import importlib
import json
import os
import re
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _diag_clean():
    """Leave the diagnostics/telemetry globals as other tests expect:
    server stopped, health checks gone, flight disarmed, gate off."""
    from paddle_tpu import observe
    from paddle_tpu.observe import diagnostics
    yield
    diagnostics.stop()
    with diagnostics._checks_lock:
        diagnostics._checks.clear()
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe._flight_armed = False
    observe._FLIGHT_DUMP.update(path=None, last_exc=None, last_path=None)
    observe.disable()
    observe.reset()


def _get(url, timeout=10):
    """(status, body) — 4xx/5xx come back as values, not raises."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode('utf-8')
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode('utf-8')


# one value line of the text exposition format
_PROM_LINE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})? '
    r'(-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?)|NaN|[+-]Inf)$')
_PROM_LABEL = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"$')


def parse_prom(text):
    """Strict exposition parse -> (series, types): every non-comment
    line must be a well-formed sample, every label well-quoted."""
    series, types = {}, {}
    for ln in text.splitlines():
        if not ln.strip():
            continue
        if ln.startswith('#'):
            parts = ln.split()
            if len(parts) >= 4 and parts[1] == 'TYPE':
                types[parts[2]] = parts[3]
            continue
        m = _PROM_LINE.match(ln)
        assert m, 'unparseable exposition line: %r' % ln
        name, labelstr, val = m.groups()
        labels = {}
        if labelstr:
            for item in re.split(r',(?=[a-zA-Z_])', labelstr):
                lm = _PROM_LABEL.match(item)
                assert lm, 'bad label %r in %r' % (item, ln)
                labels[lm.group(1)] = lm.group(2)
        series[(name, tuple(sorted(labels.items())))] = float(val)
    return series, types


# ----------------------------------------------------------- exposition
def test_prometheus_exposition_round_trip():
    from paddle_tpu.observe.registry import (Registry,
                                             prometheus_exposition)

    reg = Registry()
    reg.counter('requests_total').inc(3, shard='a')
    reg.counter('requests_total').inc(4)
    reg.gauge('queue.depth').set(7.5, ring='x')
    h = reg.histogram('step.seconds')
    for v in range(100):
        h.observe(v / 100.0, phase='feed')
    text = prometheus_exposition(reg.snapshot())
    series, types = parse_prom(text)

    assert types['requests_total'] == 'counter'
    assert types['queue_depth'] == 'gauge'
    assert types['step_seconds'] == 'summary'     # dots mangled
    assert series[('requests_total', (('shard', 'a'),))] == 3
    assert series[('requests_total', ())] == 4
    assert series[('queue_depth', (('ring', 'x'),))] == 7.5
    # summary consistency: count/sum exact, quantiles within the data
    lk = (('phase', 'feed'),)
    assert series[('step_seconds_count', lk)] == 100
    assert series[('step_seconds_sum', lk)] == pytest.approx(49.5)
    for q in ('0.5', '0.9', '0.95', '0.99'):
        v = series[('step_seconds', tuple(sorted(
            (('phase', 'feed'), ('quantile', q))))) ]
        assert 0.0 <= v <= 0.99
        assert v >= 0.4 * float(q)                # roughly ordered


# ------------------------------------------------ live server + trainer
def _tiny_trainer(fluid):
    def train_func():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))

    return fluid.Trainer(train_func,
                         lambda: fluid.optimizer.SGD(learning_rate=0.01),
                         place=fluid.CPUPlace())


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [{'x': rng.rand(8, 4).astype('float32'),
             'y': rng.rand(8, 1).astype('float32')} for _ in range(n)]


def test_serve_scrapes_during_training():
    """The acceptance e2e: with observe.serve() active during
    Trainer.train, /metrics is valid Prometheus exposition containing
    step counters and phase histograms — scraped mid-loop AND verified
    exactly after; /varz, /statusz, /tracez all answer."""
    import paddle_tpu as fluid
    from paddle_tpu import observe

    srv = observe.serve(port=0)
    assert srv.port > 0
    trainer = _tiny_trainer(fluid)
    batches = _batches(3)

    live = {}

    def handler(e):
        if isinstance(e, fluid.trainer.EndStepEvent) and e.step == 2:
            live['code'], live['body'] = _get(srv.url + '/metrics')

    trainer.train(1, reader=lambda: iter(batches),
                  event_handler=handler)

    # mid-train scrape: valid exposition with the step counter and the
    # phase histogram series already present
    assert live['code'] == 200
    series, types = parse_prom(live['body'])
    assert types['trainer_steps_total'] == 'counter'
    assert series[('trainer_steps_total', ())] >= 2
    assert types['trainer_phase_seconds'] == 'summary'
    assert any(n == 'executor_cache_miss_total' for n, _ in series)

    # post-train: exposition and /varz agree exactly
    code, body = _get(srv.url + '/metrics')
    assert code == 200
    series, _ = parse_prom(body)
    code, varz = _get(srv.url + '/varz')
    assert code == 200
    snap = json.loads(varz)
    assert snap['host'] == 0 and snap['pid'] == os.getpid()
    st = snap['histograms']['trainer.step_seconds']
    assert series[('trainer_step_seconds_count', ())] == st['count'] == 3
    assert series[('trainer_step_seconds_sum', ())] == \
        pytest.approx(st['sum'])
    for phase in ('feed', 'compute', 'fetch'):
        assert series[('trainer_phase_seconds_count',
                       (('phase', phase),))] == 3

    # /statusz: uptime, cache keys with hit/miss/compile time, pipeline
    # depth, goodput headline
    code, body = _get(srv.url + '/statusz')
    assert code == 200
    doc = json.loads(body)
    assert doc['uptime_seconds'] > 0
    assert doc['process_index'] == 0
    assert doc['steps_total'] == 3
    assert doc['inflight_depth'] == 0
    assert doc['goodput'] is not None
    cache = doc['executor_cache']
    assert cache, 'no executor cache keys in statusz'
    step_keys = [k for k, e in cache.items()
                 if e['misses'] == 1 and e['hits'] == 2]
    assert step_keys, cache      # the step program: 1 miss then 2 hits
    assert cache[step_keys[0]]['trace_seconds'] > 0
    assert doc['healthy'] is True and 'anomaly' in doc['health']

    # /tracez: completed spans with the chrome-trace fields
    code, body = _get(srv.url + '/tracez')
    assert code == 200
    tz = json.loads(body)
    names = {s['name'] for s in tz['spans']}
    assert 'trainer.step' in names and tz['dropped'] == 0
    assert all({'name', 'ts', 'dur'} <= set(s) for s in tz['spans'])

    # unknown route: typed 404, server stays up
    code, body = _get(srv.url + '/nope')
    assert code == 404 and '/metrics' in body
    observe.stop_serving()


def test_healthz_degraded_while_anomaly_tripped():
    """NaN loss trips the streaming detector immediately; /healthz
    flips to 503 degraded until enough in-band samples clear it."""
    from paddle_tpu import observe

    srv = observe.serve(port=0)
    assert _get(srv.url + '/healthz')[0] == 200
    for _ in range(5):
        observe.anomaly('loss', 1.0)
    observe.anomaly('loss', float('nan'))     # no baseline needed
    code, body = _get(srv.url + '/healthz')
    assert code == 503
    doc = json.loads(body)
    assert doc['status'] == 'degraded'
    assert 'loss' in doc['checks']['anomaly']['detail']
    assert observe.anomaly_tripped() == ['loss']
    assert observe.get_counter('anomaly_trips_total', signal='loss') == 1
    assert observe.get_gauge('anomaly_tripped', signal='loss') == 1
    # trip + clear land in the flight ring (the leading indicator a
    # postmortem wants)
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'anomaly_trip' in kinds
    # hysteresis: clear_after in-band samples recover health
    det = observe._ANOMALY.detector('loss')
    for _ in range(det.clear_after):
        observe.anomaly('loss', 1.0)
    assert observe.anomaly_tripped() == []
    assert _get(srv.url + '/healthz')[0] == 200


def test_health_check_registry_and_readyz():
    from paddle_tpu import observe

    srv = observe.serve(port=0)
    observe.register_health_check('disk', lambda: True)
    observe.register_health_check('warm', lambda: (False, 'cold cache'),
                                  readiness_only=True)
    # liveness ignores readiness-only checks; readiness honors them
    code, body = _get(srv.url + '/healthz')
    assert code == 200 and 'warm' not in json.loads(body)['checks']
    code, body = _get(srv.url + '/readyz')
    assert code == 503
    assert json.loads(body)['checks']['warm']['detail'] == 'cold cache'
    # a raising check fails closed
    observe.register_health_check('db', lambda: 1 / 0)
    code, body = _get(srv.url + '/healthz')
    assert code == 503
    assert 'ZeroDivisionError' in \
        json.loads(body)['checks']['db']['detail']
    observe.unregister_health_check('db')
    observe.unregister_health_check('warm')
    assert _get(srv.url + '/readyz')[0] == 200


# ----------------------------------------------- serving engine readiness
class _StubPredictor(object):
    feed_names = ['x']

    def feed_specs(self):
        return {'x': ((4, 3), 'float32')}

    def predict(self, feed):
        x = np.asarray(feed['x'])
        return [x.sum(axis=1, keepdims=True)]


def test_serving_engine_ready_gates_readyz():
    from paddle_tpu import observe
    from paddle_tpu.serving import ServingEngine

    srv = observe.serve(port=0)
    eng = ServingEngine(_StubPredictor(), max_batch_size=4)
    assert not eng.ready()                 # not started, not warmed
    eng.start()
    assert not eng.ready()                 # started but would compile
    code, body = _get(srv.url + '/readyz')
    assert code == 503
    checks = json.loads(body)['checks']
    name = [n for n in checks if n.startswith('serving.engine')][0]
    assert checks[name]['detail'] == 'not warmed up'
    assert _get(srv.url + '/healthz')[0] == 200   # unready != unhealthy

    nsig = eng.warmup()
    assert nsig > 0 and eng.ready()
    assert _get(srv.url + '/readyz')[0] == 200
    # and it still actually serves
    out = eng.predict({'x': np.ones((2, 3), 'float32')})
    np.testing.assert_allclose(out[0], np.full((2, 1), 3.0))

    eng.shutdown()
    assert not eng.ready()
    # the check unregisters on shutdown: readyz no longer lists it
    code, body = _get(srv.url + '/readyz')
    assert code == 200 and name not in json.loads(body)['checks']


# ------------------------------------------------------- flight recorder
def test_flight_ring_bounds_and_postmortem_schema(tmp_path):
    from paddle_tpu.observe.flight import FlightRecorder

    fr = FlightRecorder(capacity=8)
    for i in range(20):
        fr.record('step_end', step=i, loss=float(i))
    evs = fr.events()
    assert len(evs) == 8
    assert [e['data']['step'] for e in evs] == list(range(12, 20))
    total, evicted = fr.counts()
    assert total == 20 and evicted == 12

    boom = ValueError('boom')
    path = str(tmp_path / 'pm.json')
    fr.record('nan_sample', value=float('nan'))   # must stay valid JSON
    fr.dump(path, 'unit_test', exc=boom,
            metrics={'counters': {'c': 1}, 'gauges': {}},
            anomalies={'loss': {'tripped': True, 'score': 9.0}})
    doc = json.loads(open(path).read())
    assert doc['kind'] == 'paddle_tpu_postmortem' and doc['schema'] == 1
    assert doc['reason'] == 'unit_test'
    assert doc['pid'] == os.getpid()
    assert doc['exception']['type'] == 'ValueError'
    assert doc['exception']['message'] == 'boom'
    assert doc['events_total'] == 21 and doc['evicted_events'] == 13
    assert doc['events'][-1]['data']['value'] == 'nan'
    assert doc['metrics']['counters']['c'] == 1
    assert doc['anomalies']['loss']['tripped'] is True


def test_guard_raise_dumps_postmortem_once(tmp_path):
    import paddle_tpu as fluid  # noqa: F401  (platform boot)
    from paddle_tpu import observe
    from paddle_tpu.fault.guards import BadStepError, BadStepGuard

    pm = str(tmp_path / 'pm.json')
    observe.arm_flight(path=pm)
    assert observe.flight_dump_path() == pm
    g = BadStepGuard('raise')
    g.handle(np.float32(1.0), 1)
    with pytest.raises(BadStepError) as ei:
        g.handle(np.float32(np.nan), 2)
    doc = json.loads(open(pm).read())
    assert doc['reason'] == 'bad_step'
    assert doc['exception']['type'] == 'BadStepError'
    trips = [e for e in doc['events'] if e['kind'] == 'guard_trip']
    assert trips and trips[-1]['data']['policy'] == 'raise'
    # the trainer's outer handler re-dumps the SAME exception: deduped,
    # the richer reason from the raise site wins
    assert observe.flight_dump('trainer_exception', exc=ei.value) == pm
    assert json.loads(open(pm).read())['reason'] == 'bad_step'


def test_trainer_exception_path_dumps(tmp_path):
    import paddle_tpu as fluid
    from paddle_tpu import observe

    pm = str(tmp_path / 'pm.json')
    observe.arm_flight(path=pm)
    trainer = _tiny_trainer(fluid)
    batches = _batches(2)

    def bad_reader():
        yield batches[0]
        raise RuntimeError('reader died mid-epoch')

    with pytest.raises(RuntimeError, match='reader died'):
        trainer.train(1, reader=bad_reader)
    doc = json.loads(open(pm).read())
    assert doc['reason'] == 'trainer_exception'
    assert doc['exception']['type'] == 'RuntimeError'
    kinds = [e['kind'] for e in doc['events']]
    assert 'step_end' in kinds           # the ring saw the last steps
    assert kinds[-1] == 'train_exception'


def test_flight_report_cli(tmp_path):
    from paddle_tpu import observe

    pm = str(tmp_path / 'pm.json')
    observe.enable()
    observe.arm_flight(path=pm)
    for i in range(5):
        observe.flight_event('step_end', step=i, loss=1.0 - 0.1 * i)
    observe.anomaly('loss', float('nan'))
    observe.flight_dump('unit_test')
    observe.disable()

    tool = os.path.join(REPO, 'tools', 'flight_report.py')
    r = subprocess.run([sys.executable, tool, pm],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert 'reason: unit_test' in r.stdout
    assert 'TRIPPED' in r.stdout          # anomaly state at death
    assert 'step_end' in r.stdout and 'Δloss' in r.stdout

    r = subprocess.run([sys.executable, tool, pm, '--json'],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc['reason'] == 'unit_test' and doc['last_step'] == 4
    assert doc['tripped'] == ['loss']

    # not a postmortem: clean failure
    bad = str(tmp_path / 'bad.json')
    open(bad, 'w').write('{"kind": "something_else"}')
    r = subprocess.run([sys.executable, tool, bad],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1 and 'not a paddle_tpu postmortem' in r.stderr


# ------------------------------------------------------ span drop counter
def test_spans_dropped_total_counter(monkeypatch):
    from paddle_tpu import observe
    spans_mod = importlib.import_module('paddle_tpu.observe.spans')

    monkeypatch.setattr(spans_mod, 'MAX_EVENTS', 3)
    observe.enable()
    for i in range(5):
        with observe.span('s%d' % i):
            pass
    assert len(observe.spans().events()) == 3
    assert observe.get_counter('spans_dropped_total') == 2
    # visible from the exposition alone (the satellite's point)
    from paddle_tpu.observe.registry import prometheus_exposition
    series, _ = parse_prom(prometheus_exposition(observe.snapshot()))
    assert series[('spans_dropped_total', ())] == 2


# ------------------------------------------------- metrics_report updates
def test_metrics_report_per_host_and_prom(tmp_path):
    from paddle_tpu import observe

    jsonl = str(tmp_path / 'm.jsonl')
    observe.enable(jsonl=jsonl)
    observe.inc('trainer.steps_total', 5)
    observe.record('trainer.step_seconds', 0.25)
    observe.set_gauge('run.goodput', 0.5)
    observe.flush(kind='summary')
    observe._SINK['path'] = None
    observe.disable()
    # a flushed record carries the host tag (satellite)
    rec = json.loads(open(jsonl).readline())
    assert rec['host'] == 0 and rec['pid'] == os.getpid()
    # fake a second host's summary alongside (merged multihost file)
    rec2 = dict(rec)
    rec2['host'], rec2['pid'] = 1, rec['pid'] + 1
    rec2['counters'] = {'trainer.steps_total': 7}
    with open(jsonl, 'a') as f:
        f.write(json.dumps(rec2) + '\n')

    tool = os.path.join(REPO, 'tools', 'metrics_report.py')
    r = subprocess.run([sys.executable, tool, jsonl, '--per-host'],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert 'host 0' in r.stdout and 'host 1' in r.stdout

    r = subprocess.run([sys.executable, tool, jsonl, '--prom'],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    series, types = parse_prom(r.stdout)
    assert types['trainer_steps_total'] == 'counter'
    assert series[('trainer_steps_total', ())] == 7    # newest summary
    r = subprocess.run([sys.executable, tool, jsonl, '--prom', '--json'],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 2                            # mutually exclusive


# -------------------------------------------------- disabled-path contract
def test_disabled_path_one_boolean_read():
    """With the server unstarted, telemetry off, and the flight
    recorder disarmed, the NEW call sites (flight_event / anomaly) cost
    one module-global read + return and record nothing — same contract
    as inc/record/set_gauge."""
    from paddle_tpu import observe

    observe.disable()
    assert not observe.enabled()
    n = 50000
    for _ in range(1000):     # warm up
        observe.flight_event('step_end', step=1)
        observe.anomaly('loss', 1.0)
    t0 = time.perf_counter()
    for _ in range(n):
        observe.flight_event('step_end', step=1, wall=0.1)
        observe.anomaly('loss', 1.0)
    dt = (time.perf_counter() - t0) / (2 * n)
    assert dt < 2e-6, 'disabled diagnostics call costs %.3gs' % dt
    assert observe.flight_recorder().events() == []
    assert observe.anomaly_state() == {}
    assert observe.snapshot()['counters'] == {}
    from paddle_tpu.observe import diagnostics
    assert diagnostics.active() is None
