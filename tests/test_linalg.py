"""Distributed linear algebra at pod scale (ISSUE 15).

Covers the SUMMA / blocked-Cholesky / blocked-QR / power-iteration IR
ops end to end through the Executor on dp in {1, 2, 4} CPU meshes
(numpy parity, residuals), the dyadic-exact case proving SUMMA's
result is bit-identical across mesh widths, the O(N^2/P) memory
contract, panel/block resolution precedence (attr > env > tuner >
default), the autotuner's linalg op family under injected timings,
the blocked-layout analysis pass, and the bench QUEUE <-> argparse
consistency lock.
"""

import os
import sys

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import analysis, linalg, observe, tuning
from paddle_tpu.parallel.mesh import make_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_knobs(monkeypatch, tmp_path):
    for var in ('PADDLE_TPU_AUTOTUNE', 'PADDLE_TPU_SUMMA_PANEL',
                'PADDLE_TPU_LINALG_BLOCK'):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv('PADDLE_TPU_TUNING_TABLE',
                       str(tmp_path / 'tuning.json'))
    tuning.reset()
    tuning.set_timer(None)
    yield
    tuning.reset()
    tuning.set_timer(None)


def _meshes():
    """dp in {1, 2, 4}: single device, 2x2, and 4x2 grids."""
    return [None, make_mesh(dp=2, tp=2), make_mesh(dp=4, tp=2)]


# ------------------------------------------------------------- parity
def test_summa_matches_numpy_across_meshes():
    rng = np.random.RandomState(0)
    n, k, m = 32, 64, 48
    a = rng.randn(n, k).astype('float32')
    b = rng.randn(k, m).astype('float32')
    ref = a.astype('float64') @ b.astype('float64')
    for mesh in _meshes():
        got = np.asarray(linalg.matmul(a, b, mesh=mesh, panel=8))
        rel = np.abs(got - ref).max() / np.abs(ref).max()
        assert rel < 1e-5, (mesh and dict(mesh.shape), rel)


def test_summa_dyadic_bit_identity_across_mesh_widths():
    """Mesh-width independence, bit for bit: with dyadic-rational
    inputs every partial sum is exactly representable in fp32, so the
    panel-ordered SUMMA accumulation and the single-device dot must
    agree to the LAST BIT on every mesh width and panel size."""
    rng = np.random.RandomState(1)
    n = 32
    a = (rng.randint(-4, 5, (n, n)) * 0.25).astype('float32')
    b = (rng.randint(-4, 5, (n, n)) * 0.25).astype('float32')
    results = [np.asarray(linalg.matmul(a, b))]
    for mesh in (make_mesh(dp=2, tp=2), make_mesh(dp=4, tp=2)):
        for panel in (4, 8):
            results.append(np.asarray(
                linalg.matmul(a, b, mesh=mesh, panel=panel)))
    for r in results[1:]:
        assert r.dtype == results[0].dtype
        assert np.array_equal(r, results[0]), \
            'SUMMA result not bit-identical across mesh widths'


def test_blocked_cholesky_matches_numpy():
    rng = np.random.RandomState(2)
    n = 32
    m0 = rng.randn(n, n).astype('float32')
    spd = (m0 @ m0.T + n * np.eye(n)).astype('float32')
    ref = np.linalg.cholesky(spd.astype('float64'))
    for mesh in [None, make_mesh(dp=2), make_mesh(dp=4)]:
        l = np.asarray(linalg.cholesky(spd, mesh=mesh, block=4))
        assert np.abs(np.triu(l, 1)).max() == 0.0
        rel = np.abs(l - ref).max() / np.abs(ref).max()
        assert rel < 1e-5, (mesh and dict(mesh.shape), rel)
        recon = np.abs(l @ l.T - spd).max() / np.abs(spd).max()
        assert recon < 1e-5


def test_blocked_qr_orthogonality_and_reconstruction():
    rng = np.random.RandomState(3)
    n, m = 64, 32
    a = rng.randn(n, m).astype('float32')
    for mesh in [None, make_mesh(dp=2), make_mesh(dp=4)]:
        q, r = linalg.qr(a, mesh=mesh, block=8)
        q, r = np.asarray(q), np.asarray(r)
        assert q.shape == (n, m) and r.shape == (m, m)
        assert np.abs(q.T @ q - np.eye(m)).max() < 1e-5
        assert np.abs(q @ r - a).max() / np.abs(a).max() < 1e-5
        assert np.abs(np.tril(r, -1)).max() < 1e-6


def _gapped_symmetric(n, seed=4):
    rng = np.random.RandomState(seed)
    qo, _ = np.linalg.qr(rng.randn(n, n))
    spectrum = np.concatenate([[10.0, 5.0],
                               np.linspace(1.0, 2.0, n - 2)])
    s = ((qo * spectrum) @ qo.T).astype('float32')
    return (s + s.T) / 2


def test_power_iteration_matches_numpy():
    n = 48
    s = _gapped_symmetric(n)
    w = np.linalg.eigvalsh(s)
    dom = float(w[np.abs(w).argmax()])
    for mesh in [None, make_mesh(dp=4)]:
        lam, v = linalg.power_iteration(s, iters=50, mesh=mesh)
        assert abs(lam - dom) / abs(dom) < 1e-3
        # v is the dominant eigenvector up to sign
        assert np.abs(np.asarray(s @ v) - lam * v).max() < 1e-2


def test_power_iteration_quantized_reduction():
    """The PR 13 compression/accuracy trade on a non-NN workload: the
    Rayleigh reduction through quantized_all_reduce converges to the
    same dominant eigenvalue within the quantization tolerance, and
    the wire-bytes model reports >= 3x compression."""
    n = 256
    s = _gapped_symmetric(n, seed=5)
    w = np.linalg.eigvalsh(s)
    dom = float(w[np.abs(w).argmax()])
    observe.enable()
    try:
        # qblock 64 so the wire model is padding-free at this N (the
        # honest model: a vector SMALLER than one scale block does not
        # compress)
        lam, _ = linalg.power_iteration(s, iters=50,
                                        mesh=make_mesh(dp=4),
                                        quantized=True, qblock=64)
        gauges = observe.snapshot().get('gauges', {})
    finally:
        observe.disable()
    assert abs(lam - dom) / abs(dom) < 5e-2
    comp = [v for kk, v in gauges.items()
            if kk.startswith('linalg.powit_compression')]
    assert comp and comp[0] >= 3.0, gauges


# ------------------------------------------- executor cache + memory
def test_zero_cache_misses_after_warmup():
    rng = np.random.RandomState(6)
    a = rng.randn(32, 32).astype('float32')
    b = rng.randn(32, 32).astype('float32')
    exe = fluid.Executor(fluid.CPUPlace())
    prog, out = linalg.build_matmul_program(
        32, 32, 32, mesh=make_mesh(dp=2, tp=2), panel=8)
    exe.run(prog, feed={'summa_x': a, 'summa_y': b}, fetch_list=[out])
    assert exe.last_cache_miss
    for _ in range(3):
        exe.run(prog, feed={'summa_x': a, 'summa_y': b},
                fetch_list=[out])
        assert not exe.last_cache_miss


def test_memory_contract_model():
    mesh = make_mesh(dp=2, tp=4)
    # the default panel keeps the contract by construction
    panel = linalg.default_panel(2048, 2, 4, n=512, m=512)
    model = linalg.per_shard_peak_bytes('summa_matmul', mesh,
                                        (512, 2048, 512), panel=panel)
    assert model['participants'] == 8
    assert model['factor'] <= 1.5
    # an oversized panel at a small shape breaks it, and the assert
    # helper says so
    with pytest.raises(linalg.MemoryContractError):
        linalg.assert_memory_contract('summa_matmul', mesh,
                                      (64, 128, 32), panel=16)
    # plain-dict mesh shape works too (stdlib callers)
    model2 = linalg.per_shard_peak_bytes(
        'summa_matmul', {'dp': 2, 'tp': 4}, (512, 2048, 512),
        panel=panel)
    assert model2 == model


def test_panel_resolution_precedence(monkeypatch):
    """attr > env > default, observable through the trace-time
    linalg.summa_panel gauge."""
    rng = np.random.RandomState(7)
    a = rng.randn(32, 64).astype('float32')
    b = rng.randn(64, 32).astype('float32')
    mesh = make_mesh(dp=2, tp=2)
    ref = a @ b

    def run(panel=None):
        observe.enable()
        try:
            got = np.asarray(linalg.matmul(a, b, mesh=mesh,
                                           panel=panel))
            gauges = observe.snapshot().get('gauges', {})
        finally:
            observe.disable()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
        vals = [v for kk, v in gauges.items()
                if kk.startswith('linalg.summa_panel')]
        return vals[-1]

    # env knob, read per call; an illegal value rounds DOWN to legal
    monkeypatch.setenv('PADDLE_TPU_SUMMA_PANEL', '24')
    assert run() == 16
    # explicit attr beats the env
    assert run(panel=8) == 8
    monkeypatch.delenv('PADDLE_TPU_SUMMA_PANEL')
    # default: largest legal <= 256 under the memory contract
    assert run() == linalg.default_panel(64, 2, 2, n=32, m=32)


# ------------------------------------------------------ tuning family
def test_autotune_linalg_family_fake_timer(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'on')
    calls = []

    def timer(op, key, variant, thunk):
        calls.append((op, variant))
        size = variant.get('panel', variant.get('block'))
        return 0.001 if size == 16 else 0.010

    tuning.set_timer(timer)
    mesh = make_mesh(dp=2, tp=4)
    win = tuning.decide_summa_panel(64, 512, 64, 'float32', mesh)
    assert win == {'impl': 'summa', 'panel': 16}
    n = len(calls)
    assert n > 1
    # memoized: no re-measure in process
    assert tuning.decide_summa_panel(64, 512, 64, 'float32',
                                     mesh) == win
    assert len(calls) == n
    # cholesky + qr family keys record separately
    line = make_mesh(dp=4)
    wc = tuning.decide_linalg_block('blocked_cholesky', 128, 128,
                                    'float32', line)
    wq = tuning.decide_linalg_block('blocked_qr', 256, 128, 'float32',
                                    line)
    assert wc['block'] == 16 and wq['block'] == 16
    table = tuning.current_table()
    keys = sorted(k for t in table.tables.values() for k in t)
    assert any(k.startswith('summa_matmul|') for k in keys)
    assert any(k.startswith('blocked_cholesky|') for k in keys)
    assert any(k.startswith('blocked_qr|') for k in keys)


def test_tuned_panel_dispatches_through_lowering(monkeypatch):
    """PADDLE_TPU_AUTOTUNE=on + a table winner: the summa lowering uses
    the tuned panel (gauge-observable), and an explicitly set
    PADDLE_TPU_SUMMA_PANEL still overrides the table."""
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'on')
    tuning.set_timer(lambda op, key, variant, thunk:
                     0.001 if variant.get('panel') == 16 else 0.010)
    rng = np.random.RandomState(8)
    a = rng.randn(16, 32).astype('float32')
    b = rng.randn(32, 16).astype('float32')
    mesh = make_mesh(dp=2, tp=2)

    def run():
        observe.enable()
        try:
            np.asarray(linalg.matmul(a, b, mesh=mesh))
            gauges = observe.snapshot().get('gauges', {})
        finally:
            observe.disable()
        return [v for kk, v in gauges.items()
                if kk.startswith('linalg.summa_panel')][-1]

    assert run() == 16                     # table winner
    monkeypatch.setenv('PADDLE_TPU_SUMMA_PANEL', '8')
    assert run() == 8                      # explicit gate beats table


# ------------------------------------------------------ analysis pass
def test_linalg_pass_flags_indivisible_shapes():
    prog, out = linalg.build_matmul_program(
        63, 128, 32, mesh=make_mesh(dp=2, tp=4), panel=8)
    codes = [d.code for d in analysis.run_passes(prog,
                                                 fetch_names=[out])
             if d.severity == 'error']
    assert 'block-indivisible' in codes


def test_linalg_pass_flags_unblocked_layouts():
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(dp=2, tp=4)
    prog, out = linalg.build_matmul_program(64, 128, 32, mesh=mesh,
                                            panel=8)
    del prog.var_shardings['summa_y']
    diags = analysis.run_passes(prog, fetch_names=[out],
                                passes=['linalg'])
    assert [d.code for d in diags] == ['layout-not-blocked']
    assert diags[0].var == 'summa_y'

    prog, out = linalg.build_matmul_program(64, 128, 32, mesh=mesh,
                                            panel=8)
    prog.var_shardings['summa_x'] = P(None, 'tp')
    codes = [d.code for d in analysis.run_passes(
        prog, fetch_names=[out], passes=['linalg'])]
    assert codes == ['implicit-full-gather']


def test_linalg_pass_warns_misaligned_panel():
    prog, out = linalg.build_matmul_program(
        64, 128, 32, mesh=make_mesh(dp=2, tp=4), panel=24)
    diags = analysis.run_passes(prog, fetch_names=[out],
                                passes=['linalg'])
    assert [d.code for d in diags] == ['panel-misaligned']
    assert diags[0].severity == 'warning'
    assert 'rounds it down to 16' in diags[0].message


def test_linalg_pass_checks_factorization_and_powit_layouts():
    from jax.sharding import PartitionSpec as P
    line = make_mesh(dp=4)
    prog, out = linalg.build_cholesky_program(63, mesh=line, block=4)
    codes = [d.code for d in analysis.run_passes(
        prog, fetch_names=[out], passes=['linalg'])]
    assert codes == ['block-indivisible']

    prog, (vout, lam) = linalg.build_power_iter_program(64, mesh=line)
    # row-blocked instead of the contract's column-blocked layout
    prog.var_shardings['powit_x'] = P('dp', None)
    codes = [d.code for d in analysis.run_passes(
        prog, fetch_names=[vout, lam], passes=['linalg'])]
    assert codes == ['implicit-full-gather']


# -------------------------------------------------- bench consistency
def test_every_queue_workload_is_an_argparse_choice():
    """The PR 13 bug class: a watcher QUEUE entry whose workload is
    not an accepted --workload choice fails only when the watcher
    drains on chip. Lock QUEUE (and the bench child dispatch) to
    WORKLOAD_CHOICES."""
    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import bench
        import onchip_watcher
    finally:
        sys.path.pop(0)
        sys.path.pop(0)
    choices = set(bench.WORKLOAD_CHOICES)
    for key, workload, _env, _timeout in onchip_watcher.QUEUE:
        assert workload in choices, \
            'QUEUE entry %r runs workload %r which bench.py rejects' \
            % (key, workload)
    assert 'linalg' in choices
