"""Every major layer builds + runs + takes gradients (reference:
fluid/tests/unittests/test_layers.py, which only checks graph build; we
additionally execute and, for trainables, train one step)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from util import run_startup_and, rand


def _trains(loss):
    """Append SGD and check one step runs and the loss is finite."""
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return loss


def test_fc_shapes_and_grads():
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    out = fluid.layers.fc(input=[h, h], size=3)
    loss = _trains(fluid.layers.mean(out))
    got = run_startup_and({'x': rand(4, 6)}, [out, loss])
    assert got[0].shape == (4, 3)
    assert np.isfinite(got[1]).all()


def test_fc_num_flatten_dims():
    x = fluid.layers.data(name='x', shape=[5, 6], dtype='float32')
    out = fluid.layers.fc(input=x, size=7, num_flatten_dims=2)
    got = run_startup_and({'x': rand(2, 5, 6)}, [out])
    assert got[0].shape == (2, 5, 7)


def test_embedding():
    ids = fluid.layers.data(name='ids', shape=[3], dtype='int64')
    emb = fluid.layers.embedding(input=ids, size=[10, 4])
    loss = _trains(fluid.layers.mean(emb))
    got = run_startup_and(
        {'ids': rand(2, 3, dtype='int64', high=10)}, [emb, loss])
    assert got[0].shape == (2, 3, 4)


def test_conv2d_pool2d():
    img = fluid.layers.data(name='img', shape=[3, 16, 16], dtype='float32')
    c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                            padding=1, act='relu')
    p = fluid.layers.pool2d(input=c, pool_size=2, pool_type='max',
                            pool_stride=2)
    g = fluid.layers.pool2d(input=c, pool_type='avg', global_pooling=True)
    got = run_startup_and({'img': rand(2, 3, 16, 16)}, [c, p, g])
    assert got[0].shape == (2, 8, 16, 16)
    assert got[1].shape == (2, 8, 8, 8)
    assert got[2].shape[:2] == (2, 8)


def test_conv2d_groups_stride():
    img = fluid.layers.data(name='img', shape=[4, 8, 8], dtype='float32')
    c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                            stride=2, padding=1, groups=2)
    got = run_startup_and({'img': rand(1, 4, 8, 8)}, [c])
    assert got[0].shape == (1, 8, 4, 4)


def test_conv2d_transpose():
    img = fluid.layers.data(name='img', shape=[4, 5, 5], dtype='float32')
    c = fluid.layers.conv2d_transpose(input=img, num_filters=3,
                                      filter_size=4, stride=2, padding=1)
    got = run_startup_and({'img': rand(2, 4, 5, 5)}, [c])
    assert got[0].shape == (2, 3, 10, 10)


def test_batch_norm_train_vs_test():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    bn = fluid.layers.batch_norm(input=x)
    xs = rand(8, 4, seed=3)
    got = run_startup_and({'x': xs}, [bn])[0]
    # train mode: normalized by batch stats
    np.testing.assert_allclose(got.mean(0), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(got.std(0), np.ones(4), atol=1e-2)


def test_batch_norm_updates_running_stats():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    bn = fluid.layers.batch_norm(input=x, momentum=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = rand(16, 4, seed=4) + 3.0
    for _ in range(8):
        exe.run(feed={'x': xs}, fetch_list=[bn])
    scope = fluid.global_scope()
    mean_name = [n for n in scope.keys() if 'mean' in n][0]
    running_mean = np.asarray(scope.find(mean_name))
    np.testing.assert_allclose(running_mean, xs.mean(0), atol=0.1)


def test_layer_norm():
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    ln = fluid.layers.layer_norm(x)
    xs = rand(3, 6, seed=5)
    got = run_startup_and({'x': xs}, [ln])[0]
    np.testing.assert_allclose(got.mean(1), np.zeros(3), atol=1e-5)


def test_dropout_train_and_test():
    x = fluid.layers.data(name='x', shape=[100], dtype='float32')
    d_train = fluid.layers.dropout(x, dropout_prob=0.5)
    d_test = fluid.layers.dropout(x, dropout_prob=0.5, is_test=True)
    xs = np.ones((4, 100), dtype='float32')
    got = run_startup_and({'x': xs}, [d_train, d_test])
    zeros_frac = (got[0] == 0).mean()
    assert 0.2 < zeros_frac < 0.8
    # surviving values are NOT upscaled in train; inference multiplies by
    # (1 - p) — the reference dropout_op.cc "downgrade_in_infer" semantics
    kept = got[0][got[0] != 0]
    np.testing.assert_allclose(kept, np.ones_like(kept))
    np.testing.assert_allclose(got[1], xs * 0.5)


def test_cross_entropy_and_softmax_ce():
    logits = fluid.layers.data(name='l', shape=[5], dtype='float32')
    label = fluid.layers.data(name='y', shape=[1], dtype='int64')
    prob = fluid.layers.softmax(logits)
    ce = fluid.layers.cross_entropy(input=prob, label=label)
    sce = fluid.layers.softmax_with_cross_entropy(logits=logits, label=label)
    ls = rand(3, 5, seed=6)
    ys = np.array([[0], [2], [4]], dtype='int64')
    got = run_startup_and({'l': ls, 'y': ys}, [ce, sce])
    e = np.exp(ls - ls.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    expect = -np.log(p[np.arange(3), ys[:, 0]])
    np.testing.assert_allclose(got[0].ravel(), expect, rtol=1e-5)
    np.testing.assert_allclose(got[1].ravel(), expect, rtol=1e-5)


def test_square_error_cost_smooth_l1_cos_sim():
    a = fluid.layers.data(name='a', shape=[4], dtype='float32')
    b = fluid.layers.data(name='b', shape=[4], dtype='float32')
    sec = fluid.layers.square_error_cost(input=a, label=b)
    cs = fluid.layers.cos_sim(X=a, Y=b)
    av, bv = rand(3, 4, seed=7), rand(3, 4, seed=8)
    got = run_startup_and({'a': av, 'b': bv}, [sec, cs])
    np.testing.assert_allclose(got[0], (av - bv) ** 2, rtol=1e-5)
    expect_cs = (av * bv).sum(1) / (
        np.linalg.norm(av, axis=1) * np.linalg.norm(bv, axis=1))
    np.testing.assert_allclose(got[1].ravel(), expect_cs, rtol=1e-5)


def test_l2_normalize():
    a = fluid.layers.data(name='a', shape=[4], dtype='float32')
    out = fluid.layers.l2_normalize(a, axis=1)
    av = rand(3, 4, seed=9)
    got = run_startup_and({'a': av}, [out])[0]
    np.testing.assert_allclose(
        got, av / np.linalg.norm(av, axis=1, keepdims=True), rtol=1e-5)


def test_accuracy_and_auc():
    prob = fluid.layers.data(name='p', shape=[4], dtype='float32')
    label = fluid.layers.data(name='y', shape=[1], dtype='int64')
    acc = fluid.layers.accuracy(input=prob, label=label)
    ps = np.array([[0.1, 0.7, 0.1, 0.1],
                   [0.6, 0.2, 0.1, 0.1],
                   [0.2, 0.2, 0.5, 0.1]], dtype='float32')
    ys = np.array([[1], [2], [2]], dtype='int64')
    got = run_startup_and({'p': ps, 'y': ys}, [acc])
    np.testing.assert_allclose(got[0], 2.0 / 3.0, rtol=1e-6)


def test_one_hot_multiplex():
    a = fluid.layers.data(name='a', shape=[3], dtype='float32')
    b = fluid.layers.data(name='b', shape=[3], dtype='float32')
    idx = fluid.layers.data(name='i', shape=[1], dtype='int64')
    out = fluid.layers.multiplex(inputs=[a, b], index=idx)
    av, bv = rand(4, 3, seed=10), rand(4, 3, seed=11)
    iv = np.array([[0], [1], [1], [0]], dtype='int64')
    got = run_startup_and({'a': av, 'b': bv, 'i': iv}, [out])[0]
    expect = np.where(iv == 0, av, bv)
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_nets_img_conv_pool_and_glu():
    img = fluid.layers.data(name='img', shape=[1, 8, 8], dtype='float32')
    out = fluid.nets.simple_img_conv_pool(
        input=img, num_filters=4, filter_size=3, pool_size=2, pool_stride=2,
        act='relu')
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    glu = fluid.nets.glu(input=x, dim=1)
    got = run_startup_and({'img': rand(2, 1, 8, 8), 'x': rand(2, 6)},
                          [out, glu])
    assert got[0].shape[0] == 2
    assert got[1].shape == (2, 3)


def test_scaled_dot_product_attention_net():
    q = fluid.layers.data(name='q', shape=[4, 8], dtype='float32')
    k = fluid.layers.data(name='k', shape=[6, 8], dtype='float32')
    v = fluid.layers.data(name='v', shape=[6, 8], dtype='float32')
    ctx = fluid.nets.scaled_dot_product_attention(q, k, v, num_heads=2)
    got = run_startup_and(
        {'q': rand(2, 4, 8), 'k': rand(2, 6, 8), 'v': rand(2, 6, 8)}, [ctx])
    assert got[0].shape == (2, 4, 8)


def test_nce_builds_and_trains():
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='int64')
    cost = fluid.layers.nce(input=x, label=y, num_total_classes=20,
                            num_neg_samples=4)
    loss = _trains(fluid.layers.mean(cost))
    got = run_startup_and(
        {'x': rand(4, 8), 'y': rand(4, 1, dtype='int64', high=20)}, [loss])
    assert np.isfinite(got[0]).all()


def test_im2sequence():
    img = fluid.layers.data(name='img', shape=[1, 4, 4], dtype='float32')
    seq = fluid.layers.im2sequence(input=img, filter_size=2, stride=2)
    got = run_startup_and({'img': rand(2, 1, 4, 4)}, [seq])[0]
    assert got.shape[-1] == 4  # 2x2 patches flattened


def test_bilinear_tensor_product_maxout_prelu():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[5], dtype='float32')
    btp = fluid.layers.bilinear_tensor_product(x=x, y=y, size=3)
    got = run_startup_and({'x': rand(2, 4), 'y': rand(2, 5)}, [btp])
    assert got[0].shape == (2, 3)


def test_row_conv_like_sequence_conv():
    x = fluid.layers.data(name='x', shape=[5, 4], dtype='float32')
    sc = fluid.layers.sequence_conv(input=x, num_filters=6, filter_size=3)
    got = run_startup_and({'x': rand(2, 5, 4)}, [sc])
    assert got[0].shape == (2, 5, 6)


def test_pad_reverse_expand():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    pd = fluid.layers.pad(x, paddings=[0, 0, 1, 2], pad_value=9.0)
    rv = fluid.layers.reverse(x, axis=1)
    ex = fluid.layers.expand(x, expand_times=[2, 1])
    xs = rand(2, 3, seed=12)
    got = run_startup_and({'x': xs}, [pd, rv, ex])
    assert got[0].shape == (2, 6)
    np.testing.assert_allclose(got[0][:, 1:4], xs)
    np.testing.assert_allclose(got[1], xs[:, ::-1])
    np.testing.assert_allclose(got[2], np.tile(xs, (2, 1)))


def test_smooth_l1():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[4], dtype='float32')
    out = fluid.layers.smooth_l1(x=x, y=y)
    xs, ys = rand(3, 4, seed=13), rand(3, 4, seed=14)
    got = run_startup_and({'x': xs, 'y': ys}, [out])[0]
    d = xs - ys
    expect = np.where(np.abs(d) < 1.0, 0.5 * d * d,
                      np.abs(d) - 0.5).sum(1, keepdims=True)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_label_smoothed_ce_matches_onehot_path():
    logits = fluid.layers.data(name='lg', shape=[4, 7], dtype='float32')
    label = fluid.layers.data(name='lb', shape=[4], dtype='int64')
    fused = fluid.layers.label_smoothed_cross_entropy(logits, label,
                                                      epsilon=0.1)
    smooth = fluid.layers.label_smooth(
        label=fluid.layers.one_hot(label, depth=7), epsilon=0.1)
    ref = fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=smooth, soft_label=True)
    lg = rand(2, 4, 7, seed=20)
    lb = rand(2, 4, dtype='int64', high=7)
    got = run_startup_and({'lg': lg, 'lb': lb}, [fused, ref])
    np.testing.assert_allclose(got[0].ravel(), got[1].ravel(), rtol=1e-5,
                               atol=1e-6)


def test_weight_norm_param_attr():
    """WeightNormParamAttr: w = g * v/||v|| with v/g trainable; g
    startup-initializes to ||v|| so step-0 output equals the plain
    parameterization, and after training ||w_col|| tracks g."""
    import jax
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(
        input=x, size=3, bias_attr=False,
        param_attr=fluid.WeightNormParamAttr(dim=1, name='wn_fc.w'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(
        fluid.layers.reduce_sum(pred, dim=1, keep_dim=True), y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    v0 = np.asarray(scope.find('wn_fc.w.wn_v'))
    g0 = np.asarray(scope.find('wn_fc.w.wn_g'))
    # g initialized to the per-column norm of v
    np.testing.assert_allclose(g0, np.linalg.norm(v0, axis=0),
                               rtol=1e-5, atol=1e-6)
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 6).astype('f')
    w_target = rng.randn(6, 1).astype('f')
    feed = {'x': xs, 'y': xs @ w_target}
    losses = [float(np.asarray(exe.run(feed=feed,
                                       fetch_list=[loss])[0]).reshape(()))
              for _ in range(11)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0] * 0.5
    # both v and g moved (grads flow through the reparameterization)
    vT = np.asarray(scope.find('wn_fc.w.wn_v'))
    gT = np.asarray(scope.find('wn_fc.w.wn_g'))
    assert not np.allclose(vT, v0)
    assert not np.allclose(gT, g0)
    # the IN-GRAPH w equals the numpy reconstruction g * v/||v||:
    # fetch w in the next step — it is computed from the PRE-update
    # v/g just snapshotted (the fetch run also trains one step)
    w_graph = exe.run(feed=feed, fetch_list=['wn_fc.w'])[0]
    w_want = gT * vT / np.linalg.norm(vT, axis=0, keepdims=True)
    np.testing.assert_allclose(w_graph, w_want, rtol=1e-5, atol=1e-6)


def test_label_smoothed_ce_fused_gradient_parity():
    """The custom_vjp form (no [.., V] intermediate / residual) must
    match the naive fp32 composition in BOTH directions."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.nn_ops import _ls_ce_fused
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(6, 33).astype('float32') * 3)
    y = jnp.asarray(rng.randint(0, 33, (6,)))
    eps = 0.1

    def naive(x):
        lsm = jax.nn.log_softmax(x, axis=-1)
        nll = -jnp.take_along_axis(lsm, y[:, None], axis=-1)[:, 0]
        uni = -jnp.mean(lsm, axis=-1)
        return jnp.sum((1 - eps) * nll + eps * uni)

    def fused(x):
        return jnp.sum(_ls_ce_fused(x, y, eps))

    np.testing.assert_allclose(fused(x), naive(x), rtol=1e-5)
    np.testing.assert_allclose(jax.grad(fused)(x), jax.grad(naive)(x),
                               rtol=1e-4, atol=1e-6)
    # bf16 logits (the amp path) stay close to the fp32 reference
    xb = x.astype(jnp.bfloat16)
    gf = jax.grad(fused)(xb).astype(jnp.float32)
    gn = jax.grad(naive)(x)
    assert np.max(np.abs(gf - gn)) < 0.02


def test_shared_param_keeps_first_init():
    """A parameter shared by NAME across two graphs (train + infer)
    must register exactly one startup init op — a second create would
    otherwise stack a later-running random init over the first (bias
    zeros clobbered by Xavier; regression from the rnn_search infer
    graph)."""
    x = fluid.layers.data(name='xs', shape=[4], dtype='float32')
    fluid.layers.fc(input=x, size=3,
                    param_attr=fluid.ParamAttr(name='shared.w'),
                    bias_attr=fluid.ParamAttr(name='shared.b'))
    fluid.layers.fc(input=x, size=3,
                    param_attr=fluid.ParamAttr(name='shared.w'),
                    bias_attr=fluid.ParamAttr(name='shared.b'))
    outs = [n for op in
            fluid.default_startup_program().global_block().ops
            for n in op.output_names()]
    assert outs.count('shared.w') == 1
    assert outs.count('shared.b') == 1
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    assert np.all(fluid.global_scope().numpy('shared.b') == 0.0)
