"""Cross-host control plane (ISSUE 16): RPC wire framing, the
RemoteReplica engine proxy (typed sync admission errors, bounded
backoff reconnect, heartbeat ready()), mid-stream death settling
futures typed (never hanging), drain-before-shutdown-ack, networked
KV handoff (sha1 ON by default on sockets, wire corruption refused
with zero leaked pages, dedup preserved), fault.inject.kill_process,
worker-process spawn via ProcessReplicaFactory, and the merged
multi-process metrics report."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observe
from paddle_tpu.serving import (EngineClosedError, HandoffError,
                                KVPacket, QueueFullError,
                                RemoteCallError, RemoteReplica,
                                RemoteReplicaError, ServingEngine,
                                serve_engine)
from paddle_tpu.serving import handoff as handoff_mod
from paddle_tpu.serving.rpc import (ProcessReplicaFactory, pack_arrays,
                                    unpack_arrays)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _observe_clean():
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.stop_serving()
    observe.disable()
    observe.reset()


class _Pred(object):
    """Duck predictor: doubles its input; optional compute delay."""

    feed_names = ['x']

    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s

    def feed_specs(self):
        return {'x': ((-1, 3), 'float32')}

    def predict(self, feed):
        if self.delay_s:
            time.sleep(self.delay_s)
        return [np.asarray(feed['x']) * 2.0]


def _engine(name='eng0', delay_s=0.0, **kw):
    kw.setdefault('max_batch_size', 4)
    kw.setdefault('batch_timeout_ms', 1.0)
    kw.setdefault('max_queue_depth', 8)
    eng = ServingEngine(_Pred(delay_s), name=name, **kw)
    eng.warmup()
    eng.start()
    return eng


def _served(eng):
    """Bind ``eng`` onto a live diagnostics server; returns
    (url, binding)."""
    srv = observe.serve(port=0)
    binding = serve_engine(eng)
    return srv.url, binding


# ---------------------------------------------------------- wire frame
def test_pack_arrays_roundtrip_with_bf16():
    import jax.numpy as jnp
    arrays = {'a': np.arange(6, dtype=np.float32).reshape(2, 3),
              'b': np.asarray([1, 2, 3], dtype=np.int64),
              'c': np.asarray([0.5, -1.25], dtype=jnp.bfloat16)}
    meta, back = unpack_arrays(pack_arrays({'k': 'v', 'n': 3}, arrays))
    assert meta == {'k': 'v', 'n': 3}
    assert set(back) == set(arrays)
    for name in arrays:
        a, b = np.asarray(arrays[name]), np.asarray(back[name])
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()


def test_unpack_rejects_garbage_and_truncation():
    with pytest.raises(RemoteReplicaError):
        unpack_arrays(b'NOPE' + b'\x00' * 16)
    wire = pack_arrays({}, {'a': np.ones((4, 4), np.float32)})
    with pytest.raises(RemoteReplicaError):
        unpack_arrays(wire[:-7])    # worker died mid-write


# ------------------------------------------------- loopback RPC engine
def test_remote_submit_parity_and_state():
    eng = _engine('par0')
    url, binding = _served(eng)
    rep = RemoteReplica(url, name='par0')
    try:
        assert rep.ready()
        x = np.random.RandomState(0).rand(2, 3).astype('float32')
        remote = rep.submit({'x': x}).result(15)
        local = eng.predict({'x': x}, timeout=15)
        assert np.asarray(remote[0]).tobytes() == \
            np.asarray(local[0]).tobytes()
        assert rep.queue_depth() == 0
        # name travels over /rpc/state
        assert rep._state().get('name') == 'par0'
    finally:
        binding.close()
        eng.shutdown()


def test_remote_admission_errors_raise_sync_and_typed():
    """The Router sync-error contract survives the wire: bad feeds and
    queue-full raise the SAME class, synchronously, from submit() —
    and neither is an EngineClosedError (no bogus failover)."""
    eng = _engine('adm0', delay_s=0.2, max_queue_depth=1,
                  dispatch_depth=1)
    url, binding = _served(eng)
    rep = RemoteReplica(url, name='adm0')
    try:
        with pytest.raises(ValueError) as ei:
            rep.submit({'bogus': np.ones((1, 3), np.float32)})
        assert not isinstance(ei.value, EngineClosedError)
        # saturate: 1 computing + 1 queued, then typed backpressure
        futs = [rep.submit({'x': np.ones((1, 3), np.float32)})
                for _ in range(2)]
        with pytest.raises(QueueFullError):
            for _ in range(8):
                futs.append(
                    rep.submit({'x': np.ones((1, 3), np.float32)}))
        for f in futs:
            f.result(15)
    finally:
        binding.close()
        eng.shutdown()


def test_unknown_remote_error_is_not_engine_closed():
    """A worker-side exception type the client can't map must become
    RemoteCallError (plain RuntimeError) — an application bug must
    fail the request, never masquerade as a dead replica."""
    from paddle_tpu.serving.rpc import _raise_remote
    payload = json.dumps({'error': {'type': 'SomeWeirdError',
                                    'message': 'boom'}}).encode()
    with pytest.raises(RemoteCallError) as ei:
        _raise_remote(payload, 500)
    assert not isinstance(ei.value, EngineClosedError)
    with pytest.raises(QueueFullError):
        _raise_remote(json.dumps(
            {'error': {'type': 'QueueFullError',
                       'message': 'full'}}).encode(), 429)


def test_connect_refused_backoff_then_typed():
    """Satellite: connect timeout -> bounded exponential backoff ->
    EngineClosedError subclass. The injectable sleep records the
    schedule; nothing real is slept."""
    sock = socket.socket()
    sock.bind(('127.0.0.1', 0))
    port = sock.getsockname()[1]
    sock.close()                     # nobody listening here
    sleeps = []
    rep = RemoteReplica('http://127.0.0.1:%d' % port, name='ghost',
                        reconnect_tries=4, backoff_base_s=0.05,
                        backoff_max_s=0.15, sleep=sleeps.append)
    with pytest.raises(EngineClosedError) as ei:
        rep.submit({'x': np.ones((1, 3), np.float32)})
    assert isinstance(ei.value, RemoteReplicaError)
    # 4 attempts -> 3 backoffs: base * 2^i capped at max
    assert sleeps == [0.05, 0.1, 0.15]
    assert rep.ready() is False      # heartbeat shares the verdict


def test_midstream_death_settles_future_typed_never_hangs():
    """Satellite: the SIGKILL wire shape — the worker acks admission
    then the connection dies before the body. The future must settle
    with an EngineClosedError subclass, not hang."""
    srv = socket.socket()
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def half_server():
        conn, _ = srv.accept()
        conn.recv(65536)             # the POST (enough of it)
        conn.sendall(b'HTTP/1.1 200 OK\r\n'
                     b'Content-Type: application/octet-stream\r\n'
                     b'Connection: close\r\n\r\n')
        time.sleep(0.05)
        conn.close()                 # death before any result bytes

    t = threading.Thread(target=half_server, daemon=True)
    t.start()
    rep = RemoteReplica('http://127.0.0.1:%d' % port, name='victim')
    fut = rep.submit({'x': np.ones((1, 3), np.float32)})
    with pytest.raises(EngineClosedError):
        fut.result(10)
    t.join(timeout=5)
    srv.close()


def test_midstream_death_settles_generate_stream_typed():
    srv = socket.socket()
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def half_server():
        import struct as _struct
        conn, _ = srv.accept()
        conn.recv(65536)
        frame = json.dumps({'token': 7}).encode()
        conn.sendall(b'HTTP/1.1 200 OK\r\n'
                     b'Content-Type: application/octet-stream\r\n'
                     b'Connection: close\r\n\r\n'
                     + _struct.pack('<I', len(frame)) + frame)
        time.sleep(0.05)
        conn.close()                 # dies mid-stream, one token in

    t = threading.Thread(target=half_server, daemon=True)
    t.start()
    rep = RemoteReplica('http://127.0.0.1:%d' % port, name='victim',
                        kind='decode')
    stream = rep.submit([1, 2, 3], max_new_tokens=4)
    toks = [tok for tok in stream]   # terminates — never hangs
    assert toks == [7]
    with pytest.raises(EngineClosedError):
        stream.result(10)
    assert stream.finish_reason == 'error'
    t.join(timeout=5)
    srv.close()


def test_drain_completes_accepted_work_before_shutdown_ack():
    """Satellite: every request accepted before shutdown(drain=True)
    must resolve OK before the ack comes back."""
    eng = _engine('drain0', delay_s=0.05, max_queue_depth=16)
    url, binding = _served(eng)
    rep = RemoteReplica(url, name='drain0')
    try:
        futs = [rep.submit({'x': np.ones((1, 3), np.float32)})
                for _ in range(4)]
        rep.shutdown(drain=True)     # blocks until the worker drained
        for f in futs:
            out = f.result(5)        # already computed: no new work
            assert np.asarray(out[0]).shape == (1, 3)
        assert rep.ready() is False
    finally:
        binding.close()
        eng.shutdown()


# ----------------------------------------------------- KV over the wire
SPEC = None
WEIGHTS = None


def _decode_engine(name, **kw):
    global SPEC, WEIGHTS
    from paddle_tpu.serving.decode import (DecodeEngine, LMSpec,
                                           random_weights)
    if SPEC is None:
        SPEC = LMSpec(vocab_size=60, n_layer=2, n_head=2, d_key=8,
                      d_value=8, d_model=16, d_inner=32)
        WEIGHTS = random_weights(SPEC, seed=3)
    kw.setdefault('max_batch', 4)
    kw.setdefault('block_size', 4)
    kw.setdefault('num_blocks', 64)
    kw.setdefault('pages_per_seq', 8)
    kw.setdefault('weights', WEIGHTS)
    kw.setdefault('place', fluid.CPUPlace())
    kw.setdefault('prefix_cache', True)
    eng = DecodeEngine(SPEC, name=name, **kw)
    eng.warmup()
    eng.start()
    return eng


def test_handoff_verify_default_is_transport_dependent(monkeypatch):
    """Satellite: sha1 ON by default over sockets, opt-in in-process;
    the env knob still overrides both ways."""
    monkeypatch.delenv('PADDLE_TPU_HANDOFF_VERIFY', raising=False)
    assert handoff_mod.handoff_verify_enabled('socket') is True
    assert handoff_mod.handoff_verify_enabled('inproc') is False
    monkeypatch.setenv('PADDLE_TPU_HANDOFF_VERIFY', '0')
    assert handoff_mod.handoff_verify_enabled('socket') is False
    monkeypatch.setenv('PADDLE_TPU_HANDOFF_VERIFY', '1')
    assert handoff_mod.handoff_verify_enabled('inproc') is True


def test_networked_handoff_bit_identical_with_dedup(monkeypatch):
    """KVPacket over the RPC socket: same generated tokens as the
    in-process handoff, dedup-against-destination-cache preserved."""
    monkeypatch.delenv('PADDLE_TPU_HANDOFF_VERIFY', raising=False)
    src = _decode_engine('src0')
    dst = _decode_engine('dst0')
    ref = _decode_engine('ref0')
    url, binding = _served(dst)
    rep = RemoteReplica(url, name='dst0', kind='decode')
    prompt = [int(t) for t in
              np.random.RandomState(5).randint(0, 60, 12)]
    try:
        src.submit(prompt, max_new_tokens=1).result(30)
        covered = handoff_mod.handoff(src, rep, prompt)
        assert covered > 0
        stream = rep.submit(prompt, max_new_tokens=5, temperature=0.0,
                            seed=2)
        remote_toks = stream.result(30)
        # reference: plain in-process handoff to a third engine
        handoff_mod.handoff(src, ref, prompt)
        ref_toks = ref.submit(prompt, max_new_tokens=5,
                              temperature=0.0, seed=2).result(30)
        assert remote_toks == ref_toks
        # second shipment of the same prefix: destination cache dedups
        _, installed, dedup = rep.install_packet_bytes(
            handoff_mod.export_packet(src, prompt).to_bytes(
                transport='socket'))
        assert installed == 0 and dedup > 0
    finally:
        binding.close()
        for e in (src, dst, ref):
            e.shutdown()


def test_wire_corruption_refused_typed_no_leaked_pages(monkeypatch):
    """Satellite regression: flip ONE byte of the socket wire framing
    — the install must be a typed refusal (sha1 is ON by default for
    socket transport) and the decode pool must not leak a page."""
    monkeypatch.delenv('PADDLE_TPU_HANDOFF_VERIFY', raising=False)
    src = _decode_engine('csrc0')
    dst = _decode_engine('cdst0')
    url, binding = _served(dst)
    rep = RemoteReplica(url, name='cdst0', kind='decode')
    prompt = [int(t) for t in
              np.random.RandomState(9).randint(0, 60, 10)]
    try:
        src.submit(prompt, max_new_tokens=1).result(30)
        wire = bytearray(handoff_mod.export_packet(src, prompt)
                         .to_bytes(transport='socket'))
        assert b'sha1' in bytes(wire)   # stamped by DEFAULT on socket
        wire[-3] ^= 0x40                # one arena byte, bit-flipped
        free_before = dst.free_pages()
        with pytest.raises(HandoffError):
            rep.install_packet_bytes(bytes(wire))
        assert dst.free_pages() == free_before   # nothing leaked
        # and the sender-side wire is still installable untouched
        covered, installed, _ = rep.install_packet_bytes(
            handoff_mod.export_packet(src, prompt).to_bytes(
                transport='socket'))
        assert covered > 0 and installed > 0
    finally:
        binding.close()
        src.shutdown()
        dst.shutdown()


# --------------------------------------------------------- kill_process
def test_kill_process_signals_and_resolver_forms():
    from paddle_tpu.fault import inject
    proc = subprocess.Popen([sys.executable, '-c',
                             'import time; time.sleep(60)'])
    try:
        assert inject.kill_process(proc) == proc.pid
        assert proc.wait(timeout=10) == -signal.SIGKILL
        # a reaped corpse is no victim
        assert inject.kill_process(proc) is None
        # resolver form: None target means no kill (breaker engaged)
        assert inject.kill_process(lambda: None) is None
    finally:
        if proc.poll() is None:
            proc.kill()


# ------------------------------------------------- real worker process
def test_worker_subprocess_end_to_end(tmp_path):
    """ONE real spawn: ProcessReplicaFactory boots
    tools/replica_worker.py, /readyz flips over plain HTTP, submit
    round-trips, shutdown reaps the PID, and the worker's metrics
    JSONL landed beside the parent's with the replica name as host."""
    sys.path.insert(0, REPO)
    try:
        from bench import _save_chaos_model
    finally:
        sys.path.pop(0)
    parent_jsonl = tmp_path / 'run.jsonl'
    observe.enable(jsonl=str(parent_jsonl))
    fac = ProcessReplicaFactory(
        {'kind': 'serving', 'model_dir': _save_chaos_model(4),
         'backend': 'cpu',
         'engine': {'max_batch_size': 2, 'max_queue_depth': 4}},
        workdir=str(tmp_path), spawn_timeout_s=120.0,
        heartbeat_timeout_s=1.0)
    rep = fac.create('w0')
    try:
        pid = rep.pid
        assert pid is not None and rep.ready()
        out = rep.submit({'x': np.ones((1, 4), np.float32)}).result(30)
        assert np.asarray(out[0]).shape[0] == 1
        # the worker's sink landed beside the parent's
        worker_jsonl = tmp_path / 'run-w0.jsonl'
        deadline = time.time() + 10
        while not worker_jsonl.exists() and time.time() < deadline:
            time.sleep(0.1)
        assert worker_jsonl.exists()
    finally:
        rep.shutdown(drain=True)
        fac.close()
    assert rep.proc.poll() is not None      # reaped, no zombie
    recs = [json.loads(ln) for ln in
            worker_jsonl.read_text().splitlines() if ln.strip()]
    assert any(r.get('host') == 'w0' for r in recs)


# ------------------------------------------ merged multi-process report
def _jsonl(path, records):
    with open(path, 'w') as f:
        for r in records:
            f.write(json.dumps(r) + '\n')


def test_metrics_report_fleet_merges_worker_processes(tmp_path, capsys):
    """Satellite: tools/metrics_report.py --fleet over a DIRECTORY of
    JSONLs (parent + per-worker sinks) renders one merged run with the
    per-replica census from child-emitted worker.* gauges."""
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import metrics_report
    finally:
        sys.path.pop(0)
    _jsonl(tmp_path / 'run.jsonl', [
        {'ts': 10.0, 'kind': 'snapshot', 'pid': 1, 'host': 0,
         'counters': {'controller.heals_total{route=x}': 0},
         'gauges': {'controller.replicas{route=x,state=UP}': 2}},
        {'ts': 12.0, 'kind': 'summary', 'pid': 1, 'host': 0,
         'counters': {'controller.heals_total{route=x}': 1,
                      'controller.deaths_total{route=x}': 1},
         'gauges': {'controller.replicas{route=x,state=UP}': 2,
                    'controller.replica_state{replica=r0}': 0}},
    ])
    _jsonl(tmp_path / 'run-r0.jsonl', [
        {'ts': 10.5, 'kind': 'snapshot', 'pid': 101, 'host': 'r0',
         'counters': {},
         'gauges': {'worker.up{replica=r0}': 1,
                    'worker.ready{replica=r0}': 1,
                    'worker.queue_depth{replica=r0}': 3}},
    ])
    _jsonl(tmp_path / 'run-r1.jsonl', [
        {'ts': 11.0, 'kind': 'snapshot', 'pid': 102, 'host': 'r1',
         'counters': {},
         'gauges': {'worker.up{replica=r1}': 1,
                    'worker.ready{replica=r1}': 0,
                    'worker.queue_depth{replica=r1}': 0}},
    ])
    records = metrics_report.load_records(str(tmp_path))
    assert len(records) == 4
    assert [r['ts'] for r in records] == sorted(r['ts']
                                                for r in records)
    doc = metrics_report.derive_fleet(records)
    assert doc['workers'] == {
        'r0': {'pid': 101, 'up': 1, 'ready': 1, 'queue_depth': 3},
        'r1': {'pid': 102, 'up': 1, 'ready': 0, 'queue_depth': 0}}
    text = metrics_report.render_fleet(records)
    assert 'worker processes' in text
    assert 'r0' in text and 'r1' in text
    # the CLI path: --fleet over the directory
    rc = metrics_report.main([str(tmp_path), '--fleet'])
    assert rc == 0
    assert 'worker processes' in capsys.readouterr().out


def test_crosshost_workload_is_wired():
    """QUEUE <-> argparse choices lock extends to the new workload."""
    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import bench
        import onchip_watcher
    finally:
        sys.path.pop(0)
        sys.path.pop(0)
    assert 'crosshost' in bench.WORKLOAD_CHOICES
    assert any(w == 'crosshost'
               for _k, w, _e, _t in onchip_watcher.QUEUE)
    assert callable(bench.bench_crosshost)
