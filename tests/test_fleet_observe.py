"""Fleet-wide observability (ISSUE 20): reqtrace wire-form propagation
across process hops, NTP-style clock-offset estimation (/clockz +
ClockOffsetEstimator), metrics federation (relabel_snapshot,
FleetFederation scrape/merge, /fleetz, /metrics?scope=fleet, federated
/tracez), the offline Perfetto merger (tools/fleet_trace.py), SLO
fleet-derived panels, and postmortem aggregation (heartbeat-snapshot
dumps surviving SIGKILL, FleetController attaching the dead replica's
final seconds to its heal event)."""

import json
import os
import signal
import sys
import time
import types
import urllib.request

import numpy as np
import pytest

from paddle_tpu import observe
from paddle_tpu.observe import fleet as fleet_mod
from paddle_tpu.observe import reqtrace
from paddle_tpu.observe import slo as slo_mod
from paddle_tpu.observe.fleet import (ClockOffsetEstimator,
                                      FleetFederation, fleet,
                                      http_get_json)
from paddle_tpu.observe.registry import relabel_snapshot
from paddle_tpu.serving import FleetController, Router
from paddle_tpu.serving.handoff import _VERSION, KVPacket
from paddle_tpu.serving.rpc import ProcessReplicaFactory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _observe_clean():
    yield
    fleet().clear()
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.stop_serving()
    observe.disable()
    observe.reset()


def _fleet_trace_mod():
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import fleet_trace
    finally:
        sys.path.pop(0)
    return fleet_trace


# ------------------------------------------------------- wire propagation
def test_wire_roundtrip_reconstitutes_trace():
    observe.enable()
    ctx = reqtrace.new_context('rpc', deadline_s=5.0, sample=1.0,
                               baggage={'tenant': 't0'})
    assert ctx.sampled and ctx.trace_id
    wire = json.loads(json.dumps(ctx.to_wire()))   # the hop is JSON
    assert wire['trace_id'] == ctx.trace_id
    assert wire['sampled'] is True
    assert 0.0 < wire['deadline_s'] <= 5.0         # RELATIVE budget
    assert wire['route'] == 'rpc'
    assert wire['baggage'] == {'tenant': 't0'}

    back = reqtrace.from_wire(wire)
    assert back.trace_id == ctx.trace_id
    assert back.sampled and back.route == 'rpc'
    assert back.baggage == {'tenant': 't0'}
    assert 0.0 < back.remaining() <= 5.0           # re-anchored locally
    # pre-armed flow handle: flow id = the trace id, so the receiving
    # side's flow_step links back to the sender's flow_begin
    assert back._flow is not None
    assert back._flow.flow_id == int(ctx.trace_id, 16)
    # a hop with no trace reconstitutes to None, not a dummy context
    assert reqtrace.from_wire(None) is None
    assert reqtrace.from_wire({}) is None


def test_from_wire_honors_local_telemetry_gate():
    # receiving process has telemetry off: the sampled bit is dropped
    # (spans would land on the floor) but identity/deadline survive
    assert not observe.enabled()
    wire = {'trace_id': 'abc123abc123', 'sampled': True,
            'deadline_s': 1.0, 'route': 'rpc', 'baggage': None}
    ctx = reqtrace.from_wire(wire)
    assert ctx is not None and not ctx.sampled
    assert ctx.trace_id == 'abc123abc123'
    assert ctx._flow is None
    assert 0.0 < ctx.remaining() <= 1.0


def test_kv_packet_header_carries_trace_over_wire():
    observe.enable()
    ctx = reqtrace.new_context('decode', sample=1.0)
    pkt = KVPacket({'version': _VERSION, 'trace': ctx.to_wire()},
                   {'k': np.arange(8, dtype=np.float32).reshape(2, 4)})
    back = KVPacket.from_bytes(pkt.to_bytes(transport='socket'))
    assert back.header['trace']['trace_id'] == ctx.trace_id
    assert back.header['trace']['sampled'] is True
    np.testing.assert_array_equal(np.asarray(back.arrays['k']),
                                  np.asarray(pkt.arrays['k']))


# ---------------------------------------------------------- clock offset
def test_clock_offset_estimator_converges_under_skew():
    est = ClockOffsetEstimator()
    skew = 0.25                       # remote clock runs 250ms ahead
    t = 100.0
    for _ in range(20):
        d = 0.002                     # symmetric one-way delay
        t0 = t
        t1 = t0 + d + skew
        t2 = t1 + 0.0005
        t3 = t0 + 2 * d + 0.0005
        est.update(t0, t1, t2, t3)
        t += 1.0
    assert est.offset() == pytest.approx(skew, abs=1e-9)
    assert est.samples == 20
    # a grossly asymmetric outlier (rtt 150x the best) barely moves it
    est.update(t, t + 0.5 + skew, t + 0.5 + skew, t + 0.6)
    assert est.offset() == pytest.approx(skew, abs=0.002)
    assert est.rtt() == pytest.approx(0.6)


def test_clockz_endpoint_feeds_estimator():
    observe.enable()
    srv = observe.serve(port=0)
    est = ClockOffsetEstimator()
    for _ in range(5):
        t0 = time.time()
        doc = http_get_json(srv.url + '/clockz')
        t3 = time.time()
        est.update(t0, doc['t_recv'], doc['t_send'], t3)
        assert doc['t_recv'] <= doc['t_send']
        assert doc['pid'] == os.getpid()
    # same process, same clock: offset must be ~zero (bounded by rtt)
    assert abs(est.offset()) <= est.rtt() + 1e-6


# ----------------------------------------------------- metrics federation
def test_relabel_snapshot_merges_labels():
    snap = {'counters': {'a_total{route=x}': 3},
            'gauges': {'g': 1.5},
            'histograms': {'h{q=z}': {'count': 1}},
            'pid': 7, 'host': 0, 'ts': 1.0}
    out = relabel_snapshot(snap, replica='r0', host='h0')
    assert out['counters'] == {'a_total{host=h0,replica=r0,route=x}': 3}
    assert out['gauges'] == {'g{host=h0,replica=r0}': 1.5}
    assert out['histograms'] == {'h{host=h0,q=z,replica=r0}':
                                 {'count': 1}}
    # injected labels win on conflict; non-metric keys pass through
    assert out['pid'] == 7 and out['host'] == 0 and out['ts'] == 1.0
    snap2 = {'gauges': {'g{replica=old}': 2}}
    assert relabel_snapshot(snap2, replica='new')['gauges'] == \
        {'g{replica=new}': 2}


def test_poll_interval_env_knob_read_per_call():
    assert fleet_mod.poll_interval({}) == fleet_mod.DEFAULT_POLL_S
    assert fleet_mod.poll_interval(
        {fleet_mod.FLEET_POLL_ENV: '0.5'}) == 0.5
    # zero/malformed must not spin the poll thread
    assert fleet_mod.poll_interval({fleet_mod.FLEET_POLL_ENV: '0'}) \
        == 0.05
    assert fleet_mod.poll_interval({fleet_mod.FLEET_POLL_ENV: 'nan?x'}) \
        == fleet_mod.DEFAULT_POLL_S


def test_slo_fleet_derived_panels():
    r0 = {'gauges': {'worker.queue_depth{replica=r0}': 4},
          'histograms': {'serving.request_seconds{replica=r0}':
                         {'p99': 0.2}},
          'counters': {'handoff.bytes_total{transport=socket}': 1000}}
    r1 = {'gauges': {'worker.queue_depth{replica=r1}': 1},
          'histograms': {'decode.request_seconds': {'p99': 0.1}},
          'counters': {'handoff.bytes_total{transport=socket}': 500}}
    d = slo_mod.fleet_derived({'r0': r0, 'r1': r1})
    assert d['queue_depth']['per_replica'] == {'r0': 4, 'r1': 1}
    assert d['queue_depth']['skew'] == 3
    assert d['queue_depth']['mean'] == 2.5
    assert d['p99_spread_s']['per_replica'] == {'r0': 0.2, 'r1': 0.1}
    assert d['p99_spread_s']['spread'] == pytest.approx(0.1)
    assert d['handoff_bytes_total'] == 1500
    assert d['handoff_bytes_per_s'] is None     # no previous snapshot
    # wire rate from counter deltas against a previous poll
    r0b = dict(r0, counters={'handoff.bytes_total{transport=socket}':
                             3000})
    d2 = slo_mod.fleet_derived({'r0': r0b, 'r1': r1},
                               prev={'r0': r0, 'r1': r1}, dt_s=2.0)
    assert d2['handoff_bytes_per_s'] == pytest.approx(1000.0)
    # empty fleet: everything None/empty, nothing raises
    d3 = slo_mod.fleet_derived({})
    assert d3['queue_depth']['skew'] is None
    assert d3['p99_spread_s']['spread'] is None


def test_fleet_federation_scrape_merge_and_endpoints():
    observe.enable()
    observe.set_gauge('worker.queue_depth', 4, replica='self')
    observe.inc('handoff.bytes_total', 123, transport='socket')
    srv = observe.serve(port=0)
    fed = fleet()
    # a replica handle is duck-typed: .url + optional .clock_offset();
    # point one at our OWN diagnostics server (one process plays both
    # roles — the scrape path is identical)
    fed.register(types.SimpleNamespace(
        url=srv.url, name='self', clock_offset=lambda: 0.5))
    assert fed.poll_once() == 1
    sc = fed.scrapes()['self']
    assert sc['clock_offset_s'] == 0.5
    assert observe.get_gauge('rpc.clock_offset_seconds',
                             replica='self') == 0.5
    merged = fed.merged_snapshot()
    assert any('replica=self' in k for k in merged['gauges'])
    assert any('replica=controller' in k for k in merged['gauges'])
    # /fleetz: scrape health + derived panels + the merged snapshot
    doc = http_get_json(srv.url + '/fleetz')
    assert doc['replicas']['self']['scraped'] is True
    assert doc['replicas']['self']['clock_offset_s'] == 0.5
    assert doc['replicas']['self']['consecutive_errors'] == 0
    assert doc['derived']['queue_depth']['per_replica']['self'] == 4
    assert doc['derived']['handoff_bytes_total'] == 123
    # /metrics?scope=fleet: the merge as Prometheus text
    with urllib.request.urlopen(srv.url + '/metrics?scope=fleet',
                                timeout=5) as resp:
        text = resp.read().decode()
    assert 'replica="self"' in text
    assert 'worker_queue_depth' in text
    # an unreachable replica: error counted, last snapshot retained
    fed.register(types.SimpleNamespace(url='http://127.0.0.1:9',
                                       name='gone'))
    assert fed.poll_once(timeout_s=0.5) == 1
    doc2 = http_get_json(srv.url + '/fleetz')
    assert doc2['replicas']['gone']['consecutive_errors'] >= 1
    assert doc2['replicas']['self']['scraped'] is True
    assert observe.get_counter('fleet.scrape_errors_total',
                               replica='gone') >= 1


def test_fleet_polling_thread_scrapes_on_interval():
    observe.enable()
    observe.set_gauge('worker.queue_depth', 1, replica='self')
    srv = observe.serve(port=0)
    fed = FleetFederation()
    fed.register(types.SimpleNamespace(url=srv.url, name='self'))
    fed.start_polling(interval_s=0.05)
    try:
        deadline = time.time() + 10
        while not fed.scrapes() and time.time() < deadline:
            time.sleep(0.02)
        assert 'self' in fed.scrapes()
    finally:
        fed.stop_polling()


def test_federated_tracez_merges_replica_spans():
    observe.enable()
    ctx = reqtrace.new_context('rpc', sample=1.0)
    t0 = time.perf_counter()
    ctx.stage('stage_a', t0, t0 + 0.001)
    srv = observe.serve(port=0)
    fed = fleet()
    fed.register(types.SimpleNamespace(url=srv.url, name='self'))
    # &local=1 pins the query to this process (how replicas are
    # queried, so federation cannot recurse)
    local = http_get_json('%s/tracez?trace_id=%s&local=1'
                          % (srv.url, ctx.trace_id))
    assert local['recorded'] == 1
    assert 'sources' not in local
    # the federated query appends the replica's spans (here: ourselves
    # again), each tagged with the replica name
    fdoc = http_get_json('%s/tracez?trace_id=%s'
                         % (srv.url, ctx.trace_id))
    assert fdoc['recorded'] == 2
    assert fdoc['sources']['self']['ok'] is True
    assert any((e.get('args') or {}).get('replica') == 'self'
               for e in fdoc['spans'])


# ------------------------------------------------- offline trace merging
def test_fleet_trace_merge_shifts_and_remaps():
    fleet_trace = _fleet_trace_mod()
    ev_ctl = [{'name': 'rpc_admission', 'ph': 'X', 'pid': 10, 'tid': 1,
               'ts': 1000.0, 'dur': 50.0, 'args': {'trace_id': 'abc'}}]
    ev_rep = [{'name': 'rpc_execute', 'ph': 'X', 'pid': 10, 'tid': 7,
               'ts': 2000.0, 'dur': 30.0, 'args': {'trace_id': 'abc'}}]
    doc = fleet_trace.merge_traces([('controller', ev_ctl, 0.0),
                                    ('r0', ev_rep, 0.0005)])
    events = doc['traceEvents']
    xs = [e for e in events if e['ph'] == 'X']
    # pid collision across hosts: remapped to distinct tracks
    assert len({e['pid'] for e in xs}) == 2
    # replica clock 500us ahead: its span shifts back onto the
    # controller timebase
    execs = [e for e in xs if e['name'] == 'rpc_execute']
    assert execs[0]['ts'] == pytest.approx(2000.0 - 500.0)
    assert execs[0]['args']['replica'] == 'r0'
    # each labeled input got a process_name metadata track label
    names = {e['args']['name'] for e in events if e['ph'] == 'M'}
    assert names == {'controller', 'r0'}
    # originals untouched
    assert ev_rep[0]['ts'] == 2000.0 and 'replica' not in ev_rep[0]['args']


def test_fleet_trace_input_spec_and_cli(tmp_path):
    fleet_trace = _fleet_trace_mod()
    assert fleet_trace.parse_input_spec('r0=f.json:0.25') == \
        ('r0', 'f.json', 0.25)
    assert fleet_trace.parse_input_spec('f.json') == (None, 'f.json', 0.0)
    assert fleet_trace.parse_input_spec('a=b.json') == ('a', 'b.json', 0.0)
    # all three accepted file shapes
    assert fleet_trace.load_trace_events([{'ph': 'X'}]) == [{'ph': 'X'}]
    assert fleet_trace.load_trace_events(
        {'traceEvents': [1], 'displayTimeUnit': 'ms'}) == [1]
    assert fleet_trace.load_trace_events({'spans': [2]}) == [2]
    with pytest.raises(ValueError):
        fleet_trace.load_trace_events({'nope': 1})
    a = tmp_path / 'a.trace.json'
    b = tmp_path / 'b.trace.json'
    a.write_text(json.dumps({'traceEvents': [
        {'name': 's', 'ph': 'X', 'pid': 1, 'tid': 1, 'ts': 10.0,
         'dur': 1.0}]}))
    b.write_text(json.dumps({'spans': [
        {'name': 't', 'ph': 'X', 'pid': 1, 'tid': 1, 'ts': 20.0,
         'dur': 1.0}]}))
    out = tmp_path / 'merged.json'
    rc = fleet_trace.main(['--input', 'ctl=%s' % a,
                           '--input', 'r0=%s:0.000005' % b,
                           '--output', str(out)])
    assert rc == 0
    merged = json.loads(out.read_text())
    assert len([e for e in merged['traceEvents']
                if e['ph'] == 'X']) == 2
    assert {e['args']['name'] for e in merged['traceEvents']
            if e['ph'] == 'M'} == {'ctl', 'r0'}


# -------------------------------------------- postmortem aggregation
def _postmortem_doc(reason='heartbeat_snapshot'):
    return {'kind': 'paddle_tpu_postmortem', 'schema': 1,
            'reason': reason, 'pid': 4242,
            'events': [{'seq': 0, 'ts': 1.0, 'kind': 'serving_batch'},
                       {'seq': 1, 'ts': 2.0, 'kind': 'rpc_request'}]}


class _StubReplica(object):
    """Duck-typed replica for the controller: flips dead on command and
    serves a canned postmortem, like a RemoteReplica whose worker left
    a heartbeat snapshot before a SIGKILL."""

    def __init__(self, name, postmortem=None):
        self.name = name
        self._ready = True
        self._postmortem = postmortem

    def ready(self):
        return self._ready

    def queue_depth(self):
        return 0

    def postmortem(self):
        return self._postmortem

    def drain(self, timeout=None):
        return True

    def shutdown(self, drain=True):
        self._ready = False


def test_controller_heal_attaches_postmortem():
    observe.enable()
    pm = _postmortem_doc()
    reps = [_StubReplica('r0', postmortem=pm), _StubReplica('r1')]
    router = Router(reps, admission='none', session_affinity=False)
    ctl = FleetController(router, lambda name: _StubReplica(name),
                          min_replicas=1, max_replicas=3,
                          backoff_base_s=0.01, trough_s=1e9)
    now = time.perf_counter()
    reps[0]._ready = False
    ctl.step(now=now)                 # death: postmortem pulled NOW
    assert observe.get_counter('controller.postmortems_total',
                               route='serve', lineage='r0') == 1
    ctl.step(now=now + 1.0)           # backoff expired: heal
    assert observe.get_counter('controller.heals_total',
                               route='serve', lineage='r0') == 1
    evs = observe.flight_recorder().events()
    dead = [e for e in evs if e['kind'] == 'controller_replica_dead'][-1]
    assert dead['data']['postmortem_reason'] == 'heartbeat_snapshot'
    assert dead['data']['postmortem_events'] == 2
    heal = [e for e in evs if e['kind'] == 'controller_heal'][-1]
    assert heal['data']['postmortem_reason'] == 'heartbeat_snapshot'
    assert heal['data']['postmortem_pid'] == 4242
    assert heal['data']['postmortem_events'] == 2
    assert 'rpc_request' in heal['data']['postmortem_last_kinds']
    ctl.close()
    router.close()


def test_controller_heal_without_postmortem_still_works():
    observe.enable()
    reps = [_StubReplica('r0')]       # postmortem() returns None
    router = Router(reps, admission='none', session_affinity=False)
    ctl = FleetController(router, lambda name: _StubReplica(name),
                          min_replicas=1, max_replicas=2,
                          backoff_base_s=0.01, trough_s=1e9)
    now = time.perf_counter()
    reps[0]._ready = False
    ctl.step(now=now)
    ctl.step(now=now + 1.0)
    assert observe.get_counter('controller.postmortems_total',
                               route='serve', lineage='r0') == 0
    heal = [e for e in observe.flight_recorder().events()
            if e['kind'] == 'controller_heal'][-1]
    assert heal['data']['postmortem_reason'] is None
    assert heal['data']['postmortem_events'] == 0
    ctl.close()
    router.close()


def test_load_postmortem_rejects_non_postmortems(tmp_path):
    from paddle_tpu.observe.flight import load_postmortem
    assert load_postmortem(str(tmp_path / 'missing.json')) is None
    bad = tmp_path / 'bad.json'
    bad.write_text('{not json')
    assert load_postmortem(str(bad)) is None
    wrong = tmp_path / 'wrong.json'
    wrong.write_text(json.dumps({'kind': 'something_else'}))
    assert load_postmortem(str(wrong)) is None
    good = tmp_path / 'good.json'
    good.write_text(json.dumps(_postmortem_doc()))
    assert load_postmortem(str(good))['reason'] == 'heartbeat_snapshot'


def test_flight_postmortem_string_host_survives():
    # fleet workers stamp PADDLE_TPU_OBSERVE_HOST with a replica-name
    # STRING; the postmortem doc must not die in int(host)
    from paddle_tpu.observe.flight import FlightRecorder
    fr = FlightRecorder(capacity=4)
    fr.record('x')
    doc = fr.postmortem('test', host='r0')
    assert doc['host'] == 'r0'
    assert fr.postmortem('test', host=3)['host'] == 3
    assert fr.postmortem('test')['host'] == 0


# --------------------------------------------- real worker process tests
def _chaos_model():
    sys.path.insert(0, REPO)
    try:
        from bench import _save_chaos_model
    finally:
        sys.path.pop(0)
    return _save_chaos_model(4)


def test_worker_cross_process_trace_and_clock(tmp_path):
    """ONE spawn, the whole tentpole: a sampled request's trace context
    crosses the RPC hop (controller rpc_admission + worker rpc_execute
    under ONE trace_id, flow-linked), ready() piggybacks the /clockz
    exchange, the federated /tracez returns the merged cross-process
    timeline, and tools/fleet_trace.py merges the two span exports into
    one Perfetto doc with offsets applied."""
    observe.enable()
    fac = ProcessReplicaFactory(
        {'kind': 'serving', 'model_dir': _chaos_model(),
         'backend': 'cpu',
         'engine': {'max_batch_size': 2, 'max_queue_depth': 4}},
        workdir=str(tmp_path), spawn_timeout_s=120.0,
        heartbeat_timeout_s=1.0)
    rep = fac.create('w0')
    try:
        assert rep.ready()
        assert rep.clock_offset() is not None   # synced on the probe
        assert abs(rep.clock_offset()) < 5.0    # same machine
        ctx = reqtrace.new_context('rpc', sample=1.0)
        out = rep.submit({'x': np.ones((1, 4), np.float32)},
                         ctx=ctx).result(30)
        assert np.asarray(out[0]).shape[0] == 1
        # controller-side spans landed under the trace id
        local = [e for e in observe.spans().events()
                 if (e.get('args') or {}).get('trace_id')
                 == ctx.trace_id]
        assert any(e['name'] == 'rpc_admission' for e in local)
        # the flow arrow starts on our side with flow id = trace id
        fid = int(ctx.trace_id, 16)
        assert any(e.get('id') == fid and e.get('ph') == 's'
                   for e in observe.spans().events())
        # federated /tracez (factory registered w0 with the fleet):
        # the worker's rpc_execute arrives tagged with its name
        srv = observe.serve(port=0)
        deadline = time.time() + 15
        wspans = []
        while time.time() < deadline:
            doc = http_get_json('%s/tracez?trace_id=%s'
                                % (srv.url, ctx.trace_id))
            wspans = [e for e in doc['spans']
                      if (e.get('args') or {}).get('replica') == 'w0']
            if any(e.get('name') == 'rpc_execute' for e in wspans):
                break
            time.sleep(0.2)
        assert any(e.get('name') == 'rpc_execute' for e in wspans)
        assert doc['sources']['w0']['ok'] is True
        clock_off = rep.clock_offset()
    finally:
        rep.shutdown(drain=True)
        fac.close()
    assert rep.proc.poll() is not None
    # the worker exported its span recorder on exit (trace_json wired
    # by the factory); merge both processes into one Perfetto doc
    worker_trace = tmp_path / 'w0.trace.json'
    deadline = time.time() + 15
    while not worker_trace.exists() and time.time() < deadline:
        time.sleep(0.1)
    assert worker_trace.exists()
    wdoc = json.loads(worker_trace.read_text())
    fleet_trace = _fleet_trace_mod()
    merged = fleet_trace.merge_traces([
        ('controller', observe.spans().events(), 0.0),
        ('w0', fleet_trace.load_trace_events(wdoc), clock_off or 0.0)])
    traced = [e for e in merged['traceEvents']
              if (e.get('args') or {}).get('trace_id') == ctx.trace_id]
    # spans from BOTH processes share the one trace id...
    assert len({e['pid'] for e in traced}) == 2
    # ...linked by flow events sharing the trace-id-derived flow id
    flow_phs = {e['ph'] for e in merged['traceEvents']
                if e.get('id') == fid}
    assert 's' in flow_phs and flow_phs & {'t', 'f'}
    # and the worker labeled its own track at boot
    assert any(e.get('ph') == 'M'
               and (e.get('args') or {}).get('name') == 'w0'
               for e in wdoc['traceEvents'])


def test_worker_sigkill_leaves_postmortem(tmp_path):
    """Chaos kill: SIGKILL runs no handler, but the worker's periodic
    heartbeat snapshot already left a controller-known postmortem;
    RemoteReplica.postmortem() reads the dead worker's final seconds."""
    observe.enable()
    fac = ProcessReplicaFactory(
        {'kind': 'serving', 'model_dir': _chaos_model(),
         'backend': 'cpu', 'postmortem_snapshot_s': 0.2,
         'engine': {'max_batch_size': 2, 'max_queue_depth': 4}},
        workdir=str(tmp_path), spawn_timeout_s=120.0,
        heartbeat_timeout_s=1.0)
    rep = fac.create('v0')
    try:
        assert rep.ready()
        pm_path = tmp_path / 'v0.flight.json'
        assert str(pm_path) == rep.postmortem_path
        deadline = time.time() + 30
        while not pm_path.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert pm_path.exists()      # first heartbeat snapshot landed
        os.kill(rep.pid, signal.SIGKILL)
        rep.proc.wait(timeout=10)
        pm = rep.postmortem()
        assert pm is not None
        assert pm['kind'] == 'paddle_tpu_postmortem'
        assert pm['reason'] == 'heartbeat_snapshot'
        assert pm['host'] == 'v0'    # string host survived the dump
        assert pm['pid'] == rep.pid
    finally:
        rep.shutdown(drain=False)
        fac.close()
