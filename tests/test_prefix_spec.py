"""Global radix prefix cache + speculative decoding (ISSUE 12).

Unit level: trie match/publish/evict semantics over the KV pool
(full-page-boundary rule, LRU eviction through the pool's reclaimer,
pinning, rollback), fork()'s partial-last-page contract, the n-gram
draft, and the longest-accepted-prefix rule. E2E level: with the
prefix cache on, and separately with speculative decoding on,
concurrent mixed-length streams are token-for-token identical to the
sequential no-cache baseline (extending the PR 6 invariants), the
pool drains to its initial free count through cache-hit + preempt +
requeue interleavings, and warmup covers every signature so live
traffic stays at zero executor cache misses with both features
enabled."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.serving.decode import (BlockTable, DecodeEngine, KVPool,
                                       LMSpec, NgramDraft, PrefixCache,
                                       random_weights)
from paddle_tpu.serving.decode.spec import accept_drafts

SPEC = LMSpec(vocab_size=60, n_layer=2, n_head=2, d_key=8, d_value=8,
              d_model=16, d_inner=32)
WEIGHTS = random_weights(SPEC, seed=3)


@pytest.fixture(autouse=True)
def _observe_clean():
    from paddle_tpu import observe
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.disable()
    observe.reset()


def _engine(**kw):
    kw.setdefault('max_batch', 4)
    kw.setdefault('block_size', 4)
    kw.setdefault('num_blocks', 64)
    kw.setdefault('pages_per_seq', 8)
    kw.setdefault('weights', WEIGHTS)
    kw.setdefault('place', fluid.CPUPlace())
    return DecodeEngine(SPEC, **kw)


def _shared_prefix_requests(n=6, seed=0, vocab=60):
    """Mixed-length requests where most share a 9-token system prompt
    (crosses two full pages at block_size=4) — the traffic shape the
    cache exists for."""
    rng = np.random.RandomState(seed)
    shared = [7, 3, 7, 1, 7, 4, 7, 2, 7]
    reqs = []
    for i in range(n):
        if i % 3 == 2:      # a minority of cold prompts
            prompt = rng.randint(0, vocab, rng.randint(2, 8)).tolist()
        else:
            prompt = shared + rng.randint(
                0, vocab, rng.randint(1, 5)).tolist()
        reqs.append(dict(prompt_ids=prompt,
                         max_new_tokens=int(rng.randint(3, 8)),
                         temperature=0.0 if i % 2 == 0 else 0.7,
                         seed=100 + i))
    return reqs


_BASELINE = {}


def _baseline(seed):
    """Sequential single-request decode on a plain engine (no cache,
    no speculation) — the bit-identity reference."""
    if seed not in _BASELINE:
        out = []
        for r in _shared_prefix_requests(seed=seed):
            e = _engine()
            e.start()
            out.append(e.generate(timeout=120, **r))
            e.shutdown()
        _BASELINE[seed] = out
    return _BASELINE[seed]


def _misses(snap):
    return sum(v for k, v in snap['counters'].items()
               if k.startswith('executor.cache_miss_total'))


# ------------------------------------------------------ trie semantics
def test_prefix_cache_match_stops_at_full_page_boundary():
    pool = KVPool(num_blocks=16, block_size=4)
    cache = PrefixCache(pool)
    t = BlockTable()
    tokens = list(range(11))            # 2 full pages + 3-token tail
    assert pool.grow(t, len(tokens))
    cache.publish(tokens, t, upto_tokens=11)
    assert cache.cached_pages() == 2    # the partial page never enters

    # identical prompt: both full pages hit; the tail must prefill
    t2 = BlockTable()
    assert cache.match(tokens, t2) == 8
    assert t2.block_ids == t.block_ids[:2]

    # prompt that IS exactly the cached span: match must stop strictly
    # below the prompt end (>= 1 token must prefill for the sample)
    t3 = BlockTable()
    assert cache.match(tokens[:8], t3) == 4
    assert t3.block_ids == t.block_ids[:1]

    # diverging second page: only the first page hits
    t4 = BlockTable()
    other = tokens[:4] + [55, 56, 57, 58, 9]
    assert cache.match(other, t4) == 4
    assert t4.block_ids == t.block_ids[:1]
    for tb in (t2, t3, t4):
        pool.release(tb)
    pool.release(t)
    cache.clear()
    assert pool.free_blocks() == pool.num_blocks


def test_prefix_cache_eviction_integrates_with_free_list():
    pool = KVPool(num_blocks=4, block_size=4)
    cache = PrefixCache(pool)
    t = BlockTable()
    tokens = list(range(16))
    assert pool.grow(t, 16)
    cache.publish(tokens, t, upto_tokens=16)
    pool.release(t)                     # cache is now the sole owner
    assert pool.free_blocks() == 0
    assert cache.cached_pages() == 4

    # allocation pressure LRU-evicts through the reclaimer: alloc
    # succeeds even though the free list was empty
    got = pool.alloc(2)
    assert got is not None and len(got) == 2
    assert cache.cached_pages() == 2
    assert cache.evictions == 2
    pool.free(got)

    # matched (pinned) pages survive pressure: refcount 2 > 1
    t2 = BlockTable()
    matched = cache.match(list(range(9)), t2)
    assert matched == 8                 # both surviving pages hit
    assert pool.alloc(3) is None        # pinned pages are NOT evictable
    assert cache.cached_pages() == 2
    pool.release(t2)
    assert pool.alloc(3) is not None    # demoted back to evictable
    cache.clear()


def test_prefix_cache_unmatch_rolls_back_admission_failure():
    pool = KVPool(num_blocks=8, block_size=4)
    cache = PrefixCache(pool)
    t = BlockTable()
    tokens = list(range(8))
    pool.grow(t, 8)
    cache.publish(tokens, t, upto_tokens=8)
    pool.release(t)

    t2 = BlockTable()
    n = cache.match(list(range(9)), t2)
    assert n == 8 and len(t2.block_ids) == 2
    cache.unmatch(t2, n)
    assert t2.block_ids == []
    assert cache.cached_pages() == 2    # cache refs intact
    cache.clear()
    assert pool.free_blocks() == pool.num_blocks


def test_prefix_cache_lru_evicts_oldest_chain_first():
    pool = KVPool(num_blocks=8, block_size=4)
    cache = PrefixCache(pool)
    a, b = BlockTable(), BlockTable()
    pool.grow(a, 4)
    pool.grow(b, 4)
    cache.publish([1, 2, 3, 4], a, upto_tokens=4)
    cache.publish([5, 6, 7, 8], b, upto_tokens=4)
    page_a, page_b = a.block_ids[0], b.block_ids[0]
    pool.release(a)
    pool.release(b)
    # touch chain A: B becomes the LRU victim
    t = BlockTable()
    assert cache.match([1, 2, 3, 4, 9], t) == 4
    pool.release(t)
    assert cache.reclaim(1) == 1
    assert pool.refcount(page_b) == 0   # B evicted
    assert pool.refcount(page_a) == 1   # A still cached
    cache.clear()


def test_fork_partial_last_page_not_shared():
    """Satellite: a fork at a non-boundary point must stop at the last
    FULL page — the donor keeps appending into its partial page, and a
    shared partial page would leak those writes into the child."""
    pool = KVPool(num_blocks=8, block_size=4)
    t = BlockTable()
    pool.grow(t, 11)                    # pages 0,1 full; page 2 partial
    assert len(t.block_ids) == 3
    f = pool.fork(t, frozen_tokens=11)
    assert f.block_ids == t.block_ids[:2]
    assert pool.refcount(t.block_ids[2]) == 1   # partial page private
    # boundary fork shares everything below the boundary
    f2 = pool.fork(t, frozen_tokens=8)
    assert f2.block_ids == t.block_ids[:2]
    # legacy no-arg fork still shares the whole (frozen) table
    f3 = pool.fork(t)
    assert f3.block_ids == t.block_ids
    for tb in (f, f2, f3, t):
        pool.release(tb)
    assert pool.free_blocks() == pool.num_blocks


# ------------------------------------------------------- draft + rule
def test_ngram_draft_learns_and_falls_back():
    d = NgramDraft(max_ngram=3, context=2)
    assert d.propose([1], 3) == []
    # prompt-lookup fallback: suffix [1, 2] seen earlier -> continue 3, 4
    assert d.propose([1, 2, 3, 4, 1, 2], 2) == [3, 4]
    # online learning: teach 7,8 -> 9 -> 10 and chain proposals
    d.observe([7, 8, 9])
    d.observe([8, 9, 10])
    assert d.propose([5, 7, 8], 2) == [9, 10]
    # majority wins over a single conflicting observation
    d.observe([7, 8, 9])
    d.observe([7, 8, 11])
    assert d.propose([0, 7, 8], 1) == [9]


def test_accept_drafts_longest_prefix_rule():
    # out[j] is the target's token after consuming tokens[0..j]
    assert accept_drafts([5, 6, 7], [5, 6, 7, 8]) == [5, 6, 7, 8]
    assert accept_drafts([5, 6, 7], [5, 6, 9, 8]) == [5, 6, 9]
    assert accept_drafts([4, 6, 7], [5, 6, 7, 8]) == [5]
    assert accept_drafts([], [3]) == [3]


# --------------------------------------------------------------- e2es
def test_prefix_cache_bit_identical_and_pool_drains():
    """THE cache acceptance e2e: concurrent shared-prefix traffic with
    the cache on yields streams bit-identical to the sequential
    no-cache baseline, actually hits (prefill tokens skipped > 0), and
    the pool drains to its initial free count after shutdown."""
    from paddle_tpu import observe
    observe.enable()
    want = _baseline(0)
    eng = _engine(prefix_cache=True)
    eng.warmup()
    m0 = _misses(observe.snapshot())
    eng.start()
    streams = [eng.submit(**r) for r in _shared_prefix_requests(seed=0)]
    got = [s.result(timeout=120) for s in streams]
    eng.shutdown()
    snap = observe.snapshot()
    assert got == want, 'prefix cache changed token streams'
    assert _misses(snap) == m0, \
        'cache-hit prefills must reuse warmed suffix buckets'
    assert snap['counters'].get(
        'decode.prefix_tokens_reused_total', 0) > 0
    assert snap['counters'].get(
        'decode.prefix_cache_lookups_total{outcome=hit}', 0) > 0
    assert eng.pool.free_blocks() == eng.pool.num_blocks, \
        'cache.clear() at shutdown must drain the pool to initial'


def test_spec_decode_bit_identical_zero_misses():
    """THE speculation acceptance e2e: draft-and-verify decode (greedy
    and sampled rows mixed) emits streams bit-identical to plain
    decode, with the verify signature warmed (zero live misses) and
    accepted drafts actually flowing."""
    from paddle_tpu import observe
    observe.enable()
    want = _baseline(0)
    eng = _engine(spec_k=3)
    sigs = eng.warmup()
    assert sigs == len(eng.prompt_buckets) + 2   # decode + verify keys
    m0 = _misses(observe.snapshot())
    eng.start()
    streams = [eng.submit(**r) for r in _shared_prefix_requests(seed=0)]
    got = [s.result(timeout=120) for s in streams]
    eng.shutdown()
    snap = observe.snapshot()
    assert got == want, 'speculative decoding changed token streams'
    assert _misses(snap) == m0, \
        'verify dispatches must be 100% executor cache hits'
    assert snap['counters'].get('decode.spec_steps_total', 0) > 0
    assert eng.pool.free_blocks() == eng.pool.num_blocks


def test_cache_hit_preempt_requeue_drain_invariant():
    """Satellite: the pool-free-count-returns-to-initial drain
    invariant extended with cache-hit + preempt + requeue
    interleavings — a pool small enough that admission, growth, cache
    eviction, and preemption all fight over the same pages, with both
    features enabled."""
    from paddle_tpu import observe
    observe.enable()
    observe.arm_flight()
    want = _baseline(0)
    eng = _engine(num_blocks=9, prefix_cache=True, spec_k=2)
    eng.start()
    streams = [eng.submit(**r) for r in _shared_prefix_requests(seed=0)]
    got = [s.result(timeout=120) for s in streams]
    eng.shutdown()
    snap = observe.snapshot()
    assert got == want, \
        'preemption under cache pressure changed token streams'
    assert snap['counters'].get('decode.pool_exhausted_total', 0) > 0, \
        'test must actually exercise pool pressure'
    assert snap['counters'].get('decode.prefix_evictions_total', 0) > 0, \
        'test must actually exercise cache eviction'
    assert eng.pool.free_blocks() == eng.pool.num_blocks, \
        'every page must return: sequences released, cache cleared'


def test_both_features_bit_identical_with_sampling():
    """Cache + speculation together, mixed greedy/sampled rows."""
    want = _baseline(3)
    eng = _engine(prefix_cache=True, spec_k=3)
    eng.warmup()
    eng.start()
    streams = [eng.submit(**r) for r in _shared_prefix_requests(seed=3)]
    got = [s.result(timeout=120) for s in streams]
    eng.shutdown()
    assert got == want
    assert eng.pool.free_blocks() == eng.pool.num_blocks


def test_env_knobs_read_per_call(monkeypatch):
    """PADDLE_TPU_PREFIX_CACHE / PADDLE_TPU_SPEC_K are read at engine
    construction (per call), never frozen at import."""
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE', '1')
    monkeypatch.setenv('PADDLE_TPU_SPEC_K', '2')
    eng = _engine()
    assert eng.prefix_cache is not None
    assert eng.spec_k == 2
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE', '0')
    monkeypatch.setenv('PADDLE_TPU_SPEC_K', '0')
    eng2 = _engine()
    assert eng2.prefix_cache is None
    assert eng2.spec_k == 0
    # constructor args win over the env
    monkeypatch.setenv('PADDLE_TPU_SPEC_K', '5')
    eng3 = _engine(spec_k=1, prefix_cache=True)
    assert eng3.spec_k == 1 and eng3.prefix_cache is not None


def test_statusz_decode_panel_prefix_spec_fields():
    from paddle_tpu import observe
    from paddle_tpu.observe.diagnostics import _decode_status
    observe.enable()
    eng = _engine(prefix_cache=True, spec_k=2)
    eng.start()
    prompt = [7, 3, 7, 1, 7, 4, 7, 2, 7, 5]
    eng.generate(prompt, max_new_tokens=6)
    # identical repeat: the prompt hits the cache, and the draft —
    # trained on the first stream — proposes its exact continuation
    eng.generate(prompt, max_new_tokens=6)
    doc = _decode_status(observe.snapshot())
    eng.shutdown()
    assert doc['prefix_cache_hit_rate'] is not None
    assert doc['prefix_cache_hit_rate'] > 0
    assert doc['prefix_tokens_reused_total'] > 0
    assert doc['spec_steps_total'] >= 1
    assert doc['spec_accepted_len_mean'] is not None
