"""Predictor: load saved inference model, repeated predicts reuse the
compile cache (reference: inference/tests/test_helper.h flows)."""

import numpy as np

import paddle_tpu as fluid
from util import rand


def _save_model(tmp_path):
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    out = fluid.layers.fc(input=h, size=3, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = rand(4, 6)
    expect = exe.run(feed={'x': xs}, fetch_list=[out])[0]
    fluid.io.save_inference_model(str(tmp_path), ['x'], [out], exe)
    return xs, expect


def test_predictor_matches_training_graph(tmp_path):
    from paddle_tpu.inference import create_predictor
    xs, expect = _save_model(tmp_path)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    pred = create_predictor(str(tmp_path))
    got = pred({'x': xs})
    np.testing.assert_allclose(got[0], expect, rtol=1e-5)
    # cache reused across calls; new batch size recompiles transparently
    got2 = pred({'x': rand(7, 6, seed=9)})
    assert got2[0].shape == (7, 3)
    np.testing.assert_allclose(got2[0].sum(1), np.ones(7), rtol=1e-5)


def test_predictor_isolated_scope(tmp_path):
    from paddle_tpu.inference import create_predictor
    xs, expect = _save_model(tmp_path)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    pred = create_predictor(str(tmp_path))
    assert len(list(fluid.global_scope().keys())) == 0  # no leakage
    got = pred({'x': xs})
    np.testing.assert_allclose(got[0], expect, rtol=1e-5)


def test_predictor_bf16(tmp_path):
    """Predictor(bf16=True) — the serving-side AMP path — returns
    near-identical probabilities to the fp32 predictor."""
    from paddle_tpu.inference.predictor import Predictor
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    probs = fluid.layers.fc(input=x, size=4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    dirname = str(tmp_path / 'm')
    fluid.io.save_inference_model(dirname, ['x'], [probs], exe)

    xs = np.random.RandomState(0).rand(5, 8).astype('float32')
    p32 = Predictor(dirname, place=fluid.CPUPlace())
    p16 = Predictor(dirname, place=fluid.CPUPlace(), bf16=True)
    out32 = p32.predict({'x': xs})[0]
    out16 = p16.predict({'x': xs})[0]
    np.testing.assert_allclose(out32, out16, atol=2e-2)
    np.testing.assert_allclose(np.asarray(out16).sum(-1), 1.0, atol=1e-2)


def test_rnn_search_decode_inference_roundtrip(tmp_path):
    """save/load_inference_model around the rnn_search greedy-decode
    program: the reloaded program reproduces the decode ids exactly
    (serving parity for the seq2seq decode ops)."""
    from paddle_tpu.core.program import Program, program_guard
    from paddle_tpu.models.rnn_search import (make_fake_batch, rnn_search,
                                              rnn_search_greedy_infer)
    cost, _ = rnn_search(src_vocab=30, trg_vocab=30, emb_dim=8,
                         hidden_dim=8)
    fluid.optimizer.Adam(learning_rate=5e-3).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = make_fake_batch(4, 5, 4, 30, 30)
    for _ in range(20):
        exe.run(feed=feed, fetch_list=[cost])
    ip = Program()
    with program_guard(ip, fluid.default_startup_program()):
        ids, feeds = rnn_search_greedy_infer(30, 30, 8, 8, max_out_len=4)
    f = {'src_word': feed['src_word'], 'src_len': feed['src_len']}
    want = np.asarray(exe.run(program=ip, feed=f, fetch_list=[ids])[0])
    fluid.io.save_inference_model(str(tmp_path), feeds, [ids], exe,
                                  main_program=ip)
    fluid.global_scope().clear()
    exe2 = fluid.Executor(fluid.CPUPlace())
    prog, _names, fetches = fluid.io.load_inference_model(str(tmp_path),
                                                          exe2)
    got = np.asarray(exe2.run(program=prog, feed=f,
                              fetch_list=fetches)[0])
    np.testing.assert_array_equal(got, want)
