"""Predictor: load saved inference model, repeated predicts reuse the
compile cache (reference: inference/tests/test_helper.h flows)."""

import numpy as np

import paddle_tpu as fluid
from util import rand


def _save_model(tmp_path):
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    out = fluid.layers.fc(input=h, size=3, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = rand(4, 6)
    expect = exe.run(feed={'x': xs}, fetch_list=[out])[0]
    fluid.io.save_inference_model(str(tmp_path), ['x'], [out], exe)
    return xs, expect


def test_predictor_matches_training_graph(tmp_path):
    from paddle_tpu.inference import create_predictor
    xs, expect = _save_model(tmp_path)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    pred = create_predictor(str(tmp_path))
    got = pred({'x': xs})
    np.testing.assert_allclose(got[0], expect, rtol=1e-5)
    # cache reused across calls; new batch size recompiles transparently
    got2 = pred({'x': rand(7, 6, seed=9)})
    assert got2[0].shape == (7, 3)
    np.testing.assert_allclose(got2[0].sum(1), np.ones(7), rtol=1e-5)


def test_predictor_isolated_scope(tmp_path):
    from paddle_tpu.inference import create_predictor
    xs, expect = _save_model(tmp_path)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    pred = create_predictor(str(tmp_path))
    assert len(list(fluid.global_scope().keys())) == 0  # no leakage
    got = pred({'x': xs})
    np.testing.assert_allclose(got[0], expect, rtol=1e-5)
