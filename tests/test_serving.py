"""paddle_tpu.serving: shape-bucket ladder math, the micro-batching
engine under concurrency (bit-identical to sequential Predictor.predict,
zero executor cache misses after warmup), QueueFullError backpressure,
drain/shutdown semantics, the thread-safe executor cache, and the
serving_bench load generator's --json schema."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.serving import (BucketLadder, EngineClosedError,
                                QueueFullError, ServingEngine,
                                pow2_ladder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _observe_clean():
    from paddle_tpu import observe
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.disable()
    observe.reset()


def _total(counters, prefix):
    return sum(v for k, v in counters.items() if k.startswith(prefix))


def _save_mlp(dirname):
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    out = fluid.layers.fc(input=h, size=3, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(dirname, ['x'], [out], exe)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    return dirname


# ------------------------------------------------------------- buckets
def test_pow2_ladder_and_rung_lookup():
    assert pow2_ladder(8) == [1, 2, 4, 8]
    assert pow2_ladder(6) == [1, 2, 4, 6]   # non-pow2 cap is the top rung
    assert pow2_ladder(1) == [1]
    with pytest.raises(ValueError):
        pow2_ladder(0)

    lad = BucketLadder(8)
    assert lad.bucket_batch(1) == 1
    assert lad.bucket_batch(3) == 4
    assert lad.bucket_batch(8) == 8
    with pytest.raises(ValueError):
        lad.bucket_batch(9)
    assert lad.signatures() == [(1, None), (2, None), (4, None),
                                (8, None)]

    seq = BucketLadder(4, seq_axes={'ids': 1}, seq_lens=[16, 64])
    assert seq.bucket_seq(5) == 16
    assert seq.bucket_seq(64) == 64
    with pytest.raises(ValueError):
        seq.bucket_seq(65)
    assert len(seq.signatures()) == 3 * 2   # batch rungs x seq rungs


def test_assemble_pads_and_disassemble_unpads():
    lad = BucketLadder(8)
    feeds = [{'x': np.arange(6, dtype='float32').reshape(2, 3)},
             {'x': 10 + np.arange(9, dtype='float32').reshape(3, 3)}]
    padded, info = lad.assemble(feeds)
    assert padded['x'].shape == (8, 3)     # 5 rows -> rung 8
    assert info.sizes == [2, 3] and info.total == 5
    # edge padding replicates the last real row
    np.testing.assert_array_equal(padded['x'][5], padded['x'][4])
    assert abs(info.waste() - 3.0 / 8.0) < 1e-9
    np.testing.assert_array_equal(info.batch_mask(),
                                  [1, 1, 1, 1, 1, 0, 0, 0])

    fetch = padded['x'] * 2.0               # row-aligned fake result
    outs = lad.disassemble([fetch], info)
    assert len(outs) == 2
    np.testing.assert_array_equal(outs[0][0], feeds[0]['x'] * 2.0)
    np.testing.assert_array_equal(outs[1][0], feeds[1]['x'] * 2.0)


def test_assemble_seq_buckets_and_token_mask():
    lad = BucketLadder(4, seq_axes={'x': 1}, seq_lens=[4, 8], pad='zero')
    feeds = [{'x': np.ones((1, 3, 2), 'float32')},
             {'x': np.ones((2, 6, 2), 'float32')}]
    padded, info = lad.assemble(feeds)
    assert padded['x'].shape == (4, 8, 2)   # 3 rows -> 4, seq 6 -> 8
    assert info.seq_sizes == [3, 6] and info.seq_bucket == 8
    mask = info.token_mask()
    assert mask.shape == (4, 8)
    assert mask[0, :3].all() and not mask[0, 3:].any()   # req 0: len 3
    assert mask[1, :6].all() and not mask[2, 6:].any()   # req 1: len 6
    assert not mask[3].any()                             # padding row
    # element-level waste: real = 1*3*1 + 2*6*1 of 4*8
    assert abs(info.waste() - (1.0 - 15.0 / 32.0)) < 1e-9
    # per-request seq un-padding
    outs = lad.disassemble([padded['x']], info, fetch_seq_axes={0: 1})
    assert outs[0][0].shape == (1, 3, 2)
    assert outs[1][0].shape == (2, 6, 2)


def test_assemble_validation():
    lad = BucketLadder(4)
    with pytest.raises(ValueError):
        lad.assemble([])
    with pytest.raises(ValueError):    # inconsistent rows in one request
        lad.rows_of({'a': np.zeros((2, 3)), 'b': np.zeros((3, 3))})
    with pytest.raises(ValueError):    # feed-name mismatch across reqs
        lad.assemble([{'a': np.zeros((1, 2))}, {'b': np.zeros((1, 2))}])
    with pytest.raises(ValueError):
        BucketLadder(4, seq_axes={'a': 1})   # seq_axes without seq_lens


# -------------------------------------------------------------- engine
def test_engine_concurrent_matches_sequential(tmp_path):
    """Acceptance: N threads x mixed batch sizes through the engine ==
    sequential Predictor.predict bit-for-bit; with warmup, live traffic
    causes ZERO executor cache misses; compiles == warmup signatures."""
    from paddle_tpu import observe
    from paddle_tpu.inference import create_predictor

    d = _save_mlp(str(tmp_path / 'm'))
    rng = np.random.RandomState(0)
    sizes = [1, 3, 2, 4, 1, 2, 3, 4, 1, 2, 2, 1]
    reqs = [{'x': rng.rand(n, 6).astype('float32')} for n in sizes]

    seq_pred = create_predictor(d, place=fluid.CPUPlace())
    expected = [seq_pred.predict(r) for r in reqs]

    eng_pred = create_predictor(d, place=fluid.CPUPlace())
    observe.enable()
    observe.reset()
    eng = ServingEngine(eng_pred, max_batch_size=4, batch_timeout_ms=5,
                        max_queue_depth=64)
    nsig = eng.warmup()
    assert nsig == 3               # rungs [1, 2, 4]
    miss_warm = _total(observe.snapshot()['counters'],
                       'executor.cache_miss_total')
    assert miss_warm == nsig       # warmup compiled exactly the ladder

    eng.start()
    results = [None] * len(reqs)

    def client(i):
        results[i] = eng.predict(reqs[i], timeout=60)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.shutdown()

    snap = observe.snapshot()
    assert _total(snap['counters'], 'executor.cache_miss_total') == \
        miss_warm, 'live traffic recompiled despite warmup'
    assert _total(snap['counters'], 'executor.cache_hit_total') >= 1
    assert snap['counters'].get('serving.requests_total') == len(reqs)
    assert snap['histograms']['serving.batch_size']['count'] >= 1
    assert snap['histograms']['serving.padding_waste']['count'] >= 1
    for h in ('serving.queue_seconds', 'serving.compute_seconds',
              'serving.request_seconds'):
        assert any(k.startswith(h) for k in snap['histograms']), h
    assert 'serving.queue_depth' in snap['gauges']

    for i in range(len(reqs)):
        np.testing.assert_array_equal(
            np.asarray(results[i][0]), np.asarray(expected[i][0]),
            err_msg='request %d (batch %d) diverged from sequential '
                    'predict' % (i, sizes[i]))


def test_engine_seq_buckets_mask_feed(tmp_path):
    """Sequence bucketing end-to-end: variable-length requests pad up
    the (batch, seq) ladder, the engine-generated token mask keeps the
    masked reduction exact, and per-position fetches un-pad to each
    request's real length."""
    from paddle_tpu import observe
    from paddle_tpu.inference import create_predictor

    x = fluid.layers.data(name='x', shape=[-1, 2], dtype='float32')
    m = fluid.layers.data(name='m', shape=[-1], dtype='float32')
    y = fluid.layers.scale(x, scale=2.0, bias=1.0)          # [B, T, 2]
    mm = fluid.layers.unsqueeze(m, axes=[2])
    s = fluid.layers.reduce_sum(fluid.layers.elementwise_mul(x, mm),
                                dim=1)                      # [B, 2]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / 'seq')
    fluid.io.save_inference_model(d, ['x', 'm'], [y, s], exe)
    fluid.reset_default_programs()
    fluid.global_scope().clear()

    rng = np.random.RandomState(1)
    shapes = [(1, 3), (2, 5), (3, 8), (1, 6), (4, 2), (2, 7)]
    reqs = [{'x': rng.rand(n, t, 2).astype('float32')}
            for n, t in shapes]

    seq_pred = create_predictor(d, place=fluid.CPUPlace())
    expected = []
    for (n, t), r in zip(shapes, reqs):
        expected.append(seq_pred.predict(
            dict(r, m=np.ones((n, t), 'float32'))))

    observe.enable()
    observe.reset()
    eng_pred = create_predictor(d, place=fluid.CPUPlace())
    eng = ServingEngine(eng_pred, max_batch_size=4, batch_timeout_ms=5,
                        seq_axes={'x': 1}, seq_lens=[4, 8],
                        mask_feed='m', fetch_seq_axes={0: 1})
    nsig = eng.warmup()
    assert nsig == 3 * 2
    miss_warm = _total(observe.snapshot()['counters'],
                       'executor.cache_miss_total')
    eng.start()

    results = [None] * len(reqs)

    def client(i):
        results[i] = eng.predict(reqs[i], timeout=60)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.shutdown()

    assert _total(observe.snapshot()['counters'],
                  'executor.cache_miss_total') == miss_warm
    for i, (n, t) in enumerate(shapes):
        assert np.asarray(results[i][0]).shape == (n, t, 2)
        for j in range(2):
            np.testing.assert_array_equal(np.asarray(results[i][j]),
                                          np.asarray(expected[i][j]))
    # the engine owns the mask: supplying it is an error
    with pytest.raises(ValueError):
        eng_pred2 = create_predictor(d, place=fluid.CPUPlace())
        eng2 = ServingEngine(eng_pred2, max_batch_size=4,
                             seq_axes={'x': 1}, seq_lens=[4, 8],
                             mask_feed='m')
        eng2.submit({'x': np.zeros((1, 4, 2), 'float32'),
                     'm': np.ones((1, 4), 'float32')})


def test_engine_queue_full_fast_fail(tmp_path):
    """Over-capacity submits fail fast with QueueFullError instead of
    blocking; once the workers start, everything queued completes."""
    from paddle_tpu.inference import create_predictor

    d = _save_mlp(str(tmp_path / 'm'))
    pred = create_predictor(d, place=fluid.CPUPlace())
    eng = ServingEngine(pred, max_batch_size=2, batch_timeout_ms=1,
                        max_queue_depth=3)
    feeds = [{'x': np.full((1, 6), float(i), 'float32')}
             for i in range(4)]
    futs = [eng.submit(feeds[i]) for i in range(3)]   # engine not started
    t0 = time.perf_counter()
    with pytest.raises(QueueFullError):
        eng.submit(feeds[3])
    assert time.perf_counter() - t0 < 1.0   # fail-fast, not a block
    eng.warmup()
    eng.start()
    outs = [f.result(timeout=60) for f in futs]
    assert all(np.asarray(o[0]).shape == (1, 3) for o in outs)
    eng.shutdown()


def test_engine_shutdown_and_drain(tmp_path):
    from paddle_tpu.inference import create_predictor

    d = _save_mlp(str(tmp_path / 'm'))
    pred = create_predictor(d, place=fluid.CPUPlace())
    eng = ServingEngine(pred, max_batch_size=4, batch_timeout_ms=1)
    eng.warmup()
    eng.start()
    futs = [eng.submit({'x': np.zeros((2, 6), 'float32')})
            for _ in range(5)]
    eng.shutdown(drain=True, timeout=60)     # completes accepted work
    assert all(f.done() and f.exception() is None for f in futs)
    with pytest.raises(EngineClosedError):
        eng.submit({'x': np.zeros((1, 6), 'float32')})

    # non-draining shutdown on a never-started engine fails its queue
    pred2 = create_predictor(d, place=fluid.CPUPlace())
    eng2 = ServingEngine(pred2, max_batch_size=4)
    f2 = eng2.submit({'x': np.zeros((1, 6), 'float32')})
    eng2.shutdown(drain=False)
    assert isinstance(f2.exception(timeout=5), EngineClosedError)


def test_engine_rejects_malformed_submits(tmp_path):
    from paddle_tpu.inference import create_predictor

    d = _save_mlp(str(tmp_path / 'm'))
    pred = create_predictor(d, place=fluid.CPUPlace())
    eng = ServingEngine(pred, max_batch_size=4)
    with pytest.raises(ValueError):          # missing feed
        eng.submit({})
    with pytest.raises(ValueError):          # unknown feed name
        eng.submit({'x': np.zeros((1, 6), 'float32'),
                    'bogus': np.zeros((1,), 'float32')})
    with pytest.raises(ValueError):          # oversize request
        eng.submit({'x': np.zeros((5, 6), 'float32')})
    eng.shutdown(drain=False)


# ---------------------------------------------------- executor threading
def test_executor_concurrent_same_key_compiles_once():
    """Satellite: racing threads on one (program, shapes) key must
    produce exactly ONE compile (per-key lock), and last_cache_miss is
    per-thread."""
    from paddle_tpu import observe

    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out = fluid.layers.fc(input=x, size=2, act=None)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    observe.enable()
    observe.reset()
    feed = {'x': np.ones((3, 4), 'float32')}
    n_threads, outs, errs = 8, [None] * 8, []

    def worker(i):
        try:
            outs[i] = exe.run(feed=feed, fetch_list=[out])[0]
        except BaseException as e:   # pragma: no cover - diagnostic
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    misses = _total(observe.snapshot()['counters'],
                    'executor.cache_miss_total')
    assert misses == 1, 'duplicate compile under a same-key race'
    for o in outs[1:]:
        np.testing.assert_array_equal(o, outs[0])


# ------------------------------------------------------------ satellites
def test_predictor_rejects_unknown_feeds(tmp_path):
    from paddle_tpu.inference import create_predictor

    d = _save_mlp(str(tmp_path / 'm'))
    pred = create_predictor(d, place=fluid.CPUPlace())
    with pytest.raises(ValueError, match='unexpected feed'):
        pred.predict({'x': np.zeros((1, 6), 'float32'),
                      'typo': np.zeros((1, 6), 'float32')})
    specs = pred.feed_specs()
    assert set(specs) == {'x'}
    shape, dtype = specs['x']
    assert shape == (-1, 6) and dtype == 'float32'


def test_save_inference_model_atomic(tmp_path, monkeypatch):
    """A failed model dump must not clobber the existing __model__.json
    (unique tmp + os.replace, like checkpoints)."""
    import paddle_tpu.io as pio

    d = _save_mlp(str(tmp_path / 'm'))
    before = open(os.path.join(d, '__model__.json')).read()
    json.loads(before)

    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    out = fluid.layers.fc(input=x, size=3, act=None)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    class _Boom(Exception):
        pass

    real_dumps = pio.json.dumps

    def boom(*a, **k):
        raise _Boom()

    monkeypatch.setattr(pio.json, 'dumps', boom)
    with pytest.raises(_Boom):
        pio.save_inference_model(d, ['x'], [out], exe)
    monkeypatch.setattr(pio.json, 'dumps', real_dumps)

    assert open(os.path.join(d, '__model__.json')).read() == before
    leftovers = [f for f in os.listdir(d)
                 if f.startswith('__model__.json.')]
    assert leftovers == [], 'torn tmp files left behind: %s' % leftovers


# ----------------------------------------------------------- bench tool
def test_serving_bench_smoke(tmp_path):
    """tools/serving_bench.py: ~1s closed-loop run, --json schema."""
    tool = os.path.join(REPO, 'tools', 'serving_bench.py')
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    jsonl = str(tmp_path / 'bench.jsonl')
    r = subprocess.run(
        [sys.executable, tool, '--duration', '0.4', '--clients', '2',
         '--max-batch-size', '4', '--batch-timeout-ms', '1', '--json',
         '--metrics-jsonl', jsonl],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    for key in ('mode', 'duration_s', 'requests_ok', 'requests_rejected',
                'rows', 'throughput_rps', 'throughput_rows_per_s',
                'latency_ms', 'warmup', 'executor', 'engine'):
        assert key in doc, key
    assert doc['mode'] == 'closed'
    assert doc['requests_ok'] >= 1
    lat = doc['latency_ms']
    for q in ('p50', 'p95', 'p99', 'mean', 'max'):
        assert lat[q] is not None and lat[q] > 0
    assert lat['p50'] <= lat['p95'] <= lat['p99'] <= lat['max']
    assert doc['warmup']['signatures'] == 3        # rungs [1, 2, 4]
    # the zero-live-compile invariant, via the executor's own counters
    assert doc['executor']['cache_misses'] == doc['warmup']['signatures']
    assert doc['executor']['cache_hits'] >= doc['requests_ok'] // 4
    assert doc['engine']['buckets'] == [1, 2, 4]
    # metrics landed in the standard pipeline and the report reads them
    report = os.path.join(REPO, 'tools', 'metrics_report.py')
    r2 = subprocess.run([sys.executable, report, jsonl, '--json'],
                        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0, r2.stderr
    doc2 = json.loads(r2.stdout)
    assert any(k.startswith('serving.batch_size')
               for k in doc2['histograms'])


# ------------------------------------------------------------------ soak
@pytest.mark.slow
def test_engine_soak_mixed_sizes(tmp_path):
    """Soak: sustained mixed-size traffic from many threads stays
    bit-identical and never recompiles."""
    from paddle_tpu import observe
    from paddle_tpu.inference import create_predictor

    d = _save_mlp(str(tmp_path / 'm'))
    seq_pred = create_predictor(d, place=fluid.CPUPlace())
    # pre-warm the sequential oracle over every size it will see, so
    # the zero-miss assertion below measures ONLY the engine's compiles
    for n in range(1, 9):
        seq_pred.predict({'x': np.zeros((n, 6), 'float32')})
    eng_pred = create_predictor(d, place=fluid.CPUPlace())
    observe.enable()
    observe.reset()
    eng = ServingEngine(eng_pred, max_batch_size=8, batch_timeout_ms=2,
                        max_queue_depth=256)
    nsig = eng.warmup()
    miss_warm = _total(observe.snapshot()['counters'],
                       'executor.cache_miss_total')
    assert miss_warm == nsig
    eng.start()

    n_threads, per_thread = 8, 40
    errs = []

    def client(tid):
        rng = np.random.RandomState(tid)
        try:
            for k in range(per_thread):
                n = int(rng.randint(1, 9))
                feed = {'x': rng.rand(n, 6).astype('float32')}
                got = eng.predict(feed, timeout=60)
                want = seq_pred.predict(feed)
                np.testing.assert_array_equal(np.asarray(got[0]),
                                              np.asarray(want[0]))
                if k % 7 == 0:
                    time.sleep(0.001)
        except BaseException as e:
            errs.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.shutdown()
    assert not errs, errs[:1]
    snap = observe.snapshot()
    assert _total(snap['counters'], 'executor.cache_miss_total') == \
        miss_warm
    assert snap['counters'].get('serving.requests_total') == \
        n_threads * per_thread
