"""GAN training via two Programs (reference: doc/design/gan_api.md — the
fluid GAN design builds discriminator and generator losses in separate
program regions). Our executor enforces one backward section per
Program (core/executor.py raises on multiple minimize calls), so a GAN
is two Programs sharing the scope — this test proves that composition
actually trains adversarially end-to-end."""

import numpy as np

import paddle_tpu as fluid


def _mlp(x, sizes, prefix, act_last=None):
    h = x
    for i, s in enumerate(sizes):
        act = 'relu' if i < len(sizes) - 1 else act_last
        h = fluid.layers.fc(
            input=h, size=s, act=act,
            param_attr=fluid.ParamAttr(name='%s_w%d' % (prefix, i)),
            bias_attr=fluid.ParamAttr(name='%s_b%d' % (prefix, i)))
    return h


def test_gan_trains_with_shared_scope():
    rng = np.random.RandomState(0)
    noise_dim, data_dim = 4, 2

    # --- discriminator program: D(real) -> 1, D(G(z)) -> 0
    d_prog, d_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(d_prog, d_startup):
        real = fluid.layers.data(name='real', shape=[data_dim],
                                 dtype='float32')
        z = fluid.layers.data(name='z', shape=[noise_dim],
                              dtype='float32')
        fake = _mlp(z, [8, data_dim], 'gen')
        d_real = _mlp(real, [8, 1], 'disc', act_last='sigmoid')
        d_fake = _mlp(fake, [8, 1], 'disc', act_last='sigmoid')
        eps = 1e-6
        d_loss = fluid.layers.mean(
            fluid.layers.elementwise_add(
                x=fluid.layers.scale(
                    fluid.layers.log(
                        fluid.layers.scale(d_real, scale=1.0, bias=eps)),
                    scale=-1.0),
                y=fluid.layers.scale(
                    fluid.layers.log(
                        fluid.layers.scale(
                            fluid.layers.scale(d_fake, scale=-1.0,
                                               bias=1.0 + eps))),
                    scale=-1.0)))
        d_params = [p.name for p in d_prog.global_block().all_parameters()
                    if p.name.startswith('disc')]
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            d_loss, parameter_list=d_params)

    # --- generator program: maximize log D(G(z))
    g_prog, g_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(g_prog, g_startup):
        z2 = fluid.layers.data(name='z', shape=[noise_dim],
                               dtype='float32')
        fake2 = _mlp(z2, [8, data_dim], 'gen')
        d_fake2 = _mlp(fake2, [8, 1], 'disc', act_last='sigmoid')
        g_loss = fluid.layers.mean(
            fluid.layers.scale(
                fluid.layers.log(
                    fluid.layers.scale(d_fake2, scale=1.0, bias=1e-6)),
                scale=-1.0))
        g_params = [p.name for p in g_prog.global_block().all_parameters()
                    if p.name.startswith('gen')]
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            g_loss, parameter_list=g_params)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(d_startup)
    exe.run(g_startup)  # disc params already in scope; gen's get added

    target_mean = np.array([1.5, -0.5], dtype='float32')
    d_hist, g_hist = [], []
    for step in range(60):
        real_batch = (rng.randn(32, data_dim) * 0.2 +
                      target_mean).astype('float32')
        zb = rng.randn(32, noise_dim).astype('float32')
        d_val, = exe.run(program=d_prog,
                         feed={'real': real_batch, 'z': zb},
                         fetch_list=[d_loss])
        zb = rng.randn(32, noise_dim).astype('float32')
        g_val, = exe.run(program=g_prog, feed={'z': zb},
                         fetch_list=[g_loss])
        d_hist.append(float(np.asarray(d_val).reshape(())))
        g_hist.append(float(np.asarray(g_val).reshape(())))
    assert np.isfinite(d_hist).all() and np.isfinite(g_hist).all()
    # adversarial progress: generator loss fell from its start
    assert np.mean(g_hist[-10:]) < np.mean(g_hist[:10])
    # the generated distribution moved toward the data mean
    fake_out, = exe.run(program=g_prog,
                        feed={'z': rng.randn(256, noise_dim)
                              .astype('float32')},
                        fetch_list=[fake2])
    got_mean = np.asarray(fake_out).mean(axis=0)
    assert np.linalg.norm(got_mean - target_mean) < \
        np.linalg.norm(target_mean), got_mean
