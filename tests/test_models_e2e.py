"""End-to-end: loss decreases on synthetic data for every §2.6 model
(reference: the book chapters' train loops + benchmark configs, shrunk to
seconds on CPU)."""

import numpy as np

import paddle_tpu as fluid


def _train(loss, feeder, steps=12, opt=None):
    (opt or fluid.optimizer.Adam(learning_rate=1e-3)).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for i in range(steps):
        out = exe.run(feed=feeder(i), fetch_list=[loss])
        losses.append(float(np.asarray(out[0]).reshape(())))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses
    return losses


def test_linear_fit_a_line():
    from paddle_tpu.models.linear import fit_a_line
    _pred, loss = fit_a_line(feature_dim=13)
    rng = np.random.RandomState(7)
    w = rng.randn(13, 1).astype('float32')
    xs = rng.randn(32, 13).astype('float32')
    ys = xs @ w
    _train(loss, lambda i: {'x': xs, 'y': ys},
           opt=fluid.optimizer.SGD(learning_rate=0.05), steps=20)


def test_lenet_mnist():
    from paddle_tpu.models.lenet import convolutional_neural_network
    _predict, loss, _acc = convolutional_neural_network()
    rng = np.random.RandomState(0)
    xs = rng.rand(16, 1, 28, 28).astype('float32')
    ys = rng.randint(0, 10, (16, 1)).astype('int64')
    _train(loss, lambda i: {'img': xs, 'label': ys}, steps=8)


def test_mlp_mnist():
    from paddle_tpu.models.lenet import multilayer_perceptron
    _predict, loss, _acc = multilayer_perceptron()
    rng = np.random.RandomState(8)
    xs = rng.rand(16, 1, 28, 28).astype('float32')
    ys = rng.randint(0, 10, (16, 1)).astype('int64')
    _train(loss, lambda i: {'img': xs, 'label': ys})


def test_word2vec_imikolov():
    from paddle_tpu.models.word2vec import train_program
    loss, feeds = train_program(dict_size=100)
    rng = np.random.RandomState(1)
    feed = {n: rng.randint(0, 100, (32, 1)).astype('int64') for n in feeds}
    _train(loss, lambda i: feed)


def test_resnet_cifar_tiny():
    from paddle_tpu.models.resnet import resnet_cifar10
    img = fluid.layers.data(name='img', shape=[3, 8, 8], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = resnet_cifar10(img, depth=8, class_dim=10)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=predict, label=label))
    rng = np.random.RandomState(2)
    xs = rng.rand(8, 3, 8, 8).astype('float32')
    ys = rng.randint(0, 10, (8, 1)).astype('int64')
    _train(loss, lambda i: {'img': xs, 'label': ys})


def test_wide_deep_ctr():
    from paddle_tpu.models.wide_deep import build
    _predict, loss, _acc, feeds = build(num_slots=4, vocab_size=100,
                                        dense_dim=8, embed_size=8)
    rng = np.random.RandomState(3)
    feed = {}
    for n in feeds:
        if n == 'dense':
            feed[n] = rng.rand(16, 8).astype('float32')
        elif n == 'label':
            feed[n] = rng.randint(0, 2, (16, 1)).astype('int64')
        else:
            feed[n] = rng.randint(0, 100, (16, 1)).astype('int64')
    _train(loss, lambda i: feed)


def test_transformer_tiny():
    from paddle_tpu.models import transformer as T
    avg_cost, _ = T.transformer_base(
        src_vocab_size=64, trg_vocab_size=64, src_seq_len=8, trg_seq_len=8,
        n_layer=1, d_model=32, d_inner=64, d_key=8, d_value=8,
        dropout_rate=0.0)
    feed = T.make_fake_batch(4, 8, 8, 64, 64)
    _train(avg_cost, lambda i: feed)


def test_vgg_tiny():
    from paddle_tpu.models.vgg import vgg_bn_drop
    img = fluid.layers.data(name='img', shape=[3, 32, 32], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = vgg_bn_drop(img, class_dim=10)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=predict, label=label))
    rng = np.random.RandomState(4)
    xs = rng.rand(4, 3, 32, 32).astype('float32')
    ys = rng.randint(0, 10, (4, 1)).astype('int64')
    _train(loss, lambda i: {'img': xs, 'label': ys}, steps=6)


def test_sentiment_conv_net():
    from paddle_tpu.models.seq_models import convolution_net
    data = fluid.layers.data(name='words', shape=[12], dtype='int64')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    length = fluid.layers.data(name='length', shape=[], dtype='int64')
    _pred, loss, _acc = convolution_net(data, label, input_dim=200,
                                        emb_dim=16, hid_dim=16,
                                        length=length)
    rng = np.random.RandomState(5)
    feed = {'words': rng.randint(1, 200, (8, 12)).astype('int64'),
            'length': np.full((8,), 12, dtype='int64'),
            'label': rng.randint(0, 2, (8, 1)).astype('int64')}
    _train(loss, lambda i: feed)


def test_stacked_lstm_sentiment():
    from paddle_tpu.models.seq_models import stacked_lstm_net
    data = fluid.layers.data(name='words', shape=[10], dtype='int64')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    length = fluid.layers.data(name='length', shape=[], dtype='int64')
    _pred, loss, _acc = stacked_lstm_net(data, label, input_dim=100,
                                         emb_dim=16, hid_dim=16,
                                         stacked_num=3, length=length)
    rng = np.random.RandomState(6)
    feed = {'words': rng.randint(1, 100, (4, 10)).astype('int64'),
            'length': np.full((4,), 10, dtype='int64'),
            'label': rng.randint(0, 2, (4, 1)).astype('int64')}
    _train(loss, lambda i: feed, steps=8)


def test_mobilenet_tiny():
    from paddle_tpu.models.mobilenet import mobile_net
    img = fluid.layers.data(name='img', shape=[3, 32, 32], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = mobile_net(img, class_dim=10, scale=0.25)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=predict, label=label))
    rng = np.random.RandomState(6)
    xs = rng.rand(4, 3, 32, 32).astype('float32')
    ys = rng.randint(0, 10, (4, 1)).astype('int64')
    _train(loss, lambda i: {'img': xs, 'label': ys}, steps=6)


def test_resnext_tiny():
    from paddle_tpu.models.resnext import se_resnext
    img = fluid.layers.data(name='img', shape=[3, 32, 32], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = se_resnext(img, class_dim=10, depth=50, cardinality=8)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=predict, label=label))
    rng = np.random.RandomState(9)
    xs = rng.rand(2, 3, 32, 32).astype('float32')
    ys = rng.randint(0, 10, (2, 1)).astype('int64')
    _train(loss, lambda i: {'img': xs, 'label': ys}, steps=4)


def test_recommender_movielens():
    """Dual-tower recommender (recommender_system chapter) on the
    movielens dataset schema: rating regression loss decreases."""
    from paddle_tpu.models.recommender import recommender
    from paddle_tpu.dataset import movielens
    _pred, loss = recommender()
    users, movies, scores = [], [], []
    for u, m, s in list(movielens.train()())[:64]:
        users.append(u), movies.append(m), scores.append(s)
    rng = np.random.RandomState(11)
    n = len(users)
    feed = {'uid': np.asarray(users, 'int64').reshape(-1, 1),
            'mov_id': np.asarray(movies, 'int64').reshape(-1, 1),
            'score': np.asarray(scores, 'float32').reshape(-1, 1),
            'gender': rng.randint(0, 2, (n, 1)).astype('int64'),
            'age': rng.randint(0, 7, (n, 1)).astype('int64'),
            'job': rng.randint(0, 21, (n, 1)).astype('int64'),
            'category': rng.randint(0, 19, (n, 1)).astype('int64')}
    _train(loss, lambda i: feed, steps=10,
           opt=fluid.optimizer.Adam(learning_rate=5e-3))


def test_srl_crf_tagger_trains_and_decodes():
    """BiGRU + linear-chain CRF SRL (label_semantic_roles chapter):
    the CRF loss decreases and Viterbi decode on the trained emissions
    recovers the dominant tag structure of a synthetic rule."""
    from paddle_tpu.models.srl import srl_decode, srl_tagger
    vocab, labels, t = 30, 5, 8
    word = fluid.layers.data(name='word', shape=[t], dtype='int64')
    mark = fluid.layers.data(name='mark', shape=[t], dtype='int64')
    target = fluid.layers.data(name='target', shape=[t], dtype='int64')
    length = fluid.layers.data(name='length', shape=[], dtype='int64')
    emission, _crf, loss = srl_tagger(word, mark, target, vocab, labels,
                                      length=length)
    decoded = srl_decode(emission, length=length)
    rng = np.random.RandomState(12)
    words = rng.randint(1, vocab, (16, t)).astype('int64')
    marks = (rng.rand(16, t) < 0.2).astype('int64')
    # synthetic rule: tag = (word + mark) % labels
    targets = ((words + marks) % labels).astype('int64')
    feed = {'word': words, 'mark': marks, 'target': targets,
            'length': np.full((16,), t, 'int64')}
    losses = _train(loss, lambda i: feed, steps=25,
                    opt=fluid.optimizer.Adam(learning_rate=5e-2))
    exe = fluid.Executor(fluid.CPUPlace())
    paths = exe.run(feed=feed, fetch_list=[decoded])[0]
    acc = (paths == targets).mean()
    assert acc > 0.5, (acc, losses[-1])


def test_alexnet_tiny():
    from paddle_tpu.models.alexnet import alexnet
    img = fluid.layers.data(name='img', shape=[3, 67, 67], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = alexnet(img, class_dim=10)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=predict, label=label))
    rng = np.random.RandomState(11)
    xs = rng.rand(4, 3, 67, 67).astype('float32')
    ys = rng.randint(0, 10, (4, 1)).astype('int64')
    _train(loss, lambda i: {'img': xs, 'label': ys}, steps=6)


def test_googlenet_tiny():
    from paddle_tpu.models.googlenet import googlenet
    img = fluid.layers.data(name='img', shape=[3, 64, 64], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = googlenet(img, class_dim=10)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=predict, label=label))
    rng = np.random.RandomState(12)
    xs = rng.rand(4, 3, 64, 64).astype('float32')
    ys = rng.randint(0, 10, (4, 1)).astype('int64')
    _train(loss, lambda i: {'img': xs, 'label': ys}, steps=6)


def test_rnn_search_attention_seq2seq():
    """machine_translation chapter: bi-GRU encoder + additive-attention
    DynamicRNN decoder trains on a synthetic copy task; the whole
    seq2seq (attention inside the decoder scan) is one XLA program."""
    from paddle_tpu.models.rnn_search import make_fake_batch, rnn_search
    loss, _feeds = rnn_search(src_vocab=50, trg_vocab=50, emb_dim=16,
                              hidden_dim=16)
    feed = make_fake_batch(8, 6, 5, 50, 50)
    losses = _train(loss, lambda i: feed, steps=40,
                    opt=fluid.optimizer.Adam(learning_rate=5e-3))
    assert losses[-1] < losses[0] * 0.6, losses


def test_rnn_search_decodes_reproduce_training():
    """rnn_search greedy AND beam decode ops (one lax.scan each,
    training params shared by name) reproduce the trained copy task;
    the top beam equals greedy on the peaked model and beam scores
    come back sorted best-first."""
    from paddle_tpu.core.program import Program, program_guard
    from paddle_tpu.models.rnn_search import (make_fake_batch, rnn_search,
                                              rnn_search_beam_infer,
                                              rnn_search_greedy_infer)
    cost, _ = rnn_search(src_vocab=30, trg_vocab=30, emb_dim=16,
                         hidden_dim=16)
    fluid.optimizer.Adam(learning_rate=8e-3).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = make_fake_batch(8, 5, 5, 30, 30)
    for _ in range(200):
        exe.run(feed=feed, fetch_list=[cost])
    gp, bp = Program(), Program()
    with program_guard(gp, fluid.default_startup_program()):
        gids, _feeds = rnn_search_greedy_infer(
            src_vocab=30, trg_vocab=30, emb_dim=16, hidden_dim=16,
            max_out_len=5)
    with program_guard(bp, fluid.default_startup_program()):
        bids, bscores, _feeds = rnn_search_beam_infer(
            src_vocab=30, trg_vocab=30, emb_dim=16, hidden_dim=16,
            max_out_len=5, beam_size=4)
    f = {'src_word': feed['src_word'], 'src_len': feed['src_len']}
    g = np.asarray(exe.run(program=gp, feed=f, fetch_list=[gids])[0])
    bi, bs = (np.asarray(v) for v in
              exe.run(program=bp, feed=f, fetch_list=[bids, bscores]))
    assert (g == feed['lbl_word']).mean() > 0.8
    assert (bi[:, 0, :] == g).mean() > 0.9
    assert np.all(np.diff(bs, axis=1) <= 1e-5)  # sorted best-first


def test_wide_deep_ctr_lazy_adam():
    """The flagship CTR config under AdamOptimizer(lazy_mode=True) (r5):
    the is_sparse tables take the lazy row path — loss decreases and the
    compiled step never materializes a vocab-sized Adam update (the
    structural proof lives in tests/test_sparse_grad.py; this is the
    whole-model integration)."""
    from paddle_tpu.models.wide_deep import build
    _predict, loss, _acc, feeds = build(num_slots=4, vocab_size=100,
                                        dense_dim=8, embed_size=8)
    rng = np.random.RandomState(4)
    feed = {}
    for n in feeds:
        if n == 'dense':
            feed[n] = rng.rand(16, 8).astype('float32')
        elif n == 'label':
            feed[n] = rng.randint(0, 2, (16, 1)).astype('int64')
        else:
            feed[n] = rng.randint(0, 100, (16, 1)).astype('int64')
    _train(loss, lambda i: feed,
           opt=fluid.optimizer.Adam(learning_rate=1e-3, lazy_mode=True))
