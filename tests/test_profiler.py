

def test_memory_report_counts_step_memory():
    """profiler.memory_report: XLA memory analysis of the compiled step
    — argument bytes cover params + feed, temp covers activations."""
    import paddle_tpu as fluid
    from paddle_tpu import profiler
    import numpy as np

    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[64], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=256, act='relu')
    pred = fluid.layers.fc(input=h, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rep = profiler.memory_report(
        exe, feed={'x': np.zeros((8, 64), 'float32'),
                   'y': np.zeros((8, 1), 'float32')},
        fetch_list=[loss])
    assert rep, 'memory analysis unavailable'
    # params alone: fc weights 64*256 + 256*1 plus Adam moments (x3
    # with master copies) -> argument bytes must exceed that floor
    floor = (64 * 256 + 256) * 4 * 3
    assert rep['argument_bytes'] > floor, rep
    assert rep['peak_estimate_bytes'] >= rep['temp_bytes']
