"""Host staging ring (native/staging.cpp) + staged_superbatch feeder."""

import ctypes
import threading

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.native import load_staging
from paddle_tpu.reader.staging import staged_superbatch


def test_ring_roundtrip_ordering():
    lib = load_staging()
    ring = lib.staging_open(1 << 12, 3)
    assert ring
    payloads = [bytes([i] * 100 + [255 - i]) for i in range(7)]

    def produce():
        for p in payloads:
            buf = lib.staging_acquire_fill(ring)
            assert buf
            ctypes.memmove(buf, p, len(p))
            assert lib.staging_commit(ring, len(p)) == 0
        lib.staging_close_ring(ring)

    t = threading.Thread(target=produce)
    t.start()
    got = []
    while True:
        n = ctypes.c_uint64()
        buf = lib.staging_acquire_read(ring, ctypes.byref(n))
        if not buf:
            break
        got.append(ctypes.string_at(buf, n.value))
        assert lib.staging_release(ring) == 0
    t.join()
    lib.staging_free(ring)
    assert got == payloads  # FIFO, bytes intact, no tearing


def test_ring_misuse_returns_error():
    lib = load_staging()
    assert not lib.staging_open(0, 3)       # zero capacity
    assert not lib.staging_open(1024, 1)    # fewer than 2 buffers
    ring = lib.staging_open(1024, 2)
    assert lib.staging_commit(ring, 10) == -1   # commit without fill
    assert lib.staging_release(ring) == -1      # release without read
    buf = lib.staging_acquire_fill(ring)
    assert lib.staging_commit(ring, 4096) == -1  # over capacity
    lib.staging_close_ring(ring)
    lib.staging_free(ring)


def _batches(n, batch=4, seed=0):
    rng = np.random.RandomState(seed)
    return [{'x': rng.randn(batch, 8).astype('f'),
             'y': rng.randn(batch, 1).astype('f')} for _ in range(n)]


def test_staged_superbatch_windows_match_stack():
    data = _batches(7)          # 7 batches, steps=3 -> 2 windows, 1 dropped

    def reader():
        return iter(data)

    windows = list(staged_superbatch(reader, steps=3)())
    assert len(windows) == 2
    for w, start in zip(windows, (0, 3)):
        for nme in ('x', 'y'):
            want = np.stack([data[start + i][nme] for i in range(3)])
            np.testing.assert_array_equal(np.asarray(w[nme]), want)


def test_staged_superbatch_feeds_run_steps():
    """Windows drive Executor.run_steps(stacked_feed=True) to the same
    trajectory as feeding the batches one Executor.run at a time."""
    data = _batches(6, seed=3)

    def build():
        fluid.reset_default_programs()
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return cost, exe

    with fluid.scope_guard(fluid.Scope()):
        cost, exe = build()
        single = [float(np.asarray(exe.run(
            feed=b, fetch_list=[cost])[0]).reshape(())) for b in data]
    with fluid.scope_guard(fluid.Scope()):
        cost, exe = build()
        staged = []
        for window in staged_superbatch(lambda: iter(data), steps=3)():
            staged.extend(np.asarray(exe.run_steps(
                3, feed=window, fetch_list=[cost],
                stacked_feed=True)[0]).reshape(-1).tolist())
    np.testing.assert_allclose(staged, single, rtol=1e-5, atol=1e-6)


def test_staged_superbatch_mismatched_shape_raises():
    data = _batches(3)
    data[2]['x'] = np.zeros((5, 8), 'f')    # batch-size drift mid-stream

    def reader():
        return iter(data)

    import pytest
    with pytest.raises(ValueError, match='shape'):
        list(staged_superbatch(reader, steps=3)())
