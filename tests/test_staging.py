"""Host staging ring (native/staging.cpp) + staged_superbatch feeder."""

import ctypes
import threading

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.native import load_staging
from paddle_tpu.reader.staging import staged_superbatch


def test_ring_roundtrip_ordering():
    lib = load_staging()
    ring = lib.staging_open(1 << 12, 3)
    assert ring
    payloads = [bytes([i] * 100 + [255 - i]) for i in range(7)]

    def produce():
        for p in payloads:
            buf = lib.staging_acquire_fill(ring)
            assert buf
            ctypes.memmove(buf, p, len(p))
            assert lib.staging_commit(ring, len(p)) == 0
        lib.staging_close_ring(ring)

    t = threading.Thread(target=produce)
    t.start()
    got = []
    while True:
        n = ctypes.c_uint64()
        buf = lib.staging_acquire_read(ring, ctypes.byref(n))
        if not buf:
            break
        got.append(ctypes.string_at(buf, n.value))
        assert lib.staging_release(ring) == 0
    t.join()
    lib.staging_free(ring)
    assert got == payloads  # FIFO, bytes intact, no tearing


def test_ring_misuse_returns_error():
    lib = load_staging()
    assert not lib.staging_open(0, 3)       # zero capacity
    assert not lib.staging_open(1024, 1)    # fewer than 2 buffers
    ring = lib.staging_open(1024, 2)
    assert lib.staging_commit(ring, 10) == -1   # commit without fill
    assert lib.staging_release(ring) == -1      # release without read
    buf = lib.staging_acquire_fill(ring)
    assert lib.staging_commit(ring, 4096) == -1  # over capacity
    lib.staging_close_ring(ring)
    lib.staging_free(ring)


def _batches(n, batch=4, seed=0):
    rng = np.random.RandomState(seed)
    return [{'x': rng.randn(batch, 8).astype('f'),
             'y': rng.randn(batch, 1).astype('f')} for _ in range(n)]


def test_staged_superbatch_windows_match_stack():
    data = _batches(7)          # 7 batches, steps=3 -> 2 windows, 1 dropped

    def reader():
        return iter(data)

    windows = list(staged_superbatch(reader, steps=3)())
    assert len(windows) == 2
    for w, start in zip(windows, (0, 3)):
        for nme in ('x', 'y'):
            want = np.stack([data[start + i][nme] for i in range(3)])
            np.testing.assert_array_equal(np.asarray(w[nme]), want)


def test_staged_superbatch_feeds_run_steps():
    """Windows drive Executor.run_steps(stacked_feed=True) to the same
    trajectory as feeding the batches one Executor.run at a time."""
    data = _batches(6, seed=3)

    def build():
        fluid.reset_default_programs()
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return cost, exe

    with fluid.scope_guard(fluid.Scope()):
        cost, exe = build()
        single = [float(np.asarray(exe.run(
            feed=b, fetch_list=[cost])[0]).reshape(())) for b in data]
    with fluid.scope_guard(fluid.Scope()):
        cost, exe = build()
        staged = []
        for window in staged_superbatch(lambda: iter(data), steps=3)():
            staged.extend(np.asarray(exe.run_steps(
                3, feed=window, fetch_list=[cost],
                stacked_feed=True)[0]).reshape(-1).tolist())
    np.testing.assert_allclose(staged, single, rtol=1e-5, atol=1e-6)


def test_staged_superbatch_steps_one():
    """Regression (r3 advisor): steps=1 used to pack 2 batches into a
    1-step region (first batch seeded outside the flush check), writing
    past the per-field region and silently dropping every other batch."""
    data = _batches(4, seed=5)
    windows = list(staged_superbatch(lambda: iter(data), steps=1)())
    assert len(windows) == 4
    for w, b in zip(windows, data):
        for nme in ('x', 'y'):
            assert np.asarray(w[nme]).shape == (1,) + b[nme].shape
            np.testing.assert_array_equal(np.asarray(w[nme])[0], b[nme])


def test_staged_superbatch_mismatched_shape_raises():
    data = _batches(3)
    data[2]['x'] = np.zeros((5, 8), 'f')    # batch-size drift mid-stream

    def reader():
        return iter(data)

    import pytest
    with pytest.raises(ValueError, match='shape'):
        list(staged_superbatch(reader, steps=3)())


def _specs():
    import collections
    return collections.OrderedDict([('x', ((4,), 'float32')),
                                    ('y', ((1,), 'float32'))])


def test_recordio_superbatch_roundtrip(tmp_path):
    """C++ pipeline windows reproduce the written example stream in
    order (shuffle off), shaped [steps, batch, ...] per field."""
    from paddle_tpu.reader.recordio import (recordio_superbatch,
                                            write_example_recordio)
    rng = np.random.RandomState(0)
    examples = [{'x': rng.randn(4).astype('f'),
                 'y': rng.randn(1).astype('f')} for _ in range(14)]
    path = str(tmp_path / 'ex.recordio')
    assert write_example_recordio(path, examples, _specs()) == 14
    # steps=2, batch=3 -> windows of 6 records: 2 windows, 2 dropped
    windows = list(recordio_superbatch(path, _specs(), steps=2,
                                       batch=3)())
    assert len(windows) == 2
    i = 0
    for w in windows:
        assert np.asarray(w['x']).shape == (2, 3, 4)
        for s in range(2):
            for b in range(3):
                np.testing.assert_array_equal(
                    np.asarray(w['x'])[s, b], examples[i]['x'])
                np.testing.assert_array_equal(
                    np.asarray(w['y'])[s, b], examples[i]['y'])
                i += 1
    assert i == 12


def test_recordio_superbatch_trains(tmp_path):
    """End-to-end: C++ pipeline windows feed run_steps training."""
    from paddle_tpu.reader.recordio import (recordio_superbatch,
                                            write_example_recordio)
    rng = np.random.RandomState(1)
    w = rng.randn(4, 1).astype('f')
    examples = []
    for _ in range(240):
        x = rng.randn(4).astype('f')
        examples.append({'x': x, 'y': x @ w})
    path = str(tmp_path / 'train.recordio')
    write_example_recordio(path, examples, _specs())

    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = []
        for window in recordio_superbatch(path, _specs(), steps=4,
                                          batch=12, shuffle_buf=32,
                                          seed=7)():
            out = exe.run_steps(4, feed=window, fetch_list=[cost],
                                stacked_feed=True)
            losses.extend(np.asarray(out[0]).reshape(-1).tolist())
    assert len(losses) == 240 // (4 * 12) * 4
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_recordio_superbatch_schema_mismatch(tmp_path):
    """Wrong record size (schema drift) surfaces as an IOError naming
    the pipeline, not a silent mis-parse."""
    from paddle_tpu.reader.recordio import (recordio_superbatch,
                                            write_recordio)
    import pytest
    path = str(tmp_path / 'bad.recordio')
    write_recordio(path, [b'x' * 7, b'y' * 7])   # 7-byte pickled blobs
    with pytest.raises(IOError, match='pipeline'):
        list(recordio_superbatch(path, _specs(), steps=1, batch=2)())
