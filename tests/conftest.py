"""Test config: force an 8-virtual-device CPU platform BEFORE jax imports
(SURVEY.md §4), so mesh/sharding tests run without TPU hardware."""

from paddle_tpu.core.platform_boot import force_host_cpu

force_host_cpu(8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_programs():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    yield


# ---- fast/slow partition (VERDICT r4 next-#8: the full suite is ~20
# min; `-m fast` is the <5-min gate for iterating). Slow = whole-model
# e2e, mesh/multihost, amp sweeps, compiled-C clients; everything else
# is fast by default so NEW test files land in the fast gate unless
# explicitly listed here.
import os as _os

_SLOW_FILES = {
    'test_models_e2e.py', 'test_parallel.py', 'test_multihost.py',
    'test_amp.py', 'test_layers.py', 'test_capi.py', 'test_staging.py',
    'test_examples.py', 'test_moe.py', 'test_gan_two_programs.py',
    'test_transformer_infer.py', 'test_transformer_scan.py',
    'test_v1compat_sweep.py', 'test_trainer_and_losses.py',
}


def pytest_configure(config):
    config.addinivalue_line('markers', 'fast: quick-gate subset (<5 min)')
    config.addinivalue_line('markers', 'slow: whole-model/mesh suites')


def pytest_collection_modifyitems(config, items):
    for item in items:
        fname = _os.path.basename(str(item.fspath))
        marker = pytest.mark.slow if fname in _SLOW_FILES else \
            pytest.mark.fast
        item.add_marker(marker)
