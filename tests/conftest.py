"""Test config: force an 8-virtual-device CPU platform BEFORE jax imports
(SURVEY.md §4), so mesh/sharding tests run without TPU hardware."""

import os

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

# The hosted-TPU sitecustomize calls jax.config.update('jax_platforms',
# 'axon,cpu') at interpreter boot, which overrides the env var — force it
# back so tests really run on the 8-virtual-device CPU platform.
jax.config.update('jax_platforms', 'cpu')

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_programs():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    yield
