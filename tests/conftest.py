"""Test config: force an 8-virtual-device CPU platform BEFORE jax imports
(SURVEY.md §4), so mesh/sharding tests run without TPU hardware."""

from paddle_tpu.core.platform_boot import force_host_cpu

force_host_cpu(8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_programs():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    yield
