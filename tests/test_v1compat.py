"""v1 trainer_config_helpers compat shim: legacy configs build and train
over the fluid IR (reference: python/paddle/trainer_config_helpers/
layers.py, networks.py — the quick_start / fit-a-line era API)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.trainer_config_helpers import (
    AdamOptimizer, AvgPooling, L2Regularization, LinearActivation,
    MaxPooling, MomentumOptimizer, ParameterAttribute, ReluActivation,
    SoftmaxActivation, TanhActivation, addto_layer, bidirectional_lstm,
    classification_cost, concat_layer, context_projection, cos_sim,
    data_layer, dotmul_projection, embedding_layer, fc_layer,
    first_seq, full_matrix_projection, grumemory, identity_projection,
    img_conv_layer, img_pool_layer, interpolation_layer, last_seq,
    lstmemory, maxid_layer, mixed_layer, pooling_layer, recurrent_layer,
    regression_cost, repeat_layer, settings, simple_gru,
    simple_img_conv_pool, simple_lstm, slope_intercept_layer,
    trans_layer)


def _run(fetches, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, exe.run(feed=feed, fetch_list=fetches)


def test_fit_a_line_v1_style():
    x = data_layer(name='x', size=13)
    y = data_layer(name='y', size=1)
    pred = fc_layer(input=x, size=1, act=LinearActivation())
    cost = regression_cost(input=pred, label=y)
    settings(learning_rate=0.05,
             learning_method=MomentumOptimizer(momentum=0.9)).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    w = rng.randn(13, 1).astype('float32')
    losses = []
    for _ in range(60):
        xs = rng.randn(32, 13).astype('float32')
        loss, = exe.run(feed={'x': xs, 'y': xs @ w + 0.5},
                        fetch_list=[cost])
        losses.append(float(np.asarray(loss).reshape(())))
    assert losses[-1] < losses[0] * 0.1


def test_mixed_layer_full_projection_matches_matmul():
    x = data_layer(name='x', size=4)
    out = mixed_layer(
        size=3,
        input=[full_matrix_projection(
            x, param_attr=ParameterAttribute(
                initializer=fluid.initializer.Constant(0.5)))],
        bias_attr=False)
    xs = np.arange(8, dtype='float32').reshape(2, 4)
    _, (o,) = _run([out], {'x': xs})
    np.testing.assert_allclose(o, xs @ np.full((4, 3), 0.5, 'f'),
                               rtol=1e-5)


def test_mixed_layer_identity_plus_dotmul():
    x = data_layer(name='x', size=4)
    out = mixed_layer(size=4,
                      input=[identity_projection(x),
                             dotmul_projection(
                                 x, param_attr=ParameterAttribute(
                                     initializer=fluid.initializer
                                     .Constant(2.0)))],
                      bias_attr=False)
    xs = np.arange(4, dtype='float32').reshape(1, 4)
    _, (o,) = _run([out], {'x': xs})
    np.testing.assert_allclose(o, xs + 2.0 * xs, rtol=1e-5)


def test_sentiment_config_trains():
    """quick_start-style: embedding -> seq max-pool -> softmax fc."""
    words = data_layer(name='words', size=100, dtype='int64', seq_type=1)
    lbl = data_layer(name='lbl', size=1, dtype='int64')
    emb = embedding_layer(input=words, size=16)
    pooled = pooling_layer(input=emb, pooling_type=MaxPooling())
    prob = fc_layer(input=pooled, size=2, act=SoftmaxActivation())
    cost = classification_cost(input=prob, label=lbl)
    AdamOptimizer().to_fluid(0.01).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    ws = rng.randint(1, 100, (16, 12)).astype('int64')
    ys = (ws[:, 0] % 2).astype('int64').reshape(-1, 1)
    lens = np.full((16,), 12, 'int32')
    losses = []
    for _ in range(40):
        loss, = exe.run(feed={'words': ws, 'words_len': lens, 'lbl': ys},
                        fetch_list=[cost])
        losses.append(float(np.asarray(loss).reshape(())))
    assert losses[-1] < losses[0] * 0.7


def test_seq_pooling_masks_padding():
    words = data_layer(name='w', size=50, dtype='int64', seq_type=1)
    emb = embedding_layer(input=words, size=4)
    mx = pooling_layer(input=emb, pooling_type=MaxPooling())
    av = pooling_layer(input=emb, pooling_type=AvgPooling())
    lst = last_seq(input=emb)
    fst = first_seq(input=emb)
    ws = np.array([[3, 4, 0, 0], [5, 6, 7, 8]], dtype='int64')
    lens = np.array([2, 4], dtype='int32')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    table = fluid.global_scope().numpy(
        [p for p in fluid.default_main_program().all_parameters()][0].name)
    o_mx, o_av, o_l, o_f = (np.asarray(v) for v in exe.run(
        feed={'w': ws, 'w_len': lens},
        fetch_list=[mx, av, lst, fst]))
    np.testing.assert_allclose(o_mx[0], table[[3, 4]].max(0), rtol=1e-5)
    np.testing.assert_allclose(o_av[0], table[[3, 4]].mean(0), rtol=1e-5)
    np.testing.assert_allclose(o_l[0], table[4], rtol=1e-5)
    np.testing.assert_allclose(o_f[0], table[3], rtol=1e-5)


def test_lstm_gru_rnn_shapes_and_train():
    x = data_layer(name='x', size=8, seq_type=1)
    h_l = simple_lstm(input=x, size=6)
    h_g = simple_gru(input=x, size=5)
    h_r = recurrent_layer(input=fc_layer(x, 7, bias_attr=False),
                          act=TanhActivation())
    bi = bidirectional_lstm(input=x, size=4, return_seq=True)
    cost = regression_cost(
        input=fc_layer(concat_layer([last_seq(h_l), last_seq(h_g),
                                     last_seq(h_r), last_seq(bi)]),
                       size=1),
        label=data_layer(name='y', size=1))
    settings(learning_rate=0.01,
             learning_method=AdamOptimizer()).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(3, 5, 8).astype('float32'),
            'x_len': np.array([5, 3, 4], 'int32'),
            'y': rng.randn(3, 1).astype('float32')}
    vals = exe.run(feed=feed, fetch_list=[h_l, h_g, h_r, bi, cost])
    assert np.asarray(vals[0]).shape == (3, 5, 6)
    assert np.asarray(vals[1]).shape == (3, 5, 5)
    assert np.asarray(vals[2]).shape == (3, 5, 7)
    assert np.asarray(vals[3]).shape == (3, 5, 8)
    l0 = float(np.asarray(vals[4]).reshape(()))
    for _ in range(5):
        loss, = exe.run(feed=feed, fetch_list=[cost])
    assert float(np.asarray(loss).reshape(())) < l0


def test_recurrent_layer_matches_numpy():
    x = data_layer(name='x', size=3, seq_type=1)
    h = recurrent_layer(
        input=x, act=TanhActivation(),
        param_attr=ParameterAttribute(
            initializer=fluid.initializer.Constant(0.1)),
        bias_attr=False)
    xs = np.random.RandomState(0).randn(2, 4, 3).astype('float32')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    o, = exe.run(feed={'x': xs, 'x_len': np.array([4, 4], 'int32')},
                 fetch_list=[h])
    o = np.asarray(o)
    w = np.full((3, 3), 0.1, 'f')
    h_prev = np.zeros((2, 3), 'f')
    for t in range(4):
        h_prev = np.tanh(xs[:, t] + h_prev @ w)
        np.testing.assert_allclose(o[:, t], h_prev, rtol=1e-4, atol=1e-5)


def test_image_stack_runs():
    img = data_layer(name='img', size=1 * 16 * 16)
    lbl = data_layer(name='lbl', size=1, dtype='int64')
    cp = simple_img_conv_pool(input=img, filter_size=3, num_filters=4,
                              pool_size=2, num_channels=1,
                              act=ReluActivation(), conv_padding=1)
    conv2 = img_conv_layer(cp, filter_size=3, num_filters=6, padding=1,
                           act=ReluActivation())
    pool2 = img_pool_layer(conv2, pool_size=2, stride=2)
    prob = fc_layer(input=pool2, size=10, act=SoftmaxActivation())
    cost = classification_cost(input=prob, label=lbl)
    settings(learning_rate=0.01,
             learning_method=MomentumOptimizer(0.9),
             regularization=L2Regularization(1e-4)).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(8, 256).astype('float32'),
            'lbl': rng.randint(0, 10, (8, 1)).astype('int64')}
    l0 = exe.run(feed=feed, fetch_list=[cost])[0]
    for _ in range(3):
        l1 = exe.run(feed=feed, fetch_list=[cost])[0]
    assert np.isfinite(np.asarray(l1)).all()


def test_elementwise_helpers_match_numpy():
    a = data_layer(name='a', size=4)
    b = data_layer(name='b', size=4)
    wvar = data_layer(name='w', size=1)
    sums = addto_layer([a, b])
    cs = cos_sim(a, b, scale=1)
    interp = interpolation_layer([a, b], wvar)
    si = slope_intercept_layer(a, slope=2.0, intercept=1.0)
    tr = trans_layer(a)
    rep = repeat_layer(a, 2)
    mid = maxid_layer(a)
    av = np.array([[1., 2., 3., 4.], [0., 1., 0., 1.]], 'f')
    bv = np.array([[2., 2., 2., 2.], [1., 0., 1., 0.]], 'f')
    wv = np.array([[0.25], [0.75]], 'f')
    _, outs = _run([sums, cs, interp, si, tr, rep, mid],
                   {'a': av, 'b': bv, 'w': wv})
    o_sum, o_cs, o_in, o_si, o_tr, o_rep, o_mid = \
        (np.asarray(v) for v in outs)
    np.testing.assert_allclose(o_sum, av + bv, rtol=1e-5)
    ref_cs = (av * bv).sum(1) / (np.linalg.norm(av, axis=1)
                                 * np.linalg.norm(bv, axis=1))
    np.testing.assert_allclose(o_cs.reshape(-1), ref_cs, rtol=1e-5)
    np.testing.assert_allclose(o_in, wv * av + (1 - wv) * bv, rtol=1e-5)
    np.testing.assert_allclose(o_si, 2 * av + 1, rtol=1e-5)
    np.testing.assert_allclose(o_tr, av.T, rtol=1e-5)
    np.testing.assert_allclose(o_rep, np.concatenate([av, av], 1))
    np.testing.assert_allclose(o_mid.reshape(-1), av.argmax(1))


def test_context_projection_matches_numpy():
    x = data_layer(name='x', size=2, seq_type=1)
    out = mixed_layer(input=[context_projection(x, context_len=3)],
                      bias_attr=False)
    xs = np.arange(12, dtype='float32').reshape(1, 6, 2)
    _, (o,) = _run([out], {'x': xs,
                           'x_len': np.array([6], 'int32')})
    o = np.asarray(o)
    assert o.shape == (1, 6, 6)
    # middle offset (i=1) is the identity copy
    np.testing.assert_allclose(o[0, :, 2:4], xs[0], rtol=1e-5)
    # left context at t=0 is zero padding
    np.testing.assert_allclose(o[0, 0, 0:2], np.zeros(2), atol=1e-6)
    np.testing.assert_allclose(o[0, 1:, 0:2], xs[0, :-1], rtol=1e-5)
    # right context at the end is zero padding
    np.testing.assert_allclose(o[0, -1, 4:6], np.zeros(2), atol=1e-6)
    np.testing.assert_allclose(o[0, :-1, 4:6], xs[0, 1:], rtol=1e-5)


def test_unshimmed_name_names_fluid_equivalent():
    import paddle_tpu.trainer_config_helpers.layers as v1l
    # selective_fc_layer graduated to a real implementation in round 5;
    # sub_nested_seq_layer is still unshimmed (LoD depth>1 descoped)
    assert callable(v1l.selective_fc_layer)
    with pytest.raises(NotImplementedError, match='LoD'):
        v1l.sub_nested_seq_layer
    with pytest.raises(AttributeError):
        v1l.definitely_not_a_layer
    # recurrent_group graduated from this list in round 5 (recurrent.py)
    from paddle_tpu.trainer_config_helpers import recurrent_group
    assert callable(recurrent_group)


def test_simple_attention_shapes_and_sharing():
    """The shim delegates to models/rnn_search.additive_attention;
    param_attr NAMES must survive the delegation (weight sharing)."""
    from paddle_tpu.trainer_config_helpers.networks import simple_attention
    enc = data_layer(name='enc', size=8, seq_type=1)
    dec_state = data_layer(name='st', size=6)
    proj = fc_layer(input=enc, size=6, bias_attr=False)
    ctx1 = simple_attention(
        enc, proj, dec_state,
        transform_param_attr=ParameterAttribute(name='attn_transform.w'),
        softmax_param_attr=ParameterAttribute(name='attn_score.w'))
    ctx2 = simple_attention(
        enc, proj, dec_state,
        transform_param_attr=ParameterAttribute(name='attn_transform.w'),
        softmax_param_attr=ParameterAttribute(name='attn_score.w'))
    names = [p.name for p in
             fluid.default_main_program().all_parameters()]
    assert names.count('attn_transform.w') == 1  # shared, not duplicated
    assert names.count('attn_score.w') == 1
    xs = np.random.RandomState(0).randn(3, 5, 8).astype('float32')
    st = np.random.RandomState(1).randn(3, 6).astype('float32')
    _, (o1, o2) = _run([ctx1, ctx2],
                       {'enc': xs, 'enc_len': np.array([5, 3, 4], 'int32'),
                        'st': st})
    assert np.asarray(o1).shape == (3, 8)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-5)


def test_v1_layers_under_v2_trainer():
    """The reference's own composition: v2's trainer drives a cost
    built from trainer_config_helpers layers (v2.layer was a re-export
    shell over them). Here both surfaces share the fluid IR, so the v1
    config trains through paddle.trainer.SGD unchanged."""
    import paddle_tpu.v2 as paddle
    x = data_layer(name='x', size=13)
    y = data_layer(name='y', size=1)
    pred = fc_layer(input=x, size=1, act=LinearActivation())
    cost = regression_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    w_true = np.random.RandomState(0).randn(13, 1).astype('f')

    def reader():
        rng = np.random.RandomState(1)
        for _ in range(40):
            xs = rng.randn(13).astype('f')
            yield xs, (xs @ w_true + 0.5).astype('f')

    events = []
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.01),
        place=fluid.CPUPlace())
    trainer.train(reader=paddle.batch(reader, 20), num_passes=30,
                  event_handler=events.append, feeding={'x': 0, 'y': 1})
    ends = [e for e in events if isinstance(e, paddle.event.EndIteration)]
    assert ends[-1].cost < ends[0].cost * 0.1


def test_fc_layer_multi_input_sequences():
    """ADVICE r4 #1: fc_layer over a LIST of sequence inputs must stay a
    sequence op — num_flatten_dims from the original inputs (the concat
    Variable has no len var), and the output keeps the length var so a
    downstream last_seq still masks correctly."""
    from paddle_tpu.trainer_config_helpers.layers import _len_of
    a = data_layer(name='seq_a', size=6, seq_type=1)
    b = data_layer(name='seq_b', size=4, seq_type=1)
    emb_a = fc_layer(input=a, size=6, act=TanhActivation())
    emb_b = fc_layer(input=b, size=4, act=TanhActivation())
    out = fc_layer(input=[emb_a, emb_b], size=5)  # crashed pre-fix
    assert _len_of(out) is not None
    pooled = last_seq(input=out)
    xs_a = np.random.RandomState(0).randn(3, 7, 6).astype('float32')
    xs_b = np.random.RandomState(1).randn(3, 7, 4).astype('float32')
    lens = np.array([7, 4, 6], 'int32')
    _, (o_seq, o_last) = _run(
        [out, pooled],
        {'seq_a': xs_a, 'seq_a_len': lens,
         'seq_b': xs_b, 'seq_b_len': lens})
    assert np.asarray(o_seq).shape == (3, 7, 5)
    # last_seq honors the per-row length, proving the len var survived
    np.testing.assert_allclose(np.asarray(o_last)[1],
                               np.asarray(o_seq)[1, 3], rtol=1e-5)


def test_gru_unit_consumes_preprojected_input():
    """ADVICE r4 #2: reference networks.py gru_unit/gru_group consume an
    already-projected 3*size input (size defaults to width//3) — they
    must NOT add another fc projection like simple_gru does."""
    from paddle_tpu.trainer_config_helpers import gru_group, gru_unit
    x = data_layer(name='xg', size=12, seq_type=1)
    out = gru_unit(input=x)  # size inferred = 4; crashed pre-fix (None*3)
    g = fluid.default_main_program().global_block()
    # exactly one GRU recurrence and NO fc/mul projection op before it
    ops = [op.type for op in g.ops]
    assert 'gru' in ops
    assert not any(t in ('fc', 'mul', 'matmul') for t in ops)
    xs = np.random.RandomState(0).randn(2, 5, 12).astype('float32')
    _, (o,) = _run([out], {'xg': xs, 'xg_len': np.array([5, 3], 'int32')})
    assert np.asarray(o).shape == (2, 5, 4)
    with pytest.raises(ValueError, match='3'):
        gru_group(input=data_layer(name='xg2', size=10, seq_type=1))


def test_factorization_machine_matches_pair_loop():
    """r5 shim: the sum-square identity must equal the O(n^2) pairwise
    definition y = sum_{i<j} <v_i,v_j> x_i x_j."""
    from paddle_tpu.trainer_config_helpers import factorization_machine
    x = data_layer(name='fmx', size=5)
    out = factorization_machine(
        x, factor_size=3,
        param_attr=ParameterAttribute(name='fm.v'))
    xs = np.random.RandomState(0).randn(4, 5).astype('f')
    exe, (o,) = _run([out], {'fmx': xs})
    v = np.asarray(fluid.global_scope().find('fm.v'))
    want = np.zeros((4, 1), 'f')
    for i in range(5):
        for j in range(i + 1, 5):
            want[:, 0] += (v[i] @ v[j]) * xs[:, i] * xs[:, j]
    np.testing.assert_allclose(np.asarray(o), want, rtol=1e-4,
                               atol=1e-5)


def test_selective_fc_masks_columns():
    from paddle_tpu.trainer_config_helpers import selective_fc_layer
    x = data_layer(name='sfx', size=4)
    sel = data_layer(name='sel', size=6)
    out_all = selective_fc_layer(
        input=x, size=6, param_attr=ParameterAttribute(name='sf.w'),
        bias_attr=False)
    out_sel = selective_fc_layer(
        input=x, size=6, select=sel,
        param_attr=ParameterAttribute(name='sf.w'), bias_attr=False)
    xs = np.random.RandomState(1).randn(3, 4).astype('f')
    mask = (np.random.RandomState(2).rand(3, 6) > 0.5).astype('f')
    _, (a, b) = _run([out_all, out_sel],
                     {'sfx': xs, 'sel': mask})
    np.testing.assert_allclose(np.asarray(b), np.asarray(a) * mask,
                               rtol=1e-5, atol=1e-6)


def test_conv3d_layer_and_v1_shim():
    """r5: fluid conv3d wrapper over the existing lowering, and the v1
    img_conv3d_layer mapped onto it — compared against scipy's direct
    3-D correlation."""
    from scipy.ndimage import correlate
    import paddle_tpu.layers as L
    x = L.data(name='vol', shape=[1, 4, 5, 6], dtype='float32')
    out = L.conv3d(x, num_filters=1, filter_size=3, padding=1,
                   param_attr=fluid.ParamAttr(name='c3.w'),
                   bias_attr=False)
    xs = np.random.RandomState(0).randn(2, 1, 4, 5, 6).astype('f')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    o = np.asarray(exe.run(feed={'vol': xs}, fetch_list=[out])[0])
    w = np.asarray(fluid.global_scope().find('c3.w'))[0, 0]
    for b in range(2):
        want = correlate(xs[b, 0], w, mode='constant')
        np.testing.assert_allclose(o[b, 0], want, rtol=1e-4, atol=1e-4)


def test_img_conv3d_shim():
    from paddle_tpu.trainer_config_helpers import img_conv3d_layer
    import paddle_tpu.layers as L
    x = L.data(name='v3', shape=[2, 4, 4, 4], dtype='float32')
    out = img_conv3d_layer(input=x, filter_size=3, num_filters=3,
                           padding=1, act=ReluActivation())
    xs = np.random.RandomState(0).randn(2, 2, 4, 4, 4).astype('f')
    _, (o,) = _run([out], {'v3': xs})
    assert np.asarray(o).shape == (2, 3, 4, 4, 4)
    assert (np.asarray(o) >= 0).all()          # relu applied
