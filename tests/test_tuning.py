"""Kernel autotuner + AOT warm start (ISSUE 8).

Covers the tuning-table lifecycle (round-trip, corruption fallback,
deterministic winners under injected timings, env-gate precedence over
table entries), the per-call block-size satellite, the executor's AOT
serialized-executable cache (in-process warm start with zero
trace/compile events, tampered-cache fallback), the stdlib CLI, and
the subprocess cold-vs-warm e2e the acceptance criteria name.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observe, tuning


@pytest.fixture(autouse=True)
def _fresh_tuning(tmp_path, monkeypatch):
    """Every test gets its own table path, a clean tuner, and no
    autotune/gate env leakage."""
    for var in ('PADDLE_TPU_AUTOTUNE', 'PADDLE_TPU_USE_PALLAS',
                'PADDLE_TPU_PAGED_PALLAS', 'PADDLE_TPU_BN_PALLAS',
                'PADDLE_TPU_PALLAS_BLOCK_K', 'PADDLE_TPU_PALLAS_BLOCK_Q',
                'PADDLE_TPU_AOT_CACHE', 'PADDLE_TPU_AOT_CACHE_DIR'):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv('PADDLE_TPU_TUNING_TABLE',
                       str(tmp_path / 'tuning.json'))
    tuning.reset()
    tuning.set_timer(None)
    yield
    tuning.reset()
    tuning.set_timer(None)


def _fake_timer(winner_impl_by_key):
    """Timer giving 1ms to the keyed winner impl, 10ms to the rest."""
    calls = []

    def timer(op, key, variant, thunk):
        calls.append((op, key, variant.get('impl')))
        want = None
        for frag, impl in winner_impl_by_key.items():
            if frag in key:
                want = impl
        return 0.001 if variant.get('impl') == want else 0.010

    timer.calls = calls
    return timer


# ------------------------------------------------------- table lifecycle
def test_table_roundtrip(tmp_path):
    path = str(tmp_path / 't.json')
    t = tuning.TuningTable(path)
    t.put('cpu', 'flash_attention|x|f32',
          {'impl': 'pallas', 'block_k': 256},
          {'xla': 0.01, 'pallas bk256': 0.001})
    assert t.save() == path
    back = tuning.TuningTable.load(path)
    assert back.loaded_from_disk
    ent = back.lookup('cpu', 'flash_attention|x|f32')
    assert ent['winner'] == {'impl': 'pallas', 'block_k': 256}
    assert ent['timings']['xla'] == pytest.approx(0.01)
    assert back.size() == 1
    # merge-on-save composes with another writer's entries
    other = tuning.TuningTable(path)
    other.put('cpu', 'layer_norm|y|f32', {'impl': 'xla'}, {'xla': 0.002})
    other.save()
    merged = tuning.TuningTable.load(path)
    assert merged.size() == 2


def test_corrupted_table_ignored_with_flight_event(tmp_path):
    path = str(tmp_path / 'bad.json')
    with open(path, 'w') as f:
        f.write('{"this is": "not a tuning table"')
    observe.arm_flight()
    before = len(observe.flight_recorder().events())
    t = tuning.TuningTable.load(path)
    assert t.size() == 0 and not t.loaded_from_disk
    events = observe.flight_recorder().events()[before:]
    assert any(e['kind'] == 'tuning_table_ignored' for e in events)
    # version mismatch is equally ignored
    with open(path, 'w') as f:
        json.dump({'format_version': 999, 'tables': {}}, f)
    t2 = tuning.TuningTable.load(path)
    assert t2.size() == 0 and not t2.loaded_from_disk


def test_fake_timings_deterministic_winner(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'on')
    timer = _fake_timer({'tq1024': 'xla'})
    tuning.set_timer(timer)
    d1 = tuning.decide_attention(1, 8, 1024, 1024, 64, 'float32',
                                 True, False)
    assert d1 == {'impl': 'xla'}
    n = len(timer.calls)
    assert n > 1   # every candidate was timed exactly once
    # memo hit: no re-measurement in-process
    assert tuning.decide_attention(1, 8, 1024, 1024, 64, 'float32',
                                   True, False) == d1
    assert len(timer.calls) == n
    # table replay: a fresh process (reset()) trusts the persisted entry
    tuning.reset()
    tuning.set_timer(timer)
    assert tuning.decide_attention(1, 8, 1024, 1024, 64, 'float32',
                                   True, False) == d1
    assert len(timer.calls) == n


def test_record_mode_remeasures(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'on')
    timer = _fake_timer({'tq1024': 'xla'})
    tuning.set_timer(timer)
    tuning.decide_attention(1, 8, 1024, 1024, 64, 'float32', True, False)
    n = len(timer.calls)
    # record mode re-benchmarks even though the table has the entry
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'record')
    tuning.reset()
    timer2 = _fake_timer({'tq1024': 'pallas'})
    tuning.set_timer(timer2)
    d = tuning.decide_attention(1, 8, 1024, 1024, 64, 'float32',
                                True, False)
    assert d['impl'] == 'pallas' and len(timer2.calls) == n


def test_two_shapes_record_both_winners(monkeypatch):
    """Acceptance demo: in ONE process the kernel choice differs across
    two shapes and the table records both winners."""
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'on')
    tuning.set_timer(_fake_timer({'tq1024': 'xla', 'tq4096': 'pallas'}))
    d1k = tuning.decide_attention(4, 8, 1024, 1024, 64, 'bfloat16',
                                  True, False)
    d4k = tuning.decide_attention(1, 8, 4096, 4096, 64, 'bfloat16',
                                  True, False)
    assert d1k['impl'] == 'xla'
    assert d4k['impl'] == 'pallas' and d4k['block_q'] in (256, 512)
    table = tuning.current_table()
    assert table.size() == 2
    kinds = list(table.tables)
    winners = {k: e['winner']['impl']
               for k, e in table.tables[kinds[0]].items()}
    assert sorted(winners.values()) == ['pallas', 'xla']
    # and the persisted file agrees
    back = tuning.TuningTable.load(tuning.table_path())
    assert back.size() == 2


def test_env_gate_overrides_table(monkeypatch):
    """A table entry saying 'pallas' must lose to an explicit
    PADDLE_TPU_USE_PALLAS=0 (and vice versa, the gate alone dispatches
    pallas with autotune off)."""
    import jax.numpy as jnp
    from paddle_tpu.ops import attention_ops
    from paddle_tpu.ops.pallas import flash_attention as fa_mod

    called = {'n': 0}

    def marker(q, k, v, causal=False, sm_scale=None, block_q=None,
               kv_len=None, block_k=None):
        called['n'] += 1
        return attention_ops.reference_attention(q, k, v, causal=causal,
                                                 key_length=kv_len)

    monkeypatch.setattr(fa_mod, 'flash_attention', marker)
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'on')
    tuning.set_timer(_fake_timer({'tq512': 'pallas'}))
    q3 = jnp.ones((1, 512, 64), jnp.float32)

    # tuner says pallas -> flash dispatches
    out = attention_ops.fused_attention(q3, q3, q3, n_head=1, causal=True)
    assert called['n'] == 1 and out.shape == (1, 512, 64)

    # explicit env off -> table overridden, no flash dispatch
    monkeypatch.setenv('PADDLE_TPU_USE_PALLAS', '0')
    attention_ops.fused_attention(q3, q3, q3, n_head=1, causal=True)
    assert called['n'] == 1

    # explicit env on + autotune off -> flash dispatches (legacy gate)
    monkeypatch.setenv('PADDLE_TPU_USE_PALLAS', '1')
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'off')
    attention_ops.fused_attention(q3, q3, q3, n_head=1, causal=True)
    assert called['n'] == 2


# --------------------------------------------------- per-call block knobs
def test_block_k_env_read_per_call(monkeypatch):
    """The import-time DEFAULT_BLOCK_K bug: env changes after import
    must take effect (the autotuner varies blocks in-process)."""
    from paddle_tpu.ops.pallas.flash_attention import resolve_blocks
    assert resolve_blocks(1024, 1024) == (512, 128)
    monkeypatch.setenv('PADDLE_TPU_PALLAS_BLOCK_K', '256')
    assert resolve_blocks(1024, 1024)[1] == 256
    monkeypatch.setenv('PADDLE_TPU_PALLAS_BLOCK_K', '192')
    # non-pow2 override degrades to a dividing block, never asserts
    assert resolve_blocks(1024, 1024)[1] == 128
    # explicit args (the tuner's winner) beat the env
    assert resolve_blocks(1024, 1024, 256, 512) == (256, 512)


def test_attention_block_variants_divide():
    from paddle_tpu.ops.pallas.flash_attention import (
        attention_block_variants)
    for tq, tk in ((1024, 1024), (4096, 4096), (512, 768), (128, 128)):
        pairs = attention_block_variants(tq, tk)
        assert pairs
        for bq, bk in pairs:
            assert tq % bq == 0 and tk % bk == 0


# --------------------------------------------------------- AOT warm start
def _build_mlp():
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[16], dtype='float32')
    h = fluid.layers.fc(input=x, size=16, act='relu',
                        param_attr=fluid.ParamAttr(
                            initializer=fluid.initializer.Constant(0.1)))
    out = fluid.layers.fc(input=h, size=2,
                          param_attr=fluid.ParamAttr(
                              initializer=fluid.initializer.Constant(0.2)))
    return out


def test_executor_aot_warm_start_zero_trace_events(tmp_path, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_AOT_CACHE', '1')
    monkeypatch.setenv('PADDLE_TPU_AOT_CACHE_DIR', str(tmp_path / 'aot'))
    feed = {'x': np.ones((3, 16), 'float32')}

    out = _build_mlp()
    exe1 = fluid.Executor(fluid.CPUPlace())
    exe1.run(fluid.default_startup_program())
    r1 = exe1.run(feed=feed, fetch_list=[out])
    assert exe1.aot_stats['saves'] == 2           # startup + step
    assert not exe1.last_warm_from_disk

    observe.arm_flight()
    before = len(observe.flight_recorder().events())
    out2 = _build_mlp()                            # same content, new ids
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())
    r2 = exe2.run(feed=feed, fetch_list=[out2])
    assert exe2.aot_stats['hits'] == 2
    assert exe2.aot_stats['load_failures'] == 0
    assert exe2.last_warm_from_disk
    events = observe.flight_recorder().events()[before:]
    kinds = [e['kind'] for e in events]
    # THE warm-start contract: executables came off disk, nothing
    # traced, nothing compiled
    assert kinds.count('aot_load') == 2
    assert 'compile' not in kinds
    np.testing.assert_allclose(r1[0], r2[0])
    # warm executable stays dispatchable (donation honored across calls)
    r3 = exe2.run(feed=feed, fetch_list=[out2])
    np.testing.assert_allclose(r2[0], r3[0])


def test_aot_tampered_cache_falls_back(tmp_path, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_AOT_CACHE', '1')
    cache = tmp_path / 'aot'
    monkeypatch.setenv('PADDLE_TPU_AOT_CACHE_DIR', str(cache))
    feed = {'x': np.ones((3, 16), 'float32')}

    out = _build_mlp()
    exe1 = fluid.Executor(fluid.CPUPlace())
    exe1.run(fluid.default_startup_program())
    r1 = exe1.run(feed=feed, fetch_list=[out])
    for f in cache.iterdir():                      # corrupt every entry
        f.write_bytes(b'not a serialized executable')

    observe.arm_flight()
    before = len(observe.flight_recorder().events())
    out2 = _build_mlp()
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())
    r2 = exe2.run(feed=feed, fetch_list=[out2])
    assert exe2.aot_stats['hits'] == 0
    assert exe2.aot_stats['load_failures'] == 2
    events = observe.flight_recorder().events()[before:]
    assert any(e['kind'] == 'aot_fallback' for e in events)
    np.testing.assert_allclose(r1[0], r2[0])       # live compile worked


def test_aot_cache_disabled_by_default_on_cpu():
    from paddle_tpu.core import aot_cache
    assert not aot_cache.enabled({})               # auto = TPU only
    assert aot_cache.enabled({'PADDLE_TPU_AOT_CACHE': '1'})
    assert not aot_cache.enabled({'PADDLE_TPU_AOT_CACHE': '0'})


def test_aot_fingerprint_content_not_identity(tmp_path, monkeypatch):
    """Two Program OBJECTS with identical content share a fingerprint;
    different content (one extra layer) does not."""
    from paddle_tpu.core import aot_cache
    _build_mlp()
    p1 = fluid.default_main_program()
    fp1 = aot_cache.fingerprint(p1, ('single',))
    _build_mlp()
    p2 = fluid.default_main_program()
    assert p2 is not p1
    assert aot_cache.fingerprint(p2, ('single',)) == fp1
    fluid.layers.fc(input=p2.global_block().var('x'), size=3)
    assert aot_cache.fingerprint(p2, ('single',)) != fp1
    assert aot_cache.fingerprint(p1, ('multi',)) != fp1


# ------------------------------------------------------------ CLI + e2e
def test_tuning_inspect_cli(tmp_path, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'on')
    tuning.set_timer(_fake_timer({'tq1024': 'xla',
                                  'matmul_dtype': 'fp8'}))
    tuning.decide_attention(1, 8, 1024, 1024, 64, 'float32', True, False)
    # a linalg-family entry rides the same table (ISSUE 15)
    from paddle_tpu.parallel.mesh import make_mesh
    tuning.decide_summa_panel(64, 512, 64, 'float32',
                              make_mesh(dp=2, tp=2))
    # a matmul compute-dtype entry too (ISSUE 19)
    tuning.decide_matmul_dtype(64, 64, 64, 'float32')
    path = tuning.table_path()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, 'tools', 'tuning_inspect.py')
    r = subprocess.run([sys.executable, script, path, '--json'],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc['kind'] == 'paddle_tpu_tuning_table'
    assert doc['status'] == 'ok' and doc['n_entries'] == 3
    kind = doc['device_kinds'][0]
    attn = [e for k, e in doc['tables'][kind].items()
            if k.startswith('flash_attention')]
    assert attn[0]['winner'] == 'xla'
    assert attn[0]['timings_ms']['xla'] == pytest.approx(1.0)
    # the linalg summary section names the panel winner + margin
    (lkey, lent), = doc['linalg'][kind].items()
    assert lkey.startswith('summa_matmul|n64 k512 m64|dp2 tp2')
    assert lent['op'] == 'summa_matmul'
    assert isinstance(lent['size'], int)
    assert 'margin_over_runner_up' in lent
    # the matmul-dtype summary names the fp8-vs-native winner + shape
    (mkey, ment), = doc['matmul_dtype'][kind].items()
    assert mkey.startswith('matmul_dtype|m64 k64 n64')
    assert ment['op'] == 'matmul_dtype'
    assert ment['winner'] == 'fp8'
    assert ment['shape'] == 'm64 k64 n64'
    assert 'margin_over_runner_up' in ment
    # --linalg filters the tables to the family
    r3 = subprocess.run([sys.executable, script, path, '--json',
                         '--linalg'],
                        capture_output=True, text=True, timeout=60)
    doc3 = json.loads(r3.stdout)
    assert all(k.startswith('summa_matmul')
               for k in doc3['tables'][kind])
    # --matmul-dtype filters to the compute-dtype entries
    r4 = subprocess.run([sys.executable, script, path, '--json',
                         '--matmul-dtype'],
                        capture_output=True, text=True, timeout=60)
    doc4 = json.loads(r4.stdout)
    assert all(k.startswith('matmul_dtype')
               for k in doc4['tables'][kind])
    assert doc4['tables'][kind]
    # text mode renders without jax in the tool (stdlib-only contract)
    r2 = subprocess.run([sys.executable, script, path],
                        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0 and 'winner' in r2.stdout
    assert 'linalg panel/block winners' in r2.stdout
    assert 'matmul dtype winners' in r2.stdout


def _jsonl_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_cold_then_warm_subprocess_e2e(tmp_path):
    """Acceptance: the same program twice in two processes sharing one
    AOT cache dir — the second reports zero compile flight events on
    its hot keys and strictly lower startup wall (metrics JSONL is the
    evidence trail)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, os.path.join(repo, 'bench.py'),
           '--workload', 'autotune_child', '--backend', 'cpu']

    def run(tag):
        env = dict(os.environ)
        env.update({
            'PADDLE_TPU_AOT_CACHE': '1',
            'PADDLE_TPU_AOT_CACHE_DIR': str(tmp_path / 'aot'),
            'PADDLE_TPU_METRICS_JSONL': str(tmp_path / (tag + '.jsonl')),
            'JAX_PLATFORMS': 'cpu',
        })
        env.pop('PADDLE_TPU_AUTOTUNE', None)
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=300, env=env, cwd=repo)
        assert r.returncode == 0, r.stderr[-2000:]
        for line in reversed(r.stdout.splitlines()):
            if line.startswith('RESULT_JSON '):
                return json.loads(line[len('RESULT_JSON '):])
        raise AssertionError('no RESULT_JSON in child stdout:\n'
                             + r.stdout)

    cold = run('cold')
    warm = run('warm')
    assert cold['aot_hits'] == 0 and cold['aot_saves'] >= 2
    assert cold['compile_flight_events'] >= 2
    # the warm process: every hot key came off disk, ZERO compiles
    assert warm['aot_hits'] >= 2
    assert warm['compile_flight_events'] == 0
    assert warm['first_loss'] == pytest.approx(cold['first_loss'])
    # strictly-below startup wall (CPU CI tolerance: the cold run pays
    # a real multi-layer XLA compile, the warm run a deserialize)
    assert warm['startup_seconds'] < cold['startup_seconds']
    # and the metrics JSONL shows it: warm run recorded aot hits and
    # NO executor cache misses
    warm_recs = _jsonl_records(tmp_path / 'warm.jsonl')
    counters = {}
    for rec in warm_recs:
        counters.update(rec.get('counters', {}))
    assert any(k.startswith('executor.aot_hit_total') for k in counters)
    assert not any(k.startswith('executor.cache_miss_total')
                   for k in counters)
    cold_recs = _jsonl_records(tmp_path / 'cold.jsonl')
    cold_counters = {}
    for rec in cold_recs:
        cold_counters.update(rec.get('counters', {}))
    assert any(k.startswith('executor.cache_miss_total')
               for k in cold_counters)
