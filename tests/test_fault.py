"""Fault-tolerance subsystem (paddle_tpu.fault): mid-epoch checkpoint /
auto-resume, retention GC, LATEST semantics, truncated-checkpoint
fallback, NaN-policy matrix, reader.retry, and the subprocess
crash/resume e2e proving bit-identical final params (reference analog:
go/master/service.go's etcd task-queue recovery, rebuilt masterless)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io as pio
from paddle_tpu import reader as R
from paddle_tpu.fault import (BadStepError, CheckpointConfig,
                              CheckpointManager, inject)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    inject.clear()
    yield
    inject.clear()


# --------------------------------------------------------------- helpers
def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(4, 1).astype('float32')
    out = []
    for _ in range(n):
        xs = rng.randn(8, 4).astype('float32')
        out.append({'x': xs, 'y': (xs @ w).astype('float32')})
    return out


def _train_run(cfg, reader, n_epochs=1, event_handler=None):
    """One Trainer run in a fresh scope/programs; returns the final
    'fw' parameter (copy)."""
    def train_func():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1,
                               param_attr=fluid.ParamAttr(name='fw'),
                               bias_attr=fluid.ParamAttr(name='fb'))
        return [fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))]

    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        trainer = fluid.Trainer(
            train_func=train_func,
            optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.1),
            place=fluid.CPUPlace(), checkpoint_config=cfg)
        trainer.train(num_epochs=n_epochs, reader=reader,
                      event_handler=event_handler)
        return np.asarray(fluid.global_scope().find('fw')).copy()


def _build_exe_model(seed=0):
    """Direct Executor + 1-param model for manager-level tests; returns
    (exe, step_fn)."""
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='w'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(seed)
    feed = {'x': rng.rand(8, 4).astype('f'),
            'y': rng.rand(8, 1).astype('f')}
    return exe, lambda: exe.run(feed=feed, fetch_list=[loss])


# -------------------------------------------------- retention / LATEST
def test_retention_gc_keeps_exactly_k(tmp_path):
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, keep_last=2,
                                             async_save=False))
    for s in range(1, 6):
        step()
        mgr.save(exe, fluid.default_main_program(), step=s)
    dirs = sorted(n for n in os.listdir(d) if n.startswith('step_'))
    assert dirs == ['step_00000004', 'step_00000005']
    assert mgr.latest_pointer()[0] == 5
    with open(os.path.join(d, 'LATEST')) as f:
        assert f.read().strip() == 'step_00000005'


def test_retention_gc_async_path(tmp_path):
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, keep_last=1,
                                             async_save=True))
    for s in (1, 2, 3):
        step()
        mgr.save(exe, fluid.default_main_program(), step=s)
    mgr.wait()
    dirs = sorted(n for n in os.listdir(d) if n.startswith('step_'))
    assert dirs == ['step_00000003']
    assert mgr.latest_pointer()[0] == 3


def test_find_latest_empty_tree(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path)))
    assert mgr.find_latest() is None
    assert mgr.restore(None, None) is None


# ------------------------------------------- truncated-checkpoint fallback
def test_truncated_checkpoint_falls_back(tmp_path):
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, keep_last=3,
                                             async_save=False))
    step()
    w1 = np.asarray(fluid.global_scope().find('w')).copy()
    mgr.save(exe, fluid.default_main_program(), step=1)
    step()
    mgr.save(exe, fluid.default_main_program(), step=2)
    assert mgr.latest_pointer()[0] == 2
    # bit-rot / torn write on the NEWEST checkpoint, which LATEST names
    inject.truncate_file(os.path.join(mgr.step_dir(2), 'params.npz'))
    with pytest.raises(ValueError, match='torn|incomplete'):
        pio.verify_checkpoint(mgr.step_dir(2))
    fluid.global_scope().set('w', np.zeros_like(w1))
    with pytest.warns(UserWarning, match='unusable|skipping'):
        meta = mgr.restore(exe, fluid.default_main_program())
    assert meta['step'] == 1
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope().find('w')), w1)


def test_find_latest_skips_torn_dir_without_meta(tmp_path):
    """A save killed before checkpoint.json landed (params present, no
    meta) must be skipped, not loaded."""
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, async_save=False))
    step()
    mgr.save(exe, fluid.default_main_program(), step=1)
    torn = mgr.step_dir(9)
    os.makedirs(torn)
    with open(os.path.join(torn, 'params.npz'), 'wb') as f:
        f.write(b'partial write')
    with pytest.warns(UserWarning, match='skipping'):
        found = mgr.find_latest()
    assert found[0] == 1


# ----------------------------------------------------- NaN-policy matrix
def test_nan_policy_raise(tmp_path):
    batches = _batches(6)
    poisoned = inject.poison_nans(lambda: iter(batches), 2)
    cfg = CheckpointConfig(str(tmp_path), nan_policy='raise',
                           epoch_end=False)
    with pytest.raises(BadStepError, match='non-finite'):
        _train_run(cfg, poisoned)


def test_nan_policy_skip_step_equals_dropping_the_batch(tmp_path):
    batches = _batches(6)
    poisoned = inject.poison_nans(lambda: iter(batches), 2)
    cfg = CheckpointConfig(str(tmp_path), nan_policy='skip_step',
                           epoch_end=False)
    w_skip = _train_run(cfg, poisoned)
    assert np.all(np.isfinite(w_skip))
    w_ref = _train_run(None, lambda: iter(
        [b for i, b in enumerate(batches) if i != 2]))
    np.testing.assert_array_equal(w_skip, w_ref)


def test_nan_policy_rollback_restores_last_checkpoint(tmp_path):
    batches = _batches(6)
    poisoned = inject.poison_nans(lambda: iter(batches), 2)
    # checkpoint every step synchronously: the newest checkpoint IS the
    # pre-bad-step state, so rollback == skip == dropping the batch
    cfg = CheckpointConfig(str(tmp_path), save_every_steps=1,
                           async_save=False, nan_policy='rollback',
                           epoch_end=False)
    w_rb = _train_run(cfg, poisoned)
    assert np.all(np.isfinite(w_rb))
    w_ref = _train_run(None, lambda: iter(
        [b for i, b in enumerate(batches) if i != 2]))
    np.testing.assert_array_equal(w_rb, w_ref)


def test_nan_policy_rollback_without_checkpoint_raises(tmp_path):
    batches = _batches(3)
    poisoned = inject.poison_nans(lambda: iter(batches), 0)
    cfg = CheckpointConfig(str(tmp_path), nan_policy='rollback',
                           epoch_end=False)   # no cadence -> no ckpt yet
    with pytest.raises(BadStepError, match='no complete checkpoint'):
        _train_run(cfg, poisoned)


def test_nan_policy_max_bad_steps_escalates(tmp_path):
    batches = _batches(8)
    all_bad = [{'x': b['x'], 'y': np.full_like(b['y'], np.nan)}
               for b in batches]
    cfg = CheckpointConfig(str(tmp_path), nan_policy='skip_step',
                           max_bad_steps=3, epoch_end=False)
    with pytest.raises(BadStepError, match='consecutive'):
        _train_run(cfg, lambda: iter(all_bad))


def test_guard_unit_is_bad():
    from paddle_tpu.fault import is_bad
    assert is_bad(np.float32('nan'))
    assert is_bad(np.array([1.0, np.inf]))
    assert not is_bad(np.array([1.0, -2.0]))
    assert not is_bad(np.array([1, 2], dtype='int64'))


def test_checkpoint_config_validation():
    with pytest.raises(ValueError, match='dirname'):
        CheckpointConfig('')
    with pytest.raises(ValueError, match='keep_last'):
        CheckpointConfig('d', keep_last=0)
    with pytest.raises(ValueError, match='nan_policy'):
        CheckpointConfig('d', nan_policy='explode')
    with pytest.raises(ValueError, match='save_every_steps'):
        CheckpointConfig('d', save_every_steps=0)


# ----------------------------------------------------------- reader.retry
def test_retry_recovers_transient_failures():
    fl = inject.flaky(lambda: iter(range(10)), fail_times=2, fail_after=3)
    assert list(R.retry(fl, tries=3, backoff=0)()) == list(range(10))
    assert fl.state == {'fails': 2, 'calls': 3}


def test_retry_no_duplicates_no_gaps_after_midstream_failure():
    fl = inject.flaky(lambda: iter(range(8)), fail_times=1, fail_after=5)
    got = list(R.retry(fl, tries=2, backoff=0)())
    assert got == list(range(8))        # prefix not re-yielded


def test_retry_exhaustion_reraises():
    fl = inject.flaky(lambda: iter(range(5)), fail_times=99, fail_after=1)
    with pytest.raises(inject.TransientReaderError):
        list(R.retry(fl, tries=3, backoff=0)())


def test_retry_backoff_doubles(monkeypatch):
    import time as _time
    sleeps = []
    monkeypatch.setattr(_time, 'sleep', lambda s: sleeps.append(s))
    fl = inject.flaky(lambda: iter(range(4)), fail_times=2, fail_after=0)
    assert list(R.retry(fl, tries=4, backoff=0.05)()) == [0, 1, 2, 3]
    assert sleeps == [0.05, 0.1]


# ------------------------------------------------- mid-epoch auto-resume
class _Preempted(Exception):
    pass


def test_mid_epoch_resume_in_process(tmp_path):
    """Preempt (via an exception) after 5 steps of epoch 0, restart with
    resume=True, and the final params match an uninterrupted run exactly
    — mid-epoch state (params, step, reader offset) round-trips."""
    d = str(tmp_path / 'ckpt')
    batches = _batches(10, seed=3)

    def make_reader():
        return R.CheckpointableReader(lambda: iter(batches),
                                      shuffle_buf=4, seed=9)

    def cfg():
        return CheckpointConfig(d, save_every_steps=2, async_save=False,
                                resume=True, nan_policy=None)

    count = [0]

    def killer(e):
        if isinstance(e, fluid.trainer.EndStepEvent):
            count[0] += 1
            if count[0] == 5:
                raise _Preempted()

    with pytest.raises(_Preempted):
        _train_run(cfg(), make_reader(), n_epochs=2, event_handler=killer)
    assert CheckpointManager(cfg()).find_latest()[0] == 4

    w_resumed = _train_run(cfg(), make_reader(), n_epochs=2)
    w_ref = _train_run(None, make_reader(), n_epochs=2)
    np.testing.assert_array_equal(w_resumed, w_ref)


def test_resume_noop_on_empty_tree(tmp_path):
    d = str(tmp_path / 'never_written')
    cfg = CheckpointConfig(d, resume=True, epoch_end=False,
                           nan_policy=None)
    w = _train_run(cfg, lambda: iter(_batches(3)))
    assert np.all(np.isfinite(w))


# -------------------------------------------- subprocess crash/resume e2e
def _run_child(tmp, tag, extra_env, reuse_ckpt=None):
    env = dict(os.environ)
    for k in ('PADDLE_TPU_FI_KILL_AT_STEP', 'PADDLE_TPU_FI_CORRUPT_CKPT_AT',
              'PADDLE_TPU_FLIGHT_DUMP', 'XLA_FLAGS'):
        env.pop(k, None)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    ckpt = reuse_ckpt or os.path.join(str(tmp), tag + '_ckpt')
    out = os.path.join(str(tmp), tag + '.npz')
    env['FT_CKPT_DIR'] = ckpt
    env['FT_OUT'] = out
    env.update(extra_env)
    p = subprocess.run(
        [sys.executable,
         os.path.join(REPO, 'tests', 'fault_injection_child.py')],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    return p, ckpt, out


@pytest.fixture(scope='module')
def clean_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp('ft_clean')
    p, _, out = _run_child(tmp, 'clean', {})
    assert p.returncode == 0, p.stderr
    return np.load(out)


def _assert_bit_identical(a, b):
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_e2e_kill_and_resume_bit_identical(tmp_path, clean_run):
    # run killed mid-epoch at injected step 7 (12 steps/epoch); the
    # armed flight recorder must leave a postmortem behind
    pm = os.path.join(str(tmp_path), 'postmortem.json')
    p, ckpt, out = _run_child(tmp_path, 'killed',
                              {'PADDLE_TPU_FI_KILL_AT_STEP': '7',
                               'PADDLE_TPU_FLIGHT_DUMP': pm})
    assert p.returncode == inject.KILL_EXIT_CODE, (p.returncode, p.stderr)
    assert not os.path.exists(out)      # died before finishing
    assert os.path.isdir(ckpt)          # ...but left checkpoints behind
    # kill-mid-step postmortem: exists, parses, explains the death, and
    # every recorded step end precedes (or is) the kill step
    with open(pm) as f:
        doc = json.load(f)
    assert doc['kind'] == 'paddle_tpu_postmortem' and doc['schema'] == 1
    assert doc['reason'] == 'fault_injection_kill'
    evs = doc['events']
    assert evs and evs[-1]['kind'] == 'kill'
    assert evs[-1]['data']['kill_at_step'] == 7
    steps = [e['data']['step'] for e in evs if e['kind'] == 'step_end']
    assert steps and max(steps) <= 7
    assert any(e['kind'] == 'checkpoint_save' for e in evs)
    # ...and tools/flight_report.py renders it without error
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'flight_report.py'),
         pm], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert 'fault_injection_kill' in r.stdout
    # restart WITHOUT the fault env: resume=True picks up the newest
    # complete checkpoint and finishes the job
    p, _, out = _run_child(tmp_path, 'resumed', {}, reuse_ckpt=ckpt)
    assert p.returncode == 0, p.stderr
    _assert_bit_identical(clean_run, np.load(out))


def test_e2e_corrupt_newest_checkpoint_falls_back(tmp_path, clean_run):
    # sync saves (deterministic commit order); checkpoint at step 9 is
    # truncated right after its commit, then the process dies at step 10
    p, ckpt, out = _run_child(
        tmp_path, 'corrupt',
        {'PADDLE_TPU_FI_KILL_AT_STEP': '10',
         'PADDLE_TPU_FI_CORRUPT_CKPT_AT': '9',
         'FT_SYNC_SAVE': '1'})
    assert p.returncode == inject.KILL_EXIT_CODE, (p.returncode, p.stderr)
    # precondition: LATEST names the corrupted checkpoint
    with open(os.path.join(ckpt, 'LATEST')) as f:
        assert f.read().strip() == 'step_00000009'
    with pytest.raises(ValueError, match='torn|incomplete'):
        pio.verify_checkpoint(os.path.join(ckpt, 'step_00000009'))
    # resume detects the sha1 mismatch, falls back to step 6, and still
    # reproduces the uninterrupted run bit-for-bit
    p, _, out = _run_child(tmp_path, 'corrupt_resumed', {},
                           reuse_ckpt=ckpt)
    assert p.returncode == 0, p.stderr
    assert 'unusable' in p.stderr or 'falling back' in p.stderr
    _assert_bit_identical(clean_run, np.load(out))


# --------------------------------------------------- satellite regressions
def test_pallas_block_override_rounded_to_divisor():
    from paddle_tpu.ops.pallas.flash_attention import _pick_block
    assert _pick_block(256, 192) == 128   # non-pow2 override degrades
    assert _pick_block(256, 512) == 256
    assert _pick_block(64, 512) == 64
    assert _pick_block(96, 128) == 32     # halves below 128 to a divisor
    assert _pick_block(128, 128) == 128


def test_reader_state_pending_adjustment():
    r = R.CheckpointableReader(lambda: iter(range(10)))
    gen = r()
    for _ in range(4):
        next(gen)
    gen.close()
    assert r.state_dict()['offset'] == 4
    assert r.state_dict(pending=3)['offset'] == 1
    with pytest.raises(ValueError, match='pending'):
        r.state_dict(pending=5)
