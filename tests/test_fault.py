"""Fault-tolerance subsystem (paddle_tpu.fault): mid-epoch checkpoint /
auto-resume, retention GC, LATEST semantics, truncated-checkpoint
fallback, NaN-policy matrix, reader.retry, and the subprocess
crash/resume e2e proving bit-identical final params (reference analog:
go/master/service.go's etcd task-queue recovery, rebuilt masterless)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io as pio
from paddle_tpu import reader as R
from paddle_tpu.fault import (BadStepError, CheckpointConfig,
                              CheckpointManager, NoUsableCheckpointError,
                              inject)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    inject.clear()
    yield
    inject.clear()


# --------------------------------------------------------------- helpers
def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(4, 1).astype('float32')
    out = []
    for _ in range(n):
        xs = rng.randn(8, 4).astype('float32')
        out.append({'x': xs, 'y': (xs @ w).astype('float32')})
    return out


def _train_run(cfg, reader, n_epochs=1, event_handler=None):
    """One Trainer run in a fresh scope/programs; returns the final
    'fw' parameter (copy)."""
    def train_func():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1,
                               param_attr=fluid.ParamAttr(name='fw'),
                               bias_attr=fluid.ParamAttr(name='fb'))
        return [fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))]

    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        trainer = fluid.Trainer(
            train_func=train_func,
            optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.1),
            place=fluid.CPUPlace(), checkpoint_config=cfg)
        trainer.train(num_epochs=n_epochs, reader=reader,
                      event_handler=event_handler)
        return np.asarray(fluid.global_scope().find('fw')).copy()


def _build_exe_model(seed=0):
    """Direct Executor + 1-param model for manager-level tests; returns
    (exe, step_fn)."""
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='w'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(seed)
    feed = {'x': rng.rand(8, 4).astype('f'),
            'y': rng.rand(8, 1).astype('f')}
    return exe, lambda: exe.run(feed=feed, fetch_list=[loss])


# -------------------------------------------------- retention / LATEST
def test_retention_gc_keeps_exactly_k(tmp_path):
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, keep_last=2,
                                             async_save=False))
    for s in range(1, 6):
        step()
        mgr.save(exe, fluid.default_main_program(), step=s)
    dirs = sorted(n for n in os.listdir(d) if n.startswith('step_'))
    assert dirs == ['step_00000004', 'step_00000005']
    assert mgr.latest_pointer()[0] == 5
    with open(os.path.join(d, 'LATEST')) as f:
        assert f.read().strip() == 'step_00000005'


def test_retention_gc_async_path(tmp_path):
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, keep_last=1,
                                             async_save=True))
    for s in (1, 2, 3):
        step()
        mgr.save(exe, fluid.default_main_program(), step=s)
    mgr.wait()
    dirs = sorted(n for n in os.listdir(d) if n.startswith('step_'))
    assert dirs == ['step_00000003']
    assert mgr.latest_pointer()[0] == 3


def test_find_latest_empty_tree(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path)))
    assert mgr.find_latest() is None
    assert mgr.restore(None, None) is None


# ------------------------------------------- truncated-checkpoint fallback
def test_truncated_checkpoint_falls_back(tmp_path):
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, keep_last=3,
                                             async_save=False))
    step()
    w1 = np.asarray(fluid.global_scope().find('w')).copy()
    mgr.save(exe, fluid.default_main_program(), step=1)
    step()
    mgr.save(exe, fluid.default_main_program(), step=2)
    assert mgr.latest_pointer()[0] == 2
    # bit-rot / torn write on the NEWEST checkpoint, which LATEST names
    inject.truncate_file(os.path.join(mgr.step_dir(2), 'params.npz'))
    with pytest.raises(ValueError, match='torn|incomplete'):
        pio.verify_checkpoint(mgr.step_dir(2))
    fluid.global_scope().set('w', np.zeros_like(w1))
    with pytest.warns(UserWarning, match='unusable|skipping'):
        meta = mgr.restore(exe, fluid.default_main_program())
    assert meta['step'] == 1
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope().find('w')), w1)


def test_find_latest_skips_torn_dir_without_meta(tmp_path):
    """A save killed before checkpoint.json landed (params present, no
    meta) must be skipped, not loaded."""
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, async_save=False))
    step()
    mgr.save(exe, fluid.default_main_program(), step=1)
    torn = mgr.step_dir(9)
    os.makedirs(torn)
    with open(os.path.join(torn, 'params.npz'), 'wb') as f:
        f.write(b'partial write')
    with pytest.warns(UserWarning, match='skipping'):
        found = mgr.find_latest()
    assert found[0] == 1


def test_restore_exhaustion_raises_clear_error(tmp_path):
    """Keep-last-K exhaustion (satellite): LATEST torn AND the older
    candidate torn — restore must surface a clear NoUsableCheckpointError
    naming the candidates, never an arbitrary FileNotFoundError and
    never a silent from-scratch restart."""
    d = str(tmp_path)
    exe, step = _build_exe_model()
    mgr = CheckpointManager(CheckpointConfig(d, keep_last=2,
                                             async_save=False))
    for s in (1, 2):
        step()
        mgr.save(exe, fluid.default_main_program(), step=s)
    for s in (1, 2):
        inject.truncate_file(os.path.join(mgr.step_dir(s), 'params.npz'))
    with pytest.warns(UserWarning, match='unusable'):
        with pytest.raises(NoUsableCheckpointError,
                           match='NONE is usable') as ei:
            mgr.restore(exe, fluid.default_main_program())
    msg = str(ei.value)
    assert 'step_00000002' in msg and 'step_00000001' in msg
    assert not isinstance(ei.value, FileNotFoundError)


# --------------------------------------------------- elastic topology
def _build_meshed_model(dp, steps=2):
    """MLP + Adam transpiled onto a dp mesh, trained `steps` steps on a
    fixed batch; returns (exe, run_one_step)."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import ParallelStrategy, transpile
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='w'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.default_main_program().random_seed = 7
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    transpile(fluid.default_main_program(), make_mesh(dp=dp),
              ParallelStrategy(data_parallel=True))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(3)
    feed = {'x': rng.rand(8, 4).astype('f'),
            'y': rng.rand(8, 1).astype('f')}
    run = lambda: exe.run(feed=feed, fetch_list=[loss])  # noqa: E731
    for _ in range(steps):
        run()
    return exe, run


def test_restore_topology_change_reshards_and_counts(tmp_path):
    """CheckpointManager.restore across a mesh change: params AND
    optimizer state come back under the new mesh's NamedSharding, the
    fault.reshard_total counter increments, and an elastic_reshard
    flight event lands in the ring."""
    import jax
    from paddle_tpu import observe
    d = str(tmp_path)
    exe, _ = _build_meshed_model(dp=4)
    mgr = CheckpointManager(CheckpointConfig(d, async_save=False))
    mgr.save(exe, fluid.default_main_program(), step=2)

    exe2, run2 = _build_meshed_model(dp=2, steps=0)
    observe.enable()
    try:
        observe.flight_recorder().clear()
        before = observe.get_counter('fault.reshard_total') or 0
        meta = CheckpointManager(CheckpointConfig(d)).restore(
            exe2, fluid.default_main_program())
        assert meta['step'] == 2
        assert meta['mesh']['dp'] == 4      # the WRITING topology
        assert (observe.get_counter('fault.reshard_total')
                or 0) == before + 1
        evs = [e for e in observe.flight_recorder().events()
               if e['kind'] == 'elastic_reshard']
        assert evs and evs[-1]['data']['from_topology'] == 'hosts=1 dp4'
        assert evs[-1]['data']['to_topology'] == 'hosts=1 dp2'
    finally:
        observe.flight_recorder().clear()
        observe.disable()
        observe.reset()
    w = fluid.global_scope().find('w')
    assert isinstance(w, jax.Array)
    assert len(w.sharding.device_set) == 2  # placed on the dp=2 mesh
    moment = next(n for n in fluid.global_scope().keys() if 'moment' in n)
    assert isinstance(fluid.global_scope().find(moment), jax.Array)
    run2()                                  # trains on the new mesh


def test_restore_falls_back_past_pre_elastic_on_topology_change(tmp_path):
    """A newer checkpoint whose format predates the sharding specs is
    skipped (with a warning) when the topology changed; the older
    format-v2 one restores instead."""
    import json
    d = str(tmp_path)
    exe, run = _build_meshed_model(dp=4)
    mgr = CheckpointManager(CheckpointConfig(d, async_save=False))
    mgr.save(exe, fluid.default_main_program(), step=1)
    run()
    mgr.save(exe, fluid.default_main_program(), step=2)
    # doctor the NEWEST checkpoint into the pre-elastic shape
    meta_path = os.path.join(mgr.step_dir(2), 'checkpoint.json')
    with open(meta_path) as f:
        meta = json.load(f)
    for key in ('format_version', 'mesh', 'hosts'):
        meta.pop(key, None)
    with open(meta_path, 'w') as f:
        f.write(json.dumps(meta))

    exe2, _ = _build_meshed_model(dp=2, steps=0)
    with pytest.warns(UserWarning, match='elastic'):
        got = CheckpointManager(CheckpointConfig(d)).restore(
            exe2, fluid.default_main_program())
    assert got['step'] == 1


def test_preempt_at_step_sends_sigterm():
    """inject preempt_at_step: a SIGTERM (the preemption notice), not a
    hard kill — and one-shot, like the real notice."""
    import signal
    import time
    received = []
    prev = signal.signal(signal.SIGTERM,
                         lambda signum, frame: received.append(signum))
    try:
        inject.install(inject.FaultPlan(preempt_at_step=5))
        inject.fire('step_end', step=4)
        assert not received
        inject.fire('step_end', step=5)
        for _ in range(200):            # delivery is async-signal-safe
            if received:
                break
            time.sleep(0.005)
        assert received == [signal.SIGTERM]
        inject.fire('step_end', step=6)     # disarmed after firing
        time.sleep(0.02)
        assert received == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_preempt_env_contract(monkeypatch):
    inject.clear()
    plan = inject.install_from_env(
        {'PADDLE_TPU_FI_PREEMPT_AT_STEP': '9'})
    assert plan.preempt_at_step == 9 and plan.kill_at_step is None


def test_reader_offset_stays_global_under_sharding():
    """The io.py positional-sharding invariant, fixed: offset counts
    GLOBAL stream items, pending is scaled by the shard width, and a
    resume at a DIFFERENT width covers exactly the untrained remainder
    — no item skipped, none double-trained."""
    from paddle_tpu.reader.decorator import shard
    items = list(range(24))
    r = R.CheckpointableReader(lambda: iter(items))
    r.shard_width = 4                       # what shard_reader sets
    gen = shard(r, 4, 0)()
    trained = [next(gen) for _ in range(3)]  # 3 per-host yields
    gen.close()
    assert r.offset == 12                   # 4 global pulls per yield
    state = r.state_dict(pending=1)         # 1 pulled-but-untrained
    assert state['offset'] == 8             # ...scaled to 4 global items
    assert state['hosts'] == 4
    assert trained[0] in items[:4]

    # resume as dp=2: the two hosts' shards are disjoint and together
    # cover exactly global items 8..23
    streams = []
    for host in range(2):
        r2 = R.CheckpointableReader(lambda: iter(items))
        r2.load_state_dict(state)
        streams.append(list(shard(r2, 2, host)()))
    assert sorted(streams[0] + streams[1]) == items[8:]
    assert not set(streams[0]) & set(streams[1])


def test_reader_pending_exceeding_offset_raises_in_global_units():
    r = R.CheckpointableReader(lambda: iter(range(10)))
    r.shard_width = 4
    gen = r()
    for _ in range(4):
        next(gen)
    gen.close()
    with pytest.raises(ValueError, match='pending'):
        r.state_dict(pending=2)             # 8 global > offset 4


# -------------------------------------------------- ckpt_inspect tool
def test_ckpt_inspect_cli_json_schema(tmp_path):
    """tools/ckpt_inspect.py --json on a real (meshed) checkpoint tree:
    step, mesh, specs, reader state, and sha1 verification status."""
    d = str(tmp_path)
    exe, _ = _build_meshed_model(dp=4)
    reader = R.CheckpointableReader(lambda: iter(_batches(6)))
    gen = reader()
    next(gen)
    gen.close()
    mgr = CheckpointManager(CheckpointConfig(d, async_save=False))
    mgr.save(exe, fluid.default_main_program(), step=2, reader=reader,
             trainer_state={'epoch': 0, 'epoch_step': 2})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'ckpt_inspect.py'),
         d, '--json'], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc['kind'] == 'paddle_tpu_checkpoint'
    assert doc['step'] == 2
    assert doc['format_version'] == 2
    assert doc['mesh']['dp'] == 4
    assert doc['verification'] == 'ok'
    assert doc['reader']['offset'] == 1
    assert doc['trainer'] == {'epoch': 0, 'epoch_step': 2}
    assert doc['n_vars'] == len(doc['vars']) and doc['n_vars'] > 0
    assert all('spec' in e for e in doc['vars'].values())
    # text mode renders without error
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'ckpt_inspect.py'),
         d], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert 'verification    ok' in r.stdout
    # a torn checkpoint is reported as torn, not a traceback
    inject.truncate_file(os.path.join(mgr.step_dir(2), 'params.npz'))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'ckpt_inspect.py'),
         mgr.step_dir(2), '--json'], capture_output=True, text=True,
        timeout=60)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)['verification'].startswith('torn')


# ----------------------------------------------------- NaN-policy matrix
def test_nan_policy_raise(tmp_path):
    batches = _batches(6)
    poisoned = inject.poison_nans(lambda: iter(batches), 2)
    cfg = CheckpointConfig(str(tmp_path), nan_policy='raise',
                           epoch_end=False)
    with pytest.raises(BadStepError, match='non-finite'):
        _train_run(cfg, poisoned)


def test_nan_policy_skip_step_equals_dropping_the_batch(tmp_path):
    batches = _batches(6)
    poisoned = inject.poison_nans(lambda: iter(batches), 2)
    cfg = CheckpointConfig(str(tmp_path), nan_policy='skip_step',
                           epoch_end=False)
    w_skip = _train_run(cfg, poisoned)
    assert np.all(np.isfinite(w_skip))
    w_ref = _train_run(None, lambda: iter(
        [b for i, b in enumerate(batches) if i != 2]))
    np.testing.assert_array_equal(w_skip, w_ref)


def test_nan_policy_rollback_restores_last_checkpoint(tmp_path):
    batches = _batches(6)
    poisoned = inject.poison_nans(lambda: iter(batches), 2)
    # checkpoint every step synchronously: the newest checkpoint IS the
    # pre-bad-step state, so rollback == skip == dropping the batch
    cfg = CheckpointConfig(str(tmp_path), save_every_steps=1,
                           async_save=False, nan_policy='rollback',
                           epoch_end=False)
    w_rb = _train_run(cfg, poisoned)
    assert np.all(np.isfinite(w_rb))
    w_ref = _train_run(None, lambda: iter(
        [b for i, b in enumerate(batches) if i != 2]))
    np.testing.assert_array_equal(w_rb, w_ref)


def test_nan_policy_rollback_without_checkpoint_raises(tmp_path):
    batches = _batches(3)
    poisoned = inject.poison_nans(lambda: iter(batches), 0)
    cfg = CheckpointConfig(str(tmp_path), nan_policy='rollback',
                           epoch_end=False)   # no cadence -> no ckpt yet
    with pytest.raises(BadStepError, match='no complete checkpoint'):
        _train_run(cfg, poisoned)


def test_nan_policy_max_bad_steps_escalates(tmp_path):
    batches = _batches(8)
    all_bad = [{'x': b['x'], 'y': np.full_like(b['y'], np.nan)}
               for b in batches]
    cfg = CheckpointConfig(str(tmp_path), nan_policy='skip_step',
                           max_bad_steps=3, epoch_end=False)
    with pytest.raises(BadStepError, match='consecutive'):
        _train_run(cfg, lambda: iter(all_bad))


def test_guard_unit_is_bad():
    from paddle_tpu.fault import is_bad
    assert is_bad(np.float32('nan'))
    assert is_bad(np.array([1.0, np.inf]))
    assert not is_bad(np.array([1.0, -2.0]))
    assert not is_bad(np.array([1, 2], dtype='int64'))


def test_checkpoint_config_validation():
    with pytest.raises(ValueError, match='dirname'):
        CheckpointConfig('')
    with pytest.raises(ValueError, match='keep_last'):
        CheckpointConfig('d', keep_last=0)
    with pytest.raises(ValueError, match='nan_policy'):
        CheckpointConfig('d', nan_policy='explode')
    with pytest.raises(ValueError, match='save_every_steps'):
        CheckpointConfig('d', save_every_steps=0)


# ----------------------------------------------------------- reader.retry
def test_retry_recovers_transient_failures():
    fl = inject.flaky(lambda: iter(range(10)), fail_times=2, fail_after=3)
    assert list(R.retry(fl, tries=3, backoff=0)()) == list(range(10))
    assert fl.state == {'fails': 2, 'calls': 3}


def test_retry_no_duplicates_no_gaps_after_midstream_failure():
    fl = inject.flaky(lambda: iter(range(8)), fail_times=1, fail_after=5)
    got = list(R.retry(fl, tries=2, backoff=0)())
    assert got == list(range(8))        # prefix not re-yielded


def test_retry_exhaustion_reraises():
    fl = inject.flaky(lambda: iter(range(5)), fail_times=99, fail_after=1)
    with pytest.raises(inject.TransientReaderError):
        list(R.retry(fl, tries=3, backoff=0)())


def test_retry_backoff_doubles(monkeypatch):
    import time as _time
    sleeps = []
    monkeypatch.setattr(_time, 'sleep', lambda s: sleeps.append(s))
    fl = inject.flaky(lambda: iter(range(4)), fail_times=2, fail_after=0)
    assert list(R.retry(fl, tries=4, backoff=0.05)()) == [0, 1, 2, 3]
    assert sleeps == [0.05, 0.1]


# ------------------------------------------------- mid-epoch auto-resume
class _Preempted(Exception):
    pass


def test_mid_epoch_resume_in_process(tmp_path):
    """Preempt (via an exception) after 5 steps of epoch 0, restart with
    resume=True, and the final params match an uninterrupted run exactly
    — mid-epoch state (params, step, reader offset) round-trips."""
    d = str(tmp_path / 'ckpt')
    batches = _batches(10, seed=3)

    def make_reader():
        return R.CheckpointableReader(lambda: iter(batches),
                                      shuffle_buf=4, seed=9)

    def cfg():
        return CheckpointConfig(d, save_every_steps=2, async_save=False,
                                resume=True, nan_policy=None)

    count = [0]

    def killer(e):
        if isinstance(e, fluid.trainer.EndStepEvent):
            count[0] += 1
            if count[0] == 5:
                raise _Preempted()

    with pytest.raises(_Preempted):
        _train_run(cfg(), make_reader(), n_epochs=2, event_handler=killer)
    assert CheckpointManager(cfg()).find_latest()[0] == 4

    w_resumed = _train_run(cfg(), make_reader(), n_epochs=2)
    w_ref = _train_run(None, make_reader(), n_epochs=2)
    np.testing.assert_array_equal(w_resumed, w_ref)


def test_resume_noop_on_empty_tree(tmp_path):
    d = str(tmp_path / 'never_written')
    cfg = CheckpointConfig(d, resume=True, epoch_end=False,
                           nan_policy=None)
    w = _train_run(cfg, lambda: iter(_batches(3)))
    assert np.all(np.isfinite(w))


# -------------------------------------------- subprocess crash/resume e2e
def _run_child(tmp, tag, extra_env, reuse_ckpt=None):
    env = dict(os.environ)
    for k in ('PADDLE_TPU_FI_KILL_AT_STEP', 'PADDLE_TPU_FI_CORRUPT_CKPT_AT',
              'PADDLE_TPU_FI_PREEMPT_AT_STEP', 'PADDLE_TPU_FLIGHT_DUMP',
              'FT_MESH_DP', 'FT_METRICS', 'XLA_FLAGS'):
        env.pop(k, None)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    ckpt = reuse_ckpt or os.path.join(str(tmp), tag + '_ckpt')
    out = os.path.join(str(tmp), tag + '.npz')
    env['FT_CKPT_DIR'] = ckpt
    env['FT_OUT'] = out
    env.update(extra_env)
    p = subprocess.run(
        [sys.executable,
         os.path.join(REPO, 'tests', 'fault_injection_child.py')],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    return p, ckpt, out


@pytest.fixture(scope='module')
def clean_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp('ft_clean')
    p, _, out = _run_child(tmp, 'clean', {})
    assert p.returncode == 0, p.stderr
    return np.load(out)


def _assert_bit_identical(a, b):
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_e2e_kill_and_resume_bit_identical(tmp_path, clean_run):
    # run killed mid-epoch at injected step 7 (12 steps/epoch); the
    # armed flight recorder must leave a postmortem behind
    pm = os.path.join(str(tmp_path), 'postmortem.json')
    p, ckpt, out = _run_child(tmp_path, 'killed',
                              {'PADDLE_TPU_FI_KILL_AT_STEP': '7',
                               'PADDLE_TPU_FLIGHT_DUMP': pm})
    assert p.returncode == inject.KILL_EXIT_CODE, (p.returncode, p.stderr)
    assert not os.path.exists(out)      # died before finishing
    assert os.path.isdir(ckpt)          # ...but left checkpoints behind
    # kill-mid-step postmortem: exists, parses, explains the death, and
    # every recorded step end precedes (or is) the kill step
    with open(pm) as f:
        doc = json.load(f)
    assert doc['kind'] == 'paddle_tpu_postmortem' and doc['schema'] == 1
    assert doc['reason'] == 'fault_injection_kill'
    evs = doc['events']
    assert evs and evs[-1]['kind'] == 'kill'
    assert evs[-1]['data']['kill_at_step'] == 7
    steps = [e['data']['step'] for e in evs if e['kind'] == 'step_end']
    assert steps and max(steps) <= 7
    assert any(e['kind'] == 'checkpoint_save' for e in evs)
    # ...and tools/flight_report.py renders it without error
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'flight_report.py'),
         pm], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert 'fault_injection_kill' in r.stdout
    # restart WITHOUT the fault env: resume=True picks up the newest
    # complete checkpoint and finishes the job
    p, _, out = _run_child(tmp_path, 'resumed', {}, reuse_ckpt=ckpt)
    assert p.returncode == 0, p.stderr
    _assert_bit_identical(clean_run, np.load(out))


def test_e2e_corrupt_newest_checkpoint_falls_back(tmp_path, clean_run):
    # sync saves (deterministic commit order); checkpoint at step 9 is
    # truncated right after its commit, then the process dies at step 10
    p, ckpt, out = _run_child(
        tmp_path, 'corrupt',
        {'PADDLE_TPU_FI_KILL_AT_STEP': '10',
         'PADDLE_TPU_FI_CORRUPT_CKPT_AT': '9',
         'FT_SYNC_SAVE': '1'})
    assert p.returncode == inject.KILL_EXIT_CODE, (p.returncode, p.stderr)
    # precondition: LATEST names the corrupted checkpoint
    with open(os.path.join(ckpt, 'LATEST')) as f:
        assert f.read().strip() == 'step_00000009'
    with pytest.raises(ValueError, match='torn|incomplete'):
        pio.verify_checkpoint(os.path.join(ckpt, 'step_00000009'))
    # resume detects the sha1 mismatch, falls back to step 6, and still
    # reproduces the uninterrupted run bit-for-bit
    p, _, out = _run_child(tmp_path, 'corrupt_resumed', {},
                           reuse_ckpt=ckpt)
    assert p.returncode == 0, p.stderr
    assert 'unusable' in p.stderr or 'falling back' in p.stderr
    _assert_bit_identical(clean_run, np.load(out))


# -------------------------------- elastic-topology crash/resume e2e
# Train on a dp=4 CPU mesh, preempt (SIGTERM) mid-epoch, resume on a
# DIFFERENT dp width at the same global batch: final params must be
# bit-identical to the uninterrupted dp=4 run. The child's elastic
# model keeps every quantity an exact dyadic rational (integer data, L1
# loss, 2^-k learning rate), so cross-item sums are exact in any
# association and bit-identity genuinely survives the reduction-order
# changes a different mesh shape introduces.

@pytest.fixture(scope='module')
def elastic_clean_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp('ft_elastic_clean')
    p, _, out = _run_child(tmp, 'clean', {'FT_MESH_DP': '4'})
    assert p.returncode == 0, p.stderr
    return np.load(out)


def _sigterm_rc():
    import signal
    return -int(signal.SIGTERM)


def test_e2e_preempt_dp4_resume_dp2_bit_identical(tmp_path,
                                                  elastic_clean_run):
    # preemption notice at step 7: SIGTERM, so the armed flight
    # recorder's handler writes the postmortem before the default
    # action terminates the process
    pm = os.path.join(str(tmp_path), 'postmortem.json')
    p, ckpt, out = _run_child(tmp_path, 'preempted',
                              {'FT_MESH_DP': '4',
                               'PADDLE_TPU_FI_PREEMPT_AT_STEP': '7',
                               'PADDLE_TPU_FLIGHT_DUMP': pm})
    assert p.returncode == _sigterm_rc(), (p.returncode, p.stderr)
    assert not os.path.exists(out)
    with open(pm) as f:
        doc = json.load(f)
    assert doc['reason'] == 'sigterm'
    kinds = [e['kind'] for e in doc['events']]
    assert 'preempt' in kinds and 'checkpoint_save' in kinds

    # come back on HALF the slice: mesh {dp:4} -> {dp:2}, same global
    # batch — restore reshards, the reader replays the exact remainder
    metrics = os.path.join(str(tmp_path), 'metrics.jsonl')
    p, _, out = _run_child(tmp_path, 'resumed_dp2',
                           {'FT_MESH_DP': '2', 'FT_METRICS': metrics},
                           reuse_ckpt=ckpt)
    assert p.returncode == 0, p.stderr
    _assert_bit_identical(elastic_clean_run, np.load(out))
    # the reshard is visible in the metrics snapshot
    with open(metrics) as f:
        snaps = [json.loads(line) for line in f if line.strip()]
    counters = snaps[-1]['counters']
    assert counters.get('fault.reshard_total') == 1
    assert counters.get('fault.resume_total') == 1


@pytest.mark.slow
def test_e2e_elastic_sweep_dp2_and_dp8(tmp_path, elastic_clean_run):
    """Full dp in {2, 8} sweep: dp=4 preempted -> dp=2 resumes and is
    preempted AGAIN (its postmortem must carry the elastic_reshard
    event) -> dp=8 finishes; final params bit-identical to the
    uninterrupted dp=4 run."""
    p, ckpt, out = _run_child(tmp_path, 'sweep',
                              {'FT_MESH_DP': '4',
                               'PADDLE_TPU_FI_PREEMPT_AT_STEP': '7'})
    assert p.returncode == _sigterm_rc(), (p.returncode, p.stderr)

    pm2 = os.path.join(str(tmp_path), 'postmortem_dp2.json')
    p, _, out = _run_child(tmp_path, 'sweep_dp2',
                           {'FT_MESH_DP': '2',
                            'PADDLE_TPU_FI_PREEMPT_AT_STEP': '16',
                            'PADDLE_TPU_FLIGHT_DUMP': pm2},
                           reuse_ckpt=ckpt)
    assert p.returncode == _sigterm_rc(), (p.returncode, p.stderr)
    with open(pm2) as f:
        doc = json.load(f)
    kinds = [e['kind'] for e in doc['events']]
    assert 'elastic_reshard' in kinds    # the dp4 -> dp2 restore
    assert 'preempt' in kinds
    ev = next(e for e in doc['events'] if e['kind'] == 'elastic_reshard')
    assert ev['data']['from_topology'] == 'hosts=1 dp4'
    assert ev['data']['to_topology'] == 'hosts=1 dp2'

    # second elastic hop: dp2's checkpoints resume on dp=8 and finish
    p, _, out = _run_child(tmp_path, 'sweep_dp8', {'FT_MESH_DP': '8'},
                           reuse_ckpt=ckpt)
    assert p.returncode == 0, p.stderr
    _assert_bit_identical(elastic_clean_run, np.load(out))


# --------------------------------------------------- satellite regressions
def test_pallas_block_override_rounded_to_divisor():
    from paddle_tpu.ops.pallas.flash_attention import _pick_block
    assert _pick_block(256, 192) == 128   # non-pow2 override degrades
    assert _pick_block(256, 512) == 256
    assert _pick_block(64, 512) == 64
    assert _pick_block(96, 128) == 32     # halves below 128 to a divisor
    assert _pick_block(128, 128) == 128


def test_reader_state_pending_adjustment():
    r = R.CheckpointableReader(lambda: iter(range(10)))
    gen = r()
    for _ in range(4):
        next(gen)
    gen.close()
    assert r.state_dict()['offset'] == 4
    assert r.state_dict(pending=3)['offset'] == 1
    with pytest.raises(ValueError, match='pending'):
        r.state_dict(pending=5)
