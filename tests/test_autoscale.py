"""Self-healing autoscaling fleet (ISSUE 11): dynamic router
membership, hedged requests under a retry budget, the expired-deadline
admission fast path, the FleetController state machine (scale out/in,
heal with exponential backoff, crash-loop quarantine) driven on a
synthetic clock, fault.inject crash_loop / kill_replica(drain=True),
the /statusz fleet panel, metrics_report --fleet, the donation-safe
AOT warm start regression, and the bench.py autoscale chaos
acceptance contract."""

import json
import os
import subprocess
import sys
import threading
import time

from concurrent.futures import Future

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observe
from paddle_tpu.fault import inject
from paddle_tpu.observe.slo import Objective, SloTracker
from paddle_tpu.serving import (EngineClosedError, FleetController,
                                QueueFullError, Router, ServingEngine,
                                SLOShedError)
from paddle_tpu.serving.controller import (DEAD, DRAINING, QUARANTINED,
                                           UP)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _observe_clean():
    from paddle_tpu.observe import diagnostics
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.disable()
    observe.reset()
    with diagnostics._checks_lock:
        diagnostics._checks.clear()
    os.environ.pop('PADDLE_TPU_TRACE_SAMPLE', None)


def _save_mlp(dirname, in_dim=6):
    x = fluid.layers.data(name='x', shape=[in_dim], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    out = fluid.layers.fc(input=h, size=3, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(dirname, ['x'], [out], exe)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    return dirname


def _engine(model_dir, name, **kw):
    from paddle_tpu.inference import create_predictor
    pred = create_predictor(model_dir, place=fluid.CPUPlace())
    kw.setdefault('max_batch_size', 4)
    kw.setdefault('batch_timeout_ms', 1.0)
    eng = ServingEngine(pred, name=name, **kw)
    eng.warmup()
    eng.start()
    return eng


class FakeReplica(object):
    """Duck-typed replica. ``manual=True`` returns pending futures the
    test resolves by hand — deterministic hedge-race choreography."""

    def __init__(self, name, depth=0, ready=True, exc=None,
                 manual=False):
        self.name = name
        self._depth = depth
        self._ready = ready
        self.exc = exc
        self.manual = manual
        self.submitted = 0
        self.pending = []
        self.log = []

    def ready(self):
        return self._ready

    def queue_depth(self):
        return self._depth

    def submit(self, feed, ctx=None):
        self.submitted += 1
        if isinstance(self.exc, QueueFullError):
            raise self.exc
        f = Future()
        if self.manual:
            self.pending.append(f)
        elif self.exc is not None:
            f.set_exception(self.exc)
        else:
            f.set_result([self.name])
        return f

    def drain(self, timeout=None):
        self.log.append('drain')
        return True

    def shutdown(self, drain=True):
        self.log.append(('shutdown', drain))
        self._ready = False


# ---------------------------------------------------------- membership
def test_router_dynamic_membership():
    observe.enable()
    a, b = FakeReplica('a'), FakeReplica('b', depth=5)
    r = Router([a, b], session_affinity=False)
    c = FakeReplica('c')
    r.add_replica(c)
    assert [n for n, _ in r.replicas()] == ['a', 'b', 'c']
    with pytest.raises(ValueError):
        r.add_replica(FakeReplica('c'))          # names stay unique
    # removed replica takes no new work from this instant
    got = r.remove_replica('a')
    assert got is a
    for _ in range(4):
        assert r.predict({'x': 1})[0] in ('b', 'c')
    assert a.submitted == 0
    with pytest.raises(KeyError):
        r.remove_replica('nope')
    assert observe.get_counter('router.membership_changes_total',
                               change='add', route='serve') == 1
    assert observe.get_counter('router.membership_changes_total',
                               change='remove', route='serve') == 1
    r.close()


def test_router_excludes_draining_replica(tmp_path):
    """Drain-routing regression (ISSUE 11 satellite): a replica whose
    drain/shutdown has BEGUN — ready() False, queue empty, not full —
    must never appear in _candidates; scale-in retires it with zero
    new work routed on."""
    observe.enable()
    d = _save_mlp(str(tmp_path / 'm'))
    eng = _engine(d, 'retiree')
    healthy = FakeReplica('healthy')
    r = Router([eng, healthy], session_affinity=False)
    assert {n for n, _ in r._candidates()} == {'retiree', 'healthy'}
    # the moment drain/shutdown begins ready() flips; the replica is
    # not FULL (queue empty) — exclusion must key on readiness
    eng._draining = True
    assert eng.queue_depth() == 0
    assert eng.ready() is False
    assert [n for n, _ in r._candidates()] == ['healthy']
    assert r.predict({'x': 1}) == ['healthy']
    eng._draining = False
    eng.shutdown(drain=True)
    r.close()


# ---------------------------------------------------- deadline fast path
def test_router_expired_deadline_fast_path():
    """ISSUE 11 satellite: an already-exhausted deadline sheds
    synchronously in _admission_check — no dispatch, no retry-budget
    deposit or hedge token spent."""
    observe.enable()
    rep = FakeReplica('r0')
    r = Router([rep], hedge=True, hedge_delay_s=0.001,
               retry_budget=0.5, retry_budget_burst=4.0)
    tokens0 = r._budget.tokens
    with pytest.raises(SLOShedError):
        r.submit({'x': 1}, deadline_s=-0.5)
    with pytest.raises(QueueFullError):       # subclass contract holds
        r.submit({'x': 1}, deadline_s=-0.5)
    assert rep.submitted == 0                 # no dispatch consumed
    assert r._budget.tokens == tokens0        # no token moved
    assert observe.get_counter('router.shed_total',
                               reason='deadline_expired',
                               route='serve') == 2
    # a live deadline still admits
    assert r.predict({'x': 1}, deadline_s=30.0) == ['r0']
    r.close()


# ------------------------------------------------------------- hedging
def test_router_hedge_first_completion_wins():
    observe.enable()
    slow = FakeReplica('slow', manual=True)
    fast = FakeReplica('fast', depth=9)
    r = Router([slow, fast], hedge=True, hedge_delay_s=0.01,
               session_affinity=False, retries=1)
    fut = r.submit({'x': 1})
    assert slow.submitted == 1 and fast.submitted == 0
    deadline = time.perf_counter() + 5.0
    while fast.submitted == 0 and time.perf_counter() < deadline:
        time.sleep(0.005)                     # hedge timer fires
    assert fast.submitted == 1
    fast.pending = []                         # fast resolved instantly
    assert fut.result(5.0) == ['fast']        # first completion wins
    assert observe.get_counter('router.hedge_total',
                               route='serve') == 1
    assert observe.get_counter('router.hedge_wins_total',
                               winner='hedge', route='serve') == 1
    # the loser completing with the SAME payload is not a mismatch
    slow.pending[0].set_result(['fast'])
    assert observe.get_counter('router.hedge_mismatch_total',
                               route='serve') in (None, 0)
    r.close()


def test_router_hedge_mismatch_detected():
    observe.enable()
    a = FakeReplica('a', manual=True)
    b = FakeReplica('b', depth=9, manual=True)
    r = Router([a, b], hedge=True, hedge_delay_s=0.01,
               session_affinity=False)
    fut = r.submit({'x': 1})
    deadline = time.perf_counter() + 5.0
    while b.submitted == 0 and time.perf_counter() < deadline:
        time.sleep(0.005)
    a.pending[0].set_result([np.arange(3)])
    assert np.array_equal(fut.result(5.0)[0], np.arange(3))
    # the hedge completes with DIFFERENT bits: a determinism alarm
    b.pending[0].set_result([np.arange(3) + 1])
    assert observe.get_counter('router.hedge_mismatch_total',
                               route='serve') == 1
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'router_hedge_mismatch' in kinds
    r.close()


def test_router_retry_budget_bounds_hedges():
    """An empty token bucket suppresses hedging — retries can never
    amplify an overload."""
    observe.enable()
    slow1 = FakeReplica('s1', manual=True)
    slow2 = FakeReplica('s2', depth=9, manual=True)
    r = Router([slow1, slow2], hedge=True, hedge_delay_s=0.005,
               session_affinity=False, retry_budget=0.0,
               retry_budget_burst=1.0)
    futs = [r.submit({'x': i}) for i in range(3)]
    time.sleep(0.2)                # all three hedge timers fired
    # burst bought exactly ONE hedge; deposits are 0/request
    assert slow2.submitted == 1
    assert observe.get_counter('router.hedge_suppressed_total',
                               reason='budget', route='serve') == 2
    for f in slow1.pending + slow2.pending:
        f.set_result(['done'])
    for f in futs:
        assert f.result(5.0) == ['done']
    r.close()


def test_router_failover_chain_deaths_resolve_future():
    """Regression (review): _attempt_died must retire the dead
    attempt's outstanding slot even when its redispatch succeeds.
    With two replicas that BOTH die mid-request, the leaked slot used
    to make the final failure stash its exception instead of settling
    — predict() without a timeout blocked forever. The future must
    resolve with EngineClosedError."""
    observe.enable()
    a = FakeReplica('a', manual=True)
    b = FakeReplica('b', depth=5, manual=True)
    r = Router([a, b], session_affinity=False, retries=2)
    fut = r.submit({'x': 1})
    assert a.submitted == 1
    a.pending[0].set_exception(EngineClosedError('a died'))
    assert b.submitted == 1            # failover redispatch landed
    b.pending[0].set_exception(EngineClosedError('b died'))
    assert fut.done()                  # the pre-fix repro: stays False
    assert isinstance(fut.exception(timeout=5.0), EngineClosedError)
    assert observe.get_counter('router.failover_total', replica='a',
                               route='serve') == 1
    assert observe.get_counter('router.failover_total', replica='b',
                               route='serve') == 1
    r.close()


def test_router_failover_no_retry_paths_resolve_future():
    """The no-redispatch death paths settle too: retries exhausted,
    and an empty retry budget."""
    observe.enable()
    a = FakeReplica('a', manual=True)
    r = Router([a], session_affinity=False, retries=0)
    fut = r.submit({'x': 1})
    a.pending[0].set_exception(EngineClosedError('gone'))
    assert isinstance(fut.exception(timeout=5.0), EngineClosedError)
    r.close()
    c = FakeReplica('c', manual=True)
    d = FakeReplica('d', depth=5)
    r2 = Router([c, d], session_affinity=False, retries=2,
                retry_budget=0.0, retry_budget_burst=0.0)
    fut2 = r2.submit({'x': 1})
    c.pending[0].set_exception(EngineClosedError('gone'))
    assert isinstance(fut2.exception(timeout=5.0), EngineClosedError)
    assert d.submitted == 0            # no budget, no redispatch
    assert observe.get_counter('router.retry_budget_exhausted_total',
                               kind='failover', route='serve') == 1
    r2.close()


def test_router_hedge_nan_payloads_not_a_mismatch():
    """Bit-identical NaN-bearing outputs (a model that legitimately
    emits NaNs, the poison_nans chaos action) must not fire the
    hedge determinism alarm."""
    from paddle_tpu.serving.router import _results_equal
    nan_arr = np.array([1.0, np.nan, 3.0])
    assert _results_equal([nan_arr.copy()], [nan_arr.copy()])
    assert not _results_equal([nan_arr], [np.array([1.0, 2.0, 3.0])])
    # non-float dtypes take the equal_nan-free path (equal_nan raises
    # on them) and still compare correctly
    assert _results_equal([np.array(['x'])], [np.array(['x'])])
    assert not _results_equal([np.array([1, 2])], [np.array([1, 3])])
    observe.enable()
    a = FakeReplica('a', manual=True)
    b = FakeReplica('b', depth=9, manual=True)
    r = Router([a, b], hedge=True, hedge_delay_s=0.01,
               session_affinity=False)
    fut = r.submit({'x': 1})
    deadline = time.perf_counter() + 5.0
    while b.submitted == 0 and time.perf_counter() < deadline:
        time.sleep(0.005)
    a.pending[0].set_result([nan_arr.copy()])
    b.pending[0].set_result([nan_arr.copy()])
    fut.result(5.0)
    assert observe.get_counter('router.hedge_mismatch_total',
                               route='serve') in (None, 0)
    r.close()


def test_router_session_pins_stable_across_membership():
    """Rendezvous session pinning: a scale event only reassigns the
    sessions that hash onto the changed replica — everyone else keeps
    their pin (the old modulus scheme churned the whole keyspace)."""
    observe.enable()
    reps = {n: FakeReplica(n) for n in ('a', 'b', 'c')}
    r = Router(list(reps.values()))
    sessions = ['s%d' % i for i in range(40)]
    pin0 = {s: r._candidates(session=s)[0][0] for s in sessions}
    assert len(set(pin0.values())) > 1       # spread across the fleet
    victim = pin0[sessions[0]]
    removed = r.remove_replica(victim)
    for s in sessions:
        if pin0[s] != victim:                # untouched by the change
            assert r._candidates(session=s)[0][0] == pin0[s]
    r.add_replica(removed)                   # and adding it back
    assert {s: r._candidates(session=s)[0][0]
            for s in sessions} == pin0       # restores every pin
    r.close()


def test_slo_predicted_quantile():
    t = SloTracker([Objective('q', 1.0, window_s=60.0)])
    now = time.perf_counter()
    for i in range(100):
        t.record('q', (i + 1) / 100.0, now=now)
    assert t.predicted_quantile('q', 0.95, now=now) == \
        pytest.approx(0.96)
    assert t.predicted_p99('q', now=now) == pytest.approx(1.0)
    with pytest.raises(ValueError):
        t.predicted_quantile('q', 1.5)


# ------------------------------------------------------ fleet controller
def _fleet(n=2, slo=None, **ctl_kw):
    reps = [FakeReplica('r%d' % i) for i in range(n)]
    router = Router(reps, slo=slo, admission='none',
                    session_affinity=False)
    spawned = []

    def factory(name):
        rep = FakeReplica(name)
        spawned.append(rep)
        return rep

    ctl = FleetController(router, factory, slo=slo, **ctl_kw)
    return router, ctl, reps, spawned


def test_controller_scale_out_on_pressure_and_cooldown():
    observe.enable()
    tracker = SloTracker([Objective('serve', 0.05, window_s=5.0)])
    router, ctl, reps, spawned = _fleet(
        2, slo=tracker, min_replicas=2, max_replicas=4,
        burn_high=1.0, scale_out_cooldown_s=1.0, trough_s=1e9)
    now = time.perf_counter()
    for _ in range(50):
        tracker.record('serve', 0.5, ok=False, now=now)
    ctl.step(now=now + 0.3)
    assert len(spawned) == 1                   # pressure -> one spawn
    assert len(router.replicas()) == 3         # registered after ready
    ctl.step(now=now + 0.5)                    # inside cooldown
    assert len(spawned) == 1
    ctl.step(now=now + 1.5)                    # cooldown over
    assert len(spawned) == 2
    ctl.step(now=now + 3.0)
    assert len(spawned) == 2                   # max_replicas=4 caps it
    assert ctl.census()[UP] == 4
    assert observe.get_counter('controller.scale_out_total',
                               route='serve', reason='burn_rate') == 2
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'controller_scale_out' in kinds
    ctl.close()
    router.close()


def test_controller_scale_in_drains_before_shutdown():
    observe.enable()
    router, ctl, reps, spawned = _fleet(
        3, min_replicas=1, trough_s=1.0, scale_in_cooldown_s=0.1,
        queue_low=2.0)
    reps[0]._depth = 3                         # least-loaded is r1/r2
    now = time.perf_counter()
    ctl.step(now=now)                          # trough starts
    assert ctl.census()[UP] == 3
    ctl.step(now=now + 1.2)                    # sustained -> scale in
    assert ctl.census()[UP] == 2
    victim = next(rep for rep in reps if rep.log)
    assert victim is not reps[0]               # least-loaded picked
    # zero-loss ordering: deregistered, DRAINED, then shut down
    assert victim.log == ['drain', ('shutdown', True)]
    assert victim.name not in [n for n, _ in router.replicas()]
    assert observe.get_counter('controller.scale_in_total',
                               route='serve') == 1
    # min_replicas floor: another sustained trough cannot go below 1
    ctl.step(now=now + 2.5)
    ctl.step(now=now + 4.0)
    assert ctl.census()[UP] >= 1
    ctl.close()
    router.close()


def test_controller_phase_pool_custom_pressure():
    """Per-phase scaling (ISSUE 14): a FleetController driving ONE
    phase of a PhaseRouter through its pool() adapter, scaling on a
    pluggable pressure_fn/calm_fn pair (the page-pressure policy's
    shape) instead of the SLO/queue-depth default."""
    from paddle_tpu.serving import PhaseRouter
    observe.enable()
    d0 = FakeReplica('d0')
    pr = PhaseRouter([], [d0], colocated=True, route='px')
    pool = pr.pool('decode')
    assert pool.route == 'px/decode'
    spawned = []

    def factory(name):
        rep = FakeReplica(name)
        spawned.append(rep)
        return rep

    box = {'frac': 0.9}

    def press(now):
        hot = box['frac'] < 0.15
        return hot, 'page_pressure' if hot else None, \
            {'free_page_frac': box['frac'], 'mean_queue_depth': 0.0,
             'burn_rate': None}

    def calm(signals):
        return signals['free_page_frac'] > 0.5

    ctl = FleetController(pool, factory, min_replicas=1,
                          max_replicas=3, scale_out_cooldown_s=0.0,
                          trough_s=0.5, scale_in_cooldown_s=0.0,
                          pressure_fn=press, calm_fn=calm)
    now = time.perf_counter()
    ctl.step(now=now)
    assert spawned == []                       # calm: no spawn
    box['frac'] = 0.05                         # page pressure
    ctl.step(now=now + 1.0)
    assert len(spawned) == 1                   # scaled the decode pool
    assert len(pr.members('decode')) == 2
    assert pr.members('prefill') == []         # other phase untouched
    assert observe.get_counter('controller.scale_out_total',
                               route='px/decode',
                               reason='page_pressure') == 1
    box['frac'] = 0.9                          # sustained calm
    ctl.step(now=now + 2.0)                    # trough starts
    ctl.step(now=now + 3.0)                    # sustained -> scale in
    assert len(pr.members('decode')) == 1
    ctl.close()
    pr.close()


def test_controller_heal_backoff_quarantine_cycle():
    observe.enable()
    router, ctl, reps, spawned = _fleet(
        2, min_replicas=1, max_replicas=3, backoff_base_s=0.5,
        crash_loop_threshold=2, crash_window_s=30.0, quarantine_s=60.0,
        trough_s=1e9)
    now = time.perf_counter()
    # death detected, replacement held until the backoff expires
    reps[0]._ready = False
    ctl.step(now=now)
    assert ctl.states()['r0'] == DEAD
    assert 'r0' not in [n for n, _ in router.replicas()]
    ctl.step(now=now + 0.3)                    # inside 0.5s backoff
    assert not spawned
    ctl.step(now=now + 0.6)
    assert len(spawned) == 1                   # healed
    assert spawned[0].name == 'r0-r1'
    assert ctl.states()['r0-r1'] == UP
    assert observe.get_counter('controller.heals_total',
                               route='serve', lineage='r0') == 1
    # the replacement dies too: 2 deaths in window -> quarantine, no
    # more restarts, census marker visible
    spawned[0]._ready = False
    ctl.step(now=now + 1.0)
    ctl.step(now=now + 5.0)
    states = ctl.states()
    assert states.get('r0[quarantined]') == QUARANTINED
    assert len(spawned) == 1                   # breaker stopped spawns
    assert ctl.current('r0') is None
    assert observe.get_counter('controller.quarantines_total',
                               route='serve', lineage='r0') == 1
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'controller_quarantine' in kinds
    # quarantine served: one fresh chance with a clean ledger
    ctl.step(now=now + 70.0)
    assert len(spawned) == 2
    assert ctl.current('r0') is spawned[1]
    assert 'r0[quarantined]' not in ctl.states()
    ctl.close()
    router.close()


# -------------------------------------------------------- fault helpers
def test_kill_replica_drain_true_completes_accepted(tmp_path):
    """ISSUE 11 satellite: kill_replica(drain=True) — the graceful
    half of the chaos helper — completes every accepted request, flips
    the corpse's /readyz, and leaves the drain flag on the flight
    event."""
    from paddle_tpu.observe.diagnostics import run_health_checks

    observe.enable()
    d = _save_mlp(str(tmp_path / 'm'))
    eng = _engine(d, 'g0', max_queue_depth=32)
    rng = np.random.RandomState(0)
    futs = [eng.submit({'x': rng.rand(2, 6).astype('float32')})
            for _ in range(8)]
    inject.kill_replica(eng, drain=True)
    for f in futs:                         # drained, never abandoned
        assert len(f.result(10.0)) == 1
    assert eng.ready() is False
    ok, checks = run_health_checks(include_readiness=True)
    assert checks['serving.g0']['ok'] is False
    ev = [e for e in observe.flight_recorder().events()
          if e['kind'] == 'replica_kill'][-1]
    assert ev['data']['drain'] is True


def test_crash_loop_aims_at_lineage():
    observe.enable()
    victims = [FakeReplica('v0'), FakeReplica('v0-r1')]
    feed = iter(victims + [None, None])
    killed = inject.crash_loop(lambda: next(feed), kills=4,
                               interval_s=0.01)
    assert killed == 2                     # benched slot stops yielding
    assert all(not v.ready() for v in victims)
    evs = [e for e in observe.flight_recorder().events()
           if e['kind'] == 'crash_loop_kill']
    assert len(evs) == 2
    assert [e['data']['replica'] for e in evs] == ['v0', 'v0-r1']
    assert observe.get_counter('fault.replica_kills_total',
                               replica='v0') == 1


# ------------------------------------------------------- /statusz panel
def test_statusz_fleet_panel():
    from paddle_tpu.observe import diagnostics

    observe.enable()
    router, ctl, reps, spawned = _fleet(
        2, min_replicas=1, backoff_base_s=0.01,
        crash_loop_threshold=1, quarantine_s=60.0, trough_s=1e9)
    now = time.perf_counter()
    reps[0]._ready = False
    ctl.step(now=now)
    ctl.step(now=now + 1.0)                # threshold 1 -> quarantine
    doc = diagnostics._statusz_doc()
    fleet = doc['fleet']
    assert fleet['replicas']['r1'] == UP
    assert fleet['replicas']['r0[quarantined]'] == QUARANTINED
    assert fleet['census']['up'] == 1
    assert fleet['census']['quarantined'] == 1
    assert fleet['quarantines_total'] == 1
    assert fleet['deaths_total'] == 1
    assert fleet['replicas_ready'] == 1
    ctl.close()
    router.close()


# -------------------------------------------------- metrics_report --fleet
def test_metrics_report_fleet_json(tmp_path):
    """CLI satellite: --fleet reconstructs the scale timeline from a
    metrics JSONL, stdlib-only (no jax import), --json schema stable."""
    observe.enable(jsonl=str(tmp_path / 'm.jsonl'))
    observe.set_gauge('controller.replicas', 2, state='up',
                      route='serve')
    observe.set_gauge('controller.replicas', 0, state='quarantined',
                      route='serve')
    observe.set_gauge('controller.replica_state', 0, replica='r0',
                      route='serve')
    observe.inc('router.requests_total', 40, route='serve')
    observe.inc('router.hedge_total', 2, route='serve')
    observe.inc('router.dispatch_total', 42, replica='r0',
                route='serve')
    observe.flush(kind='snapshot')
    observe.inc('controller.scale_out_total', route='serve',
                reason='burn_rate')
    observe.set_gauge('controller.replicas', 3, state='up',
                      route='serve')
    observe.set_gauge('controller.replica_state', 2,
                      replica='r1[quarantined]', route='serve')
    observe.inc('controller.quarantines_total', route='serve',
                lineage='r1')
    observe.flush(kind='summary')

    tool = os.path.join(REPO, 'tools', 'metrics_report.py')
    r = subprocess.run(
        [sys.executable, tool, str(tmp_path / 'm.jsonl'), '--fleet',
         '--json'],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert len(doc['census_timeline']) == 2
    assert doc['census_timeline'][0]['census']['serve']['up'] == 2
    assert doc['census_timeline'][1]['census']['serve']['up'] == 3
    assert doc['scale_events'] == [
        {'t': doc['scale_events'][0]['t'], 'scale_out': 1,
         'quarantines': 1}]
    assert doc['replicas']['r0'] == 'UP'
    assert doc['replicas']['r1[quarantined]'] == 'QUARANTINED'
    assert doc['totals']['scale_out_total'] == 1
    assert doc['hedge']['hedges'] == 2
    assert doc['hedge']['hedge_fraction'] == pytest.approx(0.05)
    # human rendering names the timeline sections
    r2 = subprocess.run(
        [sys.executable, tool, str(tmp_path / 'm.jsonl'), '--fleet'],
        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0, r2.stderr
    assert 'fleet controller timeline' in r2.stdout
    assert 'scale_out +1' in r2.stdout
    # no jax import on the --fleet path
    probe = subprocess.run(
        [sys.executable, '-c',
         'import importlib.util, sys\n'
         'spec = importlib.util.spec_from_file_location("mr", %r)\n'
         'm = importlib.util.module_from_spec(spec)\n'
         'spec.loader.exec_module(m)\n'
         'assert m.main([%r, "--fleet"]) == 0\n'
         'assert "jax" not in sys.modules\n'
         % (tool, str(tmp_path / 'm.jsonl'))],
        capture_output=True, text=True, timeout=60)
    assert probe.returncode == 0, probe.stderr


# --------------------------------------------- donation-safe warm start
def test_warm_started_executable_cannot_corrupt_scope(tmp_path):
    """Regression for the AOT warm-start corruption the hedge
    bit-identity contract caught: a deserialized executable's donation
    bookkeeping does not survive serialize/deserialize, so its
    in-place writes could trash buffers the scope still references.
    Executor._donation_safe hands it private copies — repeated calls
    through the wrapper must keep giving identical bits while the
    caller's original arrays stay intact."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import aot_cache
    from paddle_tpu.core.executor import Executor as Exe

    def step(scope_vals, feed_vals, step_i):
        out = {k: v * 2.0 + feed_vals['x'][0]
               for k, v in scope_vals.items()}
        return [out['w'].sum()], out

    jitted = jax.jit(step, donate_argnums=(0,))
    scope0 = {'w': jnp.arange(8, dtype=jnp.float32),
              'b': jnp.ones(4, dtype=jnp.float32)}
    feed = {'x': jnp.full((2,), 3.0, dtype=jnp.float32)}
    exe = jitted.lower(scope0, feed, np.int32(0)).compile()
    os.environ['PADDLE_TPU_AOT_CACHE_DIR'] = str(tmp_path)
    try:
        assert aot_cache.save('regress', exe) is not None
        loaded, status = aot_cache.load('regress')
        assert status == 'loaded'
        call = Exe._donation_safe(loaded)
        keep = {k: jnp.array(v, copy=True) for k, v in scope0.items()}
        ref = None
        for _ in range(6):
            fetches, new_scope = call(keep, feed, np.int32(0))
            got = np.asarray(fetches[0])
            if ref is None:
                ref = got
            assert np.array_equal(got, ref)    # bit-stable across calls
            # the donated-arg COPIES protect the caller's arrays
            assert np.array_equal(np.asarray(keep['w']),
                                  np.arange(8, dtype=np.float32))
    finally:
        os.environ.pop('PADDLE_TPU_AOT_CACHE_DIR', None)


# ----------------------------------------------- autoscale chaos bench
def test_bench_autoscale_chaos_acceptance(tmp_path):
    """Acceptance: bench.py --workload autoscale passes all three
    chaos scenarios — flash-crowd scale-up before the error budget
    burns through, crash-loop quarantine with goodput recovering on
    the survivors, trough scale-in with zero request loss — and the
    hedging contract: retry dispatches inside the token budget, zero
    hedge/primary mismatches. The JSONL reconstructs the timeline via
    metrics_report --fleet."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    jsonl = str(tmp_path / 'autoscale.jsonl')
    observe.enable(jsonl=jsonl)
    r = bench.bench_autoscale(flash_duration=3.0, crash_duration=3.5,
                              trough_duration=3.5, window_s=1.0)
    observe.flush(kind='summary')

    flash = r['flash_crowd']
    assert flash['scale_outs'] >= 1          # the controller reacted
    assert flash['census_peak'][UP] > 2      # capacity actually landed
    assert flash['lost'] == 0                # zero accepted-request loss
    assert flash['burn_peak'] > 1.0          # the spike burned budget
    assert flash['burn_end'] < 1.0           # and scale-up recovered it

    crash = r['crash_loop']
    assert crash['kills_performed'] >= 2
    assert crash['quarantines'] >= 1         # the breaker engaged
    assert crash['heals'] >= 1               # after healing at least once
    assert crash['lost'] == 0
    assert crash['goodput_end_rps'] > 0.0    # survivors carried traffic
    assert crash['census_peak'][QUARANTINED] >= 1

    trough = r['trough']
    assert trough['scale_ins'] >= 1
    assert trough['lost'] == 0
    assert trough['requests_errored'] == 0   # drain lost nothing
    assert trough['drain_timeouts'] == 0

    hedge = r['hedge']
    assert hedge['within_budget'] is True    # bounded by construction
    assert hedge['retry_dispatches'] <= hedge['bound']
    assert hedge['mismatches'] == 0          # bit-identical hedges

    # the scale timeline reconstructs offline from the JSONL
    tool = os.path.join(REPO, 'tools', 'metrics_report.py')
    rep = subprocess.run(
        [sys.executable, tool, jsonl, '--fleet', '--json'],
        capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stderr
    doc = json.loads(rep.stdout)
    assert len(doc['census_timeline']) >= 3
    assert any('scale_out' in ev for ev in doc['scale_events'])
    assert any('scale_in' in ev for ev in doc['scale_events'])
    assert any('quarantines' in ev for ev in doc['scale_events'])
    assert doc['hedge']['mismatches'] == 0
    # quarantine forensics: the flight event fired and survived (the
    # flash scenario's scale_out events may have been evicted from the
    # bounded ring by its shed storm — the counters above prove those)
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'controller_quarantine' in kinds
    assert 'controller_scale_in' in kinds
