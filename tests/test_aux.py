"""Aux subsystems: errors, flags, lod, debug, memory_optimize, datasets,
profiler (reference: platform/enforce.h, fluid/debuger.py,
memory_optimization_transpiler.py, v2/dataset tests)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from util import run_startup_and, rand


def test_enforce():
    from paddle_tpu.core.errors import enforce, enforce_shape_match, \
        EnforceError
    enforce(True, 'fine')
    with pytest.raises(EnforceError):
        enforce(False, 'bad %d', 7)
    enforce_shape_match((None, 3), (8, 3))
    with pytest.raises(EnforceError):
        enforce_shape_match((2, 3), (3, 3))


def test_flags_env(monkeypatch):
    from paddle_tpu.core import flags
    monkeypatch.setenv('PADDLE_TPU_V', '3')
    got = flags.init_flags({'benchmark': True})
    assert got['v'] == 3 and got['benchmark'] is True
    with pytest.raises(KeyError):
        flags.set_flag('nope', 1)


def test_lod_pad_roundtrip():
    from paddle_tpu.core.lod import (pad_sequences, unpad_sequences,
                                     create_lod_tensor, bucket_length)
    seqs = [[1, 2, 3], [4], [5, 6]]
    padded, lengths = pad_sequences(seqs, pad_value=0)
    assert padded.shape == (3, 3)
    np.testing.assert_array_equal(lengths, [3, 1, 2])
    back = unpad_sequences(padded, lengths)
    for a, b in zip(back, seqs):
        np.testing.assert_array_equal(a, b)
    padded2, lengths2 = create_lod_tensor(
        np.arange(6), [[3, 1, 2]])
    np.testing.assert_array_equal(lengths2, [3, 1, 2])
    assert bucket_length(33) == 64


def test_debug_program_printer(tmp_path):
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out = fluid.layers.fc(input=x, size=2)
    code = fluid.debug.program_to_code()
    assert 'mul' in code and 'x[float32' in code
    dot = fluid.debug.draw_block_graphviz(
        fluid.default_main_program().global_block(),
        path=str(tmp_path / 'g.dot'))
    assert 'digraph' in open(dot).read()


def test_memory_optimize_remat_still_correct():
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=16, act='relu')
    pred = fluid.layers.fc(input=h, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    fluid.memory_optimize(level=1)
    assert fluid.default_main_program().remat_policy == 'full'
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xs = rng.rand(16, 8).astype('float32')
    ys = xs.sum(1, keepdims=True).astype('float32')
    losses = [float(np.asarray(exe.run(feed={'x': xs, 'y': ys},
                                       fetch_list=[loss])[0]).reshape(()))
              for _ in range(20)]
    assert losses[-1] < losses[0]


def test_new_datasets_schemas():
    from paddle_tpu.dataset import (conll05, sentiment, wmt16, flowers,
                                    voc2012, mq2007)
    item = next(iter(conll05.train()()))
    assert len(item) == 9 and len(item[0]) == len(item[8])
    toks, label = next(iter(sentiment.train()()))
    assert label in (0, 1) and len(toks) >= 8
    src, trg_in, trg_next = next(iter(wmt16.train()()))
    assert trg_in[0] == 0 and trg_next[-1] == 1
    assert len(trg_in) == len(trg_next)
    img, label = next(iter(flowers.train()()))
    assert img.shape == (3, 32, 32) and 0 <= label < flowers.CLASS_NUM
    img, seg = next(iter(voc2012.train()()))
    assert seg.shape == img.shape[1:]
    better, worse = next(iter(mq2007.train(format='pairwise')()))
    assert better.shape == (mq2007.FEATURE_DIM,)
    feats, rel = next(iter(mq2007.train(format='listwise')()))
    assert feats.shape[0] == len(rel)


def test_profiler_context():
    with fluid.profiler.profiler('CPU', 'total'):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        out = fluid.layers.fc(input=x, size=2)
        run_startup_and({'x': rand(2, 4)}, [out])


def test_compile_cache_env_override_and_optout(monkeypatch, tmp_path):
    """arm_compile_cache honors JAX_COMPILATION_CACHE_DIR and the
    compile_cache flag opt-out (PADDLE_TPU_COMPILE_CACHE=false)."""
    import jax

    from paddle_tpu.core import platform_boot as pb
    from paddle_tpu.core.flags import FLAGS, get_flag
    prev_dir = jax.config.jax_compilation_cache_dir
    try:
        # explicit flag opt-in: CPU backends only arm when the flag is
        # explicitly true (the XLA:CPU AOT cache is unsafe on
        # feature-mismatched hosts — see arm_compile_cache)
        monkeypatch.delenv('PADDLE_TPU_COMPILE_CACHE', raising=False)
        get_flag('compile_cache')  # populate FLAGS before setitem
        monkeypatch.setitem(FLAGS, 'compile_cache', True)
        monkeypatch.setattr(pb, '_cache_armed', False)
        monkeypatch.setenv('JAX_COMPILATION_CACHE_DIR',
                           str(tmp_path / 'c'))
        pb.arm_compile_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / 'c')
        # opt-out: with the flag False a fresh arm leaves config alone
        monkeypatch.setattr(pb, '_cache_armed', False)
        monkeypatch.setitem(FLAGS, 'compile_cache', False)
        monkeypatch.setenv('JAX_COMPILATION_CACHE_DIR',
                           str(tmp_path / 'd'))
        pb.arm_compile_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / 'c')
    finally:
        # jax.config state is session-global; restore it (monkeypatch
        # only unwinds env vars and attrs)
        jax.config.update('jax_compilation_cache_dir', prev_dir)
