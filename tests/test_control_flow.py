"""Control flow: While, StaticRNN, DynamicRNN, IfElse, arrays (reference:
fluid/tests/unittests/test_while_op.py, test_recurrent_op.py,
test_dyn_rnn.py, test_if_else_op.py)."""

import numpy as np

import paddle_tpu as fluid
from util import run_startup_and, rand


def test_static_rnn_cumsum():
    x = fluid.layers.data(name='x', shape=[5, 3], dtype='float32')
    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        mem = rnn.memory(batch_ref=x, shape=[3], value=0.0)
        acc = fluid.layers.elementwise_add(x=mem, y=xt)
        rnn.update_memory(mem, acc)
        rnn.step_output(acc)
    out = rnn()
    xs = rand(2, 5, 3, seed=0)
    got = run_startup_and({'x': xs}, [out])[0]
    np.testing.assert_allclose(got, np.cumsum(xs, axis=1), rtol=1e-5)


def test_while_countdown():
    i = fluid.layers.fill_constant(shape=[1], dtype='int64', value=0)
    limit = fluid.layers.fill_constant(shape=[1], dtype='int64', value=5)
    cond = fluid.layers.less_than(x=i, y=limit)
    total = fluid.layers.fill_constant(shape=[1], dtype='float32',
                                       value=0.0)
    w = fluid.layers.While(cond=cond)
    with w.block():
        fluid.layers.increment(x=i, value=1, in_place=True)
        fluid.layers.increment(x=total, value=2.0, in_place=True)
        fluid.layers.less_than(x=i, y=limit, cond=cond)
    got = run_startup_and({}, [total, i])
    np.testing.assert_allclose(got[0], [10.0])
    np.testing.assert_array_equal(got[1], [5])


def test_if_else_per_example_select():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    zeros = fluid.layers.fill_constant_batch_size_like(
        x, shape=[1, 1], dtype='float32', value=0.0)
    row_sum = fluid.layers.reduce_sum(x, dim=1, keep_dim=True)
    cond = fluid.layers.less_than(x=zeros, y=row_sum)  # sum > 0
    ie = fluid.layers.IfElse(cond)
    with ie.true_block():
        ie.output(fluid.layers.scale(x, scale=2.0))
    with ie.false_block():
        ie.output(fluid.layers.scale(x, scale=-1.0))
    out, = ie()
    xs = np.array([[1, 1, 1], [-1, -1, -1]], dtype='float32')
    got = run_startup_and({'x': xs}, [out])[0]
    np.testing.assert_allclose(got[0], xs[0] * 2.0)
    np.testing.assert_allclose(got[1], -xs[1])


def test_if_else_branch_reads_outer_constant():
    """Regression: a var read ONLY inside a sub-block must keep its producer
    alive through pruning (prune walks sub-blocks like fluid prune.cc)."""
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    zeros = fluid.layers.fill_constant_batch_size_like(
        x, shape=[1, 1], dtype='float32', value=0.0)
    # Produced at the parent level, consumed only inside the true branch.
    bias = fluid.layers.fill_constant(shape=[3], dtype='float32', value=7.0)
    row_sum = fluid.layers.reduce_sum(x, dim=1, keep_dim=True)
    cond = fluid.layers.less_than(x=zeros, y=row_sum)
    ie = fluid.layers.IfElse(cond)
    with ie.true_block():
        ie.output(fluid.layers.elementwise_add(x=x, y=bias))
    with ie.false_block():
        ie.output(fluid.layers.scale(x, scale=-1.0))
    out, = ie()
    xs = np.array([[1, 1, 1], [-1, -1, -1]], dtype='float32')
    got = run_startup_and({'x': xs}, [out])[0]
    np.testing.assert_allclose(got[0], xs[0] + 7.0)
    np.testing.assert_allclose(got[1], -xs[1])


def test_program_prune_keeps_sub_block_producers():
    """Program.prune (save_inference_model path) must also walk sub-blocks."""
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    zeros = fluid.layers.fill_constant_batch_size_like(
        x, shape=[1, 1], dtype='float32', value=0.0)
    bias = fluid.layers.fill_constant(shape=[3], dtype='float32', value=7.0)
    cond = fluid.layers.less_than(
        x=zeros, y=fluid.layers.reduce_sum(x, dim=1, keep_dim=True))
    ie = fluid.layers.IfElse(cond)
    with ie.true_block():
        ie.output(fluid.layers.elementwise_add(x=x, y=bias))
    with ie.false_block():
        ie.output(fluid.layers.scale(x, scale=-1.0))
    out, = ie()
    pruned = fluid.default_main_program().prune([out])
    kept_types = [op.type for op in pruned.global_block().ops]
    assert 'fill_constant' in kept_types  # bias producer must survive


def test_while_body_reads_outer_constant():
    """Same regression through a While sub-block."""
    i = fluid.layers.fill_constant(shape=[1], dtype='int64', value=0)
    limit = fluid.layers.fill_constant(shape=[1], dtype='int64', value=3)
    step = fluid.layers.fill_constant(shape=[1], dtype='float32', value=2.5)
    cond = fluid.layers.less_than(x=i, y=limit)
    total = fluid.layers.fill_constant(shape=[1], dtype='float32', value=0.0)
    w = fluid.layers.While(cond=cond)
    with w.block():
        fluid.layers.increment(x=i, value=1, in_place=True)
        acc = fluid.layers.elementwise_add(x=total, y=step)
        fluid.layers.assign(acc, total)
        fluid.layers.less_than(x=i, y=limit, cond=cond)
    got = run_startup_and({}, [total])[0]
    np.testing.assert_allclose(got, [7.5])


def test_dynamic_rnn_respects_lengths():
    x = fluid.layers.data(name='x', shape=[4, 2], dtype='float32')
    length = fluid.layers.data(name='len', shape=[], dtype='int64')
    drnn = fluid.layers.DynamicRNN(length=length)
    with drnn.block():
        xt = drnn.step_input(x)
        mem = drnn.memory(batch_ref=x, shape=[2], value=0.0)
        acc = fluid.layers.elementwise_add(x=mem, y=xt)
        drnn.update_memory(mem, acc)
        drnn.output(acc)
    out = drnn()
    xs = np.ones((2, 4, 2), dtype='float32')
    lens = np.array([2, 4], dtype='int64')
    got = run_startup_and({'x': xs, 'len': lens}, [out])[0]
    # example 0: cumsum stops after t=1; later outputs masked to 0
    np.testing.assert_allclose(got[0, :, 0], [1, 2, 0, 0])
    np.testing.assert_allclose(got[1, :, 0], [1, 2, 3, 4])


def test_array_write_read():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    i0 = fluid.layers.fill_constant(shape=[1], dtype='int64', value=0)
    i1 = fluid.layers.fill_constant(shape=[1], dtype='int64', value=1)
    arr = fluid.layers.array_write(x, i0)
    fluid.layers.array_write(fluid.layers.scale(x, 3.0), i1, array=arr)
    r0 = fluid.layers.array_read(arr, i0)
    r1 = fluid.layers.array_read(arr, i1)
    xs = rand(2, 3, seed=1)
    got = run_startup_and({'x': xs}, [r0, r1])
    np.testing.assert_allclose(got[0], xs, rtol=1e-6)
    np.testing.assert_allclose(got[1], xs * 3.0, rtol=1e-6)


def test_error_clip_inside_rnn_sub_block():
    """var.error_clip set on a StaticRNN step var clamps the cotangent
    inside the scan body (the sub-block lowering applies the same
    cotangent clamp as the global block; regression: it was silently
    ignored there)."""
    def build(clip):
        fluid.reset_default_programs()
        x = fluid.layers.data(name='x', shape=[3, 4], dtype='float32')
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            mem = rnn.memory(batch_ref=x, shape=[4], value=0.0)
            h = fluid.layers.fc(input=[xt, mem], size=4, bias_attr=False,
                                param_attr=[fluid.ParamAttr(name='rx_w'),
                                            fluid.ParamAttr(name='rh_w')])
            if clip:
                h.error_clip = fluid.clip.ErrorClipByValue(max=1e-4)
            rnn.update_memory(mem, h)
            rnn.step_output(h)
        out = rnn()
        loss = fluid.layers.reduce_sum(
            fluid.layers.scale(out, scale=1000.0))
        fluid.optimizer.SGD(learning_rate=1.0).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        scope = fluid.global_scope()
        w0 = np.asarray(scope.find('rx_w'))
        xs = np.ones((2, 3, 4), 'f')
        exe.run(feed={'x': xs}, fetch_list=[loss])
        return float(np.abs(w0 - np.asarray(scope.find('rx_w'))).max())

    s1, s2 = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(s1):
        dw_unclipped = build(clip=False)
    with fluid.scope_guard(s2):
        dw_clipped = build(clip=True)
    # cotangent ~1000 unclipped vs 1e-4 clipped: orders of magnitude
    assert dw_unclipped > 1e2, dw_unclipped
    assert dw_clipped < 1e-1, dw_clipped
