"""Quantization end-to-end (ISSUE 13): blockwise int8 numerics
(stochastic-rounding unbiasedness), the real shard_map
quantized_all_reduce vs exact psum, the O(log n) ppermute broadcast,
int8-gradient-allreduce convergence + per-call env knob on the
trainer path, the PTQ Program rewrite (parity, calibration threshold,
contract pass), and the quantized paged KV arena (concurrent ==
sequential at int8, attention parity, off-by-default bit-identity,
zero post-warmup recompiles)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import quant
from paddle_tpu.quant import core as qcore

DP = 4


def _mesh(n=DP):
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ('dp',))


def _shard_map(fn, mesh, n_in=1):
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    spec = P('dp', None)
    return shard_map(fn, mesh=mesh, in_specs=(spec,) * n_in,
                     out_specs=spec)


# ------------------------------------------------- blockwise numerics
def test_stochastic_rounding_unbiased():
    """E[dequant(quant(x))] == x under stochastic rounding; the
    deterministic rounder is biased on off-grid values (that bias is
    exactly why gradient traffic wants the stochastic mode)."""
    v = np.array([0.3, -1.7, 0.031, 100.0, -0.26, 55.5],
                 dtype='float32')
    outs = np.stack([
        np.asarray(qcore.qdq(jnp.asarray(v), block=8,
                             key=jax.random.PRNGKey(i)))
        for i in range(400)])
    # scale = 100/127 ~ 0.79; mean over 400 draws converges ~ s/sqrt(n)
    assert np.abs(outs.mean(axis=0) - v).max() < 0.12
    det = np.asarray(qcore.qdq(jnp.asarray(v), block=8))
    # deterministic: 0.3 rounds to 0 at this scale — bias ~ 0.3
    assert np.abs(det - v).max() > 0.2


def test_quantize_blockwise_round_trip_and_pad():
    x = np.random.RandomState(0).randn(3, 37).astype('float32')
    q, s = qcore.quantize_blockwise(jnp.asarray(x), block=16)
    assert np.asarray(q).dtype == np.int8
    back = np.asarray(qcore.dequantize_blockwise(q, s, shape=x.shape))
    assert back.shape == x.shape
    rel = np.abs(back - x).max() / np.abs(x).max()
    assert rel < 2.0 / 127
    # an all-zero tensor stays exactly zero (scale floor, no NaN)
    z = np.asarray(qcore.qdq(jnp.zeros((5, 5), 'float32')))
    assert np.array_equal(z, np.zeros((5, 5), 'float32'))


# ------------------------------------------ collectives (shard_map)
def test_quantized_all_reduce_matches_psum():
    from paddle_tpu.parallel import collective
    mesh = _mesh()
    x = np.random.RandomState(0).randn(DP, 500).astype('float32')
    exact = np.tile(x.sum(0, keepdims=True), (DP, 1))

    for key in (None, jax.random.PRNGKey(5)):
        f = _shard_map(
            lambda a, _k=key: collective.quantized_all_reduce(
                a.reshape(-1), 'dp', key=_k).reshape(a.shape), mesh)
        got = np.asarray(jax.jit(f)(x))
        rel = np.abs(got - exact).max() / np.abs(exact).max()
        assert rel < 0.05, rel
        # the reduced tensor must be IDENTICAL on every device — the
        # requantized-shard all_gather guarantees it by construction
        for d in range(1, DP):
            assert np.array_equal(got[0], got[d])

    # mean op + a size that is neither block- nor dp-divisible
    y = np.random.RandomState(1).randn(DP, 37).astype('float32')
    g = _shard_map(
        lambda a: collective.quantized_all_reduce(
            a.reshape(-1), 'dp', op='mean', block=16).reshape(a.shape),
        mesh)
    gm = np.asarray(jax.jit(g)(y))
    em = np.tile(y.mean(0, keepdims=True), (DP, 1))
    assert np.abs(gm - em).max() / np.abs(em).max() < 0.05


def test_broadcast_ppermute_formulation():
    """broadcast == root's value everywhere, for roots != 0 and a
    non-power-of-two axis (the recursive-doubling select covers both)."""
    from paddle_tpu.parallel import collective
    for n, root in ((4, 0), (4, 2), (3, 1)):
        mesh = _mesh(n)
        x = np.arange(2 * n, dtype='float32').reshape(n, 2)
        f = _shard_map(
            lambda a, _r=root: collective.broadcast(a, 'dp', root=_r),
            mesh)
        got = np.asarray(jax.jit(f)(x))
        np.testing.assert_array_equal(
            got, np.tile(x[root:root + 1], (n, 1)))


def test_wire_bytes_model():
    # the >=3x headline the bench asserts, straight from the model
    fp32 = qcore.allreduce_wire_bytes(1 << 20, 8)
    q = qcore.quantized_allreduce_wire_bytes(1 << 20, 8, block=256)
    assert fp32 / q >= 3.0
    assert qcore.allreduce_wire_bytes(100, 1) == 0.0


# ------------------------------------------------ trainer wiring
def _build_fit_a_line(quant_on, dp=0):
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                                transpile)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1, act=None,
                           param_attr=fluid.ParamAttr(name='fw'))
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
    if dp:
        transpile(fluid.default_main_program(), make_mesh(dp=dp),
                  ParallelStrategy(data_parallel=True,
                                   quantized_allreduce=quant_on))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, cost


def _train(exe, cost, steps=120, seed=0):
    rng = np.random.RandomState(seed)
    true_w = rng.randn(13, 1).astype('float32')
    losses = []
    for _ in range(steps):
        xs = rng.randn(32, 13).astype('float32')
        ys = xs @ true_w + 0.5
        out = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[cost])
        losses.append(float(np.asarray(out[0]).reshape(())))
    return losses, np.asarray(fluid.global_scope().find('fw'))


def test_int8_allreduce_convergence_fit_a_line():
    """The satellite contract: fit_a_line trains to tolerance with the
    quantized gradient allreduce on, and the off path is bit-identical
    to never having had the feature."""
    exe, cost = _build_fit_a_line(False, dp=DP)
    loss_f, w_f = _train(exe, cost)
    exe, cost = _build_fit_a_line(False, dp=DP)
    loss_f2, w_f2 = _train(exe, cost)
    assert np.array_equal(w_f, w_f2)          # off == off, bit-exact
    exe, cost = _build_fit_a_line(True, dp=DP)
    loss_q, w_q = _train(exe, cost)
    assert loss_q[-1] < 0.05, loss_q[-5:]
    assert abs(loss_q[-1] - loss_f[-1]) < 0.05
    assert not np.array_equal(w_q, w_f)       # the wire format ran


def test_quant_allreduce_env_knob_per_call():
    """PADDLE_TPU_QUANT_ALLREDUCE is read per executor call and folded
    into the compile-cache key: flipping it mid-process changes the
    traced step (recompile), and '0' overrides a program that asked
    for quantization."""
    from paddle_tpu import observe
    exe, cost = _build_fit_a_line(True, dp=DP)
    rng = np.random.RandomState(3)
    xs = rng.randn(32, 13).astype('float32')
    ys = (xs @ rng.randn(13, 1)).astype('float32')
    feed = {'x': xs, 'y': ys}
    prev = os.environ.pop('PADDLE_TPU_QUANT_ALLREDUCE', None)
    try:
        exe.run(feed=feed, fetch_list=[cost])        # quantized (flag)
        assert exe.last_cache_miss
        os.environ['PADDLE_TPU_QUANT_ALLREDUCE'] = '0'
        exe.run(feed=feed, fetch_list=[cost])        # override -> off
        assert exe.last_cache_miss                   # new cache key
        os.environ['PADDLE_TPU_QUANT_ALLREDUCE'] = '1'
        exe.run(feed=feed, fetch_list=[cost])
        # env '1' == the program flag's policy: SAME key, cache hit —
        # the key tracks the resolved policy, not the knob's source
        assert not exe.last_cache_miss
        os.environ['PADDLE_TPU_QUANT_BLOCK'] = '64'
        exe.run(feed=feed, fetch_list=[cost])        # block change: miss
        assert exe.last_cache_miss
        os.environ.pop('PADDLE_TPU_QUANT_BLOCK')
        os.environ['PADDLE_TPU_QUANT_ALLREDUCE'] = '0'
        exe.run(feed=feed, fetch_list=[cost])        # off again: hit
        assert not exe.last_cache_miss
    finally:
        os.environ.pop('PADDLE_TPU_QUANT_BLOCK', None)
        if prev is None:
            os.environ.pop('PADDLE_TPU_QUANT_ALLREDUCE', None)
        else:
            os.environ['PADDLE_TPU_QUANT_ALLREDUCE'] = prev


# --------------------------------------------------------------- PTQ
def _build_infer_model():
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    np.random.seed(0)
    ids = fluid.layers.data(name='ids', shape=[4], dtype='int64')
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    emb = fluid.layers.embedding(input=ids, size=[50, 8])
    pooled = fluid.layers.reduce_sum(emb, dim=1)
    h = fluid.layers.fc(input=[x, pooled], size=16, act='relu')
    out = fluid.layers.fc(input=h, size=4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    infer = fluid.io.get_inference_program([out])
    feed = {'ids': np.random.randint(0, 50, (16, 4, 1)).astype('int64'),
            'x': np.random.rand(16, 8).astype('float32')}
    return exe, infer, out, feed


def test_ptq_parity_and_weight_drop():
    exe, infer, out, feed = _build_infer_model()
    scope = fluid.global_scope()
    ref = exe.run(program=infer, feed=feed, fetch_list=[out])[0]
    qprog, report = quant.quantize_inference_program(
        infer, scope, sample_feed=feed, executor=exe)
    assert report['quantized'] == 4       # embedding + 3 matmuls
    assert report['weight_bytes_int8'] < report['weight_bytes_fp32'] / 2
    got = exe.run(program=qprog, feed=feed, fetch_list=[out])[0]
    cos = float((ref * got).sum() /
                (np.linalg.norm(ref) * np.linalg.norm(got)))
    assert cos > 0.999
    assert np.abs(ref - got).max() < 0.02
    # every calibrated rel_err was measured and small
    assert all(o['rel_err'] is not None and o['rel_err'] < 0.05
               for o in report['ops'])
    # the fp32 originals are gone from the rewritten program; int8 +
    # scale pairs exist and live in scope
    qb = qprog.global_block()
    for o in report['ops']:
        assert not qb.has_var(o['param'])
        assert qb.var(o['param'] + quant.INT8_SUFFIX).dtype == 'int8'
        assert scope.find(o['param'] + quant.SCALE_SUFFIX) is not None
    # the ORIGINAL program still runs fp32 (never mutated)
    ref2 = exe.run(program=infer, feed=feed, fetch_list=[out])[0]
    np.testing.assert_array_equal(ref, ref2)


def test_ptq_calibration_threshold_reverts():
    """A max_rel_err below what int8 can deliver must keep ops fp32 —
    and the resulting program is bit-identical to the original."""
    exe, infer, out, feed = _build_infer_model()
    ref = exe.run(program=infer, feed=feed, fetch_list=[out])[0]
    qprog, report = quant.quantize_inference_program(
        infer, fluid.global_scope(), sample_feed=feed, executor=exe,
        max_rel_err=1e-9)
    assert report['quantized'] == 0 and report['skipped'] == 4
    got = exe.run(program=qprog, feed=feed, fetch_list=[out])[0]
    np.testing.assert_array_equal(ref, got)


def test_ptq_save_load_round_trip(tmp_path):
    """A PTQ'd program survives save_inference_model /
    create_predictor — int8 weights and scales serialize like any
    persistable."""
    exe, infer, out, feed = _build_infer_model()
    scope = fluid.global_scope()
    ref = exe.run(program=infer, feed=feed, fetch_list=[out])[0]
    qprog, _ = quant.quantize_inference_program(infer, scope)
    model_dir = str(tmp_path / 'ptq_model')
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(model_dir, ['ids', 'x'], [out],
                                      exe, main_program=qprog)
    from paddle_tpu.inference import create_predictor
    pred = create_predictor(model_dir, place=fluid.CPUPlace())
    got = pred.predict(feed)[0]
    cos = float((ref * got).sum() /
                (np.linalg.norm(ref) * np.linalg.norm(got)))
    assert cos > 0.999


def test_quant_analysis_pass_contracts():
    """The quant pass errors on every broken pairing the PTQ rewrite
    could produce if it rotted."""
    from paddle_tpu import analysis
    exe, infer, out, feed = _build_infer_model()
    qprog, _ = quant.quantize_inference_program(infer,
                                                fluid.global_scope())
    diags = analysis.run_passes(qprog, feed_names=['ids', 'x'],
                                fetch_names=[out.name],
                                passes=['quant'])
    assert [d for d in diags if d.severity == 'error'] == []

    def broken(mutate):
        p = qprog.clone()
        mutate(p.global_block())
        return [d.code for d in analysis.run_passes(
            p, feed_names=['ids', 'x'], fetch_names=[out.name],
            passes=['quant']) if d.severity == 'error']

    qops = [op for op in qprog.global_block().ops
            if op.type.startswith('quant_')]
    assert len(qops) == 4

    def drop_scale(b):
        next(o for o in b.ops if o.type == 'quant_mul') \
            .inputs.pop('Scale')
    assert 'quant-missing-scale' in broken(drop_scale)

    def wrong_accum(b):
        next(o for o in b.ops if o.type == 'quant_mul') \
            .attrs['accum_dtype'] = 'bfloat16'
    assert 'quant-accum-dtype' in broken(wrong_accum)

    def wrong_scale_shape(b):
        op = next(o for o in b.ops if o.type == 'quant_mul')
        b.vars[op.input('Scale')].shape = (3,)
    assert 'quant-scale-shape' in broken(wrong_scale_shape)

    def fp32_weight(b):
        op = next(o for o in b.ops if o.type == 'quant_lookup_table')
        b.vars[op.input('W')].dtype = 'float32'
    assert 'quant-weight-dtype' in broken(fp32_weight)


def test_quant_analysis_pass_kv_contracts():
    from paddle_tpu import analysis
    from paddle_tpu.serving.decode.model import (LMSpec,
                                                 build_lm_programs)
    progs = build_lm_programs(LMSpec(vocab_size=64), 2, 4, 8, 4,
                              kv_dtype='int8')

    def errs(p):
        return [d.code for d in analysis.run_passes(
            p, fetch_names=[progs.decode_fetch], passes=['quant'])
            if d.severity == 'error']

    assert errs(progs.decode) == []
    broken = progs.decode.clone()
    op = next(o for o in broken.global_block().ops
              if o.type == 'paged_decode_step')
    op.inputs.pop('KScale')
    assert 'kv-missing-scale' in errs(broken)
    broken2 = progs.decode.clone()
    op2 = next(o for o in broken2.global_block().ops
               if o.type == 'paged_decode_step')
    op2.outputs.pop('VScaleOut')
    assert 'kv-scale-not-written' in errs(broken2)


# --------------------------------------------------- quantized KV
from paddle_tpu.serving.decode import (DecodeEngine, LMSpec,  # noqa: E402
                                       random_weights)

KV_SPEC = LMSpec(vocab_size=60, n_layer=2, n_head=2, d_key=8,
                 d_value=8, d_model=16, d_inner=32)
KV_WEIGHTS = random_weights(KV_SPEC, seed=3)


def _kv_engine(**kw):
    kw.setdefault('max_batch', 4)
    kw.setdefault('block_size', 4)
    kw.setdefault('num_blocks', 64)
    kw.setdefault('pages_per_seq', 4)
    kw.setdefault('weights', KV_WEIGHTS)
    kw.setdefault('place', fluid.CPUPlace())
    return DecodeEngine(KV_SPEC, **kw)


def _kv_requests(n=5, seed=0):
    rng = np.random.RandomState(seed)
    return [dict(prompt_ids=rng.randint(0, 60,
                                        int(rng.randint(1, 10))).tolist(),
                 max_new_tokens=int(rng.randint(3, 7)),
                 temperature=0.0 if i % 2 == 0 else 0.7,
                 seed=100 + i) for i in range(n)]


def test_kv_int8_concurrent_matches_sequential():
    """The PR 6 bit-consistency invariant SURVIVES quantization:
    int8-KV concurrent mixed-length decode == int8-KV sequential
    single-request decode, pages fully reclaimed, zero post-warmup
    executor cache misses (signatures unchanged by the scale arenas)."""
    from paddle_tpu import observe
    reqs = _kv_requests()
    seq_out = []
    for r in reqs:
        e = _kv_engine(kv_dtype='int8')
        e.start()
        seq_out.append(e.generate(timeout=120, **r))
        e.shutdown()

    observe.enable()
    try:
        eng = _kv_engine(kv_dtype='int8')
        eng.warmup()
        before = observe.snapshot()
        eng.start()
        streams = [eng.submit(**r) for r in reqs]
        conc = [s.result(120) for s in streams]
        eng.shutdown(drain=True)
        snap = observe.snapshot()
    finally:
        observe.disable()
        observe.reset()
    assert conc == seq_out
    assert eng.pool.free_blocks() == eng.num_blocks
    misses = [
        (k, v) for k, v in snap['counters'].items()
        if k.startswith('executor.cache_miss_total') and
        v > before['counters'].get(k, 0)]
    assert misses == [], misses
    assert eng.resident_seqs_peak >= 2


def test_kv_dtypes_generate_and_default_is_fp32():
    reqs = _kv_requests(n=3, seed=1)

    def run(kv_dtype):
        e = _kv_engine(kv_dtype=kv_dtype)
        e.start()
        outs = [e.generate(timeout=120, **r) for r in reqs]
        e.shutdown()
        return outs

    base = run(None)
    assert run('fp32') == base        # explicit fp32 == default, bit-exact
    for dt in ('bf16', 'int8') + \
            (('fp8',) if qcore.kv_fp8_supported() else ()):
        outs = run(dt)
        assert all(len(o) > 0 for o in outs)
        assert outs == run(dt)        # deterministic per dtype


def test_kv_dtype_env_knob_per_call():
    prev = os.environ.pop('PADDLE_TPU_KV_DTYPE', None)
    try:
        os.environ['PADDLE_TPU_KV_DTYPE'] = 'int8'
        eng = _kv_engine()
        assert eng.kv_dtype == 'int8'
        assert eng._progs.arena_names == ('lm_kcache', 'lm_vcache',
                                          'lm_kscale', 'lm_vscale')
        os.environ.pop('PADDLE_TPU_KV_DTYPE')
        eng2 = _kv_engine()
        assert eng2.kv_dtype == 'float32'
        # explicit ctor arg beats env
        os.environ['PADDLE_TPU_KV_DTYPE'] = 'int8'
        assert _kv_engine(kv_dtype='bf16').kv_dtype == 'bfloat16'
        with pytest.raises(ValueError):
            qcore.resolve_kv_dtype('int4')
    finally:
        if prev is None:
            os.environ.pop('PADDLE_TPU_KV_DTYPE', None)
        else:
            os.environ['PADDLE_TPU_KV_DTYPE'] = prev


def test_paged_attention_quantized_parity():
    """The dequantizing gather path vs fp32 on ragged mixed lengths —
    the parity bound the bench asserts, in unit form."""
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    rng = np.random.RandomState(7)
    nb, h, bs, d = 6, 2, 4, 8
    kf = rng.randn(nb, h, bs, d).astype('float32')
    vf = rng.randn(nb, h, bs, d).astype('float32')
    q = rng.randn(3, h, d).astype('float32')
    tables = np.array([[0, 1, 2, 6], [3, 4, 6, 6], [5, 6, 6, 6]],
                      'int32')
    lens = np.array([11, 8, 3], 'int32')
    ref = np.asarray(paged_attention_reference(q, kf, vf, tables, lens))
    for dt in ('int8',) + \
            (('float8_e4m3fn',) if qcore.kv_fp8_supported() else ()):
        kq, ks = qcore.quantize_rows(jnp.asarray(kf), dt)
        vq, vs = qcore.quantize_rows(jnp.asarray(vf), dt)
        got = np.asarray(paged_attention(
            q, np.asarray(kq), np.asarray(vq), tables, lens,
            k_scales=np.asarray(ks), v_scales=np.asarray(vs)))
        cos = float((ref * got).sum() /
                    (np.linalg.norm(ref) * np.linalg.norm(got)))
        assert cos > 0.995, (dt, cos)
        assert np.abs(ref - got).max() < 0.1, dt


def test_kv_bytes_accounting():
    from paddle_tpu.serving.decode.model import (arena_bytes,
                                                 kv_bytes_per_token,
                                                 num_blocks_for_budget)
    # L*H*(dk+dv) = 2*2*16 = 64 elements/token
    assert kv_bytes_per_token(KV_SPEC, 'float32') == 64 * 4
    assert kv_bytes_per_token(KV_SPEC, 'bfloat16') == 64 * 2
    assert kv_bytes_per_token(KV_SPEC, 'int8') == 64 + 2 * 2 * 2 * 4
    budget = arena_bytes(KV_SPEC, 16, 4, 'float32')
    nb8 = num_blocks_for_budget(budget, KV_SPEC, 4, 'int8')
    assert nb8 / 16.0 >= 1.8     # the equal-bytes capacity headline
    assert arena_bytes(KV_SPEC, nb8, 4, 'int8') <= budget
