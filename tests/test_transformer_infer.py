"""Transformer inference decode: greedy + beam (reference: the transformer
infer program — While + beam_search over LoD; here unrolled static)."""

import numpy as np

import paddle_tpu as fluid


def _overfit_copy_task(seq_len=6, vocab=16, steps=60):
    """Train a tiny transformer to copy the source sequence."""
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(0)
    src = rng.randint(2, vocab, (8, seq_len)).astype('int64')
    avg, _ = T.transformer(
        vocab, vocab, max_length=32, n_layer=1, n_head=2, d_key=8,
        d_value=8, d_model=16, d_inner=32, dropout_rate=0.0,
        label_smooth_eps=0.0, src_seq_len=seq_len, trg_seq_len=seq_len)
    fluid.optimizer.Adam(learning_rate=0.01).minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    # teacher forcing: decoder input = [bos, src[:-1]]; labels = src
    trg_in = np.concatenate([np.zeros((8, 1), 'int64'), src[:, :-1]], 1)
    feed = {'src_word': src,
            'src_length': np.full((8,), seq_len, 'int64'),
            'trg_word': trg_in, 'lbl_word': src,
            'lbl_weight': np.ones((8, seq_len), 'float32')}
    for _ in range(steps):
        out = exe.run(feed=feed, fetch_list=[avg])
    return exe, src, float(np.asarray(out[0]).reshape(()))


def test_greedy_infer_copies_after_overfit():
    from paddle_tpu.models import transformer as T
    seq_len, vocab = 6, 16
    exe, src, loss = _overfit_copy_task(seq_len, vocab)
    assert loss < 0.15, loss
    infer_prog = fluid.Program()
    with fluid.program_guard(infer_prog, fluid.Program()):
        ids, feeds = T.transformer_greedy_infer(
            vocab, vocab, max_out_len=seq_len + 1, src_seq_len=seq_len,
            max_length=32, n_layer=1, n_head=2, d_key=8, d_value=8,
            d_model=16, d_inner=32)
    got = exe.run(program=infer_prog,
                  feed={'src_word': src,
                        'src_length': np.full((8,), seq_len, 'int64')},
                  fetch_list=[ids])[0]
    # positions 1..seq_len should reproduce the source
    acc = (got[:, 1:] == src).mean()
    assert acc > 0.9, (acc, got[:2], src[:2])


def test_beam_infer_matches_greedy_top1():
    from paddle_tpu.models import transformer as T
    seq_len, vocab = 5, 12
    exe, src, loss = _overfit_copy_task(seq_len, vocab, steps=80)
    infer_prog = fluid.Program()
    with fluid.program_guard(infer_prog, fluid.Program()):
        (sent, scores), feeds = T.transformer_beam_infer(
            vocab, vocab, beam_size=3, max_out_len=seq_len + 1,
            src_seq_len=seq_len, max_length=32, n_layer=1, n_head=2,
            d_key=8, d_value=8, d_model=16, d_inner=32, eos_id=1)
    got, got_scores = exe.run(
        program=infer_prog,
        feed={'src_word': src,
              'src_length': np.full((8,), seq_len, 'int64')},
        fetch_list=[sent, scores])
    # top beam should reproduce the source (overfit copy task)
    acc = (got[:, 0, :seq_len] == src[:, :seq_len]).mean()
    assert acc > 0.85, (acc, got[:2, 0], src[:2])
    # scores sorted descending across beams
    assert (np.diff(got_scores, axis=1) <= 1e-5).all()


def test_incremental_greedy_matches_unrolled():
    """KV-cached incremental decode (transformer_greedy_decode op) must
    emit exactly the ids the unrolled per-prefix decode emits; the
    unrolled-trained scope converts via stack_trained_weights."""
    from paddle_tpu.models import transformer as T
    seq_len, vocab = 6, 16
    exe, src, loss = _overfit_copy_task(seq_len, vocab)
    feed = {'src_word': src,
            'src_length': np.full((8,), seq_len, 'int64')}
    kw = dict(max_out_len=seq_len + 1, src_seq_len=seq_len,
              max_length=32, n_layer=1, n_head=2, d_key=8, d_value=8,
              d_model=16, d_inner=32)
    unrolled_prog = fluid.Program()
    with fluid.program_guard(unrolled_prog, fluid.Program()):
        ids_u, _ = T.transformer_greedy_infer(vocab, vocab, **kw)
    got_u = exe.run(program=unrolled_prog, feed=feed,
                    fetch_list=[ids_u])[0]
    T.stack_trained_weights(fluid.global_scope(), n_layer=1)
    inc_prog = fluid.Program()
    with fluid.program_guard(inc_prog, fluid.Program()):
        ids_i, _ = T.transformer_greedy_infer(vocab, vocab,
                                              incremental=True, **kw)
    got_i = exe.run(program=inc_prog, feed=feed, fetch_list=[ids_i])[0]
    np.testing.assert_array_equal(got_i, got_u)
    acc = (got_i[:, 1:] == src).mean()
    assert acc > 0.9, (acc, got_i[:2], src[:2])


def test_incremental_beam_matches_unrolled():
    """transformer_beam_decode (KV-cached, single scan) must emit the
    same sentences and scores as the unrolled beam graph."""
    from paddle_tpu.models import transformer as T
    seq_len, vocab = 5, 12
    exe, src, loss = _overfit_copy_task(seq_len, vocab, steps=80)
    feed = {'src_word': src,
            'src_length': np.full((8,), seq_len, 'int64')}
    kw = dict(beam_size=3, max_out_len=seq_len + 1, src_seq_len=seq_len,
              max_length=32, n_layer=1, n_head=2, d_key=8, d_value=8,
              d_model=16, d_inner=32, eos_id=1)
    unrolled_prog = fluid.Program()
    with fluid.program_guard(unrolled_prog, fluid.Program()):
        (sent_u, scores_u), _ = T.transformer_beam_infer(vocab, vocab,
                                                         **kw)
    got_u, sc_u = exe.run(program=unrolled_prog, feed=feed,
                          fetch_list=[sent_u, scores_u])
    T.stack_trained_weights(fluid.global_scope(), n_layer=1)
    inc_prog = fluid.Program()
    with fluid.program_guard(inc_prog, fluid.Program()):
        (sent_i, scores_i), _ = T.transformer_beam_infer(
            vocab, vocab, incremental=True, **kw)
    got_i, sc_i = exe.run(program=inc_prog, feed=feed,
                          fetch_list=[sent_i, scores_i])
    np.testing.assert_array_equal(got_i, got_u)
    np.testing.assert_allclose(sc_i, sc_u, rtol=1e-4, atol=1e-5)


def test_infer_graph_fresh_scope():
    """The infer graphs must be self-contained: fresh scope, run startup,
    decode — no prior training graph in the process (regression: a [B,1]
    first prefix used to mis-shape the decoder weights)."""
    from paddle_tpu.models import transformer as T
    vocab, s = 12, 4
    ids, feeds = T.transformer_greedy_infer(
        vocab, vocab, max_out_len=5, src_seq_len=s, max_length=32,
        n_layer=1, n_head=2, d_key=8, d_value=8, d_model=16, d_inner=32,
        eos_id=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    got = exe.run(feed={'src_word': rng.randint(2, vocab, (3, s))
                        .astype('int64'),
                        'src_length': np.full((3,), s, 'int64')},
                  fetch_list=[ids])[0]
    assert got.shape == (3, 5)
    # post-EOS positions are EOS
    for row in got:
        hit = np.where(row == 1)[0]
        if len(hit):
            assert (row[hit[0]:] == 1).all()


def test_incremental_greedy_on_dp_mesh_matches_unsharded():
    """Distributed inference: the KV-cached decode runs under a dp mesh
    (batch sharded over 8 devices; caches/activations follow via GSPMD
    propagation) and emits exactly the unsharded sequences."""
    from paddle_tpu.models import transformer as T
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                                transpile)
    seq_len, vocab = 6, 16
    exe, src, loss = _overfit_copy_task(seq_len, vocab)
    T.stack_trained_weights(fluid.global_scope(), n_layer=1)
    feed = {'src_word': src,
            'src_length': np.full((8,), seq_len, 'int64')}
    kw = dict(max_out_len=seq_len + 1, src_seq_len=seq_len,
              max_length=32, n_layer=1, n_head=2, d_key=8, d_value=8,
              d_model=16, d_inner=32)

    def build(mesh):
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            ids, _ = T.transformer_greedy_infer(vocab, vocab,
                                                incremental=True, **kw)
        if mesh is not None:
            transpile(prog, mesh, ParallelStrategy(data_parallel=True))
        return prog, ids

    prog_u, ids_u = build(None)
    got_u = exe.run(program=prog_u, feed=feed, fetch_list=[ids_u])[0]
    prog_s, ids_s = build(make_mesh(dp=8))
    got_s = exe.run(program=prog_s, feed=feed, fetch_list=[ids_s])[0]
    np.testing.assert_array_equal(got_s, got_u)
    assert (got_s[:, 1:] == src).mean() > 0.9
