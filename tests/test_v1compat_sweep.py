"""v1 shim layer sweep: numeric/shape checks for every shimmed
layer family not covered by test_v1compat.py (costs, image ops, misc
projections/arithmetic, evaluators)."""

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.trainer_config_helpers as v1


def _run(fetches, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return [np.asarray(v) for v in exe.run(feed=feed, fetch_list=fetches)]


def test_v1_rank_cost():
    a = v1.data_layer(name='a', size=1); b = v1.data_layer(name='b', size=1)
    l = v1.data_layer(name='l', size=1)
    cost = v1.rank_cost(a, b, l)
    out, = _run([cost], {'a': np.array([[0.3]],'f'), 'b': np.array([[0.6]],'f'), 'l': np.array([[1.0]],'f')})
    assert out.shape == () or out.size == 1


def test_v1_huber_regression_cost():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=3)
    cost = v1.huber_regression_cost(a, b)
    _run([cost], {'a': np.ones((2,3),'f'), 'b': np.zeros((2,3),'f')})


def test_v1_huber_classification_cost():
    a = v1.data_layer(name='a', size=1)
    lbl = v1.data_layer(name='l', size=1, dtype='int64')
    cost = v1.huber_classification_cost(a, lbl)
    _run([cost], {'a': np.array([[0.3],[-0.7]],'f'), 'l': np.array([[1],[0]],'i8')})


def test_v1_multi_binary_label_cross_entropy():
    p = v1.data_layer(name='p', size=4)
    lbl = v1.data_layer(name='l', size=4)
    cost = v1.multi_binary_label_cross_entropy(p, lbl)
    out, = _run([cost], {'p': np.full((2,4),0.5,'f'), 'l': np.array([[1,0,1,0],[0,1,0,1]],'f')})


def test_v1_smooth_l1_cost():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=3)
    _run([v1.smooth_l1_cost(a, b)], {'a': np.ones((2,3),'f'), 'b': np.zeros((2,3),'f')})


def test_v1_sum_cost():
    a = v1.data_layer(name='a', size=3)
    _run([v1.sum_cost(a)], {'a': np.ones((2,3),'f')})


def test_v1_batch_norm_layer():
    img = v1.data_layer(name='im', size=3*8*8)
    out = v1.batch_norm_layer(v1.img_conv_layer(img, 3, 4, num_channels=3, padding=1), act=v1.ReluActivation())
    _run([out], {'im': np.random.rand(2,192).astype('f')})


def test_v1_img_cmrnorm_layer():
    img = v1.data_layer(name='im', size=4*8*8)
    out = v1.img_cmrnorm_layer(img, size=5, num_channels=4)
    _run([out], {'im': np.random.rand(2,256).astype('f')})


def test_v1_maxout_layer():
    img = v1.data_layer(name='im', size=4*4*4)
    out = v1.maxout_layer(img, groups=2, num_channels=4)
    _run([out], {'im': np.random.rand(2,64).astype('f')})


def test_v1_spp_layer():
    img = v1.data_layer(name='im', size=3*8*8)
    out = v1.spp_layer(img, num_channels=3, pyramid_height=2)
    _run([out], {'im': np.random.rand(2,192).astype('f')})


def test_v1_pad_layer():
    img = v1.data_layer(name='im', size=3*4*4)
    x = v1.img_conv_layer(img, 3, 3, num_channels=3, padding=1)
    out = v1.pad_layer(x, pad_c=[1,1], pad_h=[0,0], pad_w=[0,0])
    _run([out], {'im': np.random.rand(2,48).astype('f')})


def test_v1_bilinear_interp_layer():
    img = v1.data_layer(name='im', size=3*4*4)
    x = v1.img_conv_layer(img, 3, 3, num_channels=3, padding=1)
    out = v1.bilinear_interp_layer(x, out_size_x=8, out_size_y=8)
    _run([out], {'im': np.random.rand(2,48).astype('f')})


def test_v1_tensor_layer():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=4)
    out = v1.tensor_layer(a, b, size=5)
    o, = _run([out], {'a': np.ones((2,3),'f'), 'b': np.ones((2,4),'f')})
    assert o.shape == (2,5), o.shape


def test_v1_multiplex_layer():
    idx = v1.data_layer(name='i', size=1, dtype='int64')
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=3)
    out = v1.multiplex_layer([idx, a, b])
    o, = _run([out], {'i': np.array([[0],[1]],'i8'), 'a': np.zeros((2,3),'f'), 'b': np.ones((2,3),'f')})
    assert np.allclose(o[0], 0) and np.allclose(o[1], 1), o


def test_v1_sampling_id_layer():
    p = v1.data_layer(name='p', size=4)
    out = v1.sampling_id_layer(p)
    o, = _run([out], {'p': np.array([[0,0,1,0],[1,0,0,0]],'f')})
    assert o[0] == 2 and o[1] == 0, o


def test_v1_out_prod_layer():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=4)
    o, = _run([v1.out_prod_layer(a, b)], {'a': np.ones((2,3),'f'), 'b': np.ones((2,4),'f')})
    assert o.shape == (2,3,4), o.shape


def test_v1_linear_comb_layer():
    w = v1.data_layer(name='w', size=2); vv = v1.data_layer(name='v', size=6)
    o, = _run([v1.linear_comb_layer(w, vv, size=3)],
             {'w': np.array([[1,2]],'f'), 'v': np.arange(6,dtype='f').reshape(1,6)})
    assert o.shape == (1,3)
    np.testing.assert_allclose(o[0], 1*np.arange(3) + 2*np.arange(3,6))


def test_v1_rotate_layer():
    img = v1.data_layer(name='im', size=1*2*3)
    o, = _run([v1.rotate_layer(img, height=2, width=3)],
             {'im': np.arange(6,dtype='f').reshape(1,6)})
    ref = np.rot90(np.arange(6,dtype='f').reshape(2,3)).reshape(-1)
    np.testing.assert_allclose(o.reshape(-1), ref)


def test_v1_eos_layer():
    x = v1.data_layer(name='x', size=1, dtype='int64')
    o, = _run([v1.eos_layer(x, eos_id=2)], {'x': np.array([[2],[3]],'i8')})
    assert o[0] == 1.0 and o[1] == 0.0, o


def test_v1_l2_distance_layer():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=3)
    o, = _run([v1.l2_distance_layer(a, b)], {'a': np.zeros((2,3),'f'), 'b': np.ones((2,3),'f')})
    np.testing.assert_allclose(o.reshape(-1), [3**0.5]*2, rtol=1e-5)


def test_v1_norm_layers():
    a = v1.data_layer(name='a', size=4)
    o1, o2 = _run([v1.sum_to_one_norm_layer(a), v1.row_l2_norm_layer(a)],
                 {'a': np.array([[1,1,2,4]],'f')})
    np.testing.assert_allclose(o1.sum(), 1.0, rtol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(o2), 1.0, rtol=1e-5)


def test_v1_gated_unit_layer():
    x = v1.data_layer(name='x', size=4)
    o, = _run([v1.gated_unit_layer(x, size=3)], {'x': np.ones((2,4),'f')})
    assert o.shape == (2,3)


def test_v1_conv_shift_layer():
    a = v1.data_layer(name='a', size=5); b = v1.data_layer(name='b', size=3)
    o, = _run([v1.conv_shift_layer(a, b)], {'a': np.ones((1,5),'f'), 'b': np.ones((1,3),'f')})
    assert o.shape == (1,5)


def test_v1_crop_layer():
    img = v1.data_layer(name='im', size=3*4*4)
    x = v1.img_conv_layer(img, 3, 3, num_channels=3, padding=1)
    o, = _run([v1.crop_layer(x, offset=[0,0,1,1], shape=[2,3,2,2])],
             {'im': np.random.rand(2,48).astype('f')})
    assert o.shape == (2,3,2,2), o.shape


def test_v1_prelu_layer():
    x = v1.data_layer(name='x', size=4)
    o, = _run([v1.prelu_layer(x)], {'x': np.array([[-1,1,-2,2]],'f')})
    assert o.shape == (1,4)


def test_v1_scaling_layer():
    x = v1.data_layer(name='x', size=4); w = v1.data_layer(name='w', size=1)
    o, = _run([v1.scaling_layer(x, w)], {'x': np.ones((2,4),'f'), 'w': np.array([[2],[3]],'f')})
    np.testing.assert_allclose(o, [[2]*4,[3]*4])


def test_v1_power_layer():
    x = v1.data_layer(name='x', size=4); w = v1.data_layer(name='w', size=1)
    o, = _run([v1.power_layer(x, w)], {'x': np.full((1,4),2.0,'f'), 'w': np.array([[3]],'f')})
    np.testing.assert_allclose(o, np.full((1,4),8.0), rtol=1e-5)


def test_v1_seq_reshape_layer():
    x = v1.data_layer(name='x', size=4, seq_type=1)
    r = v1.seq_reshape_layer(x, 2)
    o, = _run([r], {'x': np.arange(8,dtype='f').reshape(1,2,4), 'x_len': np.array([2],'i4')})
    assert o.shape == (1,4,2), o.shape


def test_v1_expand_layer():
    x = v1.data_layer(name='x', size=3)
    seq = v1.data_layer(name='s', size=2, seq_type=1)
    o, = _run([v1.expand_layer(x, seq)],
             {'x': np.ones((2,3),'f'), 's': np.ones((2,4,2),'f'), 's_len': np.array([4,4],'i4')})
    assert o.shape == (2,4,3), o.shape


def test_v1_classification_error_evaluator():
    p = v1.data_layer(name='p', size=5)
    lbl = v1.data_layer(name='l', size=1, dtype='int64')
    err = v1.evaluators.classification_error_evaluator(p, lbl)
    o, = _run([err], {'p': np.eye(5,dtype='f')[:3], 'l': np.array([[0],[1],[3]],'i8')})
    np.testing.assert_allclose(float(o), 1/3, rtol=1e-4)

