"""v1 shim layer sweep: numeric/shape checks for every shimmed
layer family not covered by test_v1compat.py (costs, image ops, misc
projections/arithmetic, evaluators)."""

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.trainer_config_helpers as v1


def _run(fetches, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return [np.asarray(v) for v in exe.run(feed=feed, fetch_list=fetches)]


def test_v1_rank_cost():
    a = v1.data_layer(name='a', size=1); b = v1.data_layer(name='b', size=1)
    l = v1.data_layer(name='l', size=1)
    cost = v1.rank_cost(a, b, l)
    out, = _run([cost], {'a': np.array([[0.3]],'f'), 'b': np.array([[0.6]],'f'), 'l': np.array([[1.0]],'f')})
    assert out.shape == () or out.size == 1


def test_v1_huber_regression_cost():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=3)
    cost = v1.huber_regression_cost(a, b)
    _run([cost], {'a': np.ones((2,3),'f'), 'b': np.zeros((2,3),'f')})


def test_v1_huber_classification_cost():
    a = v1.data_layer(name='a', size=1)
    lbl = v1.data_layer(name='l', size=1, dtype='int64')
    cost = v1.huber_classification_cost(a, lbl)
    _run([cost], {'a': np.array([[0.3],[-0.7]],'f'), 'l': np.array([[1],[0]],'i8')})


def test_v1_multi_binary_label_cross_entropy():
    p = v1.data_layer(name='p', size=4)
    lbl = v1.data_layer(name='l', size=4)
    cost = v1.multi_binary_label_cross_entropy(p, lbl)
    out, = _run([cost], {'p': np.full((2,4),0.5,'f'), 'l': np.array([[1,0,1,0],[0,1,0,1]],'f')})


def test_v1_smooth_l1_cost():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=3)
    _run([v1.smooth_l1_cost(a, b)], {'a': np.ones((2,3),'f'), 'b': np.zeros((2,3),'f')})


def test_v1_sum_cost():
    a = v1.data_layer(name='a', size=3)
    _run([v1.sum_cost(a)], {'a': np.ones((2,3),'f')})


def test_v1_batch_norm_layer():
    img = v1.data_layer(name='im', size=3*8*8)
    out = v1.batch_norm_layer(v1.img_conv_layer(img, 3, 4, num_channels=3, padding=1), act=v1.ReluActivation())
    _run([out], {'im': np.random.rand(2,192).astype('f')})


def test_v1_img_cmrnorm_layer():
    img = v1.data_layer(name='im', size=4*8*8)
    out = v1.img_cmrnorm_layer(img, size=5, num_channels=4)
    _run([out], {'im': np.random.rand(2,256).astype('f')})


def test_v1_maxout_layer():
    img = v1.data_layer(name='im', size=4*4*4)
    out = v1.maxout_layer(img, groups=2, num_channels=4)
    _run([out], {'im': np.random.rand(2,64).astype('f')})


def test_v1_spp_layer():
    img = v1.data_layer(name='im', size=3*8*8)
    out = v1.spp_layer(img, num_channels=3, pyramid_height=2)
    _run([out], {'im': np.random.rand(2,192).astype('f')})


def test_v1_pad_layer():
    img = v1.data_layer(name='im', size=3*4*4)
    x = v1.img_conv_layer(img, 3, 3, num_channels=3, padding=1)
    out = v1.pad_layer(x, pad_c=[1,1], pad_h=[0,0], pad_w=[0,0])
    _run([out], {'im': np.random.rand(2,48).astype('f')})


def test_v1_bilinear_interp_layer():
    img = v1.data_layer(name='im', size=3*4*4)
    x = v1.img_conv_layer(img, 3, 3, num_channels=3, padding=1)
    out = v1.bilinear_interp_layer(x, out_size_x=8, out_size_y=8)
    _run([out], {'im': np.random.rand(2,48).astype('f')})


def test_v1_tensor_layer():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=4)
    out = v1.tensor_layer(a, b, size=5)
    o, = _run([out], {'a': np.ones((2,3),'f'), 'b': np.ones((2,4),'f')})
    assert o.shape == (2,5), o.shape


def test_v1_multiplex_layer():
    idx = v1.data_layer(name='i', size=1, dtype='int64')
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=3)
    out = v1.multiplex_layer([idx, a, b])
    o, = _run([out], {'i': np.array([[0],[1]],'i8'), 'a': np.zeros((2,3),'f'), 'b': np.ones((2,3),'f')})
    assert np.allclose(o[0], 0) and np.allclose(o[1], 1), o


def test_v1_sampling_id_layer():
    p = v1.data_layer(name='p', size=4)
    out = v1.sampling_id_layer(p)
    o, = _run([out], {'p': np.array([[0,0,1,0],[1,0,0,0]],'f')})
    assert o[0] == 2 and o[1] == 0, o


def test_v1_out_prod_layer():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=4)
    o, = _run([v1.out_prod_layer(a, b)], {'a': np.ones((2,3),'f'), 'b': np.ones((2,4),'f')})
    assert o.shape == (2,3,4), o.shape


def test_v1_linear_comb_layer():
    w = v1.data_layer(name='w', size=2); vv = v1.data_layer(name='v', size=6)
    o, = _run([v1.linear_comb_layer(w, vv, size=3)],
             {'w': np.array([[1,2]],'f'), 'v': np.arange(6,dtype='f').reshape(1,6)})
    assert o.shape == (1,3)
    np.testing.assert_allclose(o[0], 1*np.arange(3) + 2*np.arange(3,6))


def test_v1_rotate_layer():
    img = v1.data_layer(name='im', size=1*2*3)
    o, = _run([v1.rotate_layer(img, height=2, width=3)],
             {'im': np.arange(6,dtype='f').reshape(1,6)})
    ref = np.rot90(np.arange(6,dtype='f').reshape(2,3)).reshape(-1)
    np.testing.assert_allclose(o.reshape(-1), ref)


def test_v1_eos_layer():
    x = v1.data_layer(name='x', size=1, dtype='int64')
    o, = _run([v1.eos_layer(x, eos_id=2)], {'x': np.array([[2],[3]],'i8')})
    assert o[0] == 1.0 and o[1] == 0.0, o


def test_v1_l2_distance_layer():
    a = v1.data_layer(name='a', size=3); b = v1.data_layer(name='b', size=3)
    o, = _run([v1.l2_distance_layer(a, b)], {'a': np.zeros((2,3),'f'), 'b': np.ones((2,3),'f')})
    np.testing.assert_allclose(o.reshape(-1), [3**0.5]*2, rtol=1e-5)


def test_v1_norm_layers():
    a = v1.data_layer(name='a', size=4)
    o1, o2 = _run([v1.sum_to_one_norm_layer(a), v1.row_l2_norm_layer(a)],
                 {'a': np.array([[1,1,2,4]],'f')})
    np.testing.assert_allclose(o1.sum(), 1.0, rtol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(o2), 1.0, rtol=1e-5)


def test_v1_gated_unit_layer():
    x = v1.data_layer(name='x', size=4)
    o, = _run([v1.gated_unit_layer(x, size=3)], {'x': np.ones((2,4),'f')})
    assert o.shape == (2,3)


def test_v1_conv_shift_layer():
    a = v1.data_layer(name='a', size=5); b = v1.data_layer(name='b', size=3)
    o, = _run([v1.conv_shift_layer(a, b)], {'a': np.ones((1,5),'f'), 'b': np.ones((1,3),'f')})
    assert o.shape == (1,5)


def test_v1_crop_layer():
    img = v1.data_layer(name='im', size=3*4*4)
    x = v1.img_conv_layer(img, 3, 3, num_channels=3, padding=1)
    o, = _run([v1.crop_layer(x, offset=[0,0,1,1], shape=[2,3,2,2])],
             {'im': np.random.rand(2,48).astype('f')})
    assert o.shape == (2,3,2,2), o.shape


def test_v1_prelu_layer():
    x = v1.data_layer(name='x', size=4)
    o, = _run([v1.prelu_layer(x)], {'x': np.array([[-1,1,-2,2]],'f')})
    assert o.shape == (1,4)


def test_v1_scaling_layer():
    x = v1.data_layer(name='x', size=4); w = v1.data_layer(name='w', size=1)
    o, = _run([v1.scaling_layer(x, w)], {'x': np.ones((2,4),'f'), 'w': np.array([[2],[3]],'f')})
    np.testing.assert_allclose(o, [[2]*4,[3]*4])


def test_v1_power_layer():
    x = v1.data_layer(name='x', size=4); w = v1.data_layer(name='w', size=1)
    o, = _run([v1.power_layer(x, w)], {'x': np.full((1,4),2.0,'f'), 'w': np.array([[3]],'f')})
    np.testing.assert_allclose(o, np.full((1,4),8.0), rtol=1e-5)


def test_v1_seq_reshape_layer():
    x = v1.data_layer(name='x', size=4, seq_type=1)
    r = v1.seq_reshape_layer(x, 2)
    o, = _run([r], {'x': np.arange(8,dtype='f').reshape(1,2,4), 'x_len': np.array([2],'i4')})
    assert o.shape == (1,4,2), o.shape


def test_v1_expand_layer():
    x = v1.data_layer(name='x', size=3)
    seq = v1.data_layer(name='s', size=2, seq_type=1)
    o, = _run([v1.expand_layer(x, seq)],
             {'x': np.ones((2,3),'f'), 's': np.ones((2,4,2),'f'), 's_len': np.array([4,4],'i4')})
    assert o.shape == (2,4,3), o.shape


def test_v1_classification_error_evaluator():
    p = v1.data_layer(name='p', size=5)
    lbl = v1.data_layer(name='l', size=1, dtype='int64')
    err = v1.evaluators.classification_error_evaluator(p, lbl)
    o, = _run([err], {'p': np.eye(5,dtype='f')[:3], 'l': np.array([[0],[1],[3]],'i8')})
    np.testing.assert_allclose(float(o), 1/3, rtol=1e-4)



def test_v1_block_expand_layer():
    img = v1.data_layer(name='im', size=1 * 6 * 6)
    be = v1.block_expand_layer(img, block_x=2, block_y=2, stride_x=2,
                               stride_y=2, num_channels=1)
    o, = _run([be], {'im': np.arange(36, dtype='f').reshape(1, 36)})
    assert o.shape == (9, 4)


def test_v1_channel_and_order_layers():
    img = v1.data_layer(name='im', size=4 * 3 * 3)
    x = v1.img_conv_layer(img, 3, 4, num_channels=4, padding=1)
    cn = v1.cross_channel_norm_layer(x)
    so = v1.switch_order_layer(x)
    ss = v1.scale_shift_layer(v1.data_layer(name='z', size=5))
    rz = v1.resize_layer(x, 12)
    o1, o2, o3, o4 = _run([cn, so, ss, rz],
                          {'im': np.random.rand(2, 36).astype('f'),
                           'z': np.ones((2, 5), 'f')})
    assert o1.shape == (2, 4, 3, 3)
    # per-pixel channel vectors are unit-norm before the learned scale
    assert o2.shape == (2, 3, 3, 4) and o3.shape == (2, 5)
    assert o4.shape == (6, 12)


def test_v1_seq_slice_and_kmax():
    sq = v1.data_layer(name='s', size=3, seq_type=1)
    sl = v1.seq_slice_layer(sq, starts=1, ends=2)
    km = v1.kmax_seq_score_layer(
        v1.data_layer(name='sc', size=1, seq_type=1), beam_size=2)
    # row 0 has only 2 real (negative, beam-log-prob-like) scores and a
    # zero pad slot: masking must keep the pad slot OUT of the top-k
    o, k = _run([sl, km],
                {'s': np.arange(24, dtype='f').reshape(2, 4, 3),
                 's_len': np.array([4, 4], 'i4'),
                 'sc': np.array([[-0.5, -0.2, 0.0],
                                 [-0.8, -0.2, -0.3]], 'f')[..., None],
                 'sc_len': np.array([2, 3], 'i4')})
    assert o.shape == (2, 2, 3)
    np.testing.assert_array_equal(k, [[1, 0], [1, 2]])


def test_v1_ssd_detection_shims():
    """priorbox/multibox_loss/detection_output through the v1 shim:
    priors flattened to [N, 4], heads accepted as lists, nonzero loss
    on a prior-scaled gt box, and the gt_box divergence raises a clear
    error instead of dying inside iou_similarity."""
    import pytest
    img = v1.data_layer(name='im', size=3 * 32 * 32)
    image4 = v1.img_conv_layer(img, 3, 8, num_channels=3, padding=1)
    feat = v1.img_pool_layer(image4, pool_size=2, stride=2)
    pb = v1.priorbox_layer(feat, image4, aspect_ratio=[2.0],
                           variance=[0.1, 0.1, 0.2, 0.2], min_size=[10],
                           max_size=[20])
    ppc, n_priors = 2, 16 * 16 * 2
    loc = fluid.layers.reshape(
        fluid.layers.transpose(
            v1.img_conv_layer(feat, 3, ppc * 4, padding=1),
            [0, 2, 3, 1]), [-1, n_priors, 4])
    conf = fluid.layers.reshape(
        fluid.layers.transpose(
            v1.img_conv_layer(feat, 3, ppc * 5, padding=1),
            [0, 2, 3, 1]), [-1, n_priors, 5])
    gt_box = fluid.layers.data(name='gt', shape=[1, 4], dtype='float32')
    gt_lbl = fluid.layers.data(name='gl', shape=[1], dtype='int64')
    # list-of-heads form (one per feature map in real v1 configs)
    loss = v1.multibox_loss_layer([loc], [conf], pb, gt_lbl,
                                  num_classes=5, gt_box=gt_box)
    out = v1.detection_output_layer([loc], [conf], pb, num_classes=5)
    cost = fluid.layers.reduce_mean(loss)
    rng = np.random.RandomState(0)
    feed = {'im': rng.rand(1, 3 * 32 * 32).astype('f'),
            'gt': np.array([[[0.35, 0.35, 0.65, 0.65]]], 'f'),
            'gl': np.array([[2]], 'int64')}
    l, o = _run([cost, out], feed)
    assert np.isfinite(l).all() and float(l) > 0
    with pytest.raises(ValueError, match='gt_box'):
        v1.multibox_loss_layer(loc, conf, pb, gt_lbl, num_classes=5)


def test_v1_gru_step_and_slice_projection():
    x = v1.data_layer(name='x', size=12)   # 3*4 pre-projection
    h0 = v1.data_layer(name='h', size=4)
    h1 = v1.gru_step_layer(x, h0)
    z = v1.data_layer(name='z', size=6)
    mix = v1.mixed_layer(input=[v1.slice_projection(z, [(0, 2), (4, 6)])],
                         size=4, bias_attr=False)
    o1, o2 = _run([h1, mix],
                  {'x': np.ones((2, 12), 'f'),
                   'h': np.zeros((2, 4), 'f'),
                   'z': np.arange(6, dtype='f')[None].repeat(2, 0)})
    assert o1.shape == (2, 4)
    # v1 semantics: slices CONCATENATE -> [z0, z1, z4, z5]
    np.testing.assert_allclose(o2, [[0, 1, 4, 5]] * 2, rtol=1e-5)
    # get_output_layer passes primary outputs through but refuses the
    # cell-state selection the shimmed lstmemory cannot serve
    assert v1.get_output_layer(h1, 'hidden') is h1
    import pytest
    with pytest.raises(NotImplementedError, match='dynamic_lstm'):
        v1.get_output_layer(h1, 'state')


def test_v1_linear_activation_is_identity_in_rnn():
    """An EXPLICIT LinearActivation (v1 name None) must map to
    'identity', not fall through to the tanh/sigmoid defaults
    (regression: `_act_name(act) or 'tanh'` conflated the two)."""
    x = v1.data_layer(name='x', size=3, seq_type=1)
    h = v1.recurrent_layer(
        input=x, act=v1.LinearActivation(),
        param_attr=v1.ParameterAttribute(
            initializer=fluid.initializer.Constant(0.0)),
        bias_attr=False)
    xs = np.array([[[1., -2., 3.], [0.5, 0.5, -4.]]], 'f')
    o, = _run([h], {'x': xs, 'x_len': np.array([2], 'i4')})
    # W == 0 -> h_t = act(x_t); identity keeps negatives/magnitudes
    np.testing.assert_allclose(o, xs, rtol=1e-6)
