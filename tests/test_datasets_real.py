"""Real-data dataset parsers (VERDICT r4 next-#6): miniature archives
built in-test, dropped where a user would cache them, parsed through
the same reader code paths the full downloads would take (reference:
python/paddle/v2/dataset/{wmt14,cifar,imdb,movielens}.py)."""

import io
import os
import pickle
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.dataset import common


@pytest.fixture
def data_home(tmp_path, monkeypatch):
    monkeypatch.setattr(common, 'DATA_HOME', str(tmp_path))
    return tmp_path


def _add_tar_member(tar, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tar.addfile(info, io.BytesIO(data))


def test_wmt14_tar_parse(data_home):
    from paddle_tpu.dataset import wmt14
    d = data_home / 'wmt14'
    d.mkdir()
    src_dict = b'<s>\n<e>\n<unk>\nhello\nworld\n'
    trg_dict = b'<s>\n<e>\n<unk>\nguten\ntag\n'
    long_src = ' '.join(['hello'] * 90)
    train_tsv = ('hello world\tguten tag\n'
                 'hello mystery\tguten tag\n'        # OOV -> <unk>
                 'not-a-pair-line\n'                 # malformed: skipped
                 '%s\tguten tag\n' % long_src        # >80 tokens: dropped
                 ).encode()
    test_tsv = b'world\ttag\n'
    with tarfile.open(str(d / wmt14.TRAIN_ARCHIVE), 'w:gz') as t:
        _add_tar_member(t, 'data/src.dict', src_dict)
        _add_tar_member(t, 'data/trg.dict', trg_dict)
        _add_tar_member(t, 'data/train/train', train_tsv)
        _add_tar_member(t, 'data/test/test', test_tsv)

    rows = list(wmt14.train(dict_size=5)())
    assert len(rows) == 2                            # malformed+long drop
    src_ids, trg_in, trg_out = rows[0]
    assert src_ids == [0, 3, 4, 1]                   # <s> hello world <e>
    assert trg_in == [0, 3, 4]                       # <s> guten tag
    assert trg_out == [3, 4, 1]                      # guten tag <e>
    assert rows[1][0] == [0, 3, 2, 1]                # mystery -> <unk>=2
    test_rows = list(wmt14.test(dict_size=5)())
    assert test_rows == [([0, 4, 1], [0, 4], [4, 1])]
    src_d, trg_d = wmt14.get_dict(dict_size=5)
    assert src_d['hello'] == 3 and trg_d['tag'] == 4  # REAL vocab
    rsrc, _ = wmt14.get_dict(dict_size=5, reverse=True)
    assert rsrc[3] == 'hello'


def test_wmt14_synthetic_fallback_get_dict_shape(data_home):
    from paddle_tpu.dataset import wmt14
    src_d, trg_d = wmt14.get_dict(dict_size=50)
    assert src_d['<s>'] == 0 and src_d['<e>'] == 1 and src_d['<unk>'] == 2
    assert len(src_d) == 50 and len(trg_d) == 50


def test_cifar_tar_parse(data_home):
    from paddle_tpu.dataset import cifar
    d = data_home / 'cifar'
    d.mkdir()
    rng = np.random.RandomState(0)
    tr = {b'data': rng.randint(0, 256, (4, 3072)).astype('uint8'),
          b'labels': [1, 2, 3, 4]}
    te = {b'data': rng.randint(0, 256, (2, 3072)).astype('uint8'),
          b'labels': [5, 6]}
    with tarfile.open(str(d / cifar.CIFAR10_ARCHIVE), 'w:gz') as t:
        _add_tar_member(t, 'cifar-10-batches-py/data_batch_1',
                        pickle.dumps(tr, protocol=2))
        _add_tar_member(t, 'cifar-10-batches-py/test_batch',
                        pickle.dumps(te, protocol=2))
    rows = list(cifar.train10()())
    assert len(rows) == 4
    x, y = rows[0]
    assert x.dtype == np.float32 and x.shape == (3072,)
    np.testing.assert_allclose(x, tr[b'data'][0] / 255.0, rtol=1e-6)
    assert [r[1] for r in rows] == [1, 2, 3, 4]
    assert [r[1] for r in cifar.test10()()] == [5, 6]
    # cifar-100: fine_labels key
    tr100 = {b'data': rng.randint(0, 256, (2, 3072)).astype('uint8'),
             b'fine_labels': [7, 8]}
    with tarfile.open(str(d / cifar.CIFAR100_ARCHIVE), 'w:gz') as t:
        _add_tar_member(t, 'cifar-100-python/train',
                        pickle.dumps(tr100, protocol=2))
        _add_tar_member(t, 'cifar-100-python/test',
                        pickle.dumps(te, protocol=2))
    assert [r[1] for r in cifar.train100()()] == [7, 8]


def test_imdb_tar_parse(data_home):
    import re
    from paddle_tpu.dataset import imdb
    d = data_home / 'imdb'
    d.mkdir()
    docs = {
        'aclImdb/train/pos/0.txt': b'A great, GREAT movie!',
        'aclImdb/train/pos/1.txt': b'great fun\n',
        'aclImdb/train/neg/0.txt': b'terrible movie...',
        'aclImdb/test/pos/0.txt': b'great',
        'aclImdb/test/neg/0.txt': b'awful; terrible',
    }
    with tarfile.open(str(d / imdb.ARCHIVE), 'w:gz') as t:
        for name, data in docs.items():
            _add_tar_member(t, name, data)
    # tokenize: lowercase, punctuation stripped
    toks = list(imdb.tokenize(re.compile(r'aclImdb/train/pos/.*\.txt$')))
    assert ['a', 'great', 'great', 'movie'] in toks
    word_idx = imdb.build_dict(
        re.compile(r'aclImdb/train/.*\.txt$'), cutoff=0)
    # frequency-sorted: 'great' (3x) first; <unk> appended last
    assert word_idx['great'] == 0
    assert word_idx['<unk>'] == max(word_idx.values())
    rows = list(imdb.train(word_idx)())
    assert len(rows) == 3
    labels = [l for _, l in rows]
    assert labels.count(0) == 2 and labels.count(1) == 1  # pos=0, neg=1
    test_rows = list(imdb.test(word_idx)())
    unk = word_idx['<unk>']
    assert ([word_idx['great']], 0) in test_rows
    assert ([unk, word_idx.get('terrible', unk)], 1) in test_rows
    # word_dict() over the tiny corpus: cutoff 150 leaves only <unk>
    assert '<unk>' in imdb.word_dict()


def test_movielens_zip_parse(data_home):
    from paddle_tpu.dataset import movielens
    d = data_home / 'movielens'
    d.mkdir()
    movies = ("1::Toy Story (1995)::Animation|Comedy\n"
              "2::Heat (1995)::Action\n").encode('latin1')
    users = ("1::M::25::12::55117\n"
             "2::F::45::3::55105\n").encode('latin1')
    ratings = ''.join('%d::%d::%d::97830%d\n' % (1 + i % 2, 1 + i % 2,
                                                 1 + i % 5, i)
                      for i in range(40)).encode('latin1')
    with zipfile.ZipFile(str(d / movielens.ARCHIVE), 'w') as z:
        z.writestr('ml-1m/movies.dat', movies)
        z.writestr('ml-1m/users.dat', users)
        z.writestr('ml-1m/ratings.dat', ratings)

    rows = list(movielens.train()())
    test_rows = list(movielens.test()())
    assert len(rows) + len(test_rows) == 40
    assert len(test_rows) > 0                 # the seeded 10% holdout
    uid, gender, age, job, mid, cats, title, rating = rows[0]
    assert uid in (1, 2) and gender in (0, 1)
    assert age == movielens.age_table().index(25 if uid == 1 else 45)
    assert job == (12 if uid == 1 else 3)
    assert isinstance(cats, list) and isinstance(title, list)
    # raw ratings are ints 1..5 → rescaled values are exactly these
    assert rating[0] in (-3.0, -1.0, 1.0, 3.0, 5.0)
    assert movielens.max_user_id() == 2
    assert movielens.max_movie_id() == 2
    assert movielens.max_job_id() == 12
    assert 'Action' in movielens.movie_categories()
    assert 'toy' in movielens.get_movie_title_dict()


def test_imikolov_tar_parse(data_home):
    from paddle_tpu.dataset import imikolov
    d = data_home / 'imikolov'
    d.mkdir()
    train_txt = b'the cat sat\nthe cat ran\n'
    valid_txt = b'the dog sat\n'
    with tarfile.open(str(d / imikolov.ARCHIVE), 'w:gz') as t:
        _add_tar_member(t, './simple-examples/data/ptb.train.txt',
                        train_txt)
        _add_tar_member(t, './simple-examples/data/ptb.valid.txt',
                        valid_txt)
    wd = imikolov.build_dict(min_word_freq=0)
    # 'the' (3x) and the per-line <s>/<e> (3x each) dominate; <unk> last
    assert wd['<unk>'] == max(wd.values())
    assert wd['the'] < wd['dog']
    grams = list(imikolov.train(wd, n=3)())
    framed = ['<s>', 'the', 'cat', 'sat', '<e>']
    want_first = tuple(wd[w] for w in framed[:3])
    assert grams[0] == want_first
    assert len(grams) == 3 + 3 + 0   # two 5-token lines -> 3 trigrams each
    seqs = list(imikolov.train(wd, n=0,
                               data_type=imikolov.DataType.SEQ)())
    assert seqs[0][0][0] == wd['<s>'] and seqs[0][1][-1] == wd['<e>']


def test_wmt16_tar_parse(data_home):
    from paddle_tpu.dataset import wmt16
    d = data_home / 'wmt16'
    d.mkdir()
    train_tsv = (b'a cat\neine katze\n'            # malformed: skipped
                 b'a cat\teine katze\n'
                 b'the cat\tdie katze\n')
    test_tsv = b'a dog\tein hund\n'
    with tarfile.open(str(d / wmt16.ARCHIVE), 'w:gz') as t:
        _add_tar_member(t, 'wmt16/train', train_tsv)
        _add_tar_member(t, 'wmt16/test', test_tsv)
        _add_tar_member(t, 'wmt16/val', test_tsv)
    en = wmt16.get_dict('en', 8)
    de = wmt16.get_dict('de', 8)
    assert en['<s>'] == 0 and en['<e>'] == 1 and en['<unk>'] == 2
    assert 'cat' in en and 'katze' in de        # built from train side
    rows = list(wmt16.train(8, 8)())
    assert len(rows) == 2
    src, trg_in, trg_next = rows[0]
    assert src == [0, en['a'], en['cat'], 1]
    assert trg_in == [0, de['eine'], de['katze']]
    assert trg_next == [de['eine'], de['katze'], 1]
    # de as source swaps columns
    rows_de = list(wmt16.train(8, 8, src_lang='de')())
    assert rows_de[0][0] == [0, de['eine'], de['katze'], 1]
    # unknown words in test map to <unk>=2
    t_rows = list(wmt16.test(8, 8)())
    assert t_rows[0][0] == [0, en.get('a'), 2, 1]


def test_uci_housing_file_parse(data_home):
    from paddle_tpu.dataset import uci_housing
    d = data_home / 'uci_housing'
    d.mkdir()
    rng = np.random.RandomState(7)
    table = rng.rand(10, 14) * 10
    with open(str(d / uci_housing.DATA_FILE), 'w') as f:
        for row in table:
            f.write(' '.join('%.6f' % v for v in row) + '\n')
    rows = list(uci_housing.train()())
    test_rows = list(uci_housing.test()())
    assert len(rows) == 8 and len(test_rows) == 2     # 80/20 in order
    x0, y0 = rows[0]
    assert x0.shape == (13,) and y0.shape == (1,)
    # reference normalization: (x - mean) / (max - min), target raw
    want = (table[0, 0] - table[:, 0].mean()) / \
        (table[:, 0].max() - table[:, 0].min())
    np.testing.assert_allclose(x0[0], want, rtol=1e-5)
    np.testing.assert_allclose(y0[0], table[0, 13], rtol=1e-5)


def test_mq2007_letor_parse(data_home):
    from paddle_tpu.dataset import mq2007
    d = data_home / 'mq2007' / 'Fold1'
    d.mkdir(parents=True)
    def line(rel, qid, base):
        feats = ' '.join('%d:%.3f' % (i + 1, base + i * 0.01)
                         for i in range(46))
        return '%d qid:%d %s #docid = GX%03d\n' % (rel, qid, feats, qid)
    with open(str(d / 'train.txt'), 'w') as f:
        f.write(line(2, 10, 0.5))
        f.write(line(0, 10, 0.1))
        f.write('garbage line\n')                     # skipped
        f.write(line(1, 11, 0.3))
    pt = list(mq2007.train('pointwise')())
    assert [y for _, y in pt] == [2, 0, 1]
    assert pt[0][0].shape == (46,)
    np.testing.assert_allclose(pt[0][0][0], 0.5, rtol=1e-5)
    pairs = list(mq2007.train('pairwise')())
    assert len(pairs) == 1                            # only 2>0 in qid 10
    np.testing.assert_allclose(pairs[0][0][0], 0.5, rtol=1e-5)
    np.testing.assert_allclose(pairs[0][1][0], 0.1, rtol=1e-5)
    lists = list(mq2007.train('listwise')())
    assert len(lists) == 2                            # two queries
    assert lists[0][0].shape == (2, 46)
    assert lists[1][1].tolist() == [1]


def test_conll05_tar_parse(data_home):
    import gzip
    from paddle_tpu.dataset import conll05
    d = data_home / 'conll05st'
    d.mkdir()
    # one 5-token sentence with TWO predicates (columns), then EOS line
    words = 'The cat chased the mouse\n'.replace(' ', '\n') + '\n'
    props_rows = [
        # lemma  pred1-tags  pred2-tags
        ('-', '(A0*', '*'),
        ('-', '*)', '(A0*)'),
        ('chase', '(V*)', '*'),
        ('-', '(A1*', '(V*)'),
        ('see', '*)', '(A1*)'),
    ]
    props = ''.join('\t'.join(r) + '\n' for r in props_rows) + '\n'
    with tarfile.open(str(d / conll05.ARCHIVE), 'w:gz') as t:
        _add_tar_member(t, conll05.WORDS_NAME,
                        gzip.compress(words.encode()))
        _add_tar_member(t, conll05.PROPS_NAME,
                        gzip.compress(props.encode()))
    for fname, items in ((conll05.WORD_DICT_FILE,
                          ['<unk>', 'The', 'cat', 'chased', 'the',
                           'mouse', 'bos', 'eos']),
                         (conll05.VERB_DICT_FILE, ['chase', 'see']),
                         (conll05.LABEL_DICT_FILE,
                          ['O', 'B-A0', 'I-A0', 'B-V', 'I-V', 'B-A1',
                           'I-A1'])):
        with open(str(d / fname), 'w') as f:
            f.write('\n'.join(items) + '\n')

    rows = list(conll05.test()())
    assert len(rows) == 2                     # one per predicate
    (w, n2, n1, c0, p1, p2, pred, mark, lab) = rows[0]
    wd, vd, ld = conll05.get_dict()
    assert w == [wd[t] for t in ['The', 'cat', 'chased', 'the', 'mouse']]
    # predicate 1: verb at index 2 → ctx windows around it
    assert c0 == [wd['chased']] * 5 and n1 == [wd['cat']] * 5
    assert p2 == [wd['mouse']] * 5
    assert mark == [1, 1, 1, 1, 1]            # ±2 covers all 5 tokens
    assert lab == [ld[t] for t in ['B-A0', 'I-A0', 'B-V', 'B-A1',
                                   'I-A1']]
    assert pred == [vd['chase']] * 5
    # predicate 2: verb at index 3, B-A0 single-token at index 1
    lab2 = rows[1][8]
    assert lab2 == [ld[t] for t in ['O', 'B-A0', 'O', 'B-V', 'B-A1']]
    assert rows[1][6] == [vd['see']] * 5


def test_sentiment_zip_parse(data_home):
    from paddle_tpu.dataset import sentiment
    d = data_home / 'sentiment'
    d.mkdir()
    docs = {
        'movie_reviews/neg/cv000.txt': b'bad awful bad',
        'movie_reviews/neg/cv001.txt': b'bad plot',
        'movie_reviews/pos/cv000.txt': b'good great GOOD',
        'movie_reviews/pos/cv001.txt': b'good fun',
    }
    with zipfile.ZipFile(str(d / sentiment.ARCHIVE), 'w') as z:
        for name, data in docs.items():
            z.writestr(name, data)
    wd = dict(sentiment.get_word_dict())
    # frequency-sorted: 'bad' and 'good' (3x each) take ids 0/1
    assert {wd['bad'], wd['good']} == {0, 1}
    rows = list(sentiment.train()())
    assert len(rows) == 4
    # interleaved neg/pos: labels alternate 0,1,0,1
    assert [l for _, l in rows] == [0, 1, 0, 1]
    assert rows[0][0] == [wd['bad'], wd['awful'], wd['bad']]
    assert rows[1][0] == [wd['good'], wd['great'], wd['good']]
    assert list(sentiment.test()()) == []     # tiny corpus: all in train


def test_voc2012_tar_parse(data_home):
    from PIL import Image
    from paddle_tpu.dataset import voc2012
    d = data_home / 'voc2012'
    d.mkdir()

    def jpg_bytes(seed):
        rng = np.random.RandomState(seed)
        im = Image.fromarray(rng.randint(0, 255, (8, 10, 3), 'uint8'))
        buf = io.BytesIO()
        im.save(buf, format='JPEG')
        return buf.getvalue()

    def png_label(cls):
        # grayscale PNG: exact index roundtrip (real VOC uses 'P' with
        # the fixed 256-entry palette; np.array decodes both to the
        # class-index map through the same parser path)
        arr = np.full((8, 10), cls, 'uint8')
        im = Image.fromarray(arr, mode='L')
        buf = io.BytesIO()
        im.save(buf, format='PNG')
        return buf.getvalue()

    with tarfile.open(str(d / voc2012.ARCHIVE), 'w') as t:
        _add_tar_member(t, voc2012.SET_FILE.format('trainval'),
                        b'f0\nf1\n')
        _add_tar_member(t, voc2012.SET_FILE.format('train'), b'f0\n')
        _add_tar_member(t, voc2012.SET_FILE.format('val'), b'f1\n')
        for i in range(2):
            _add_tar_member(t, voc2012.DATA_FILE.format('f%d' % i),
                            jpg_bytes(i))
            _add_tar_member(t, voc2012.LABEL_FILE.format('f%d' % i),
                            png_label(i + 3))
    rows = list(voc2012.train()())
    assert len(rows) == 2                      # trainval list
    img, seg = rows[0]
    assert img.shape == (8, 10, 3) and seg.shape == (8, 10)
    assert (seg == 3).all()                    # palette index preserved
    assert len(list(voc2012.test()())) == 1    # reference quirk: 'train'
    assert (list(voc2012.val()())[0][1] == 4).all()


def test_flowers_tar_parse(data_home):
    import scipy.io as scio
    from PIL import Image
    from paddle_tpu.dataset import flowers
    d = data_home / 'flowers'
    d.mkdir()

    def jpg_bytes(seed):
        rng = np.random.RandomState(seed)
        im = Image.fromarray(rng.randint(0, 255, (300, 280, 3), 'uint8'))
        buf = io.BytesIO()
        im.save(buf, format='JPEG')
        return buf.getvalue()

    with tarfile.open(str(d / flowers.DATA_ARCHIVE), 'w:gz') as t:
        for i in (1, 2, 3, 4):
            _add_tar_member(t, 'jpg/image_%05d.jpg' % i, jpg_bytes(i))
    scio.savemat(str(d / flowers.LABEL_FILE),
                 {'labels': np.array([[5, 6, 7, 8]])})
    scio.savemat(str(d / flowers.SETID_FILE),
                 {'tstid': np.array([[1, 2]]),      # train (swapped)
                  'trnid': np.array([[3]]),
                  'valid': np.array([[4]])})
    rows = list(flowers.train()())
    assert len(rows) == 2
    x, y = rows[0]
    assert x.dtype == np.float32 and x.shape == (3 * 224 * 224,)
    assert y == 4                              # 1-based 5 -> label-1
    t_rows = list(flowers.test()())
    assert len(t_rows) == 1 and t_rows[0][1] == 6
    assert [r[1] for r in flowers.valid()()] == [7]
