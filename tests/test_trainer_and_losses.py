"""Trainer high-level loop + the loss/misc layer wrappers (reference:
v2/trainer.py event-handler loop; test_rank_loss_op.py etc.)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from util import run_startup_and, rand


def test_trainer_event_loop(tmp_path):
    events = []

    def train_func():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        return [fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))]

    rng = np.random.RandomState(0)
    w = rng.randn(4, 1).astype('float32')

    def reader():
        for _ in range(5):
            xs = rng.randn(8, 4).astype('float32')
            yield {'x': xs, 'y': xs @ w}

    trainer = fluid.Trainer(
        train_func=train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.1),
        place=fluid.CPUPlace(), checkpoint_config=str(tmp_path))
    losses = []
    trainer.train(num_epochs=3, event_handler=lambda e: (
        losses.append(float(np.asarray(e.metrics[0]).reshape(())))
        if isinstance(e, fluid.trainer.EndStepEvent) else
        events.append(type(e).__name__)),
        reader=reader)
    assert events.count('BeginEpochEvent') == 3
    assert events.count('EndEpochEvent') == 3
    assert losses[-1] < losses[0]
    assert (tmp_path / 'checkpoint_meta.json').exists() or \
        len(list(tmp_path.iterdir())) > 0  # checkpoint written


def test_huber_log_hinge_losses():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    y = fluid.layers.data(name='y', shape=[3], dtype='float32')
    hl = fluid.layers.huber_loss(x, y, delta=1.0)
    xs = np.array([[0.2, 2.0, -3.0]], dtype='float32')
    ys = np.zeros((1, 3), dtype='float32')
    got = run_startup_and({'x': xs, 'y': ys}, [hl])[0]
    d = ys - xs
    expect = np.where(np.abs(d) <= 1.0, 0.5 * d * d,
                      np.abs(d) - 0.5)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_rank_and_margin_rank_loss():
    lbl = fluid.layers.data(name='l', shape=[1], dtype='float32')
    left = fluid.layers.data(name='a', shape=[1], dtype='float32')
    right = fluid.layers.data(name='b', shape=[1], dtype='float32')
    rl = fluid.layers.rank_loss(lbl, left, right)
    mrl = fluid.layers.margin_rank_loss(lbl, left, right, margin=0.1)
    lv = np.array([[1.0], [0.0]], dtype='float32')
    av = np.array([[2.0], [1.0]], dtype='float32')
    bv = np.array([[1.0], [3.0]], dtype='float32')
    got = run_startup_and({'l': lv, 'a': av, 'b': bv}, [rl, mrl])
    diff = av - bv
    expect_rl = np.log1p(np.exp(diff)) - lv * diff
    np.testing.assert_allclose(got[0], expect_rl, rtol=1e-5)
    # margin rank: max(0, -label*(x1-x2)+margin), label in {-1,1}-ish
    assert np.isfinite(got[1]).all()


def test_row_conv_and_conv_shift_and_dot():
    x = fluid.layers.data(name='x', shape=[5, 4], dtype='float32')
    rc = fluid.layers.row_conv(x, future_context_size=2)
    a = fluid.layers.data(name='a', shape=[6], dtype='float32')
    b = fluid.layers.data(name='b', shape=[3], dtype='float32')
    cs = fluid.layers.conv_shift(a, b)
    d = fluid.layers.dot(a, a)
    got = run_startup_and({'x': rand(2, 5, 4), 'a': rand(2, 6),
                           'b': rand(2, 3)}, [rc, cs, d])
    assert got[0].shape == (2, 5, 4)
    assert got[1].shape == (2, 6)
    av = rand(2, 6)
    np.testing.assert_allclose(got[2], (av * av).sum(-1, keepdims=True),
                               rtol=1e-5)


def test_resize_and_spp():
    img = fluid.layers.data(name='img', shape=[2, 8, 8], dtype='float32')
    rb = fluid.layers.resize_bilinear(img, out_shape=[16, 16])
    rn = fluid.layers.resize_nearest(img, out_shape=[4, 4])
    sp = fluid.layers.spp(img, pyramid_height=2)
    got = run_startup_and({'img': rand(2, 2, 8, 8)}, [rb, rn, sp])
    assert got[0].shape == (2, 2, 16, 16)
    assert got[1].shape == (2, 2, 4, 4)
    assert got[2].shape[0] == 2  # [B, C*(1+4)]


def test_metrics_accumulate():
    m = fluid.metrics.Accuracy()
    m.update(value=0.5, weight=10)
    m.update(value=1.0, weight=10)
    assert abs(m.eval() - 0.75) < 1e-6
    p = fluid.metrics.Precision()
    p.update(preds=np.array([[0.9], [0.2], [0.8]]),
             labels=np.array([[1], [0], [0]]))
    assert 0.0 <= p.eval() <= 1.0
    auc = fluid.metrics.Auc(name='auc')
    preds = np.array([[0.3, 0.7], [0.6, 0.4], [0.2, 0.8]])
    auc.update(preds=preds, labels=np.array([[1], [0], [1]]))
    assert 0.9 <= auc.eval() <= 1.0


def test_lod_bucketed_training_bounds_recompiles():
    """e2e: ragged batches padded to BUCKETED lengths train a sequence
    model while the executor compiles at most one program per bucket —
    the SURVEY §6 static-shape stance actually holding under varying
    sequence lengths (VERDICT r2 weak #9)."""
    from paddle_tpu.core.lod import pad_sequences, bucket_length
    words = fluid.layers.data(name='words', shape=[-1], dtype='int64',
                              lod_level=1)
    length = fluid.layers.data(name='words_len', shape=[], dtype='int32')
    emb = fluid.layers.embedding(input=words, size=[40, 8])
    pooled = fluid.layers.sequence.sequence_pool(emb, 'avg', length=length)
    probs = fluid.layers.fc(input=pooled, size=2, act='softmax')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=probs, label=label))
    fluid.optimizer.Adagrad(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    lengths_seen = set()
    losses = []
    for step in range(12):
        n_max = int(rng.randint(3, 40))  # raw max length varies per batch
        seqs = [rng.randint(1, 40, size=int(rng.randint(1, n_max + 1)))
                for _ in range(8)]
        padded, lens = pad_sequences(seqs, bucketed=True)
        lengths_seen.add(padded.shape[1])
        labels = np.asarray([int(np.mean(sq) > 20) for sq in seqs])
        feed = {'words': padded.astype('int64'),
                'words_len': lens.astype('int32'),
                'label': labels.astype('int64').reshape(-1, 1)}
        losses.append(float(np.asarray(
            exe.run(feed=feed, fetch_list=[loss])[0]).reshape(())))
    # every padded length is a bucket boundary...
    assert lengths_seen <= {16, 32, 64}, lengths_seen
    # ...so the executor compiled once per (bucket) feed signature, not
    # once per raw max length (+1 entry for the startup program)
    assert len(exe._cache) == len(lengths_seen) + 1
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])


def test_trainer_windowed_dispatch_matches_per_step():
    """steps_per_dispatch>1 (run_steps windows, trailing remainder
    per-step) reproduces the per-step trajectory exactly and fires the
    same number of step events."""
    def make(steps_per_dispatch):
        def train_func():
            x = fluid.layers.data(name='x', shape=[4], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            pred = fluid.layers.fc(input=x, size=1)
            return [fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))]

        rng = np.random.RandomState(3)
        w = rng.randn(4, 1).astype('float32')
        batches = []
        r2 = np.random.RandomState(4)
        for _ in range(7):          # 7 = 2 windows of 3 + 1 remainder
            xs = r2.randn(8, 4).astype('float32')
            batches.append({'x': xs, 'y': xs @ w})

        with fluid.scope_guard(fluid.Scope()):
            fluid.reset_default_programs()
            trainer = fluid.Trainer(
                train_func=train_func,
                optimizer_func=lambda: fluid.optimizer.SGD(
                    learning_rate=0.1),
                place=fluid.CPUPlace())
            losses, begins = [], []
            trainer.train(
                num_epochs=1,
                event_handler=lambda e: (
                    losses.append(float(np.asarray(
                        e.metrics[0]).reshape(())))
                    if isinstance(e, fluid.trainer.EndStepEvent) else
                    begins.append(e.step)
                    if isinstance(e, fluid.trainer.BeginStepEvent)
                    else None),
                reader=lambda: iter(batches),
                steps_per_dispatch=steps_per_dispatch)
        return losses, begins

    base, base_begins = make(1)
    win, win_begins = make(3)
    assert len(base) == len(win) == 7
    assert sorted(win_begins) == sorted(base_begins)
    np.testing.assert_allclose(win, base, rtol=1e-5, atol=1e-6)


def test_trainer_windowed_dispatch_bucketed_shapes():
    """A mid-window batch-shape change (bucketed readers) flushes the
    collected prefix per-step instead of crashing np.stack."""
    def train_func():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        return [fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))]

    rng = np.random.RandomState(5)
    w = rng.randn(4, 1).astype('float32')
    sizes = [8, 8, 5, 8, 8, 8, 5]      # bucket switches mid-window

    def reader():
        r = np.random.RandomState(6)
        for b in sizes:
            xs = r.randn(b, 4).astype('float32')
            yield {'x': xs, 'y': xs @ w}

    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        trainer = fluid.Trainer(
            train_func=train_func,
            optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.1),
            place=fluid.CPUPlace())
        losses = []
        trainer.train(
            num_epochs=1,
            event_handler=lambda e: (
                losses.append(float(np.asarray(
                    e.metrics[0]).reshape(())))
                if isinstance(e, fluid.trainer.EndStepEvent) else None),
            reader=reader, steps_per_dispatch=3)
    assert len(losses) == len(sizes)
    assert np.isfinite(losses).all()
