"""v1 recurrent_group / memory / beam_search generation shim
(reference trainer_config_helpers/layers.py:4082/:4215/:4406 — the
seqToseq-era step-function API, VERDICT r4 next-#5). The step function
traces into a fluid DynamicRNN (training) or the generation_decode op
(beam generation); parity checks run against whole-sequence fluid
builds and a manual single-step rollout of the identical step math."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.program import Program, program_guard
from paddle_tpu.trainer_config_helpers import (
    GeneratedInput, ParameterAttribute, SoftmaxActivation, StaticInput,
    TanhActivation, addto_layer, beam_search, classification_cost,
    data_layer, embedding_layer, fc_layer, gru_step_layer, last_seq,
    memory, recurrent_group, simple_attention, simple_gru)


def _run(fetches, feed, program=None):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, exe.run(program=program, feed=feed, fetch_list=fetches)


def test_recurrent_group_stateless_step_matches_whole_sequence():
    """A step that just projects each timestep must equal the fc applied
    to the whole sequence with the SAME weights (shared by name)."""
    x = data_layer(name='xs', size=6, seq_type=1)
    pa = ParameterAttribute(name='rg_fc.w')

    def step(x_t):
        return fc_layer(input=x_t, size=4, act=TanhActivation(),
                        param_attr=ParameterAttribute(name='rg_fc.w'),
                        bias_attr=False)

    seq_out = recurrent_group(step=step, input=x)
    whole = fc_layer(input=x, size=4, act=TanhActivation(),
                     param_attr=pa, bias_attr=False)
    xs = np.random.RandomState(0).randn(3, 5, 6).astype('f')
    lens = np.array([5, 3, 4], 'int32')
    _, (a, b) = _run([seq_out, whole], {'xs': xs, 'xs_len': lens})
    a, b = np.asarray(a), np.asarray(b)
    # masked region: recurrent_group zeroes past each row's length
    for i, l in enumerate(lens):
        np.testing.assert_allclose(a[i, :l], b[i, :l], rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(a[i, l:], 0.0, atol=1e-6)


def test_recurrent_group_memory_accumulates():
    """The named-memory protocol: memory(name='acc') reads the previous
    value of the step layer NAMED 'acc' — an addto accumulator becomes
    a cumulative sum over time."""
    x = data_layer(name='xa', size=4, seq_type=1)

    def step(x_t):
        acc = memory(name='acc', size=4)
        return addto_layer(input=[x_t, acc], name='acc')

    out = recurrent_group(step=step, input=x)
    xs = np.random.RandomState(1).randn(2, 6, 4).astype('f')
    lens = np.array([6, 4], 'int32')
    _, (o,) = _run([out], {'xa': xs, 'xa_len': lens})
    o = np.asarray(o)
    want = np.cumsum(xs, axis=1)
    for i, l in enumerate(lens):
        np.testing.assert_allclose(o[i, :l], want[i, :l], rtol=1e-5,
                                   atol=1e-5)


def _seq2seq_step(emb, state, vocab, hidden, encoded=None,
                  encoded_proj=None):
    """One home for the decoder step math, shared by the training
    recurrent_group, the beam_search generation, and the manual
    single-step rollout program — so the parity test compares the same
    computation through three different harnesses."""
    parts = [emb]
    if encoded is not None:
        ctx = simple_attention(
            encoded_sequence=encoded, encoded_proj=encoded_proj,
            decoder_state=state,
            transform_param_attr=ParameterAttribute(name='att_trans.w'),
            softmax_param_attr=ParameterAttribute(name='att_score.w'))
        parts.append(ctx)
    x = fc_layer(input=parts if len(parts) > 1 else parts[0],
                 size=hidden * 3, bias_attr=False,
                 param_attr=ParameterAttribute(name='dec_proj.w'))
    new_state = gru_step_layer(
        input=x, output_mem=state, name='dec_state',
        param_attr=ParameterAttribute(name='dec_gru.w'),
        bias_attr=ParameterAttribute(name='dec_gru.b'))
    prob = fc_layer(input=new_state, size=vocab,
                    act=SoftmaxActivation(),
                    param_attr=ParameterAttribute(name='dec_out.w'),
                    bias_attr=ParameterAttribute(name='dec_out.b'))
    return prob, new_state


def _build_encoder(vocab, emb_dim, hidden, src_name='src'):
    src = data_layer(name=src_name, size=vocab, dtype='int64', seq_type=1)
    emb = embedding_layer(input=src, size=emb_dim,
                          param_attr=ParameterAttribute(name='src_emb'))
    enc = simple_gru(input=emb, size=hidden,
                     mixed_param_attr=ParameterAttribute(name='enc_mix.w'),
                     gru_param_attr=ParameterAttribute(name='enc_gru.w'),
                     gru_bias_attr=ParameterAttribute(name='enc_gru.b'))
    boot = fc_layer(input=last_seq(input=enc), size=hidden,
                    act=TanhActivation(),
                    param_attr=ParameterAttribute(name='boot.w'),
                    bias_attr=ParameterAttribute(name='boot.b'))
    enc_proj = fc_layer(input=enc, size=hidden, bias_attr=False,
                        param_attr=ParameterAttribute(name='enc_proj.w'))
    return enc, enc_proj, boot


def test_seq2seq_recurrent_group_trains_and_beam_generates():
    """The seqToseq shape end-to-end by changing only the import line:
    bi-directionless GRU encoder, attention decoder as a
    recurrent_group over the target sequence, trained on a copy task;
    then beam_search generation with GeneratedInput feedback + the
    SAME parameter names reproduces the copy mapping."""
    V, E, H, T = 20, 12, 16, 5
    enc, enc_proj, boot = _build_encoder(V, E, H)
    trg = data_layer(name='trg', size=V, dtype='int64', seq_type=1)
    trg_emb = embedding_layer(
        input=trg, size=E, param_attr=ParameterAttribute(name='trg_emb'))
    lbl = data_layer(name='lbl', size=1, dtype='int64', seq_type=1)

    def train_step(emb_t):
        state = memory(name='dec_state', size=H, boot_layer=boot)
        return _seq2seq_step(emb_t, state, V, H, encoded=enc,
                             encoded_proj=enc_proj)[0]

    probs = recurrent_group(step=train_step, input=trg_emb)
    cost = classification_cost(input=probs, label=lbl)
    fluid.optimizer.Adam(learning_rate=8e-3).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    b = 8
    src = rng.randint(2, V, (b, T)).astype('int64')
    lbl_ids = src.copy()                        # copy task
    trg_in = np.concatenate([np.ones((b, 1), 'int64'),
                             lbl_ids[:, :-1]], axis=1)
    feed = {'src': src, 'src_len': np.full((b,), T, 'int32'),
            'trg': trg_in, 'trg_len': np.full((b,), T, 'int32'),
            'lbl': lbl_ids[..., None], 'lbl_len': np.full((b,), T,
                                                          'int32')}
    losses = []
    for _ in range(150):
        loss, = exe.run(feed=feed, fetch_list=[cost])
        losses.append(float(np.asarray(loss).reshape(())))
    assert losses[-1] < losses[0] * 0.5

    # ---- beam generation in a fresh program, params shared by name
    gp = Program()
    with program_guard(gp, fluid.default_startup_program()):
        enc_g, proj_g, boot_g = _build_encoder(V, E, H, src_name='src')

        def gen_step(enc_s, proj_s, boot_s, emb):
            state = memory(name='dec_state', size=H, boot_layer=boot_s)
            return _seq2seq_step(emb, state, V, H, encoded=enc_s,
                                 encoded_proj=proj_s)[0]

        ids = beam_search(
            step=gen_step,
            input=[StaticInput(enc_g, is_seq=True), StaticInput(proj_g),
                   StaticInput(boot_g), GeneratedInput(
                       size=V, embedding_name='trg_emb',
                       embedding_size=E)],
            bos_id=1, eos_id=0, beam_size=4, max_length=T)
        scores = ids._beam_scores
    f = {'src': src, 'src_len': np.full((b,), T, 'int32')}
    bi, bs = (np.asarray(v) for v in exe.run(
        program=gp, feed=f, fetch_list=[ids, scores]))
    assert bi.shape == (b, 4, T)
    assert np.all(np.diff(bs, axis=1) <= 1e-5)   # sorted best-first
    # the trained copy task: the top beam reproduces the source
    assert (bi[:, 0, :] == lbl_ids).mean() > 0.8


def test_beam_size1_matches_manual_single_step_rollout():
    """Numeric parity vs the fluid build: beam_size=1 generation must
    equal a manual python rollout of a SINGLE-STEP program built from
    the identical step function (the per-token re-run the reference's
    generator performed)."""
    V, E, H, T = 12, 8, 8, 4
    b = 4
    # params + a few random training steps so weights are non-trivial
    enc, enc_proj, boot = _build_encoder(V, E, H)
    trg = data_layer(name='trg', size=V, dtype='int64', seq_type=1)
    trg_emb = embedding_layer(
        input=trg, size=E, param_attr=ParameterAttribute(name='trg_emb'))
    lbl = data_layer(name='lbl', size=1, dtype='int64', seq_type=1)

    def train_step(emb_t):
        state = memory(name='dec_state', size=H, boot_layer=boot)
        return _seq2seq_step(emb_t, state, V, H, encoded=enc,
                             encoded_proj=enc_proj)[0]

    probs = recurrent_group(step=train_step, input=trg_emb)
    cost = classification_cost(input=probs, label=lbl)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(3)
    src = rng.randint(2, V, (b, T)).astype('int64')
    feed = {'src': src, 'src_len': np.full((b,), T, 'int32'),
            'trg': src, 'trg_len': np.full((b,), T, 'int32'),
            'lbl': src[..., None], 'lbl_len': np.full((b,), T, 'int32')}
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[cost])

    gp = Program()
    with program_guard(gp, fluid.default_startup_program()):
        enc_g, proj_g, boot_g = _build_encoder(V, E, H, src_name='src')

        def gen_step(enc_s, proj_s, boot_s, emb):
            state = memory(name='dec_state', size=H, boot_layer=boot_s)
            return _seq2seq_step(emb, state, V, H, encoded=enc_s,
                                 encoded_proj=proj_s)[0]

        ids = beam_search(
            step=gen_step,
            input=[StaticInput(enc_g, is_seq=True), StaticInput(proj_g),
                   StaticInput(boot_g), GeneratedInput(
                       size=V, embedding_name='trg_emb',
                       embedding_size=E)],
            bos_id=1, eos_id=0, beam_size=1, max_length=T)
    f = {'src': src, 'src_len': np.full((b,), T, 'int32')}
    got = np.asarray(exe.run(program=gp, feed=f, fetch_list=[ids])[0])

    # single-step program: same step fn, state/ids fed from python
    sp = Program()
    with program_guard(sp, fluid.default_startup_program()):
        enc_s, proj_s, boot_s = _build_encoder(V, E, H, src_name='src')
        import paddle_tpu.layers as L
        prev = L.data(name='prev_id', shape=[], dtype='int64')
        st = L.data(name='state_in', shape=[H], dtype='float32')
        emb_s = L.embedding(
            input=prev, size=[V, E],
            param_attr=fluid.ParamAttr(name='trg_emb'))
        # note the named layer writes: gru_step_layer(name='dec_state')
        # just produces the var here — no active recurrent context
        prob_s, new_state_var = _seq2seq_step(
            emb_s, st, V, H, encoded=enc_s, encoded_proj=proj_s)
    state = None
    ids_np = np.full((b,), 1, 'int64')
    out_steps = []
    # boot state: fetch boot_g value via the single-step program's boot
    boot_val = np.asarray(exe.run(program=sp, feed=dict(
        f, prev_id=ids_np, state_in=np.zeros((b, H), 'f')),
        fetch_list=[boot_s])[0])
    state = boot_val
    for _ in range(T):
        prob_v, ns = (np.asarray(v) for v in exe.run(
            program=sp,
            feed=dict(f, prev_id=ids_np, state_in=state.astype('f')),
            fetch_list=[prob_s, new_state_var]))
        ids_np = prob_v.argmax(axis=-1).astype('int64')
        state = ns
        out_steps.append(ids_np.copy())
    want = np.stack(out_steps, axis=1)
    # freeze after eos like the decode op
    seen = np.cumsum(want == 0, axis=1)
    want = np.where((seen >= 1) & (want != 0), 0, want)
    np.testing.assert_array_equal(got[:, 0, :], want)


def test_recurrent_group_target_inlink_length():
    """targetInlink selects which input link's sequence layout the
    output follows (reference :4133) — the output's length var must be
    the designated link's, not the first input's."""
    from paddle_tpu.trainer_config_helpers.layers import _len_of
    a = data_layer(name='tia', size=4, seq_type=1)
    b = data_layer(name='tib', size=4, seq_type=1)

    def step(a_t, b_t):
        return fc_layer(input=[a_t, b_t], size=3,
                        param_attr=ParameterAttribute(name='ti_fc.w'),
                        bias_attr=False)

    out = recurrent_group(step=step, input=[a, b], targetInlink=b)
    assert _len_of(out) is _len_of(b)


def test_beam_generation_on_dp_mesh_matches_unsharded():
    """generation_decode under a dp mesh (batch sharded over 8 devices,
    memories/statics follow via GSPMD propagation) emits exactly the
    unsharded beams — the new op composes with the transpiler like the
    transformer decode ops do."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                                transpile)
    V, E, H, T = 12, 8, 8, 4
    b = 8
    enc, enc_proj, boot = _build_encoder(V, E, H)
    trg = data_layer(name='trg', size=V, dtype='int64', seq_type=1)
    trg_emb = embedding_layer(
        input=trg, size=E, param_attr=ParameterAttribute(name='trg_emb'))
    lbl = data_layer(name='lbl', size=1, dtype='int64', seq_type=1)

    def train_step(emb_t):
        state = memory(name='dec_state', size=H, boot_layer=boot)
        return _seq2seq_step(emb_t, state, V, H, encoded=enc,
                             encoded_proj=enc_proj)[0]

    probs = recurrent_group(step=train_step, input=trg_emb)
    cost = classification_cost(input=probs, label=lbl)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(9)
    src = rng.randint(2, V, (b, T)).astype('int64')
    feed = {'src': src, 'src_len': np.full((b,), T, 'int32'),
            'trg': src, 'trg_len': np.full((b,), T, 'int32'),
            'lbl': src[..., None], 'lbl_len': np.full((b,), T, 'int32')}
    for _ in range(5):
        exe.run(feed=feed, fetch_list=[cost])

    def build(mesh):
        gp = Program()
        with program_guard(gp, fluid.default_startup_program()):
            enc_g, proj_g, boot_g = _build_encoder(V, E, H,
                                                   src_name='src')

            def gen_step(enc_s, proj_s, boot_s, emb):
                state = memory(name='dec_state', size=H,
                               boot_layer=boot_s)
                return _seq2seq_step(emb, state, V, H, encoded=enc_s,
                                     encoded_proj=proj_s)[0]

            ids = beam_search(
                step=gen_step,
                input=[StaticInput(enc_g, is_seq=True),
                       StaticInput(proj_g), StaticInput(boot_g),
                       GeneratedInput(size=V, embedding_name='trg_emb',
                                      embedding_size=E)],
                bos_id=1, eos_id=0, beam_size=4, max_length=T)
        if mesh is not None:
            transpile(gp, mesh, ParallelStrategy(data_parallel=True))
        return gp, ids

    f = {'src': src, 'src_len': np.full((b,), T, 'int32')}
    gp_u, ids_u = build(None)
    got_u = np.asarray(exe.run(program=gp_u, feed=f,
                               fetch_list=[ids_u])[0])
    gp_s, ids_s = build(make_mesh(dp=8))
    got_s = np.asarray(exe.run(program=gp_s, feed=f,
                               fetch_list=[ids_s])[0])
    np.testing.assert_array_equal(got_s, got_u)


def test_lstm_recurrent_group_unit_pattern():
    """The reference lstmemory_unit pattern inside recurrent_group (r5:
    lstm_step_layer over a pre-projected gate input + cell memory via
    get_output_layer(arg_name='state')): trains, and the whole-sequence
    output matches a manual single-step rollout of the same IR."""
    from paddle_tpu.trainer_config_helpers import (
        full_matrix_projection, get_output_layer, lstm_step_layer,
        mixed_layer, regression_cost)
    V, H, T, b = 10, 6, 4, 3
    x = data_layer(name='xl', size=V, seq_type=1)

    def step(x_t):
        out_mem = memory(name='lstm_out', size=H)
        cell_mem = memory(name='lstm_out_state', size=H)
        gates = mixed_layer(
            size=H * 4,
            input=[full_matrix_projection(
                       x_t, param_attr=ParameterAttribute(name='lx.w')),
                   full_matrix_projection(
                       out_mem,
                       param_attr=ParameterAttribute(name='lh.w'))],
            bias_attr=False)
        h = lstm_step_layer(input=gates, state=cell_mem,
                            name='lstm_out')
        get_output_layer(input=h, arg_name='state',
                         name='lstm_out_state')
        return h

    seq = recurrent_group(step=step, input=x)
    pred = fc_layer(input=last_seq(input=seq), size=1,
                    param_attr=ParameterAttribute(name='lp.w'))
    y = data_layer(name='yl', size=1)
    cost = regression_cost(input=pred, label=y)
    fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    xs = rng.randn(b, T, V).astype('f')
    feed = {'xl': xs, 'xl_len': np.full((b,), T, 'int32'),
            'yl': rng.randn(b, 1).astype('f')}
    losses = [float(np.asarray(exe.run(feed=feed,
                                       fetch_list=[cost])[0]).reshape(()))
              for _ in range(30)]
    assert losses[-1] < losses[0]

    # manual rollout FIRST: the training program's fetch run would also
    # apply one more SGD update after computing its outputs, so the
    # rollout (update-free program) must read the same param state
    sp = Program()
    with program_guard(sp, fluid.default_startup_program()):
        import paddle_tpu.layers as L
        xt = L.data(name='xt', shape=[V], dtype='float32')
        hp = L.data(name='hp', shape=[H], dtype='float32')
        cp = L.data(name='cp', shape=[H], dtype='float32')
        g1 = L.fc(input=xt, size=4 * H, bias_attr=False,
                  param_attr=fluid.ParamAttr(name='lx.w'))
        g2 = L.fc(input=hp, size=4 * H, bias_attr=False,
                  param_attr=fluid.ParamAttr(name='lh.w'))
        gate_sum = L.elementwise_add(g1, g2)
        hs = lstm_step_layer(input=gate_sum, state=cp)
        cs = hs._v1_cell
    hvec = np.zeros((b, H), 'f')
    cvec = np.zeros((b, H), 'f')
    for t in range(T):
        hvec, cvec = (np.asarray(v) for v in exe.run(
            program=sp, feed={'xt': xs[:, t], 'hp': hvec, 'cp': cvec},
            fetch_list=[hs, cs]))
    got = np.asarray(exe.run(feed=feed, fetch_list=[seq])[0])
    np.testing.assert_allclose(got[:, T - 1], hvec, rtol=1e-4,
                               atol=1e-5)
