"""Training raw speed (ISSUE 19): bucketed backward/allreduce overlap
(deterministic size-targeted assignment, bit-identical exact path,
per-call PADDLE_TPU_GRAD_BUCKET_MB knob), fp8(e4m3) matmul (parity,
straight-through gradients, tuner-table dispatch with the explicit
PADDLE_TPU_FP8_MATMUL gate beating the table), ZeRO-1 sharded optimizer
state (bit-identity, analytic memory ledger + gauges, env override),
the overlap-fraction gauge, the quantized+bucketed composition bound,
and the analysis pass's zero-* contracts."""

import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import observe, tuning
from paddle_tpu.parallel.collective import (assign_grad_buckets,
                                            grad_bucket_policy)
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                            optimizer_state_bytes,
                                            shard_opt_state_env,
                                            transpile)

DP = 8
IN, HID, BATCH = 16, 32, 4


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in ('PADDLE_TPU_GRAD_BUCKET_MB', 'PADDLE_TPU_SHARD_OPT_STATE',
                'PADDLE_TPU_FP8_MATMUL', 'PADDLE_TPU_AUTOTUNE',
                'PADDLE_TPU_TUNING_TABLE', 'PADDLE_TPU_QUANT_ALLREDUCE'):
        monkeypatch.delenv(var, raising=False)
    yield
    tuning.set_timer(None)
    tuning.reset()
    observe.disable()
    observe.reset()


# ------------------------------------------------- bucket assignment
def test_bucket_assignment_reversed_and_size_targeted():
    # parameter order w1 b1 w2 b2; the walk is REVERSED (backward
    # production order) and greedy against the byte target
    items = [(2048, 'float32'), (128, 'float32'),
             (128, 'float32'), (4, 'float32')]
    buckets = assign_grad_buckets(items, 104)
    # every index exactly once, last params first
    assert sorted(i for b in buckets for i in b) == [0, 1, 2, 3]
    assert buckets[0][0] == 3
    assert len(buckets) == 4          # 4+128 > 104 closes immediately
    # a roomier target merges the small tail grads into one bucket
    buckets = assign_grad_buckets(items, 1024)
    assert buckets[0] == [3, 2, 1]    # 4+128+128 <= 1024
    assert buckets[1] == [0]          # 2048 alone exceeds the target
    # deterministic: identical inputs, identical assignment
    assert assign_grad_buckets(items, 1024) == \
        assign_grad_buckets(list(items), 1024)


def test_bucket_assignment_group_change_closes():
    # buckets never mix dtype groups — concatenation must not promote
    items = [(8, 'float32'), (8, 'float32'),
             (8, 'bfloat16'), (8, 'bfloat16')]
    buckets = assign_grad_buckets(items, 1 << 20)
    assert buckets == [[3, 2], [1, 0]]


def test_bucket_assignment_oversized_and_edge():
    assert assign_grad_buckets([(999, 'f4')], 10) == [[0]]
    assert assign_grad_buckets([], 10) == []


def test_grad_bucket_policy_env_beats_program(monkeypatch):
    prog = types.SimpleNamespace(grad_bucket_mb=2.0)
    assert grad_bucket_policy(prog) == ('mb', 2.0)
    assert grad_bucket_policy(types.SimpleNamespace()) is None
    monkeypatch.setenv('PADDLE_TPU_GRAD_BUCKET_MB', '4')
    assert grad_bucket_policy(prog) == ('mb', 4.0)
    assert grad_bucket_policy(None) == ('mb', 4.0)
    for off in ('0', 'off', 'false'):
        monkeypatch.setenv('PADDLE_TPU_GRAD_BUCKET_MB', off)
        assert grad_bucket_policy(prog) is None
    monkeypatch.setenv('PADDLE_TPU_GRAD_BUCKET_MB', '')
    assert grad_bucket_policy(prog) == ('mb', 2.0)   # blank = unset


# --------------------------------------------------- e2e train legs
def _train(bucket_mb=None, shard_opt=False, quant_on=False, opt='sgd',
           dp=DP, steps=8, seed=3):
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[IN], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=HID, act='relu')
    pred = fluid.layers.fc(input=h, size=1, act=None)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    if opt == 'adam':
        fluid.optimizer.Adam(learning_rate=0.125).minimize(cost)
    else:
        fluid.optimizer.SGD(learning_rate=0.125).minimize(cost)
    prog = fluid.default_main_program()
    prog.random_seed = 7
    transpile(prog, make_mesh(dp=dp), ParallelStrategy(
        grad_bucket_mb=bucket_mb,
        shard_optimizer_state=True if shard_opt else None,
        quantized_allreduce=quant_on))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    # dyadic feed values (k/8): dp partial sums are exact in fp32 under
    # any association, so bit-identity asserts are meaningful
    rng = np.random.RandomState(seed)
    X = (rng.randint(-8, 8, (BATCH * dp, IN)) / 8.0).astype('float32')
    Y = (rng.randint(-8, 8, (BATCH * dp, 1)) / 8.0).astype('float32')
    losses = []
    for _ in range(steps):
        got = exe.run(feed={'x': X, 'y': Y}, fetch_list=[cost])
        losses.append(float(np.asarray(got[0]).reshape(())))
    weights = {p.name: np.asarray(fluid.global_scope().find(p.name))
               for p in prog.all_parameters()}
    return losses, weights, prog


def test_bucketed_bit_identical_across_bucket_sizes():
    """The exact bucketed path is a pure relayout: any bucket size must
    give the same bits as the unbucketed allreduce."""
    observe.enable()
    _, w_ref, _ = _train()
    for mb in (0.004, 1e-4):
        _, w_b, _ = _train(bucket_mb=mb)
        for k in w_ref:
            assert np.array_equal(w_ref[k], w_b[k]), (mb, k)
    g = observe.snapshot()['gauges']
    # the 1e-4MB (104-byte) leg ran last: every grad but the biases
    # exceeds the target, so the net splits into several buckets
    assert g.get('trainer.grad_bucket_count', 0) >= 2
    assert g.get('trainer.grad_bucket_target_bytes') == int(1e-4 * 2**20)
    assert g.get('trainer.grad_bucket_max_bytes', 0) >= IN * HID * 4


def test_bucketed_env_knob_per_call(monkeypatch):
    """PADDLE_TPU_GRAD_BUCKET_MB=0 disables bucketing even when the
    strategy asked for it — and the run stays bit-identical."""
    observe.enable()
    _, w_ref, _ = _train()
    monkeypatch.setenv('PADDLE_TPU_GRAD_BUCKET_MB', '0')
    _, w_off, prog = _train(bucket_mb=1e-4)
    assert grad_bucket_policy(prog) is None
    for k in w_ref:
        assert np.array_equal(w_ref[k], w_off[k])


# ------------------------------------------------------- fp8 matmul
def _skip_no_fp8():
    from paddle_tpu.ops.fp8_matmul import fp8_supported
    if not fp8_supported():
        pytest.skip('jax build has no float8_e4m3fn')


def test_fp8_matmul_parity_and_straight_through_grads():
    _skip_no_fp8()
    from paddle_tpu.ops.fp8_matmul import fp8_matmul
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(48, 32).astype('float32'))
    b = jnp.asarray(rng.randn(32, 24).astype('float32'))
    ref = np.asarray(jnp.matmul(a, b))
    got = np.asarray(fp8_matmul(a, b))
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 0.05, rel
    assert got.dtype == ref.dtype
    # straight-through vjp: gradients are the EXACT f32 matmul vjp —
    # fp8 quantization error must not leak into the backward
    gx, gy = jax.grad(lambda x, y: fp8_matmul(x, y).sum(),
                      argnums=(0, 1))(a, b)
    rx, ry = jax.grad(lambda x, y: jnp.matmul(x, y).sum(),
                      argnums=(0, 1))(a, b)
    assert np.array_equal(np.asarray(gx), np.asarray(rx))
    assert np.array_equal(np.asarray(gy), np.asarray(ry))


def test_fp8_dispatch_table_and_env_gate(tmp_path, monkeypatch):
    """Dispatch discipline: fp8 runs only where the tuner measured a
    win; the explicit env gate beats the table in either direction."""
    _skip_no_fp8()
    from paddle_tpu.ops.fp8_matmul import maybe_fp8_matmul
    observe.enable()
    a = jnp.ones((32, 32), jnp.float32)
    b = jnp.ones((32, 32), jnp.float32)

    def count():
        return observe.snapshot()['counters'].get(
            'fp8.matmul_dispatch_total', 0)

    # no table, no gate -> no dispatch (autotune off by default)
    assert maybe_fp8_matmul(a, b) is None
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE', 'record')
    # fp8-winning table -> dispatched, counter moves
    monkeypatch.setenv('PADDLE_TPU_TUNING_TABLE',
                       str(tmp_path / 'fp8_wins.json'))
    tuning.reset()
    tuning.set_timer(lambda op, key, v, t:
                     0.001 if v.get('impl') == 'fp8' else 0.010)
    c0 = count()
    out = maybe_fp8_matmul(a, b)
    assert out is not None
    assert np.allclose(np.asarray(out), 32.0, rtol=0.05)
    assert count() == c0 + 1
    # gate '0' beats the fp8-winning table
    monkeypatch.setenv('PADDLE_TPU_FP8_MATMUL', '0')
    assert maybe_fp8_matmul(a, b) is None
    # native-winning table -> not dispatched, counter still
    monkeypatch.delenv('PADDLE_TPU_FP8_MATMUL')
    monkeypatch.setenv('PADDLE_TPU_TUNING_TABLE',
                       str(tmp_path / 'native_wins.json'))
    tuning.reset()
    tuning.set_timer(lambda op, key, v, t:
                     0.001 if v.get('impl') == 'native' else 0.010)
    c0 = count()
    assert maybe_fp8_matmul(a, b) is None
    assert count() == c0
    # gate '1' beats the native-winning table
    monkeypatch.setenv('PADDLE_TPU_FP8_MATMUL', '1')
    assert maybe_fp8_matmul(a, b) is not None


def test_fp8_matmul_rejects_non_2d_and_ints():
    from paddle_tpu.ops.fp8_matmul import maybe_fp8_matmul
    f = jnp.ones((4, 4), jnp.float32)
    assert maybe_fp8_matmul(jnp.ones((4,), jnp.float32), f) is None
    assert maybe_fp8_matmul(jnp.ones((2, 4, 4), jnp.float32), f) is None
    assert maybe_fp8_matmul(jnp.ones((4, 4), jnp.int32),
                            jnp.ones((4, 4), jnp.int32)) is None


# ------------------------------------------------------------ ZeRO-1
def test_zero1_bit_identical_and_memory_model():
    observe.enable()
    _, w_r, prog_r = _train(opt='adam')
    _, w_z, prog_z = _train(opt='adam', shard_opt=True)
    for k in w_r:
        assert np.array_equal(w_r[k], w_z[k]), k
    mem_r = optimizer_state_bytes(prog_r)
    mem_z = optimizer_state_bytes(prog_z)
    assert mem_r['total'] == mem_z['total']
    assert mem_r['reduction'] == pytest.approx(1.0)
    # accumulators shard ~dp x; only the [1]-shaped beta-pow scalars
    # stay replicated
    assert mem_z['reduction'] >= 0.8 * DP, mem_z
    assert mem_z['per_device'] < mem_r['per_device'] / (0.8 * DP)
    assert mem_z['n_state_vars'] == mem_r['n_state_vars']
    g = observe.snapshot()['gauges']
    assert g.get('trainer.optimizer_state_bytes_total') == mem_z['total']
    assert g.get('trainer.optimizer_state_bytes_per_device') == \
        pytest.approx(mem_z['per_device'])
    assert g.get('trainer.optimizer_state_reduction_x') >= 0.8 * DP
    # the transpiled program honors the zero-* analysis contracts
    from paddle_tpu import analysis
    diags = analysis.run_passes(prog_z)
    assert not [d for d in diags if d.code.startswith('zero-')], diags


def test_zero1_env_override(monkeypatch):
    assert shard_opt_state_env(True) is True
    assert shard_opt_state_env(False) is False
    assert shard_opt_state_env(None) is False
    monkeypatch.setenv('PADDLE_TPU_SHARD_OPT_STATE', '1')
    assert shard_opt_state_env(False) is True
    monkeypatch.setenv('PADDLE_TPU_SHARD_OPT_STATE', 'off')
    assert shard_opt_state_env(True) is False


def test_analysis_zero1_contract_warnings():
    """Structural zero-* checks fire on a hand-built program whose
    optimizer state specs disagree and whose grad stayed replicated."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu import analysis
    prog = fluid.Program()
    b = prog.global_block()
    b.create_parameter('w', shape=[8, 4], dtype='float32')
    b.create_var(name='w@GRAD', shape=[8, 4], dtype='float32')
    b.create_var(name='lr', shape=[1], dtype='float32',
                 persistable=True)
    b.create_var(name='m1', shape=[8, 4], dtype='float32',
                 persistable=True)
    b.create_var(name='m2', shape=[8, 4], dtype='float32',
                 persistable=True)
    b.append_op('adam',
                inputs={'Param': ['w'], 'Grad': ['w@GRAD'],
                        'LearningRate': ['lr'],
                        'Moment1': ['m1'], 'Moment2': ['m2']},
                outputs={'ParamOut': ['w'], 'Moment1Out': ['m1'],
                         'Moment2Out': ['m2']})
    prog.mesh = make_mesh(dp=8)
    prog.var_shardings = {'w': P(), 'm1': P('dp', None), 'm2': P()}
    diags = analysis.run_passes(prog)
    codes = {d.code for d in diags}
    assert 'zero-state-spec-mismatch' in codes
    assert 'zero-grad-replicated' in codes
    mism = [d for d in diags if d.code == 'zero-state-spec-mismatch'][0]
    assert mism.severity == 'warning' and mism.var == 'w'
    repl = [d for d in diags if d.code == 'zero-grad-replicated'][0]
    assert repl.var == 'w@GRAD'


# --------------------------------------------- overlap + composition
def test_overlap_fraction_math():
    f = observe.overlap_fraction
    assert f(1.0, 1.0, 1.0) == pytest.approx(1.0)     # fully hidden
    assert f(2.0, 1.0, 1.0) == pytest.approx(0.0)     # fully serial
    assert f(1.5, 1.0, 1.0) == pytest.approx(0.5)
    assert f(0.5, 1.0, 0.2) == 1.0                    # clamped high
    assert f(9.9, 1.0, 1.0) == 0.0                    # clamped low
    assert f(0.0, 1.0, 1.0) is None                   # degenerate
    assert f(1.0, -1.0, 1.0) is None
    assert f(None, 1.0, 1.0) is None
    assert f('x', 1.0, 1.0) is None


def test_record_allreduce_overlap_gauge():
    from paddle_tpu.trainer import record_allreduce_overlap
    observe.enable()
    frac = record_allreduce_overlap(1.5, 1.0, 1.0)
    assert frac == pytest.approx(0.5)
    g = observe.snapshot()['gauges']
    assert g.get('trainer.allreduce_overlap_fraction') == \
        pytest.approx(0.5)
    # degenerate legs record nothing and return None
    assert record_allreduce_overlap(0.0, 1.0, 1.0) is None


def test_quantized_plus_bucketed_composition():
    """EQuARX int8 gradient compression rides inside the buckets; the
    composed run must train to the same neighborhood as exact."""
    loss_f, _, _ = _train(steps=12)
    loss_qb, _, _ = _train(bucket_mb=1e-4, quant_on=True, steps=12)
    tol = max(0.05, 0.25 * abs(loss_f[-1]))
    assert abs(loss_qb[-1] - loss_f[-1]) <= tol, (loss_f[-1],
                                                  loss_qb[-1])
