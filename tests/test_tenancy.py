"""Multi-tenant fleet (ISSUE 18): priority classes, token-bucket
quotas charged at router admission (typed QuotaExceededError over the
QueueFullError hierarchy and the RPC wire), tenant-prefixed rendezvous
session pinning, priority-aware decode preemption / prefix-cache
eviction, the training/serving co-location yield (bit-identical
params), metrics_report --tenants, and the bench.py multitenant
acceptance scenario."""

import json
import os
import subprocess
import sys
import threading
import time

from concurrent.futures import Future

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io as _io
from paddle_tpu import observe
from paddle_tpu.observe.slo import Objective, SloTracker
from paddle_tpu.serving import (PRIORITIES, QueueFullError,
                                QuotaExceededError, Router,
                                TenantRegistry, colocation_yield,
                                slo_burn_pressure, tenant_of_session)
from paddle_tpu.serving.decode.kv_pool import BlockTable, KVPool
from paddle_tpu.serving.decode.prefix_cache import PrefixCache
from paddle_tpu.serving.decode.scheduler import (RUNNING, WAITING,
                                                 Scheduler, Sequence)
from paddle_tpu.serving.rpc import _ERR_STATUS, _error_classes
from paddle_tpu.serving.tenancy import TokenBucket, priority_rank

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _observe_clean():
    from paddle_tpu.observe import diagnostics
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.disable()
    observe.reset()
    with diagnostics._checks_lock:
        diagnostics._checks.clear()


class FakeReplica(object):
    """Duck-typed replica: resolves immediately with its own name."""

    def __init__(self, name, ready=True):
        self.name = name
        self._ready = ready
        self.submitted = 0

    def ready(self):
        return self._ready

    def queue_depth(self):
        return 0

    def submit(self, feed, ctx=None):
        self.submitted += 1
        f = Future()
        f.set_result([self.name])
        return f

    def drain(self, timeout=None):
        return True

    def shutdown(self, drain=True):
        self._ready = False


# --------------------------------------------------------- token bucket
def test_token_bucket_refill_and_refund_deterministic():
    b = TokenBucket(rate=2.0, burst=2.0)
    assert b.try_charge(1.0, now=0.0)
    assert b.try_charge(1.0, now=0.0)
    assert not b.try_charge(1.0, now=0.0)       # burst spent
    assert not b.try_charge(1.0, now=0.25)      # refilled only 0.5
    assert b.try_charge(1.0, now=0.5)           # 0.5 + 0.5 = 1.0
    # a full second refills to burst, never beyond it
    assert b.try_charge(2.0, now=10.0)
    assert not b.try_charge(0.5, now=10.0)
    b.refund(1.0)
    assert b.try_charge(1.0, now=10.0)
    # refund caps at burst
    b.refund(100.0)
    assert b.tokens == 2.0
    # the clock never runs backwards (stale now <= last is a no-op refill)
    assert b.try_charge(2.0, now=20.0)
    assert not b.try_charge(1.0, now=5.0)


def test_session_parsing_and_priority_rank():
    assert tenant_of_session('acme/user-42') == 'acme'
    assert tenant_of_session('acme/a/b') == 'acme'
    assert tenant_of_session('user-42') == 'default'
    assert tenant_of_session(None) == 'default'
    assert tenant_of_session('/oops') == 'default'
    assert tenant_of_session(1234) == 'default'
    assert [priority_rank(p) for p in PRIORITIES] == [0, 1, 2]
    # None and unknown classes land on 'standard': untenanted traffic
    # keeps today's scheduling behavior exactly
    assert priority_rank(None) == 1
    assert priority_rank('no-such-class') == 1


# ------------------------------------------------------------ admission
def test_registry_admit_sheds_typed_and_recovers():
    observe.enable()
    reg = TenantRegistry()
    reg.add('acme', priority='interactive', request_rate=2.0)
    reg.admit('acme/u1', now=0.0)
    reg.admit('acme/u2', now=0.0)
    with pytest.raises(QuotaExceededError) as ei:
        reg.admit('acme/u1', now=0.0)
    assert isinstance(ei.value, QueueFullError)  # existing paths apply
    assert 'requests' in str(ei.value)
    # continuous refill on the caller's clock: admitted again later
    reg.admit('acme/u1', now=1.0)
    assert observe.get_counter('tenant.admitted', tenant='acme',
                               priority='interactive',
                               route='serve') == 3
    assert observe.get_counter('tenant.shed', tenant='acme',
                               priority='interactive',
                               reason='requests', route='serve') == 1
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'tenant_quota_shed' in kinds


def test_registry_token_reject_refunds_request_charge():
    reg = TenantRegistry()
    reg.add('t', request_rate=10.0, token_rate=5.0)
    with pytest.raises(QuotaExceededError) as ei:
        reg.admit('t/s1', tokens=100, now=0.0)
    assert 'tokens' in str(ei.value)
    # the request charge came back, so the oversized request did not
    # also burn request quota
    assert reg.get('t').requests.tokens == 10.0
    reg.admit('t/s1', tokens=5, now=0.0)
    assert reg.get('t').requests.tokens == 9.0


def test_registry_env_knobs_read_per_call(monkeypatch):
    reg = TenantRegistry()
    monkeypatch.setenv('PADDLE_TPU_TENANT_DEFAULT_PRIORITY', 'batch')
    monkeypatch.setenv('PADDLE_TPU_TENANT_DEFAULT_RPS', '1')
    t = reg.resolve('lazy/s0')              # lazily created from env
    assert t.name == 'lazy' and t.priority == 'batch'
    assert t.requests is not None and t.requests.rate == 1.0
    # knobs are read per call, never at import: a tenant first seen
    # under different env gets the new defaults
    monkeypatch.setenv('PADDLE_TPU_TENANT_DEFAULT_PRIORITY', 'bogus')
    monkeypatch.delenv('PADDLE_TPU_TENANT_DEFAULT_RPS')
    t2 = reg.resolve('other/s0')
    assert t2.priority == 'standard' and t2.requests is None
    # unprefixed sessions account under the implicit 'default' tenant
    assert reg.resolve(None).name == 'default'
    assert reg.names() == ['default', 'lazy', 'other']


def test_router_quota_shed_never_touches_a_replica():
    rep = FakeReplica('r0')
    reg = TenantRegistry()
    reg.add('acme', priority='interactive', request_rate=1.0)
    router = Router([rep], tenants=reg)
    try:
        fut = router.submit({'x': np.zeros((1, 4), np.float32)},
                            session='acme/u1')
        assert fut.result(timeout=10) == ['r0']
        with pytest.raises(QuotaExceededError):
            router.submit({'x': np.zeros((1, 4), np.float32)},
                          session='acme/u1')
        assert rep.submitted == 1           # shed before any dispatch
    finally:
        router.close()


# ---------------------------------------- rendezvous pinning (tenants)
def test_rendezvous_pinning_with_tenant_prefixed_sessions():
    """Tenant-prefixed session ids feed the rendezvous hash whole: the
    pin is stable, a membership change only moves sessions that touch
    the added/removed replica, and two tenants' identical suffixes pin
    independently (the prefix is an accounting key, not a placement
    override that would herd one tenant onto one replica)."""
    router = Router([FakeReplica(n) for n in ('r0', 'r1', 'r2')])
    try:
        sessions = ['%s/u%d' % (t, i) for t in ('acme', 'bob')
                    for i in range(12)]

        def pins():
            return {s: router._candidates(s)[0][0] for s in sessions}

        first = pins()
        assert first == pins()              # stable across calls
        router.add_replica(FakeReplica('r3'), name='r3')
        after_add = pins()
        moved = [s for s in sessions if after_add[s] != first[s]]
        assert moved                        # some keyspace shifts...
        assert all(after_add[s] == 'r3' for s in moved)   # ...only to r3
        router.remove_replica('r3')
        assert pins() == first              # and shifts back exactly
        # same suffix, different tenant prefix: independent pins
        acme = {s.split('/', 1)[1]: first[s] for s in sessions
                if s.startswith('acme/')}
        bob = {s.split('/', 1)[1]: first[s] for s in sessions
               if s.startswith('bob/')}
        assert acme != bob
        # every tenant still spreads over the fleet (no herding)
        assert len(set(acme.values())) > 1
        assert len(set(bob.values())) > 1
    finally:
        router.close()


# ------------------------------------------------- decode scheduling
def _seq(rid, priority=None, prompt_len=3, max_new=4):
    return Sequence(rid, list(range(1, prompt_len + 1)), max_new, 0.0,
                    1, None, priority=priority)


def test_scheduler_admits_highest_class_first_batch_backfills():
    pool = KVPool(num_blocks=8, block_size=4)
    sched = Scheduler(pool, max_batch=2)
    b, s, i = _seq('b', 'batch'), _seq('s', None), _seq('i', 'interactive')
    for seq in (b, s, i):
        sched.add(seq)
    assert sched.pop_admittable().request_id == 'i'
    assert sched.pop_admittable().request_id == 's'
    # batch only backfills a slot no latency-class request wants
    assert sched.pop_admittable() is None
    sched.finish(s, 'max_tokens')
    assert sched.pop_admittable().request_id == 'b'


def test_scheduler_preempts_lowest_class_first():
    observe.enable()
    pool = KVPool(num_blocks=3, block_size=4)
    sched = Scheduler(pool, max_batch=3)
    i, s, b = _seq('i', 'interactive'), _seq('s', None), _seq('b', 'batch')
    for seq in (i, s, b):
        sched.add(seq)
    while sched.pop_admittable() is not None:
        pass
    assert [x.request_id for x in sched.running] == ['i', 's', 'b']
    assert pool.free_blocks() == 0
    # growth under exhaustion evicts the batch-class victim, never the
    # latency classes, and requeues it at the front for continuation
    assert sched.ensure_growth(i, need_tokens=5)
    assert i.state == RUNNING and s.state == RUNNING
    assert b.state == WAITING and b.preemptions == 1
    assert sched.waiting[0] is b
    assert observe.get_counter('tenant.preempted', tenant='default',
                               priority='batch') == 1
    assert observe.get_counter('tenant.preempted', tenant='default',
                               priority='standard') == 0


def test_scheduler_equal_classes_keep_youngest_victim_rule():
    pool = KVPool(num_blocks=2, block_size=4)
    sched = Scheduler(pool, max_batch=2)
    x, y = _seq('x'), _seq('y')
    sched.add(x)
    sched.add(y)
    while sched.pop_admittable() is not None:
        pass
    assert sched.ensure_growth(x, need_tokens=5)
    assert y.state == WAITING and x.state == RUNNING


def test_prefix_cache_evicts_batch_pages_before_interactive():
    observe.enable()
    pool = KVPool(num_blocks=8, block_size=4)
    cache = PrefixCache(pool)
    ti, tb = BlockTable(), BlockTable()
    assert pool.grow(ti, 4) and pool.grow(tb, 4)
    cache.publish([1, 2, 3, 4], ti, 4, tenant='fg',
                  priority='interactive')
    cache.publish([9, 9, 9, 9], tb, 4, tenant='bulk', priority='batch')
    pool.release(ti)
    pool.release(tb)
    # touch the batch page LAST: plain LRU would evict the interactive
    # page first; the priority order still takes the batch page
    t = BlockTable()
    assert cache.match([9, 9, 9, 9, 0], t) == 4
    pool.release(t)
    assert cache.reclaim(1) == 1
    t2, t3 = BlockTable(), BlockTable()
    assert cache.match([9, 9, 9, 9, 0], t2) == 0     # batch page gone
    assert cache.match([1, 2, 3, 4, 0], t3) == 4     # interactive kept
    pool.release(t3)
    assert observe.get_counter('tenant.evicted_pages', tenant='bulk',
                               priority='batch') == 1
    cache.clear()


def test_prefix_cache_shared_page_keeps_most_protected_class():
    pool = KVPool(num_blocks=8, block_size=4)
    cache = PrefixCache(pool)
    ti, tb = BlockTable(), BlockTable()
    assert pool.grow(ti, 4) and pool.grow(tb, 4)
    cache.publish([1, 2, 3, 4], ti, 4, tenant='fg',
                  priority='interactive')
    # a batch publish of the SAME chain must not demote the page
    cache.publish([1, 2, 3, 4], ti, 4, tenant='bulk', priority='batch')
    cache.publish([7, 7, 7, 7], tb, 4, tenant='bulk', priority='batch')
    pool.release(ti)
    pool.release(tb)
    assert cache.reclaim(1) == 1
    t = BlockTable()
    assert cache.match([1, 2, 3, 4, 0], t) == 4      # survived as
    pool.release(t)                                  # interactive
    cache.clear()


# ----------------------------------------------------------- RPC wire
def test_quota_error_is_typed_over_rpc():
    assert _error_classes()['QuotaExceededError'] is QuotaExceededError
    assert issubclass(QuotaExceededError, QueueFullError)
    # backpressure status: same 429 the other admission sheds use
    assert _ERR_STATUS['QuotaExceededError'] == 429


# ------------------------------------------------------- co-location
class _FakeTrainer(object):
    def __init__(self):
        self.calls = []

    def request_yield(self):
        self.calls.append('yield')

    def resume_from_yield(self):
        self.calls.append('resume')


def test_colocation_yield_edge_triggered_with_hysteresis():
    observe.enable()
    ft = _FakeTrainer()
    flag = {'pressured': False, 'burn': 0.0}

    def pf(now):
        return (flag['pressured'], 'test',
                {'burn_rate': flag['burn'], 'mean_queue_depth': 0.0})

    def cf(signals):
        return signals['burn_rate'] < 0.5

    wp, wc = colocation_yield(ft, pf, cf, route='colo')
    assert wp(0.0)[0] is False and ft.calls == []
    flag.update(pressured=True, burn=2.0)
    assert wp(1.0)[0] is True
    wp(2.0)                                  # edge: yields only once
    assert ft.calls == ['yield']
    assert observe.get_counter('tenant.trainer_yields_total',
                               route='colo') == 1
    assert observe.get_gauge('tenant.trainer_yielded', route='colo') == 1
    # pressure gone but burn above the calm floor: hysteresis holds
    flag.update(pressured=False, burn=1.0)
    wp(3.0)
    assert ft.calls == ['yield']
    flag.update(burn=0.3)
    wp(4.0)
    assert ft.calls == ['yield', 'resume']
    assert observe.get_gauge('tenant.trainer_yielded', route='colo') == 0
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'tenant_yield' in kinds and 'tenant_resume' in kinds
    # the inner calm verdict passes through for fleet scaling
    assert wc({'burn_rate': 0.3}) and not wc({'burn_rate': 0.9})


def test_slo_burn_pressure_tracks_tracker_burn():
    tracker = SloTracker([Objective('colo', 0.01, 0.5, window_s=100.0)])
    pf, cf = slo_burn_pressure(tracker, 'colo')
    pressured, reason, signals = pf(0.5)
    assert pressured is False and signals['burn_rate'] == 0.0
    for _ in range(4):
        tracker.record('colo', 0.1, ok=True, now=1.0)   # violations
    pressured, reason, signals = pf(1.5)
    assert pressured is True and reason == 'burn_rate'
    assert signals['burn_rate'] == pytest.approx(2.0)
    assert not cf(signals)
    for _ in range(20):
        tracker.record('colo', 0.001, ok=True, now=2.0)  # in SLO
    pressured, _, signals = pf(2.5)
    assert pressured is False
    assert signals['burn_rate'] < 0.5 and cf(signals)


def _linreg_train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    return [fluid.layers.mean(fluid.layers.square_error_cost(pred, y))]


def _make_batches(n, batch=8, seed=4):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(3).randn(4, 1).astype('float32')
    out = []
    for _ in range(n):
        x = rng.randn(batch, 4).astype('float32')
        out.append({'x': x, 'y': (x @ w).astype('float32')})
    return out


def _train(batches, yield_at=None):
    """One fresh run; with ``yield_at`` the event handler requests a
    yield after that step and a sidecar thread resumes once the loop
    has actually parked (drained + blocked)."""
    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        trainer = fluid.Trainer(
            train_func=_linreg_train_func,
            optimizer_func=lambda: fluid.optimizer.SGD(
                learning_rate=0.1),
            place=fluid.CPUPlace())
        losses, parked_seen = [], []

        def resumer():
            deadline = time.time() + 30
            while not trainer.yielded() and time.time() < deadline:
                time.sleep(0.005)
            parked_seen.append(trainer.yielded())
            trainer.resume_from_yield()

        def handler(e):
            if isinstance(e, fluid.trainer.EndStepEvent):
                losses.append(float(np.asarray(
                    e.metrics[0]).reshape(())))
                if yield_at is not None and e.step == yield_at \
                        and not parked_seen:
                    threading.Thread(target=resumer).start()
                    trainer.request_yield()

        trainer.train(num_epochs=1, event_handler=handler,
                      reader=lambda: iter(batches))
        arrays, _ = _io._snapshot_vars(trainer.program,
                                       predicate=_io._is_persistable)
        return losses, {k: np.array(v) for k, v in arrays.items()}, \
            parked_seen


def test_trainer_yield_resume_is_bit_identical():
    """A mid-run yield/resume parks the drained loop and changes
    nothing about the trajectory: same per-step losses, bitwise-equal
    final params."""
    batches = _make_batches(6)
    base_losses, base_params, _ = _train(batches)
    y_losses, y_params, parked_seen = _train(batches, yield_at=2)
    assert parked_seen == [True]            # it really parked
    assert y_losses == base_losses
    assert set(y_params) == set(base_params)
    for k in base_params:
        np.testing.assert_array_equal(y_params[k], base_params[k])


# ------------------------------------------- metrics_report --tenants
def test_metrics_report_tenants_json(tmp_path):
    """CLI satellite: --tenants renders the per-tenant isolation panel
    from a JSONL, stdlib-only (no jax import), --json schema stable."""
    observe.enable(jsonl=str(tmp_path / 'm.jsonl'))
    observe.inc('tenant.admitted', 5, tenant='acme',
                priority='interactive', route='serve')
    observe.inc('tenant.shed', 3, tenant='bulk', priority='batch',
                reason='requests', route='serve')
    observe.inc('tenant.shed', 2, tenant='bulk', priority='batch',
                reason='tokens', route='serve')
    observe.inc('tenant.preempted', 2, tenant='bulk', priority='batch')
    observe.inc('tenant.evicted_pages', 4, tenant='bulk',
                priority='batch')
    observe.inc('tenant.trainer_yields_total', route='serve')
    observe.set_gauge('tenant.trainer_yielded', 1, route='serve')
    observe.flush(kind='summary')

    tool = os.path.join(REPO, 'tools', 'metrics_report.py')
    r = subprocess.run(
        [sys.executable, tool, str(tmp_path / 'm.jsonl'), '--tenants',
         '--json'],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    acme, bulk = doc['tenants']['acme'], doc['tenants']['bulk']
    assert acme['priority'] == 'interactive' and acme['admitted'] == 5
    assert bulk['shed'] == 5
    assert bulk['shed_reasons'] == {'requests': 3, 'tokens': 2}
    assert bulk['preempted'] == 2 and bulk['evicted_pages'] == 4
    assert doc['trainer']['yields'] == 1
    assert doc['trainer']['yielded'] == 1
    # human rendering: most protected class first, shed-reason split
    r2 = subprocess.run(
        [sys.executable, tool, str(tmp_path / 'm.jsonl'), '--tenants'],
        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0, r2.stderr
    assert r2.stdout.index('acme') < r2.stdout.index('bulk')
    assert 'shed by' in r2.stdout and 'trainer' in r2.stdout
    # no jax import on the --tenants path
    probe = subprocess.run(
        [sys.executable, '-c',
         'import importlib.util, sys\n'
         'spec = importlib.util.spec_from_file_location("mr", %r)\n'
         'm = importlib.util.module_from_spec(spec)\n'
         'spec.loader.exec_module(m)\n'
         'assert m.main([%r, "--tenants"]) == 0\n'
         'assert "jax" not in sys.modules\n'
         % (tool, str(tmp_path / 'm.jsonl'))],
        capture_output=True, text=True, timeout=60)
    assert probe.returncode == 0, probe.stderr


# --------------------------------------------- bench.py acceptance
@pytest.mark.slow
def test_bench_multitenant_acceptance(tmp_path):
    """Acceptance: bench.py --workload multitenant proves noisy-
    neighbor isolation, typed quota sheds with zero losses, zero
    priority inversions, and a bit-identical co-location yield — and
    the tenant.* ledger lands in the metrics JSONL for --tenants."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    jsonl = str(tmp_path / 'mt.jsonl')
    observe.enable(jsonl=jsonl)
    r = bench.bench_multitenant(mix_duration=1.5, quota_duration=1.5,
                                inv_batch_new=28, train_batches=8)
    observe.flush(kind='summary')

    assert r['noisy_neighbor']['isolation_ratio'] >= 0.9
    bg = r['noisy_neighbor']['mixed']['tenants']['bg']
    assert bg['quota_sheds'] > 0
    q = r['quota_exhaustion']['tenants']['acme']
    assert q['quota_sheds'] > 0 and q['untyped_rejects'] == 0
    assert q['lost'] == 0 and q['errors'] == 0
    assert r['priority_inversion']['preempted_interactive'] == 0
    assert r['priority_inversion']['preempted_batch'] > 0
    colo = r['colocation']
    assert colo['parked'] and colo['resumed'] and colo['bit_identical']
    assert colo['yield_latency_s'] is not None

    tool = os.path.join(REPO, 'tools', 'metrics_report.py')
    rep = subprocess.run(
        [sys.executable, tool, jsonl, '--tenants', '--json'],
        capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stderr
    doc = json.loads(rep.stdout)
    assert doc['tenants']                    # isolation panel populated
