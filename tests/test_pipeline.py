"""Pipelined async training loop (ISSUE 4): bounded in-flight
dispatches, host prefetch worker, device-resident feeds, widened guard
semantics, and the new overlap telemetry."""

import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io as _io
from paddle_tpu.reader import decorator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _linreg_train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    return [fluid.layers.mean(fluid.layers.square_error_cost(pred, y))]


def _make_batches(n, batch=8, seed=4, wseed=3):
    rng = np.random.RandomState(wseed)
    w = rng.randn(4, 1).astype('float32')
    r = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xs = r.randn(batch, 4).astype('float32')
        out.append({'x': xs, 'y': xs @ w})
    return out


def _train(batches, num_epochs=1, events=None, ckpt=None, **train_kw):
    """One fresh training run; returns (losses, final persistables)."""
    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        trainer = fluid.Trainer(
            train_func=_linreg_train_func,
            optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.1),
            place=fluid.CPUPlace(), checkpoint_config=ckpt)
        losses = []

        def handler(e):
            if events is not None:
                events.append((type(e).__name__,
                               getattr(e, 'step', None)))
            if isinstance(e, fluid.trainer.EndStepEvent):
                losses.append(float(np.asarray(
                    e.metrics[0]).reshape(())))

        trainer.train(num_epochs=num_epochs, event_handler=handler,
                      reader=lambda: iter(batches), **train_kw)
        arrays, _ = _io._snapshot_vars(trainer.program,
                                       predicate=_io._is_persistable)
        return losses, arrays, trainer


# ------------------------------------------------ bit-identical e2e
@pytest.mark.parametrize('depth', [2, 4])
def test_pipelined_bit_identical_per_step(depth):
    """pipeline_depth>1 reproduces the sync loop's trajectory exactly:
    same per-step losses, bitwise-identical final params."""
    batches = _make_batches(7)
    base_losses, base_params, _ = _train(batches, num_epochs=2)
    pl_losses, pl_params, _ = _train(batches, num_epochs=2,
                                     pipeline_depth=depth)
    assert pl_losses == base_losses
    assert set(pl_params) == set(base_params)
    for k in base_params:
        np.testing.assert_array_equal(pl_params[k], base_params[k])


@pytest.mark.parametrize('depth', [2, 4])
def test_pipelined_bit_identical_windowed(depth):
    """Pipelined run_steps windows (w=3, trailing remainder per-step)
    match the sync windowed loop bitwise."""
    batches = _make_batches(7)
    base_losses, base_params, _ = _train(batches, steps_per_dispatch=3)
    pl_losses, pl_params, _ = _train(batches, steps_per_dispatch=3,
                                     pipeline_depth=depth)
    np.testing.assert_allclose(pl_losses, base_losses, rtol=0, atol=0)
    for k in base_params:
        np.testing.assert_array_equal(pl_params[k], base_params[k])


def test_host_prefetch_matches_inline():
    """The host prefetch worker changes where feed prep runs, never
    what is dispatched."""
    batches = _make_batches(7)
    _, base_params, _ = _train(batches, pipeline_depth=2)
    _, pf_params, _ = _train(batches, pipeline_depth=2, host_prefetch=3)
    for k in base_params:
        np.testing.assert_array_equal(pf_params[k], base_params[k])


def test_stacked_windows_device_resident():
    """stacked_windows=True feeds device-resident [w, ...] superbatches
    (the staged_superbatch contract) straight to run_steps — same
    trajectory as host-side window stacking."""
    import jax
    batches = _make_batches(6)
    base_losses, base_params, _ = _train(batches, steps_per_dispatch=2)

    def superbatches():
        for i in range(0, 6, 2):
            pair = batches[i:i + 2]
            yield {n: jax.device_put(np.stack([b[n] for b in pair]))
                   for n in pair[0]}

    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        trainer = fluid.Trainer(
            train_func=_linreg_train_func,
            optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.1),
            place=fluid.CPUPlace())
        losses = []
        trainer.train(
            num_epochs=1,
            event_handler=lambda e: losses.append(float(np.asarray(
                e.metrics[0]).reshape(())))
            if isinstance(e, fluid.trainer.EndStepEvent) else None,
            reader=superbatches, steps_per_dispatch=2,
            stacked_windows=True, pipeline_depth=2)
        arrays, _ = _io._snapshot_vars(trainer.program,
                                       predicate=_io._is_persistable)
    np.testing.assert_allclose(losses, base_losses, rtol=0, atol=0)
    for k in base_params:
        np.testing.assert_array_equal(arrays[k], base_params[k])


# ------------------------------------------------- event ordering
def test_event_ordering_contract():
    """Begin fires at dispatch, End at resolve: both streams stay
    in step order, End(k) never precedes Begin(k), and no step is ever
    resolved more than pipeline_depth dispatches late."""
    depth = 3
    events = []
    _train(_make_batches(9), events=events, pipeline_depth=depth)
    begins = [s for n, s in events if n == 'BeginStepEvent']
    ends = [s for n, s in events if n == 'EndStepEvent']
    assert begins == list(range(9))
    assert ends == list(range(9))
    seen_begin, seen_end = set(), set()
    for name, s in events:
        if name == 'BeginStepEvent':
            seen_begin.add(s)
        elif name == 'EndStepEvent':
            assert s in seen_begin          # End only after its Begin
            seen_end.add(s)
        # bounded pipeline: in-flight = begun minus ended <= depth
        assert len(seen_begin) - len(seen_end) <= depth
    # depth>1 actually overlaps: some Begin(k+1) precedes End(k)
    first_end = events.index(('EndStepEvent', 0))
    assert ('BeginStepEvent', 1) in events[:first_end]


def test_depth1_event_stream_is_sync():
    """pipeline_depth=1 keeps the classic strict interleave."""
    events = []
    _train(_make_batches(5), events=events, pipeline_depth=1)
    steps = [e for e in events if e[0] in ('BeginStepEvent',
                                           'EndStepEvent')]
    expect = []
    for i in range(5):
        expect += [('BeginStepEvent', i), ('EndStepEvent', i)]
    assert steps == expect


# ------------------------------------------------------ guards
def _poisoned_batches(n, poison_at):
    batches = _make_batches(n)
    batches[poison_at] = {
        'x': np.full((8, 4), np.nan, 'float32'),
        'y': np.zeros((8, 1), 'float32')}
    return batches


def test_guard_raise_at_depth(tmp_path):
    """'raise' surfaces the BadStepError even when the bad step is
    detected at resolve, dispatches late."""
    cfg = fluid.CheckpointConfig(str(tmp_path), nan_policy='raise',
                                 epoch_end=False)
    from paddle_tpu.fault.guards import BadStepError
    with pytest.raises(BadStepError):
        _train(_poisoned_batches(6, 2), ckpt=cfg, pipeline_depth=3)


def test_guard_skip_step_at_depth_group_undo(tmp_path):
    """skip_step at depth D: the snapshot covers the whole drain group,
    so a bad step undoes the group (<= D steps) and training continues —
    final params equal a run that never saw the group's batches."""
    batches = _poisoned_batches(6, 3)
    cfg = fluid.CheckpointConfig(str(tmp_path / 'a'),
                                 nan_policy='skip_step',
                                 epoch_end=False)
    _, params, trainer = _train(batches, ckpt=cfg, pipeline_depth=2)
    # groups of 2: [0,1] ok, [2,3] undone as a unit (3 is bad), [4,5] ok
    assert trainer._step == 4
    for arr in params.values():
        assert np.isfinite(np.asarray(arr)).all()
    control = [batches[i] for i in (0, 1, 4, 5)]
    cfg2 = fluid.CheckpointConfig(str(tmp_path / 'b'),
                                  nan_policy='skip_step',
                                  epoch_end=False)
    _, want, _ = _train(control, ckpt=cfg2, pipeline_depth=2)
    for k in want:
        np.testing.assert_array_equal(params[k], want[k])
    # every step still fired its events (the drained one included):
    events = []
    cfg3 = fluid.CheckpointConfig(str(tmp_path / 'c'),
                                  nan_policy='skip_step',
                                  epoch_end=False)
    _train(batches, ckpt=cfg3, pipeline_depth=2, events=events)
    assert [s for n, s in events if n == 'EndStepEvent'] == \
        list(range(6))


def test_guard_skip_step_depth1_unchanged(tmp_path):
    """At depth 1 the widened semantics degenerate to the classic
    single-step undo."""
    batches = _poisoned_batches(5, 2)
    cfg = fluid.CheckpointConfig(str(tmp_path / 'a'),
                                 nan_policy='skip_step',
                                 epoch_end=False)
    _, params, trainer = _train(batches, ckpt=cfg, pipeline_depth=1)
    assert trainer._step == 4          # only the bad step was undone
    control = [batches[i] for i in (0, 1, 3, 4)]
    cfg2 = fluid.CheckpointConfig(str(tmp_path / 'b'),
                                  nan_policy='skip_step',
                                  epoch_end=False)
    _, want, _ = _train(control, ckpt=cfg2)
    for k in want:
        np.testing.assert_array_equal(params[k], want[k])


def test_pipelined_checkpoint_cadence_resume(tmp_path):
    """Mid-epoch cadence saves under pipelining drain first: a resumed
    run replays exactly the untrained remainder (bit-identical params),
    even though the save point floated up to D-1 steps."""
    from paddle_tpu.reader.state import CheckpointableReader
    batches = _make_batches(8)
    base_losses, base_params, _ = _train(batches)

    def run(dirname, resume):
        with fluid.scope_guard(fluid.Scope()):
            fluid.reset_default_programs()
            cfg = fluid.CheckpointConfig(dirname, save_every_steps=3,
                                         resume=resume, epoch_end=False,
                                         async_save=False,
                                         nan_policy=None)
            trainer = fluid.Trainer(
                train_func=_linreg_train_func,
                optimizer_func=lambda: fluid.optimizer.SGD(
                    learning_rate=0.1),
                place=fluid.CPUPlace(), checkpoint_config=cfg)
            reader = CheckpointableReader(lambda: iter(batches))
            stop = {'n': 0}

            def handler(e):
                if isinstance(e, fluid.trainer.EndStepEvent):
                    stop['n'] += 1
                    if not resume and stop['n'] == 6:
                        raise KeyboardInterrupt   # simulated preemption
            try:
                trainer.train(num_epochs=1, event_handler=handler,
                              reader=reader, pipeline_depth=2)
            except KeyboardInterrupt:
                return None
            arrays, _ = _io._snapshot_vars(
                trainer.program, predicate=_io._is_persistable)
            return arrays

    d = str(tmp_path)
    assert run(d, resume=False) is None     # killed at step 6
    arrays = run(d, resume=True)            # resumes past the save
    for k in base_params:
        np.testing.assert_array_equal(arrays[k], base_params[k])


# ----------------------------------------------------- StepHandle
def test_executor_step_handle():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    out = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {'x': np.ones((2, 3), 'float32')}
    want = exe.run(feed=feed, fetch_list=[out])[0]
    h = exe.run(feed=feed, fetch_list=[out], return_handle=True)
    assert h.steps == 1 and h.dispatched_at > 0
    got = h.resolve()
    assert h.ready()
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want))
    assert h.resolve() is got               # idempotent


# ------------------------------------------------- reader satellites
def test_prefetch_to_device_mutation_safety_and_tail():
    """A reader that reuses its output buffer (recordio-slot style)
    must not corrupt in-flight prefetched batches on hosts where
    XLA:CPU zero-copies aligned arrays; the buffered tail drains after
    the source exhausts."""
    buf = np.zeros((2, 3), dtype='float32')

    def reuse_reader():
        for i in range(5):
            buf[:] = i          # overwrite the SAME buffer every yield
            yield {'x': buf}

    dev = decorator.prefetch_to_device(reuse_reader, buffer_size=2)
    got = [np.asarray(b['x']).copy() for b in dev()]
    assert len(got) == 5                         # tail fully drained
    for i, arr in enumerate(got):
        np.testing.assert_allclose(arr, i)       # no slot aliasing


def test_buffered_early_exit_no_thread_leak():
    """Breaking out of a buffered reader must not leave its worker
    thread blocked in q.put forever."""
    def slow_reader():
        for i in range(10000):
            yield i

    before = {t.ident for t in threading.enumerate()}
    for _ in range(3):                 # one leaked thread per epoch…
        it = decorator.buffered(slow_reader, size=2)()
        assert next(it) == 0
        it.close()                     # early consumer exit
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.name == 'paddle_tpu_buffered_reader'
                  and t.is_alive() and t.ident not in before]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, 'buffered worker threads leaked: %r' % leaked
    # normal full consumption still intact
    assert [x for x in decorator.buffered(slow_reader, size=4)()][:5] \
        == [0, 1, 2, 3, 4]


def test_trainer_prefetch_worker_no_thread_leak(tmp_path):
    """The trainer's host_prefetch worker exits when training aborts
    mid-epoch."""
    batches = _make_batches(50)

    class Boom(RuntimeError):
        pass

    def handler(e):
        if isinstance(e, fluid.trainer.EndStepEvent) and e.step >= 2:
            raise Boom()

    with pytest.raises(Boom):
        with fluid.scope_guard(fluid.Scope()):
            fluid.reset_default_programs()
            trainer = fluid.Trainer(
                train_func=_linreg_train_func,
                optimizer_func=lambda: fluid.optimizer.SGD(
                    learning_rate=0.1),
                place=fluid.CPUPlace())
            trainer.train(num_epochs=1, event_handler=handler,
                          reader=lambda: iter(batches),
                          pipeline_depth=2, host_prefetch=2)
    deadline = time.time() + 5.0
    while time.time() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == 'paddle_tpu_trainer_prefetch'
                 and t.is_alive()]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, 'prefetch worker leaked: %r' % alive


# ------------------------------------------------------ telemetry
def test_pipeline_metrics_flow(tmp_path):
    """inflight/resolve/blocked metrics land in the registry and flow
    through the JSONL into tools/metrics_report.py's overlap figure."""
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import metrics_report
    finally:
        sys.path.pop(0)
    from paddle_tpu import observe

    jsonl = str(tmp_path / 'm.jsonl')
    observe.reset()
    observe.enable(jsonl=jsonl)
    try:
        _train(_make_batches(6), pipeline_depth=2, host_prefetch=2)
        snap = observe.snapshot()
        assert 'trainer.inflight_depth' in snap['gauges']
        assert 'trainer.pipeline_overlap_fraction' in snap['gauges']
        hists = snap['histograms']
        assert hists['trainer.resolve_seconds']['count'] >= 6
        hb = snap['gauges'].get('trainer.host_blocked_seconds')
        db = snap['gauges'].get('trainer.device_blocked_seconds')
        assert hb is not None and hb >= 0.0
        assert db is None or db >= 0.0
        observe.flush()
    finally:
        observe._SINK['path'] = None
        observe._SINK['trace_path'] = None
        observe.disable()
        observe.reset()
    recs = metrics_report.load_records(jsonl)
    assert recs
    d = metrics_report.derive(metrics_report.pick(recs, any_kind=True))
    assert d['overlap_fraction'] is not None
    assert 0.0 <= d['overlap_fraction'] <= 1.0
    assert 'overlap' in metrics_report.render(recs[-1])


def test_windowed_feed_histogram_labeled(tmp_path):
    """Window stacking records its feed cost under a steps=w label so
    per-step phase percentiles stay comparable across dispatch modes."""
    from paddle_tpu import observe
    observe.reset()
    observe.enable()
    try:
        _train(_make_batches(6), steps_per_dispatch=3)
        reg = observe.registry()
        h = reg.histogram('trainer.phase_seconds')
        assert h.count(phase='feed', steps=3) == 2      # two windows
        assert h.count(phase='feed') == 6               # per-batch
        assert h.count(phase='compute', steps=3) == 2
        assert h.count(phase='compute') == 0            # no singles ran
    finally:
        observe.disable()
        observe.reset()
