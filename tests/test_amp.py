"""amp='bf16' end-to-end: the exact codepath the headline bench runs
(bench.py:92,112). Whitelist ops (mul/conv/attention) compute in
bfloat16 on the MXU; blacklist ops (softmax/norms/losses) stay fp32;
master weights stay fp32 in the scope (registry.py AMP policy)."""

import numpy as np

import paddle_tpu as fluid
from util import rand


def _train(amp, steps=15, seed=0):
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    img = fluid.layers.data(name='img', shape=[1, 12, 12], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    # bias_attr=False: the fp32 bias-add would promote the activation
    # back to fp32 (per-op promotion policy), which is fine for training
    # but would blur the in-graph dtype assertion below.
    conv = fluid.layers.conv2d(img, num_filters=6, filter_size=3,
                               act='relu', bias_attr=False,
                               param_attr=fluid.ParamAttr(
                                   name='amp_conv_w'))
    pool = fluid.layers.pool2d(conv, pool_size=2, pool_stride=2)
    logits = fluid.layers.fc(input=pool, size=10, act='softmax')
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=logits, label=label))
    fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)
    if amp:
        fluid.default_main_program().amp = amp
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(seed)
    xs = rng.rand(32, 1, 12, 12).astype('float32')
    ys = (xs.sum((1, 2, 3), keepdims=False)[:, None] > 36).astype('int64')
    losses = []
    for _ in range(steps):
        losses.append(float(np.asarray(
            exe.run(feed={'img': xs, 'label': ys},
                    fetch_list=[loss])[0]).reshape(())))
    return losses, conv


def test_bf16_lenet_loss_decreases():
    losses, _ = _train('bf16')
    assert losses[-1] < losses[0] * 0.7, losses
    assert np.isfinite(losses).all()


def test_bf16_tracks_fp32():
    """bf16 training must land near the fp32 trajectory (not diverge)."""
    l32, _ = _train(None)
    l16, _ = _train('bf16')
    assert abs(l16[-1] - l32[-1]) < 0.15, (l32[-1], l16[-1])


def test_bf16_dtypes_in_graph_and_scope():
    """Whitelist op outputs are bfloat16 in-graph; master weights stay
    float32 in the scope."""
    import jax.numpy as jnp
    losses, conv = _train('bf16', steps=1)
    fluid_prog = fluid.default_main_program()
    assert fluid_prog.amp == 'bf16'
    # conv activation inside the jitted graph is bf16
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(1)
    xs = rng.rand(4, 1, 12, 12).astype('float32')
    ys = np.zeros((4, 1), 'int64')
    out = exe.run(program=fluid_prog, feed={'img': xs, 'label': ys},
                  fetch_list=[conv], return_numpy=False)[0]
    assert out.dtype == jnp.bfloat16, out.dtype
    # master weights in scope stay fp32
    w = fluid.global_scope().find('amp_conv_w')
    assert np.asarray(w).dtype == np.float32


def test_bf16_resnet_tiny_e2e():
    from paddle_tpu.models.resnet import resnet_cifar10
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    img = fluid.layers.data(name='image', shape=[3, 16, 16],
                            dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    net = resnet_cifar10(img, depth=8)
    logits = fluid.layers.fc(input=net, size=10, act='softmax')
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=logits, label=label))
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = rand(8, 3, 16, 16, seed=2)
    ys = np.arange(8).reshape(-1, 1).astype('int64') % 10
    first = last = None
    for _ in range(12):
        val = float(np.asarray(exe.run(
            feed={'image': xs, 'label': ys},
            fetch_list=[loss])[0]).reshape(()))
        first = val if first is None else first
        last = val
    assert np.isfinite(last)
    assert last < first, (first, last)


def _train_bn(steps=10, seed=3):
    """conv->bn->fc under amp; returns (losses, bn_out_var)."""
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    img = fluid.layers.data(name='img', shape=[3, 12, 12], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    conv = fluid.layers.conv2d(img, num_filters=8, filter_size=3,
                               bias_attr=False)
    bn = fluid.layers.batch_norm(input=conv, act='relu')
    logits = fluid.layers.fc(input=bn, size=10, act='softmax')
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=logits, label=label))
    fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(seed)
    xs = rng.rand(16, 3, 12, 12).astype('float32')
    ys = (xs.sum((1, 2, 3))[:, None] > 216).astype('int64')
    losses = []
    for _ in range(steps):
        losses.append(float(np.asarray(exe.run(
            feed={'img': xs, 'label': ys},
            fetch_list=[loss])[0]).reshape(())))
    return losses, bn


def test_bn_bf16_compute_default(monkeypatch):
    """Under amp the BN elementwise path stays bf16 (the +13% on-chip
    lever, norm_ops._bn_bf16_compute): the BN activation is bfloat16
    in-graph while running statistics stay fp32 in the scope."""
    import jax.numpy as jnp
    monkeypatch.delenv('PADDLE_TPU_BN_COMPUTE', raising=False)
    losses, bn = _train_bn()
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(4)
    out = exe.run(program=fluid.default_main_program(),
                  feed={'img': rng.rand(4, 3, 12, 12).astype('float32'),
                        'label': np.zeros((4, 1), 'int64')},
                  fetch_list=[bn], return_numpy=False)[0]
    assert out.dtype == jnp.bfloat16, out.dtype
    # running statistics (persistable scope state) remain fp32
    stats = [n for n in fluid.global_scope().keys()
             if 'batch_norm' in n and ('mean' in n or 'variance' in n)]
    assert stats, 'no BN statistics vars found in scope'
    for n in stats:
        assert np.asarray(fluid.global_scope().find(n)).dtype == np.float32


def test_bn_bf16_tracks_fp32_compute(monkeypatch):
    """PADDLE_TPU_BN_COMPUTE=fp32 (the ablation knob) must follow the
    same training trajectory as the bf16 default."""
    monkeypatch.delenv('PADDLE_TPU_BN_COMPUTE', raising=False)
    l16, _ = _train_bn()
    monkeypatch.setenv('PADDLE_TPU_BN_COMPUTE', 'fp32')
    l32, _ = _train_bn()
    np.testing.assert_allclose(l16, l32, rtol=5e-2, atol=5e-3)


def test_nhwc_conv_layout_matches_nchw(monkeypatch):
    """PADDLE_TPU_CONV_LAYOUT=NHWC is numerics-identical (the bench
    ablation flag, SURVEY §5)."""
    l_nchw, _ = _train('bf16', steps=5)
    monkeypatch.setenv('PADDLE_TPU_CONV_LAYOUT', 'NHWC')
    l_nhwc, _ = _train('bf16', steps=5)
    np.testing.assert_allclose(l_nchw, l_nhwc, rtol=2e-2, atol=1e-3)


def _train_native_layout(fmt, steps=3):
    """Small residual conv net built natively in `fmt` (models/resnet.py
    building blocks with data_format threaded through the IR)."""
    from paddle_tpu.models.resnet import conv_bn_layer, basicblock

    fluid.reset_default_programs()
    fluid.global_scope().clear()
    fluid.default_main_program().random_seed = 7
    img = fluid.layers.data(name='image', shape=[3, 16, 16],
                            dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    x = img
    if fmt == 'NHWC':
        x = fluid.layers.transpose(x, [0, 2, 3, 1])
    x = conv_bn_layer(x, 8, 3, 1, 1, data_format=fmt)
    x = fluid.layers.pool2d(x, pool_size=3, pool_type='max', pool_stride=2,
                            pool_padding=1, data_format=fmt)
    x = basicblock(x, 8, 1, data_format=fmt)
    x = basicblock(x, 16, 2, data_format=fmt)
    x = fluid.layers.pool2d(x, pool_type='avg', global_pooling=True,
                            data_format=fmt)
    pred = fluid.layers.fc(x, size=10, act='softmax')
    cost = fluid.layers.mean(
        fluid.layers.cross_entropy(input=pred, label=label))
    fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {'image': rng.rand(4, 3, 16, 16).astype('float32'),
            'label': rng.randint(0, 10, (4, 1)).astype('int64')}
    return [float(np.asarray(exe.run(feed=feed, fetch_list=[cost])[0])
                  .reshape(())) for _ in range(steps)]


def test_native_nhwc_network_matches_nchw():
    """data_format='NHWC' through the IR (conv2d/pool2d/batch_norm +
    resnet blocks — the transpose-free TPU layout) trains identically to
    the NCHW build: same seed, same feed, same loss trajectory."""
    l_nchw = _train_native_layout('NCHW')
    l_nhwc = _train_native_layout('NHWC')
    np.testing.assert_allclose(l_nchw, l_nhwc, rtol=2e-4, atol=2e-5)


def test_resnet50_data_format_arg_builds_nhwc_shapes():
    """resnet50_with_loss(data_format='NHWC') produces channels-last
    activation shapes in the IR while the feed stays NCHW."""
    from paddle_tpu.models.resnet import resnet50_with_loss

    fluid.reset_default_programs()
    _, cost, _ = resnet50_with_loss(image_shape=(3, 64, 64), class_dim=10,
                                    data_format='NHWC')
    block = fluid.default_main_program().global_block()
    # every conv output is NHWC: channel dim (last) matches the filter
    # count
    for op in block.ops:
        if op.type != 'conv2d':
            continue
        shape = block.var(op.output('Output')).shape
        n_filters = block.var(op.input('Filter')).shape[0]
        assert shape[-1] == n_filters, (shape, n_filters)
    assert any(op.type == 'transpose' for op in block.ops)


def test_mobilenet_native_nhwc_matches_nchw():
    """MobileNet's depthwise/pointwise stack threads data_format too
    (depthwise convs are the layout-sensitive case: feature_group_count
    = C with HWIO filters)."""
    from paddle_tpu.models.mobilenet import mobile_net

    def run(fmt):
        fluid.reset_default_programs()
        fluid.global_scope().clear()
        fluid.default_main_program().random_seed = 5
        img = fluid.layers.data(name='img', shape=[3, 32, 32],
                                dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        pred = mobile_net(img, class_dim=10, scale=0.25, data_format=fmt)
        cost = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(1)
        feed = {'img': rng.rand(4, 3, 32, 32).astype('f'),
                'label': rng.randint(0, 10, (4, 1)).astype('int64')}
        return [float(np.asarray(exe.run(feed=feed,
                                         fetch_list=[cost])[0]).reshape(()))
                for _ in range(3)]

    np.testing.assert_allclose(run('NCHW'), run('NHWC'),
                               rtol=2e-4, atol=2e-5)


def test_s2d_stem_matches_direct_conv(monkeypatch):
    """PADDLE_TPU_CONV_S2D=1 rewrites the ResNet stem conv (7x7 s2 p3,
    small Cin, NHWC-native) onto a space-to-depth 4x4 s1 conv — exact
    math, MXU-friendlier contraction (the MLPerf stem trick)."""
    def _stem(steps=3):
        fluid.reset_default_programs()
        fluid.global_scope().clear()
        fluid.default_main_program().random_seed = 11
        img = fluid.layers.data(name='image', shape=[3, 32, 32],
                                dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        x = fluid.layers.transpose(img, [0, 2, 3, 1])
        x = fluid.layers.conv2d(input=x, num_filters=16, filter_size=7,
                                stride=2, padding=3, bias_attr=False,
                                data_format='NHWC')
        x = fluid.layers.pool2d(x, pool_type='avg', global_pooling=True,
                                data_format='NHWC')
        pred = fluid.layers.fc(x, size=10, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9) \
            .minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(3)
        feed = {'image': rng.rand(4, 3, 32, 32).astype('float32'),
                'label': rng.randint(0, 10, (4, 1)).astype('int64')}
        return [float(np.asarray(exe.run(feed=feed,
                                         fetch_list=[loss])[0]).reshape(()))
                for _ in range(steps)]

    monkeypatch.delenv('PADDLE_TPU_CONV_S2D', raising=False)
    base = _stem()
    monkeypatch.setenv('PADDLE_TPU_CONV_S2D', '1')
    s2d = _stem()
    np.testing.assert_allclose(base, s2d, rtol=1e-4, atol=1e-5)


def test_lstm_under_bf16_amp_trains():
    """RNN ops under amp: uniform bf16 inputs (AMP_WHITELIST) and a
    dtype-pinned scan carry — regression: a fp32 weight against the
    bf16 pre-projection used to promote h mid-scan and break lax.scan's
    carry contract."""
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[-1, 8], dtype='float32',
                          lod_level=1)
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    proj = fluid.layers.fc(input=x, size=24, num_flatten_dims=2,
                           bias_attr=False)
    h, _ = fluid.layers.dynamic_lstm(input=proj, size=24)
    g = fluid.layers.dynamic_gru(
        input=fluid.layers.fc(input=x, size=15, num_flatten_dims=2,
                              bias_attr=False), size=5)
    last = fluid.layers.concat([fluid.layers.sequence_last_step(h),
                                fluid.layers.sequence_last_step(g)],
                               axis=-1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(
        fluid.layers.fc(input=last, size=1), y))
    fluid.optimizer.Adam(learning_rate=5e-3).minimize(cost)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(4, 6, 8).astype('float32'),
            'y': rng.randn(4, 1).astype('float32')}
    losses = [np.asarray(exe.run(feed=feed,
                                 fetch_list=[cost])[0]).item()
              for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
