"""Reader decorators + DataFeeder (reference: v2/reader/tests +
fluid/data_feeder.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.reader import decorator


def _counter(n):
    def reader():
        for i in range(n):
            yield (i,)
    return reader


def test_map_readers():
    # func receives one item per reader (v2/reader/decorator.py semantics)
    r = decorator.map_readers(lambda a: a[0] * 2, _counter(5))
    assert [x for x in r()] == [0, 2, 4, 6, 8]


def test_shuffle_preserves_elements():
    r = decorator.shuffle(_counter(20), buf_size=7)
    got = sorted(x[0] for x in r())
    assert got == list(range(20))


def test_chain_and_compose():
    c = decorator.chain(_counter(3), _counter(2))
    assert [x[0] for x in c()] == [0, 1, 2, 0, 1]
    z = decorator.compose(_counter(3), _counter(3))
    assert [x for x in z()] == [(0, 0), (1, 1), (2, 2)]


def test_buffered_and_firstn():
    r = decorator.buffered(_counter(10), size=3)
    assert [x[0] for x in r()] == list(range(10))
    f = decorator.firstn(_counter(10), 4)
    assert [x[0] for x in f()] == [0, 1, 2, 3]


def test_xmap_readers_ordered():
    r = decorator.xmap_readers(lambda a: a[0] + 100, _counter(8),
                               process_num=2, buffer_size=4, order=True)
    assert [x for x in r()] == [100 + i for i in range(8)]


def test_cache_and_batch():
    r = decorator.cache(_counter(5))
    assert [x[0] for x in r()] == list(range(5))
    assert [x[0] for x in r()] == list(range(5))  # replays
    b = decorator.batch(_counter(7), batch_size=3, drop_last=False)
    batches = list(b())
    assert [len(x) for x in batches] == [3, 3, 1]


def test_data_feeder_builds_arrays():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='int64')
    feeder = fluid.DataFeeder(feed_list=[x, y], place=fluid.CPUPlace())
    minibatch = [([1.0, 2.0, 3.0], [0]), ([4.0, 5.0, 6.0], [1])]
    feed = feeder.feed(minibatch)
    assert feed['x'].shape == (2, 3)
    assert feed['x'].dtype == np.float32
    assert feed['y'].shape == (2, 1)
    assert feed['y'].dtype == np.int64


def test_dataset_synthetic_fallback():
    """Zero-egress: datasets serve deterministic synthetic data."""
    from paddle_tpu.dataset import uci_housing, mnist
    r = uci_housing.train()
    first = next(iter(r()))
    assert len(first) == 2 and len(first[0]) == 13
    m = next(iter(mnist.train()()))
    assert np.asarray(m[0]).size == 784


def test_recordio_roundtrip(tmp_path):
    from paddle_tpu.reader.recordio import write_recordio, recordio_reader
    items = [(np.arange(i + 1).tolist(), i) for i in range(50)]
    path = str(tmp_path / 'data.recordio')
    assert write_recordio(path, items) == 50
    got = list(recordio_reader(path)())
    assert got == items


def test_recordio_shuffle_preserves_multiset(tmp_path):
    from paddle_tpu.reader.recordio import write_recordio, recordio_reader
    items = [(i,) for i in range(100)]
    path = str(tmp_path / 'data.recordio')
    write_recordio(path, items)
    got = list(recordio_reader(path, shuffle_buf=17, seed=3)())
    assert got != items  # order changed
    assert sorted(got) == items  # same elements


def test_recordio_multi_file_and_corruption(tmp_path):
    from paddle_tpu.reader.recordio import write_recordio, recordio_reader
    p1, p2 = str(tmp_path / 'a.rio'), str(tmp_path / 'b.rio')
    write_recordio(p1, [(1,), (2,)])
    write_recordio(p2, [(3,)])
    got = list(recordio_reader([p1, p2])())
    assert got == [(1,), (2,), (3,)]
    # corrupt a payload byte -> crc error surfaces as IOError
    with open(p1, 'r+b') as f:
        f.seek(-1, 2)
        f.write(b'\xFF')
    import pytest as _pytest
    with _pytest.raises(IOError):
        list(recordio_reader(p1)())
    # records buffered BEFORE the corrupt one must still be delivered
    # (the reader drains its ring before surfacing the error)
    it = recordio_reader(p1)()
    assert next(it) == (1,)
    with _pytest.raises(IOError):
        list(it)
    # same with shuffling: valid records held in the shuffle pool when the
    # crc error hits must drain before the error surfaces
    it = recordio_reader(p1, shuffle_buf=64, seed=0)()
    assert next(it) == (1,)
    with _pytest.raises(IOError):
        list(it)


def test_prefetch_to_device():
    from paddle_tpu.reader.decorator import prefetch_to_device

    def batches():
        for i in range(5):
            yield {'x': np.full((2, 3), i, dtype='float32')}

    dev = prefetch_to_device(lambda: batches(), buffer_size=2)
    got = list(dev())
    assert len(got) == 5
    for i, b in enumerate(got):
        assert hasattr(b['x'], 'devices')  # on device
        np.testing.assert_allclose(np.asarray(b['x']), i)
