"""Program IR verifier (paddle_tpu.analysis): each of the five passes
against a minimally-broken Program (asserting pass name, severity, op
index, and construction provenance file:line), the executor's
PADDLE_TPU_VERIFY integration (strict raises BEFORE any trace, warn
compiles and runs with the flight event + counters recorded, one
verification per program key), startup verification in the trainer and
decode engine, the tools/program_lint.py CLI, and the bench overhead
guard."""

import inspect
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis, observe
from paddle_tpu.analysis import ProgramVerifyError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ME = os.path.basename(__file__)


@pytest.fixture(autouse=True)
def _clean():
    os.environ.pop('PADDLE_TPU_VERIFY', None)
    yield
    os.environ.pop('PADDLE_TPU_VERIFY', None)
    observe._flight_armed = False
    observe._FLIGHT_DUMP.update(path=None, last_exc=None, last_path=None)
    observe.disable()
    observe.reset()


def _here():
    """'test_analysis.py:<line of the caller>'."""
    return '%s:%d' % (_ME, inspect.currentframe().f_back.f_lineno)


def _find(diags, pass_name, code):
    got = [d for d in diags if d.pass_name == pass_name and
           d.code == code]
    assert got, 'no %s/%s in %s' % (pass_name, code,
                                    [d.format() for d in diags])
    return got[0]


def _assert_provenance(diag, expect):
    assert diag.provenance is not None, diag.format()
    assert diag.provenance.endswith(expect), \
        '%r does not end with %r' % (diag.provenance, expect)


def _program_verify_events():
    return [e['data'] for e in observe.flight_recorder().events()
            if e['kind'] == 'program_verify']


# ------------------------------------------------------------ the passes
def test_wellformed_undefined_input_with_provenance():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name='o', shape=[2, 2], dtype='float32')
    b.append_op('relu', inputs={'X': ['nope']}, outputs={'Out': ['o']}); line = _here()  # noqa: E702
    d = _find(analysis.run_passes(prog), 'wellformed', 'undefined-input')
    assert d.severity == 'error'
    assert d.op_index == 0
    assert d.op_type == 'relu'
    assert d.var == 'nope'
    _assert_provenance(d, line)


def test_wellformed_use_before_def_and_duplicate_and_dead():
    prog = fluid.Program()
    b = prog.global_block()
    for n in ('x', 't', 'o', 'dead'):
        b.create_var(name=n, shape=[2, 2], dtype='float32',
                     is_data=(n == 'x'))
    b.append_op('relu', inputs={'X': ['t']}, outputs={'Out': ['o']}); use_line = _here()  # noqa: E702
    b.append_op('tanh', inputs={'X': ['x']}, outputs={'Out': ['t']})
    b.append_op('tanh', inputs={'X': ['x']}, outputs={'Out': ['t']}); dup_line = _here()  # noqa: E702
    b.append_op('sigmoid', inputs={'X': ['x']}, outputs={'Out': ['dead']}); dead_line = _here()  # noqa: E702
    diags = analysis.run_passes(prog, fetch_names=['o'])

    d = _find(diags, 'wellformed', 'use-before-def')
    assert (d.severity, d.op_index) == ('error', 0)
    _assert_provenance(d, use_line)

    d = _find(diags, 'wellformed', 'duplicate-writer')
    assert (d.severity, d.op_index, d.var) == ('warning', 2, 't')
    _assert_provenance(d, dup_line)

    # ops 1-2 are dead too: liveness walks in reverse, and the only
    # read of 't' (op#0) precedes both writers, so neither reaches the
    # fetch — exactly the bug the use-before-def error explains
    dead = [x for x in diags
            if x.pass_name == 'wellformed' and x.code == 'dead-op']
    assert sorted(x.op_index for x in dead) == [1, 2, 3]
    d, = (x for x in dead if x.op_index == 3)
    assert d.severity == 'info'
    _assert_provenance(d, dead_line)


def test_shapes_matmul_mismatch():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name='x', shape=[-1, 4], dtype='float32', is_data=True)
    b.create_parameter('w', shape=[5, 3], dtype='float32')
    b.create_var(name='o', shape=[-1, 3], dtype='float32')
    b.append_op('mul', inputs={'X': ['x'], 'Y': ['w']}, outputs={'Out': ['o']}); line = _here()  # noqa: E702
    d = _find(analysis.run_passes(prog), 'shapes', 'matmul-mismatch')
    assert d.severity == 'error'
    assert d.op_index == 0
    assert d.op_type == 'mul'
    _assert_provenance(d, line)
    assert '4' in d.message and '5' in d.message


def test_shapes_elementwise_and_optimizer_contracts():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name='x', shape=[-1, 8], dtype='float32', is_data=True)
    b.create_var(name='y', shape=[3], dtype='float32', is_data=True)
    b.create_var(name='o', shape=[-1, 8], dtype='float32')
    b.append_op('elementwise_add', inputs={'X': ['x'], 'Y': ['y']},
                outputs={'Out': ['o']})
    w = b.create_parameter('w', shape=[4, 4], dtype='float32')
    b.create_var(name='w@GRAD', shape=[4, 5], dtype='float32')
    b.create_var(name='lr', shape=[1], dtype='float32', persistable=True)
    b.append_op('sgd', inputs={'Param': ['w'], 'Grad': ['w@GRAD'],
                               'LearningRate': ['lr']},
                outputs={'ParamOut': ['w']})
    diags = analysis.run_passes(prog)
    d = _find(diags, 'shapes', 'broadcast-mismatch')
    assert (d.severity, d.op_index) == ('error', 0)
    d = _find(diags, 'shapes', 'update-shape-mismatch')
    assert (d.severity, d.op_index) == ('error', 1)
    assert w.name in d.message


def test_sharding_indivisible_and_conflict():
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.mesh import make_mesh
    prog = fluid.Program()
    b = prog.global_block()
    b.create_parameter('w', shape=[3, 4], dtype='float32')
    b.create_var(name='a', shape=[8, 8], dtype='float32', is_data=True)
    b.create_var(name='c', shape=[8, 8], dtype='float32', is_data=True)
    b.create_var(name='o', shape=[8, 8], dtype='float32')
    b.append_op('elementwise_add', inputs={'X': ['a'], 'Y': ['c']}, outputs={'Out': ['o']}); line = _here()  # noqa: E702
    prog.mesh = make_mesh(tp=8)
    prog.var_shardings = {'w': P('tp'), 'a': P('tp', None),
                          'c': P(None, 'tp')}
    diags = analysis.run_passes(prog)

    d = _find(diags, 'sharding', 'axis-indivisible')
    assert d.severity == 'error'
    assert d.var == 'w'
    assert '3 % 8' in d.message

    d = _find(diags, 'sharding', 'spec-conflict')
    assert (d.severity, d.op_index) == ('warning', 0)
    _assert_provenance(d, line)


def test_donation_double_and_read_after_donate():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_parameter('w', shape=[4], dtype='float32')
    b.create_var(name='g', shape=[4], dtype='float32', is_data=True)
    b.create_var(name='lr', shape=[1], dtype='float32', persistable=True)
    b.create_var(name='peek', shape=[4], dtype='float32')
    sgd = {'inputs': {'Param': ['w'], 'Grad': ['g'],
                      'LearningRate': ['lr']},
           'outputs': {'ParamOut': ['w']}}
    b.append_op('sgd', **sgd)
    b.append_op('sgd', **sgd); dup_line = _here()  # noqa: E702
    b.append_op('scale', inputs={'X': ['w']}, outputs={'Out': ['peek']}, attrs={'scale': 1.0}); read_line = _here()  # noqa: E702
    diags = analysis.run_passes(prog)

    d = _find(diags, 'donation', 'double-donation')
    assert (d.severity, d.op_index, d.var) == ('error', 1, 'w')
    _assert_provenance(d, dup_line)

    d = _find(diags, 'donation', 'read-after-donate')
    assert (d.severity, d.op_index, d.var) == ('warning', 2, 'w')
    _assert_provenance(d, read_line)


def test_recompile_attr_object_and_dynamic_feed():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name='x', shape=[-1, -1], dtype='int64', is_data=True)
    b.create_var(name='o', shape=[-1, -1], dtype='int64')
    b.append_op('scale', inputs={'X': ['x']}, outputs={'Out': ['o']}, attrs={'hook': lambda v: v}); line = _here()  # noqa: E702
    diags = analysis.run_passes(prog)

    d = _find(diags, 'recompile', 'attr-callable')
    assert (d.severity, d.op_index) == ('error', 0)
    _assert_provenance(d, line)

    # object() repr embeds a memory address
    b.append_op('scale', inputs={'X': ['x']}, outputs={'Out': ['o']},
                attrs={'thing': object()})
    diags = analysis.run_passes(prog)
    d = _find(diags, 'recompile', 'attr-object-id')
    assert (d.severity, d.op_index) == ('error', 1)

    d = _find(diags, 'recompile', 'dynamic-feed-dim')
    assert (d.severity, d.var) == ('warning', 'x')


def test_recompile_attr_object_only_when_present():
    # the lambda also repr-matches object-id; this case is the pure one
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name='x', shape=[-1, 2], dtype='float32', is_data=True)
    b.create_var(name='o', shape=[-1, 2], dtype='float32')
    b.append_op('scale', inputs={'X': ['x']}, outputs={'Out': ['o']},
                attrs={'scale': 2.0, 'name': 'fine', 'dims': [1, 2]})
    diags = analysis.run_passes(prog)
    assert not [d for d in diags if d.pass_name == 'recompile'
                and d.code.startswith('attr-')]


# --------------------------------------------------- executor integration
def _broken_program():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name='o', shape=[2, 2], dtype='float32')
    b.append_op('relu', inputs={'X': ['nope']}, outputs={'Out': ['o']})
    return prog


def test_strict_mode_raises_before_any_trace():
    os.environ['PADDLE_TPU_VERIFY'] = 'strict'
    observe.arm_flight()
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(ProgramVerifyError) as ei:
        exe.run(program=_broken_program(), feed={}, fetch_list=['o'])
    assert ei.value.diagnostics
    assert any(d.code == 'undefined-input' for d in ei.value.diagnostics)
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    # verification fired; nothing traced or compiled
    assert 'program_verify' in kinds
    assert 'compile' not in kinds


def test_warn_mode_compiles_and_records():
    os.environ['PADDLE_TPU_VERIFY'] = 'warn'
    observe.enable()
    observe.arm_flight()
    # a program with a warning-severity finding that still runs fine:
    # two writers of one temporary (last write wins in the trace)
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    h = fluid.layers.fc(input=x, size=4, act='relu')
    b = fluid.default_main_program().global_block()
    b.append_op('tanh', inputs={'X': [x.name]}, outputs={'Out': [h.name]})
    b.append_op('tanh', inputs={'X': [x.name]}, outputs={'Out': [h.name]})
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out, = exe.run(feed={'x': np.ones((2, 4), 'float32')},
                   fetch_list=[h])
    assert np.asarray(out).shape == (2, 4)

    events = _program_verify_events()
    assert any(e['warnings'] >= 1 for e in events)
    n = observe.get_counter('analysis.diagnostics_total',
                            severity='warning', **{'pass': 'wellformed'})
    assert n >= 1

    # once per key: re-running the same signature adds no new event
    before = len(_program_verify_events())
    exe.run(feed={'x': np.ones((2, 4), 'float32')}, fetch_list=[h])
    assert len(_program_verify_events()) == before


def test_verify_off_by_default_on_executor():
    observe.arm_flight()
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    h = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={'x': np.ones((2, 4), 'float32')}, fetch_list=[h])
    assert not _program_verify_events()


def test_trainer_verifies_at_startup():
    observe.arm_flight()

    def net():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        return [fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))]

    def opt():
        return fluid.optimizer.SGD(learning_rate=0.1)

    def reader():
        for _ in range(2):
            yield {'x': np.ones((2, 4), 'float32'),
                   'y': np.ones((2, 1), 'float32')}

    t = fluid.Trainer(net, opt, place=fluid.CPUPlace())
    t.train(num_epochs=1, reader=reader)
    assert any(e['label'] == 'trainer'
               for e in _program_verify_events())


def test_serving_engine_verifies_at_startup(tmp_path):
    observe.arm_flight()
    from paddle_tpu.inference import create_predictor
    from paddle_tpu.serving import ServingEngine
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    pred = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / 'm')
    fluid.io.save_inference_model(d, ['x'], [pred], exe)
    eng = ServingEngine(create_predictor(d, place=fluid.CPUPlace()),
                        max_batch_size=2)
    try:
        eng.start()
        assert any(e['label'] == 'serving'
                   for e in _program_verify_events())
    finally:
        eng.shutdown(drain=False)


def test_decode_engine_verifies_at_startup():
    observe.arm_flight()
    from paddle_tpu.serving.decode import DecodeEngine, LMSpec
    eng = DecodeEngine(LMSpec(vocab_size=64), max_batch=2, block_size=4,
                       num_blocks=8, pages_per_seq=2)
    try:
        labels = set(e['label'] for e in _program_verify_events())
        assert {'decode_startup', 'decode_prefill',
                'decode_step'} <= labels
    finally:
        eng.shutdown(drain=False)


def test_strict_engine_construction_fails_on_broken_graph():
    # strict refuses at startup_verify too: ProgramVerifyError from the
    # trainer before any compile
    os.environ['PADDLE_TPU_VERIFY'] = 'strict'

    def net():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        # sabotage: an op reading a name nothing defines
        fluid.default_main_program().global_block().append_op(
            'relu', inputs={'X': ['ghost']}, outputs={'Out': [cost.name]})
        return [cost]

    t = fluid.Trainer(net, lambda: fluid.optimizer.SGD(learning_rate=0.1),
                      place=fluid.CPUPlace())
    with pytest.raises(ProgramVerifyError):
        t.train(num_epochs=1,
                reader=lambda: iter([{'x': np.ones((2, 4), 'float32'),
                                      'y': np.ones((2, 1), 'float32')}]))


# ------------------------------------------------------------------- CLI
def test_program_lint_cli_json_schema():
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    d = tempfile.mkdtemp()
    fluid.io.save_inference_model(d, ['x'], [pred], exe)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'program_lint.py'),
         d, '--json'], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert set(rep) == {'model', 'ops', 'counts', 'diagnostics'}
    assert rep['counts'] == {'error': 0, 'warning': 0, 'info': 0}
    assert rep['ops'] >= 2


def test_program_lint_cli_flags_broken_model():
    from paddle_tpu.core.serialize import program_to_dict
    prog = _broken_program()
    d = tempfile.mkdtemp()
    with open(os.path.join(d, '__model__.json'), 'w') as f:
        json.dump({'feed_names': [], 'fetch_names': ['o'],
                   'program': program_to_dict(prog)}, f)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'program_lint.py'),
         d, '--json'], capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert rep['counts']['error'] >= 1
    bad = [dd for dd in rep['diagnostics']
           if dd['code'] == 'undefined-input']
    assert bad and bad[0]['pass'] == 'wellformed'
    # provenance survived serialization: this very file built the op
    assert bad[0]['provenance'] and _ME in bad[0]['provenance']


# ------------------------------------------------------- overhead guard
def test_verifier_overhead_vs_cold_compile():
    sys.path.insert(0, REPO)
    import bench
    out = bench.bench_verify(batch=2, seq=16, vocab=512, iters=3)
    assert set(out) >= {'verify_seconds', 'cold_compile_seconds',
                       'verify_vs_compile_ratio', 'ok', 'diagnostics'}
    assert out['diagnostics']['error'] == 0
    assert out['verify_vs_compile_ratio'] < 0.01, out
    assert out['ok'] is True
