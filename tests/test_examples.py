"""The examples/ directory stays runnable: each script executes
end-to-end on CPU in a subprocess (compile-heavy ones get generous
watchdogs). The C inference example is covered by tests/test_capi.py's
compiled-client tests."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(name, timeout=420):
    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    # force CPU in-script BEFORE any device query: under the hosted
    # sitecustomize the env-var route still probes the (possibly hung)
    # TPU relay first — force_host_cpu is the one home of that dance
    boot = ("from paddle_tpu.core.platform_boot import force_host_cpu; "
            "force_host_cpu(); "
            "import runpy; runpy.run_path(%r, run_name='__main__')"
            % os.path.join(REPO, 'examples', name))
    r = subprocess.run([sys.executable, '-c', boot],
                       capture_output=True, text=True, env=env,
                       timeout=timeout)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    return r.stdout


def test_fit_a_line_example():
    out = _run_example('train_fit_a_line.py')
    assert 'reloaded model max abs err' in out


def test_pipelined_transformer_example():
    out = _run_example('train_transformer_pipelined.py')
    assert 'step 9' in out


def test_ctr_sparse_resume_example():
    out = _run_example('train_ctr_sparse_resume.py')
    assert 'expect 8' in out
    assert 'epoch finished' in out


def test_v1_quickstart_example():
    out = _run_example('train_v1_quickstart.py')
    final = float(out.strip().splitlines()[-1].split()[-1])
    assert final < 0.1


def test_v1_seq2seq_generate_example():
    out = _run_example('train_v1_seq2seq_generate.py')
    assert 'top-beam copy accuracy' in out
