"""scan-over-layers transformer (PADDLE_TPU_SCAN_LAYERS /
transformer(scan_layers=True)): the n_layer stacks compile as ONE
lax.scan body over [n_layer, ...] stacked weights
(ops/transformer_ops.py). Parity gate: with identical weights the
scanned graph must follow the unrolled graph's training trajectory
exactly (same losses step by step => same gradients)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import transformer as T

CFG = dict(n_layer=2, n_head=2, d_key=4, d_value=4, d_model=8,
           d_inner=16, dropout_rate=0.0, label_smooth_eps=0.1,
           src_seq_len=6, trg_seq_len=6)
VOCAB = 50


def _build(scan):
    fluid.reset_default_programs()
    avg_cost, _ = T.transformer(VOCAB, VOCAB, max_length=16,
                                scan_layers=scan, **CFG)
    fluid.optimizer.SGD(learning_rate=0.5).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return avg_cost, exe, fluid.default_main_program()


def _snapshot(scope):
    return {n: np.asarray(scope.find(n)) for n in scope.keys()
            if scope.find(n) is not None}


def _copy_weights(src_vals, dst_scope, n_layer):
    """Copy the unrolled model's weights into the scan model's scope:
    per-layer params are np.stack'ed onto the leading layer axis (the
    production stack_trained_weights mapping), the rest (embeddings,
    pos table, out_proj) share names verbatim."""
    stacks = {}
    for name, val in src_vals.items():
        sname, i = T._unrolled_to_stacked_name(name)
        if sname is None:
            if dst_scope.find(name) is not None:
                dst_scope.set(name, val)
        else:
            stacks.setdefault(sname, [None] * n_layer)[i] = val
    for sname, parts in stacks.items():
        assert all(p is not None for p in parts), sname
        assert dst_scope.find(sname) is not None, \
            'scan model has no param %r' % sname
        dst_scope.set(sname, np.stack(parts, axis=0))


def test_scan_matches_unrolled_trajectory():
    feed = T.make_fake_batch(4, CFG['src_seq_len'], CFG['trg_seq_len'],
                             VOCAB, VOCAB, seed=7)
    scope_u, scope_s = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(scope_u):
        cost_u, exe_u, prog_u = _build(scan=False)
        init_vals = _snapshot(scope_u)  # before training mutates scope
        losses_u = [float(np.asarray(
            exe_u.run(feed=feed, fetch_list=[cost_u])[0]).reshape(()))
            for _ in range(3)]
    with fluid.scope_guard(scope_s):
        cost_s, exe_s, prog_s = _build(scan=True)
        _copy_weights(init_vals, scope_s, CFG['n_layer'])
        losses_s = [float(np.asarray(
            exe_s.run(feed=feed, fetch_list=[cost_s])[0]).reshape(()))
            for _ in range(3)]
    # identical weights + identical math => identical trajectory
    np.testing.assert_allclose(losses_s, losses_u, rtol=1e-4, atol=1e-5)


def test_scan_layers_trains():
    feed = T.make_fake_batch(4, CFG['src_seq_len'], CFG['trg_seq_len'],
                             VOCAB, VOCAB, seed=1)
    with fluid.scope_guard(fluid.Scope()):
        cost, exe, _ = _build(scan=True)
        losses = [float(np.asarray(
            exe.run(feed=feed, fetch_list=[cost])[0]).reshape(()))
            for _ in range(6)]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


def test_scan_trained_scope_decodes():
    """A scan-trained scope must drive the inference builders directly:
    greedy decode reuses the stacked 'enc_stack_*'/'dec_stack_*' params
    (review finding: the infer graph silently re-initialized unrolled
    names before scan_layers was wired through _infer_cfg)."""
    from paddle_tpu.models import transformer as T
    seq_len, vocab = 5, 12
    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        rng = np.random.RandomState(0)
        src = rng.randint(2, vocab, (8, seq_len)).astype('int64')
        avg, _ = T.transformer(
            vocab, vocab, max_length=32, n_layer=1, n_head=2, d_key=8,
            d_value=8, d_model=16, d_inner=32, dropout_rate=0.0,
            label_smooth_eps=0.0, src_seq_len=seq_len,
            trg_seq_len=seq_len, scan_layers=True)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(avg)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        trg_in = np.concatenate([np.zeros((8, 1), 'int64'),
                                 src[:, :-1]], 1)
        feed = {'src_word': src,
                'src_length': np.full((8,), seq_len, 'int64'),
                'trg_word': trg_in, 'lbl_word': src,
                'lbl_weight': np.ones((8, seq_len), 'float32')}
        for _ in range(80):
            out = exe.run(feed=feed, fetch_list=[avg])
        assert float(np.asarray(out[0]).reshape(())) < 0.2
        infer_prog = fluid.Program()
        with fluid.program_guard(infer_prog, fluid.Program()):
            ids, feeds = T.transformer_greedy_infer(
                vocab, vocab, max_out_len=seq_len + 1,
                src_seq_len=seq_len, max_length=32, n_layer=1, n_head=2,
                d_key=8, d_value=8, d_model=16, d_inner=32,
                scan_layers=True)
        got = exe.run(program=infer_prog,
                      feed={'src_word': src,
                            'src_length': np.full((8,), seq_len,
                                                  'int64')},
                      fetch_list=[ids])[0]
        acc = (got[:, 1:] == src).mean()
        assert acc > 0.9, (acc, got[:2], src[:2])


def test_scan_layers_env_knob(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_SCAN_LAYERS', '1')
    with fluid.scope_guard(fluid.Scope()):
        fluid.reset_default_programs()
        T.transformer(VOCAB, VOCAB, max_length=16, **CFG)
        ops = [op.type for op in
               fluid.default_main_program().global_block().ops]
    assert ops.count('transformer_layer_stack') == 2, ops


def test_scan_layers_with_ring_attention_sp_mesh():
    """Composition of the two long-context levers: scan-over-layers with
    the ring-attention sp dispatch INSIDE the scan body (shard_map
    nested in lax.scan). Trajectory must match the unsharded scan run."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                                transpile)
    cfg = dict(CFG, src_seq_len=8, trg_seq_len=8, dropout_rate=0.0)
    feed = T.make_fake_batch(4, 8, 8, VOCAB, VOCAB, seed=2)

    def run(mesh):
        with fluid.scope_guard(fluid.Scope()):
            fluid.reset_default_programs()
            avg, _ = T.transformer(VOCAB, VOCAB, max_length=16,
                                   scan_layers=True, **cfg)
            fluid.default_main_program().random_seed = 5
            fluid.optimizer.SGD(learning_rate=0.5).minimize(avg)
            if mesh is not None:
                transpile(fluid.default_main_program(), mesh,
                          ParallelStrategy(
                              data_parallel=True,
                              sequence_parallel=True,
                              sp_vars=['src_word', 'trg_word',
                                       'lbl_word', 'lbl_weight']))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            return [float(np.asarray(exe.run(
                feed=feed, fetch_list=[avg])[0]).reshape(()))
                for _ in range(3)]

    base = run(None)
    sp = run(make_mesh(dp=2, sp=4))
    np.testing.assert_allclose(sp, base, rtol=2e-4, atol=1e-5)
