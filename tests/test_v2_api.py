"""v2 high-level API: book-chapter style programs run verbatim over the
fluid IR (reference: python/paddle/v2 — layer.py, trainer.py:37-249,
parameters.py:27-404, inference.py)."""

import io

import numpy as np
import pytest

import paddle_tpu.v2 as paddle


def test_fit_a_line_v2_style():
    paddle.init(use_gpu=False, trainer_count=1)
    x = paddle.layer.data(name='x', type=paddle.data_type.dense_vector(13))
    y = paddle.layer.data(name='y', type=paddle.data_type.dense_vector(1))
    y_ = paddle.layer.fc(input=x, size=1, act=paddle.activation.Linear())
    cost = paddle.layer.square_error_cost(input=y_, label=y)
    params = paddle.parameters.create(cost)
    assert len(params.names()) == 2  # weight + bias

    w_true = np.random.RandomState(0).randn(13, 1).astype('float32')

    def train_reader():
        rng = np.random.RandomState(1)
        for _ in range(40):
            xs = rng.randn(13).astype('float32')
            yield xs, (xs @ w_true + 0.5).astype('float32')

    events = []
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.01),
        place=__import__('paddle_tpu').CPUPlace())
    trainer.train(reader=paddle.batch(train_reader, 20), num_passes=30,
                  event_handler=events.append, feeding={'x': 0, 'y': 1})
    end_iters = [e for e in events
                 if isinstance(e, paddle.event.EndIteration)]
    assert end_iters[-1].cost < end_iters[0].cost * 0.1
    assert any(isinstance(e, paddle.event.EndPass) for e in events)

    # inference over the trained params — WITHOUT a feeding map the feed
    # slots come from the pruned graph (label slot must not be demanded)
    samples = [(np.zeros(13, 'float32'),)]
    out = paddle.infer(output_layer=y_, parameters=params, input=samples)
    assert out.shape == (1, 1)
    np.testing.assert_allclose(out[0, 0], 0.5, atol=0.2)


@pytest.mark.xfail(
    reason='ISSUE 6: miscalibrated convergence threshold, failing since '
           'the seed. The constant-intensity images (every pixel = '
           'label/10) reduce the task to 1-D ordinal regression — '
           'softmax logits are (piecewise-)linear in one scalar, so 40 '
           'Adam steps at lr 2e-2 from Xavier init plateau near '
           'cost*0.63, just short of the 0.5x bar (200 steps reach '
           '~0.9 absolute, still descending). The conv/pool/Adam '
           'machinery itself converges: test_models_e2e lenet/mlp '
           'MNIST pass.')
def test_recognize_digits_v2_style():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    images = paddle.layer.data(
        name='pixel', type=paddle.data_type.dense_array(784, [1, 16, 16]))
    label = paddle.layer.data(name='label',
                              type=paddle.data_type.integer_value(10))
    conv_pool = paddle.networks.simple_img_conv_pool(
        input=images, filter_size=3, num_filters=4, pool_size=2,
        pool_stride=2, act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=conv_pool, size=10,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    params = paddle.parameters.create(cost)

    def reader():
        rng = np.random.RandomState(2)
        for _ in range(16):
            lab = int(rng.randint(10))
            img = np.full((1, 16, 16), lab / 10.0, 'float32')
            yield img, lab

    costs = []
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-2),
        place=__import__('paddle_tpu').CPUPlace())
    trainer.train(
        reader=paddle.batch(reader, 16), num_passes=40,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.5
    result = trainer.test(reader=paddle.batch(reader, 16))
    assert np.isfinite(result.cost)


def test_parameters_get_set_and_tar_roundtrip():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = paddle.layer.data(name='x', type=paddle.data_type.dense_vector(4))
    h = paddle.layer.fc(input=x, size=3,
                        param_attr=paddle.attr.Param(name='v2_w',
                                                     initial_std=0.1))
    params = paddle.parameters.create(h)
    assert 'v2_w' in params
    assert params.get_shape('v2_w') == (4, 3)
    w = params['v2_w']
    assert w.shape == (4, 3)
    params['v2_w'] = np.ones((4, 3), 'float32')
    np.testing.assert_array_equal(params['v2_w'], np.ones((4, 3)))

    buf = io.BytesIO()
    params.to_tar(buf)
    params['v2_w'] = np.zeros((4, 3), 'float32')
    buf.seek(0)
    params.init_from_tar(buf)
    np.testing.assert_array_equal(params['v2_w'], np.ones((4, 3)))


def test_embedding_and_sequence_padding():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    words = paddle.layer.data(
        name='words', type=paddle.data_type.integer_value_sequence(50))
    emb = paddle.layer.embedding(input=words, size=8)
    pooled = paddle.layer.pooling(input=emb,
                                  pooling_type=paddle.pooling.Sum())
    probs = paddle.layer.fc(input=pooled, size=2,
                            act=paddle.activation.Softmax())
    label = paddle.layer.data(name='label',
                              type=paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=probs, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.AdaGrad(learning_rate=0.05),
        place=__import__('paddle_tpu').CPUPlace())

    def reader():
        rng = np.random.RandomState(3)
        for _ in range(8):
            n = int(rng.randint(2, 6))  # ragged lengths -> padded batch
            seq = rng.randint(1, 50, n).astype('int64')
            yield seq, int(seq[0] % 2)

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 8), num_passes=3,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
        feeding={'words': 0, 'label': 1})
    assert np.isfinite(costs).all()

    # pad positions are MASKED: the same sequence with/without extra
    # padding (forced by a longer batch-mate) pools identically
    out_short = paddle.infer(output_layer=pooled,
                             input=[([3, 4],), ([5],)],
                             feeding={'words': 0})
    out_long = paddle.infer(output_layer=pooled,
                            input=[([3, 4],), ([5, 6, 7, 8, 9],)],
                            feeding={'words': 0})
    np.testing.assert_allclose(out_short[0], out_long[0], rtol=1e-5)


def test_partial_tail_batch_is_kept():
    """Reference v2 minibatch yields the ragged tail — a dataset smaller
    than batch_size must still train (review finding)."""
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = paddle.layer.data(name='x', type=paddle.data_type.dense_vector(3))
    y = paddle.layer.data(name='y', type=paddle.data_type.dense_vector(1))
    cost = paddle.layer.square_error_cost(
        input=paddle.layer.fc(input=x, size=1), label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.01),
        place=__import__('paddle_tpu').CPUPlace())

    def tiny_reader():  # 5 samples, batch 8 -> one partial batch
        rng = np.random.RandomState(4)
        for _ in range(5):
            yield rng.rand(3).astype('f'), rng.rand(1).astype('f')

    iters = []
    trainer.train(reader=paddle.batch(tiny_reader, 8), num_passes=1,
                  event_handler=lambda e: iters.append(e)
                  if isinstance(e, paddle.event.EndIteration) else None,
                  feeding={'x': 0, 'y': 1})
    assert len(iters) == 1  # the tail batch trained


def test_sparse_binary_vector_densifies():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    feats = paddle.layer.data(
        name='feats', type=paddle.data_type.sparse_binary_vector(16))
    out = paddle.layer.fc(input=feats, size=2,
                          act=paddle.activation.Softmax())
    paddle.parameters.create(out)
    got = paddle.infer(output_layer=out,
                       input=[([1, 3, 5],), ([0, 15],)],
                       feeding={'feats': 0})
    assert got.shape == (2, 2)
    assert np.isfinite(got).all()
    # float variant: (index, value) pairs
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    feats = paddle.layer.data(
        name='feats', type=paddle.data_type.sparse_float_vector(8))
    dense = paddle.layer.fc(input=feats, size=1)
    paddle.parameters.create(dense)
    got = paddle.infer(output_layer=dense,
                       input=[([(2, 0.5), (7, 1.5)],)],
                       feeding={'feats': 0})
    assert got.shape == (1, 1)


def test_v2_evaluator_namespace():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = paddle.layer.data(name='x', type=paddle.data_type.dense_vector(4))
    probs = paddle.layer.fc(input=x, size=3,
                            act=paddle.activation.Softmax())
    label = paddle.layer.data(name='l',
                              type=paddle.data_type.integer_value(3))
    err = paddle.evaluator.classification_error(input=probs, label=label)
    paddle.parameters.create(probs)
    import numpy as np
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={'x': np.zeros((6, 4), 'f'),
                         'l': np.zeros((6, 1), 'int64')},
                   fetch_list=[err])
    assert -1e-6 <= float(np.asarray(got).reshape(())) <= 1.0 + 1e-6
    auc = paddle.evaluator.auc(probs, label)
    auc.update(np.array([[0.2, 0.8], [0.7, 0.3]]), np.array([1, 0]))
    assert 0.0 <= auc.eval() <= 1.0


def test_plot_and_reader_creators(tmp_path, monkeypatch):
    monkeypatch.setenv('DISABLE_PLOT', 'True')
    ploter = paddle.plot.Ploter('train', 'test')
    ploter.append('train', 0, 1.5)
    ploter.append('train', 1, 1.2)
    ploter.plot()
    ploter.reset()
    assert ploter.__plot_data__['train'].step == []

    from paddle_tpu.reader import creator
    assert list(creator.np_array(np.arange(6).reshape(3, 2))())[1].tolist() \
        == [2, 3]
    p = tmp_path / 'lines.txt'
    p.write_text('a\nb\n')
    assert list(creator.text_file(str(p))()) == ['a', 'b']
    from paddle_tpu.reader.recordio import write_recordio
    rp = str(tmp_path / 'r.rio')
    write_recordio(rp, [(1,), (2,)])
    raw = list(creator.recordio(rp)())
    assert len(raw) == 2 and all(isinstance(r, bytes) for r in raw)


def test_v2_dataset_import_paths():
    """Both reference spellings work and resolve to the SAME modules:
    paddle.v2.dataset.mnist (v2 era) and paddle.dataset.mnist."""
    import paddle_tpu.dataset.mnist as base_mnist
    import paddle_tpu.v2.dataset.mnist as v2_mnist
    from paddle_tpu.v2.dataset import imdb, uci_housing  # noqa: F401
    assert v2_mnist is base_mnist
    import paddle_tpu.v2 as v2
    assert v2.dataset.mnist is base_mnist


def test_v2_layer_forwards_to_v1_shim():
    """Reference v2.layer was a re-export shell over
    trainer_config_helpers — unknown names resolve against the shim,
    with the `_layer` suffix stripped like the reference did."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu import trainer_config_helpers as tch
    assert paddle.layer.recurrent_group is tch.recurrent_group
    assert paddle.layer.memory is tch.memory
    assert paddle.layer.beam_search is tch.beam_search
    assert paddle.layer.lstmemory is tch.lstmemory
    assert paddle.layer.addto is tch.addto_layer     # suffix stripped
    import pytest
    with pytest.raises(AttributeError):
        paddle.layer.not_a_real_layer_name
