"""Disaggregated prefill/decode: KV handoff packet round-trips at
every arena dtype (bit-identical, scales included), typed dtype/
geometry refusal, host-staging no-allocation-growth, pool
fragmentation + alloc-stall observability, the PhaseRouter pipeline
(prefill replica -> zero-copy handoff -> decode replica) bit-identical
to single-replica decode with zero post-warmup executor cache misses,
preempt-and-resume after a handoff, per-phase autoscaling policies,
and the disagg chaos-bench acceptance."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.serving import (EngineClosedError, HandoffError,
                                KVDtypeMismatchError, KVGeometryError,
                                KVPacket, PhaseRouter, SLOShedError,
                                handoff as handoff_mod,
                                page_pressure, ttft_pressure)
from paddle_tpu.serving.decode import (DecodeEngine, KVPool, LMSpec,
                                       random_weights)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPEC = LMSpec(vocab_size=60, n_layer=2, n_head=2, d_key=8, d_value=8,
              d_model=16, d_inner=32)
WEIGHTS = random_weights(SPEC, seed=3)


@pytest.fixture(autouse=True)
def _observe_clean():
    from paddle_tpu import observe
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.disable()
    observe.reset()


def _engine(**kw):
    kw.setdefault('max_batch', 4)
    kw.setdefault('block_size', 4)
    kw.setdefault('num_blocks', 64)
    kw.setdefault('pages_per_seq', 8)
    kw.setdefault('weights', WEIGHTS)
    kw.setdefault('place', fluid.CPUPlace())
    kw.setdefault('prefix_cache', True)
    return DecodeEngine(SPEC, **kw)


def _prompt(n, seed=0):
    return [int(t) for t in
            np.random.RandomState(seed).randint(0, 60, n)]


def _arena_dtypes():
    from paddle_tpu.quant.core import kv_fp8_supported
    out = ['float32', 'bfloat16', 'int8']
    if kv_fp8_supported():
        out.append('fp8')
    return out


# --------------------------------------------------- pool observability
def test_pool_fragmentation_and_alloc_stall():
    """Satellite: free-count vs largest-contiguous-run gauges and the
    alloc-stall histogram — allocator pressure must be visible."""
    from paddle_tpu import observe
    observe.enable()
    pool = KVPool(num_blocks=8, block_size=4)
    assert pool.largest_free_run() == 8
    assert pool.fragmentation() == 0.0
    # carve holes: claim all, free alternating pages
    ids = pool.alloc(8)
    pool.free([ids[i] for i in range(0, 8, 2)])
    assert pool.free_blocks() == 4
    assert pool.largest_free_run() == 1
    assert pool.fragmentation() == pytest.approx(0.75)
    snap = observe.snapshot()
    assert snap['gauges']['decode.kv_free_pages'] == 4
    assert snap['gauges']['decode.kv_largest_free_run'] == 1
    assert snap['gauges']['decode.kv_fragmentation'] == \
        pytest.approx(0.75)

    # a shortfall that the reclaimer rescues records a stall sample
    def reclaimer(n):
        held = [i for i in range(8) if pool.refcount(i) > 0][:n]
        if held:
            pool.free(held)
        return len(held)

    pool.set_reclaimer(reclaimer)
    got = pool.alloc(6)
    assert got is not None and len(got) == 6
    snap = observe.snapshot()
    stall = snap['histograms'].get('decode.alloc_stall_seconds', {})
    assert stall.get('count', 0) >= 1


def test_alloc_stall_on_exhaustion():
    from paddle_tpu import observe
    observe.enable()
    pool = KVPool(num_blocks=4, block_size=4)
    pool.alloc(4)
    assert pool.alloc(1) is None        # no reclaimer: stall recorded
    snap = observe.snapshot()
    assert snap['histograms'][
        'decode.alloc_stall_seconds']['count'] >= 1


# ------------------------------------------------------ packet wire form
@pytest.mark.parametrize('kv_dtype', _arena_dtypes())
def test_packet_roundtrip_bit_identical(kv_dtype):
    """Satellite: bytes -> restored page bit-identical to the source
    page at every arena dtype, per-row scales included."""
    eng = _engine(kv_dtype=kv_dtype)
    eng.start()
    prompt = _prompt(11, seed=1)
    eng.generate(prompt, max_new_tokens=1, timeout=120)
    pkt = handoff_mod.export_packet(eng, prompt)
    assert pkt is not None and pkt.n_pages == 2   # 11 tokens, bs=4
    assert pkt.kv_dtype == eng.kv_dtype
    assert pkt.tokens == prompt[:8]
    if kv_dtype in ('int8', 'fp8'):
        assert set(pkt.arrays) == {'lm_kcache', 'lm_vcache',
                                   'lm_kscale', 'lm_vscale'}
    else:
        assert set(pkt.arrays) == {'lm_kcache', 'lm_vcache'}

    back = KVPacket.from_bytes(pkt.to_bytes())
    assert back.header['kv_dtype'] == pkt.header['kv_dtype']
    assert back.tokens == pkt.tokens
    for name, arr in pkt.arrays.items():
        got = back.arrays[name]
        assert got.shape == arr.shape
        assert np.asarray(got).tobytes() == np.asarray(arr).tobytes(), \
            'arena %s not bit-identical across the wire' % name

    # install into a fresh engine and read the pages back out: the
    # restored arena content must match the packet bit-for-bit too
    dst = _engine(kv_dtype=kv_dtype)
    covered, installed, dedup = handoff_mod.install_packet(dst, back)
    assert covered == 8 and installed == 2 and dedup == 0
    ids, n = dst.prefix_cache.acquire(prompt)
    assert n == 8
    staged = dst.read_pages(ids)
    for name, arr in back.arrays.items():
        assert np.asarray(staged[name]).tobytes() == \
            np.asarray(arr).tobytes(), \
            'installed arena %s differs from the packet' % name
    dst.pool.free(ids)
    eng.shutdown()
    dst.shutdown(drain=False)


def test_cross_dtype_mismatch_raises_typed():
    """Satellite: an int8 packet must REFUSE an fp32 destination (and
    vice versa) instead of silently dequantizing."""
    a = _engine(kv_dtype='int8')
    a.start()
    prompt = _prompt(9, seed=2)
    a.generate(prompt, max_new_tokens=1, timeout=120)
    pkt = handoff_mod.export_packet(a, prompt)
    b = _engine()                       # fp32 arenas
    with pytest.raises(KVDtypeMismatchError):
        handoff_mod.install_packet(b, pkt)
    # geometry mismatch is its own typed error
    c = _engine(block_size=8, kv_dtype='int8')
    with pytest.raises(KVGeometryError):
        handoff_mod.install_packet(c, pkt)
    a.shutdown()
    b.shutdown(drain=False)
    c.shutdown(drain=False)


def test_packet_verify_knob_catches_corruption(monkeypatch):
    """PADDLE_TPU_HANDOFF_VERIFY (read per call): sha1 over the page
    payload, checked on decode."""
    eng = _engine()
    eng.start()
    prompt = _prompt(9, seed=3)
    eng.generate(prompt, max_new_tokens=1, timeout=120)
    monkeypatch.setenv('PADDLE_TPU_HANDOFF_VERIFY', '1')
    pkt = handoff_mod.export_packet(eng, prompt)
    wire = bytearray(pkt.to_bytes())
    assert KVPacket.from_bytes(bytes(wire)).tokens == prompt[:8]
    wire[-3] ^= 0xFF                    # flip a payload byte
    with pytest.raises(HandoffError):
        KVPacket.from_bytes(bytes(wire))
    monkeypatch.setenv('PADDLE_TPU_HANDOFF_VERIFY', '0')
    with pytest.raises(HandoffError):
        # a STAMPED packet is always verified on receive — the knob
        # gates whether the writer stamps (ISSUE 16: a socket packet
        # that went bad in flight must refuse typed, never install)
        KVPacket.from_bytes(bytes(wire))
    unstamped = bytearray(handoff_mod.export_packet(eng, prompt)
                          .to_bytes())
    assert b'sha1' not in bytes(unstamped)
    unstamped[-3] ^= 0xFF
    KVPacket.from_bytes(bytes(unstamped))   # knob off: never stamped
    eng.shutdown()


def test_staging_no_per_handoff_allocation_growth():
    """Satellite: page export serializes through REUSED host staging
    buffers — one per (arena, dtype), allocated on first use, never
    per handoff."""
    eng = _engine()
    eng.start()
    prompt = _prompt(30, seed=4)        # 7 full pages of 4
    eng.generate(prompt, max_new_tokens=1, timeout=120)
    first = handoff_mod.export_packet(eng, prompt)
    allocs_after_first = eng._staging_allocs
    assert allocs_after_first >= 1
    wires = {first.to_bytes()}
    for _ in range(4):
        pkt = handoff_mod.export_packet(eng, prompt)
        wires.add(pkt.to_bytes())
    assert eng._staging_allocs == allocs_after_first, \
        'staging buffers must be reused across handoffs'
    assert len(wires) == 1, 'repeated exports must be byte-identical'
    eng.shutdown()


def test_export_owns_its_arrays():
    """Regression: read_pages used to return views of the shared
    staging buffers, so a later export (the router runs handoffs on a
    thread pool) silently overwrote an earlier packet's payload.
    Packets must own their arrays."""
    eng = _engine()
    eng.start()
    a, b = _prompt(16, seed=11), _prompt(16, seed=12)
    eng.generate(a, max_new_tokens=1, timeout=120)
    eng.generate(b, max_new_tokens=1, timeout=120)
    pkt_a = handoff_mod.export_packet(eng, a)
    before = {name: np.asarray(arr).tobytes()
              for name, arr in pkt_a.arrays.items()}
    handoff_mod.export_packet(eng, b)
    for name, arr in pkt_a.arrays.items():
        assert np.asarray(arr).tobytes() == before[name], \
            'arena %s of an exported packet was overwritten by a ' \
            'later export' % name
    eng.shutdown()


def test_install_failure_frees_pages(monkeypatch):
    """Regression: a write_pages failure mid-install must release the
    acquired head pins AND the freshly allocated tail pages — repeated
    handoff failures must not drain the decode pool."""
    src = _engine()
    src.start()
    prompt = _prompt(16, seed=13)
    src.generate(prompt, max_new_tokens=1, timeout=120)
    pkt = handoff_mod.export_packet(src, prompt)
    dst = _engine()
    free0 = dst.pool.free_blocks()

    def boom(*a, **kw):
        raise RuntimeError('injected write failure')

    monkeypatch.setattr(dst, 'write_pages', boom)
    with pytest.raises(RuntimeError):
        handoff_mod.install_packet(dst, pkt)
    assert dst.pool.free_blocks() == free0, \
        'failed install leaked KV pool pages'
    src.shutdown()
    dst.shutdown(drain=False)


def test_arena_set_mismatch_raises_before_alloc():
    """A packet whose arena-name set does not match the destination
    (e.g. scales missing) is refused as KVGeometryError before any
    page is allocated."""
    src = _engine()
    src.start()
    prompt = _prompt(9, seed=14)
    src.generate(prompt, max_new_tokens=1, timeout=120)
    pkt = handoff_mod.export_packet(src, prompt)
    pkt.header['arena_names'] = ['lm_kcache']
    dst = _engine()
    free0 = dst.pool.free_blocks()
    with pytest.raises(KVGeometryError):
        handoff_mod.install_packet(dst, pkt)
    assert dst.pool.free_blocks() == free0
    src.shutdown()
    dst.shutdown(drain=False)


def test_oversized_page_group_chunks_through_warmed_rungs():
    """Regression: page groups larger than pages_per_seq (a packet
    from a replica configured with a larger pages_per_seq) used to
    pad the gather/scatter to a shape warmup never traced; they now
    chunk through the warmed rungs. Round-trip stays bit-identical."""
    eng = _engine()
    n = eng.pages_per_seq + 3
    ids = eng.pool.alloc(n)
    assert ids is not None and len(ids) == n
    shapes = {name: np.asarray(arr).shape
              for name, arr in eng.read_pages(ids).items()}
    rng = np.random.RandomState(15)
    payload = {name: rng.uniform(-1, 1, size=shp).astype('float32')
               for name, shp in shapes.items()}
    eng.write_pages(ids, payload)
    back = eng.read_pages(ids)
    for name, want in payload.items():
        assert np.array_equal(np.asarray(back[name]), want), \
            'arena %s lost data across the chunked round-trip' % name
    eng.pool.free(ids)
    eng.shutdown(drain=False)


# ------------------------------------------------------------ e2e hops
@pytest.mark.parametrize('kv_dtype', ['float32', 'int8'])
def test_handoff_e2e_bit_identical(kv_dtype):
    """Acceptance: prefill on replica A, decode on replica B ==
    single-replica decode, bit for bit, at fp32 and int8 KV."""
    prompt = _prompt(13, seed=5)
    base = _engine(kv_dtype=kv_dtype)
    base.start()
    ref = base.generate(prompt, max_new_tokens=10, temperature=0.7,
                        seed=42, timeout=120)
    base.shutdown()

    a = _engine(kv_dtype=kv_dtype)
    b = _engine(kv_dtype=kv_dtype)
    a.start()
    b.start()
    a.generate(prompt, max_new_tokens=1, temperature=0.7, seed=42,
               timeout=120)
    covered = handoff_mod.handoff(a, b, prompt)
    assert covered == (len(prompt) // 4) * 4
    got = b.generate(prompt, max_new_tokens=10, temperature=0.7,
                     seed=42, timeout=120)
    assert got == ref
    a.shutdown()
    b.shutdown()


def test_handoff_then_preempt_and_resume_on_b():
    """Acceptance: after the handoff, replica B preempts the sequence
    under page pressure and the recompute-requeue continuation is
    still bit-exact."""
    from paddle_tpu import observe
    observe.enable()
    long_prompt = _prompt(14, seed=6)
    other_prompt = _prompt(12, seed=7)
    refs = []
    for p, mn in ((long_prompt, 12), (other_prompt, 12)):
        e = _engine()
        e.start()
        refs.append(e.generate(p, max_new_tokens=mn, temperature=0.6,
                               seed=9, timeout=120))
        e.shutdown()

    a = _engine()
    a.start()
    a.generate(long_prompt, max_new_tokens=1, temperature=0.6, seed=9,
               timeout=120)
    # B: 12 pages total; each sequence needs up to 7 — two running
    # sequences exhaust the pool and preempt the youngest
    b = _engine(num_blocks=12)
    b.start()
    handoff_mod.handoff(a, b, long_prompt)
    s1 = b.submit(long_prompt, max_new_tokens=12, temperature=0.6,
                  seed=9)
    s2 = b.submit(other_prompt, max_new_tokens=12, temperature=0.6,
                  seed=9)
    got = [s1.result(120), s2.result(120)]
    snap = observe.snapshot()
    assert snap['counters'].get('decode.preemptions_total', 0) > 0, \
        'test must actually exercise preemption on B'
    assert got == refs
    a.shutdown()
    b.shutdown()
    assert b.pool.free_blocks() == b.pool.num_blocks


def test_phase_router_e2e_zero_misses():
    """The pipeline: mixed requests through 1 prefill + 2 decode
    replicas == sequential single-engine decode, with ZERO post-warmup
    executor cache misses on either fleet and dedup across the
    handoff boundary for the shared system prompt."""
    from paddle_tpu import observe
    observe.enable()
    shared = _prompt(8, seed=8)
    rng = np.random.RandomState(9)
    reqs = []
    for i in range(6):
        tail = [int(t) for t in rng.randint(0, 60, 3 + i)]
        reqs.append(dict(prompt_ids=shared + tail,
                         max_new_tokens=5 + (i % 3),
                         temperature=0.0 if i % 2 else 0.6,
                         seed=100 + i))
    base = _engine()
    base.start()
    refs = [base.generate(timeout=120, **r) for r in reqs]
    base.shutdown()

    pre = [_engine(name='pf0')]
    dec = [_engine(name='dc0'), _engine(name='dc1')]
    for e in pre + dec:
        e.warmup()
        e.start()
    router = PhaseRouter(pre, dec, route='hx')

    def misses(snap):
        return sum(v for k, v in snap['counters'].items()
                   if k.startswith('executor.cache_miss_total'))

    snap0 = observe.snapshot()
    streams = [router.submit(r['prompt_ids'],
                             max_new_tokens=r['max_new_tokens'],
                             temperature=r['temperature'],
                             seed=r['seed'], session='s1')
               for r in reqs]
    got = [s.result(120) for s in streams]
    snap1 = observe.snapshot()
    assert got == refs
    assert misses(snap1) - misses(snap0) == 0, \
        'handoff traffic must not mint executor signatures'
    assert snap1['counters'].get('handoff.count_total', 0) >= 1
    # the shared prefix crossed the wire once per decode replica at
    # most — later handoffs dedup against the destination cache
    assert snap1['counters'].get('handoff.pages_deduped_total', 0) > 0
    gauges = snap1['gauges']
    assert gauges.get('router.phase_replicas{phase=prefill,'
                      'route=hx}') == 1
    assert gauges.get('router.phase_replicas{phase=decode,'
                      'route=hx}') == 2
    router.close(shutdown_replicas=True)


def test_phase_router_colocated_and_sheds():
    dec = [_engine(name='c0')]
    dec[0].warmup()
    dec[0].start()
    router = PhaseRouter([], dec, route='cx', colocated=True)
    prompt = _prompt(9, seed=10)
    base = _engine()
    base.start()
    ref = base.generate(prompt, max_new_tokens=6, timeout=120)
    base.shutdown()
    assert router.generate(prompt, timeout=120,
                           max_new_tokens=6) == ref
    # expired deadline sheds synchronously, before any phase runs
    with pytest.raises(SLOShedError):
        router.submit(prompt, deadline_s=-0.001)
    router.close()
    with pytest.raises(EngineClosedError):
        router.submit(prompt)
    dec[0].shutdown()


def test_phase_pressure_policies():
    """ttft_pressure / page_pressure close the per-phase scaling loop
    over the PhaseRouter's signals."""

    class FakePR(object):
        ttft = None
        frac = None

        def prefill_phase_p95(self):
            return self.ttft

        def decode_free_page_frac(self):
            return self.frac

    pr = FakePR()
    press, calm = ttft_pressure(pr, budget_s=0.5)
    assert press(0.0) == (False, None, {'ttft_p95': None,
                                        'ttft_budget': 0.5,
                                        'mean_queue_depth': 0.0,
                                        'burn_rate': None})
    pr.ttft = 0.6
    hot, reason, signals = press(1.0)
    assert hot and reason == 'ttft_burn'
    assert not calm(signals)
    pr.ttft = 0.2
    _, _, signals = press(2.0)
    assert calm(signals)

    press, calm = page_pressure(pr, free_low=0.2, free_high=0.5)
    assert press(0.0)[0] is False       # no decode replicas yet
    pr.frac = 0.1
    hot, reason, signals = press(1.0)
    assert hot and reason == 'page_pressure'
    assert not calm(signals)
    pr.frac = 0.7
    _, _, signals = press(2.0)
    assert calm(signals)


def test_statusz_panels_show_handoff_and_phases():
    from paddle_tpu import observe
    from paddle_tpu.observe.diagnostics import (_decode_status,
                                                _router_status)
    observe.enable()
    a = _engine()
    b = _engine()
    a.start()
    b.start()
    prompt = _prompt(12, seed=11)
    a.generate(prompt, max_new_tokens=1, timeout=120)
    handoff_mod.handoff(a, b, prompt)
    observe.set_gauge('router.phase_replicas', 1, phase='prefill',
                      route='r')
    observe.set_gauge('router.phase_replicas_ready', 1,
                      phase='prefill', route='r')
    observe.inc('router.phase_dispatch_total', phase='prefill',
                replica='pf0', route='r')
    snap = observe.snapshot()
    doc = _decode_status(snap)
    assert doc['kv_largest_free_run'] is not None
    assert doc['kv_fragmentation'] is not None
    assert doc['handoff_total'] == 1
    assert doc['handoff_pages_installed_total'] == 3
    assert doc['handoff_bytes_total'] > 0
    rdoc = _router_status(snap)
    assert rdoc['phases']['prefill']['total'] == 1
    assert rdoc['phases']['prefill']['dispatched'] == 1
    a.shutdown()
    b.shutdown()


# ------------------------------------------------------------- tooling
def test_metrics_report_fleet_phase_split(tmp_path):
    """Satellite: --fleet renders the phase-split view (census,
    handoff, TTFT attribution) from a snapshot JSONL — schema-stable,
    no jax import."""
    from paddle_tpu import observe
    observe.enable(jsonl=str(tmp_path / 'm.jsonl'))
    observe.set_gauge('router.phase_replicas', 1, phase='prefill',
                      route='dx')
    observe.set_gauge('router.phase_replicas', 2, phase='decode',
                      route='dx')
    observe.set_gauge('router.phase_replicas_ready', 2,
                      phase='decode', route='dx')
    observe.inc('router.phase_dispatch_total', 7, phase='decode',
                replica='dc0', route='dx')
    observe.inc('handoff.count_total', 7)
    observe.inc('handoff.bytes_total', 7168)
    observe.inc('handoff.pages_installed_total', 20)
    observe.inc('handoff.pages_deduped_total', 8)
    for v in (0.01, 0.02, 0.03):
        observe.record('handoff.seconds', v)
        observe.record('handoff.ttft_attributed_seconds', v * 2,
                       route='dx')
        observe.record('decode.inter_token_seconds', v / 2)
    observe.record('decode.ttft_seconds', 0.05, cached='0')
    observe.flush(kind='summary')

    tool = os.path.join(REPO, 'tools', 'metrics_report.py')
    r = subprocess.run(
        [sys.executable, tool, str(tmp_path / 'm.jsonl'), '--fleet',
         '--json'],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    ph = doc['phases']
    assert ph['census']['prefill']['replicas'] == 1
    assert ph['census']['decode']['replicas'] == 2
    assert ph['census']['decode']['replicas_ready'] == 2
    assert ph['census']['decode']['dispatched'] == 7
    assert ph['handoff']['count'] == 7
    assert ph['handoff']['bytes'] == 7168
    assert ph['handoff']['pages_deduped'] == 8
    assert ph['handoff']['seconds']['count'] == 3
    assert ph['attribution']['prefill_plus_handoff']['count'] == 3
    assert ph['attribution']['ttft_cold']['count'] == 1
    assert ph['attribution']['inter_token']['count'] == 3
    # human rendering names the sections
    r2 = subprocess.run(
        [sys.executable, tool, str(tmp_path / 'm.jsonl'), '--fleet'],
        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0, r2.stderr
    assert 'phase split' in r2.stdout
    assert 'TTFT vs inter-token attribution' in r2.stdout
    # no jax import on the --fleet path
    probe = subprocess.run(
        [sys.executable, '-c',
         'import importlib.util, sys\n'
         'spec = importlib.util.spec_from_file_location("mr", %r)\n'
         'm = importlib.util.module_from_spec(spec)\n'
         'spec.loader.exec_module(m)\n'
         'assert m.main([%r, "--fleet"]) == 0\n'
         'assert "jax" not in sys.modules\n'
         % (tool, str(tmp_path / 'm.jsonl'))],
        capture_output=True, text=True, timeout=60)
    assert probe.returncode == 0, probe.stderr


def test_bench_disagg_acceptance():
    """ISSUE 14 headline: under the mixed long-prompt/long-decode
    chaos schedule, the disaggregated fleet's inter-token p99 beats
    the colocated fleet at equal chip count, TTFT stays in budget,
    lost == 0, and the zero-recompile invariant holds on both fleets
    — bench_disagg asserts all of it internally."""
    from paddle_tpu import observe
    observe.enable()
    sys.path.insert(0, REPO)
    try:
        import bench
        out = bench.bench_disagg(duration=2.5, clients=6, vocab=2048,
                                 n_layer=2, n_head=4, d_model=64,
                                 d_inner=128, pages_per_seq=32,
                                 num_blocks=256)
    finally:
        sys.path.remove(REPO)
    assert out['workload'] == 'disagg'
    assert out['inter_token_p99_improvement'] > 1.0
    assert out['colocated']['lost'] == 0
    assert out['disaggregated']['lost'] == 0
    assert out['disaggregated']['post_warmup_cache_misses'] == 0
    assert out['colocated']['post_warmup_cache_misses'] == 0
    assert out['disaggregated']['handoffs'] > 0
    assert out['disaggregated']['handoff_pages_deduped'] > 0
    assert out['page_wire_bytes_fp32'] > 0
