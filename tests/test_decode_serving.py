"""Decode serving: KV pool alloc/free/refcount + exhaustion, ragged
paged attention vs a dense masked reference across mixed lengths, and
the continuous-batching e2e — concurrent mixed-length generation
bit-identical to sequential single-request decode, zero executor cache
misses after warmup, KV pages fully reclaimed after drain, preemption
(evict-and-requeue) preserving streams."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.serving import QueueFullError, EngineClosedError
from paddle_tpu.serving.decode import (BlockTable, DecodeEngine, KVPool,
                                       LMSpec, random_weights)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPEC = LMSpec(vocab_size=60, n_layer=2, n_head=2, d_key=8, d_value=8,
              d_model=16, d_inner=32)
WEIGHTS = random_weights(SPEC, seed=3)


@pytest.fixture(autouse=True)
def _observe_clean():
    from paddle_tpu import observe
    yield
    observe._SINK['path'] = None
    observe._SINK['trace_path'] = None
    observe.disable()
    observe.reset()


def _engine(**kw):
    kw.setdefault('max_batch', 4)
    kw.setdefault('block_size', 4)
    kw.setdefault('num_blocks', 64)
    kw.setdefault('pages_per_seq', 4)
    kw.setdefault('weights', WEIGHTS)
    kw.setdefault('place', fluid.CPUPlace())
    return DecodeEngine(SPEC, **kw)


def _mixed_requests(n=6, seed=0, vocab=60):
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.randint(1, 10))
        reqs.append(dict(prompt_ids=rng.randint(0, vocab, plen).tolist(),
                         max_new_tokens=int(rng.randint(3, 7)),
                         temperature=0.0 if i % 2 == 0 else 0.7,
                         seed=100 + i))
    return reqs


def _misses(snap):
    return sum(v for k, v in snap['counters'].items()
               if k.startswith('executor.cache_miss_total'))


_SEQ_REF = {}


def _sequential_reference(seed):
    """Per-request sequential decode outputs (one fresh engine per
    request), cached per request-set — the bit-identity baseline shared
    by the continuous-batching and preemption e2es."""
    if seed not in _SEQ_REF:
        out = []
        for r in _mixed_requests(seed=seed):
            e = _engine()
            e.start()
            out.append(e.generate(timeout=120, **r))
            e.shutdown()
        _SEQ_REF[seed] = out
    return _SEQ_REF[seed]


# ------------------------------------------------------------- KV pool
def test_kv_pool_alloc_free_refcount():
    pool = KVPool(num_blocks=8, block_size=4)
    assert pool.free_blocks() == 8
    assert pool.blocks_for(0) == 0
    assert pool.blocks_for(1) == 1
    assert pool.blocks_for(4) == 1
    assert pool.blocks_for(5) == 2

    a = pool.alloc(3)
    assert len(a) == 3 and pool.free_blocks() == 5
    assert pool.alloc(6) is None          # exhaustion is None, not raise
    assert pool.free_blocks() == 5        # failed alloc takes nothing

    pool.incref(a)                        # shared prefix: two owners
    pool.free(a)
    assert pool.free_blocks() == 5        # still one owner
    pool.free(a)
    assert pool.free_blocks() == 8        # last owner returns the pages
    with pytest.raises(ValueError):
        pool.free(a)                      # double free detected


def test_kv_pool_grow_and_release():
    pool = KVPool(num_blocks=4, block_size=4)
    t = BlockTable()
    assert pool.grow(t, 1) and len(t) == 1
    assert pool.grow(t, 4) and len(t) == 1     # still fits page 0
    assert pool.grow(t, 5) and len(t) == 2
    assert pool.grow(t, 16) and len(t) == 4
    t2 = BlockTable()
    assert not pool.grow(t2, 1)                # exhausted
    pool.release(t)
    assert pool.free_blocks() == 4 and len(t) == 0
    assert pool.grow(t2, 16)


def test_kv_pool_fork_shares_pages():
    pool = KVPool(num_blocks=4, block_size=4)
    t = BlockTable()
    pool.grow(t, 8)
    f = pool.fork(t)
    assert f.block_ids == t.block_ids
    pool.release(t)
    assert pool.free_blocks() == 2             # fork still owns them
    pool.release(f)
    assert pool.free_blocks() == 4


# -------------------------------------------- ragged paged attention
def test_paged_attention_matches_dense_masked_reference():
    """XLA gather path vs reference_attention (dense keys + key_length
    mask) across mixed lengths: gathering pages in block-table order
    must reconstruct exactly the dense sequence."""
    import jax.numpy as jnp
    from paddle_tpu.ops.attention_ops import reference_attention
    from paddle_tpu.ops.pallas.paged_attention import paged_attention

    rng = np.random.RandomState(7)
    b, h, nb, bs, p, d = 4, 2, 32, 4, 4, 8
    lens = np.asarray([1, 4, 7, 15], np.int32)     # mixed, page-crossing
    dense_k = rng.randn(b, h, p * bs, d).astype('f')
    dense_v = rng.randn(b, h, p * bs, d).astype('f')
    q = rng.randn(b, h, d).astype('f')

    # scatter the dense sequences into shuffled physical pages
    k_pages = rng.randn(nb, h, bs, d).astype('f')  # garbage elsewhere
    v_pages = rng.randn(nb, h, bs, d).astype('f')
    perm = rng.permutation(nb)[:b * p].reshape(b, p)
    for i in range(b):
        for j in range(p):
            k_pages[perm[i, j]] = dense_k[i, :, j * bs:(j + 1) * bs, :]
            v_pages[perm[i, j]] = dense_v[i, :, j * bs:(j + 1) * bs, :]

    got = paged_attention(jnp.asarray(q), jnp.asarray(k_pages),
                          jnp.asarray(v_pages),
                          jnp.asarray(perm, jnp.int32),
                          jnp.asarray(lens))
    want = reference_attention(jnp.asarray(q)[:, :, None, :],
                               jnp.asarray(dense_k),
                               jnp.asarray(dense_v),
                               key_length=jnp.asarray(lens))[:, :, 0, :]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------- continuous batching
def test_continuous_batching_bit_identical_and_zero_misses():
    """THE acceptance e2e: concurrent mixed-length generation through
    the decode engine yields per-sequence token streams bit-identical
    to sequential single-request decode, with zero executor cache
    misses after warmup and the pool fully reclaimed after drain."""
    from paddle_tpu import observe
    observe.enable()
    reqs = _mixed_requests()

    eng = _engine()
    assert eng.warmup() == len(eng.prompt_buckets) + 1
    m0 = _misses(observe.snapshot())
    eng.start()
    assert eng.ready()
    streams = [eng.submit(**r) for r in reqs]
    conc = [s.result(timeout=120) for s in streams]
    eng.shutdown()
    assert _misses(observe.snapshot()) == m0, \
        'live decode traffic must be 100% executor cache hits'
    assert eng.pool.free_blocks() == eng.pool.num_blocks, \
        'KV pages must be fully reclaimed after drain'

    assert conc == _sequential_reference(0), \
        'continuous batching changed token streams'
    for s, r in zip(streams, reqs):
        assert len(s.result()) <= r['max_new_tokens']
        assert s.finish_reason in ('eos', 'max_tokens')


def test_preemption_requeue_preserves_streams():
    """A pool too small for the offered load must preempt-and-requeue
    (never fail requests), reclaim every page, still produce the exact
    sequential token streams (recompute-style preemption), and leave a
    flight-recorder trail explaining the latency spikes."""
    from paddle_tpu import observe
    observe.enable()
    observe.arm_flight()
    reqs = _mixed_requests(seed=0)
    want = _sequential_reference(0)

    eng = _engine(num_blocks=7)    # max seq needs 4 pages; force evicts
    eng.start()
    streams = [eng.submit(**r) for r in reqs]
    got = [s.result(timeout=120) for s in streams]
    eng.shutdown()
    snap = observe.snapshot()
    assert snap['counters'].get('decode.preemptions_total', 0) > 0, \
        'test must actually exercise eviction'
    assert snap['counters'].get('decode.pool_exhausted_total', 0) > 0
    assert got == want
    assert eng.pool.free_blocks() == eng.pool.num_blocks
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'decode_pool_exhausted' in kinds
    assert 'decode_preempt' in kinds


def test_streaming_tokens_arrive_incrementally():
    eng = _engine()
    eng.start()
    stream = eng.submit([5, 9, 2], max_new_tokens=8)
    got = []
    for tok in stream:
        got.append(tok)
        assert isinstance(tok, int)
    assert got == stream.result()
    assert stream.done()
    eng.shutdown()


def test_sampled_streams_deterministic_per_seed():
    eng = _engine()
    eng.start()
    kw = dict(max_new_tokens=8, temperature=0.9)
    a = eng.generate([4, 4, 4], seed=11, **kw)
    b = eng.generate([4, 4, 4], seed=11, **kw)
    c = eng.generate([4, 4, 4], seed=12, **kw)
    eng.shutdown()
    assert a == b
    assert a != c   # astronomically unlikely to collide over 8 tokens


def test_submit_validation_and_backpressure():
    eng = _engine(max_queue_depth=2)
    # never started: requests queue but nothing drains
    with pytest.raises(ValueError):
        eng.submit([])
    with pytest.raises(ValueError):
        eng.submit([1], max_new_tokens=0)
    with pytest.raises(ValueError):
        eng.submit(list(range(40)))            # > max_prompt_len
    with pytest.raises(ValueError):
        eng.submit([1, 2], max_new_tokens=100)  # > per-seq capacity
    eng.submit([1], max_new_tokens=2)
    eng.submit([1], max_new_tokens=2)
    with pytest.raises(QueueFullError):
        eng.submit([1], max_new_tokens=2)
    eng.shutdown(drain=False)
    with pytest.raises(EngineClosedError):
        eng.submit([1], max_new_tokens=2)


def test_shutdown_without_drain_fails_pending():
    eng = _engine()
    stream = eng.submit([1, 2], max_new_tokens=4)   # never started
    eng.shutdown(drain=False)
    with pytest.raises(EngineClosedError):
        stream.result(timeout=5)
    assert stream.finish_reason == 'error'
    assert eng.pool.free_blocks() == eng.pool.num_blocks


def test_statusz_decode_panel():
    from paddle_tpu import observe
    from paddle_tpu.observe.diagnostics import _decode_status
    observe.enable()
    assert _decode_status(observe.snapshot()) is None
    eng = _engine()
    eng.start()
    eng.generate([3, 1, 4], max_new_tokens=4)
    doc = _decode_status(observe.snapshot())
    assert doc['tokens_total'] >= 4
    assert doc['kv_blocks_total'] == eng.pool.num_blocks
    assert doc['kv_blocks_free'] == eng.pool.num_blocks  # drained
    assert doc['finished_total'].get('max_tokens', 0) + \
        doc['finished_total'].get('eos', 0) >= 1
    eng.shutdown()
    assert doc['running_seqs'] is not None


def test_decode_bench_json_schema(tmp_path):
    """The --json schema decode_bench promises (and bench.py's
    decode_transformer scenario builds on) cannot rot."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'decode_bench.py'),
         '--duration', '1.0', '--clients', '2', '--vocab', '60',
         '--n-layer', '1', '--n-head', '2', '--d-model', '16',
         '--d-inner', '32', '--block-size', '4', '--num-blocks', '32',
         '--pages-per-seq', '6', '--prompt-lo', '1', '--prompt-hi', '12',
         '--max-new', '8', '--prefix-cache', '--spec-k', '2',
         '--shared-prefix', '0.9', '--shared-prefix-len', '9',
         '--kv-dtype', 'int8', '--json'],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS='cpu'))
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    for key in ('tokens_per_s', 'inter_token_ms', 'request_ms',
                'requests_ok', 'preemptions', 'warmup', 'executor',
                'engine', 'kv_blocks_free_end', 'cache_hit_rate',
                'prefill_tokens_skipped', 'accepted_draft_length',
                'ttft_ms', 'spec_steps', 'resident_seqs_peak',
                'kv_bytes_per_token'):
        assert key in doc, key
    assert doc['requests_ok'] > 0
    assert doc['inter_token_ms']['p99'] is not None
    assert doc['executor']['cache_misses'] <= \
        doc['warmup']['signatures'] + 1   # +1: startup program compile
    assert doc['kv_blocks_free_end'] == doc['engine']['num_blocks']
    # the shared-prefix mix must actually exercise the new machinery
    assert doc['cache_hit_rate'] > 0
    assert doc['prefill_tokens_skipped'] > 0
    assert doc['ttft_ms']['cached'] is not None
    for k in ('p50', 'mean'):
        assert k in doc['accepted_draft_length'], k
    assert doc['engine']['prefix_cache'] is True
    assert doc['engine']['spec_k'] == 2
    # the int8 arena: 1 byte/elem + per-row fp32 scale pair, and the
    # whole prefix-cache/spec path ran over it (asserts above)
    assert doc['engine']['kv_dtype'] == 'int8'
    spec_bytes = 1 * 2 * (8 + 8) + 1 * 2 * 2 * 4   # L*H*(dk+dv) + scales
    assert doc['kv_bytes_per_token'] == spec_bytes
    assert doc['resident_seqs_peak'] >= 1


@pytest.mark.slow
def test_decode_soak_concurrent_submitters():
    """Sustained mixed traffic from concurrent submit threads: every
    stream resolves, pages reclaim, worker survives."""
    eng = _engine(num_blocks=24, max_queue_depth=256)
    eng.start()
    results, errs = [], []
    mu = threading.Lock()

    def client(seed):
        rng = np.random.RandomState(seed)
        for _ in range(12):
            plen = int(rng.randint(1, 10))
            try:
                toks = eng.generate(
                    rng.randint(0, 60, plen).tolist(),
                    max_new_tokens=int(rng.randint(1, 7)),
                    temperature=float(rng.choice([0.0, 0.8])),
                    seed=int(rng.randint(1 << 30)), timeout=120)
                with mu:
                    results.append(toks)
            except Exception as e:   # pragma: no cover - diagnostic
                with mu:
                    errs.append(e)

    threads = [threading.Thread(target=client, args=(50 + i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.shutdown()
    assert not errs
    assert len(results) == 72
    assert all(len(r) >= 1 for r in results)
    assert eng.pool.free_blocks() == eng.pool.num_blocks
