"""Child process for the crash/resume e2e (driven by tests/test_fault.py
— NOT a test module itself).

Trains a deterministic model with mid-epoch checkpointing and writes
the final parameters to an .npz. Environment contract:

    FT_CKPT_DIR                  checkpoint tree root (required)
    FT_OUT                       final-params .npz path (required)
    FT_SYNC_SAVE                 optional: synchronous saves (so commit
                                 order is deterministic vs the kill step)
    FT_MESH_DP=k                 optional: ELASTIC mode — transpile onto
                                 a dp=k data-parallel mesh over 8 virtual
                                 CPU devices and train the dyadic-exact
                                 linear model (see below) at a fixed
                                 global batch; resume runs may pass a
                                 DIFFERENT k to exercise mesh resharding
    FT_METRICS                   optional: observe JSONL snapshot path
                                 (the driver asserts fault.reshard_total
                                 appears after an elastic resume)
    PADDLE_TPU_FI_KILL_AT_STEP   optional: die (exit 42) at global step k
    PADDLE_TPU_FI_PREEMPT_AT_STEP  optional: SIGTERM self at step k (the
                                 preemption notice; exit code -SIGTERM)
    PADDLE_TPU_FI_CORRUPT_CKPT_AT  optional: truncate the checkpoint
                                 committed at step k

Run once clean to get the reference params; run with a kill/preempt var
to simulate preemption; run again WITHOUT it (resume=True picks up the
newest complete checkpoint) and the final params must be bit-identical
to the clean run — init, shuffle order, and updates are all
deterministic, so any divergence is a checkpoint/replay bug.

The elastic model keeps EVERY quantity an exactly-representable dyadic
rational: integer data, zero init, L1 loss (each item's gradient
contribution is ±x/8), lr = 2^-3. All cross-item sums are then exact in
ANY association, so the update stream — and therefore the final params
— is bitwise identical at ANY dp width, and the e2e's bit-identity
assertion survives the reduction-order changes a different mesh shape
introduces.
"""

import os

from paddle_tpu.core.platform_boot import force_host_cpu

_MESH_DP = int(os.environ.get('FT_MESH_DP', '0') or 0)

force_host_cpu(8 if _MESH_DP else None)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import io as pio  # noqa: E402
from paddle_tpu import reader as R  # noqa: E402
from paddle_tpu.fault import CheckpointConfig  # noqa: E402


def train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='tanh')
    pred = fluid.layers.fc(input=h, size=1)
    return [fluid.layers.mean(fluid.layers.square_error_cost(pred, y))]


def batches():
    rng = np.random.RandomState(7)
    w = rng.randn(4, 1).astype('float32')
    for _ in range(12):
        xs = rng.randn(8, 4).astype('float32')
        yield {'x': xs, 'y': (xs @ w).astype('float32')}


def elastic_train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(
        input=x, size=1,
        param_attr=fluid.ParamAttr(
            name='ew', initializer=fluid.initializer.Constant(0.0)),
        bias_attr=fluid.ParamAttr(
            name='eb', initializer=fluid.initializer.Constant(0.0)))
    return [fluid.layers.mean(fluid.layers.abs(
        fluid.layers.elementwise_sub(pred, y)))]


def elastic_batches():
    # integer data at a FIXED global batch of 8 (divisible by every dp
    # width the drill uses: 2, 4, 8)
    rng = np.random.RandomState(5)
    w = rng.randint(-3, 4, (4, 1)).astype('float32')
    for _ in range(12):
        xs = rng.randint(-4, 5, (8, 4)).astype('float32')
        yield {'x': xs, 'y': (xs @ w).astype('float32')}


def main():
    ckpt_dir = os.environ['FT_CKPT_DIR']
    out = os.environ['FT_OUT']
    if os.environ.get('FT_METRICS'):
        from paddle_tpu import observe
        observe.enable(jsonl=os.environ['FT_METRICS'])
    elastic = _MESH_DP > 0
    reader = R.CheckpointableReader(
        elastic_batches if elastic else batches, shuffle_buf=4, seed=11)
    cfg = CheckpointConfig(ckpt_dir, save_every_steps=3, keep_last=3,
                           resume=True,
                           async_save=not os.environ.get('FT_SYNC_SAVE'))
    trainer = fluid.Trainer(
        train_func=elastic_train_func if elastic else train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(
            learning_rate=0.125 if elastic else 0.05),
        place=fluid.CPUPlace(), checkpoint_config=cfg)
    if elastic:
        from paddle_tpu.parallel.mesh import make_mesh
        from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                                    transpile)
        transpile(fluid.default_main_program(), make_mesh(dp=_MESH_DP),
                  ParallelStrategy(data_parallel=True))
    trainer.train(num_epochs=2, reader=reader)
    arrays, _ = pio._snapshot_vars(fluid.default_main_program(),
                                   predicate=pio._is_parameter)
    with open(out, 'wb') as f:
        np.savez(f, **arrays)


if __name__ == '__main__':
    main()
