"""Child process for the crash/resume e2e (driven by tests/test_fault.py
— NOT a test module itself).

Trains a deterministic 2-layer model with mid-epoch checkpointing and
writes the final parameters to an .npz. Environment contract:

    FT_CKPT_DIR                  checkpoint tree root (required)
    FT_OUT                       final-params .npz path (required)
    FT_SYNC_SAVE                 optional: synchronous saves (so commit
                                 order is deterministic vs the kill step)
    PADDLE_TPU_FI_KILL_AT_STEP   optional: die (exit 42) at global step k
    PADDLE_TPU_FI_CORRUPT_CKPT_AT  optional: truncate the checkpoint
                                 committed at step k

Run once clean to get the reference params; run with the kill var to
simulate preemption; run again WITHOUT it (resume=True picks up the
newest complete checkpoint) and the final params must be bit-identical
to the clean run — init, shuffle order, and updates are all
deterministic, so any divergence is a checkpoint/replay bug.
"""

import os

from paddle_tpu.core.platform_boot import force_host_cpu

force_host_cpu()

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import io as pio  # noqa: E402
from paddle_tpu import reader as R  # noqa: E402
from paddle_tpu.fault import CheckpointConfig  # noqa: E402


def train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='tanh')
    pred = fluid.layers.fc(input=h, size=1)
    return [fluid.layers.mean(fluid.layers.square_error_cost(pred, y))]


def batches():
    rng = np.random.RandomState(7)
    w = rng.randn(4, 1).astype('float32')
    for _ in range(12):
        xs = rng.randn(8, 4).astype('float32')
        yield {'x': xs, 'y': (xs @ w).astype('float32')}


def main():
    ckpt_dir = os.environ['FT_CKPT_DIR']
    out = os.environ['FT_OUT']
    reader = R.CheckpointableReader(batches, shuffle_buf=4, seed=11)
    cfg = CheckpointConfig(ckpt_dir, save_every_steps=3, keep_last=3,
                           resume=True,
                           async_save=not os.environ.get('FT_SYNC_SAVE'))
    trainer = fluid.Trainer(
        train_func=train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.05),
        place=fluid.CPUPlace(), checkpoint_config=cfg)
    trainer.train(num_epochs=2, reader=reader)
    arrays, _ = pio._snapshot_vars(fluid.default_main_program(),
                                   predicate=pio._is_parameter)
    with open(out, 'wb') as f:
        np.savez(f, **arrays)


if __name__ == '__main__':
    main()
