"""Per-op numerical checks vs numpy (reference: fluid/tests/unittests
test_activation_op.py, test_elementwise_*_op.py, test_reduce_op.py —
check_output analog)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from util import run_startup_and, rand

X = rand(3, 4, seed=1, low=0.1, high=2.0)  # positive, for log/sqrt domains
XS = rand(3, 4, seed=2)                    # signed


def _unary(layer_fn, x, **kwargs):
    inp = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out = layer_fn(inp, **kwargs)
    return run_startup_and({'x': x}, [out])[0]


ACTIVATIONS = [
    ('sigmoid', lambda x: 1 / (1 + np.exp(-x)), XS),
    ('logsigmoid', lambda x: np.log(1 / (1 + np.exp(-x))), XS),
    ('exp', np.exp, XS),
    ('relu', lambda x: np.maximum(x, 0), XS),
    ('tanh', np.tanh, XS),
    ('sqrt', np.sqrt, X),
    ('abs', np.abs, XS),
    ('ceil', np.ceil, XS),
    ('floor', np.floor, XS),
    ('round', np.round, XS),
    ('reciprocal', lambda x: 1 / x, X),
    ('log', np.log, X),
    ('square', np.square, XS),
    ('softplus', lambda x: np.log1p(np.exp(x)), XS),
    ('softsign', lambda x: x / (1 + np.abs(x)), XS),
    ('leaky_relu', lambda x: np.where(x > 0, x, 0.02 * x), XS),
    ('elu', lambda x: np.where(x > 0, x, np.expm1(x)), XS),
    ('relu6', lambda x: np.clip(x, 0, 6), XS),
    ('softshrink', lambda x: np.where(x > 0.5, x - 0.5,
                                      np.where(x < -0.5, x + 0.5, 0)), XS),
    ('hard_shrink', lambda x: np.where(np.abs(x) > 0.5, x, 0), XS),
    ('hard_sigmoid', lambda x: np.clip(0.2 * x + 0.5, 0, 1), XS),
    ('swish', lambda x: x / (1 + np.exp(-x)), XS),
    ('stanh', lambda x: 1.7159 * np.tanh(2.0 / 3.0 * x), XS),
    ('soft_relu', lambda x: np.log1p(np.exp(np.clip(x, -40, 40))), XS),
    ('brelu', lambda x: np.clip(x, 0, 24), XS),
    ('thresholded_relu', lambda x: np.where(x > 1.0, x, 0), XS),
    ('sin', np.sin, XS),
    ('cos', np.cos, XS),
    ('rsqrt', lambda x: 1 / np.sqrt(x), X),
]


@pytest.mark.parametrize('name,ref,x', ACTIVATIONS,
                         ids=[a[0] for a in ACTIVATIONS])
def test_activation(name, ref, x):
    out = _unary(getattr(fluid.layers, name), x)
    np.testing.assert_allclose(out, ref(x.astype('float64')), rtol=2e-5,
                               atol=1e-6)


ELEMENTWISE = [
    ('elementwise_add', np.add), ('elementwise_sub', np.subtract),
    ('elementwise_mul', np.multiply), ('elementwise_div', np.divide),
    ('elementwise_max', np.maximum), ('elementwise_min', np.minimum),
    ('elementwise_pow', np.power),
]


@pytest.mark.parametrize('name,ref', ELEMENTWISE,
                         ids=[e[0] for e in ELEMENTWISE])
def test_elementwise(name, ref):
    a, b = rand(3, 4, seed=3, low=0.5, high=2.0), \
        rand(3, 4, seed=4, low=0.5, high=2.0)
    xa = fluid.layers.data(name='a', shape=[4], dtype='float32')
    xb = fluid.layers.data(name='b', shape=[4], dtype='float32')
    out = getattr(fluid.layers, name)(x=xa, y=xb)
    got = run_startup_and({'a': a, 'b': b}, [out])[0]
    np.testing.assert_allclose(got, ref(a, b), rtol=1e-5)


def test_elementwise_broadcast_axis():
    """Paddle-style broadcast: y's shape aligns to x at `axis`."""
    a = rand(2, 3, 4, seed=5)
    b = rand(3, seed=6)
    xa = fluid.layers.data(name='a', shape=[3, 4], dtype='float32')
    xb = fluid.layers.data(name='b', shape=[], dtype='float32')
    xb.shape = (3,)
    out = fluid.layers.elementwise_add(x=xa, y=xb, axis=1)
    got = run_startup_and({'a': a, 'b': b}, [out])[0]
    np.testing.assert_allclose(got, a + b[None, :, None], rtol=1e-6)


REDUCES = [('reduce_sum', np.sum), ('reduce_mean', np.mean),
           ('reduce_max', np.max), ('reduce_min', np.min),
           ('reduce_prod', np.prod)]


@pytest.mark.parametrize('name,ref', REDUCES, ids=[r[0] for r in REDUCES])
def test_reduce(name, ref):
    x = rand(3, 4, seed=7)
    inp = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out_all = getattr(fluid.layers, name)(inp)
    out_d1 = getattr(fluid.layers, name)(inp, dim=1, keep_dim=True)
    got = run_startup_and({'x': x}, [out_all, out_d1])
    np.testing.assert_allclose(got[0], ref(x), rtol=1e-5)
    np.testing.assert_allclose(got[1], ref(x, axis=1, keepdims=True),
                               rtol=1e-5)


def test_matmul_and_transpose():
    a, b = rand(2, 3, 4, seed=8), rand(2, 4, 5, seed=9)
    xa = fluid.layers.data(name='a', shape=[3, 4], dtype='float32')
    xb = fluid.layers.data(name='b', shape=[4, 5], dtype='float32')
    mm = fluid.layers.matmul(xa, xb)
    tr = fluid.layers.transpose(xa, perm=[0, 2, 1])
    got = run_startup_and({'a': a, 'b': b}, [mm, tr])
    np.testing.assert_allclose(got[0], a @ b, rtol=1e-5)
    np.testing.assert_allclose(got[1], a.transpose(0, 2, 1))


def test_softmax_log_softmax_clip_cumsum():
    x = rand(3, 5, seed=10)
    inp = fluid.layers.data(name='x', shape=[5], dtype='float32')
    sm = fluid.layers.softmax(inp)
    cl = fluid.layers.clip(inp, min=-0.5, max=0.5)
    cs = fluid.layers.cumsum(inp, axis=1)
    got = run_startup_and({'x': x}, [sm, cl, cs])
    e = np.exp(x - x.max(1, keepdims=True))
    np.testing.assert_allclose(got[0], e / e.sum(1, keepdims=True),
                               rtol=1e-5)
    np.testing.assert_allclose(got[1], np.clip(x, -0.5, 0.5))
    np.testing.assert_allclose(got[2], np.cumsum(x, axis=1), rtol=1e-5)


def test_concat_split_stack():
    a, b = rand(2, 3, seed=11), rand(2, 3, seed=12)
    xa = fluid.layers.data(name='a', shape=[3], dtype='float32')
    xb = fluid.layers.data(name='b', shape=[3], dtype='float32')
    cc = fluid.layers.concat([xa, xb], axis=1)
    st = fluid.layers.stack([xa, xb], axis=0)
    parts = fluid.layers.split(xa, num_or_sections=3, dim=1)
    got = run_startup_and({'a': a, 'b': b}, [cc, st] + list(parts))
    np.testing.assert_allclose(got[0], np.concatenate([a, b], 1))
    np.testing.assert_allclose(got[1], np.stack([a, b], 0))
    for i in range(3):
        np.testing.assert_allclose(got[2 + i], a[:, i:i + 1])


def test_logical_and_compare():
    a = np.array([[True, False], [True, True]])
    b = np.array([[True, True], [False, True]])
    xa = fluid.layers.data(name='a', shape=[2], dtype='bool')
    xb = fluid.layers.data(name='b', shape=[2], dtype='bool')
    ops = [fluid.layers.logical_and(xa, xb), fluid.layers.logical_or(xa, xb),
           fluid.layers.logical_xor(xa, xb), fluid.layers.logical_not(xa)]
    got = run_startup_and({'a': a, 'b': b}, ops)
    np.testing.assert_array_equal(got[0], a & b)
    np.testing.assert_array_equal(got[1], a | b)
    np.testing.assert_array_equal(got[2], a ^ b)
    np.testing.assert_array_equal(got[3], ~a)


def test_less_than_equal():
    a, b = rand(4, seed=13), rand(4, seed=13)
    b2 = b.copy()
    b2[0] += 1.0
    xa = fluid.layers.data(name='a', shape=[], dtype='float32')
    xb = fluid.layers.data(name='b', shape=[], dtype='float32')
    xa.shape, xb.shape = (4,), (4,)
    lt = fluid.layers.less_than(x=xa, y=xb)
    eq = fluid.layers.equal(x=xa, y=xb)
    got = run_startup_and({'a': a, 'b': b2}, [lt, eq])
    np.testing.assert_array_equal(got[0], a < b2)
    np.testing.assert_array_equal(got[1], a == b2)


def test_cast_one_hot_label_smooth():
    ids = np.array([[1], [3], [0]], dtype='int64')
    inp = fluid.layers.data(name='ids', shape=[1], dtype='int64')
    oh = fluid.layers.one_hot(inp, depth=4)
    ls = fluid.layers.label_smooth(label=oh, epsilon=0.1)
    ct = fluid.layers.cast(inp, dtype='float32')
    got = run_startup_and({'ids': ids}, [oh, ls, ct])
    expect = np.zeros((3, 4), dtype='float32')
    expect[np.arange(3), ids[:, 0]] = 1
    np.testing.assert_allclose(got[0].reshape(3, 4), expect)
    np.testing.assert_allclose(got[1].reshape(3, 4),
                               expect * 0.9 + 0.1 / 4, rtol=1e-5)
    np.testing.assert_allclose(got[2], ids.astype('float32'))


def test_topk_argsort_argmax():
    x = rand(3, 6, seed=14)
    inp = fluid.layers.data(name='x', shape=[6], dtype='float32')
    vals, idx = fluid.layers.topk(inp, k=2)
    am = fluid.layers.argmax(inp, axis=1)
    got = run_startup_and({'x': x}, [vals, idx, am])
    ref_idx = np.argsort(-x, axis=1)[:, :2]
    np.testing.assert_allclose(got[0], np.take_along_axis(x, ref_idx, 1),
                               rtol=1e-6)
    np.testing.assert_array_equal(got[1], ref_idx)
    np.testing.assert_array_equal(got[2], np.argmax(x, 1))


def test_argsort():
    """The argsort lowering itself (a stray statement in its body made
    it crash for four rounds with no test noticing — r5 review)."""
    x = rand(3, 6, seed=15)
    inp = fluid.layers.data(name='x', shape=[6], dtype='float32')
    out, idx = fluid.layers.argsort(inp, axis=1)
    got = run_startup_and({'x': x}, [out, idx])
    np.testing.assert_allclose(got[0], np.sort(x, axis=1), rtol=1e-6)
    np.testing.assert_array_equal(got[1], np.argsort(x, axis=1))


def test_gather_scatter_where():
    x = rand(5, 3, seed=15)
    idx = np.array([0, 2, 4], dtype='int64')
    xi = fluid.layers.data(name='x', shape=[3], dtype='float32')
    xi.shape = (5, 3)
    ii = fluid.layers.data(name='i', shape=[], dtype='int64')
    ii.shape = (3,)
    g = fluid.layers.gather(xi, ii)
    got = run_startup_and({'x': x, 'i': idx}, [g])
    np.testing.assert_allclose(got[0], x[idx])


def test_uniform_gaussian_random_shapes():
    u = fluid.layers.uniform_random(shape=[4, 5], min=-2.0, max=3.0)
    g = fluid.layers.gaussian_random(shape=[4, 5], mean=1.0, std=0.5)
    got = run_startup_and({}, [u, g])
    assert got[0].shape == (4, 5) and got[1].shape == (4, 5)
    assert got[0].min() >= -2.0 and got[0].max() <= 3.0
    assert abs(got[1].mean() - 1.0) < 0.5


def test_fill_ones_zeros_shape_range():
    fc = fluid.layers.fill_constant(shape=[2, 3], dtype='float32', value=7.0)
    on = fluid.layers.ones(shape=[2, 2], dtype='float32')
    ze = fluid.layers.zeros(shape=[3], dtype='int64')
    rg = fluid.layers.range(0, 10, 2, 'int64')
    got = run_startup_and({}, [fc, on, ze, rg])
    np.testing.assert_allclose(got[0], np.full((2, 3), 7.0))
    np.testing.assert_allclose(got[1], np.ones((2, 2)))
    np.testing.assert_allclose(got[2], np.zeros(3))
    np.testing.assert_array_equal(got[3], np.arange(0, 10, 2))


def test_minus():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    y = fluid.layers.data(name='y', shape=[3], dtype='float32')
    out = fluid.layers.tensor.create_tensor(dtype='float32')
    fluid.default_main_program().global_block().append_op(
        type='minus', inputs={'X': [x], 'Y': [y]}, outputs={'Out': [out]})
    xs, ys = rand(2, 3, seed=1), rand(2, 3, seed=2)
    got = run_startup_and({'x': xs, 'y': ys}, [out])[0]
    np.testing.assert_allclose(got, xs - ys, rtol=1e-6)
