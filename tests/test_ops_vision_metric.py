"""Numeric checks vs numpy for the vision/metric/loss op tail
(reference: paddle/fluid/operators/{lrn,roi_pool,crop,pool_with_index,
unpool,precision_recall,positive_negative_pair,modified_huber_loss,
squared_l2_norm,squared_l2_distance,l1_norm,sign}_op)."""

import numpy as np

import paddle_tpu as fluid
from util import run_startup_and, rand


def _np_lrn(x, n=5, k=2.0, alpha=1e-4, beta=0.75):
    # reference loop (lrn_op.cc:30-56): inclusive window start..start+n
    N, C, H, W = x.shape
    start = -(n - 1) // 2
    mid = np.full_like(x, k)
    for i in range(C):
        for off in range(start, start + n + 1):
            ch = i + off
            if 0 <= ch < C:
                mid[:, i] += alpha * x[:, ch] ** 2
    return x * mid ** (-beta), mid


def test_lrn_matches_numpy():
    xs = rand(2, 7, 3, 3, seed=1)
    x = fluid.layers.data(name='x', shape=[7, 3, 3], dtype='float32')
    out = fluid.layers.lrn(x, n=5)
    got = run_startup_and({'x': xs}, [out])[0]
    want, _ = _np_lrn(xs)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def _np_roi_pool(x, rois, ph_n, pw_n, scale):
    # reference kernel (roi_pool_op.h:60-120)
    R = rois.shape[0]
    C, H, W = x.shape[1:]
    out = np.zeros((R, C, ph_n, pw_n), dtype=x.dtype)
    argmax = np.full((R, C, ph_n, pw_n), -1, dtype='int64')
    for r in range(R):
        b, x1, y1, x2, y2 = rois[r]
        x1, y1, x2, y2 = [int(round(v * scale)) for v in (x1, y1, x2, y2)]
        rh, rw = max(y2 - y1 + 1, 1), max(x2 - x1 + 1, 1)
        bh, bw = rh / ph_n, rw / pw_n
        for c in range(C):
            for ph in range(ph_n):
                for pw in range(pw_n):
                    hs = min(max(int(np.floor(ph * bh)) + y1, 0), H)
                    he = min(max(int(np.ceil((ph + 1) * bh)) + y1, 0), H)
                    ws = min(max(int(np.floor(pw * bw)) + x1, 0), W)
                    we = min(max(int(np.ceil((pw + 1) * bw)) + x1, 0), W)
                    if he <= hs or we <= ws:
                        continue
                    patch = x[b, c, hs:he, ws:we]
                    out[r, c, ph, pw] = patch.max()
                    h_loc, w_loc = np.unravel_index(patch.argmax(),
                                                    patch.shape)
                    argmax[r, c, ph, pw] = (hs + h_loc) * W + (ws + w_loc)
    return out, argmax


def test_roi_pool_matches_numpy():
    xs = rand(2, 3, 8, 8, seed=2)
    rois_np = np.array([[0, 1, 1, 5, 6], [1, 0, 0, 7, 7], [0, 3, 3, 3, 3]],
                       dtype='int64')
    x = fluid.layers.data(name='x', shape=[3, 8, 8], dtype='float32')
    rois = fluid.layers.data(name='rois', shape=[5], dtype='int64')
    out = fluid.layers.roi_pool(x, rois, pooled_height=2, pooled_width=2,
                                spatial_scale=1.0)
    got = run_startup_and({'x': xs, 'rois': rois_np}, [out])[0]
    want, _ = _np_roi_pool(xs, rois_np, 2, 2, 1.0)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_crop_static_and_variable_shape():
    xs = rand(3, 5, 6, seed=3)
    x = fluid.layers.data(name='x', shape=[5, 6], dtype='float32')
    out = fluid.layers.crop(x, shape=[2, 3, 4], offsets=[1, 2, 1])
    got = run_startup_and({'x': xs}, [out])[0]
    np.testing.assert_allclose(got, xs[1:3, 2:5, 1:5])

    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[5, 6], dtype='float32')
    y = fluid.layers.data(name='y', shape=[4, 4], dtype='float32')
    out = fluid.layers.crop(x, shape=y, offsets=[0, 1, 1])
    got = run_startup_and(
        {'x': xs, 'y': np.zeros((2, 4, 4), 'float32')}, [out])[0]
    np.testing.assert_allclose(got, xs[0:2, 1:5, 1:5])


def test_max_pool_with_index_and_unpool_roundtrip():
    xs = rand(2, 3, 6, 6, seed=4)
    x = fluid.layers.data(name='x', shape=[3, 6, 6], dtype='float32')
    pooled, mask = fluid.layers.max_pool2d_with_index(
        x, ksize=[2, 2], strides=[2, 2])
    restored = fluid.layers.unpool(pooled, mask, ksize=[2, 2],
                                   strides=[2, 2])
    got_p, got_m, got_r = run_startup_and({'x': xs},
                                          [pooled, mask, restored])
    # pooled matches a plain 2x2/2 max pool
    want_p = xs.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    np.testing.assert_allclose(got_p, want_p, rtol=1e-6)
    # mask holds flattened h*W+w of each max; unpool scatters back there
    want_r = np.zeros_like(xs)
    for b in range(2):
        for c in range(3):
            for i in range(3):
                for j in range(3):
                    idx = got_m[b, c, i, j]
                    want_r[b, c, idx // 6, idx % 6] = got_p[b, c, i, j]
    np.testing.assert_allclose(got_r, want_r, rtol=1e-6)
    # every mask entry actually points at the max value
    flat = xs.reshape(2, 3, 36)
    np.testing.assert_allclose(
        np.take_along_axis(flat, got_m.reshape(2, 3, 9), -1).reshape(got_p.shape),
        got_p)


def _np_precision_recall(ids, labels, cls):
    # reference kernel (precision_recall_op.h:30-98), weights = 1
    states = np.zeros((cls, 4))  # TP FP TN FN
    TP, FP, TN, FN = 0, 1, 2, 3
    for i, l in zip(ids, labels):
        if i == l:
            states[i, TP] += 1
            states[:, TN] += 1
            states[i, TN] -= 1
        else:
            states[l, FN] += 1
            states[i, FP] += 1
            states[:, TN] += 1
            states[i, TN] -= 1
            states[l, TN] -= 1

    def prec(tp, fp):
        return tp / (tp + fp) if tp > 0 or fp > 0 else 1.0

    def f1(p, r):
        return 2 * p * r / (p + r) if p > 0 or r > 0 else 0.0

    mp = np.mean([prec(states[c, TP], states[c, FP]) for c in range(cls)])
    mr = np.mean([prec(states[c, TP], states[c, FN]) for c in range(cls)])
    up = prec(states[:, TP].sum(), states[:, FP].sum())
    ur = prec(states[:, TP].sum(), states[:, FN].sum())
    return np.array([mp, mr, f1(mp, mr), up, ur, f1(up, ur)]), states


def test_precision_recall_matches_numpy():
    ids_np = np.array([0, 1, 2, 1, 0, 2, 2, 1], 'int64')[:, None]
    lab_np = np.array([0, 2, 2, 1, 1, 0, 2, 1], 'int64')[:, None]
    ids = fluid.layers.data(name='ids', shape=[1], dtype='int64')
    lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
    batch, accum, states = fluid.layers.precision_recall(ids, lab, 3)
    got_b, got_s = run_startup_and({'ids': ids_np, 'lab': lab_np},
                                   [batch, states])
    want_m, want_s = _np_precision_recall(ids_np.ravel(), lab_np.ravel(), 3)
    np.testing.assert_allclose(got_b, want_m, rtol=1e-5)
    np.testing.assert_allclose(got_s, want_s, rtol=1e-6)


def test_positive_negative_pair_matches_numpy():
    rng = np.random.RandomState(5)
    n = 12
    score_np = rng.rand(n, 1).astype('float32')
    score_np[3] = score_np[7]  # force an equal-score pair
    label_np = rng.randint(0, 3, (n, 1)).astype('float32')
    qid_np = rng.randint(0, 3, (n, 1)).astype('int64')
    pos = neg = neu = 0.0
    for i in range(n):
        for j in range(i + 1, n):
            if qid_np[i, 0] != qid_np[j, 0] or label_np[i, 0] == label_np[j, 0]:
                continue
            ds = score_np[i, 0] - score_np[j, 0]
            dl = label_np[i, 0] - label_np[j, 0]
            if ds == 0:
                neu += 1
            if ds * dl > 0:
                pos += 1
            else:
                neg += 1
    score = fluid.layers.data(name='s', shape=[1], dtype='float32')
    label = fluid.layers.data(name='l', shape=[1], dtype='float32')
    qid = fluid.layers.data(name='q', shape=[1], dtype='int64')
    p, ng, nu = fluid.layers.positive_negative_pair(score, label, qid)
    got = run_startup_and({'s': score_np, 'l': label_np, 'q': qid_np},
                          [p, ng, nu])
    np.testing.assert_allclose([got[0][0], got[1][0], got[2][0]],
                               [pos, neg, neu], rtol=1e-6)


def test_modified_huber_loss_matches_numpy():
    xs = np.linspace(-3, 3, 13).astype('float32')[:, None]
    ys = (np.arange(13) % 2).astype('float32')[:, None]
    z = xs * (2 * ys - 1)
    want = np.where(z < -1, -4 * z, np.where(z < 1, (1 - z) ** 2, 0.0))
    x = fluid.layers.data(name='x', shape=[1], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    out = fluid.layers.modified_huber_loss(x, y)
    got = run_startup_and({'x': xs, 'y': ys}, [out])[0]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_norms_distance_sign_match_numpy():
    xs = rand(4, 5, seed=6)
    ys = rand(4, 5, seed=7)
    x = fluid.layers.data(name='x', shape=[5], dtype='float32')
    y = fluid.layers.data(name='y', shape=[5], dtype='float32')
    outs = [fluid.layers.l1_norm(x), fluid.layers.squared_l2_norm(x),
            fluid.layers.squared_l2_distance(x, y), fluid.layers.sign(x)]
    g1, g2, g3, g4 = run_startup_and({'x': xs, 'y': ys}, outs)
    np.testing.assert_allclose(g1, [np.abs(xs).sum()], rtol=1e-5)
    np.testing.assert_allclose(g2, [(xs ** 2).sum()], rtol=1e-5)
    np.testing.assert_allclose(g3, ((xs - ys) ** 2).sum(-1, keepdims=True),
                               rtol=1e-5)
    np.testing.assert_allclose(g4, np.sign(xs))


def test_squared_l2_distance_is_differentiable():
    """The loss-shaped ops must run under append_backward (grad flows)."""
    x = fluid.layers.data(name='x', shape=[5], dtype='float32')
    y = fluid.layers.data(name='y', shape=[5], dtype='float32')
    h = fluid.layers.fc(input=x, size=5,
                        param_attr=fluid.ParamAttr(name='sq_w'))
    loss = fluid.layers.mean(fluid.layers.squared_l2_distance(h, y))
    fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs, ys = rand(8, 5, seed=8), rand(8, 5, seed=9)
    l0 = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])[0]
    for _ in range(20):
        l1 = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])[0]
    assert float(np.asarray(l1).reshape(())) < float(np.asarray(l0).reshape(()))


def test_detection_map_hand_computed():
    from paddle_tpu.metrics import DetectionMAP
    m = DetectionMAP(overlap_threshold=0.5, ap_version='integral')
    # one image, one class, 2 gt boxes; 3 detections: hit, dup-hit(fp), miss
    gts = np.array([[1, 0, 0, 10, 10], [1, 20, 20, 30, 30]], 'float64')
    dets = np.array([
        [1, 0.9, 0, 0, 10, 10],    # tp (iou 1.0)
        [1, 0.8, 1, 1, 10, 10],    # fp (same gt already matched)
        [1, 0.7, 20, 20, 30, 30],  # tp
    ], 'float64')
    m.update(dets, gts)
    # precision at hits: 1/1 then 2/3; recall steps 0.5, 0.5->1.0
    # integral AP = 1.0*0.5 + (2/3)*0.5 = 0.8333 -> 83.33
    np.testing.assert_allclose(m.eval(), (0.5 + (2 / 3) * 0.5) * 100,
                               rtol=1e-6)
    # accumulation across images
    m.update(np.zeros((0, 6)), gts)  # 2 more positives, no detections
    # recalls now over npos=4: 0.25, 0.5 -> AP = 1*0.25 + 2/3*0.25
    np.testing.assert_allclose(m.eval(), (0.25 + (2 / 3) * 0.25) * 100,
                               rtol=1e-6)


def test_detection_map_11point():
    from paddle_tpu.metrics import DetectionMAP
    m = DetectionMAP(ap_version='11point')
    gts = np.array([[0, 0, 0, 4, 4]], 'float64')
    dets = np.array([[0, 0.6, 0, 0, 4, 4]], 'float64')
    m.update(dets, gts)
    # single tp: precision 1 at recall 1 -> all 11 points max precision 1
    np.testing.assert_allclose(m.eval(), 100.0)


def _np_levenshtein(a, b):
    import numpy as _np
    d = _np.zeros((len(a) + 1, len(b) + 1))
    d[:, 0] = _np.arange(len(a) + 1)
    d[0, :] = _np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return d[-1, -1]


def test_edit_distance_matches_numpy():
    rng = np.random.RandomState(7)
    seqs = []
    for _ in range(6):
        hl, rl = int(rng.randint(1, 8)), int(rng.randint(1, 9))
        seqs.append((rng.randint(0, 5, hl), rng.randint(0, 5, rl)))
    t1 = max(len(h) for h, _ in seqs)
    t2 = max(len(r) for _, r in seqs)
    hyp = np.zeros((6, t1), 'int64'); ref = np.zeros((6, t2), 'int64')
    hl = np.zeros(6, 'int64'); rl = np.zeros(6, 'int64')
    for i, (h, r) in enumerate(seqs):
        hyp[i, :len(h)] = h; ref[i, :len(r)] = r
        hl[i], rl[i] = len(h), len(r)
    hv = fluid.layers.data(name='h', shape=[t1], dtype='int64')
    rv = fluid.layers.data(name='r', shape=[t2], dtype='int64')
    hlv = fluid.layers.data(name='hl', shape=[], dtype='int64')
    rlv = fluid.layers.data(name='rl', shape=[], dtype='int64')
    dist, n = fluid.layers.edit_distance(hv, rv, normalized=False,
                                         input_length=hlv,
                                         label_length=rlv)
    got_d, got_n = run_startup_and(
        {'h': hyp, 'r': ref, 'hl': hl, 'rl': rl}, [dist, n])
    want = np.array([[_np_levenshtein(list(h), list(r))]
                     for h, r in seqs])
    np.testing.assert_allclose(got_d, want)
    assert got_n[0] == 6
    # normalized variant divides by ref length
    dist_n, _ = fluid.layers.edit_distance(hv, rv, normalized=True,
                                           input_length=hlv,
                                           label_length=rlv)
    got_dn = run_startup_and(
        {'h': hyp, 'r': ref, 'hl': hl, 'rl': rl}, [dist_n])[0]
    np.testing.assert_allclose(got_dn, want / rl[:, None], rtol=1e-6)
