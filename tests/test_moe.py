"""Switch-MoE: routing semantics, e2e training, and expert-parallel
sharding on the 8-virtual-device CPU mesh (mesh axis 'ep')."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.transpiler import ParallelStrategy, transpile
# old-jax SPMD capability gate shared with the other pp suites
from test_parallel import requires_modern_spmd


def _numpy_switch_moe(x2, gate_w, w1, b1, w2, b2, capacity, k=1):
    """Independent numpy re-derivation of the top-k dispatch: choice-
    major capacity filling (all first choices claim slots first),
    gates renormalized for k>=2, dropped assignments contribute zero."""
    s, d = x2.shape
    e = gate_w.shape[-1]
    logits = x2 @ gate_w
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    top_idx = np.argsort(-p, axis=-1)[:, :k]             # [S, k]
    top_gates = np.take_along_axis(p, top_idx, axis=-1)
    if k > 1:
        top_gates = top_gates / top_gates.sum(-1, keepdims=True)
    out = np.zeros_like(x2)
    count = np.zeros(e, np.int64)
    for j in range(k):                       # choice-major
        for si in range(s):                  # sequential capacity filling
            ei = top_idx[si, j]
            if count[ei] >= capacity:
                continue                     # dropped -> zero contribution
            count[ei] += 1
            h = np.maximum(x2[si] @ w1[ei] + b1[ei], 0.0)
            out[si] += top_gates[si, j] * (h @ w2[ei] + b2[ei])
    frac = np.eye(e)[top_idx[:, 0]].mean(0)
    aux = e * float((frac * p.mean(0)).sum())
    return out, aux


@pytest.mark.parametrize('k', [1, 2])
def test_switch_moe_matches_numpy_reference(k):
    import jax.numpy as jnp
    from paddle_tpu.ops.moe_ops import switch_moe_reference
    rng = np.random.RandomState(0)
    s, d, e, h, cap = 16, 8, 4, 12, 3    # capacity binds for some experts
    x2 = rng.randn(s, d).astype('float32')
    gate_w = rng.randn(d, e).astype('float32')
    w1 = rng.randn(e, d, h).astype('float32') * 0.3
    b1 = rng.randn(e, h).astype('float32') * 0.1
    w2 = rng.randn(e, h, d).astype('float32') * 0.3
    b2 = rng.randn(e, d).astype('float32') * 0.1
    got, aux, _ = switch_moe_reference(
        jnp.asarray(x2), jnp.asarray(gate_w), jnp.asarray(w1),
        jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2), cap, k=k)
    want, aux_want = _numpy_switch_moe(x2, gate_w, w1, b1, w2, b2, cap,
                                       k=k)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(float(aux), aux_want, rtol=1e-5)


def _train_moe_lm(mesh=None, steps=5, seed=0, num_experts=4, top_k=1):
    from paddle_tpu.models.moe import switch_transformer_lm
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    vocab, seq = 32, 8
    avg, _ = switch_transformer_lm(vocab, seq, n_layer=2, n_head=2,
                                   d_model=16, d_inner=32,
                                   num_experts=num_experts, top_k=top_k)
    fluid.default_main_program().random_seed = 7
    fluid.optimizer.Adam(learning_rate=3e-3).minimize(avg)
    if mesh is not None:
        transpile(fluid.default_main_program(), mesh,
                  ParallelStrategy(data_parallel=True))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(seed)
    words = rng.randint(1, vocab, (8, seq)).astype('int64')
    labels = np.roll(words, -1, axis=1)
    losses = []
    for _ in range(steps):
        losses.append(float(np.asarray(exe.run(
            feed={'word': words, 'label': labels},
            fetch_list=[avg])[0]).reshape(())))
    return losses


def test_moe_lm_trains():
    losses = _train_moe_lm(steps=8)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize('top_k', [1, 2])
def test_moe_expert_parallel_matches_unsharded(top_k):
    """dp=2 x ep=4 sharded run follows the unsharded trajectory: expert
    weights [E, ...] shard E/ep per device, routing/dispatch numerics
    unchanged (GSPMD exchanges tokens, never reroutes them)."""
    base = _train_moe_lm(mesh=None, top_k=top_k)
    mesh = make_mesh(dp=2, ep=4)
    ep = _train_moe_lm(mesh=mesh, top_k=top_k)
    np.testing.assert_allclose(ep, base, rtol=2e-4, atol=1e-5)


def test_moe_params_marked_and_sharded():
    from paddle_tpu.models.moe import switch_transformer_lm
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    avg, _ = switch_transformer_lm(32, 8, n_layer=1, n_head=2,
                                   d_model=16, d_inner=32, num_experts=4)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg)
    mesh = make_mesh(dp=2, ep=4)
    prog = transpile(fluid.default_main_program(), mesh,
                     ParallelStrategy(data_parallel=True))
    expert_params = [v for v in prog.list_vars()
                     if getattr(v, 'expert_shard', False)]
    assert len(expert_params) == 4, [v.name for v in expert_params]
    for v in expert_params:
        spec = prog.var_shardings[v.name]
        assert tuple(spec)[0] == 'ep', (v.name, spec)
    # the router gate stays replicated
    gates = [v for v in prog.list_vars() if v.name.endswith('gate.w')]
    assert gates and all(
        tuple(prog.var_shardings[g.name]) in ((), (None,) * 2)
        for g in gates)


def test_moe_scan_layers_matches_unrolled():
    """moe_layer_stack (one lax.scan over stacked blocks) follows the
    unrolled MoE LM's trajectory exactly given identical weights."""
    from paddle_tpu.models.moe import switch_transformer_lm
    vocab, seq, L = 32, 8, 2
    kw = dict(n_layer=L, n_head=2, d_model=16, d_inner=32,
              num_experts=4, top_k=2)
    rng = np.random.RandomState(9)
    words = rng.randint(1, vocab, (8, seq)).astype('int64')
    labels = np.roll(words, -1, axis=1)

    def build(scan):
        fluid.reset_default_programs()
        avg, _ = switch_transformer_lm(vocab, seq, scan_layers=scan,
                                       **kw)
        fluid.optimizer.SGD(learning_rate=0.3).minimize(avg)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return avg, exe

    su, ss = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(su):
        avg, exe = build(False)
        init = {n: np.asarray(su.find(n)) for n in su.keys()
                if su.find(n) is not None}
        base = [float(np.asarray(exe.run(
            feed={'word': words, 'label': labels},
            fetch_list=[avg])[0]).reshape(())) for _ in range(3)]
    with fluid.scope_guard(ss):
        avg, exe = build(True)
        # seed the scan scope with the unrolled init, then convert via
        # the production mapping (models.moe.stack_moe_trained_weights);
        # leftover per-layer names in the scope are simply unread
        from paddle_tpu.models.moe import stack_moe_trained_weights
        for name, val in init.items():
            ss.set(name, val)
        stacked = stack_moe_trained_weights(ss, L)
        assert stacked, 'no params were stacked'
        got = [float(np.asarray(exe.run(
            feed={'word': words, 'label': labels},
            fetch_list=[avg])[0]).reshape(())) for _ in range(3)]
    np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-5)


def test_moe_scan_layers_ep_mesh():
    """The stacked MoE LM trains on a dp2 x ep4 mesh, with the expert
    axis (axis 1 of the [L, E, ...] stacks) sharded over 'ep'."""
    from paddle_tpu.models.moe import switch_transformer_lm
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    vocab, seq = 32, 8
    avg, _ = switch_transformer_lm(vocab, seq, n_layer=2, n_head=2,
                                   d_model=16, d_inner=32,
                                   num_experts=4, scan_layers=True)
    fluid.default_main_program().random_seed = 7
    fluid.optimizer.Adam(learning_rate=3e-3).minimize(avg)
    mesh = make_mesh(dp=2, ep=4)
    prog = transpile(fluid.default_main_program(), mesh,
                     ParallelStrategy(data_parallel=True))
    spec = prog.var_shardings['moe_stack_1.w']
    assert tuple(spec)[:2] == (None, 'ep'), spec
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    words = rng.randint(1, vocab, (8, seq)).astype('int64')
    losses = [float(np.asarray(exe.run(
        feed={'word': words, 'label': np.roll(words, -1, axis=1)},
        fetch_list=[avg])[0]).reshape(())) for _ in range(6)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses


def _train_moe_pp(mesh=None, strategy=None, aux_weight=0.0, steps=3,
                  top_k=1):
    """Stacked MoE LM, capacity_factor high enough that nothing drops
    (pipelined routing is per-microbatch, so only the no-drop regime is
    bit-comparable to the full-batch scan)."""
    from paddle_tpu.models.moe import switch_transformer_lm
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    fluid.default_main_program().random_seed = 7
    cost, _ = switch_transformer_lm(
        vocab_size=64, seq_len=8, n_layer=2, n_head=2, d_model=16,
        d_inner=32, num_experts=4, capacity_factor=4.0, top_k=top_k,
        aux_weight=aux_weight, scan_layers=True)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(cost)
    if mesh is not None:
        transpile(fluid.default_main_program(), mesh, strategy)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    words = rng.randint(1, 64, (8, 8)).astype('int64')
    feed = {'word': words, 'label': np.roll(words, -1, axis=1)}
    return [float(np.asarray(exe.run(
        feed=feed, fetch_list=[cost])[0]).reshape(()))
        for _ in range(steps)]


@pytest.mark.parametrize('top_k', [1, 2])
@requires_modern_spmd
def test_moe_pipeline_ep_matches_single_device(top_k):
    """Program-path pipelining of the MoE stack (pp x ep): stage-sharded
    layers, expert weights still 'ep'-split inside the stage (GSPMD
    manages ep under the pp-manual shard_map), aux accumulated over
    valid ticks only. aux_weight=0 + no capacity drops -> trajectory
    equals single device — Switch top-1 AND GShard top-2 routing."""
    base = _train_moe_pp(top_k=top_k)
    pp_ep = _train_moe_pp(
        top_k=top_k,
        mesh=make_mesh(dp=1, pp=2, ep=4),
        strategy=ParallelStrategy(data_parallel=False,
                                  pipeline_parallel=True))
    np.testing.assert_allclose(pp_ep, base, rtol=2e-4, atol=1e-5)
    prog = fluid.default_main_program()
    spec = prog.var_shardings['moe_stack_1.w']
    assert tuple(spec)[:2] == ('pp', 'ep'), spec


@requires_modern_spmd
def test_moe_pipeline_four_axis_matches_single_device():
    """pp x sp x ep (+ the causal ring nested inside the stage): the MoE
    stack's attention dispatches ring attention under pipelining while
    experts stay 'ep'-split — all in one program, trajectory equal to
    single device in the no-drop regime."""
    base = _train_moe_pp()
    four = _train_moe_pp(
        mesh=make_mesh(dp=1, pp=2, sp=2, ep=2),
        strategy=ParallelStrategy(data_parallel=False,
                                  sequence_parallel=True,
                                  pipeline_parallel=True,
                                  sp_vars=['word', 'label']))
    np.testing.assert_allclose(four, base, rtol=2e-4, atol=1e-5)


@requires_modern_spmd
def test_moe_pipeline_with_aux_trains():
    """dp x pp x ep with the load-balancing aux on: the pipelined aux is
    the mean of per-microbatch means (documented semantic difference),
    so assert training health, not bit equality."""
    losses = _train_moe_pp(
        mesh=make_mesh(dp=2, pp=2, ep=2),
        strategy=ParallelStrategy(data_parallel=True,
                                  pipeline_parallel=True),
        aux_weight=1e-2, steps=4)
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
