"""Multihost glue: reader sharding semantics (in-process) and a REAL
2-process jax.distributed CPU cluster (init + pod mesh + cross-process
allgather + disjoint reader shards). Reference roles: go/master/service.go
(input partitioning), paddle/pserver (cluster membership)."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_shard_decorator_disjoint_cover():
    from paddle_tpu.reader.decorator import shard
    base = lambda: iter(range(23))
    shards = [list(shard(base, 4, i)()) for i in range(4)]
    # equal length (drop_uneven), disjoint, in-order
    assert all(len(s) == 5 for s in shards)
    flat = sorted(x for s in shards for x in s)
    assert flat == list(range(20))  # ragged tail 20..22 dropped
    # keep_uneven mode keeps the tail on the low shards
    shards_k = [list(shard(base, 4, i, drop_uneven=False)()) for i in
                range(4)]
    assert sorted(x for s in shards_k for x in s) == list(range(23))


def test_shard_rejects_bad_id():
    from paddle_tpu.reader.decorator import shard
    with pytest.raises(ValueError):
        shard(lambda: iter([]), 4, 4)


_CHILD = textwrap.dedent('''
    import sys
    import jax
    jax.config.update('jax_platforms', 'cpu')
    rank, port = int(sys.argv[1]), sys.argv[2]
    from paddle_tpu.parallel import multihost
    ok = multihost.init_distributed(
        coordinator_address='127.0.0.1:' + port,
        num_processes=2, process_id=rank)
    assert ok and multihost.is_initialized()
    assert multihost.process_count() == 2
    assert multihost.process_index() == rank
    assert len(jax.devices()) == 8, jax.devices()   # 4 local x 2 procs
    mesh = multihost.global_device_mesh(tp=2)        # dp inferred = 4
    assert mesh.shape['dp'] == 4 and mesh.shape['tp'] == 2, mesh.shape

    # disjoint input shards (the go/master role)
    got = list(multihost.shard_reader(lambda: iter(range(10)))())
    print('SHARD %d %s' % (rank, ','.join(map(str, got))), flush=True)

    # the cluster is real: values cross process boundaries
    import numpy as np
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.array([rank + 1]))
    assert sorted(gathered.ravel().tolist()) == [1, 2], gathered

    # train a dp-sharded step over the POD mesh, then checkpoint: the
    # sharded state gathers to host and exactly one process writes
    import os
    import paddle_tpu as fluid
    from paddle_tpu.parallel.transpiler import transpile
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='mh_w'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    transpile(fluid.default_main_program(), mesh)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)  # same data everywhere; dp shards it
    feed = {'x': rng.rand(16, 4).astype('f'),
            'y': rng.rand(16, 1).astype('f')}
    val = exe.run(feed=feed, fetch_list=[loss])[0]
    assert np.isfinite(np.asarray(val)).all()
    ckpt = sys.argv[3]
    fluid.io.save_params(exe, ckpt)
    assert os.path.exists(os.path.join(ckpt, 'params.npz')) or \
        any(f.endswith('.npz') for f in os.listdir(ckpt))
    print('OK %d' % rank, flush=True)
''')


def test_two_process_distributed_cpu(tmp_path):
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = str(s.getsockname()[1])
    script = tmp_path / 'child.py'
    script.write_text(_CHILD)
    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
    env.pop('JAX_PLATFORMS', None)
    ckpt_dir = str(tmp_path / 'pod_ckpt')
    procs = [subprocess.Popen([sys.executable, str(script), str(r), port,
                               ckpt_dir],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for r in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail('2-process distributed test hung')
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, err[-1500:]
        assert 'OK' in out
    shards = {}
    for _, out, _ in outs:
        for line in out.splitlines():
            if line.startswith('SHARD'):
                _, rank, vals = line.split(' ')
                shards[int(rank)] = [int(v) for v in vals.split(',')]
    assert sorted(shards) == [0, 1]
    assert not set(shards[0]) & set(shards[1])  # no duplicate samples
    assert sorted(shards[0] + shards[1]) == list(range(10))
