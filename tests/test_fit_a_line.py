"""End-to-end fit_a_line (reference: book chapter 01 + fluid tests).
The first of the five BASELINE configs: linear regression trains to low
loss through the whole stack (layers -> backward -> SGD -> executor)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_fit_a_line_converges():
    np.random.seed(0)
    true_w = np.random.randn(13, 1).astype('float32')

    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    cost = fluid.layers.square_error_cost(input=y_predict, label=y)
    avg_cost = fluid.layers.mean(cost)

    sgd = fluid.optimizer.SGD(learning_rate=0.05)
    sgd.minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    losses = []
    for step in range(200):
        xs = np.random.randn(32, 13).astype('float32')
        ys = xs @ true_w + 0.5
        out = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[avg_cost])
        losses.append(float(np.asarray(out[0]).reshape(())))
    assert losses[-1] < 0.05, 'loss did not converge: %s' % losses[-10:]
    assert losses[-1] < losses[0]


def test_executor_fetch_and_infer():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    out = fluid.layers.fc(input=h, size=2, act='softmax')

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = np.random.rand(5, 4).astype('float32')
    res = exe.run(feed={'x': xs}, fetch_list=[out])
    assert res[0].shape == (5, 2)
    np.testing.assert_allclose(res[0].sum(axis=1), np.ones(5), rtol=1e-5)
