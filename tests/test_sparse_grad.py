"""Row-sparse embedding gradients (the reference's SelectedRows path,
lookup_table_op.cc:119-127 + the pserver sparse-row protocol): under an
SGD/Adagrad minimize, an is_sparse embedding's gradient is the
O(batch x dim) row stack, scattered into the table by the optimizer op —
a dense [vocab, dim] grad is never materialized."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.core.backward import GRAD_SUFFIX


def _build(is_sparse, opt, vocab=50, dim=8, seed=11):
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    fluid.default_main_program().random_seed = seed
    ids = fluid.layers.data(name='ids', shape=[6], dtype='int64')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    emb = fluid.layers.embedding(input=ids, size=[vocab, dim],
                                 is_sparse=is_sparse,
                                 param_attr=fluid.ParamAttr(name='table'))
    pooled = fluid.layers.reduce_mean(emb, dim=1)
    pred = fluid.layers.fc(input=pooled, size=1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    opt.minimize(cost)
    return cost


def _train(is_sparse, opt_fn, steps=3):
    cost = _build(is_sparse, opt_fn())
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        xids = rng.randint(0, 50, (4, 6)).astype('int64')
        xids[0, :3] = 7   # duplicate ids within and across rows —
        xids[1, :2] = 7   # the merge path must stay exact
        yv = rng.randn(4, 1).astype('f')
        losses.append(float(np.asarray(exe.run(
            feed={'ids': xids, 'y': yv},
            fetch_list=[cost])[0]).reshape(())))
    return losses, np.asarray(fluid.global_scope().find('table'))


@pytest.mark.parametrize('opt_fn', [
    lambda: fluid.optimizer.SGD(learning_rate=0.5),
    lambda: fluid.optimizer.Adagrad(learning_rate=0.5),
], ids=['sgd', 'adagrad'])
def test_sparse_matches_dense(opt_fn):
    l_dense, t_dense = _train(False, opt_fn)
    l_sparse, t_sparse = _train(True, opt_fn)
    np.testing.assert_allclose(l_sparse, l_dense, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(t_sparse, t_dense, rtol=1e-5, atol=1e-6)


def _count_vocab_sized_outputs(jaxpr, vocab, dim):
    """Number of jaxpr equations producing a [vocab, dim] value,
    including nested sub-jaxprs."""
    count = 0
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            shape = getattr(getattr(v, 'aval', None), 'shape', ())
            if tuple(shape) == (vocab, dim):
                count += 1
        for p in eqn.params.values():
            if hasattr(p, 'jaxpr'):
                count += _count_vocab_sized_outputs(p.jaxpr, vocab, dim)
    return count


def test_no_dense_grad_materialized():
    """Structural proof: the sparse step's jaxpr produces at most two
    [vocab, dim] values (the scatter update + the donated pass-through),
    while the dense path materializes more (the zeros+scatter-add grad
    and the subtract). This is the O(batch x dim) guarantee."""
    vocab, dim = 64, 16

    def compile_step(is_sparse):
        cost = _build(is_sparse,
                      fluid.optimizer.SGD(learning_rate=0.5),
                      vocab=vocab, dim=dim)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        feed = {'ids': np.zeros((4, 6), 'int64'),
                'y': np.zeros((4, 1), 'f')}
        fn, scope_vals, feed_vals = exe.compile_step(
            feed=feed, fetch_list=[cost])
        return jax.make_jaxpr(fn)(scope_vals, feed_vals, np.int32(0))

    n_sparse = _count_vocab_sized_outputs(compile_step(True).jaxpr,
                                          vocab, dim)
    n_dense = _count_vocab_sized_outputs(compile_step(False).jaxpr,
                                         vocab, dim)
    assert n_sparse <= 2, 'sparse path materializes %d vocab-sized ' \
        'intermediates' % n_sparse
    assert n_dense > n_sparse


def test_marker_carries_sparse_annotation():
    cost = _build(True, fluid.optimizer.SGD(learning_rate=0.1))
    block = fluid.default_main_program().global_block()
    marker = [op for op in block.ops if op.type == 'backward_marker'][0]
    assert 'table' in marker.attrs['sparse_grads']
    g = block._find_var_recursive('table' + GRAD_SUFFIX)
    assert getattr(g, 'sparse_ids', None) == 'ids'
    assert g.shape == (-1, 8)


def test_unsupported_optimizer_falls_back_dense():
    """Adam decays every moment row every step: row-sparse updates would
    diverge from the dense semantics, so is_sparse tables silently take
    the exact dense path under Adam."""
    cost = _build(True, fluid.optimizer.Adam(learning_rate=0.1))
    block = fluid.default_main_program().global_block()
    marker = [op for op in block.ops if op.type == 'backward_marker'][0]
    assert marker.attrs['sparse_grads'] == {}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out = exe.run(feed={'ids': np.zeros((4, 6), 'int64'),
                        'y': np.zeros((4, 1), 'f')}, fetch_list=[cost])
    assert np.isfinite(np.asarray(out[0])).all()


def test_optimizer_regularization_forces_dense():
    """Optimizer-level regularization= applies to every param against
    the dense grad shape — sparse must switch off (r4 review)."""
    from paddle_tpu.regularizer import L2Decay
    cost = _build(True, fluid.optimizer.SGD(learning_rate=0.1,
                                            regularization=L2Decay(1e-4)))
    block = fluid.default_main_program().global_block()
    marker = [op for op in block.ops if op.type == 'backward_marker'][0]
    assert marker.attrs['sparse_grads'] == {}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out = exe.run(feed={'ids': np.zeros((4, 6), 'int64'),
                        'y': np.zeros((4, 1), 'f')}, fetch_list=[cost])
    assert np.isfinite(np.asarray(out[0])).all()


def test_program_gradient_clip_forces_dense():
    """set_gradient_clip rewrites every grad var (dense shape) — sparse
    must switch off (r4 review)."""
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    ids = fluid.layers.data(name='ids', shape=[6], dtype='int64')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    emb = fluid.layers.embedding(input=ids, size=[50, 8], is_sparse=True,
                                 param_attr=fluid.ParamAttr(name='table'))
    pred = fluid.layers.fc(input=fluid.layers.reduce_mean(emb, dim=1),
                           size=1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    from paddle_tpu.clip import GradientClipByValue, set_gradient_clip
    set_gradient_clip(GradientClipByValue(max=1.0, min=-1.0))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    block = fluid.default_main_program().global_block()
    marker = [op for op in block.ops if op.type == 'backward_marker'][0]
    assert marker.attrs['sparse_grads'] == {}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out = exe.run(feed={'ids': np.zeros((4, 6), 'int64'),
                        'y': np.zeros((4, 1), 'f')}, fetch_list=[cost])
    assert np.isfinite(np.asarray(out[0])).all()


def test_grad_accumulator_forces_dense():
    """Row grads can't accumulate across micro steps (each step's rows
    index different ids) — the accumulator wrapper forces dense."""
    cost = _build(True, fluid.optimizer.GradientAccumulator(
        fluid.optimizer.SGD(learning_rate=0.1), 2))
    block = fluid.default_main_program().global_block()
    marker = [op for op in block.ops if op.type == 'backward_marker'][0]
    assert marker.attrs['sparse_grads'] == {}
    # and the capability flag is restored on the inner optimizer class
    assert fluid.optimizer.SGD(learning_rate=0.1)._supports_sparse_update


def test_wide_deep_ctr_scale_table():
    """The CTR-scale shape the design exists for: a 1e6-row table trains
    under SGD with row-sparse grads; loss decreases and only touched
    rows move."""
    vocab, dim = 1_000_000, 16
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    fluid.default_main_program().random_seed = 3
    ids = fluid.layers.data(name='ids', shape=[4], dtype='int64')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    emb = fluid.layers.embedding(input=ids, size=[vocab, dim],
                                 is_sparse=True,
                                 param_attr=fluid.ParamAttr(name='big'))
    pooled = fluid.layers.reduce_sum(emb, dim=1)
    pred = fluid.layers.fc(input=pooled, size=1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    before = np.asarray(fluid.global_scope().find('big')[:100])
    rng = np.random.RandomState(0)
    xids = rng.randint(100, vocab, (8, 4)).astype('int64')  # rows >= 100
    losses = []
    for _ in range(5):
        losses.append(float(np.asarray(exe.run(
            feed={'ids': xids, 'y': np.ones((8, 1), 'f')},
            fetch_list=[cost])[0]).reshape(())))
    assert losses[-1] < losses[0]
    after = np.asarray(fluid.global_scope().find('big')[:100])
    np.testing.assert_array_equal(before, after)  # untouched rows frozen


def test_wide_deep_model_uses_sparse_grads():
    """The actual wide&deep flagship (models/wide_deep.py): every
    is_sparse table (deep + wide slots) takes the row-sparse path under
    SGD, and the sparse trajectory equals the dense one."""
    from paddle_tpu.models.wide_deep import build as build_wd

    def train(force_dense, steps=3):
        fluid.reset_default_programs()
        fluid.global_scope().clear()
        fluid.default_main_program().random_seed = 5
        _, avg_cost, _, _feeds = build_wd(num_slots=4, vocab_size=200)
        block = fluid.default_main_program().global_block()
        if force_dense:
            for p in fluid.default_main_program().all_parameters():
                if getattr(p, 'sparse_grad', False):
                    p.sparse_grad = False
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
        marker = [op for op in block.ops
                  if op.type == 'backward_marker'][0]
        n_sparse = len(marker.attrs['sparse_grads'])
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(steps):
            feed = {'C%d' % i: rng.randint(0, 200, (8, 1)).astype('int64')
                    for i in range(4)}
            feed['dense'] = rng.rand(8, 13).astype('float32')
            feed['label'] = rng.randint(0, 2, (8, 1)).astype('int64')
            losses.append(float(np.asarray(exe.run(
                feed=feed, fetch_list=[avg_cost])[0]).reshape(())))
        return n_sparse, losses

    n_sparse, sparse_losses = train(force_dense=False)
    assert n_sparse == 8    # 4 deep + 4 wide tables all row-sparse
    n_dense, dense_losses = train(force_dense=True)
    assert n_dense == 0
    np.testing.assert_allclose(sparse_losses, dense_losses,
                               rtol=1e-5, atol=1e-6)


def test_lazy_adam_no_dense_grad_materialized():
    """VERDICT r4 next-#7 structural proof: AdamOptimizer(lazy_mode=
    True) keeps the sparse path — the jaxpr materializes at most the
    scatter outputs' [vocab, dim] values (param + two moments + their
    donated pass-throughs), never the dense grad + dense moment math."""
    vocab, dim = 64, 16

    def compile_step(lazy):
        cost = _build(True, fluid.optimizer.Adam(learning_rate=0.01,
                                                 lazy_mode=lazy),
                      vocab=vocab, dim=dim)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        feed = {'ids': np.zeros((4, 6), 'int64'),
                'y': np.zeros((4, 1), 'f')}
        fn, scope_vals, feed_vals = exe.compile_step(
            feed=feed, fetch_list=[cost])
        return jax.make_jaxpr(fn)(scope_vals, feed_vals, np.int32(0))

    n_lazy = _count_vocab_sized_outputs(compile_step(True).jaxpr,
                                        vocab, dim)
    n_dense = _count_vocab_sized_outputs(compile_step(False).jaxpr,
                                         vocab, dim)
    # param + m1 + m2 scatters (+ pass-throughs) vs the dense path's
    # grad materialization + full-table moment/param arithmetic
    assert n_lazy <= 6, 'lazy adam materializes %d vocab-sized ' \
        'intermediates' % n_lazy
    assert n_dense > n_lazy


def test_lazy_adam_first_step_exact_then_documented_divergence():
    """Step 1 from zero moments: lazy == dense EVERYWHERE (untouched
    rows have zero grad and zero moments, so dense moves them nowhere).
    Step 2 on different ids: dense keeps decaying step-1 rows' moments
    (they move again); lazy freezes them — the documented divergence."""
    vocab, dim = 30, 4

    def run(lazy, id_batches):
        cost = _build(True, fluid.optimizer.Adam(learning_rate=0.05,
                                                 lazy_mode=lazy),
                      vocab=vocab, dim=dim)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        snaps = []
        for ids in id_batches:
            exe.run(feed={'ids': ids, 'y': np.ones((ids.shape[0], 1),
                                                   'f')},
                    fetch_list=[cost])
            snaps.append(np.asarray(fluid.global_scope().find('table'))
                         .copy())
        return snaps

    step1 = np.full((2, 6), 3, 'int64')      # touch row 3 only
    step2 = np.full((2, 6), 9, 'int64')      # touch row 9 only
    lazy1, lazy2 = run(True, [step1, step2])
    dense1, dense2 = run(False, [step1, step2])
    np.testing.assert_allclose(lazy1, dense1, rtol=1e-5, atol=1e-6)
    # divergence on the step-1 row after step 2:
    assert np.abs(lazy2[3] - lazy1[3]).max() < 1e-7   # lazy froze row 3
    assert np.abs(dense2[3] - dense1[3]).max() > 1e-6  # dense moved it
    # both moved row 9, identically from identical step-1 row-9 state
    np.testing.assert_allclose(lazy2[9], dense2[9], rtol=1e-5, atol=1e-6)


def test_lazy_momentum_matches_dense_on_touched_rows():
    vocab, dim = 30, 4

    def run(lazy):
        cost = _build(True, fluid.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9, lazy_mode=lazy),
            vocab=vocab, dim=dim)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        ids = np.full((2, 6), 5, 'int64')
        ids[0, :2] = 11                       # duplicates + second row
        for _ in range(3):                    # same rows every step:
            exe.run(feed={'ids': ids, 'y': np.ones((2, 1), 'f')},
                    fetch_list=[cost])
        return np.asarray(fluid.global_scope().find('table'))

    lazy_t, dense_t = run(True), run(False)
    # rows touched every step see the identical momentum recurrence
    np.testing.assert_allclose(lazy_t[5], dense_t[5], rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(lazy_t[11], dense_t[11], rtol=1e-5,
                               atol=1e-6)
