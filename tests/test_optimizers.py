"""Each optimizer's update vs a hand-computed numpy reference (reference:
fluid/tests/unittests/test_sgd_op.py, test_adam_op.py, ... check_output).

Setup: single parameter p (init p0), loss = reduce_sum(p * x) so
dL/dp = x exactly — every rule below is verified analytically.
"""

import numpy as np
import pytest

import paddle_tpu as fluid

P0 = np.array([1.0, -2.0, 3.0, 0.5], dtype='float32')
X = np.array([0.5, -1.0, 2.0, 0.25], dtype='float32')
LR = 0.1


def _run_steps(make_opt, n_steps=3):
    p = fluid.layers.create_parameter(
        shape=[4], dtype='float32', name='p',
        default_initializer=fluid.initializer.NumpyArrayInitializer(P0))
    x = fluid.layers.data(name='x', shape=[], dtype='float32')
    x.shape = (4,)
    loss = fluid.layers.reduce_sum(fluid.layers.elementwise_mul(x=p, y=x))
    make_opt().minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for _ in range(n_steps):
        exe.run(feed={'x': X}, fetch_list=[loss])
    return np.asarray(fluid.global_scope().find('p'))


def test_sgd():
    got = _run_steps(lambda: fluid.optimizer.SGD(learning_rate=LR))
    expect = P0 - 3 * LR * X
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_momentum():
    got = _run_steps(lambda: fluid.optimizer.Momentum(learning_rate=LR,
                                                      momentum=0.9))
    p, v = P0.copy(), np.zeros_like(P0)
    for _ in range(3):
        v = 0.9 * v + X
        p = p - LR * v
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_momentum_nesterov():
    got = _run_steps(lambda: fluid.optimizer.Momentum(
        learning_rate=LR, momentum=0.9, use_nesterov=True))
    p, v = P0.copy(), np.zeros_like(P0)
    for _ in range(3):
        v = 0.9 * v + X
        p = p - LR * (X + 0.9 * v)
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_adagrad():
    got = _run_steps(lambda: fluid.optimizer.Adagrad(learning_rate=LR,
                                                     epsilon=1e-6))
    p, m = P0.copy(), np.zeros_like(P0)
    for _ in range(3):
        m = m + X * X
        p = p - LR * X / (np.sqrt(m) + 1e-6)
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_adam():
    got = _run_steps(lambda: fluid.optimizer.Adam(
        learning_rate=LR, beta1=0.9, beta2=0.999, epsilon=1e-8))
    p = P0.copy().astype('float64')
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    b1p, b2p = 1.0, 1.0
    for _ in range(3):
        m = 0.9 * m + 0.1 * X
        v = 0.999 * v + 0.001 * X * X
        b1p *= 0.9
        b2p *= 0.999
        lr_t = LR * np.sqrt(1 - b2p) / (1 - b1p)
        p = p - lr_t * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(got, p, rtol=1e-4)


def test_adamax():
    got = _run_steps(lambda: fluid.optimizer.Adamax(
        learning_rate=LR, beta1=0.9, beta2=0.999, epsilon=1e-8))
    p = P0.copy().astype('float64')
    m = np.zeros_like(p)
    u = np.zeros_like(p)
    b1p = 1.0
    for _ in range(3):
        m = 0.9 * m + 0.1 * X
        u = np.maximum(0.999 * u, np.abs(X))
        b1p *= 0.9
        p = p - (LR / (1 - b1p)) * m / (u + 1e-8)
    np.testing.assert_allclose(got, p, rtol=1e-4)


def test_decayed_adagrad():
    got = _run_steps(lambda: fluid.optimizer.DecayedAdagrad(
        learning_rate=LR, decay=0.95, epsilon=1e-6))
    p, m = P0.copy(), np.zeros_like(P0)
    for _ in range(3):
        m = 0.95 * m + 0.05 * X * X
        p = p - LR * X / (np.sqrt(m) + 1e-6)
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_adadelta():
    got = _run_steps(lambda: fluid.optimizer.Adadelta(
        learning_rate=LR, rho=0.95, epsilon=1e-6))
    p = P0.copy().astype('float64')
    g_acc = np.zeros_like(p)
    u_acc = np.zeros_like(p)
    for _ in range(3):
        g_acc = 0.95 * g_acc + 0.05 * X * X
        upd = np.sqrt(u_acc + 1e-6) / np.sqrt(g_acc + 1e-6) * X
        u_acc = 0.95 * u_acc + 0.05 * upd * upd
        p = p - upd
    np.testing.assert_allclose(got, p, rtol=1e-4)


def test_rmsprop():
    got = _run_steps(lambda: fluid.optimizer.RMSProp(
        learning_rate=LR, rho=0.95, epsilon=1e-6, momentum=0.9))
    p = P0.copy().astype('float64')
    ms = np.zeros_like(p)
    mom = np.zeros_like(p)
    for _ in range(3):
        ms = 0.95 * ms + 0.05 * X * X
        mom = 0.9 * mom + LR * X / np.sqrt(ms + 1e-6)
        p = p - mom
    np.testing.assert_allclose(got, p, rtol=1e-4)


def test_ftrl():
    got = _run_steps(lambda: fluid.optimizer.Ftrl(
        learning_rate=LR, l1=0.0, l2=0.0, lr_power=-0.5))
    p = P0.copy().astype('float64')
    sq = np.zeros_like(p)
    lin = np.zeros_like(p)
    for _ in range(3):
        new_sq = sq + X * X
        sigma = (new_sq ** 0.5 - sq ** 0.5) / LR
        lin = lin + X - sigma * p
        sq = new_sq
        p = -lin / (sq ** 0.5 / LR)  # l1=l2=0 closed form
    np.testing.assert_allclose(got, p, rtol=1e-4)


def test_global_step_lr_decay():
    p = fluid.layers.create_parameter(
        shape=[4], dtype='float32', name='p',
        default_initializer=fluid.initializer.NumpyArrayInitializer(P0))
    x = fluid.layers.data(name='x', shape=[], dtype='float32')
    x.shape = (4,)
    loss = fluid.layers.reduce_sum(fluid.layers.elementwise_mul(x=p, y=x))
    lr = fluid.learning_rate_decay.exponential_decay(
        learning_rate=LR, decay_steps=1, decay_rate=0.5, staircase=True)
    fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for _ in range(3):
        exe.run(feed={'x': X}, fetch_list=[loss])
    got = np.asarray(fluid.global_scope().find('p'))
    # steps 0,1,2 -> lr = LR, LR/2, LR/4
    expect = P0 - (LR + LR / 2 + LR / 4) * X
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_regularizer_l2():
    p = fluid.layers.create_parameter(
        shape=[4], dtype='float32', name='p',
        default_initializer=fluid.initializer.NumpyArrayInitializer(P0))
    x = fluid.layers.data(name='x', shape=[], dtype='float32')
    x.shape = (4,)
    loss = fluid.layers.reduce_sum(fluid.layers.elementwise_mul(x=p, y=x))
    fluid.optimizer.SGD(
        learning_rate=LR,
        regularization=fluid.regularizer.L2Decay(0.5)).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={'x': X}, fetch_list=[loss])
    got = np.asarray(fluid.global_scope().find('p'))
    expect = P0 - LR * (X + 0.5 * P0)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_grad_clip_global_norm():
    p = fluid.layers.create_parameter(
        shape=[4], dtype='float32', name='p',
        default_initializer=fluid.initializer.NumpyArrayInitializer(P0))
    x = fluid.layers.data(name='x', shape=[], dtype='float32')
    x.shape = (4,)
    loss = fluid.layers.reduce_sum(fluid.layers.elementwise_mul(x=p, y=x))
    fluid.clip.set_gradient_clip(
        fluid.clip.GradientClipByGlobalNorm(clip_norm=1.0))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={'x': X}, fetch_list=[loss])
    got = np.asarray(fluid.global_scope().find('p'))
    gnorm = np.linalg.norm(X)
    scaled = X * min(1.0, 1.0 / gnorm)
    expect = P0 - LR * scaled
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_proximal_adagrad():
    got = _run_steps(lambda: fluid.optimizer.ProximalAdagrad(
        learning_rate=LR, l1=0.01, l2=0.02))
    p, m = P0.copy(), np.zeros_like(P0)
    for _ in range(3):
        m = m + X * X
        lr_t = LR / np.sqrt(m)
        prox = p - lr_t * X
        p = np.sign(prox) * np.maximum(np.abs(prox) - lr_t * 0.01, 0.0) / \
            (1.0 + lr_t * 0.02)
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_gradient_accumulator_equals_big_batch_sgd():
    """GradientAccumulator(SGD, k): k micro-steps apply ONE update with
    the mean gradient — identical to a single step on the concatenated
    batch (mean losses make the math exact)."""
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 6).astype('float32')
    w_true = rng.randn(6, 1).astype('float32')
    ys = xs @ w_true

    def build(accum):
        fluid.reset_default_programs()
        x = fluid.layers.data(name='x', shape=[6], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1, bias_attr=False,
                               param_attr=fluid.ParamAttr(name='ga_w'))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        if accum:
            fluid.optimizer.GradientAccumulator(opt, 2).minimize(loss)
        else:
            opt.minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return loss, exe

    s1, s2 = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(s1):  # one step on the full batch
        loss, exe = build(accum=False)
        w0 = np.asarray(s1.find('ga_w'))
        exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])
        w_big = np.asarray(s1.find('ga_w'))
    with fluid.scope_guard(s2):  # two micro-steps, accumulated
        loss, exe = build(accum=True)
        s2.set('ga_w', w0)       # same init as the big-batch run
        exe.run(feed={'x': xs[:8], 'y': ys[:8]}, fetch_list=[loss])
        w_mid = np.asarray(s2.find('ga_w'))
        np.testing.assert_allclose(w_mid, w0, rtol=1e-6)  # no update yet
        exe.run(feed={'x': xs[8:], 'y': ys[8:]}, fetch_list=[loss])
        w_acc = np.asarray(s2.find('ga_w'))
    np.testing.assert_allclose(w_acc, w_big, rtol=1e-5, atol=1e-6)


def test_gradient_accumulator_adam_state_gating():
    """With Adam inside, moments and beta-pow accumulators advance only
    on apply steps, and the trajectory over 2k micro-steps equals k
    big-batch Adam steps."""
    rng = np.random.RandomState(1)
    xs = rng.randn(8, 4).astype('float32')
    ys = rng.randn(8, 1).astype('float32')

    def build(accum):
        fluid.reset_default_programs()
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1, bias_attr=False,
                               param_attr=fluid.ParamAttr(name='gaa_w'))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.Adam(learning_rate=0.05)
        if accum:
            fluid.optimizer.GradientAccumulator(opt, 2).minimize(loss)
        else:
            opt.minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return loss, exe

    s1, s2 = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(s1):
        loss, exe = build(accum=False)
        w0 = np.asarray(s1.find('gaa_w'))
        for _ in range(3):  # 3 big-batch Adam steps
            exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])
        w_big = np.asarray(s1.find('gaa_w'))
        beta1_big = [np.asarray(s1.find(n)).reshape(())
                     for n in s1.keys() if 'beta1_pow' in n]
    with fluid.scope_guard(s2):
        loss, exe = build(accum=True)
        s2.set('gaa_w', w0)
        for _ in range(6):  # 6 micro-steps = 3 applied updates
            exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])
        w_acc = np.asarray(s2.find('gaa_w'))
        beta1_acc = [np.asarray(s2.find(n)).reshape(())
                     for n in s2.keys() if 'beta1_pow' in n]
    # identical micro-batches -> mean grad == big-batch grad, so the
    # whole Adam trajectory (incl. beta powers) must match
    np.testing.assert_allclose(beta1_acc, beta1_big, rtol=1e-6)
    np.testing.assert_allclose(w_acc, w_big, rtol=1e-5, atol=1e-6)
