"""tools/repo_lint.py — the repo-wide AST lint runs clean over the
whole tree (tier-1: a regression in any of its three bug classes fails
the build) and actually catches planted violations of each class."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, REPO)

from tools.repo_lint import lint_source, lint_tree  # noqa: E402


def test_repo_tree_is_clean():
    violations = lint_tree(REPO)
    assert not violations, '\n'.join(v.format() for v in violations)


def test_cli_exit_codes_and_json(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'repo_lint.py'),
         '--json'], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert rep['count'] == 0 and rep['violations'] == []

    pkg = tmp_path / 'paddle_tpu' / 'ops'
    pkg.mkdir(parents=True)
    (pkg / 'bad.py').write_text(
        'import os\n'
        "K = os.environ.get('PADDLE_TPU_K')\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'repo_lint.py'),
         '--root', str(tmp_path), '--json'],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    rep = json.loads(r.stdout)
    assert rep['count'] == 1
    assert rep['violations'][0]['code'] == 'import-time-env'


@pytest.mark.parametrize('code,source,env_scoped', [
    ('import-time-env', "import os\nX = os.environ.get('A')\n", True),
    ('import-time-env', "import os\nX = os.getenv('A')\n", True),
    ('import-time-env',
     "import os\ndef f(x=os.environ.get('A')):\n    return x\n", True),
    ('import-time-env',
     "from ..core.flags import get_flag\nB = get_flag('use_bf16')\n",
     True),
    ('import-time-env',
     "import os\nclass C:\n    K = os.environ.get('A')\n", True),
    ('bare-except',
     'def f():\n    try:\n        pass\n    except:\n        pass\n',
     False),
    ('mutable-default', 'def f(x=[]):\n    return x\n', False),
    ('mutable-default', 'def f(*, x={}):\n    return x\n', False),
    ('mutable-default', 'def f(x=dict()):\n    return x\n', False),
])
def test_catches_each_class(code, source, env_scoped):
    out = lint_source('x.py', source, env_scoped=env_scoped)
    assert any(v.code == code for v in out), \
        [v.format() for v in out]


@pytest.mark.parametrize('source,env_scoped', [
    # env read inside a function body: per-call, allowed everywhere
    ("import os\ndef f():\n    return os.environ.get('A')\n", True),
    # module-level env read OUTSIDE the scoped dirs is fine
    ("import os\nX = os.environ.get('A')\n", False),
    ('def f(x=None):\n    x = x or []\n    return x\n', True),
    ('def f():\n    try:\n        pass\n    except Exception:\n'
     '        pass\n', True),
    ('def f(x=(1, 2)):\n    return x\n', True),
])
def test_allows_clean_patterns(source, env_scoped):
    assert lint_source('x.py', source, env_scoped=env_scoped) == []
