"""Pallas kernel parity in interpret mode (CPU): flash attention
forward AND the new FA2 backward kernels vs the XLA reference VJP, and
the fused layer_norm kernel. On-chip parity of the compiled kernels is
additionally checked every bench run (bench.pallas_parity)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PALLAS_INTERPRET', '1')


def _qkv(b=1, h=2, t=256, d=128, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize('d', [64, 128])
@pytest.mark.parametrize('causal', [False, True])
def test_flash_forward_parity(causal, d):
    # d=64 is the base bench model's head dim — the shape class the
    # dispatch gate admits since it widened from %128 to %64
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                       _reference)
    q, k, v = _qkv(d=d)
    scale = q.shape[-1] ** -0.5
    got = flash_attention(q, k, v, causal=causal, block_q=128)
    want = _reference(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize('d', [64, 128])
@pytest.mark.parametrize('causal', [False, True])
def test_flash_backward_parity(causal, d):
    """The FA2 two-kernel backward (dq / dk+dv, driven by the forward's
    saved logsumexp) must match the XLA reference VJP."""
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                       _reference)
    q, k, v = _qkv(seed=1, d=d)
    scale = q.shape[-1] ** -0.5

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=128)
        return jnp.sum(out * jnp.cos(out))   # non-trivial cotangent

    def loss_ref(q, k, v):
        out = _reference(q, k, v, causal, scale)
        return jnp.sum(out * jnp.cos(out))

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, 'qkv'):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg='d%s mismatch' % name)


def test_flash_backward_xla_fallback_matches(monkeypatch):
    from paddle_tpu.ops.pallas import flash_attention as fa
    q, k, v = _qkv(seed=2)

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, causal=True,
                                          block_q=128) ** 2)

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv('PADDLE_TPU_PALLAS_BWD', '0')
    want = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-4, atol=5e-4)


def test_fused_layer_norm_kernel_parity(monkeypatch):
    from paddle_tpu.ops.pallas.layer_norm import _ln_pallas, _ln_reference
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 1024), jnp.float32)
    g = jnp.asarray(rng.rand(1024) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(1024), jnp.float32)
    got = _ln_pallas(x, g, b, 1e-5)
    want = _ln_reference(x, g, b, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
