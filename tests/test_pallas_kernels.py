"""Pallas kernel parity in interpret mode (CPU): flash attention
forward AND the new FA2 backward kernels vs the XLA reference VJP, and
the fused layer_norm kernel. On-chip parity of the compiled kernels is
additionally checked every bench run (bench.pallas_parity)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PALLAS_INTERPRET', '1')


def _qkv(b=1, h=2, t=256, d=128, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize('d', [64, 128])
@pytest.mark.parametrize('causal', [False, True])
def test_flash_forward_parity(causal, d):
    # d=64 is the base bench model's head dim — the shape class the
    # dispatch gate admits since it widened from %128 to %64
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                       _reference)
    q, k, v = _qkv(d=d)
    scale = q.shape[-1] ** -0.5
    got = flash_attention(q, k, v, causal=causal, block_q=128)
    want = _reference(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize('d', [64, 128])
@pytest.mark.parametrize('causal', [False, True])
def test_flash_backward_parity(causal, d):
    """The FA2 two-kernel backward (dq / dk+dv, driven by the forward's
    saved logsumexp) must match the XLA reference VJP."""
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                       _reference)
    q, k, v = _qkv(seed=1, d=d)
    scale = q.shape[-1] ** -0.5

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=128)
        return jnp.sum(out * jnp.cos(out))   # non-trivial cotangent

    def loss_ref(q, k, v):
        out = _reference(q, k, v, causal, scale)
        return jnp.sum(out * jnp.cos(out))

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, 'qkv'):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg='d%s mismatch' % name)


def test_flash_backward_xla_fallback_matches(monkeypatch):
    from paddle_tpu.ops.pallas import flash_attention as fa
    q, k, v = _qkv(seed=2)

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, causal=True,
                                          block_q=128) ** 2)

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv('PADDLE_TPU_PALLAS_BWD', '0')
    want = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-4, atol=5e-4)


def test_fused_layer_norm_kernel_parity(monkeypatch):
    from paddle_tpu.ops.pallas.layer_norm import _ln_pallas, _ln_reference
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 1024), jnp.float32)
    g = jnp.asarray(rng.rand(1024) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(1024), jnp.float32)
    got = _ln_pallas(x, g, b, 1e-5)
    want = _ln_reference(x, g, b, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_flash_masked_parity(causal):
    """r5: per-example kv_len padding masks (VERDICT r4 next-#3/#4) —
    forward AND backward must match the masked XLA reference, including
    rows whose length is far below the padded T (whole key blocks
    skipped by the run predicate)."""
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                       _reference)
    q, k, v = _qkv(b=3, h=2, t=256, d=64, seed=3)
    lens = jnp.asarray([256, 130, 7], jnp.int32)
    scale = q.shape[-1] ** -0.5

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=128,
                              kv_len=lens)
        return jnp.sum(out * jnp.cos(out))

    def loss_ref(q, k, v):
        out = _reference(q, k, v, causal, scale, kv_len=lens)
        return jnp.sum(out * jnp.cos(out))

    got_o = flash_attention(q, k, v, causal=causal, block_q=128,
                            kv_len=lens)
    want_o = _reference(q, k, v, causal, scale, kv_len=lens)
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(want_o),
                               rtol=2e-4, atol=2e-5)
    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, 'qkv'):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg='d%s mismatch' % name)


def test_flash_bf16_dots_stay_close():
    """r5: the kernels no longer upcast tiles to fp32 — bf16 inputs run
    bf16×bf16→fp32 MXU dots. Parity tolerance is bf16-level but the
    softmax recurrence stays fp32, so results track the fp32 reference
    to ~1e-2."""
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                       _reference)
    q, k, v = _qkv(t=256, d=64, seed=4)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got = flash_attention(qb, kb, vb, causal=True,
                          block_q=128).astype(jnp.float32)
    want = _reference(q, k, v, True, q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_fused_attention_masked_dispatches_pallas(monkeypatch):
    """The dispatch gate admits key_length now: a variable-length batch
    at seq>=512 must take the Pallas path (not silently fall back) and
    match the unfused reference."""
    monkeypatch.setenv('PADDLE_TPU_USE_PALLAS', '1')
    import paddle_tpu.ops.attention_ops as ao
    from paddle_tpu.ops.pallas import flash_attention as fa
    calls = []
    orig = fa.flash_attention

    def spy(*a, **kw):
        calls.append(kw.get('kv_len') is not None)
        return orig(*a, **kw)

    monkeypatch.setattr(
        'paddle_tpu.ops.pallas.flash_attention.flash_attention', spy)
    rng = np.random.RandomState(5)
    b, t, hd, nh = 2, 512, 128, 2
    q3, k3, v3 = (jnp.asarray(rng.randn(b, t, hd), jnp.float32)
                  for _ in range(3))
    lens = jnp.asarray([512, 300], jnp.int32)
    got = ao.fused_attention(q3, k3, v3, nh, causal=True, key_length=lens)
    assert calls == [True], 'Pallas path not taken for masked batch'
    monkeypatch.setenv('PADDLE_TPU_USE_PALLAS', '0')
    want = ao.fused_attention(q3, k3, v3, nh, causal=True, key_length=lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_fused_batch_norm_forward_parity():
    """r5 one-pass BN kernel (VERDICT r4 next-#2): y/mean/var must match
    the two-pass jnp form, fp32 stats, for NHWC 4-D and [N,C] inputs."""
    from paddle_tpu.ops.pallas.batch_norm import (fused_batch_norm_train,
                                                  _bn_reference)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 8, 8, 64) * 2 + 1, jnp.float32)
    scale = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(64), jnp.float32)
    y, m, v = fused_batch_norm_train(x, scale, bias, 1e-5, block_r=64)
    wy, wm, wv = _bn_reference(x.reshape(-1, 64), scale, bias, 1e-5)
    np.testing.assert_allclose(np.asarray(m), np.asarray(wm), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(v), np.asarray(wv), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 64),
                               np.asarray(wy), rtol=1e-4, atol=1e-4)


def test_fused_batch_norm_backward_parity():
    """custom_vjp BN gradient vs jax.grad through the reference form."""
    from paddle_tpu.ops.pallas.batch_norm import (fused_batch_norm_train,
                                                  _bn_reference)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(256, 64), jnp.float32)
    scale = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(64), jnp.float32)

    def loss_pallas(x, s, b):
        y, _, _ = fused_batch_norm_train(x, s, b, 1e-5, block_r=64)
        return jnp.sum(y * jnp.cos(y))

    def loss_ref(x, s, b):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=0)
        var = jnp.var(xf, axis=0)
        y = (xf - mean) * jax.lax.rsqrt(var + 1e-5) * s + b
        return jnp.sum(y * jnp.cos(y))

    got = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, scale, bias)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
    for g, w, name in zip(got, want, ['x', 'scale', 'bias']):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg='d%s mismatch' % name)


def test_batch_norm_ir_pallas_matches_default(monkeypatch):
    """The batch_norm lowering under PADDLE_TPU_BN_PALLAS=1 must train
    identically (same loss trajectory) to the default jnp path."""
    import paddle_tpu as fluid

    def train(env_on):
        if env_on:
            monkeypatch.setenv('PADDLE_TPU_BN_PALLAS', '1')
        else:
            monkeypatch.delenv('PADDLE_TPU_BN_PALLAS', raising=False)
        fluid.reset_default_programs()
        fluid.global_scope().clear()
        x = fluid.layers.data(name='x', shape=[8, 8, 8], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.batch_norm(input=x, data_layout='NCHW')
        h = fluid.layers.pool2d(h, pool_size=8, pool_type='avg')
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(5):
            xs = rng.randn(16, 8, 8, 8).astype('f')
            ys = rng.randn(16, 1).astype('f')
            loss, = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[cost])
            losses.append(float(np.asarray(loss).reshape(())))
        return losses

    base = train(False)
    pallas = train(True)
    np.testing.assert_allclose(pallas, base, rtol=1e-4, atol=1e-5)


# ------------------------------------------------- ragged paged attention
def _paged_case(b=3, h=2, nb=16, bs=8, p=4, d=16, seed=5):
    rng = np.random.RandomState(seed)
    k_pages = jnp.asarray(rng.randn(nb, h, bs, d), jnp.float32)
    v_pages = jnp.asarray(rng.randn(nb, h, bs, d), jnp.float32)
    q = jnp.asarray(rng.randn(b, h, d), jnp.float32)
    # distinct physical pages per sequence, deliberately out of order
    perm = rng.permutation(nb)[:b * p].reshape(b, p)
    tables = jnp.asarray(perm, jnp.int32)
    lens = jnp.asarray([1, 9, 25], jnp.int32)[:b]   # ragged, page-crossing
    return q, k_pages, v_pages, tables, lens


def test_paged_attention_kernel_parity(monkeypatch):
    """The scalar-prefetch Pallas kernel (block table drives the page
    index map) must match the XLA gather reference across mixed
    lengths."""
    monkeypatch.setenv('PADDLE_TPU_PAGED_PALLAS', '1')
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    q, kp, vp, tables, lens = _paged_case()
    got = paged_attention(q, kp, vp, tables, lens)
    want = paged_attention_reference(q, kp, vp, tables, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_paged_attention_kernel_ignores_unowned_pages(monkeypatch):
    """Entries past a sequence's length (including the >= NB 'no page'
    sentinel) must not leak into the output."""
    monkeypatch.setenv('PADDLE_TPU_PAGED_PALLAS', '1')
    from paddle_tpu.ops.pallas.paged_attention import paged_attention
    q, kp, vp, tables, lens = _paged_case()
    base = np.asarray(paged_attention(q, kp, vp, tables, lens))
    # scribble over every table entry beyond the owned pages
    t2 = np.asarray(tables).copy()
    nb, bs = kp.shape[0], kp.shape[2]
    for i, n in enumerate(np.asarray(lens)):
        owned = (int(n) + bs - 1) // bs
        t2[i, owned:] = nb + 7
    got = np.asarray(paged_attention(q, kp, vp,
                                     jnp.asarray(t2, jnp.int32), lens))
    np.testing.assert_array_equal(base, got)
