"""Executor semantics: feed/fetch, compile cache, pruning, errors
(reference: fluid/tests/test_executor_and_mul.py + framework/prune.cc
tests)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from util import rand


def test_missing_feed_raises():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    with pytest.raises(ValueError):
        exe.run(feed={}, fetch_list=[out])


def test_uninitialized_scope_raises():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(RuntimeError):
        exe.run(feed={'x': rand(2, 4)}, fetch_list=[out])


def test_compile_cache_reused_across_steps():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={'x': rand(2, 4)}, fetch_list=[out])
    n_compiled = len(exe._cache)
    for _ in range(3):
        exe.run(feed={'x': rand(2, 4)}, fetch_list=[out])
    assert len(exe._cache) == n_compiled  # same shapes: no re-compile
    exe.run(feed={'x': rand(5, 4)}, fetch_list=[out])
    assert len(exe._cache) == n_compiled + 1  # new batch size: new entry


def test_prune_skips_unrelated_branches():
    """Fetching one branch must not require feeds of the other."""
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[3], dtype='float32')
    out_x = fluid.layers.fc(input=x, size=2)
    out_y = fluid.layers.fc(input=y, size=2)  # noqa: F841
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    res = exe.run(feed={'x': rand(2, 4)}, fetch_list=[out_x])
    assert res[0].shape == (2, 2)


def test_fetch_intermediate_and_multiple():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    out = fluid.layers.fc(input=h, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    res = exe.run(feed={'x': rand(2, 4)}, fetch_list=[h, out, 'x'])
    assert res[0].shape == (2, 8)
    assert res[1].shape == (2, 2)
    assert res[2].shape == (2, 4)


def test_return_numpy_false_returns_device_arrays():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    res = exe.run(feed={'x': rand(2, 4)}, fetch_list=[out],
                  return_numpy=False)
    assert hasattr(res[0], 'devices') or hasattr(res[0], 'device')


def test_two_programs_independent():
    prog_a = fluid.Program()
    prog_b = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog_a, startup):
        xa = fluid.layers.data(name='x', shape=[4], dtype='float32')
        out_a = fluid.layers.fc(input=xa, size=2)
    with fluid.program_guard(prog_b, startup):
        xb = fluid.layers.data(name='x', shape=[4], dtype='float32')
        out_b = fluid.layers.fc(input=xb, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ra = exe.run(program=prog_a, feed={'x': rand(2, 4)}, fetch_list=[out_a])
    rb = exe.run(program=prog_b, feed={'x': rand(2, 4)}, fetch_list=[out_b])
    assert ra[0].shape == (2, 2)
    assert rb[0].shape == (2, 3)


def test_program_random_seed_reproducible():
    prog = fluid.default_main_program()
    prog.random_seed = 42
    u = fluid.layers.uniform_random(shape=[8], min=0., max=1.)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    a = exe.run(feed={}, fetch_list=[u])[0]
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())  # same step sequence as exe
    b = exe2.run(feed={}, fetch_list=[u])[0]
    np.testing.assert_allclose(a, b)  # same seed, same step index
    c = exe2.run(feed={}, fetch_list=[u])[0]
    assert not np.allclose(a, c)  # next step: different draw


def test_startup_initializers():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    fluid.layers.fc(input=x, size=3,
                    param_attr=fluid.ParamAttr(
                        name='w_const',
                        initializer=fluid.initializer.Constant(0.5)),
                    bias_attr=fluid.ParamAttr(
                        name='b_const',
                        initializer=fluid.initializer.Constant(-1.0)))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    w = np.asarray(fluid.global_scope().find('w_const'))
    b = np.asarray(fluid.global_scope().find('b_const'))
    np.testing.assert_allclose(w, np.full((4, 3), 0.5))
    np.testing.assert_allclose(b, np.full((3,), -1.0))


def test_scope_guard_isolates_state():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    out = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    my_scope = fluid.Scope()
    with fluid.scope_guard(my_scope):
        exe.run(fluid.default_startup_program())
        res = exe.run(feed={'x': rand(2, 4)}, fetch_list=[out],
                      scope=my_scope)
    assert res[0].shape == (2, 2)
    assert len(list(fluid.global_scope().keys())) == 0


def test_bogus_fetch_target_raises_keyerror():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    out = fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    with pytest.raises(KeyError):
        exe.run(feed={'x': np.zeros((2, 3), 'f')},
                fetch_list=['no_such_var'])


def test_batch_size_change_recompiles_correctly():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    out = fluid.layers.reduce_sum(x, dim=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    a = exe.run(feed={'x': np.ones((2, 3), 'f')}, fetch_list=[out])[0]
    b = exe.run(feed={'x': np.ones((5, 3), 'f')}, fetch_list=[out])[0]
    assert a.shape == (2,) and b.shape == (5,)
    np.testing.assert_allclose(b, 3.0)


def test_wrong_dtype_feed_autocasts():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    out = fluid.layers.scale(x, scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got = exe.run(feed={'x': np.ones((2, 3), dtype='float64')},
                  fetch_list=[out], return_numpy=False)[0]
    import jax.numpy as jnp
    assert got.dtype == jnp.float32


def _mlp_with_dropout():
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=16, act='relu',
                        param_attr=fluid.ParamAttr(name='ms_w1'))
    h = fluid.layers.dropout(h, dropout_prob=0.3)
    pred = fluid.layers.fc(input=h, size=1,
                           param_attr=fluid.ParamAttr(name='ms_w2'))
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(
        cost)
    return cost


def test_run_steps_matches_per_step_trajectory():
    """Executor.run_steps (training loop compiled into the XLA program
    via lax.scan) must reproduce the per-step Executor.run trajectory
    EXACTLY — including dropout masks (the per-op PRNG folds the same
    global step index on both paths) and optimizer accumulator state."""
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(16, 8).astype('f'),
            'y': rng.randn(16, 1).astype('f')}
    s1, s2 = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(s1):
        fluid.reset_default_programs()
        cost = _mlp_with_dropout()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        single = [float(np.asarray(exe.run(
            feed=feed, fetch_list=[cost])[0]).reshape(()))
            for _ in range(5)]
        w1 = np.asarray(s1.find('ms_w1'))
    with fluid.scope_guard(s2):
        fluid.reset_default_programs()
        cost = _mlp_with_dropout()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        multi = np.asarray(exe.run_steps(
            5, feed=feed, fetch_list=[cost])[0]).reshape(-1)
        w2 = np.asarray(s2.find('ms_w1'))
    np.testing.assert_allclose(multi, single, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-6)


def test_run_steps_stacked_feed():
    """stacked_feed=True: each step consumes its own slice of a
    [steps, ...] superbatch — equal to feeding the batches one by one."""
    rng = np.random.RandomState(1)
    xs = rng.randn(4, 16, 8).astype('f')
    ys = rng.randn(4, 16, 1).astype('f')
    s1, s2 = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(s1):
        fluid.reset_default_programs()
        cost = _mlp_with_dropout()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        single = [float(np.asarray(exe.run(
            feed={'x': xs[i], 'y': ys[i]},
            fetch_list=[cost])[0]).reshape(())) for i in range(4)]
    with fluid.scope_guard(s2):
        fluid.reset_default_programs()
        cost = _mlp_with_dropout()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        multi = np.asarray(exe.run_steps(
            4, feed={'x': xs, 'y': ys}, fetch_list=[cost],
            stacked_feed=True)[0]).reshape(-1)
    np.testing.assert_allclose(multi, single, rtol=1e-5, atol=1e-6)


def test_run_steps_stacked_feed_wrong_leading_dim():
    fluid.reset_default_programs()
    cost = _mlp_with_dropout()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    with pytest.raises(ValueError, match='leading'):
        exe.run_steps(3, feed={'x': np.zeros((2, 16, 8), 'f'),
                               'y': np.zeros((2, 16, 1), 'f')},
                      fetch_list=[cost], stacked_feed=True)


def test_rbg_prng_dropout_semantics(monkeypatch):
    """PADDLE_TPU_PRNG=rbg (the TPU default, executor._default_prng —
    +62% tok/s on chip): dropout still zeroes ~p of activations,
    differs across steps, and a same-seed rerun reproduces the
    trajectory exactly on a given backend."""
    monkeypatch.setenv('PADDLE_TPU_PRNG', 'rbg')

    def run_once():
        with fluid.scope_guard(fluid.Scope()):
            fluid.reset_default_programs()
            x = fluid.layers.data(name='x', shape=[512],
                                  dtype='float32')
            out = fluid.layers.dropout(x, dropout_prob=0.4)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            ones = np.ones((16, 512), 'f')
            masks = [exe.run(feed={'x': ones}, fetch_list=[out])[0]
                     for _ in range(3)]
        return masks

    a = run_once()
    b = run_once()
    for m in a:
        frac = float((m == 0).mean())
        assert 0.3 < frac < 0.5, frac          # ~p zeroed
    assert not np.array_equal(a[0], a[1])       # per-step keys differ
    for ma, mb in zip(a, b):                    # same-seed reproducible
        np.testing.assert_array_equal(ma, mb)


def test_run_steps_advances_lr_schedule():
    """The lr-decay step counter is in-graph persistable state; inside a
    run_steps window it must advance per inner step (scan carry), giving
    the same trajectory and final counter as per-step dispatch."""
    from paddle_tpu import learning_rate_decay as lrd

    def build():
        fluid.reset_default_programs()
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        lr = lrd.exponential_decay(learning_rate=0.5, decay_steps=2,
                                   decay_rate=0.5, staircase=True)
        fluid.optimizer.SGD(learning_rate=lr).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return cost, exe

    rng = np.random.RandomState(2)
    feed = {'x': rng.randn(8, 4).astype('f'),
            'y': rng.randn(8, 1).astype('f')}
    s1, s2 = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(s1):
        cost, exe = build()
        single = [float(np.asarray(exe.run(
            feed=feed, fetch_list=[cost])[0]).reshape(()))
            for _ in range(6)]
        counter1 = int(np.asarray(
            s1.find('@LR_DECAY_COUNTER@')).reshape(()))
    with fluid.scope_guard(s2):
        cost, exe = build()
        multi = np.asarray(exe.run_steps(
            6, feed=feed, fetch_list=[cost])[0]).reshape(-1)
        counter2 = int(np.asarray(
            s2.find('@LR_DECAY_COUNTER@')).reshape(()))
    # 6 runs advance the counter identically on both paths (absolute
    # value is the begin-offset convention of the counter op)
    assert counter1 == counter2, (counter1, counter2)
    assert counter1 >= 5
    np.testing.assert_allclose(multi, single, rtol=1e-5, atol=1e-6)
    # the decay actually kicked in (loss scale changes across windows)
    assert not np.allclose(single[0], single[-1])


def test_error_clip_clamps_activation_gradient():
    """var.error_clip = ErrorClipByValue(...) clamps the cotangent
    flowing back through that var (reference fluid/clip.py ErrorClip +
    backward error_clip_callback; here a custom_vjp at lowering)."""
    def build(clip):
        fluid.reset_default_programs()
        x = fluid.layers.data(name='x', shape=[3], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1, bias_attr=False,
                               param_attr=fluid.ParamAttr(name='ec_w'))
        if clip:
            pred.error_clip = fluid.clip.ErrorClipByValue(max=0.01)
        loss = fluid.layers.reduce_sum(fluid.layers.scale(pred,
                                                          scale=100.0))
        fluid.optimizer.SGD(learning_rate=1.0).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return loss, exe

    xs = np.ones((4, 3), 'f')
    s1, s2 = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(s1):
        loss, exe = build(clip=False)
        w0 = np.asarray(s1.find('ec_w'))
        exe.run(feed={'x': xs}, fetch_list=[loss])
        dw_unclipped = (w0 - np.asarray(s1.find('ec_w')))  # lr=1
    with fluid.scope_guard(s2):
        loss, exe = build(clip=True)
        w0 = np.asarray(s2.find('ec_w'))
        exe.run(feed={'x': xs}, fetch_list=[loss])
        dw_clipped = (w0 - np.asarray(s2.find('ec_w')))
    # unclipped cotangent is 100 per element -> dw = sum_b x = 4 * 100
    np.testing.assert_allclose(dw_unclipped, 400.0, rtol=1e-5)
    # clipped to 0.01 per element -> dw = 4 * 0.01
    np.testing.assert_allclose(dw_clipped, 0.04, rtol=1e-5)


def test_error_clip_set_after_run_invalidates_cache():
    """Setting var.error_clip AFTER a compiled run must bump the program
    version so the warm executor cache recompiles with the clamp."""
    fluid.reset_default_programs()
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1, bias_attr=False,
                           param_attr=fluid.ParamAttr(name='ec2_w'))
    loss = fluid.layers.reduce_sum(fluid.layers.scale(pred, scale=100.0))
    fluid.optimizer.SGD(learning_rate=1.0).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = np.ones((4, 3), 'f')
    scope = fluid.global_scope()
    w0 = np.asarray(scope.find('ec2_w'))
    exe.run(feed={'x': xs}, fetch_list=[loss])
    w1 = np.asarray(scope.find('ec2_w'))
    np.testing.assert_allclose(w0 - w1, 400.0, rtol=1e-5)
    pred.error_clip = fluid.clip.ErrorClipByValue(max=0.01)
    exe.run(feed={'x': xs}, fetch_list=[loss])
    w2 = np.asarray(scope.find('ec2_w'))
    # fp32 ulp at |w|~400 dominates the 0.04 delta -> atol
    np.testing.assert_allclose(w1 - w2, 0.04, atol=2e-3)
