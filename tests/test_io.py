"""save/load roundtrip + inference model + checkpoint (reference:
fluid/tests/unittests/test_io_save_load*, book chapters' save/load)."""

import numpy as np

import paddle_tpu as fluid
from util import rand


def _build_and_train(exe, steps=2):
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='w'),
                           bias_attr=fluid.ParamAttr(name='b'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe.run(fluid.default_startup_program())
    xs, ys = rand(8, 4), rand(8, 1)
    for _ in range(steps):
        exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])
    return pred, loss


def test_save_load_params_roundtrip(tmp_path):
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe)
    w0 = np.asarray(fluid.global_scope().find('w'))
    fluid.io.save_params(exe, str(tmp_path))
    # clobber then restore
    fluid.global_scope().set('w', np.zeros_like(w0))
    fluid.io.load_params(exe, str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find('w')), w0)


def test_save_load_persistables_includes_opt_state(tmp_path):
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe)
    moments = [n for n in fluid.global_scope().keys() if 'moment' in n]
    assert moments, 'Adam accumulators should be persistable'
    m0 = np.asarray(fluid.global_scope().find(moments[0]))
    fluid.io.save_persistables(exe, str(tmp_path))
    fluid.global_scope().set(moments[0], np.zeros_like(m0))
    fluid.io.load_persistables(exe, str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find(moments[0])), m0)


def test_save_load_inference_model(tmp_path):
    exe = fluid.Executor(fluid.CPUPlace())
    pred, _ = _build_and_train(exe)
    xs = rand(3, 4)
    infer_prog = fluid.io.get_inference_program([pred])
    expect = exe.run(program=infer_prog, feed={'x': xs},
                     fetch_list=[pred])[0]
    fluid.io.save_inference_model(str(tmp_path), ['x'], [pred], exe)

    fluid.reset_default_programs()
    fluid.global_scope().clear()
    exe2 = fluid.Executor(fluid.CPUPlace())
    prog, feed_names, fetch_targets = fluid.io.load_inference_model(
        str(tmp_path), exe2)
    assert feed_names == ['x']
    got = exe2.run(program=prog, feed={'x': xs}, fetch_list=fetch_targets)
    np.testing.assert_allclose(got[0], expect, rtol=1e-5)


def test_checkpoint_roundtrip(tmp_path):
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=3)
    w0 = np.asarray(fluid.global_scope().find('w'))
    fluid.io.save_checkpoint(exe, str(tmp_path), step=3)
    fluid.global_scope().set('w', np.zeros_like(w0))
    step = fluid.io.load_checkpoint(exe, str(tmp_path))
    assert step == 3
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find('w')), w0)


def test_reader_state_kill_and_resume(tmp_path):
    """Mid-epoch resume (reference go/master/service.go:165-213 task
    recovery): kill after k batches, resume from the checkpoint, and the
    resumed stream replays EXACTLY the untrained remainder — no item
    re-seen, none skipped."""
    from paddle_tpu.reader import CheckpointableReader
    items = list(range(20))

    def base():
        return iter(items)

    reader = CheckpointableReader(base, shuffle_buf=8, seed=42)
    full_epoch = list(CheckpointableReader(base, shuffle_buf=8, seed=42)())
    assert sorted(full_epoch) == items      # a permutation of the data

    gen = reader()
    seen = [next(gen) for _ in range(7)]    # ... then the process dies
    gen.close()
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=1)
    fluid.io.save_checkpoint(exe, str(tmp_path), step=7, reader=reader)

    resumed = CheckpointableReader(base, shuffle_buf=8, seed=42)
    step = fluid.io.load_checkpoint(exe, str(tmp_path), reader=resumed)
    assert step == 7
    rest = list(resumed())
    assert seen + rest == full_epoch        # exactly the remainder
    # the NEXT epoch reshuffles (different seed chain) but stays complete
    nxt = list(resumed())
    assert sorted(nxt) == items
    assert nxt != full_epoch


def test_reader_state_mismatched_seed_rejected(tmp_path):
    from paddle_tpu.reader import CheckpointableReader
    r = CheckpointableReader(lambda: iter(range(5)), shuffle_buf=4, seed=1)
    state = r.state_dict()
    other = CheckpointableReader(lambda: iter(range(5)), shuffle_buf=4,
                                 seed=2)
    import pytest
    with pytest.raises(ValueError, match='seed'):
        other.load_state_dict(state)


def test_async_checkpoint_snapshot_isolation(tmp_path):
    """save_checkpoint(async_save=True): training continues while the
    write happens; the checkpoint holds the values AT save time, not
    the post-save ones; writes are atomic."""
    import paddle_tpu as fluid
    d = str(tmp_path / 'ckpt_async')
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='aw'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {'x': rng.rand(8, 4).astype('f'), 'y': rng.rand(8, 1).astype('f')}
    exe.run(feed=feed, fetch_list=[loss])
    w_at_save = np.asarray(fluid.global_scope().find('aw')).copy()

    handle = fluid.io.save_checkpoint(exe, d, step=1, async_save=True)
    # keep training while the writer thread runs
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[loss])
    w_after = np.asarray(fluid.global_scope().find('aw'))
    assert not np.allclose(w_at_save, w_after)  # training moved on
    handle.result(timeout=30)
    assert handle.done()

    # restore into a fresh scope: must equal the AT-SAVE values
    fluid.global_scope().clear()
    exe.run(fluid.default_startup_program())
    step = fluid.io.load_checkpoint(exe, d)
    assert step == 1
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope().find('aw')), w_at_save)


def test_torn_checkpoint_detected(tmp_path):
    """Crash between the params rename and the checkpoint.json rename
    (the torn-pair window): load_checkpoint must refuse, not silently
    resume new weights against a stale step."""
    import pytest
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=2)
    fluid.io.save_checkpoint(exe, str(tmp_path), step=2)
    # simulate the torn state: params.npz replaced after meta was cut
    w = np.asarray(fluid.global_scope().find('w'))
    fluid.global_scope().set('w', w + 1.0)
    fluid.io.save_persistables(exe, str(tmp_path))
    with pytest.raises(ValueError, match='torn'):
        fluid.io.load_checkpoint(exe, str(tmp_path))


# ------------------------------------------------- elastic topology (v2)
def _build_meshed(dp, opt='adam', steps=2, seed=0):
    """MLP + optimizer transpiled onto a dp mesh, trained `steps` steps.
    Returns (exe, loss, feed)."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import ParallelStrategy, transpile
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='tanh',
                        param_attr=fluid.ParamAttr(name='w1'))
    pred = fluid.layers.fc(input=h, size=1,
                           param_attr=fluid.ParamAttr(name='w2'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.default_main_program().random_seed = 7
    {'adam': lambda: fluid.optimizer.Adam(learning_rate=0.01),
     'sgd': lambda: fluid.optimizer.SGD(learning_rate=0.1),
     }[opt]().minimize(loss)
    if dp:
        transpile(fluid.default_main_program(), make_mesh(dp=dp),
                  ParallelStrategy(data_parallel=True))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs, ys = rand(8, 4, seed=seed), rand(8, 1, seed=seed + 1)
    feed = {'x': xs, 'y': ys}
    for _ in range(steps):
        exe.run(feed=feed, fetch_list=[loss])
    return exe, loss, feed


def test_checkpoint_records_topology_and_specs(tmp_path):
    """Format v2: checkpoint.json records format_version / writing mesh
    / host count, and the manifest records each var's LOGICAL sharding
    spec (axis names, no device positions)."""
    import json
    import os
    exe, _, _ = _build_meshed(dp=4)
    fluid.io.save_checkpoint(exe, str(tmp_path), step=2)
    with open(os.path.join(str(tmp_path), 'checkpoint.json')) as f:
        meta = json.load(f)
    assert meta['format_version'] == fluid.io.CHECKPOINT_FORMAT_VERSION
    assert meta['mesh']['dp'] == 4 and meta['mesh']['tp'] == 1
    assert meta['hosts'] == 1
    with open(os.path.join(str(tmp_path), 'manifest.json')) as f:
        manifest = json.load(f)
    # every persistable entry carries a spec list (params replicate
    # under pure dp -> [])
    assert all('spec' in e for e in manifest.values())
    assert manifest['w1']['spec'] == []


def test_checkpoint_unmeshed_records_trivial_topology(tmp_path):
    """A save from an unsharded program still upgrades to v2 (all-ones
    mesh): it stays restorable on ANY topology."""
    import json
    import os
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe)
    fluid.io.save_checkpoint(exe, str(tmp_path), step=1)
    with open(os.path.join(str(tmp_path), 'checkpoint.json')) as f:
        meta = json.load(f)
    assert meta['format_version'] == 2
    assert all(v == 1 for v in meta['mesh'].values())
    with open(os.path.join(str(tmp_path), 'manifest.json')) as f:
        manifest = json.load(f)
    assert all('spec' not in e for e in manifest.values())


def test_elastic_restore_reshards_onto_new_mesh(tmp_path):
    """Save while training on dp=4, restore into a program transpiled
    for dp=2: every restored array lands device_put under the NEW
    mesh's NamedSharding (2 devices), and continued training matches
    the uninterrupted dp=4 run."""
    import jax
    exe4, loss4, feed = _build_meshed(dp=4, steps=2)
    fluid.io.save_checkpoint(exe4, str(tmp_path), step=2)
    ref = [float(np.asarray(exe4.run(
        feed=feed, fetch_list=[loss4])[0]).reshape(())) for _ in range(2)]

    exe2, loss2, _ = _build_meshed(dp=2, steps=0)
    assert fluid.io.load_checkpoint(
        exe2, str(tmp_path), fluid.default_main_program()) == 2
    w1 = fluid.global_scope().find('w1')
    assert isinstance(w1, jax.Array)
    assert len(w1.sharding.device_set) == 2     # placed on the dp=2 mesh
    moments = [n for n in fluid.global_scope().keys() if 'moment' in n]
    assert moments
    m = fluid.global_scope().find(moments[0])
    assert isinstance(m, jax.Array)             # optimizer state too
    got = [float(np.asarray(exe2.run(
        feed=feed, fetch_list=[loss2])[0]).reshape(())) for _ in range(2)]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-6)


def _strip_to_legacy(dirname):
    """Rewrite checkpoint.json WITHOUT the elastic keys — the on-disk
    shape a pre-elastic writer produced (checkpoint.json's own sha1 is
    not recorded, so the edit keeps the checkpoint complete)."""
    import json
    import os
    path = os.path.join(dirname, 'checkpoint.json')
    with open(path) as f:
        meta = json.load(f)
    for key in ('format_version', 'mesh', 'hosts'):
        meta.pop(key, None)
    if isinstance(meta.get('reader'), dict):
        meta['reader'].pop('hosts', None)
    with open(path, 'w') as f:
        f.write(json.dumps(meta))


def test_legacy_checkpoint_same_topology_still_loads(tmp_path):
    """A pre-elastic checkpoint (no format_version) on an unsharded
    single-host program restores exactly as before."""
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=2)
    w0 = np.asarray(fluid.global_scope().find('w'))
    fluid.io.save_checkpoint(exe, str(tmp_path), step=2)
    _strip_to_legacy(str(tmp_path))
    fluid.global_scope().set('w', np.zeros_like(w0))
    assert fluid.io.load_checkpoint(exe, str(tmp_path)) == 2
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find('w')), w0)


def test_legacy_checkpoint_topology_change_is_actionable_error(tmp_path):
    """A pre-elastic checkpoint restored onto a DIFFERENT topology must
    fail naming the missing sharding specs, not silently assume the
    layouts line up."""
    import pytest
    exe, _, _ = _build_meshed(dp=4, opt='sgd')
    fluid.io.save_checkpoint(exe, str(tmp_path), step=2)
    _strip_to_legacy(str(tmp_path))
    exe2, _, _ = _build_meshed(dp=2, opt='sgd', steps=0)
    with pytest.raises(ValueError, match='sharding specs'):
        fluid.io.load_checkpoint(exe2, str(tmp_path),
                                 fluid.default_main_program())


def test_unverified_legacy_dir_warns_and_flags(tmp_path, monkeypatch):
    """Satellite: a bare save_persistables dir (no checkpoint.json)
    still restores, but loudly — warning + ckpt_unverified_restore
    flight event — so unprotected restores show up in postmortems."""
    import pytest
    from paddle_tpu import observe
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe)
    fluid.io.save_persistables(exe, str(tmp_path))
    monkeypatch.setattr(observe, '_flight_on', True)
    observe.flight_recorder().clear()
    with pytest.warns(UserWarning, match='WITHOUT sha1 verification'):
        assert fluid.io.load_checkpoint(exe, str(tmp_path)) is None
    kinds = [e['kind'] for e in observe.flight_recorder().events()]
    assert 'ckpt_unverified_restore' in kinds
    observe.flight_recorder().clear()


def test_missing_recorded_file_is_torn_not_filenotfound(tmp_path):
    """ADVICE r4 #3: checkpoint.json present but a recorded file missing
    (partial delete/copy) must produce the torn-checkpoint diagnostic,
    not a raw FileNotFoundError from the sha1 pass."""
    import os
    import pytest
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=2)
    fluid.io.save_checkpoint(exe, str(tmp_path), step=2)
    os.remove(os.path.join(str(tmp_path), 'params.npz'))
    with pytest.raises(ValueError, match='torn|incomplete'):
        fluid.io.load_checkpoint(exe, str(tmp_path))
