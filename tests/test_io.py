"""save/load roundtrip + inference model + checkpoint (reference:
fluid/tests/unittests/test_io_save_load*, book chapters' save/load)."""

import numpy as np

import paddle_tpu as fluid
from util import rand


def _build_and_train(exe, steps=2):
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='w'),
                           bias_attr=fluid.ParamAttr(name='b'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe.run(fluid.default_startup_program())
    xs, ys = rand(8, 4), rand(8, 1)
    for _ in range(steps):
        exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])
    return pred, loss


def test_save_load_params_roundtrip(tmp_path):
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe)
    w0 = np.asarray(fluid.global_scope().find('w'))
    fluid.io.save_params(exe, str(tmp_path))
    # clobber then restore
    fluid.global_scope().set('w', np.zeros_like(w0))
    fluid.io.load_params(exe, str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find('w')), w0)


def test_save_load_persistables_includes_opt_state(tmp_path):
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe)
    moments = [n for n in fluid.global_scope().keys() if 'moment' in n]
    assert moments, 'Adam accumulators should be persistable'
    m0 = np.asarray(fluid.global_scope().find(moments[0]))
    fluid.io.save_persistables(exe, str(tmp_path))
    fluid.global_scope().set(moments[0], np.zeros_like(m0))
    fluid.io.load_persistables(exe, str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find(moments[0])), m0)


def test_save_load_inference_model(tmp_path):
    exe = fluid.Executor(fluid.CPUPlace())
    pred, _ = _build_and_train(exe)
    xs = rand(3, 4)
    infer_prog = fluid.io.get_inference_program([pred])
    expect = exe.run(program=infer_prog, feed={'x': xs},
                     fetch_list=[pred])[0]
    fluid.io.save_inference_model(str(tmp_path), ['x'], [pred], exe)

    fluid.reset_default_programs()
    fluid.global_scope().clear()
    exe2 = fluid.Executor(fluid.CPUPlace())
    prog, feed_names, fetch_targets = fluid.io.load_inference_model(
        str(tmp_path), exe2)
    assert feed_names == ['x']
    got = exe2.run(program=prog, feed={'x': xs}, fetch_list=fetch_targets)
    np.testing.assert_allclose(got[0], expect, rtol=1e-5)


def test_checkpoint_roundtrip(tmp_path):
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=3)
    w0 = np.asarray(fluid.global_scope().find('w'))
    fluid.io.save_checkpoint(exe, str(tmp_path), step=3)
    fluid.global_scope().set('w', np.zeros_like(w0))
    step = fluid.io.load_checkpoint(exe, str(tmp_path))
    assert step == 3
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find('w')), w0)


def test_reader_state_kill_and_resume(tmp_path):
    """Mid-epoch resume (reference go/master/service.go:165-213 task
    recovery): kill after k batches, resume from the checkpoint, and the
    resumed stream replays EXACTLY the untrained remainder — no item
    re-seen, none skipped."""
    from paddle_tpu.reader import CheckpointableReader
    items = list(range(20))

    def base():
        return iter(items)

    reader = CheckpointableReader(base, shuffle_buf=8, seed=42)
    full_epoch = list(CheckpointableReader(base, shuffle_buf=8, seed=42)())
    assert sorted(full_epoch) == items      # a permutation of the data

    gen = reader()
    seen = [next(gen) for _ in range(7)]    # ... then the process dies
    gen.close()
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=1)
    fluid.io.save_checkpoint(exe, str(tmp_path), step=7, reader=reader)

    resumed = CheckpointableReader(base, shuffle_buf=8, seed=42)
    step = fluid.io.load_checkpoint(exe, str(tmp_path), reader=resumed)
    assert step == 7
    rest = list(resumed())
    assert seen + rest == full_epoch        # exactly the remainder
    # the NEXT epoch reshuffles (different seed chain) but stays complete
    nxt = list(resumed())
    assert sorted(nxt) == items
    assert nxt != full_epoch


def test_reader_state_mismatched_seed_rejected(tmp_path):
    from paddle_tpu.reader import CheckpointableReader
    r = CheckpointableReader(lambda: iter(range(5)), shuffle_buf=4, seed=1)
    state = r.state_dict()
    other = CheckpointableReader(lambda: iter(range(5)), shuffle_buf=4,
                                 seed=2)
    import pytest
    with pytest.raises(ValueError, match='seed'):
        other.load_state_dict(state)


def test_async_checkpoint_snapshot_isolation(tmp_path):
    """save_checkpoint(async_save=True): training continues while the
    write happens; the checkpoint holds the values AT save time, not
    the post-save ones; writes are atomic."""
    import paddle_tpu as fluid
    d = str(tmp_path / 'ckpt_async')
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='aw'))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {'x': rng.rand(8, 4).astype('f'), 'y': rng.rand(8, 1).astype('f')}
    exe.run(feed=feed, fetch_list=[loss])
    w_at_save = np.asarray(fluid.global_scope().find('aw')).copy()

    handle = fluid.io.save_checkpoint(exe, d, step=1, async_save=True)
    # keep training while the writer thread runs
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[loss])
    w_after = np.asarray(fluid.global_scope().find('aw'))
    assert not np.allclose(w_at_save, w_after)  # training moved on
    handle.result(timeout=30)
    assert handle.done()

    # restore into a fresh scope: must equal the AT-SAVE values
    fluid.global_scope().clear()
    exe.run(fluid.default_startup_program())
    step = fluid.io.load_checkpoint(exe, d)
    assert step == 1
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope().find('aw')), w_at_save)


def test_torn_checkpoint_detected(tmp_path):
    """Crash between the params rename and the checkpoint.json rename
    (the torn-pair window): load_checkpoint must refuse, not silently
    resume new weights against a stale step."""
    import pytest
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=2)
    fluid.io.save_checkpoint(exe, str(tmp_path), step=2)
    # simulate the torn state: params.npz replaced after meta was cut
    w = np.asarray(fluid.global_scope().find('w'))
    fluid.global_scope().set('w', w + 1.0)
    fluid.io.save_persistables(exe, str(tmp_path))
    with pytest.raises(ValueError, match='torn'):
        fluid.io.load_checkpoint(exe, str(tmp_path))


def test_missing_recorded_file_is_torn_not_filenotfound(tmp_path):
    """ADVICE r4 #3: checkpoint.json present but a recorded file missing
    (partial delete/copy) must produce the torn-checkpoint diagnostic,
    not a raw FileNotFoundError from the sha1 pass."""
    import os
    import pytest
    exe = fluid.Executor(fluid.CPUPlace())
    _build_and_train(exe, steps=2)
    fluid.io.save_checkpoint(exe, str(tmp_path), step=2)
    os.remove(os.path.join(str(tmp_path), 'params.npz'))
    with pytest.raises(ValueError, match='torn|incomplete'):
        fluid.io.load_checkpoint(exe, str(tmp_path))
