"""SPMD correctness on the 8-virtual-device CPU mesh (SURVEY.md §4):
data-parallel grads == single-device, tensor-parallel == unsharded,
ring attention == full attention."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.transpiler import ParallelStrategy, transpile
from util import rand


def modern_spmd_supported():
    """Version/capability probe for the pipeline-parallel SPMD tests.

    jax builds that export ``jax.shard_map`` lower the partial-manual
    stage map (manual over 'pp', GSPMD managing dp/tp/sp inside the
    stage) correctly. Older builds with only the experimental
    shard_map hit genuine XLA SPMD limits on those programs:
    ``PartitionId instruction is not supported for SPMD partitioning``
    at dispatch, ``shard_map._SpecError`` on unreduced outputs, and
    scan-carry replication-type mismatches (PR 14 review notes). A
    LIVE compile probe is not an option — one of the failure modes is
    a hard C++ CHECK abort (spmd_partitioner.cc) that would take the
    whole pytest process down — so this is a version gate, with
    ``PADDLE_TPU_FORCE_PP_TESTS=1`` to run the guarded tests anyway
    (e.g. to revalidate a backported fix)."""
    import os
    if os.environ.get('PADDLE_TPU_FORCE_PP_TESTS') == '1':
        return True
    return hasattr(jax, 'shard_map')


requires_modern_spmd = pytest.mark.skipif(
    not modern_spmd_supported(),
    reason='pipeline-parallel programs need a jax build with modern '
           'SPMD support (jax.shard_map); this one hits PartitionId/'
           '_SpecError — set PADDLE_TPU_FORCE_PP_TESTS=1 to run anyway')


def _build_mlp_loss():
    x = fluid.layers.data(name='x', shape=[6], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='int64')
    h = fluid.layers.fc(input=x, size=16, act='relu',
                        param_attr=fluid.ParamAttr(name='w1'),
                        bias_attr=fluid.ParamAttr(name='b1'))
    out = fluid.layers.fc(input=h, size=4, act='softmax',
                          param_attr=fluid.ParamAttr(name='w2'),
                          bias_attr=fluid.ParamAttr(name='b2'))
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=out, label=y))
    return loss


def _train_k_steps(mesh=None, strategy=None, steps=3, seed=0, opt='sgd'):
    """Build + train the MLP; returns (final loss, final w1)."""
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    loss = _build_mlp_loss()
    fluid.default_main_program().random_seed = 7
    {'sgd': lambda: fluid.optimizer.SGD(learning_rate=0.1),
     'momentum': lambda: fluid.optimizer.Momentum(learning_rate=0.1,
                                                  momentum=0.9),
     'adam': lambda: fluid.optimizer.Adam(learning_rate=0.05),
     }[opt]().minimize(loss)
    if mesh is not None:
        transpile(fluid.default_main_program(), mesh, strategy)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(seed)
    xs = rng.rand(16, 6).astype('float32')
    ys = rng.randint(0, 4, (16, 1)).astype('int64')
    final = None
    for _ in range(steps):
        final = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])
    w1 = np.asarray(fluid.global_scope().find('w1'))
    return float(np.asarray(final[0]).reshape(())), w1


def test_data_parallel_matches_single_device():
    loss_1, w1_1 = _train_k_steps(mesh=None)
    mesh = make_mesh(dp=8)
    loss_dp, w1_dp = _train_k_steps(
        mesh=mesh, strategy=ParallelStrategy(data_parallel=True))
    assert abs(loss_1 - loss_dp) < 1e-4
    np.testing.assert_allclose(w1_1, w1_dp, rtol=1e-4, atol=1e-5)


def test_tensor_parallel_matches_unsharded():
    loss_1, w1_1 = _train_k_steps(mesh=None)
    mesh = make_mesh(dp=2, tp=4)
    strategy = ParallelStrategy(
        data_parallel=True, tensor_parallel=True,
        tp_rules=[('w1', 1), ('w2', 0)])  # column then row split
    loss_tp, w1_tp = _train_k_steps(mesh=mesh, strategy=strategy)
    assert abs(loss_1 - loss_tp) < 1e-4
    np.testing.assert_allclose(w1_1, w1_tp, rtol=1e-4, atol=1e-5)


def _train_wide_deep(mesh=None, strategy=None, steps=3, vocab=64):
    """Wide&Deep (is_sparse embeddings) for the row-sharding parity check."""
    from paddle_tpu.models import wide_deep
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    predict, avg_cost, acc, feeds = wide_deep.build(
        num_slots=4, vocab_size=vocab, dense_dim=5, embed_size=8)
    fluid.default_main_program().random_seed = 11
    fluid.optimizer.Adagrad(learning_rate=0.05).minimize(avg_cost)
    if mesh is not None:
        transpile(fluid.default_main_program(), mesh, strategy)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(3)
    feed = {'C%d' % i: rng.randint(0, vocab, (16, 1)).astype('int64')
            for i in range(4)}
    feed['dense'] = rng.rand(16, 5).astype('float32')
    feed['label'] = rng.randint(0, 2, (16, 1)).astype('int64')
    final = None
    for _ in range(steps):
        final = exe.run(feed=feed, fetch_list=[avg_cost])
    emb = np.asarray(fluid.global_scope().find('emb_slot_0'))
    return float(np.asarray(final[0]).reshape(())), emb


def test_row_sharded_embedding_matches_unsharded():
    """is_sparse tables row-sharded over tp must train identically to the
    replicated run (the pserver sparse-row role via GSPMD gather)."""
    loss_1, emb_1 = _train_wide_deep(mesh=None)
    mesh = make_mesh(dp=2, tp=4)
    loss_sh, emb_sh = _train_wide_deep(
        mesh=mesh, strategy=ParallelStrategy(data_parallel=True))
    # the transpiled program must actually row-shard the tables
    sh = fluid.default_main_program().var_shardings
    assert sh['emb_slot_0'] == ('tp',) or sh['emb_slot_0'][0] == 'tp'
    assert sh['wide_slot_0'][0] == 'tp'
    assert abs(loss_1 - loss_sh) < 1e-4
    np.testing.assert_allclose(emb_1, emb_sh, rtol=1e-4, atol=1e-5)


def test_ring_attention_equals_full_attention():
    from paddle_tpu.parallel.ring_attention import ring_attention
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel.mesh import compat_shard_map as shard_map

    b, h, t, d, n_shards = 2, 2, 32, 8, 8
    rng = np.random.RandomState(0)
    q = rng.randn(b, h, t, d).astype('float32')
    k = rng.randn(b, h, t, d).astype('float32')
    v = rng.randn(b, h, t, d).astype('float32')

    # full attention reference
    def full(q, k, v, causal):
        s = np.einsum('bhqd,bhkd->bhqk', q * d ** -0.5, k)
        if causal:
            mask = np.tril(np.ones((t, t), dtype=bool))
            s = np.where(mask[None, None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return np.einsum('bhqk,bhkd->bhqd', p, v)

    mesh = Mesh(np.array(jax.devices()[:n_shards]).reshape(n_shards),
                ('sp',))
    spec = P(None, None, 'sp', None)

    for causal in (False, True):
        ring = shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name='sp',
                                           causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        got = np.asarray(jax.jit(ring)(q, k, v))
        np.testing.assert_allclose(got, full(q, k, v, causal),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg='causal=%s' % causal)


def test_collectives_roundtrip():
    from paddle_tpu.parallel import collective
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.parallel.mesh import compat_shard_map as shard_map

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ('dp',))
    x = np.arange(8, dtype='float32').reshape(4, 2)

    f = shard_map(lambda a: collective.all_reduce(a, 'dp'),
                  mesh=mesh, in_specs=(P('dp', None),),
                  out_specs=P('dp', None))
    got = np.asarray(jax.jit(f)(x))
    expect = np.tile(x.sum(0, keepdims=True), (4, 1))
    np.testing.assert_allclose(got, expect)

    g = shard_map(
        lambda a: collective.all_gather(a, 'dp', axis=0)[None],
        mesh=mesh, in_specs=(P('dp', None),), out_specs=P('dp', None),
        check_vma=False)
    got_g = np.asarray(jax.jit(g)(x))  # each shard returns the full gather
    np.testing.assert_allclose(got_g.reshape(4, 4, 2)[0], x)


def test_transpiler_attaches_shardings():
    loss = _build_mlp_loss()
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    mesh = make_mesh(dp=4, tp=2)
    strategy = ParallelStrategy(data_parallel=True, tensor_parallel=True,
                                tp_rules=[('w1', 1), ('w2', 0)])
    prog = transpile(fluid.default_main_program(), mesh, strategy)
    sh = prog.var_shardings
    assert sh['x'][0] == 'dp'
    assert sh['w1'] == ('tp',) or sh['w1'][1] == 'tp'
    assert sh['w2'][0] == 'tp'
    # Adam moments follow the param sharding
    moment_names = [n for n in sh if 'w1' in n and 'moment' in n]
    assert moment_names
    for n in moment_names:
        assert sh[n] == sh['w1']


def test_auto_tp_matches_unsharded():
    """tensor_parallel with NO tp_rules: Megatron col/row pairing derived
    from the op graph must still train identically to unsharded."""
    loss_1, w1_1 = _train_k_steps(mesh=None)
    mesh = make_mesh(dp=2, tp=4)
    strategy = ParallelStrategy(data_parallel=True, tensor_parallel=True)
    loss_tp, w1_tp = _train_k_steps(mesh=mesh, strategy=strategy)
    sh = fluid.default_main_program().var_shardings
    assert sh['w1'][-1] == 'tp'   # first fc: column split
    assert sh['w2'][0] == 'tp'    # second fc: row split
    assert sh['b1'] == ('tp',)    # column-split layer's bias follows
    assert abs(loss_1 - loss_tp) < 1e-4
    np.testing.assert_allclose(w1_1, w1_tp, rtol=1e-4, atol=1e-5)


def test_accumulator_sharding_survives_colliding_names():
    """Params named so prefix-matching would pair accumulators with the
    WRONG param ('w' vs 'w_x', same shape, different specs): structural
    matching keys on the optimizer op, so each velocity follows its own
    param."""
    x = fluid.layers.data(name='x', shape=[16], dtype='float32')
    h = fluid.layers.fc(input=x, size=16, act='relu',
                        param_attr=fluid.ParamAttr(name='w'),
                        bias_attr=False)
    out = fluid.layers.fc(input=h, size=16,
                          param_attr=fluid.ParamAttr(name='w_x'),
                          bias_attr=False)
    loss = fluid.layers.mean(out)
    fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    # Force same shapes but different specs via explicit rules.
    mesh = make_mesh(dp=2, tp=4)
    strategy = ParallelStrategy(
        data_parallel=True, tensor_parallel=True,
        tp_rules=[('w_x', 0), ('w', 1)])
    prog = transpile(fluid.default_main_program(), mesh, strategy)
    sh = prog.var_shardings
    block = prog.global_block()
    for op in block.ops:
        if op.inputs.get('Param') and op.inputs.get('Velocity'):
            pname = op.inputs['Param'][0]
            vname = op.inputs['Velocity'][0]
            assert sh[vname] == sh[pname], (pname, vname)


@requires_modern_spmd
def test_dryrun_multichip_entrypoint():
    import importlib
    import __graft_entry__
    importlib.reload(__graft_entry__)
    __graft_entry__.dryrun_multichip(8)


def test_pipeline_parallel_matches_sequential():
    from paddle_tpu.parallel.pipeline import pipelined_apply
    from jax.sharding import Mesh

    n_stages, batch, n_micro, d = 4, 8, 4, 16
    rng = np.random.RandomState(0)
    # 4 identical-shape linear+tanh stages
    ws = rng.randn(n_stages, d, d).astype('float32') * 0.3
    bs = rng.randn(n_stages, d).astype('float32') * 0.1
    x = rng.randn(batch, d).astype('float32')

    def stage_fn(params, h):
        w, b = params
        return jnp.tanh(h @ w + b)

    mesh = Mesh(np.array(jax.devices()[:n_stages]).reshape(n_stages),
                ('pp',))
    got = np.asarray(pipelined_apply(stage_fn, (ws, bs), x, n_micro, mesh))

    ref = x
    for s in range(n_stages):
        ref = np.tanh(ref @ ws[s] + bs[s])
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)


def test_pipeline_parallel_differentiable():
    from paddle_tpu.parallel.pipeline import pipelined_apply
    from jax.sharding import Mesh

    n_stages, batch, d = 2, 4, 8
    rng = np.random.RandomState(1)
    ws = rng.randn(n_stages, d, d).astype('float32') * 0.3
    x = rng.randn(batch, d).astype('float32')
    mesh = Mesh(np.array(jax.devices()[:n_stages]).reshape(n_stages),
                ('pp',))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def loss(ws):
        return pipelined_apply(stage_fn, ws, x, 2, mesh).sum()

    g = jax.grad(loss)(jnp.asarray(ws))
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0

    def loss_ref(ws):
        h = x
        for s in range(n_stages):
            h = jnp.tanh(h @ ws[s])
        return h.sum()

    g_ref = jax.grad(loss_ref)(jnp.asarray(ws))
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=2e-4, atol=1e-5)


def _build_scan_transformer(mesh=None, strategy=None, dropout=0.0,
                            n_layer=4, optimizer=None):
    """Tiny scan-stacked transformer (enc+dec), minimized (Adam unless
    an optimizer factory is given), transpiled onto `mesh`, startup run.
    Returns (cost, exe) — the one copy of this build recipe."""
    from paddle_tpu.models import transformer as T
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    fluid.default_main_program().random_seed = 7
    avg_cost, _ = T.transformer_base(
        src_vocab_size=64, trg_vocab_size=64, src_seq_len=8, trg_seq_len=8,
        n_layer=n_layer, d_model=16, d_inner=32, d_key=8, d_value=8,
        n_head=2, dropout_rate=dropout, scan_layers=True)
    opt = optimizer() if optimizer is not None else \
        fluid.optimizer.Adam(learning_rate=1e-3)
    opt.minimize(avg_cost)
    if mesh is not None:
        transpile(fluid.default_main_program(), mesh, strategy)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return avg_cost, exe


def _scan_transformer_feed():
    from paddle_tpu.models import transformer as T
    return T.make_fake_batch(8, 8, 8, 64, 64, seed=3)


def _train_scan_transformer(mesh=None, strategy=None, steps=3,
                            dropout=0.0, n_layer=4, optimizer=None):
    """Build + train `steps` steps on a constant batch; returns the
    per-step losses."""
    avg_cost, exe = _build_scan_transformer(mesh, strategy, dropout,
                                            n_layer, optimizer)
    feed = _scan_transformer_feed()
    return [float(np.asarray(exe.run(
        feed=feed, fetch_list=[avg_cost])[0]).reshape(()))
        for _ in range(steps)]


@requires_modern_spmd
def test_program_pipeline_matches_single_device():
    """Program-level pipeline parallelism: a fluid-built transformer
    (scan_layers=True) transpiled with pipeline_parallel trains through
    Executor.run on a pp mesh with the SAME loss trajectory as single
    device — encoder and decoder stacks both pipelined, cross-attention
    memory microbatched alongside."""
    base = _train_scan_transformer()
    pp4 = _train_scan_transformer(
        mesh=make_mesh(dp=1, pp=4),
        strategy=ParallelStrategy(data_parallel=False,
                                  pipeline_parallel=True))
    np.testing.assert_allclose(pp4, base, rtol=2e-4, atol=1e-5)
    # composes with dp: 2 stages x 2-way data parallel
    pp_dp = _train_scan_transformer(
        mesh=make_mesh(dp=2, pp=2),
        strategy=ParallelStrategy(data_parallel=True,
                                  pipeline_parallel=True,
                                  pipeline_microbatches=4))
    np.testing.assert_allclose(pp_dp, base, rtol=2e-4, atol=1e-5)


@requires_modern_spmd
def test_program_pipeline_composes_with_tp():
    """pp x tp (the scaling-book large-model config): the shard_map is
    manual over pp only, so GSPMD manages the intra-stage Megatron
    column/row splits — loss trajectory must equal single device."""
    base = _train_scan_transformer()
    pp_tp = _train_scan_transformer(
        mesh=make_mesh(dp=1, pp=2, tp=4),
        strategy=ParallelStrategy(data_parallel=False,
                                  tensor_parallel=True,
                                  pipeline_parallel=True))
    np.testing.assert_allclose(pp_tp, base, rtol=2e-4, atol=1e-5)
    # the stacked qkv weights really are tp-split inside their stage
    prog = fluid.default_main_program()
    spec = prog.var_shardings['enc_stack_slf_q.w']
    assert tuple(spec) == ('pp', None, 'tp'), spec
    spec_o = prog.var_shardings['enc_stack_slf_o.w']
    assert tuple(spec_o) == ('pp', 'tp', None), spec_o


@requires_modern_spmd
def test_program_pipeline_composes_with_sp():
    """pp x sp: the ring-attention dispatch nests as an sp-manual inner
    shard_map inheriting the pp-manual context mesh — long-context
    sequence parallelism inside a pipeline stage, loss-equal to single
    device."""
    base = _train_scan_transformer(n_layer=2)
    pp_sp = _train_scan_transformer(
        mesh=make_mesh(dp=1, pp=2, sp=4), n_layer=2,
        strategy=ParallelStrategy(
            data_parallel=False, sequence_parallel=True,
            pipeline_parallel=True,
            sp_vars=['src_word', 'trg_word', 'lbl_word', 'lbl_weight']))
    np.testing.assert_allclose(pp_sp, base, rtol=2e-4, atol=1e-5)


def test_program_pipeline_composes_with_run_steps():
    """The pipelined step under Executor.run_steps (shard_map inside the
    multi-step lax.scan): trajectory equals per-step dispatch."""
    mesh = make_mesh(dp=1, pp=2)
    strat = ParallelStrategy(data_parallel=False, pipeline_parallel=True)

    per_step = _train_scan_transformer(mesh=mesh, strategy=strat, steps=4,
                                       n_layer=2)

    avg_cost, exe = _build_scan_transformer(mesh=mesh, strategy=strat,
                                            n_layer=2)
    out = exe.run_steps(4, feed=_scan_transformer_feed(),
                        fetch_list=[avg_cost])
    windowed = np.asarray(out[0]).reshape(-1).tolist()
    np.testing.assert_allclose(windowed, per_step, rtol=2e-4, atol=1e-5)


@requires_modern_spmd
def test_program_pipeline_composes_with_grad_accum():
    """GradientAccumulator's gated updates under a pipelined program:
    the accumulator state and phase counter live OUTSIDE the pp
    shard_map, so accumulation semantics are unchanged — trajectory
    equals single device (loss repeats in pairs: k=2)."""
    def accum():
        return fluid.optimizer.GradientAccumulator(
            fluid.optimizer.SGD(learning_rate=0.1), 2)

    base = _train_scan_transformer(steps=4, n_layer=2, optimizer=accum)
    assert base[0] == base[1] and base[2] == base[3]  # k=2 gating
    pp = _train_scan_transformer(
        steps=4, n_layer=2, optimizer=accum,
        mesh=make_mesh(dp=2, pp=2),
        strategy=ParallelStrategy(data_parallel=True,
                                  pipeline_parallel=True))
    np.testing.assert_allclose(pp, base, rtol=2e-4, atol=1e-5)


def test_program_pipeline_with_dropout_runs():
    """Dropout keys fold the microbatch index (masks per microbatch);
    trajectory differs from single-device by design — train steps must
    run and decrease."""
    losses = _train_scan_transformer(
        mesh=make_mesh(dp=1, pp=2), dropout=0.1, steps=4,
        strategy=ParallelStrategy(data_parallel=False,
                                  pipeline_parallel=True))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_program_pipeline_requires_pp_axis():
    """pipeline_parallel on a mesh without a pp axis must raise, not
    silently train unpipelined (r4 review)."""
    from paddle_tpu.models import transformer as T
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    T.transformer_base(
        src_vocab_size=64, trg_vocab_size=64, src_seq_len=8, trg_seq_len=8,
        n_layer=2, d_model=16, d_inner=32, d_key=8, d_value=8, n_head=2,
        dropout_rate=0.0, scan_layers=True)
    with pytest.raises(ValueError, match='pp axis'):
        transpile(fluid.default_main_program(), make_mesh(dp=8),
                  ParallelStrategy(pipeline_parallel=True))


def test_program_pipeline_requires_scan_stack():
    from paddle_tpu.models import transformer as T
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    avg_cost, _ = T.transformer_base(
        src_vocab_size=64, trg_vocab_size=64, src_seq_len=8, trg_seq_len=8,
        n_layer=2, d_model=16, d_inner=32, d_key=8, d_value=8, n_head=2,
        dropout_rate=0.0, scan_layers=False)   # unrolled: no stack op
    with pytest.raises(ValueError, match='scan_layers'):
        transpile(fluid.default_main_program(), make_mesh(dp=1, pp=2),
                  ParallelStrategy(pipeline_parallel=True))


def test_program_pipeline_indivisible_layers_raises():
    from paddle_tpu.models import transformer as T
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    T.transformer_base(
        src_vocab_size=64, trg_vocab_size=64, src_seq_len=8, trg_seq_len=8,
        n_layer=3, d_model=16, d_inner=32, d_key=8, d_value=8, n_head=2,
        dropout_rate=0.0, scan_layers=True)
    with pytest.raises(ValueError, match='divisible'):
        transpile(fluid.default_main_program(), make_mesh(dp=1, pp=2),
                  ParallelStrategy(pipeline_parallel=True))


@requires_modern_spmd
def test_checkpoint_portable_across_meshes(tmp_path):
    """A checkpoint saved while training on a dp x pp x tp mesh (params
    sharded: stage-split stacks, Megatron tp splits) loads on a single
    device and continues with the same trajectory — save gathers global
    values, so checkpoints are mesh-layout-free."""
    feed = _scan_transformer_feed()
    cost, exe = _build_scan_transformer(
        make_mesh(dp=2, pp=2, tp=2),
        ParallelStrategy(data_parallel=True, tensor_parallel=True,
                         pipeline_parallel=True), n_layer=2)
    for _ in range(2):
        exe.run(feed=feed, fetch_list=[cost])
    fluid.io.save_checkpoint(exe, str(tmp_path), step=2)
    l_mesh = [float(np.asarray(exe.run(
        feed=feed, fetch_list=[cost])[0]).reshape(())) for _ in range(2)]

    cost, exe = _build_scan_transformer(n_layer=2)
    assert fluid.io.load_checkpoint(exe, str(tmp_path)) == 2
    l_single = [float(np.asarray(exe.run(
        feed=feed, fetch_list=[cost])[0]).reshape(())) for _ in range(2)]
    np.testing.assert_allclose(l_single, l_mesh, rtol=2e-4, atol=1e-5)


def test_retranspile_clears_pipeline_schedule():
    """Re-transpiling with pipeline_parallel=False must clear the old
    schedule — the stack lowerings key off program.pipeline (r4
    review)."""
    from paddle_tpu.models import transformer as T
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    T.transformer_base(
        src_vocab_size=64, trg_vocab_size=64, src_seq_len=8, trg_seq_len=8,
        n_layer=2, d_model=16, d_inner=32, d_key=8, d_value=8, n_head=2,
        dropout_rate=0.0, scan_layers=True)
    prog = fluid.default_main_program()
    transpile(prog, make_mesh(dp=1, pp=2),
              ParallelStrategy(pipeline_parallel=True,
                               pipeline_microbatches=4))
    assert prog.pipeline == {'n_micro': 4}
    transpile(prog, make_mesh(dp=1, pp=2),
              ParallelStrategy(pipeline_parallel=False))
    assert prog.pipeline is None


def test_transpile_invalidates_compiled_cache():
    """A step compiled before transpile must not be reused after: the
    old trace has no sharding constraints (and no pipeline schedule).
    transpile bumps the program version, which keys the executor
    cache."""
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    loss = _build_mlp_loss()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = fluid.default_main_program()
    v0 = prog._version
    transpile(prog, make_mesh(dp=8), ParallelStrategy(data_parallel=True))
    assert prog._version > v0


def test_multihost_autodetect_failure_warns(monkeypatch):
    """Auto-detect path (PADDLE_TRAINERS set, no coordinator): a failed
    jax.distributed init falls back single-host but WARNS — a pod with
    broken metadata must not silently train on duplicate data."""
    import warnings
    from paddle_tpu.parallel import multihost
    monkeypatch.setattr(multihost, '_initialized', False)
    monkeypatch.setenv('PADDLE_TRAINERS', '4')
    monkeypatch.delenv('PADDLE_COORDINATOR', raising=False)
    monkeypatch.delenv('PADDLE_TRAINER_ID', raising=False)

    class _FakeDist(object):
        @staticmethod
        def initialize(*a, **k):
            raise RuntimeError('no pod metadata')

    import jax
    monkeypatch.setattr(jax, 'distributed', _FakeDist)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        ok = multihost.init_distributed()
    assert ok is False
    assert any('SINGLE-HOST' in str(x.message) for x in w), \
        [str(x.message) for x in w]


def test_multihost_single_host_fallbacks():
    from paddle_tpu.parallel import multihost
    assert multihost.init_distributed() in (True, False)
    assert multihost.process_count() >= 1
    assert multihost.host_local_batch(16) == 16 // multihost.process_count()
    mesh = multihost.global_device_mesh(tp=2)
    assert mesh.shape['tp'] == 2


def _train_attention_model(mesh=None, strategy=None, steps=3, causal=True):
    """Tiny attention model via the fused_attention IR op; returns
    (loss, q-projection weights) after training."""
    from paddle_tpu.models.transformer import _multi_head_attention
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    x = fluid.layers.data(name='x', shape=[16, 32], dtype='float32')
    y = fluid.layers.data(name='y', shape=[16, 32], dtype='float32')
    attn = _multi_head_attention(x, x, x, d_key=8, d_value=8, n_head=4,
                                 d_model=32, dropout_rate=0.0,
                                 causal=causal, name='spattn')
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(attn, y))
    fluid.default_main_program().random_seed = 5
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    if mesh is not None:
        transpile(fluid.default_main_program(), mesh, strategy)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    xs = rng.randn(4, 16, 32).astype('float32')
    ys = rng.randn(4, 16, 32).astype('float32')
    final = None
    for _ in range(steps):
        final = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])
    w = np.asarray(fluid.global_scope().find('spattn_q.w'))
    return float(np.asarray(final[0]).reshape(())), w


def test_ring_attention_dispatch_matches_unsharded():
    """fused_attention on a mesh with sp>1 dispatches to ring attention
    (K/V rotating over ICI) and must train identically to the unsharded
    run — fwd AND bwd (long-context sequence parallelism end-to-end)."""
    for causal in (False, True):
        loss_1, w_1 = _train_attention_model(mesh=None, causal=causal)
        mesh = make_mesh(dp=2, sp=4)
        strategy = ParallelStrategy(data_parallel=True,
                                    sequence_parallel=True,
                                    sp_vars=['x', 'y'])
        loss_sp, w_sp = _train_attention_model(mesh=mesh,
                                               strategy=strategy,
                                               causal=causal)
        assert abs(loss_1 - loss_sp) < 1e-4, (causal, loss_1, loss_sp)
        np.testing.assert_allclose(w_1, w_sp, rtol=1e-4, atol=1e-5,
                                   err_msg='causal=%s' % causal)


def test_parallel_executor_facade():
    """ParallelExecutor API over GSPMD: global batch shards over dp,
    training matches the single-device run (reference ParallelExecutor
    role, parallel/executor.py)."""
    from paddle_tpu.parallel import ParallelExecutor
    loss_1, w1_1 = _train_k_steps(mesh=None)

    fluid.reset_default_programs()
    fluid.global_scope().clear()
    loss = _build_mlp_loss()
    fluid.default_main_program().random_seed = 7
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    pe = ParallelExecutor(use_cuda=False, loss_name=loss.name,
                          place=fluid.CPUPlace())
    assert pe.device_count == 8
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xs = rng.rand(16, 6).astype('float32')
    ys = rng.randint(0, 4, (16, 1)).astype('int64')
    final = None
    for _ in range(3):
        final = pe.run([loss], feed={'x': xs, 'y': ys})
    assert abs(float(np.asarray(final[0]).reshape(())) - loss_1) < 1e-4
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find('w1')), w1_1,
        rtol=1e-4, atol=1e-5)
    pe.bcast_params()  # no-op, API compatibility


@pytest.mark.parametrize('mesh_kw,strat_kw', [
    (dict(dp=8), dict(data_parallel=True)),
    (dict(dp=4, tp=2), dict(data_parallel=True, tensor_parallel=True)),
], ids=['dp8', 'dp4xtp2'])
def test_run_steps_on_mesh_with_stacked_feed(mesh_kw, strat_kw):
    """run_steps(stacked_feed=True) on a mesh: the var's PartitionSpec
    describes the per-step batch, so the superbatch shards with a
    replicated leading [steps] axis (steps need not divide the mesh) and
    the trajectory equals per-step dispatch — including under dp x tp
    (auto-derived Megatron splits inside the scanned step)."""
    steps = 3  # deliberately not divisible by either mesh's dp axis
    rng = np.random.RandomState(3)
    xs = rng.rand(steps, 16, 6).astype('float32')
    ys = rng.randint(0, 4, (steps, 16, 1)).astype('int64')

    def build():
        fluid.reset_default_programs()
        fluid.global_scope().clear()
        loss = _build_mlp_loss()
        fluid.default_main_program().random_seed = 7
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        transpile(fluid.default_main_program(), make_mesh(**mesh_kw),
                  ParallelStrategy(**strat_kw))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return loss, exe

    loss, exe = build()
    single = [float(np.asarray(exe.run(
        feed={'x': xs[i], 'y': ys[i]}, fetch_list=[loss])[0]).reshape(()))
        for i in range(steps)]
    loss, exe = build()
    multi = np.asarray(exe.run_steps(
        steps, feed={'x': xs, 'y': ys}, fetch_list=[loss],
        stacked_feed=True)[0]).reshape(-1)
    np.testing.assert_allclose(multi, single, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('opt', ['momentum', 'adam'])
def test_zero1_optimizer_state_sharding_matches_single_device(opt):
    """ParallelStrategy(shard_optimizer_states=True): accumulators get a
    'dp' axis in their spec (ZeRO-1) and training is numerically the
    single-device trajectory — GSPMD derives the reduce-scatter /
    all-gather."""
    loss_1, w1_1 = _train_k_steps(mesh=None, opt=opt)
    mesh = make_mesh(dp=8)
    loss_z, w1_z = _train_k_steps(
        mesh=mesh,
        strategy=ParallelStrategy(data_parallel=True,
                                  shard_optimizer_states=True),
        opt=opt)
    assert abs(loss_1 - loss_z) < 1e-4, (loss_1, loss_z)
    np.testing.assert_allclose(w1_1, w1_z, rtol=1e-4, atol=1e-5)
    # the state specs actually carry 'dp' (not just replicated copies)
    shardings = fluid.default_main_program().var_shardings
    acc_specs = {n: s for n, s in shardings.items() if n.endswith('_acc')}
    assert acc_specs, 'no accumulator specs recorded'
    dp_sharded = [n for n, s in acc_specs.items() if 'dp' in tuple(s)]
    assert dp_sharded, acc_specs


def test_zero1_composes_with_tensor_parallel():
    """shard_optimizer_states under dp x tp: tp axes stay, 'dp' lands on
    a free divisible axis (or not at all — divisibility-gated)."""
    loss_1, w1_1 = _train_k_steps(mesh=None, opt='adam')
    mesh = make_mesh(dp=2, tp=4)
    loss_z, w1_z = _train_k_steps(
        mesh=mesh,
        strategy=ParallelStrategy(
            data_parallel=True, tensor_parallel=True,
            tp_rules=[('w1', 1), ('w2', 0)],
            shard_optimizer_states=True),
        opt='adam')
    assert abs(loss_1 - loss_z) < 1e-4, (loss_1, loss_z)
    np.testing.assert_allclose(w1_1, w1_z, rtol=1e-4, atol=1e-5)
    shardings = fluid.default_main_program().var_shardings
    # w1's moments keep their tp split on axis 1, gain 'dp' on axis 0
    # (6 % 2 == 0 under dp=2)
    m1 = tuple(shardings['w1_moment1_acc'])
    assert 'tp' in m1 and 'dp' in m1, m1


def test_fsdp_parameter_sharding_matches_single_device():
    """ParallelStrategy(fully_shard_parameters=True): weights, grads,
    and state all take 'dp' (ZeRO-3/FSDP); XLA all-gathers weights at
    use and reduce-scatters grads. Numerics == single device."""
    loss_1, w1_1 = _train_k_steps(mesh=None, opt='adam')
    mesh = make_mesh(dp=8)
    loss_f, w1_f = _train_k_steps(
        mesh=mesh,
        strategy=ParallelStrategy(data_parallel=True,
                                  fully_shard_parameters=True,
                                  shard_optimizer_states=True),
        opt='adam')
    assert abs(loss_1 - loss_f) < 1e-4, (loss_1, loss_f)
    np.testing.assert_allclose(w1_1, w1_f, rtol=1e-4, atol=1e-5)
    shardings = fluid.default_main_program().var_shardings
    # w1 [6,16]: axis0 % 8 != 0, axis1 16 % 8 == 0 -> P(None, 'dp')
    assert 'dp' in tuple(shardings['w1']), shardings['w1']
    assert tuple(shardings['w1_moment1_acc']) == tuple(shardings['w1'])


def test_ring_attention_masked_equals_reference():
    """r5: per-example kv_len padding masks under sequence parallelism —
    ring attention over an 8-shard sp axis must equal the unsharded
    masked reference, including rows whose length falls inside an
    earlier shard's block."""
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.parallel.mesh import compat_shard_map as shard_map
    from paddle_tpu.parallel.ring_attention import ring_attention
    from paddle_tpu.ops.attention_ops import reference_attention

    b, h, t, d, n_shards = 3, 2, 32, 8, 8
    rng = np.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
               for _ in range(3))
    lens = jnp.asarray([32, 13, 3], jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:n_shards]).reshape(n_shards),
                ('sp',))
    spec = P(None, None, 'sp', None)
    for causal in (False, True):
        ring = shard_map(
            lambda q_, k_, v_, l_: ring_attention(
                q_, k_, v_, axis_name='sp', causal=causal, kv_len=l_),
            mesh=mesh, in_specs=(spec, spec, spec, P(None)),
            out_specs=spec)
        got = np.asarray(jax.jit(ring)(q, k, v, lens))
        want = np.asarray(reference_attention(q, k, v, causal=causal,
                                              key_length=lens))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5,
                                   err_msg='causal=%s' % causal)


def test_masked_attention_dispatch_rides_ring():
    """The fused_attention sp gate no longer requires key_length=None:
    a masked batch on an sp mesh takes the ring path and matches the
    unfused reference."""
    import paddle_tpu.ops.attention_ops as ao
    mesh = make_mesh(sp=8)
    rng = np.random.RandomState(6)
    b, t, hd, nh = 2, 32, 16, 2
    q3, k3, v3 = (jnp.asarray(rng.randn(b, t, hd), jnp.float32)
                  for _ in range(3))
    lens = jnp.asarray([32, 9], jnp.int32)
    qlen = jnp.asarray([30, 32], jnp.int32)
    with mesh:
        got = jax.jit(lambda a, b_, c, l, ql: ao.fused_attention(
            a, b_, c, nh, causal=False, key_length=l, query_length=ql,
            mesh=mesh))(q3, k3, v3, lens, qlen)
    want = ao.fused_attention(q3, k3, v3, nh, causal=False,
                              key_length=lens, query_length=qlen)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
