"""Detection suite: matching, target assign, hard mining, NMS, SSD loss
(reference: fluid/tests/unittests/test_bipartite_match_op.py,
test_target_assign_op.py, test_mine_hard_examples_op.py,
test_multiclass_nms_op.py, test_ssd_loss...)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from util import run_startup_and, rand


def test_iou_similarity_batched():
    gt = np.array([[[0., 0., 2., 2.], [1., 1., 3., 3.]]], dtype='float32')
    pr = np.array([[0., 0., 2., 2.], [2., 2., 4., 4.]], dtype='float32')
    x = fluid.layers.data(name='x', shape=[2, 4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[4], dtype='float32')
    y.shape = (2, 4)
    out = fluid.layers.iou_similarity(x, y)
    got = run_startup_and({'x': gt, 'y': pr}, [out])[0]
    np.testing.assert_allclose(got[0, 0], [1.0, 0.0], atol=1e-6)
    # gt[1] vs pr[0]: inter 1, union 7; vs pr[1]: inter 1, union 7
    np.testing.assert_allclose(got[0, 1], [1 / 7, 1 / 7], rtol=1e-5)


def test_bipartite_match_greedy():
    # 2 gt x 3 priors; global best 0.9 at (0,1); then (1,2)=0.6
    dist_np = np.array([[[0.5, 0.9, 0.3],
                         [0.4, 0.8, 0.6]]], dtype='float32')
    d = fluid.layers.data(name='d', shape=[2, 3], dtype='float32')
    idx, dval = fluid.layers.bipartite_match(d)
    gi, gd = run_startup_and({'d': dist_np}, [idx, dval])
    np.testing.assert_array_equal(gi[0], [-1, 0, 1])
    np.testing.assert_allclose(gd[0], [0.0, 0.9, 0.6], rtol=1e-6)


def test_bipartite_match_per_prediction():
    dist_np = np.array([[[0.5, 0.9, 0.3],
                         [0.4, 0.8, 0.6]]], dtype='float32')
    d = fluid.layers.data(name='d', shape=[2, 3], dtype='float32')
    idx, _ = fluid.layers.bipartite_match(d, match_type='per_prediction',
                                          dist_threshold=0.45)
    gi, = run_startup_and({'d': dist_np}, [idx])
    # prior 0 unmatched by bipartite; best gt is 0 (0.5 > 0.45)
    np.testing.assert_array_equal(gi[0], [0, 0, 1])


def test_target_assign():
    x_np = np.arange(12, dtype='float32').reshape(1, 3, 4)  # 3 gts
    match_np = np.array([[1, -1, 0, 2]], dtype='int64')
    x = fluid.layers.data(name='x', shape=[3, 4], dtype='float32')
    m = fluid.layers.data(name='m', shape=[4], dtype='int64')
    out, w = fluid.layers.target_assign(x, m, mismatch_value=0)
    go, gw = run_startup_and({'x': x_np, 'm': match_np}, [out, w])
    np.testing.assert_allclose(go[0, 0], x_np[0, 1])
    np.testing.assert_allclose(go[0, 1], np.zeros(4))
    np.testing.assert_allclose(go[0, 2], x_np[0, 0])
    np.testing.assert_allclose(gw[0].ravel(), [1, 0, 1, 1])


def test_mine_hard_examples():
    # 1 positive, 4 negatives, ratio 2 -> keep top-2 loss negatives
    loss_np = np.array([[0.1, 0.9, 0.3, 0.7, 0.5]], dtype='float32')
    match_np = np.array([[0, -1, -1, -1, -1]], dtype='int64')
    lo = fluid.layers.data(name='l', shape=[5], dtype='float32')
    m = fluid.layers.data(name='m', shape=[5], dtype='int64')
    upd, neg = fluid.layers.mine_hard_examples(lo, m, neg_pos_ratio=2.0)
    gu, gn = run_startup_and({'l': loss_np, 'm': match_np}, [upd, neg])
    np.testing.assert_array_equal(gu[0], [0, -1, -2, -1, -2])
    np.testing.assert_array_equal(gn[0], [0, 1, 0, 1, 0])


def test_multiclass_nms_suppresses_overlaps():
    boxes_np = np.array([[[0, 0, 2, 2], [0, 0, 2.1, 2.1], [5, 5, 7, 7]]],
                        dtype='float32')
    # class 0 = background; class 1 scores
    scores_np = np.zeros((1, 2, 3), dtype='float32')
    scores_np[0, 1] = [0.9, 0.8, 0.7]
    b = fluid.layers.data(name='b', shape=[3, 4], dtype='float32')
    s = fluid.layers.data(name='s', shape=[2, 3], dtype='float32')
    out = fluid.layers.multiclass_nms(b, s, score_threshold=0.1,
                                      nms_threshold=0.5, keep_top_k=4)
    got = run_startup_and({'b': boxes_np, 's': scores_np}, [out])[0]
    kept = got[0][got[0][:, 0] >= 0]
    assert len(kept) == 2  # the near-duplicate box suppressed
    np.testing.assert_allclose(kept[0, 1], 0.9, rtol=1e-6)
    np.testing.assert_allclose(kept[0, 2:], [0, 0, 2, 2], atol=1e-6)
    np.testing.assert_allclose(kept[1, 2:], [5, 5, 7, 7], atol=1e-6)


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.RandomState(0)
    priors_np = np.array([[0.1, 0.1, 0.5, 0.5], [0.4, 0.4, 0.9, 0.8]],
                         dtype='float32')
    var_np = np.tile(np.array([0.1, 0.1, 0.2, 0.2], dtype='float32'),
                     (2, 1))
    gt_np = np.array([[0.15, 0.12, 0.48, 0.55]], dtype='float32')
    p = fluid.layers.data(name='p', shape=[4], dtype='float32')
    p.shape = (2, 4)
    v = fluid.layers.data(name='v', shape=[4], dtype='float32')
    v.shape = (2, 4)
    t = fluid.layers.data(name='t', shape=[4], dtype='float32')
    t.shape = (1, 4)
    enc = fluid.layers.box_coder(p, v, t, code_type='encode_center_size')
    dec = fluid.layers.box_coder(p, v, enc[0] if False else enc,
                                 code_type='decode_center_size')
    ge, = run_startup_and({'p': priors_np, 'v': var_np, 't': gt_np}, [enc])
    assert ge.shape == (1, 2, 4)


def test_ssd_loss_end_to_end_trains():
    B, N, M, C = 2, 8, 2, 3
    rng = np.random.RandomState(1)
    priors_np = rng.uniform(0.0, 0.8, (N, 4)).astype('float32')
    priors_np[:, 2:] = priors_np[:, :2] + 0.2
    gt_box_np = priors_np[:M].copy()[None].repeat(B, 0)
    gt_lbl_np = np.array([[1, 2], [2, 1]], dtype='int64')

    loc = fluid.layers.data(name='loc', shape=[N, 4], dtype='float32')
    conf = fluid.layers.data(name='conf', shape=[N, C], dtype='float32')
    gtb = fluid.layers.data(name='gtb', shape=[M, 4], dtype='float32')
    gtl = fluid.layers.data(name='gtl', shape=[M], dtype='int64')
    pb = fluid.layers.data(name='pb', shape=[4], dtype='float32')
    pb.shape = (N, 4)

    # trainable head on top of fed features so the loss can decrease
    feat = fluid.layers.data(name='feat', shape=[N, 8], dtype='float32')
    loc_pred = fluid.layers.fc(input=feat, size=4, num_flatten_dims=2)
    conf_pred = fluid.layers.fc(input=feat, size=C, num_flatten_dims=2)
    loss = fluid.layers.ssd_loss(loc_pred, conf_pred, gtb, gtl, pb)
    avg = fluid.layers.mean(loss)
    fluid.optimizer.Adam(learning_rate=0.05).minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {'feat': rng.randn(B, N, 8).astype('float32'),
            'gtb': gt_box_np, 'gtl': gt_lbl_np, 'pb': priors_np}
    losses = [float(np.asarray(exe.run(feed=feed, fetch_list=[avg])[0]).reshape(()))
              for _ in range(12)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_multi_box_head_shapes():
    img = fluid.layers.data(name='img', shape=[3, 32, 32], dtype='float32')
    f1 = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                             stride=4, padding=1)
    f2 = fluid.layers.conv2d(input=f1, num_filters=8, filter_size=3,
                             stride=2, padding=1)
    locs, confs, boxes, vars_ = fluid.layers.multi_box_head(
        inputs=[f1, f2], image=img, num_classes=4,
        min_sizes=[8.0, 16.0], aspect_ratios=[[1.0], [1.0, 2.0]],
        flip=True)
    got = run_startup_and({'img': rand(2, 3, 32, 32)},
                          [locs, confs, boxes, vars_])
    n_priors = got[2].shape[0]
    assert got[0].shape == (2, n_priors, 4)
    assert got[1].shape == (2, n_priors, 4)
    assert got[3].shape == (n_priors, 4)


def test_ssd_model_trains_and_infers():
    from paddle_tpu.models.ssd import ssd_train
    avg, feeds = ssd_train(num_classes=4, image_shape=(3, 64, 64),
                           max_gt=3)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    gt = rng.uniform(0.1, 0.5, (2, 3, 4)).astype('float32')
    gt[:, :, 2:] = gt[:, :, :2] + 0.3
    feed = {'image': rng.rand(2, 3, 64, 64).astype('float32'),
            'gt_box': gt,
            'gt_label': rng.randint(1, 4, (2, 3)).astype('int64')}
    losses = [float(np.asarray(exe.run(feed=feed, fetch_list=[avg])[0]).reshape(()))
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_ssd_detection_output_shape():
    from paddle_tpu.models.ssd import ssd_infer
    out, feeds = ssd_infer(num_classes=4, image_shape=(3, 64, 64),
                           keep_top_k=8)
    rng = np.random.RandomState(3)
    got = run_startup_and({'image': rng.rand(2, 3, 64, 64)
                           .astype('float32')}, [out])[0]
    assert got.shape == (2, 8, 6)
