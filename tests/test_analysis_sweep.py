"""Lint-sweep: the strict verifier over every Program our builders
produce — the example-shaped graphs (fit_a_line, CTR sparse, the v1
quickstart config, the pipelined dp x pp x tp transformer), the model
zoo's heavy hitters, and the serving/decode program builders. Zero
error-severity diagnostics required: this locks the IR builders (and
the passes' false-positive rate) against regressions — every later
IR-mutating PR runs under it."""

import paddle_tpu as fluid
from paddle_tpu import analysis


def _strict(label, program, fetches=None, feeds=None):
    diags = analysis.verify(program, feed_names=feeds,
                            fetch_names=fetches or [], mode='strict',
                            label=label)
    return diags


def _strict_defaults(label, fetches):
    _strict(label + '_startup', fluid.default_startup_program())
    return _strict(label, fluid.default_main_program(), fetches)


def test_fit_a_line_programs_verify():
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
    _strict_defaults('fit_a_line', [cost])
    # and the pruned inference program save_inference_model serializes
    infer = fluid.io.get_inference_program([pred])
    _strict('fit_a_line_infer', infer, [pred])


def test_ctr_sparse_program_verifies():
    ids = fluid.layers.data(name='ids', shape=[8], dtype='int64')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    emb = fluid.layers.embedding(input=ids, size=[100000, 16],
                                 is_sparse=True)
    pooled = fluid.layers.reduce_sum(emb, dim=1)
    pred = fluid.layers.fc(input=pooled, size=1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    _strict_defaults('ctr_sparse', [cost])


def test_v1_quickstart_config_verifies():
    from paddle_tpu.trainer_config_helpers import (
        AdamOptimizer, L2Regularization, SoftmaxActivation,
        classification_cost, data_layer, embedding_layer, fc_layer,
        sequence_conv_pool, settings)
    words = data_layer(name='words', size=1000, dtype='int64',
                       seq_type=1)
    label = data_layer(name='label', size=1, dtype='int64')
    emb = embedding_layer(input=words, size=64)
    conv = sequence_conv_pool(input=emb, context_len=3, hidden_size=128)
    prob = fc_layer(input=conv, size=2, act=SoftmaxActivation())
    cost = classification_cost(input=prob, label=label)
    settings(batch_size=64, learning_rate=5e-3,
             learning_method=AdamOptimizer(),
             regularization=L2Regularization(1e-5)).minimize(cost)
    _strict_defaults('v1_quickstart', [cost])


def test_pipelined_transformer_example_graph_verifies():
    # the examples/train_transformer_pipelined.py graph, including the
    # transpiled shardings — exercises the sharding pass on a real
    # dp x pp x tp layout
    from paddle_tpu.models import transformer as T
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                                transpile)
    avg_cost, _ = T.transformer_base(
        src_vocab_size=1024, trg_vocab_size=1024,
        src_seq_len=32, trg_seq_len=32,
        n_layer=4, d_model=64, d_inner=256, d_key=16, d_value=16,
        dropout_rate=0.1, scan_layers=True)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    mesh = make_mesh(dp=2, pp=2, tp=2)
    transpile(fluid.default_main_program(), mesh,
              ParallelStrategy(data_parallel=True, tensor_parallel=True,
                               pipeline_parallel=True,
                               pipeline_microbatches=2))
    _strict_defaults('pipelined_transformer', [avg_cost])


def test_transformer_and_moe_builders_verify():
    from paddle_tpu.models import transformer as T
    avg_cost, _ = T.transformer_base(
        src_vocab_size=512, trg_vocab_size=512, src_seq_len=16,
        trg_seq_len=16, n_layer=2, d_model=32, d_inner=64, d_key=16,
        d_value=16, dropout_rate=0.1)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    _strict_defaults('transformer', [avg_cost])

    fluid.reset_default_programs()
    from paddle_tpu.models.moe import switch_transformer_lm
    avg_cost, _ = switch_transformer_lm(
        vocab_size=512, seq_len=16, n_layer=2, n_head=2, d_model=32,
        d_inner=64, num_experts=4, capacity_factor=1.25,
        dropout_rate=0.1, max_length=64)
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    _strict_defaults('moe', [avg_cost])


def test_decode_model_builders_verify():
    from paddle_tpu.serving.decode.model import (LMSpec,
                                                 build_lm_programs)
    progs = build_lm_programs(LMSpec(vocab_size=128), 4, 8, 16, 4,
                              spec_k=3)
    _strict('decode_startup', progs.startup)
    _strict('decode_prefill', progs.prefill, [progs.prefill_fetch])
    _strict('decode_step', progs.decode, [progs.decode_fetch])
    _strict('decode_spec_verify', progs.verify, [progs.verify_fetch])


def test_quantized_decode_builders_verify():
    # the int8 KV arena builders, including the quant pass's
    # arena/scale pairing contracts
    from paddle_tpu.serving.decode.model import (LMSpec,
                                                 build_lm_programs)
    progs = build_lm_programs(LMSpec(vocab_size=128), 4, 8, 16, 4,
                              spec_k=2, kv_dtype='int8')
    _strict('decode_q_startup', progs.startup)
    _strict('decode_q_prefill', progs.prefill, [progs.prefill_fetch])
    _strict('decode_q_step', progs.decode, [progs.decode_fetch])
    _strict('decode_q_verify', progs.verify, [progs.verify_fetch])


def test_ptq_program_verifies():
    # the PTQ Program->Program rewrite under the strict sweep — the
    # quant pass's dtype/scale contracts must hold on its own output
    import numpy as np

    from paddle_tpu import quant

    ids = fluid.layers.data(name='ids', shape=[4], dtype='int64')
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    emb = fluid.layers.embedding(input=ids, size=[64, 8])
    pooled = fluid.layers.reduce_sum(emb, dim=1)
    h = fluid.layers.fc(input=[x, pooled], size=16, act='relu')
    out = fluid.layers.fc(input=h, size=4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    infer = fluid.io.get_inference_program([out])
    qprog, report = quant.quantize_inference_program(
        infer, fluid.global_scope(),
        sample_feed={'ids': np.zeros((4, 4, 1), 'int64'),
                     'x': np.zeros((4, 8), 'float32')},
        executor=exe)
    assert report['quantized'] >= 3
    _strict('ptq_mlp', qprog, [out.name], feeds=['ids', 'x'])


def test_linalg_programs_verify():
    # the distributed linear-algebra builders (ISSUE 15) under the
    # strict sweep — the blocked-layout pass's false-positive lock on
    # all four ops, meshed and single-device
    from paddle_tpu import linalg
    from paddle_tpu.parallel.mesh import make_mesh

    grid = make_mesh(dp=2, tp=4)
    line = make_mesh(dp=8)
    prog, out = linalg.build_matmul_program(64, 128, 32, mesh=grid,
                                            panel=8)
    _strict('linalg_summa', prog, [out],
            feeds=['summa_x', 'summa_y'])
    prog, out = linalg.build_cholesky_program(64, mesh=line, block=4)
    _strict('linalg_cholesky', prog, [out], feeds=['chol_x'])
    prog, (q, r) = linalg.build_qr_program(128, 64, mesh=line, block=8)
    _strict('linalg_qr', prog, [q, r], feeds=['qr_x'])
    for quantized in (False, True):
        prog, (v, lam) = linalg.build_power_iter_program(
            64, mesh=line, quantized=quantized)
        _strict('linalg_powit', prog, [v, lam],
                feeds=['powit_x', 'powit_v'])
    prog, out = linalg.build_matmul_program(8, 8, 8)   # no mesh
    _strict('linalg_summa_1dev', prog, [out],
            feeds=['summa_x', 'summa_y'])


def test_seq2seq_graphs_verify():
    # the attention seq2seq train graph plus the beam-search generation
    # graph — the hairiest builders in the model zoo (recurrent nets,
    # dynamic decode)
    from paddle_tpu.models.rnn_search import (rnn_search,
                                              rnn_search_beam_infer)
    cost = rnn_search(src_vocab=64, trg_vocab=64, emb_dim=8,
                      hidden_dim=8)
    cost = cost[0] if isinstance(cost, (list, tuple)) else cost
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(cost)
    _strict_defaults('seq2seq', [cost])

    fluid.reset_default_programs()
    out = rnn_search_beam_infer(src_vocab=64, trg_vocab=64, emb_dim=8,
                                hidden_dim=8)
    outs = out if isinstance(out, (list, tuple)) else [out]
    _strict('seq2seq_beam', fluid.default_main_program(),
            [o for o in outs if hasattr(o, 'name')])
