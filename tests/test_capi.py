"""Inference C ABI: a plain C program loads a saved model through
libcapi.so (embedded Python/JAX runtime) and classifies. Reference:
paddle/capi/tests + paddle/capi/examples/model_inference."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

C_CLIENT = r'''
#include <stdio.h>
#include <stdlib.h>
#include "capi.h"

#define CHECK(expr) do { paddle_error e_ = (expr); if (e_ != kPD_NO_ERROR) { \
  fprintf(stderr, "%s -> %s: %s\n", #expr, paddle_error_string(e_), \
          paddle_last_error_message()); exit(1); } } while (0)

int main(int argc, char** argv) {
  CHECK(paddle_tpu_init("cpu"));
  paddle_predictor pred;
  CHECK(paddle_predictor_create(argv[1], &pred));

  float x[2 * 4];
  for (int i = 0; i < 8; i++) x[i] = (i < 4) ? 1.0f : -1.0f;
  paddle_tensor in;
  in.dtype = PD_FLOAT32;
  in.ndim = 2;
  in.shape[0] = 2;
  in.shape[1] = 4;
  in.data = x;
  const char* names[] = {"x"};
  CHECK(paddle_predictor_run(pred, 1, names, &in));

  int32_t n;
  CHECK(paddle_predictor_output_count(pred, &n));
  printf("outputs=%d\n", n);
  paddle_tensor out;
  CHECK(paddle_predictor_output(pred, 0, &out));
  printf("shape=%lld,%lld\n", (long long)out.shape[0],
         (long long)out.shape[1]);
  const float* p = (const float*)out.data;
  for (int r = 0; r < 2; r++) {
    int best = 0;
    for (int c = 1; c < out.shape[1]; c++)
      if (p[r * out.shape[1] + c] > p[r * out.shape[1] + best]) best = c;
    printf("row%d argmax=%d prob=%.4f\n", r, best,
           p[r * out.shape[1] + best]);
  }
  CHECK(paddle_predictor_destroy(pred));
  printf("OK\n");
  return 0;
}
'''


def _save_tiny_classifier(dirname):
    """2-class linear classifier with hand-set weights so the C client's
    expected argmax is deterministic: class1 iff sum(x) > 0."""
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    prob = fluid.layers.fc(input=x, size=2, act='softmax',
                           param_attr=fluid.ParamAttr(name='cap_w'),
                           bias_attr=fluid.ParamAttr(name='cap_b'))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    w = np.zeros((4, 2), dtype='float32')
    w[:, 1] = 1.0  # logit1 = sum(x), logit0 = 0
    fluid.global_scope().set('cap_w', w)
    fluid.global_scope().set('cap_b', np.zeros(2, dtype='float32'))
    fluid.io.save_inference_model(dirname, ['x'], [prob], exe)


@pytest.mark.skipif(sys.platform != 'linux', reason='embed build is linux')
def test_c_client_classifies(tmp_path):
    from paddle_tpu.native import build_capi
    model_dir = str(tmp_path / 'model')
    _save_tiny_classifier(model_dir)

    so = build_capi()
    src = tmp_path / 'client.c'
    src.write_text(C_CLIENT)
    exe_path = str(tmp_path / 'client')
    subprocess.run(
        ['gcc', str(src), '-I', os.path.join(REPO, 'paddle_tpu', 'native'),
         so, '-o', exe_path, '-Wl,-rpath,' + os.path.dirname(so)],
        check=True, capture_output=True)

    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run([exe_path, model_dir], capture_output=True,
                       text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert 'outputs=1' in lines[0]
    assert 'shape=2,2' in lines[1]
    assert 'row0 argmax=1' in lines[2]  # sum=+4 -> class 1
    assert 'row1 argmax=0' in lines[3]  # sum=-4 -> class 0
    assert lines[-1] == 'OK'


C_CONCURRENT = r'''
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include "capi.h"

/* Two threads, one predictor EACH (the documented thread contract),
 * running concurrently with thread-distinct inputs; every iteration
 * checks the outputs belong to THIS thread's input. */

#define ITERS 8

typedef struct { const char* model; float sign; int failures; } job_t;

static void* worker(void* arg) {
  job_t* job = (job_t*)arg;
  paddle_predictor pred;
  if (paddle_predictor_create(job->model, &pred) != kPD_NO_ERROR) {
    fprintf(stderr, "create failed: %s\n", paddle_last_error_message());
    job->failures = ITERS;
    return NULL;
  }
  for (int it = 0; it < ITERS; it++) {
    float x[2 * 4];
    /* sign=+1 -> rows sum +4/-4; sign=-1 -> rows sum -4/+4 */
    for (int i = 0; i < 8; i++)
      x[i] = ((i < 4) ? 1.0f : -1.0f) * job->sign;
    paddle_tensor in;
    in.dtype = PD_FLOAT32; in.ndim = 2;
    in.shape[0] = 2; in.shape[1] = 4; in.data = x;
    const char* names[] = {"x"};
    if (paddle_predictor_run(pred, 1, names, &in) != kPD_NO_ERROR) {
      job->failures++; continue;
    }
    paddle_tensor out;
    if (paddle_predictor_output(pred, 0, &out) != kPD_NO_ERROR) {
      job->failures++; continue;
    }
    const float* p = (const float*)out.data;
    int want_row0 = job->sign > 0 ? 1 : 0;   /* class1 iff sum(x) > 0 */
    int got_row0 = p[1] > p[0] ? 1 : 0;
    int got_row1 = p[3] > p[2] ? 1 : 0;
    if (got_row0 != want_row0 || got_row1 != 1 - want_row0)
      job->failures++;
  }
  paddle_predictor_destroy(pred);
  return NULL;
}

int main(int argc, char** argv) {
  if (paddle_tpu_init("cpu") != kPD_NO_ERROR) return 1;
  job_t jobs[2] = {{argv[1], 1.0f, 0}, {argv[1], -1.0f, 0}};
  pthread_t ts[2];
  for (int i = 0; i < 2; i++) pthread_create(&ts[i], NULL, worker, &jobs[i]);
  for (int i = 0; i < 2; i++) pthread_join(ts[i], NULL);
  printf("failures=%d,%d\n", jobs[0].failures, jobs[1].failures);
  if (jobs[0].failures || jobs[1].failures) return 1;
  printf("OK\n");
  return 0;
}
'''


@pytest.mark.skipif(sys.platform != 'linux', reason='embed build is linux')
def test_c_client_concurrent_predictors(tmp_path):
    """The capi.h thread contract: two predictors on two pthreads run
    concurrently; each thread's outputs always match its own inputs
    (reference: capi/examples/model_inference/multi_thread)."""
    from paddle_tpu.native import build_capi
    model_dir = str(tmp_path / 'model')
    _save_tiny_classifier(model_dir)

    so = build_capi()
    src = tmp_path / 'client_mt.c'
    src.write_text(C_CONCURRENT)
    exe_path = str(tmp_path / 'client_mt')
    subprocess.run(
        ['gcc', str(src), '-I', os.path.join(REPO, 'paddle_tpu', 'native'),
         so, '-lpthread', '-o', exe_path,
         '-Wl,-rpath,' + os.path.dirname(so)],
        check=True, capture_output=True)

    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run([exe_path, model_dir], capture_output=True,
                       text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.strip().splitlines()[-1] == 'OK'


def test_capi_via_ctypes_repeated_runs(tmp_path):
    """Drive the C ABI through ctypes from the host process: repeated
    runs reuse the cached executable and outputs stay stable; error
    paths return proper codes."""
    import ctypes

    from paddle_tpu.native import build_capi
    model_dir = str(tmp_path / 'model')
    _save_tiny_classifier(model_dir)

    lib = ctypes.CDLL(build_capi())

    class Tensor(ctypes.Structure):
        _fields_ = [('dtype', ctypes.c_int), ('ndim', ctypes.c_int32),
                    ('shape', ctypes.c_int64 * 8),
                    ('data', ctypes.c_void_p)]

    lib.paddle_predictor_create.restype = ctypes.c_int
    lib.paddle_predictor_create.argtypes = [ctypes.c_char_p,
                                            ctypes.POINTER(ctypes.c_void_p)]
    lib.paddle_predictor_run.restype = ctypes.c_int
    lib.paddle_predictor_run.argtypes = [
        ctypes.c_void_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(Tensor)]
    lib.paddle_predictor_output.restype = ctypes.c_int
    lib.paddle_predictor_output.argtypes = [ctypes.c_void_p,
                                            ctypes.c_int32,
                                            ctypes.POINTER(Tensor)]
    lib.paddle_predictor_destroy.restype = ctypes.c_int
    lib.paddle_tpu_init.restype = ctypes.c_int
    lib.paddle_tpu_init.argtypes = [ctypes.c_char_p]

    assert lib.paddle_tpu_init(None) == 0  # attaches to THIS interpreter
    pred = ctypes.c_void_p()
    assert lib.paddle_predictor_create(model_dir.encode(),
                                       ctypes.byref(pred)) == 0

    outs = []
    for rep in range(3):
        xs = np.full((2, 4), 1.0 - rep, dtype='float32')
        t = Tensor()
        t.dtype, t.ndim = 0, 2
        t.shape[0], t.shape[1] = 2, 4
        t.data = xs.ctypes.data_as(ctypes.c_void_p)
        names = (ctypes.c_char_p * 1)(b'x')
        assert lib.paddle_predictor_run(pred, 1, names,
                                        ctypes.byref(t)) == 0
        out = Tensor()
        assert lib.paddle_predictor_output(pred, 0, ctypes.byref(out)) == 0
        assert (out.shape[0], out.shape[1]) == (2, 2)
        buf = np.ctypeslib.as_array(
            ctypes.cast(out.data, ctypes.POINTER(ctypes.c_float)),
            shape=(2, 2)).copy()
        outs.append(buf)
    # deterministic: same input -> same probs; argmax follows sum(x)
    assert outs[0][0].argmax() == 1   # sum=+4
    assert outs[2][0].argmax() == 0   # sum=-4
    # out-of-range + null errors
    bad = Tensor()
    assert lib.paddle_predictor_output(pred, 99, ctypes.byref(bad)) == 2
    assert lib.paddle_predictor_destroy(pred) == 0
    assert lib.paddle_predictor_destroy(None) == 1
