"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle (reference layout: python/paddle/fluid).

Front-end: declarative Program/Block/Op IR (like fluid). Back-end: the
Executor lowers whole programs through JAX to single XLA computations;
parallelism is SPMD over a jax.sharding.Mesh (paddle_tpu.parallel).

Typical flow (identical to the reference's fluid API):

    import paddle_tpu as fluid
    x = fluid.layers.data(name='x', shape=[13])
    y = fluid.layers.data(name='y', shape=[1])
    pred = fluid.layers.fc(input=x, size=1, act=None)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    exe.run(feed={'x': ..., 'y': ...}, fetch_list=[loss])
"""

from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import learning_rate_decay  # noqa: F401
from . import nets  # noqa: F401
from . import io  # noqa: F401
from . import evaluator  # noqa: F401
from . import metrics  # noqa: F401
from . import observe  # noqa: F401
from . import analysis  # noqa: F401
from . import profiler  # noqa: F401
from . import backward  # noqa: F401
from . import debug  # noqa: F401
from . import trainer  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import fault  # noqa: F401
from .fault import CheckpointConfig  # noqa: F401
from . import serving  # noqa: F401
from . import memory_optimize as _memory_optimize_mod  # noqa: F401
from .memory_optimize import memory_optimize, release_memory  # noqa: F401
from .core.errors import EnforceError, enforce  # noqa: F401
from .core.flags import init_flags  # noqa: F401
from .core.lod import create_lod_tensor, pad_sequences  # noqa: F401
from . import parallel  # noqa: F401
from . import linalg  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import image  # noqa: F401

from .core.backward import append_backward  # noqa: F401
from .core.executor import Executor  # noqa: F401
from .core.place import CPUPlace, CUDAPlace, TPUPlace  # noqa: F401
from .core.program import (Program, Variable, default_main_program,  # noqa
                           default_startup_program, program_guard,
                           reset_default_programs, switch_main_program,
                           switch_startup_program)
from .core.scope import Scope, global_scope, scope_guard  # noqa: F401
from .core import unique_name  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

__version__ = '0.1.0'

# Drop-in familiarity: scripts written against the reference often do
# `import paddle.fluid as fluid`; `paddle_tpu` IS the fluid-level namespace.
fluid = __import__(__name__)
