"""Image preprocessing for input pipelines.

Reference: python/paddle/v2/image.py:41-381 (same public API). The
reference decodes/augments with cv2; this uses PIL + numpy (cv2 is not
in the image). All functions work on HWC uint8/float ndarrays and run on
HOST inside the reader worker threads — the TPU step consumes the
already-augmented CHW float batch (augmentation is branchy per-sample
work with no MXU mapping; keeping it in the data pipeline overlaps it
with device compute, same as the reference's C++ DataProvider).
"""

import io as _io
import tarfile

import numpy as np

__all__ = [
    'batch_images_from_tar', 'load_image_bytes', 'load_image',
    'resize_short', 'to_chw', 'center_crop', 'random_crop',
    'left_right_flip', 'simple_transform', 'load_and_transform',
]


def _pil():
    from PIL import Image
    return Image


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Read images from a tar, batch them into numpy files
    (v2/image.py:48-110). Returns the meta-file path listing batches."""
    import os
    import pickle
    out_path = "%s_%s_batch" % (data_file, dataset_name)
    meta_file = os.path.join(out_path, 'batch_meta')
    if os.path.exists(meta_file):
        return meta_file
    os.makedirs(out_path, exist_ok=True)
    tf = tarfile.open(data_file)
    names = [m.name for m in tf.getmembers() if m.name in img2label]
    data, labels, batch_names = [], [], []
    file_id = 0
    for name in names:
        data.append(tf.extractfile(name).read())
        labels.append(img2label[name])
        if len(data) == num_per_batch:
            batch_name = os.path.join(out_path, 'batch_%d' % file_id)
            with open(batch_name, 'wb') as f:
                pickle.dump({'data': data, 'label': labels}, f,
                            protocol=2)
            batch_names.append(batch_name)
            data, labels = [], []
            file_id += 1
    if data:
        batch_name = os.path.join(out_path, 'batch_%d' % file_id)
        with open(batch_name, 'wb') as f:
            pickle.dump({'data': data, 'label': labels}, f, protocol=2)
        batch_names.append(batch_name)
    with open(meta_file, 'w') as f:
        f.write('\n'.join(batch_names))
    return meta_file


def load_image_bytes(bytes_, is_color=True):
    """Decode an encoded (jpeg/png/...) byte string to an HWC uint8 array
    (v2/image.py:111-134)."""
    img = _pil().open(_io.BytesIO(bytes_))
    img = img.convert('RGB' if is_color else 'L')
    return np.asarray(img)


def load_image(file, is_color=True):
    """Load an image file as an HWC uint8 array (v2/image.py:135-162)."""
    img = _pil().open(file)
    img = img.convert('RGB' if is_color else 'L')
    return np.asarray(img)


def resize_short(im, size):
    """Resize so the SHORTER edge is `size`, keeping aspect ratio
    (v2/image.py:163-188)."""
    h, w = im.shape[:2]
    if h > w:
        new_h, new_w = int(round(h * size / float(w))), size
    else:
        new_h, new_w = size, int(round(w * size / float(h)))
    pil_im = _pil().fromarray(np.ascontiguousarray(im))
    resized = pil_im.resize((new_w, new_h), _pil().BILINEAR)
    return np.asarray(resized)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (v2/image.py:189-212)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Crop the center size x size patch (v2/image.py:213-240)."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True, rng=None):
    """Crop a random size x size patch (v2/image.py:241-268)."""
    rng = rng or np.random
    h, w = im.shape[:2]
    h_start = rng.randint(0, h - size + 1)
    w_start = rng.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    """Mirror horizontally (v2/image.py:269-290)."""
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> (random crop + coin-flip mirror | center crop) ->
    CHW float32 -> optional mean subtraction (v2/image.py:291-347).
    `mean` may be per-channel ([C]) or elementwise (CHW)."""
    rng = rng or np.random
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color, rng=rng)
        if rng.randint(0, 2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype('float32')
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """load_image + simple_transform (v2/image.py:348-381)."""
    im = load_image(filename, is_color)
    return simple_transform(im, resize_size, crop_size, is_train,
                            is_color, mean)
