"""Composite networks (reference: python/paddle/fluid/nets.py)."""

from . import layers

__all__ = ['simple_img_conv_pool', 'sequence_conv_pool', 'glu',
           'scaled_dot_product_attention', 'img_conv_group']


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, param_attr=None,
                         pool_type='max', use_cudnn=True, use_mkldnn=False):
    conv_out = layers.conv2d(input=input, num_filters=num_filters,
                             filter_size=filter_size, param_attr=param_attr,
                             act=act)
    return layers.pool2d(input=conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type='max', use_cudnn=True,
                   use_mkldnn=False):
    tmp = input
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]

    def _ith(arg, i):
        if isinstance(arg, (list, tuple)):
            return arg[i]
        return arg

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=_ith(conv_filter_size, i),
            padding=_ith(conv_padding, i),
            param_attr=_ith(param_attr, i) if isinstance(param_attr, list)
            else param_attr,
            act=local_conv_act)
        if conv_with_batchnorm:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = _ith(conv_batchnorm_drop_rate, i)
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act='sigmoid', pool_type='max', length=None):
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type,
                                length=length)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(x=a, y=layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled-dot-product attention (reference nets.py:
    scaled_dot_product_attention) built from IR ops; Executor-level Pallas
    flash-attention kicks in via ops/attention fusion for long sequences."""
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError('queries and keys must have the same hidden size')
    d_key = keys.shape[-1] // num_heads

    def _split_heads(x):
        if num_heads == 1:
            return x
        b, t, d = x.shape
        reshaped = layers.reshape(x=x, shape=[b if b and b > 0 else -1, t,
                                              num_heads, d // num_heads])
        return layers.transpose(x=reshaped, perm=[0, 2, 1, 3])

    def _combine_heads(x):
        if num_heads == 1:
            return x
        b, h, t, d = x.shape
        trans = layers.transpose(x=x, perm=[0, 2, 1, 3])
        return layers.reshape(x=trans, shape=[b if b and b > 0 else -1, t,
                                              h * d])

    q = _split_heads(queries)
    k = _split_heads(keys)
    v = _split_heads(values)
    scaled_q = layers.scale(x=q, scale=d_key ** -0.5)
    product = layers.matmul(x=scaled_q, y=k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx_multiheads = layers.matmul(weights, v)
    return _combine_heads(ctx_multiheads)
