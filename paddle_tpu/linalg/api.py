"""Program builders and host wrappers for the distributed linalg tier.

Each builder returns a standalone :class:`Program` holding exactly one
linalg IR op, with the blocked-layout PartitionSpecs attached the same
way ``parallel.transpile`` annotates training programs — so the static
verifier's ``linalg`` pass, the executor's GSPMD feed sharding, and
the compile cache all treat these like any other workload. The host
wrappers build+run through a (cached-per-wrapper-call) Executor:

    from paddle_tpu import linalg
    mesh = make_mesh(dp=2, tp=4)
    c = linalg.matmul(a, b, mesh=mesh)            # SUMMA under the hood
    l = linalg.cholesky(spd, mesh=make_mesh(dp=8))
    q, r = linalg.qr(tall, mesh=make_mesh(dp=8))
    lam, v = linalg.power_iteration(sym, iters=60, mesh=make_mesh(dp=8),
                                    quantized=True)

Nothing ever materializes a full matrix on one shard: feeds arrive
pre-blocked via ``device_put`` under their NamedSharding, the kernels
move panels only, and :func:`assert_memory_contract` raises if the
analytic per-shard peak exceeds ``factor`` x the O(N^2/P) ideal.
"""

import numpy as np

from ..core.executor import Executor
from ..core.program import Program
from . import kernels


class MemoryContractError(AssertionError):
    """Per-shard peak memory would exceed the O(N^2/P) contract."""


def _data(block, name, shape, dtype):
    v = block.create_var(name=name,
                         shape=tuple(int(s) for s in shape),
                         dtype=dtype, is_data=True)
    v.stop_gradient = True
    return v


def _attach(program, mesh, shardings):
    program.mesh = mesh
    if mesh is None:
        return
    from jax.sharding import PartitionSpec as P
    for name, spec in shardings.items():
        program.var_shardings[name] = P(*spec)


def assert_memory_contract(op, mesh, dims, dtype='float32', panel=None,
                           block=None, factor=1.5):
    """Check the analytic per-shard peak against `factor` x the evenly
    divided operand+result footprint; raises MemoryContractError on
    violation, returns the model dict otherwise. bench.py asserts this
    for the largest SUMMA shape; builders call it with a loose factor
    as a construction-time guard."""
    model = kernels.per_shard_peak_bytes(op, mesh, dims, dtype=dtype,
                                         panel=panel, block=block)
    if model['factor'] > factor:
        raise MemoryContractError(
            '%s at %s on %s shards: per-shard peak %d bytes is %.2fx '
            'the O(N^2/P) ideal %d (contract: <= %.2fx)'
            % (op, tuple(dims), model['participants'], model['peak'],
               model['factor'], model['ideal'], factor))
    return model


# ------------------------------------------------------------ builders
def build_matmul_program(n, k, m, dtype='float32', mesh=None,
                         panel=None):
    prog = Program()
    b = prog.global_block()
    x = _data(b, 'summa_x', (n, k), dtype)
    y = _data(b, 'summa_y', (k, m), dtype)
    out = b.create_var(name='summa_out', shape=(n, m), dtype=dtype)
    b.append_op('summa_matmul', {'X': x, 'Y': y}, {'Out': out},
                {'panel': int(panel or 0)})
    _attach(prog, mesh, {'summa_x': ('dp', 'tp'),
                         'summa_y': ('dp', 'tp'),
                         'summa_out': ('dp', 'tp')})
    return prog, out


def build_cholesky_program(n, dtype='float32', mesh=None, block=None):
    prog = Program()
    b = prog.global_block()
    x = _data(b, 'chol_x', (n, n), dtype)
    out = b.create_var(name='chol_out', shape=(n, n), dtype=dtype)
    b.append_op('blocked_cholesky', {'X': x}, {'Out': out},
                {'block': int(block or 0)})
    _attach(prog, mesh, {'chol_x': ('dp', None),
                         'chol_out': ('dp', None)})
    return prog, out


def build_qr_program(n, m, dtype='float32', mesh=None, block=None):
    prog = Program()
    b = prog.global_block()
    x = _data(b, 'qr_x', (n, m), dtype)
    q = b.create_var(name='qr_q', shape=(n, m), dtype=dtype)
    r = b.create_var(name='qr_r', shape=(m, m), dtype=dtype)
    b.append_op('blocked_qr', {'X': x}, {'Q': q, 'R': r},
                {'block': int(block or 0)})
    _attach(prog, mesh, {'qr_x': ('dp', None), 'qr_q': ('dp', None),
                         'qr_r': ()})
    return prog, (q, r)


def build_power_iter_program(n, dtype='float32', mesh=None,
                             quantized=False, qblock=256):
    prog = Program()
    b = prog.global_block()
    x = _data(b, 'powit_x', (n, n), dtype)
    v = _data(b, 'powit_v', (n,), dtype)
    vout = b.create_var(name='powit_v_next', shape=(n,), dtype=dtype)
    lam = b.create_var(name='powit_eigval', shape=(1,), dtype=dtype)
    b.append_op('power_iter_step', {'X': x, 'V': v},
                {'VOut': vout, 'Eigval': lam},
                {'quantized': bool(quantized), 'qblock': int(qblock)})
    _attach(prog, mesh, {'powit_x': (None, 'dp'), 'powit_v': (),
                         'powit_v_next': (), 'powit_eigval': ()})
    return prog, (vout, lam)


# ------------------------------------------------------- host wrappers
def _pre_shard(value, mesh, spec_axes):
    """device_put a feed under its blocked NamedSharding ONCE, so
    host loops (power_iteration) re-feed a device-resident array the
    executor passes through without copies."""
    if mesh is None:
        return value
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.device_put(value, NamedSharding(mesh, P(*spec_axes)))


def matmul(a, b, mesh=None, panel=None, executor=None):
    """SUMMA blocked matmul of two host (or device) arrays."""
    a = np.asarray(a) if not hasattr(a, 'sharding') else a
    b = np.asarray(b) if not hasattr(b, 'sharding') else b
    n, k = a.shape
    m = b.shape[1]
    prog, out = build_matmul_program(n, k, m, dtype=str(a.dtype),
                                     mesh=mesh, panel=panel)
    exe = executor or Executor()
    return exe.run(prog, feed={'summa_x': a, 'summa_y': b},
                   fetch_list=[out])[0]


def cholesky(a, mesh=None, block=None, executor=None):
    a = np.asarray(a) if not hasattr(a, 'sharding') else a
    prog, out = build_cholesky_program(a.shape[0], dtype=str(a.dtype),
                                       mesh=mesh, block=block)
    exe = executor or Executor()
    return exe.run(prog, feed={'chol_x': a}, fetch_list=[out])[0]


def qr(a, mesh=None, block=None, executor=None):
    a = np.asarray(a) if not hasattr(a, 'sharding') else a
    prog, (q, r) = build_qr_program(a.shape[0], a.shape[1],
                                    dtype=str(a.dtype), mesh=mesh,
                                    block=block)
    exe = executor or Executor()
    got = exe.run(prog, feed={'qr_x': a}, fetch_list=[q, r])
    return got[0], got[1]


def power_iteration(a, iters=50, mesh=None, quantized=False, qblock=256,
                    v0=None, executor=None):
    """Dominant eigenvalue/eigenvector by repeated
    ``power_iter_step`` dispatch: one executor cache entry, `iters`
    cache-hit runs, A device-resident and column-blocked the whole
    time. Returns ``(eigenvalue, eigenvector)``."""
    a = np.asarray(a) if not hasattr(a, 'sharding') else a
    n = a.shape[0]
    prog, (vout, lam) = build_power_iter_program(
        n, dtype=str(np.dtype(str(a.dtype))), mesh=mesh,
        quantized=quantized, qblock=qblock)
    exe = executor or Executor()
    a_dev = _pre_shard(a, mesh, (None, 'dp'))
    v = v0 if v0 is not None else \
        np.full((n,), 1.0 / np.sqrt(n), str(a.dtype))
    lam_val = None
    for _ in range(max(1, int(iters))):
        v, lam_val = exe.run(prog, feed={'powit_x': a_dev,
                                         'powit_v': v},
                             fetch_list=[vout, lam],
                             return_numpy=False)
    return float(np.asarray(lam_val).reshape(())), np.asarray(v)
