"""Distributed dense linear-algebra kernels over the device mesh.

PAPERS "Large Scale Distributed Linear Algebra With Tensor Processing
Units": TPU pods run dense matmul/QR/eigensolvers at sizes (100k x
100k+) no single host holds, by keeping every matrix blocked across
the mesh and moving PANELS — never whole operands — over ICI. These
are the shard_map bodies that implement that discipline on the repo's
dp x tp mesh:

- :func:`summa_matmul` — SUMMA blocked matmul on the 2-D dp x tp grid.
  A is blocked [dp, tp], B is blocked [dp, tp], C accumulates in place
  [dp, tp]. For each k-panel the owning grid column broadcasts its A
  panel along the row ('tp' axis) and the owning grid row broadcasts
  its B panel along the column ('dp' axis); every device accumulates
  the local panel product. The panel fetch for step t+1 is issued
  BEFORE step t's dot (double-buffered scan carry), so XLA overlaps
  the broadcast ppermute chain with the previous panel's matmul.
- :func:`blocked_cholesky` — right-looking blocked Cholesky with the
  matrix row-blocked over one axis: the panel owner's diagonal block
  is broadcast, every device panel-solves its local rows, the column
  panel is all-gathered, and the trailing Schur complement updates
  locally.
- :func:`blocked_qr` — blocked Householder QR: each column panel is
  all-gathered ([N, b] — the ONE tall-skinny temporary, never the
  full matrix) and factored redundantly through the backend's
  Householder QR; the trailing block row of R is a psum-reduced
  projection and the trailing matrix updates locally (block
  Gram-Schmidt between panels).
- :func:`power_iter_step` — one power-iteration step with A
  column-blocked: z = A v is a local [N, N/P] matvec followed by an
  N-element allreduce, which routes through exact ``psum`` or the PR
  13 ``quantized_all_reduce`` — the compression/accuracy trade on an
  allreduce-DOMINATED workload (the reduction is the step).

Per-shard peak memory stays O(N^2/P) everywhere: the only cross-shard
temporaries are panels (O(N b / P_axis)) and the QR/Cholesky gathered
panel (O(N b)). :func:`paddle_tpu.linalg.per_shard_peak_bytes` is the
analytic model bench.py asserts against.

Panel/block sizes: explicit argument > ``PADDLE_TPU_SUMMA_PANEL`` /
``PADDLE_TPU_LINALG_BLOCK`` env knobs (read per call) > the PR 8
autotuner's ``linalg`` op family (``tuning.decide_summa_panel`` /
``decide_linalg_block``) > :func:`default_panel`. Resolution lives in
``ops/linalg_ops.py`` so direct kernel callers pass concrete sizes.
"""

import math

import jax
import jax.numpy as jnp

from ..parallel.collective import broadcast, quantized_all_reduce
from ..parallel.mesh import compat_shard_map

__all__ = ['summa_matmul', 'blocked_cholesky', 'blocked_qr',
           'power_iter_step', 'matmul_reference', 'cholesky_reference',
           'qr_reference', 'legal_panels', 'default_panel',
           'default_block', 'legal_blocks', 'axis_sizes_of',
           'per_shard_peak_bytes']


# ------------------------------------------------------------- helpers
def axis_sizes_of(mesh, *axes):
    """Sizes of the named axes on `mesh` (1 when absent or mesh None)."""
    shape = dict(mesh.shape) if mesh is not None else {}
    return tuple(int(shape.get(a, 1)) for a in axes)


def _divisors(n):
    out = []
    d = 1
    while d * d <= n:
        if n % d == 0:
            out.append(d)
            if d != n // d:
                out.append(n // d)
        d += 1
    return sorted(out)


def legal_panels(k, n_dp, n_tp):
    """Legal SUMMA panel sizes for contraction length `k` on a
    dp x tp grid: a panel must divide BOTH local block extents
    (K/tp for A's columns, K/dp for B's rows) so no panel ever
    straddles an owner boundary."""
    if k % max(n_dp, 1) or k % max(n_tp, 1):
        return []
    g = math.gcd(k // max(n_tp, 1), k // max(n_dp, 1))
    return _divisors(g)


def default_panel(k, n_dp, n_tp, n=None, m=None, dtype='float32'):
    """Untuned SUMMA panel: the largest legal panel <= 256 (an
    MXU-friendly contraction tile) that also keeps the double-buffered
    panel temporaries inside the 1.5x O(N^2/P) memory contract when
    the full (n, m) shape is known — the default never trades the
    contract away; the autotuner's ladder may, explicitly. Coarser
    panels win when per-step collective latency dominates, finer when
    overlap does."""
    panels = legal_panels(k, n_dp, n_tp)
    if not panels:
        raise ValueError(
            'summa_matmul: contraction dim %d not divisible by the '
            'dp=%d x tp=%d grid' % (k, n_dp, n_tp))
    capped = [p for p in panels if p <= 256] or panels[:1]
    if n is not None and m is not None:
        shape = {'dp': n_dp, 'tp': n_tp}
        fits = [p for p in capped
                if per_shard_peak_bytes('summa_matmul', shape,
                                        (n, k, m), dtype=dtype,
                                        panel=p)['factor'] <= 1.5]
        if fits:
            capped = fits
    return capped[-1]


def legal_blocks(n, local=None):
    """Legal Cholesky/QR panel widths: divisors of the factored extent
    `n` that (when `local` is given) also divide the per-shard
    row-block extent, so a panel's diagonal block lives on exactly one
    owner."""
    blocks = _divisors(n)
    if local is not None:
        blocks = [b for b in blocks if local % b == 0]
    return blocks


def default_block(n, local=None):
    """Untuned factorization panel width: largest legal <= 64 (panel
    factorizations are O(N b^2) serial work — small panels keep the
    trailing updates, which parallelize, dominant)."""
    blocks = legal_blocks(n, local=local)
    if not blocks:
        raise ValueError('no legal factorization block for extent %d '
                         '(local %r)' % (n, local))
    capped = [b for b in blocks if b <= 64]
    return capped[-1] if capped else blocks[0]


# ------------------------------------------------------- memory model
def _itemsize(dtype):
    import numpy as np
    return int(np.dtype(str(dtype).replace('bfloat16', 'uint16'))
               .itemsize)


def per_shard_peak_bytes(op, mesh, dims, dtype='float32', panel=None,
                         block=None):
    """Analytic per-shard peak resident bytes for one linalg op — the
    memory contract ``bench.py --workload linalg`` asserts. Returns
    ``{'peak', 'ideal', 'factor', 'participants'}`` where `ideal` is
    the operand+result footprint divided evenly over the participating
    shards (the O(N^2/P) floor) and `factor` = peak/ideal. The model
    counts everything a shard holds at once: its operand blocks, the
    fp32 accumulator/working set, and the panel temporaries (double-
    buffered for SUMMA, the gathered [N, b] panel for QR/Cholesky).

    `mesh` may be a Mesh or a plain {axis: size} mapping (the analysis
    pass and stdlib callers use the latter)."""
    shape = dict(mesh.shape) if hasattr(mesh, 'shape') else \
        dict(mesh or {})
    isz = _itemsize(dtype)
    if op == 'summa_matmul':
        n, k, m = dims
        dp = int(shape.get('dp', 1))
        tp = int(shape.get('tp', 1))
        p = dp * tp
        a_loc = (n // dp) * (k // tp) * isz
        b_loc = (k // dp) * (m // tp) * isz
        # fp32 output IS the accumulator (the final astype is identity);
        # narrower dtypes materialize a separate cast result
        out_loc = 0 if isz == 4 else (n // dp) * (m // tp) * isz
        acc = (n // dp) * (m // tp) * 4
        pb = int(panel or default_panel(k, dp, tp))
        panels = 2 * ((n // dp) + (m // tp)) * pb * isz  # double-buffered
        peak = a_loc + b_loc + out_loc + acc + panels
        ideal = (n * k + k * m + n * m) * isz // p
    elif op in ('blocked_cholesky', 'blocked_qr'):
        n, m = dims
        dp = int(shape.get('dp', 1))
        p = dp
        nb = n // dp
        blk = int(block or default_block(
            n if op == 'blocked_cholesky' else m,
            local=nb if op == 'blocked_cholesky' else None))
        in_loc = nb * m * isz
        work = nb * m * 4                      # fp32 working copy
        out_loc = nb * m * 4 + (0 if op == 'blocked_cholesky'
                                else m * m * 4)   # L / (Q, replicated R)
        gathered = n * blk * 4                 # the [N, b] panel
        peak = in_loc + work + out_loc + gathered
        ideal = 2 * n * m * isz // p
    elif op == 'power_iter_step':
        (n,) = dims if isinstance(dims, (tuple, list)) else (dims,)
        dp = int(shape.get('dp', 1))
        p = dp
        a_loc = n * (n // dp) * isz
        vecs = 4 * n * 4                       # v, v_loc, z_part, z
        peak = a_loc + vecs
        ideal = n * n * isz // p
    else:
        raise ValueError('per_shard_peak_bytes: unknown op %r' % op)
    return {'peak': int(peak), 'ideal': int(max(ideal, 1)),
            'factor': peak / float(max(ideal, 1)),
            'participants': int(p)}


# -------------------------------------------------- single-device refs
def matmul_reference(a, b):
    return jnp.matmul(a, b)


def cholesky_reference(a):
    return jnp.linalg.cholesky(a)


def qr_reference(a):
    return jnp.linalg.qr(a, mode='reduced')


# --------------------------------------------------------------- SUMMA
def summa_matmul(a, b, mesh, panel, row_axis='dp', col_axis='tp'):
    """SUMMA blocked matmul: global ``a [N, K] @ b [K, M] -> [N, M]``
    with every operand blocked ``P(row_axis, col_axis)`` across the
    mesh. Call inside the executor's jit (or any jit) — the shard_map
    partitions the global values. Accumulation is fp32 regardless of
    input dtype; panel ordering is fixed by the k-offset, so the
    result is independent of the mesh WIDTH for exactly-representable
    inputs (the dyadic bit-identity test)."""
    n_dp, n_tp = axis_sizes_of(mesh, row_axis, col_axis)
    if mesh is None or (n_dp == 1 and n_tp == 1):
        return matmul_reference(a, b)
    from jax.sharding import PartitionSpec as P

    n, k = a.shape
    k2, m = b.shape
    if k != k2:
        raise ValueError('summa_matmul: inner dims %d vs %d' % (k, k2))
    if n % n_dp or m % n_tp or k % n_dp or k % n_tp:
        raise ValueError(
            'summa_matmul: shape (%d, %d) x (%d, %d) not divisible by '
            'the dp=%d x tp=%d grid' % (n, k, k, m, n_dp, n_tp))
    ak = k // n_tp          # local A columns
    bk = k // n_dp          # local B rows
    panel = int(panel)
    if panel <= 0 or ak % panel or bk % panel:
        raise ValueError(
            'summa_matmul: panel %d must divide both local block '
            'extents K/tp=%d and K/dp=%d' % (panel, ak, bk))
    n_steps = k // panel

    def body(a_loc, b_loc):
        # a_loc [N/dp, K/tp], b_loc [K/dp, M/tp]
        offs = jnp.arange(n_steps, dtype=jnp.int32) * panel
        a_roots = offs // ak            # grid column owning A panel t
        b_roots = offs // bk            # grid row owning B panel t
        a_offs = offs - a_roots * ak    # local col offset on the owner
        b_offs = offs - b_roots * bk    # local row offset on the owner

        def fetch(t):
            # off-owner slices are clamped junk; broadcast() keeps only
            # the root's value, so they never pollute the product
            ap = jax.lax.dynamic_slice(
                a_loc, (0, a_offs[t]), (a_loc.shape[0], panel))
            bp = jax.lax.dynamic_slice(
                b_loc, (b_offs[t], 0), (panel, b_loc.shape[1]))
            ap = broadcast(ap, col_axis, root=a_roots[t])
            bp = broadcast(bp, row_axis, root=b_roots[t])
            return ap, bp

        ap0, bp0 = fetch(0)
        acc0 = jnp.zeros((a_loc.shape[0], b_loc.shape[1]), jnp.float32)

        def step(carry, t):
            acc, ap, bp = carry
            # issue step t+1's broadcast BEFORE step t's dot: the
            # ppermute chain has no data dependence on the product, so
            # XLA overlaps the k-panel transfer with the local matmul
            ap_n, bp_n = fetch(jnp.minimum(t + 1, n_steps - 1))
            acc = acc + jnp.matmul(ap.astype(jnp.float32),
                                   bp.astype(jnp.float32))
            return (acc, ap_n, bp_n), None

        (acc, _, _), _ = jax.lax.scan(
            step, (acc0, ap0, bp0),
            jnp.arange(n_steps, dtype=jnp.int32))
        return acc.astype(a_loc.dtype)

    fn = compat_shard_map(
        body, mesh=mesh,
        in_specs=(P(row_axis, col_axis), P(row_axis, col_axis)),
        out_specs=P(row_axis, col_axis))
    return fn(a, b)


# ------------------------------------------------------------ Cholesky
def blocked_cholesky(a, mesh, block, axis='dp'):
    """Right-looking blocked Cholesky of SPD ``a [N, N]`` row-blocked
    ``P(axis, None)``. Returns the lower-triangular factor with the
    same distribution. ``block`` must divide the per-shard row extent
    N/dp so each panel's diagonal block has one owner."""
    (n_dp,) = axis_sizes_of(mesh, axis)
    if mesh is None or n_dp == 1:
        return cholesky_reference(a)
    from jax.sharding import PartitionSpec as P

    n = a.shape[0]
    if a.shape[1] != n:
        raise ValueError('blocked_cholesky: square input required')
    if n % n_dp:
        raise ValueError('blocked_cholesky: N=%d %% dp=%d != 0'
                         % (n, n_dp))
    nb = n // n_dp
    b = int(block)
    if b <= 0 or nb % b:
        raise ValueError('blocked_cholesky: block %d must divide the '
                         'per-shard row extent N/dp=%d' % (b, nb))
    n_panels = n // b

    def body(a_loc):
        idx = jax.lax.axis_index(axis)
        grow = idx * nb + jnp.arange(nb)        # global row ids
        s = a_loc.astype(jnp.float32)
        l_out = jnp.zeros_like(s)
        for p in range(n_panels):
            c0 = p * b
            owner = c0 // nb                    # static python int
            loc0 = c0 - owner * nb
            # the owner's diagonal Schur block, shipped to everyone
            # (off-owner slices are junk until the broadcast replaces
            # them); the b^3 factorization is then redundant on every
            # device — cheaper than a second broadcast of the factor
            diag = jax.lax.dynamic_slice(s, (loc0, c0), (b, b))
            diag = broadcast(diag, axis, root=owner)
            lpp = jnp.linalg.cholesky(diag)
            span = jax.lax.dynamic_slice(s, (0, c0), (nb, b))
            sol = jax.scipy.linalg.solve_triangular(
                lpp, span.T, lower=True).T      # [nb, b]
            below = (grow >= c0 + b)[:, None]
            inpanel = ((grow >= c0) & (grow < c0 + b))[:, None]
            lpp_rows = lpp[jnp.clip(grow - c0, 0, b - 1)]
            pan = jnp.where(below, sol,
                            jnp.where(inpanel, lpp_rows, 0.0))
            l_out = jax.lax.dynamic_update_slice(l_out, pan, (0, c0))
            pan_full = jax.lax.all_gather(pan, axis, axis=0,
                                          tiled=True)  # [N, b]
            trail = (jnp.arange(n) >= c0 + b)[None, :]
            s = s - jnp.where(below & trail, pan @ pan_full.T, 0.0)
        return l_out.astype(a_loc.dtype)

    fn = compat_shard_map(body, mesh=mesh, in_specs=(P(axis, None),),
                          out_specs=P(axis, None))
    return fn(a)


# ------------------------------------------------------------------ QR
def blocked_qr(a, mesh, block, axis='dp'):
    """Blocked Householder QR of ``a [N, M]`` (N >= M) row-blocked
    ``P(axis, None)``: returns (Q [N, M] row-blocked, R [M, M]
    replicated). Each column panel is all-gathered — a [N, block]
    tall-skinny temporary, the only time more than a 1/P slice of
    anything crosses a shard — and factored through the backend's
    Householder QR on every device; panels compose by block
    Gram-Schmidt with psum-reduced projections."""
    (n_dp,) = axis_sizes_of(mesh, axis)
    if mesh is None or n_dp == 1:
        return qr_reference(a)
    from jax.sharding import PartitionSpec as P

    n, m = a.shape
    if m > n:
        raise ValueError('blocked_qr: N=%d < M=%d (tall input '
                         'required)' % (n, m))
    if n % n_dp:
        raise ValueError('blocked_qr: N=%d %% dp=%d != 0' % (n, n_dp))
    nb = n // n_dp
    b = int(block)
    if b <= 0 or m % b:
        raise ValueError('blocked_qr: block %d must divide M=%d'
                         % (b, m))
    n_panels = m // b

    def body(a_loc):
        idx = jax.lax.axis_index(axis)
        row0 = idx * nb
        s = a_loc.astype(jnp.float32)
        q_out = jnp.zeros((nb, m), jnp.float32)
        r_out = jnp.zeros((m, m), jnp.float32)
        for p in range(n_panels):
            c0 = p * b
            panel = jax.lax.dynamic_slice(s, (0, c0), (nb, b))
            pan_full = jax.lax.all_gather(panel, axis, axis=0,
                                          tiled=True)    # [N, b]
            qf, rf = jnp.linalg.qr(pan_full, mode='reduced')
            q_loc = jax.lax.dynamic_slice(qf, (row0, 0), (nb, b))
            r_out = jax.lax.dynamic_update_slice(r_out, rf, (c0, c0))
            rest = m - c0 - b
            if rest > 0:
                s_rest = jax.lax.dynamic_slice(s, (0, c0 + b),
                                               (nb, rest))
                proj = jax.lax.psum(q_loc.T @ s_rest, axis)
                r_out = jax.lax.dynamic_update_slice(
                    r_out, proj, (c0, c0 + b))
                s = jax.lax.dynamic_update_slice(
                    s, s_rest - q_loc @ proj, (0, c0 + b))
            q_out = jax.lax.dynamic_update_slice(q_out, q_loc, (0, c0))
        return q_out.astype(a_loc.dtype), r_out.astype(a_loc.dtype)

    # check_vma off: R is assembled from all-gathered panels and psum
    # projections — identical on every device by construction, but the
    # replication checker cannot infer it through the gathered-panel QR
    fn = compat_shard_map(body, mesh=mesh, in_specs=(P(axis, None),),
                          out_specs=(P(axis, None), P(None, None)),
                          check_vma=False)
    return fn(a)


# ------------------------------------------------------ power iteration
def power_iter_step(a, v, mesh, axis='dp', quantized=False, qblock=256,
                    key=None):
    """One power-iteration step with ``a [N, N]`` COLUMN-blocked
    ``P(None, axis)`` and ``v [N]`` replicated: ``z = A v`` is a local
    [N, N/P] matvec plus an N-element allreduce — through exact
    ``psum`` or (``quantized=True``) the PR 13 block-scaled int8
    ``quantized_all_reduce``. Returns ``(v_next [N] replicated,
    rayleigh [1])`` where rayleigh = v . A v (v is unit-norm by
    construction after the first step).

    The allreduce IS this workload's step — power iteration stresses
    collectives the way gradient aggregation does, with none of the
    surrounding matmul tonnage, which is what makes it the second
    measurement axis for the quantized-collective trade."""
    (n_dp,) = axis_sizes_of(mesh, axis)
    n = a.shape[0]
    if a.shape[1] != n:
        raise ValueError('power_iter_step: square input required')
    if mesh is None or n_dp == 1:
        z = jnp.matmul(a.astype(jnp.float32), v.astype(jnp.float32))
        lam = jnp.vdot(v.astype(jnp.float32), z)
        vn = z / jnp.maximum(jnp.linalg.norm(z), 1e-30)
        return vn.astype(v.dtype), lam.reshape(1).astype(v.dtype)
    from jax.sharding import PartitionSpec as P

    if n % n_dp:
        raise ValueError('power_iter_step: N=%d %% dp=%d != 0'
                         % (n, n_dp))
    nb = n // n_dp

    def body(a_loc, v_full):
        idx = jax.lax.axis_index(axis)
        v_loc = jax.lax.dynamic_slice(v_full, (idx * nb,), (nb,))
        z_part = jnp.matmul(a_loc.astype(jnp.float32),
                            v_loc.astype(jnp.float32))
        if quantized:
            z = quantized_all_reduce(z_part, axis, block=qblock,
                                     key=key)
        else:
            z = jax.lax.psum(z_part, axis)
        lam = jnp.vdot(v_full.astype(jnp.float32), z)
        vn = z / jnp.maximum(jnp.linalg.norm(z), 1e-30)
        return vn.astype(v_full.dtype), lam.reshape(1).astype(
            v_full.dtype)

    # check_vma off: the quantized allreduce ends in an all_gather of
    # already-rounded shards — identical on every device by
    # construction, but not provably replicated to the checker
    fn = compat_shard_map(body, mesh=mesh,
                          in_specs=(P(None, axis), P(None)),
                          out_specs=(P(None), P(None)),
                          check_vma=False)
    return fn(a, v)
