"""paddle_tpu.linalg — distributed dense linear algebra at pod scale
(ROADMAP item 4; PAPERS "Large Scale Distributed Linear Algebra With
Tensor Processing Units").

The non-NN workload tier: SUMMA blocked matmul, blocked Cholesky,
blocked Householder QR, and power iteration, all expressed as Program
IR ops (``ops/linalg_ops.py``) over the existing dp x tp mesh — the
same NamedSharding/GSPMD machinery, executor compile cache, autotuner
(``tuning.decide_summa_panel`` / ``decide_linalg_block``), and static
verifier (the ``linalg`` blocked-layout pass) that serve training and
decoding. No shard ever materializes a full matrix: per-shard peak
memory stays O(N^2/P), modeled by :func:`per_shard_peak_bytes` and
enforced by :func:`assert_memory_contract`.

See docs/linalg.md for the panel schedule diagrams, the memory
contract, the autotuner key family, and the quantized-reduction
ablation (``bench.py --workload linalg``).
"""

from .api import (MemoryContractError, assert_memory_contract,  # noqa: F401
                  build_cholesky_program, build_matmul_program,
                  build_power_iter_program, build_qr_program, cholesky,
                  matmul, power_iteration, qr)
from .kernels import (axis_sizes_of, blocked_cholesky,  # noqa: F401
                      blocked_qr, default_block, default_panel,
                      legal_blocks, legal_panels, per_shard_peak_bytes,
                      power_iter_step, summa_matmul)

__all__ = ['matmul', 'cholesky', 'qr', 'power_iteration',
           'build_matmul_program', 'build_cholesky_program',
           'build_qr_program', 'build_power_iter_program',
           'summa_matmul', 'blocked_cholesky', 'blocked_qr',
           'power_iter_step', 'legal_panels', 'default_panel',
           'legal_blocks', 'default_block', 'axis_sizes_of',
           'per_shard_peak_bytes', 'assert_memory_contract',
           'MemoryContractError']
